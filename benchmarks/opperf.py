"""Operator micro-benchmark harness (reference: ``benchmark/opperf/``
[unverified]).

Times registered operators one by one — eager dispatch and jit-compiled —
and prints per-op rows plus a JSON summary. The op set covers the
reference harness's categories (unary/binary math, reductions, NN core,
contrib detection ops); ``--ops`` selects a subset.

    python -m benchmarks.opperf --runs 50
    python -m benchmarks.opperf --ops dot relu softmax
"""

from __future__ import annotations

import argparse
import json
import time

import numpy as np


def _inputs(shapes, dtype=np.float32, seed=0, int_slots=()):
    rng = np.random.RandomState(seed)
    import jax.numpy as jnp

    out = []
    for i, s in enumerate(shapes):
        if i in int_slots:
            out.append(jnp.asarray(rng.randint(0, 64, s), jnp.int32))
        else:
            out.append(jnp.asarray(rng.rand(*s).astype(dtype) + 0.1))
    return out


# op name -> (input shapes, static params)
DEFAULT_SPECS = {
    # unary / binary tensor math
    "relu": ([(256, 256)], {}),
    "sigmoid": ([(256, 256)], {}),
    "exp": ([(256, 256)], {}),
    "log": ([(256, 256)], {}),
    "sqrt": ([(256, 256)], {}),
    "broadcast_add": ([(256, 256), (1, 256)], {}),
    "broadcast_mul": ([(256, 256), (1, 256)], {}),
    "elemwise_add": ([(256, 256), (256, 256)], {}),
    # reductions / linalg
    "sum": ([(256, 256)], {}),
    "mean": ([(256, 256)], {}),
    "max": ([(256, 256)], {}),
    "dot": ([(256, 256), (256, 256)], {}),
    "batch_dot": ([(16, 64, 64), (16, 64, 64)], {}),
    # shape ops
    "transpose": ([(256, 256)], {}),
    "Reshape": ([(256, 256)], {"shape": (64, 1024)}),
    "Concat": ([(64, 128), (64, 128)], {"dim": 1}),
    # NN core
    "softmax": ([(128, 1000)], {}),
    "log_softmax": ([(128, 1000)], {}),
    "FullyConnected": ([(64, 512), (256, 512), (256,)],
                       {"num_hidden": 256}),
    "Convolution": ([(8, 16, 32, 32), (32, 16, 3, 3), (32,)],
                    {"kernel": (3, 3), "num_filter": 32, "pad": (1, 1)}),
    "Pooling": ([(8, 16, 32, 32)],
                {"kernel": (2, 2), "stride": (2, 2), "pool_type": "max"}),
    "BatchNorm": ([(32, 64, 16, 16), (64,), (64,), (64,), (64,)], {}),
    "LayerNorm": ([(64, 512), (512,), (512,)], {}),
    "Dropout": ([(256, 256)], {"p": 0.5}),
    "Activation": ([(256, 256)], {"act_type": "tanh"}),
    # round-4 families: linalg, spatial, multi-tensor, loss heads
    "linalg_gemm2": ([(16, 64, 64), (16, 64, 64)], {}),
    "linalg_potrf": ([(16, 64, 64)], {"__spd__": True}),
    "linalg_trsm": ([(16, 64, 64), (16, 64, 64)], {"__spd__": True}),
    "linalg_syrk": ([(16, 64, 64)], {}),
    "BilinearSampler": ([(8, 16, 32, 32), (8, 2, 32, 32)], {}),
    "GridGenerator": ([(8, 6)], {"transform_type": "affine",
                                 "target_shape": (32, 32)}),
    "SpatialTransformer": ([(8, 16, 32, 32), (8, 6)],
                           {"target_shape": (32, 32)}),
    "Correlation": ([(4, 16, 24, 24), (4, 16, 24, 24)],
                    {"max_displacement": 2, "pad_size": 2}),
    "im2col": ([(8, 16, 32, 32)], {"kernel": (3, 3), "pad": (1, 1)}),
    "multi_sum_sq": ([(256, 256), (256, 256), (256, 256)],
                     {"num_arrays": 3}),
    "multi_sgd_update": ([(256, 256), (256, 256), (128, 128), (128, 128)],
                         {"lrs": (0.1, 0.1), "num_weights": 2}),
    "LinearRegressionOutput": ([(256, 256), (256, 256)], {}),
    "SVMOutput": ([(256, 64), (256,)], {}),
    "cumsum": ([(256, 256)], {"axis": 1}),
    "add_n": ([(256, 256), (256, 256), (256, 256)], {}),
    "swapaxes": ([(64, 32, 16)], {"dim1": 0, "dim2": 2}),
    "reshape_like": ([(256, 256), (64, 1024)], {}),
    # contrib detection ops
    "_contrib_box_iou": ([(1, 64, 4), (1, 64, 4)], {}),
    "_contrib_box_nms": ([(1, 128, 6)], {}),
    "_contrib_ROIAlign": ([(1, 32, 32, 32), (8, 5)],
                          {"pooled_size": (7, 7), "spatial_scale": 1.0}),
    # trig / rounding / power unary family
    "sin": ([(256, 256)], {}),
    "cos": ([(256, 256)], {}),
    "tanh": ([(256, 256)], {}),
    "erf": ([(256, 256)], {}),
    "abs": ([(256, 256)], {}),
    "floor": ([(256, 256)], {}),
    "round": ([(256, 256)], {}),
    "square": ([(256, 256)], {}),
    "rsqrt": ([(256, 256)], {}),
    "reciprocal": ([(256, 256)], {}),
    # binary / comparison broadcasting
    "broadcast_sub": ([(256, 256), (1, 256)], {}),
    "broadcast_div": ([(256, 256), (1, 256)], {}),
    "broadcast_maximum": ([(256, 256), (1, 256)], {}),
    "broadcast_power": ([(256, 256), (1, 256)], {}),
    "broadcast_greater": ([(256, 256), (1, 256)], {}),
    "broadcast_equal": ([(256, 256), (256, 256)], {}),
    # reductions with axes / norms
    "prod": ([(256, 256)], {"axis": 1}),
    "min": ([(256, 256)], {"axis": 0}),
    "argmax": ([(256, 256)], {"axis": 1}),
    "argmin": ([(256, 256)], {"axis": 1}),
    "norm": ([(256, 256)], {}),
    "L2Normalization": ([(64, 512)], {}),
    # sorting / indexing / gather-scatter
    "sort": ([(64, 1024)], {}),
    "argsort": ([(64, 1024)], {}),
    "topk": ([(64, 1024)], {"k": 16}),
    "take": ([(1024, 64), (256,)], {}),
    "one_hot": ([(4096,)], {"depth": 128}),
    "where": ([(256, 256), (256, 256), (256, 256)], {}),
    "clip": ([(256, 256)], {"a_min": 0.2, "a_max": 0.8}),
    "tile": ([(64, 64)], {"reps": (2, 4)}),
    "repeat": ([(64, 64)], {"repeats": 4, "axis": 1}),
    "expand_dims": ([(256, 256)], {"axis": 1}),
    "slice": ([(256, 256)], {"begin": (32, 32), "end": (224, 224)}),
    "flip": ([(256, 256)], {"axis": 1}),
    # NN extras
    "Embedding": ([(64, 32), (8192, 128)],
                  {"input_dim": 8192, "output_dim": 128}),
    "SoftmaxOutput": ([(128, 1000), (128,)], {}),
    "LeakyReLU": ([(256, 256)], {"act_type": "leaky"}),
    "Deconvolution": ([(8, 16, 16, 16), (16, 8, 2, 2)],
                      {"kernel": (2, 2), "stride": (2, 2), "num_filter": 8,
                       "num_group": 1}),
    "_contrib_DeformableConvolution": (
        [(2, 8, 16, 16), (2, 18, 16, 16), (8, 8, 3, 3)],
        {"kernel": (3, 3), "pad": (1, 1), "num_filter": 8, "no_bias": True}),
    "_contrib_ModulatedDeformableConvolution": (
        [(2, 8, 16, 16), (2, 18, 16, 16), (2, 9, 16, 16), (8, 8, 3, 3)],
        {"kernel": (3, 3), "pad": (1, 1), "num_filter": 8, "no_bias": True}),
    "_contrib_PSROIPooling": ([(1, 196, 32, 32), (8, 5)],
                              {"output_dim": 4, "pooled_size": 7,
                               "spatial_scale": 1.0}),
    "linalg_gesvd": ([(4, 64, 64)], {}),
    "sample_multinomial": ([(64, 128)], {"shape": (16,)}),
    "_contrib_flash_attention": ([(2, 4, 512, 64)] * 3, {}),
    "_contrib_AdaptiveAvgPooling2D": ([(8, 16, 32, 32)],
                                      {"output_size": 7}),
    "linear_cross_entropy": ([(512, 128), (8192, 128), (512,)], {}),
    # fused optimizer updates
    "sgd_update": ([(1024, 1024), (1024, 1024)], {"lr": 0.1}),
    "adam_update": ([(1024, 1024)] * 4, {"lr": 0.1}),
}

# ops whose extra inputs must be integer (index) arrays
_INT_INPUT = {"take": [1], "Embedding": [0], "SoftmaxOutput": [1],
              "linear_cross_entropy": [2]}


def bench_op(name, shapes, params, warmup=2, runs=20, dtype=np.float32,
             device=False):
    import jax

    from mxnet_tpu.ops import registry

    op = registry.maybe_get(name)
    if op is None:
        return None
    params = dict(params)
    spd = params.pop("__spd__", False)
    # linear_cross_entropy takes labels as arg 2 with small vocab index
    args = _inputs(shapes, dtype=dtype,
                   int_slots=_INT_INPUT.get(name, ()))
    if spd:
        # factorization/solve ops need a well-conditioned SPD (or its
        # Cholesky-factor) leading operand
        import jax.numpy as jnp

        a = args[0]
        n = a.shape[-1]
        args[0] = jnp.matmul(a, jnp.swapaxes(a, -1, -2)) \
            + n * jnp.eye(n, dtype=a.dtype)
    import functools

    fn = functools.partial(op.fn, **params) if params else op.fn

    def _sync(o):
        leaves = jax.tree.leaves(o)
        np.asarray(jax.device_get(leaves[0]).reshape(-1)[:1])

    # eager
    try:
        for _ in range(warmup):
            out = fn(*args)
        _sync(out)
        t0 = time.perf_counter()
        for _ in range(runs):
            out = fn(*args)
        _sync(out)
        eager_us = (time.perf_counter() - t0) / runs * 1e6
    except Exception as e:  # noqa: BLE001
        return {"op": name, "error": f"{type(e).__name__}: {e}"[:120]}
    # jitted
    jfn = jax.jit(fn)
    try:
        for _ in range(warmup):
            out = jfn(*args)
        _sync(out)
        t0 = time.perf_counter()
        for _ in range(runs):
            out = jfn(*args)
        _sync(out)
        jit_us = (time.perf_counter() - t0) / runs * 1e6
    except Exception as e:  # noqa: BLE001
        jit_us = None
    dev_us = None
    if device and jit_us is not None:
        from .common import device_us

        try:
            dev_us = device_us(jfn, args)
        except Exception:  # noqa: BLE001 - profiler unavailable (CPU rigs)
            dev_us = None
    return {"op": name, "dtype": np.dtype(dtype).name,
            "eager_us": round(eager_us, 1),
            "jit_us": round(jit_us, 1) if jit_us is not None else None,
            "device_us": round(dev_us, 1) if dev_us is not None else None}


def run(ops=None, warmup=2, runs=20, dtypes=("float32",), device=False):
    specs = DEFAULT_SPECS if not ops else {
        k: v for k, v in DEFAULT_SPECS.items()
        if k in ops or k.removeprefix("_contrib_") in ops
    }
    import jax.numpy as jnp

    rows = []
    for name, (shapes, params) in specs.items():
        for dt in dtypes:
            dtype = jnp.bfloat16 if dt == "bfloat16" else np.dtype(dt)
            row = bench_op(name, shapes, params, warmup, runs, dtype=dtype,
                           device=device)
            if row is None:
                continue
            rows.append(row)
            if "error" in row:
                print(f"{name:28s} [{dt:8s}] ERROR {row['error']}")
            else:
                j = f"{row['jit_us']:10.1f}"                     if row["jit_us"] is not None else "       n/a"
                dv = row.get("device_us")
                dv = f"   device {dv:9.1f} us" if dv is not None else ""
                print(f"{name:28s} [{dt:8s}] eager "
                      f"{row['eager_us']:10.1f} us   jit {j} us{dv}")
    return rows


def write_markdown(rows, path):
    """Markdown report (the reference harness wrote one per category)."""
    lines = ["# opperf report", "",
             "| op | dtype | eager (us) | jit (us) | device (us) |",
             "|---|---|---|---|---|"]
    for r in rows:
        if "error" in r:
            lines.append(f"| {r['op']} | — | ERROR | {r['error']} | — |")
        else:
            j = r["jit_us"] if r["jit_us"] is not None else "n/a"
            d = r.get("device_us")
            d = d if d is not None else "n/a"
            lines.append(
                f"| {r['op']} | {r.get('dtype', 'float32')} | "
                f"{r['eager_us']} | {j} | {d} |"
            )
    with open(path, "w") as f:
        f.write("\n".join(lines) + "\n")


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--ops", nargs="*", default=None)
    ap.add_argument("--runs", type=int, default=20)
    ap.add_argument("--warmup", type=int, default=2)
    ap.add_argument("--dtypes", nargs="*", default=["float32"],
                    help="e.g. --dtypes float32 bfloat16")
    ap.add_argument("--json", action="store_true",
                    help="print one JSON line with all rows")
    ap.add_argument("--md", default=None,
                    help="write a markdown report to this path")
    ap.add_argument("--device", action="store_true",
                    help="add a profiler-counted DEVICE time column (the "
                         "wall columns sit at the tunnel dispatch floor)")
    args = ap.parse_args()
    rows = run(args.ops, args.warmup, args.runs, tuple(args.dtypes),
               device=args.device)
    if args.json:
        print(json.dumps({"opperf": rows}))
    if args.md:
        write_markdown(rows, args.md)


if __name__ == "__main__":
    main()
