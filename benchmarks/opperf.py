"""Operator micro-benchmark harness (reference: ``benchmark/opperf/``
[unverified]).

Times registered operators one by one — eager dispatch and jit-compiled —
and prints per-op rows plus a JSON summary. The op set covers the
reference harness's categories (unary/binary math, reductions, NN core,
contrib detection ops); ``--ops`` selects a subset.

    python -m benchmarks.opperf --runs 50
    python -m benchmarks.opperf --ops dot relu softmax
"""

from __future__ import annotations

import argparse
import json
import time

import numpy as np


def _inputs(shapes, dtype=np.float32, seed=0):
    rng = np.random.RandomState(seed)
    import jax.numpy as jnp

    return [jnp.asarray(rng.rand(*s).astype(dtype) + 0.1) for s in shapes]


# op name -> (input shapes, static params)
DEFAULT_SPECS = {
    # unary / binary tensor math
    "relu": ([(256, 256)], {}),
    "sigmoid": ([(256, 256)], {}),
    "exp": ([(256, 256)], {}),
    "log": ([(256, 256)], {}),
    "sqrt": ([(256, 256)], {}),
    "broadcast_add": ([(256, 256), (1, 256)], {}),
    "broadcast_mul": ([(256, 256), (1, 256)], {}),
    "elemwise_add": ([(256, 256), (256, 256)], {}),
    # reductions / linalg
    "sum": ([(256, 256)], {}),
    "mean": ([(256, 256)], {}),
    "max": ([(256, 256)], {}),
    "dot": ([(256, 256), (256, 256)], {}),
    "batch_dot": ([(16, 64, 64), (16, 64, 64)], {}),
    # shape ops
    "transpose": ([(256, 256)], {}),
    "Reshape": ([(256, 256)], {"shape": (64, 1024)}),
    "Concat": ([(64, 128), (64, 128)], {"dim": 1}),
    # NN core
    "softmax": ([(128, 1000)], {}),
    "log_softmax": ([(128, 1000)], {}),
    "FullyConnected": ([(64, 512), (256, 512), (256,)],
                       {"num_hidden": 256}),
    "Convolution": ([(8, 16, 32, 32), (32, 16, 3, 3), (32,)],
                    {"kernel": (3, 3), "num_filter": 32, "pad": (1, 1)}),
    "Pooling": ([(8, 16, 32, 32)],
                {"kernel": (2, 2), "stride": (2, 2), "pool_type": "max"}),
    "BatchNorm": ([(32, 64, 16, 16), (64,), (64,), (64,), (64,)], {}),
    "LayerNorm": ([(64, 512), (512,), (512,)], {}),
    "Dropout": ([(256, 256)], {"p": 0.5}),
    "Activation": ([(256, 256)], {"act_type": "tanh"}),
    # contrib detection ops
    "_contrib_box_iou": ([(1, 64, 4), (1, 64, 4)], {}),
    "_contrib_box_nms": ([(1, 128, 6)], {}),
    "_contrib_ROIAlign": ([(1, 32, 32, 32), (8, 5)],
                          {"pooled_size": (7, 7), "spatial_scale": 1.0}),
}


def bench_op(name, shapes, params, warmup=2, runs=20):
    import jax

    from mxnet_tpu.ops import registry

    op = registry.maybe_get(name)
    if op is None:
        return None
    args = _inputs(shapes)
    import functools

    fn = functools.partial(op.fn, **params) if params else op.fn

    def _sync(o):
        leaves = jax.tree.leaves(o)
        np.asarray(jax.device_get(leaves[0]).reshape(-1)[:1])

    # eager
    try:
        for _ in range(warmup):
            out = fn(*args)
        _sync(out)
        t0 = time.perf_counter()
        for _ in range(runs):
            out = fn(*args)
        _sync(out)
        eager_us = (time.perf_counter() - t0) / runs * 1e6
    except Exception as e:  # noqa: BLE001
        return {"op": name, "error": f"{type(e).__name__}: {e}"[:120]}
    # jitted
    jfn = jax.jit(fn)
    try:
        for _ in range(warmup):
            out = jfn(*args)
        _sync(out)
        t0 = time.perf_counter()
        for _ in range(runs):
            out = jfn(*args)
        _sync(out)
        jit_us = (time.perf_counter() - t0) / runs * 1e6
    except Exception as e:  # noqa: BLE001
        jit_us = None
    return {"op": name, "eager_us": round(eager_us, 1),
            "jit_us": round(jit_us, 1) if jit_us is not None else None}


def run(ops=None, warmup=2, runs=20):
    specs = DEFAULT_SPECS if not ops else {
        k: v for k, v in DEFAULT_SPECS.items()
        if k in ops or k.removeprefix("_contrib_") in ops
    }
    rows = []
    for name, (shapes, params) in specs.items():
        row = bench_op(name, shapes, params, warmup, runs)
        if row is None:
            continue
        rows.append(row)
        if "error" in row:
            print(f"{name:24s} ERROR {row['error']}")
        else:
            j = f"{row['jit_us']:10.1f}" if row["jit_us"] is not None else "       n/a"
            print(f"{name:24s} eager {row['eager_us']:10.1f} us   jit {j} us")
    return rows


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--ops", nargs="*", default=None)
    ap.add_argument("--runs", type=int, default=20)
    ap.add_argument("--warmup", type=int, default=2)
    ap.add_argument("--json", action="store_true",
                    help="print one JSON line with all rows")
    args = ap.parse_args()
    rows = run(args.ops, args.warmup, args.runs)
    if args.json:
        print(json.dumps({"opperf": rows}))


if __name__ == "__main__":
    main()
