"""End-to-end input pipeline bench (round-3 verdict item 7): ResNet-50
training FED by the multiprocessing DataLoader from host memory —
augment -> batchify -> pin_memory device_put -> TrainStep — the
steady-state images/sec a real user gets, input included.

Also times the same step on a device-resident batch in the same session
so the input-pipeline overhead (and achieved overlap) is explicit.

    python -m benchmarks.bench_e2e_input [--batch 64] [--steps 40]
"""

from __future__ import annotations

import argparse
import json
import time

import numpy as np


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--batch", type=int, default=64)
    ap.add_argument("--steps", type=int, default=40)
    ap.add_argument("--workers", type=int, default=2)
    args = ap.parse_args()

    import mxnet_tpu as mx
    from mxnet_tpu import gluon, nd, optimizer as opt
    from mxnet_tpu.gluon import data as gdata
    from mxnet_tpu.gluon.model_zoo.vision import get_model
    from mxnet_tpu.parallel import TrainStep

    B = args.batch

    class SyntheticImageNet(gdata.Dataset):
        """uint8 image pool with the standard train-time augment chain
        (random crop + flip + normalize) done in numpy per sample —
        the shape of a decoded-JPEG pipeline without the codec."""

        def __init__(self, n=512):
            rng = np.random.RandomState(0)
            self._pool = rng.randint(0, 255, (64, 256, 256, 3), np.uint8)
            self._n = n

        def __len__(self):
            return self._n

        def __getitem__(self, i):
            rng = np.random.RandomState(i)
            img = self._pool[i % len(self._pool)]
            y0, x0 = rng.randint(0, 32, 2)
            crop = img[y0:y0 + 224, x0:x0 + 224]
            if rng.rand() < 0.5:
                crop = crop[:, ::-1]
            out = crop.astype(np.float32) / 255.0
            out = (out - 0.45) / 0.225
            return out.transpose(2, 0, 1).copy(), np.float32(i % 1000)

    # fork workers BEFORE the first device computation (see DataLoader
    # docstring: post-runtime forks inherit locked mutexes)
    loader = gdata.DataLoader(
        SyntheticImageNet(n=B * (args.steps + 8)), batch_size=B,
        num_workers=args.workers, pin_memory=True, last_batch="discard")
    it = iter(loader)
    first = next(it)  # workers up before the net compiles

    net = get_model("resnet50_v1")
    net.initialize(mx.initializer.Xavier())
    net._probe_shapes(nd.zeros((2, 3, 224, 224)))
    loss_fn = gluon.loss.SoftmaxCrossEntropyLoss()
    step = TrainStep(net, lambda o, l: loss_fn(o, l),
                     opt.SGD(learning_rate=0.1, momentum=0.9),
                     compute_dtype="bfloat16", state_dtype="bfloat16")
    # compile + warm
    loss = step(first[0], first[1])
    float(loss.asscalar())

    # device-resident reference rate (same session, same step)
    xd, yd = first[0], first[1]
    for _ in range(3):
        loss = step(xd, yd)
    float(loss.asscalar())
    t0 = time.perf_counter()
    ndev = 10
    for _ in range(ndev):
        loss = step(xd, yd)
    float(loss.asscalar())
    dev_rate = B * ndev / (time.perf_counter() - t0)

    # the real loop: DataLoader -> pin -> step
    done = 0
    t0 = time.perf_counter()
    loss = None
    for x, y in it:
        loss = step(x, y)
        done += B
        if done >= args.steps * B:
            break
    float(loss.asscalar())
    e2e_rate = done / (time.perf_counter() - t0)

    overlap = e2e_rate / dev_rate if dev_rate else 0.0
    print(json.dumps({
        "metric": "resnet50_e2e_input_images_per_sec",
        "value": round(e2e_rate, 1), "unit": "images/sec",
        "device_resident_images_per_sec": round(dev_rate, 1),
        "input_overlap_fraction": round(overlap, 3),
        "workers": args.workers, "batch": B,
    }))


if __name__ == "__main__":
    main()
