"""End-to-end input pipeline bench (round-3 verdict item 7): ConvNet
training FED by the multiprocessing DataLoader from host memory —
augment -> batchify -> device feed -> TrainStep — the steady-state
images/sec a real user gets, input included.

Three rates from the SAME session so the input-pipeline overhead and the
async-feed win are explicit:

- ``device_resident``: the step re-fed one pre-placed DeviceBatch (the
  synthetic ceiling every BASELINE number is quoted against);
- ``fed_raw``: DataLoader -> synchronous ``TrainStep.__call__`` staging
  (reshape/split + device_put on the critical path);
- ``fed_prefetched`` (``--prefetch N``): DataLoader ->
  ``prefetch_to_device(..., feed=step)`` -> the pre-placed fast path,
  with the achieved overlap computed from the ``input/wait_ms``
  telemetry histogram the prefetcher feeds.

    python -m benchmarks.bench_e2e_input [--prefetch 2] [--batch 64]
"""

from __future__ import annotations

import argparse
import json
import time

import numpy as np


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--batch", type=int, default=None,
                    help="global batch (default: 64, or 8 on CPU)")
    ap.add_argument("--steps", type=int, default=None,
                    help="steps per measured phase (default: 40, 6 on CPU)")
    ap.add_argument("--workers", type=int, default=2)
    ap.add_argument("--prefetch", type=int, default=0,
                    help="staged device batches for the async feed phase "
                         "(0 = raw fed loop only)")
    ap.add_argument("--model", default=None,
                    help="model_zoo name (default: resnet50_v1, or "
                         "resnet18_v1 on CPU)")
    args = ap.parse_args()

    import jax

    import mxnet_tpu as mx
    from mxnet_tpu import gluon, nd, optimizer as opt
    from mxnet_tpu.gluon import data as gdata
    from mxnet_tpu.gluon.data.prefetch import prefetch_to_device
    from mxnet_tpu.gluon.model_zoo.vision import get_model
    from mxnet_tpu.parallel import TrainStep

    on_cpu = jax.default_backend() == "cpu"
    B = args.batch or (8 if on_cpu else 64)
    steps = args.steps or (6 if on_cpu else 40)
    model = args.model or ("resnet18_v1" if on_cpu else "resnet50_v1")

    class SyntheticImageNet(gdata.Dataset):
        """uint8 image pool with the standard train-time augment chain
        (random crop + flip + normalize) done in numpy per sample —
        the shape of a decoded-JPEG pipeline without the codec."""

        def __init__(self, n=512):
            rng = np.random.RandomState(0)
            self._pool = rng.randint(0, 255, (64, 256, 256, 3), np.uint8)
            self._n = n

        def __len__(self):
            return self._n

        def __getitem__(self, i):
            rng = np.random.RandomState(i)
            img = self._pool[i % len(self._pool)]
            y0, x0 = rng.randint(0, 32, 2)
            crop = img[y0:y0 + 224, x0:x0 + 224]
            if rng.rand() < 0.5:
                crop = crop[:, ::-1]
            out = crop.astype(np.float32) / 255.0
            out = (out - 0.45) / 0.225
            return out.transpose(2, 0, 1).copy(), np.float32(i % 1000)

    # fork workers BEFORE the first device computation (see DataLoader
    # docstring: post-runtime forks inherit locked mutexes)
    loader = gdata.DataLoader(
        SyntheticImageNet(n=B * (steps + 4)), batch_size=B,
        num_workers=args.workers, pin_memory=True, last_batch="discard")
    it = iter(loader)
    first = next(it)  # workers up before the net compiles

    net = get_model(model)
    net.initialize(mx.initializer.Xavier())
    net._probe_shapes(nd.zeros((2, 3, 224, 224)))
    loss_fn = gluon.loss.SoftmaxCrossEntropyLoss()
    step = TrainStep(net, lambda o, l: loss_fn(o, l),
                     opt.SGD(learning_rate=0.1, momentum=0.9),
                     compute_dtype="bfloat16", state_dtype="bfloat16")
    # compile + warm
    loss = step(first[0], first[1])
    float(loss.asscalar())

    def timed_loop(feed):
        """Run `steps` steps from `feed` (callable -> loss); returns rate."""
        t0 = time.perf_counter()
        loss = None
        for _ in range(steps):
            loss = feed()
        float(loss.asscalar())
        return B * steps / (time.perf_counter() - t0)

    # device-resident ceiling: ONE pre-placed batch re-fed through the
    # fast path (batch operands are not donated, so this is legal)
    db = step.device_put_batch((first[0], first[1]))
    for _ in range(3):
        loss = step(db)
    float(loss.asscalar())
    dev_rate = timed_loop(lambda: step(db))

    # the raw real loop: DataLoader -> synchronous staging in __call__
    raw_iter = iter(loader)
    raw_rate = timed_loop(lambda: step(*next(raw_iter)))
    if hasattr(raw_iter, "close"):
        raw_iter.close()

    wait_hist = mx.telemetry.registry().histogram("input/wait_ms")
    pf_rate = None
    overlap_achieved = None
    wait_summary = None
    if args.prefetch > 0:
        wait_before = wait_hist.sum
        pf = prefetch_to_device(iter(loader), size=args.prefetch, feed=step)
        t0 = time.perf_counter()
        loss = None
        for _ in range(steps):
            loss = step(next(pf))
        float(loss.asscalar())
        elapsed = time.perf_counter() - t0
        pf.close()
        pf_rate = B * steps / elapsed
        # achieved overlap: fraction of the fed wall time NOT spent
        # blocked waiting for a staged batch (from the new telemetry)
        wait_s = (wait_hist.sum - wait_before) / 1e3
        overlap_achieved = max(0.0, 1.0 - wait_s / elapsed)
        wait_summary = wait_hist.summary()

    fed_rate = pf_rate if pf_rate is not None else raw_rate
    report = mx.telemetry.report()
    print(json.dumps({
        "metric": f"{model.split('_')[0]}_e2e_input_images_per_sec",
        "value": round(fed_rate, 1), "unit": "images/sec",
        "model": model,
        "device_resident_images_per_sec": round(dev_rate, 1),
        "fed_images_per_sec_raw": round(raw_rate, 1),
        "fed_images_per_sec_prefetched":
            round(pf_rate, 1) if pf_rate is not None else None,
        "input_overlap_fraction":
            round(fed_rate / dev_rate, 3) if dev_rate else 0.0,
        "input_overlap_achieved":
            round(overlap_achieved, 3) if overlap_achieved is not None
            else None,
        "input_wait_ms_p50": report["input_wait_ms_p50"],
        "input_wait_ms_p95": report["input_wait_ms_p95"],
        "input_wait_ms_mean":
            round(wait_summary["mean"], 3) if wait_summary else None,
        "prefetch": args.prefetch, "workers": args.workers, "batch": B,
        "steps": steps,
    }))


if __name__ == "__main__":
    main()
