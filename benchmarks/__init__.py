"""Driver-format benchmarks for the BASELINE.json configs.

Run from the repo root as modules (so ``mxnet_tpu`` imports without
PYTHONPATH, which breaks the axon TPU plugin):

    python -m benchmarks.bench_lenet        # config 1
    python -m benchmarks.bench_resnet50     # config 2
    python bench.py                         # config 3 (driver metric)
    python -m benchmarks.bench_transformer  # config 4
    python -m benchmarks.bench_ssd          # config 5
    python -m benchmarks.run_all            # all five

Each prints ONE JSON line {"metric", "value", "unit", "vs_baseline"}.
Ceilings come from BASELINE.md's v4-derived 45%-MFU arithmetic.
"""
