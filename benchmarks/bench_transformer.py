"""BASELINE config 4: Transformer-base WMT En-De train step (the config
that exercises graph fusion: encoder+decoder+tied-logits in one XLA
program via TrainStep, bf16 + AdamW).

``--variable-length`` instead runs the shape-stability ablation (CPU-
sized by default): the same variable-length token stream fed (a)
unbucketed — every batch padded to its own max length, one compiled
program per distinct length — and (b) bucketed through
``FixedBucketSampler`` + ``PadToBucket`` with ``TrainStep.warmup`` over
the bucket signatures, which must hold compiles to <= n_buckets with
ZERO steady-state recompiles (counter-verified via the step's
``compile_guard``). With ``MXTPU_COMPILE_CACHE_DIR`` set, a second
process run also reports persistent-cache hits.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

import numpy as np

from .common import run_bench, run_varlen_mode

BATCH, SRC_LEN, TGT_LEN = 64, 64, 64
STEPS_PER_CALL = 40
VOCAB = 32768
# derived ceiling (BASELINE.md arithmetic style): ~61M non-embedding params
# => ~0.37 GFLOPs/token train cost; 45% of v4 peak 275T => ~3.3e5 tok/s.
CEILING = 3.3e5


def fixed_main(amp=None, remat=None, mesh=None, sharding=None):
    import mxnet_tpu as mx
    from mxnet_tpu import gluon, nd, optimizer as opt
    from mxnet_tpu.gluon.model_zoo.transformer import transformer_base
    from mxnet_tpu.parallel import TrainStep

    mesh_obj = None
    if mesh:
        from mxnet_tpu.parallel import sharding as _shard

        # --mesh NxM: in-graph SPMD over the first N*M visible devices;
        # --sharding picks the placement rules (default fsdp: params +
        # moments sharded so the per-device bytes drop mesh.size-fold)
        mesh_obj = _shard.make_global_mesh(mesh)
        if sharding is None:
            sharding = "fsdp"

    net = transformer_base(src_vocab=VOCAB, tgt_vocab=VOCAB, max_length=512,
                           dropout=0.1)
    net.initialize(mx.initializer.Xavier())
    net._probe_shapes(nd.zeros((2, 8), dtype="int32"),
                      nd.zeros((2, 8), dtype="int32"))
    ce = gluon.loss.SoftmaxCrossEntropyLoss()

    class _Loss:
        def __call__(self, logits, label):
            return ce(logits.reshape(-1, VOCAB), label.reshape(-1))

    # steps_per_call: STEPS_PER_CALL full optimizer steps on as many
    # DISTINCT microbatches per dispatch (device-side scan,
    # parallel/step.py) — amortizes tunnel dispatch latency like a real
    # input pipeline. Default precision is the legacy cast-everything
    # bf16; --amp switches to the lists-driven AMP pass, --remat arms
    # whole-graph rematerialization.
    precision = ({"amp": amp} if amp else
                 {"compute_dtype": "bfloat16", "state_dtype": "bfloat16"})
    step_fn = TrainStep(net, _Loss(), opt.AdamW(learning_rate=1e-4),
                        steps_per_call=STEPS_PER_CALL, remat=remat,
                        mesh=mesh_obj, sharding=sharding, **precision)
    rng = np.random.RandomState(0)
    n = BATCH * STEPS_PER_CALL
    src = nd.array(rng.randint(0, VOCAB, (n, SRC_LEN)), dtype="int32")
    tgt = nd.array(rng.randint(0, VOCAB, (n, TGT_LEN)), dtype="int32")
    labels = nd.array(rng.randint(0, VOCAB, (n, TGT_LEN)), dtype="int32")

    run_bench(
        "transformer_wmt_tokens_per_sec_per_chip", "tokens/sec", CEILING,
        lambda: step_fn(src, tgt, labels),
        lambda loss: float(loss.asscalar()),
        STEPS_PER_CALL * BATCH * TGT_LEN,
        warmup=2, steps=16,
    )


# ------------------------------------------------------ variable-length mode
def variable_length_main(args):
    import jax
    import jax.numpy as jnp

    import mxnet_tpu as mx
    from mxnet_tpu import compile_cache, gluon, nd, optimizer as opt
    from mxnet_tpu.gluon.data import (DataLoader, FixedBucketSampler,
                                      PadToBucket)
    from mxnet_tpu.gluon.model_zoo.transformer import TransformerModel
    from mxnet_tpu.ndarray.ndarray import NDArray
    from mxnet_tpu.parallel import TrainStep

    V = args.vocab
    rng = np.random.RandomState(args.seed)
    lengths = rng.randint(args.min_len, args.max_len + 1,
                          size=args.samples).tolist()
    dataset = []
    for length in lengths:
        s = rng.randint(1, V, size=length).astype("int32")
        t = rng.randint(1, V, size=length).astype("int32")
        dataset.append((s, t, t))  # label = tgt; pad with -1 for the mask
    tokens_per_epoch = int(sum(lengths))

    class MaskedCE:
        """Per-token CE averaged over VALID (label != -1) tokens only.
        Reduced per row THEN across rows: appending pad columns only adds
        exact zeros to each row's reduction, so padded and unpadded
        batches of the same sentences are bit-identical (asserted in
        tests/test_bucketing.py)."""

        def __call__(self, logits, label):
            x = logits.data.astype(jnp.float32)
            y = label.data
            mask = y >= 0
            safe = jnp.where(mask, y, 0).astype(jnp.int32)
            logp = jax.nn.log_softmax(x, axis=-1)
            nll = -jnp.take_along_axis(logp, safe[..., None],
                                       axis=-1)[..., 0]
            row = jnp.where(mask, nll, 0.0).sum(axis=-1)
            return NDArray(row.sum() / mask.sum())

    def make_step():
        net = TransformerModel(
            src_vocab=V, tgt_vocab=V, units=args.units,
            hidden_size=args.units * 2, num_layers=args.layers, num_heads=2,
            max_length=args.max_len + 8, dropout=0.0)
        net.initialize(mx.initializer.Xavier())
        net._probe_shapes(nd.zeros((2, 8), dtype="int32"),
                          nd.zeros((2, 8), dtype="int32"))
        return TrainStep(net, MaskedCE(), opt.AdamW(learning_rate=1e-4))

    # ---- unbucketed: shuffled fixed-size batches, each padded to its own
    # max length — the classic one-compile-per-distinct-length feed
    def pad_batch(idxs):
        ml = max(lengths[i] for i in idxs)
        s = np.zeros((len(idxs), ml), "int32")
        t = np.zeros((len(idxs), ml), "int32")
        lab = np.full((len(idxs), ml), -1, "int32")
        for r, i in enumerate(idxs):
            s[r, : lengths[i]] = dataset[i][0]
            t[r, : lengths[i]] = dataset[i][1]
            lab[r, : lengths[i]] = dataset[i][2]
        return nd.array(s), nd.array(t), nd.array(lab)

    def unbucketed_epochs(ep):
        order = np.random.RandomState(args.seed + 1 + ep).permutation(
            len(dataset))
        for i in range(0, len(order) - args.batch_size + 1,
                       args.batch_size):
            yield pad_batch(order[i: i + args.batch_size].tolist())

    step_u = make_step()
    unbucketed = run_varlen_mode(step_u, unbucketed_epochs,
                                 tokens_per_epoch, epochs=args.epochs)

    # ---- bucketed: FixedBucketSampler + PadToBucket, every bucket
    # signature compiled up front by TrainStep.warmup
    sampler = FixedBucketSampler(
        lengths, args.batch_size, num_buckets=args.buckets,
        ratio=args.ratio, shuffle=True, last_batch="pad")
    batchify = PadToBucket(sampler.bucket_keys, pad_val=0,
                           label_pad_val=[0, -1], valid_length=False)
    loader = DataLoader(dataset, batch_sampler=sampler,
                        batchify_fn=batchify)
    step_b = make_step()
    warm_sigs = [
        (((bs, key), "int32"), ((bs, key), "int32"), ((bs, key), "int32"))
        for bs, key in sampler.signatures()
    ]
    t0 = time.perf_counter()
    warm_compiles = step_b.warmup(warm_sigs)
    warmup_s = time.perf_counter() - t0

    def bucketed_epochs(ep):
        np.random.seed(args.seed + 100 + ep)  # sampler shuffle per epoch
        yield from iter(loader)

    bucketed = run_varlen_mode(step_b, bucketed_epochs, tokens_per_epoch,
                               epochs=args.epochs)
    bucketed["warmup_compiles"] = warm_compiles
    bucketed["warmup_s"] = round(warmup_s, 3)
    bucketed["n_buckets"] = len(sampler.bucket_keys)

    row = {
        "metric": "transformer_varlen_bucketed_tokens_per_sec",
        "value": bucketed["steady_tokens_per_sec"],
        "unit": "tokens/sec",
        "unbucketed": unbucketed,
        "bucketed": bucketed,
        "compile_cache": compile_cache.cache_stats(),
    }
    print(json.dumps(row))
    print(f"unbucketed: {unbucketed['signatures_total']} compiled programs "
          f"({unbucketed['signatures_per_epoch']} per epoch), "
          f"{unbucketed['steady_tokens_per_sec']} tok/s steady")
    print(f"bucketed:   {bucketed['signatures_total']} compiled programs "
          f"(warmup {warm_compiles} <= {bucketed['n_buckets']} buckets), "
          f"{bucketed['steady_state_recompiles']} steady-state recompiles, "
          f"{bucketed['steady_tokens_per_sec']} tok/s steady")
    cache = compile_cache.cache_stats()
    if cache["enabled"]:
        print(f"persistent cache: dir={cache['dir']} hits={cache['hits']} "
              f"misses={cache['misses']}")
    ok = (bucketed["steady_state_recompiles"] == 0
          and bucketed["signatures_total"] <= len(warm_sigs))
    if not ok:
        print("FAIL: bucketed mode recompiled in steady state",
              file=sys.stderr)
    return 0 if ok else 1


# ------------------------------------------------------------- decode mode
def decode_main(args):
    """Inference ablation (CPU-sized): KV-cached incremental decode vs
    naive re-forward generation on the same model and prompts.

    Naive = the pre-engine reality: every emitted token re-runs the full
    forward over the whole prefix (one jitted program PER emitted length,
    O(T²) total compute, one host round trip per token for the argmax).
    KV = ``InferStep``: bucketed prefill + one ``lax.while_loop`` decode
    program, warmed over the prompt-bucket menu — the acceptance gate is
    >= 5x naive tokens/sec with ZERO steady-state recompiles."""
    import warnings

    import mxnet_tpu as mx
    from mxnet_tpu import nd
    from mxnet_tpu.gluon.model_zoo.transformer import TransformerModel
    from mxnet_tpu.parallel import InferStep
    from .common import infer_fields

    V, B, T = args.vocab, args.batch_size, args.decode_tokens
    rng = np.random.RandomState(args.seed)
    net = TransformerModel(
        src_vocab=V, tgt_vocab=V, units=args.units,
        hidden_size=args.units * 2, num_layers=args.layers, num_heads=2,
        max_length=args.max_len + T + 8, dropout=0.0)
    net.initialize(mx.initializer.Xavier())
    net._probe_shapes(nd.zeros((2, 8), dtype="int32"),
                      nd.zeros((2, 8), dtype="int32"))

    # one prompt batch padded to the largest bucket (both paths see the
    # same (B, bucket) prompt + valid_length contract)
    bucket = args.max_len
    lens = rng.randint(args.min_len, args.max_len + 1, size=B)
    src_np = np.zeros((B, bucket), "int32")
    for i, n in enumerate(lens):
        src_np[i, :n] = rng.randint(3, V, size=n)
    vl_np = lens.astype("int32")

    # ---- naive: hybridized full re-forward per emitted token (programs
    # compile on pass 0; pass 1 is the steady-state figure). The per-step
    # argmax host read is PART of the baseline being replaced.
    net.hybridize()

    def naive_generate():
        tgt = np.full((B, 1), 1, "int32")  # BOS
        for _ in range(T):
            logits = net(nd.array(src_np), nd.array(tgt),
                         nd.array(vl_np, dtype="int32"))
            nxt = logits.asnumpy()[:, -1].argmax(-1).astype("int32")
            tgt = np.concatenate([tgt, nxt[:, None]], axis=1)
        return tgt[:, 1:]

    with warnings.catch_warnings():
        warnings.simplefilter("ignore", RuntimeWarning)  # sig-count alarm
        naive_generate()  # compile pass: T programs
        t0 = time.perf_counter()
        naive_tokens = naive_generate()
        naive_s = time.perf_counter() - t0
    net.hybridize(False)
    naive_tps = B * T / naive_s

    # ---- KV-cached: warmed InferStep, one prefill + one decode dispatch
    eng = InferStep(net, max_len=bucket + T + 4)
    warm = eng.warmup([(B, bucket)], max_new_tokens=T)
    eng.decode_n(src_np, vl_np, max_new_tokens=T)  # dispatch-cache hot
    t0 = time.perf_counter()
    toks, lengths = eng.decode_n(src_np, vl_np, max_new_tokens=T)
    kv_tokens = toks.asnumpy()
    kv_s = time.perf_counter() - t0
    kv_tps = B * T / kv_s

    parity = bool(np.array_equal(kv_tokens, naive_tokens))
    recompiles = eng.compile_guard.steady_state_recompiles
    row = {
        "metric": "transformer_decode_tokens_per_sec",
        "value": round(kv_tps, 1),
        "unit": "tokens/sec",
        "naive_tokens_per_sec": round(naive_tps, 1),
        "speedup": round(kv_tps / naive_tps, 2),
        "greedy_tokens_match_naive": parity,
        "warmup_compiles": warm,
        "steady_state_recompiles": recompiles,
        "batch": B, "prompt_bucket": bucket, "decode_tokens": T,
    }
    row.update(infer_fields())
    row["steady_state_recompiles"] = recompiles
    print(json.dumps(row))
    print(f"naive re-forward: {naive_tps:.1f} tok/s ({T} programs, "
          f"O(T^2) recompute); kv-cached: {kv_tps:.1f} tok/s "
          f"({row['speedup']}x, {recompiles} steady recompiles, greedy "
          f"tokens match naive: {parity})")
    ok = kv_tps >= 5 * naive_tps and recompiles == 0
    if not ok:
        print("FAIL: kv-cached decode must be >= 5x naive with zero "
              "steady-state recompiles", file=sys.stderr)
    return 0 if ok else 1


# --------------------------------------------------------- open-loop mode
def open_loop_main(args):
    """Continuous-vs-fixed batching under Poisson open-loop load (the
    ISSUE-8 acceptance ablation, CPU-sized).

    One seeded request stream — exponential inter-arrival gaps at
    ``--open-loop RATE`` req/s, uniform prompt lengths, and a 50/50 mix
    of short (``T // 4``) and long (``T``) ``max_new_tokens`` — is
    replayed against (a) the PR-5 fixed-dispatch ``DynamicBatcher``
    (every batch decodes the full ``T`` and a finished row idles its slot
    until the batch drains) and (b) the paged-KV ``ContinuousBatcher``
    (iteration-level retire/admit). Gates: sustained decode-batch
    occupancy >= 0.9 for the continuous engine and >= 1.5x the fixed
    batcher's decode tokens/sec, with zero steady-state recompiles."""
    import mxnet_tpu as mx
    from mxnet_tpu import nd
    from mxnet_tpu.gluon.model_zoo.transformer import TransformerModel
    from mxnet_tpu.parallel import InferStep
    from mxnet_tpu.serving import ContinuousBatcher, DynamicBatcher
    from .common import infer_fields

    V, B, T = args.vocab, args.batch_size, args.decode_tokens
    bucket = args.max_len
    rate = args.open_loop
    n_requests = args.samples
    # scheduling quality only shows when MODEL COMPUTE is the scheduled
    # resource: at the other modes' micro sizes a decode step costs less
    # than its dispatch and every scheduler measures python overhead, so
    # this mode floors the model at a small-but-real serving size
    units = max(args.units, 128)
    layers = max(args.layers, 2)
    iter_tokens = args.iter_tokens if args.iter_tokens is not None else 8

    net = TransformerModel(
        src_vocab=V, tgt_vocab=V, units=units,
        hidden_size=units * 2, num_layers=layers, num_heads=2,
        max_length=bucket + T + 8, dropout=0.0)
    net.initialize(mx.initializer.Xavier())
    net._probe_shapes(nd.zeros((2, 8), dtype="int32"),
                      nd.zeros((2, 8), dtype="int32"))

    # one seeded workload, replayed identically against both schedulers.
    # The max_new mix mirrors real serving traffic: mostly short
    # responses with a long tail (the regime Orca/PagedAttention target —
    # the fixed batcher decodes EVERY batch to the full T while its short
    # rows idle their slots)
    short = max(T // 8, 2)
    rng = np.random.RandomState(args.seed)
    stream = []
    for _ in range(n_requests):
        n = rng.randint(args.min_len, bucket + 1)
        stream.append({
            "gap": rng.exponential(1.0 / rate) if rate > 0 else 0.0,
            "prompt": rng.randint(3, V, (n,)).astype("int32"),
            "max_new": short if rng.rand() < 0.8 else T,
        })
    total_requested = sum(r["max_new"] for r in stream)

    def drive(batcher):
        futs = []
        t0 = time.perf_counter()
        for r in stream:
            if r["gap"]:
                time.sleep(r["gap"])
            futs.append(batcher.submit(r["prompt"],
                                       max_new_tokens=r["max_new"]))
        tokens = ttfts = 0
        ttft_list, lat_list = [], []
        for f in futs:
            out = f.result(timeout=600)
            tokens += len(out)
            done = time.perf_counter()
            lat_list.append((done - f.enqueued_at) * 1e3 / max(len(out), 1))
            if f.first_token_at is not None:
                ttft_list.append((f.first_token_at - f.enqueued_at) * 1e3)
                ttfts += 1
        wall = time.perf_counter() - t0
        ttft_list.sort()
        lat_list.sort()
        return {
            "tokens": tokens,
            "tokens_per_sec": round(tokens / wall, 1),
            "wall_s": round(wall, 3),
            "ttft_ms_p50": round(_q(ttft_list, 50), 1) if ttft_list
            else None,
            "ttft_ms_p95": round(_q(ttft_list, 95), 1) if ttft_list
            else None,
            "token_latency_ms_p50": round(_q(lat_list, 50), 2),
            "token_latency_ms_p95": round(_q(lat_list, 95), 2),
        }

    # ---- fixed (PR-5): whole-batch dispatches at the batcher's max_new
    eng_f = InferStep(net, max_len=bucket + T + 4)
    fixed_bat = DynamicBatcher(eng_f, bucket_keys=(bucket,), slots=B,
                               timeout_ms=2.0, max_new_tokens=T,
                               warmup=True, name="fixed")
    fixed = drive(fixed_bat)
    fixed_bat.stop()
    fixed["steady_state_recompiles"] = \
        eng_f.compile_guard.steady_state_recompiles

    # ---- continuous: iteration-level retire/admit over the paged pool
    eng_c = InferStep(net, max_len=bucket + T + 4)
    cont_bat = ContinuousBatcher(
        eng_c, bucket_keys=(bucket,), slots=B, max_new_tokens=T,
        page_size=args.page_size, iter_tokens=iter_tokens,
        warmup=True, name="continuous")
    cont = drive(cont_bat)
    occupancy = round(cont_bat.sustained_occupancy, 4)
    stats = dict(cont_bat.stats)
    pool = cont_bat.pool
    cont_bat.stop()
    cont["steady_state_recompiles"] = \
        eng_c.compile_guard.steady_state_recompiles
    cont["sustained_occupancy"] = occupancy
    cont["iterations"] = stats["iterations"]
    cont["preempted"] = stats["preempted"]

    speedup = round(cont["tokens_per_sec"] / max(fixed["tokens_per_sec"],
                                                 1e-9), 2)
    row = {
        "metric": "transformer_open_loop_tokens_per_sec",
        "value": cont["tokens_per_sec"],
        "unit": "tokens/sec",
        "open_loop_rate": rate,
        "requests": n_requests,
        "tokens_requested": total_requested,
        "sustained_occupancy": occupancy,
        "speedup_vs_fixed": speedup,
        "fixed": fixed,
        "continuous": cont,
        "slots": B, "prompt_bucket": bucket, "decode_tokens": T,
        "page_size": pool.page_size, "num_pages": pool.num_pages,
        "iter_tokens": cont_bat.iter_tokens,
    }
    row.update(infer_fields())
    print(json.dumps(row))
    print(f"open loop @ {rate}/s, {n_requests} req (max_new {short}|{T} "
          f"mix): fixed {fixed['tokens_per_sec']} tok/s "
          f"(ttft p50 {fixed['ttft_ms_p50']} ms) vs continuous "
          f"{cont['tokens_per_sec']} tok/s ({speedup}x, occupancy "
          f"{occupancy}, ttft p50 {cont['ttft_ms_p50']} ms, "
          f"{stats['preempted']} preemptions, "
          f"{cont['steady_state_recompiles']} steady recompiles)")
    ok = (occupancy >= 0.9 and speedup >= 1.5
          and cont["steady_state_recompiles"] == 0)
    if not ok:
        print("FAIL: continuous batching must sustain >= 90% occupancy "
              "and >= 1.5x fixed-batcher tokens/sec with zero steady "
              "recompiles", file=sys.stderr)
    return 0 if ok else 1


# -------------------------------------------------------- prefix-mix mode
def prefix_mix_main(args):
    """Prefix caching ablation (the ISSUE-13 acceptance run, CPU-sized).

    One seeded multi-turn chat workload — half the conversations share
    one system prompt (their histories diverge at per-conversation user
    tokens: the COW/branching regime), half carry distinct prompts (one
    linear trie chain each) — is replayed TWICE through the same warmed
    engine: once through a ``ContinuousBatcher`` with the prefix trie on
    and once with it off (every turn re-prefills its full forced
    history). Turn 1 is cold for both; turns >= 2 re-send the
    accumulated history as ``prefix_ids``.

    Gates: >= 3x TTFT p50 improvement on the prefix-carrying turns,
    BIT-identical greedy transcripts between the two runs, a
    refcount-exact pool/trie audit after the cached run, and zero
    steady-state recompiles."""
    import mxnet_tpu as mx
    from mxnet_tpu import nd
    from mxnet_tpu.gluon.model_zoo.transformer import TransformerModel
    from mxnet_tpu.parallel import InferStep
    from mxnet_tpu.serving import ContinuousBatcher
    from .common import infer_fields

    V = args.vocab
    bucket = 16          # prompt bucket (system prompts are short)
    T = 16               # new tokens per turn
    turns = 4            # 1 cold + 3 prefix-carrying
    max_prefix = 96      # >= turns' accumulated history
    convs = max(args.batch_size, 6)
    # prefix savings only show when the replayed HISTORY costs real
    # compute (same floor rationale as the open-loop mode): the hit
    # path's adoption overhead is O(1) in history length, the cold
    # replay O(len) — at micro sizes both drown in dispatch overhead
    units = max(args.units, 128)
    layers = max(args.layers, 2)

    np.random.seed(args.seed)
    mx.random.seed(args.seed)
    net = TransformerModel(
        src_vocab=V, tgt_vocab=V, units=units, hidden_size=units * 2,
        num_layers=layers, num_heads=2,
        max_length=max_prefix + T + 8, dropout=0.0)
    net.initialize(mx.initializer.Xavier())
    net._probe_shapes(nd.zeros((2, 8), dtype="int32"),
                      nd.zeros((2, 8), dtype="int32"))
    eng = InferStep(net, max_len=max_prefix + T + 8)

    rng = np.random.RandomState(args.seed)
    system = rng.randint(3, V, (12,)).astype("int32")
    prompts = [system if i < convs // 2
               else rng.randint(3, V, (rng.randint(8, 13),))
               .astype("int32") for i in range(convs)]
    # the user's reply tokens per conversation+turn: what makes shared-
    # prompt histories diverge (and exercises the COW tail)
    user = [[rng.randint(3, V, (2,)).tolist() for _ in range(turns)]
            for _ in range(convs)]

    def drive(cache_on, tag):
        # every conversation gets a slot (TTFT measures the cache, not
        # queueing) and the pool holds the whole working set — eviction
        # thrash would bill the cached run for pool pressure instead
        bat = ContinuousBatcher(
            eng, bucket_keys=(bucket,), slots=convs, max_new_tokens=T,
            page_size=args.page_size if args.page_size is not None else 8,
            num_pages=convs * 2 * ((max_prefix + T) // 8 + 2),
            iter_tokens=args.iter_tokens
            if args.iter_tokens is not None else 4,
            max_prefix_tokens=max_prefix, prefix_cache=cache_on,
            warmup=True, name=tag)
        hist = [[] for _ in range(convs)]
        transcript = []
        ttfts = []
        t0 = time.perf_counter()
        for turn in range(turns):
            futs = []
            for c in range(convs):
                futs.append(bat.submit(
                    prompts[c], max_new_tokens=T,
                    prefix_ids=hist[c] if turn else None))
            for c, f in enumerate(futs):
                out = f.result(timeout=600)
                transcript.append(list(out))
                if turn and f.first_token_at is not None:
                    ttfts.append((f.first_token_at - f.enqueued_at) * 1e3)
                hist[c] = hist[c] + list(out) + user[c][turn]
        wall = time.perf_counter() - t0
        stats = bat.prefix_stats()
        audit_ok = True
        try:
            bat.cache.check_invariants()
            bat.pool.check_invariants(cache_pages=bat.cache.pages())
        except Exception as e:  # noqa: BLE001 - report, don't crash
            audit_ok = False
            print(f"AUDIT FAIL ({tag}): {e}", file=sys.stderr)
        bat.stop()
        ttfts.sort()
        return transcript, {
            "wall_s": round(wall, 3),
            "prefix_ttft_ms_p50": round(_q(ttfts, 50), 1),
            "prefix_ttft_ms_p95": round(_q(ttfts, 95), 1),
            "hits": stats["hits"],
            "hit_rate": round(stats["hit_rate"], 4),
            "tokens_saved": stats["tokens_saved"],
            "cow_copies": stats["cow_copies"],
            "cached_pages": stats["pages"],
            "evicted_pages": stats["evicted_pages"],
            "audit_ok": audit_ok,
        }

    cached_transcript, cached = drive(True, "prefix-cached")
    cold_transcript, cold = drive(False, "prefix-off")

    identical = cached_transcript == cold_transcript
    speedup = round(cold["prefix_ttft_ms_p50"]
                    / max(cached["prefix_ttft_ms_p50"], 1e-9), 2)
    recompiles = eng.compile_guard.steady_state_recompiles
    row = {
        "metric": "transformer_prefix_mix_ttft_speedup",
        "value": speedup,
        "unit": "x",
        "conversations": convs,
        "turns": turns,
        "max_prefix_tokens": max_prefix,
        "bit_identical": identical,
        "steady_state_recompiles": recompiles,
        "cached": cached,
        "uncached": cold,
    }
    row.update(infer_fields())
    print(json.dumps(row))
    print(f"prefix mix, {convs} convs x {turns} turns: cached ttft p50 "
          f"{cached['prefix_ttft_ms_p50']} ms (hit rate "
          f"{cached['hit_rate']}, {cached['cow_copies']} COW copies) vs "
          f"uncached {cold['prefix_ttft_ms_p50']} ms -> {speedup}x, "
          f"bit-identical={identical}, {recompiles} steady recompiles")
    ok = (speedup >= 3.0 and identical and cached["audit_ok"]
          and cached["hits"] >= convs * (turns - 1) and recompiles == 0)
    if not ok:
        print("FAIL: prefix caching must cut prefix-turn TTFT p50 by "
              ">= 3x with bit-identical greedy transcripts, every "
              "prefix turn a trie hit, a refcount-exact audit and zero "
              "steady recompiles", file=sys.stderr)
    return 0 if ok else 1


# -------------------------------------------------------- serve-chaos mode
def serve_chaos_main(args):
    """Self-healing serving ablation (CPU-sized): sustained open-loop
    load on a 2-replica ``Router`` while (a) a hot weight swap lands
    mid-stream (``CheckpointWatcher`` over a freshly committed sharded
    checkpoint) and (b) one replica is killed by fault injection
    (``serving.faults``, the ``batcher.thread`` point).

    Acceptance: ZERO lost requests (every future resolves), responses
    carry both the old and the new ``weights_version`` (the swap neither
    dropped nor stalled the stream), ``serve/failovers >= 1``, and zero
    steady-state recompiles through both events."""
    import os
    import tempfile

    import mxnet_tpu as mx
    from mxnet_tpu import nd
    from mxnet_tpu import checkpoint_sharded as cs
    from mxnet_tpu.gluon.model_zoo.transformer import TransformerModel
    from mxnet_tpu.parallel import InferStep
    from mxnet_tpu.serving import (CheckpointWatcher, DynamicBatcher,
                                   Replica, Router, faults)

    V, B, T = args.vocab, args.batch_size, args.decode_tokens
    bucket = args.max_len
    rng = np.random.RandomState(args.seed)

    def make_net(seed):
        np.random.seed(seed)
        mx.random.seed(seed)
        net = TransformerModel(
            src_vocab=V, tgt_vocab=V, units=args.units,
            hidden_size=args.units * 2, num_layers=args.layers,
            num_heads=2, max_length=bucket + T + 8, dropout=0.0,
            prefix="serve_net_")
        net.initialize(mx.initializer.Xavier())
        net._probe_shapes(nd.zeros((2, 8), dtype="int32"),
                          nd.zeros((2, 8), dtype="int32"))
        return net

    # the serving net and the "newly trained" weights it will swap to
    net = make_net(args.seed)
    trained = make_net(args.seed + 1)
    ckpt_root = tempfile.mkdtemp(prefix="mxtpu_serve_chaos_")
    cs.save_sharded(
        os.path.join(ckpt_root, "step_1"),
        {n: p._data.data for n, p in trained.collect_params().items()})

    def make_replica(name):
        eng = InferStep(net, max_len=bucket + T + 4)
        bat = DynamicBatcher(eng, bucket_keys=(bucket,), slots=B,
                             timeout_ms=2.0, max_new_tokens=T,
                             warmup=True, name=name)
        return Replica(name, bat)

    replicas = [make_replica("r0"), make_replica("r1")]
    # shedding off: this mode measures failover/swap under a backlog
    # that deliberately outruns the CPU rig's service rate (the shed
    # policy is --procs mode's phase 3)
    router = Router(replicas, retry_backoff_s=0.01,
                    health_interval_s=0.02, shed_queue_depth=10 ** 6)
    watcher = CheckpointWatcher(router.engines, ckpt_root, start=False)

    n_requests = args.samples
    futs, lat = [], []
    faults.inject("batcher.thread", times=1, match="r1")
    t0 = time.perf_counter()
    for i in range(n_requests):
        n = rng.randint(args.min_len, bucket + 1)
        futs.append(router.submit(rng.randint(3, V, (n,)).astype("int32"),
                                  max_new_tokens=T))
        if i == n_requests // 3:
            watcher.poll_once()  # hot swap mid-stream
        time.sleep(0.001)
    errors = 0
    for f in futs:
        try:
            f.result(timeout=120)
            lat.append((time.perf_counter() - f.enqueued_at) * 1e3)
        except Exception:  # noqa: BLE001 - counted as lost
            errors += 1
    wall_s = time.perf_counter() - t0

    # distributed-tracing tax on this same serving path: identical
    # load with tracing forced off vs on, gated <= 2%
    from .common import trace_overhead_fields

    def _overhead_load():
        fs = [router.submit(
            rng.randint(3, V, (bucket,)).astype("int32"),
            max_new_tokens=T) for _ in range(4)]
        for f in fs:
            f.result(timeout=120)

    overhead = trace_overhead_fields(_overhead_load)
    router.stop()
    faults.clear()

    versions = sorted({f.weights_version for f in futs
                       if f.weights_version is not None})
    reg = mx.telemetry.registry()
    recompiles = sum(
        rep.engine.compile_guard.steady_state_recompiles
        for rep in replicas)
    lat.sort()
    row = {
        "metric": "transformer_serve_chaos_requests_per_sec",
        "value": round(len(lat) / wall_s, 1),
        "unit": "requests/sec",
        "requests": n_requests,
        "errors": errors,
        "latency_ms_p50": round(_q(lat, 50), 1) if lat else None,
        "latency_ms_p99": round(_q(lat, 99), 1) if lat else None,
        "weights_versions": versions,
        "serve_swaps": reg.counter("serve/swaps").value,
        "serve_failovers": reg.counter("serve/failovers").value,
        "serve_retries": reg.counter("serve/retries").value,
        "serve_dropped": reg.counter("serve/dropped").value,
        "steady_state_recompiles": recompiles,
        "batch": B, "prompt_bucket": bucket, "decode_tokens": T,
    }
    row.update(overhead)
    print(json.dumps(row))
    print(f"{n_requests} requests through swap+replica-kill: "
          f"{errors} lost, versions {versions}, "
          f"{row['serve_failovers']} failover(s), "
          f"{row['serve_retries']} retries, p99 "
          f"{row['latency_ms_p99']} ms, {recompiles} steady recompiles, "
          f"trace overhead {row['trace_overhead_pct']}%")
    ok = (errors == 0 and len(versions) >= 2 and
          row["serve_failovers"] >= 1 and recompiles == 0 and
          row["trace_overhead_ok"] is not False)
    if not ok:
        print("FAIL: swap+failover under load must lose zero requests, "
              "serve both weight versions, evict the killed replica and "
              "never recompile", file=sys.stderr)
    return 0 if ok else 1


# ------------------------------------------------- serve-chaos, real procs
def serve_chaos_procs_main(args):
    """Cross-process chaos (``--serve-chaos --procs N``): N REAL
    ``serving.worker`` processes behind ``RemoteReplica``s, under
    open-loop load, through the full failure matrix —

    1. a coordinated hot swap lands mid-stream (two-phase stage/flip
       over the control channel; every process ends on ONE version tag),
    2. one worker is SIGKILL'd mid-decode (dead socket + stale
       heartbeat → eviction → transparent resubmission → the factory
       respawns a REAL process which rejoins at the swapped version),
    3. a deadline flood hits the now-degraded fleet and the router
       SHEDS at admission (``serve/shed_*``) with the backlog bounded
       by construction.

    Acceptance: zero lost requests through swap+SIGKILL, >= 1 failover,
    one coherent post-swap version across every live process, every
    flood request resolved (served or shed — none hanging), observed
    router backlog <= MXTPU_SHED_MAX_QUEUE, zero steady recompiles in
    this process (remote engines warm in their own)."""
    import os
    import shutil
    import tempfile

    import mxnet_tpu as mx
    from mxnet_tpu import checkpoint_sharded as cs
    from mxnet_tpu.serving import (Backpressure, CheckpointWatcher,
                                   RemoteReplica, Router)
    from mxnet_tpu.serving.worker import make_transformer_net, spawn_worker

    V, B, T = args.vocab, args.batch_size, args.decode_tokens
    bucket = args.max_len
    n_procs = args.procs
    rng = np.random.RandomState(args.seed)
    root = tempfile.mkdtemp(prefix="mxtpu_serve_chaos_procs_")
    ckpt_root = os.path.join(root, "ckpt")
    model = dict(vocab=V, units=args.units, layers=args.layers,
                 heads=2, seed=args.seed, max_length=bucket + T + 8)
    wkw = dict(model=model, max_len=bucket + T + 4, bucket_keys=(bucket,),
               slots=B, max_new=T, ckpt_dir=ckpt_root)

    handles = [spawn_worker(os.path.join(root, f"w{i}"), name=f"w{i}",
                            **wkw) for i in range(n_procs)]
    spawned = [len(handles)]

    def factory():
        i = spawned[0]
        spawned[0] += 1
        h = spawn_worker(os.path.join(root, f"w{i}"), name=f"w{i}", **wkw)
        handles.append(h)
        return RemoteReplica.spawning(h, heartbeat_stale_s=2.0)

    print(f"spawning {n_procs} worker processes ...", file=sys.stderr)
    replicas = [RemoteReplica(h.name, address=h.address,
                              heartbeat_path=h.heartbeat_path,
                              heartbeat_stale_s=2.0) for h in handles]
    router = Router(replicas, retry_backoff_s=0.01, health_interval_s=0.05,
                    replica_factory=factory, respawn_backoff_s=0.05,
                    no_replica_timeout_s=60.0,
                    shed_queue_depth=10 ** 6)  # phase 3 tightens this
    trained = make_transformer_net(**dict(model, seed=args.seed + 1))
    cs.save_sharded(
        os.path.join(ckpt_root, "step_1"),
        {n: p._data.data for n, p in trained.collect_params().items()})
    watcher = CheckpointWatcher(router.engines, ckpt_root, start=False)

    # ---- phase 1+2: open-loop load through swap + SIGKILL
    n_requests = args.samples
    futs, lat = [], []
    swap_version = None
    t0 = time.perf_counter()
    for i in range(n_requests):
        n = rng.randint(args.min_len, bucket + 1)
        futs.append(router.submit(rng.randint(3, V, (n,)).astype("int32"),
                                  max_new_tokens=T))
        if i == n_requests // 3:
            swap_version = watcher.poll_once()
            assert swap_version is not None, "swap did not land"
        if i == n_requests // 2:
            print(f"SIGKILL {handles[1].name} (pid {handles[1].pid})",
                  file=sys.stderr)
            handles[1].kill()
        time.sleep(0.002)
    errors = 0
    for f in futs:
        try:
            f.result(timeout=240)
            lat.append((time.perf_counter() - f.enqueued_at) * 1e3)
        except Exception:  # noqa: BLE001 - counted as lost
            errors += 1
    wall_s = time.perf_counter() - t0
    versions = sorted({f.weights_version for f in futs
                       if f.weights_version is not None})

    # the respawned process must rejoin and report the swapped version
    deadline = time.perf_counter() + 120
    live = []
    while time.perf_counter() < deadline:
        live = [r for r in router.replicas if not r.evicted and r.healthy]
        if len(live) >= n_procs:
            break
        time.sleep(0.2)
    live_versions = sorted({r.weights_version for r in live})

    # ---- phase 3: shed flood against a deliberately degraded fleet
    router.shed_queue_depth = 2
    router.shed_max_queue = max(2 * B, 8)
    flood = []
    max_backlog = 0
    for _ in range(4 * router.shed_max_queue):
        flood.append(router.submit(
            rng.randint(3, V, (rng.randint(args.min_len, bucket + 1),))
            .astype("int32"), max_new_tokens=T, deadline_ms=10_000.0))
        max_backlog = max(max_backlog, len(router._inflight))
    shed = served = flood_lost = 0
    flood_waits = []
    for f in flood:
        try:
            f.result(timeout=240)
            served += 1
            if f.queue_wait_ms is not None:
                flood_waits.append(f.queue_wait_ms)
        except Backpressure:
            shed += 1
        except Exception:  # noqa: BLE001 - deadline/drop = lost
            flood_lost += 1

    # trace-overhead measurement on the surviving fleet: restore the
    # open admission phases 1+2 ran under, then identical load with
    # tracing forced off vs on (router-side spans; gate <= 2%)
    router.shed_queue_depth = 10 ** 6
    from .common import trace_overhead_fields

    def _overhead_load():
        fs = [router.submit(
            rng.randint(3, V, (bucket,)).astype("int32"),
            max_new_tokens=T) for _ in range(4)]
        for f in fs:
            f.result(timeout=240)

    overhead = trace_overhead_fields(_overhead_load)
    router.stop()
    reg = mx.telemetry.registry()
    shed_counted = sum(
        reg.counter(f"serve/shed_{k}").value
        for k in ("queue_full", "deadline"))

    # ---- graceful teardown: SIGTERM drains, exit 0
    rcs = []
    for h in handles:
        if h.alive():
            h.terminate()
    for h in handles:
        try:
            rcs.append(h.wait(timeout=60))
        except Exception:  # noqa: BLE001
            h.kill()
            rcs.append(-9)
    rcs = [rc for rc in rcs if rc != -9]  # the SIGKILL'd one

    lat.sort()
    flood_waits.sort()
    local_recompiles = 0  # remote engines warm in their own processes
    row = {
        "metric": "transformer_serve_chaos_procs_requests_per_sec",
        "value": round(len(lat) / wall_s, 1),
        "unit": "requests/sec",
        "procs": n_procs,
        "requests": n_requests,
        "errors": errors,
        "latency_ms_p50": round(_q(lat, 50), 1) if lat else None,
        "latency_ms_p99": round(_q(lat, 99), 1) if lat else None,
        "weights_versions": versions,
        "live_versions": live_versions,
        "serve_swaps": reg.counter("serve/swaps").value,
        "serve_failovers": reg.counter("serve/failovers").value,
        "serve_retries": reg.counter("serve/retries").value,
        "serve_dropped": reg.counter("serve/dropped").value,
        "serve_replica_restarts":
            reg.counter("serve/replica_restarts").value,
        "transport_reconnects":
            reg.counter("transport/reconnects").value,
        "transport_errors": reg.counter("transport/errors").value,
        "shed": shed, "shed_counted": shed_counted,
        "flood_served": served, "flood_lost": flood_lost,
        "flood_wait_ms_p95": round(_q(flood_waits, 95), 1)
            if flood_waits else None,
        "max_router_backlog": max_backlog,
        "shed_max_queue": router.shed_max_queue,
        "drain_exit_codes": rcs,
        "steady_state_recompiles": local_recompiles,
        "batch": B, "prompt_bucket": bucket, "decode_tokens": T,
    }
    row.update(overhead)
    print(json.dumps(row))
    print(f"{n_requests} requests through cross-process swap+SIGKILL: "
          f"{errors} lost, versions {versions}, "
          f"{row['serve_failovers']} failover(s), "
          f"{row['serve_replica_restarts']} respawn(s), live fleet on "
          f"{live_versions}; flood: {served} served / {shed} shed "
          f"({shed_counted} counted), backlog max {max_backlog} <= "
          f"{router.shed_max_queue}, drain rcs {rcs}, trace overhead "
          f"{row['trace_overhead_pct']}%")
    ok = (errors == 0 and len(versions) >= 2
          and row["serve_failovers"] >= 1
          and live_versions == [swap_version]
          and flood_lost == 0
          and shed >= 1 and shed_counted >= shed
          and max_backlog <= router.shed_max_queue
          and all(rc == 0 for rc in rcs)
          and row["trace_overhead_ok"] is not False)
    shutil.rmtree(root, ignore_errors=True)
    if not ok:
        print("FAIL: cross-process chaos must lose zero requests, "
              "evict+respawn the killed worker, converge every process "
              "on one swapped version, shed (with accounting) under a "
              "degraded fleet with bounded backlog, and drain cleanly "
              "on SIGTERM", file=sys.stderr)
    return 0 if ok else 1


def _q(sorted_vals, p):
    if not sorted_vals:
        return None
    rank = (p / 100.0) * (len(sorted_vals) - 1)
    lo = int(rank)
    hi = min(lo + 1, len(sorted_vals) - 1)
    return sorted_vals[lo] * (1 - (rank - lo)) + sorted_vals[hi] * (rank - lo)


# -------------------------------------------------------------- disagg mode
def disagg_main(args):
    """Disaggregated prefill/decode ablation (``--disagg --procs N``,
    ISSUE-11 acceptance): ONE seeded mixed-class open-loop stream —
    interactive (short prompt, short response, 80 %) + batch (long
    prompt, long response, 20 %) — replayed against two REAL worker
    fleets of the same total size:

    1. **co-scheduled** — N ``both``-role workers, every worker prefills
       and decodes (the PR-10 baseline);
    2. **disaggregated** — 1 ``prefill``-role + (N-1) ``decode``-role
       workers (SAME total process count): the router sends every
       admission prefill to the prefill worker, which ships the filled
       KV over ``kv_push``; decode workers adopt without re-prefilling.

    Why this wins even on the 1-core CPU rig: a co-scheduled worker's
    scheduler loop is SEQUENTIAL — a long batch-class admission prefill
    (one indivisible ~100 ms dispatch at this size) blocks every queued
    interactive request on that worker; padding also drags short
    prompts up to the long bucket when classes mix in one admission
    round. Disaggregation moves prefills into a separate OS-scheduled
    process (decode iterations preempt them) and the prefill engine
    batches per bucket, smallest first — interactive admission on the
    decode worker becomes a ~10 ms host-side adoption instead of a
    prefill dispatch.

    Acceptance: interactive-class TTFT p95 improves under
    disaggregation while aggregate tokens/sec holds within 10 %, every
    request serves on both fleets, and every handoff adopts (0 router
    re-prefills on the happy path)."""
    import os
    import shutil
    import tempfile

    import mxnet_tpu as mx
    from mxnet_tpu.serving import RemoteReplica, Router
    from mxnet_tpu.serving.worker import spawn_worker
    from .common import disagg_fields

    V, T = args.vocab, args.decode_tokens
    # the disaggregation regime: batch prompts LONG (their admission
    # prefill is the interference co-scheduling suffers from), the
    # model at a serving-real size so that prefill costs dominate the
    # handoff's fixed overhead (one extra RPC hop + host adoption,
    # ~30 ms on the CPU rig) — at micro sizes there is nothing worth
    # moving off the decode workers
    bucket = max(args.max_len, 256)
    short_bucket = max(args.min_len, 8)
    n_procs = max(args.procs, 2)
    # default operating point validated on the CPU rig (procs=3,
    # samples=72): an SLO-feasible utilization — at saturating rates
    # BOTH fleets just queue and the comparison measures backlog, not
    # scheduling
    rate = args.open_loop if args.open_loop is not None else 12.0
    n_requests = args.samples
    units = max(args.units, 256)
    layers = max(args.layers, 2)
    # interactive responses sized to the scheduler's iteration burst:
    # a 4-token response retires exactly at the iteration boundary, so
    # neither fleet wastes decode steps on the 80 % class
    short_new = max(T // 4, 4)

    rng = np.random.RandomState(args.seed)
    stream = []
    for _ in range(n_requests):
        interactive = rng.rand() < 0.8
        n = rng.randint(3, short_bucket + 1) if interactive \
            else rng.randint(bucket // 2, bucket + 1)
        stream.append({
            "gap": rng.exponential(1.0 / rate) if rate > 0 else 0.0,
            "prompt": rng.randint(3, V, (n,)).astype("int32"),
            "max_new": short_new if interactive else T,
            "klass": "interactive" if interactive else "batch",
        })

    root = tempfile.mkdtemp(prefix="mxtpu_disagg_bench_")
    model = dict(vocab=V, units=units, layers=layers, heads=2,
                 seed=args.seed, max_length=bucket + T + 8)
    wkw = dict(model=model, max_len=bucket + T + 4,
               bucket_keys=(short_bucket, bucket),
               slots=args.batch_size, max_new=T,
               extra_env={"MXTPU_ITER_TOKENS": str(
                   args.iter_tokens if args.iter_tokens is not None
                   else max(T // 4, 4))})

    def spawn_fleet(tag, roles):
        handles = [spawn_worker(os.path.join(root, f"{tag}{i}"),
                                name=f"{tag}{i}", role=role, **wkw)
                   for i, role in enumerate(roles)]
        reps = [RemoteReplica(h.name, address=h.address,
                              heartbeat_path=h.heartbeat_path,
                              heartbeat_stale_s=10.0, role=role)
                for h, role in zip(handles, roles)]
        return handles, reps

    def drive(router):
        futs = []
        t0 = time.perf_counter()
        for r in stream:
            if r["gap"]:
                time.sleep(r["gap"])
            futs.append(router.submit(r["prompt"],
                                      max_new_tokens=r["max_new"],
                                      klass=r["klass"]))
        tokens = errors = 0
        ttft = {"interactive": [], "batch": []}
        for f, r in zip(futs, stream):
            try:
                out = f.result(timeout=600)
            except Exception:  # noqa: BLE001 - counted as lost
                errors += 1
                continue
            tokens += len(out)
            if f.first_token_at is not None:
                ttft[r["klass"]].append(
                    (f.first_token_at - f.enqueued_at) * 1e3)
        wall = time.perf_counter() - t0
        for v in ttft.values():
            v.sort()
        return {
            "tokens": tokens, "errors": errors,
            "tokens_per_sec": round(tokens / wall, 1),
            "wall_s": round(wall, 3),
            "ttft_interactive_p50":
                round(_q(ttft["interactive"], 50), 1)
                if ttft["interactive"] else None,
            "ttft_interactive_p95":
                round(_q(ttft["interactive"], 95), 1)
                if ttft["interactive"] else None,
            "ttft_batch_p50": round(_q(ttft["batch"], 50), 1)
                if ttft["batch"] else None,
            "ttft_batch_p95": round(_q(ttft["batch"], 95), 1)
                if ttft["batch"] else None,
        }

    def run_fleet(tag, roles):
        print(f"spawning {tag} fleet {roles} ...", file=sys.stderr)
        handles, reps = spawn_fleet(tag, roles)
        router = Router(reps, health_interval_s=0.05,
                        no_replica_timeout_s=120.0,
                        shed_queue_depth=10 ** 6)
        # fleet warmup: a few throwaway requests so first-contact costs
        # (peer connects, health probes, per-process page-ins) stay out
        # of BOTH fleets' percentiles
        warm = [router.submit(stream[i % len(stream)]["prompt"],
                              max_new_tokens=4)
                for i in range(2 * len(roles))]
        for f in warm:
            f.result(timeout=600)
        out = drive(router)
        adopted = re_prefilled = 0
        for rep in router.replicas:
            try:
                info = rep.client.call("health")
            except Exception:  # noqa: BLE001 - best-effort accounting
                continue
            adopted += info.get("disagg_adopted") or 0
            re_prefilled += info.get("disagg_re_prefills") or 0
        out["worker_adopted"] = adopted
        out["worker_re_prefills"] = re_prefilled

        # tracing tax on this fleet: identical load forced off vs on
        from .common import trace_overhead_fields

        def _overhead_load():
            fs = [router.submit(stream[i % len(stream)]["prompt"],
                                max_new_tokens=4) for i in range(4)]
            for f in fs:
                f.result(timeout=600)

        out.update(trace_overhead_fields(_overhead_load))
        router.stop()
        for h in handles:
            if h.alive():
                h.terminate()
        for h in handles:
            try:
                h.wait(timeout=60)
            except Exception:  # noqa: BLE001
                h.kill()
        return out

    cosched = run_fleet("both", ["both"] * n_procs)
    disagg = run_fleet("split", ["prefill"] + ["decode"] * (n_procs - 1))
    shutil.rmtree(root, ignore_errors=True)

    reg = mx.telemetry.registry()
    tps_ratio = round(disagg["tokens_per_sec"]
                      / max(cosched["tokens_per_sec"], 1e-9), 3)
    row = {
        "metric": "transformer_disagg_ttft_interactive_p95_ms",
        "value": disagg["ttft_interactive_p95"],
        "unit": "ms",
        "procs": n_procs,
        "requests": n_requests,
        "open_loop_rate": rate,
        "cosched": cosched,
        "disagg": disagg,
        "tokens_per_sec_ratio": tps_ratio,
        "router_re_prefills": reg.counter("disagg/re_prefills").value,
        "slots": args.batch_size, "prompt_buckets":
            [short_bucket, bucket], "decode_tokens": T,
        "trace_overhead_pct": disagg["trace_overhead_pct"],
        "trace_overhead_ok": disagg["trace_overhead_ok"],
    }
    row.update(disagg_fields())
    print(json.dumps(row))
    print(f"disagg vs co-scheduled ({n_procs} procs, {n_requests} req): "
          f"interactive ttft p95 {disagg['ttft_interactive_p95']} vs "
          f"{cosched['ttft_interactive_p95']} ms, tokens/sec "
          f"{disagg['tokens_per_sec']} vs {cosched['tokens_per_sec']} "
          f"({tps_ratio}x), {disagg['worker_adopted']} adopted / "
          f"{disagg['worker_re_prefills']} worker re-prefills / "
          f"{row['router_re_prefills']} router fallbacks")
    ok = (cosched["errors"] == 0 and disagg["errors"] == 0
          and disagg["worker_adopted"] >= 1
          and disagg["ttft_interactive_p95"] is not None
          and cosched["ttft_interactive_p95"] is not None
          and disagg["ttft_interactive_p95"]
          <= cosched["ttft_interactive_p95"]
          and tps_ratio >= 0.9
          and disagg["trace_overhead_ok"] is not False)
    if not ok:
        print("FAIL: disaggregation must lose zero requests, adopt "
              "handoffs, improve interactive TTFT p95 and hold "
              "aggregate tokens/sec within 10%", file=sys.stderr)
    return 0 if ok else 1


# ------------------------------------------------- speculative decoding mode
def speculative_main(args):
    """Flash/paged kernel x speculative decoding ablation (the ISSUE-14
    acceptance run, CPU-sized).

    One seeded prompt batch decodes through five configurations of the
    SAME weights:

    1. ``dense`` — the PR-8 engine (``decode_n``: dense KV slab, one
       fused while_loop program). The baseline every row gates against.
    2. ``paged`` — the paged-KV sequential path (``decode_spec_n`` with
       ``k=0``: one ``decode_iter`` round per token), kernels off.
    3. ``paged+spec`` — speculative decoding (draft proposes
       ``--spec-k`` tokens/round, ONE wide target dispatch verifies),
       kernels off. THE GATE ROW: >= 2x the dense baseline.
    4/5. the same two with ``MXTPU_FLASH_PAGED=force`` — the Pallas
       paged kernels in interpret mode (CPU correctness rows; on-TPU
       they are the perf path, here they are slower than dense math).

    The draft is an ORACLE built from the target itself: the target's
    tail ``--spec-layers - 1`` layers have their sublayer output
    projections zeroed (pre-LN residual blocks collapse to identity), so
    a 1-layer draft holding the surviving layer's weights computes the
    IDENTICAL function at 1/L the depth — full acceptance, maximal
    speedup, and the bit-identity gate still checks the real rejection
    machinery (acceptance only decides how many tokens land per round,
    never which). Gates: every row's transcript equals the dense
    baseline exactly; the spec row >= 2x dense tokens/sec; zero steady-
    state recompiles in every engine."""
    import mxnet_tpu as mx
    from mxnet_tpu import nd
    from mxnet_tpu.gluon.model_zoo.transformer import TransformerModel
    from mxnet_tpu.parallel import InferStep
    from .common import infer_fields

    V, B = args.vocab, args.batch_size
    L, K = args.spec_layers, args.spec_k
    # the spec ablation needs enough math per dispatch to measure: at the
    # shared CPU defaults (units=32, T=32) per-round host overhead
    # dominates every row equally and the comparison is noise, so this
    # mode floors both knobs at the smallest config where the dense
    # baseline is compute-bound
    units = max(args.units, 128)
    T = max(args.decode_tokens, 64)
    rng = np.random.RandomState(args.seed)

    def make_net(layers, seed):
        mx.random.seed(seed)
        net = TransformerModel(
            src_vocab=V, tgt_vocab=V, units=units,
            hidden_size=units * 2, num_layers=layers, num_heads=2,
            max_length=args.max_len + T + K + 16, dropout=0.0)
        net.initialize(mx.initializer.Xavier())
        net._probe_shapes(nd.zeros((2, 8), dtype="int32"),
                          nd.zeros((2, 8), dtype="int32"))
        return net

    target = make_net(L, args.seed)
    # collapse the tail layers to identity (pre-LN residual blocks: a
    # zeroed sublayer output projection contributes exactly 0)
    zero_suffixes = (
        "multiheadattention0_out_weight", "multiheadattention0_out_bias",
        "multiheadattention1_out_weight", "multiheadattention1_out_bias",
        "_ffn0_dense1_weight", "_ffn0_dense1_bias")
    for pname, p in target.collect_params().items():
        for li in range(1, L):
            for tag in (f"encoderlayer{li}_", f"decoderlayer{li}_"):
                if tag in pname and any(pname.endswith(z)
                                        for z in zero_suffixes):
                    p.set_data(nd.NDArray(np.zeros_like(
                        np.asarray(p._data.data))))
    draft = make_net(1, args.seed + 1)
    # draft layer-0/embedding/final-norm names are a subset of the
    # target's (indices match); copy by instance-prefix-stripped name
    tparams = {n.split("_", 1)[1]: p
               for n, p in target.collect_params().items()}
    for pname, p in draft.collect_params().items():
        p.set_data(nd.NDArray(tparams[pname.split("_", 1)[1]]._data.data))

    bucket = args.max_len
    lens = rng.randint(args.min_len, args.max_len + 1, size=B)
    src_np = np.zeros((B, bucket), "int32")
    for i, n in enumerate(lens):
        src_np[i, :n] = rng.randint(3, V, size=n)
    vl_np = lens.astype("int32")
    max_len = bucket + T + K + 8
    page_size = args.page_size or 16

    def timed(run_fn, eng, reps):
        out = run_fn()  # warm: compiles + caches every program
        eng.compile_guard.mark_steady()
        t0 = time.perf_counter()
        for _ in range(reps):
            out = run_fn()
        toks, lengths = out
        toks = toks.asnumpy()
        elapsed = (time.perf_counter() - t0) / reps
        return toks, lengths.asnumpy(), B * T / elapsed

    spec_on = args.speculative or not args.flash_paged
    results = []
    prior = os.environ.get("MXTPU_FLASH_PAGED")
    try:
        for kernel in (False, True):
            os.environ["MXTPU_FLASH_PAGED"] = "force" if kernel else "0"
            reps = 1 if kernel else 3  # interpret rows: correctness pace
            if not kernel:
                eng = InferStep(target, max_len=max_len)
                toks_d, lens_d, dense_tps = timed(
                    lambda: eng.decode_n(src_np, vl_np, max_new_tokens=T),
                    eng, reps)
                results.append(("dense", False, False, dense_tps,
                                toks_d, lens_d, eng))
            peng = InferStep(target, max_len=max_len)
            peng.attach_draft(draft)
            toks_p, lens_p, paged_tps = timed(
                lambda: peng.decode_spec_n(
                    src_np, vl_np, max_new_tokens=T, k=0,
                    page_size=page_size), peng, reps)
            results.append(("paged", kernel, False, paged_tps,
                            toks_p, lens_p, peng))
            if spec_on:
                seng = InferStep(target, max_len=max_len)
                seng.attach_draft(draft)
                toks_s, lens_s, spec_tps = timed(
                    lambda: seng.decode_spec_n(
                        src_np, vl_np, max_new_tokens=T, k=K, wide=True,
                        page_size=page_size), seng, reps)
                results.append(("paged+spec", kernel, True, spec_tps,
                                toks_s, lens_s, seng))
    finally:
        if prior is None:
            os.environ.pop("MXTPU_FLASH_PAGED", None)
        else:
            os.environ["MXTPU_FLASH_PAGED"] = prior

    base = next(r for r in results if r[0] == "dense")
    base_tps, base_toks, base_lens = base[3], base[4], base[5]
    all_equal = True
    recompiles = 0
    for name, kernel, spec, tps, toks, lengths, eng in results:
        equal = bool(np.array_equal(toks, base_toks)
                     and np.array_equal(lengths, base_lens))
        all_equal = all_equal and equal
        recompiles += eng.compile_guard.steady_state_recompiles
        row = {
            "metric": "transformer_spec_decode_tokens_per_sec",
            "value": round(tps, 1),
            "unit": "tokens/sec",
            "config": name + ("+kernel" if kernel else ""),
            "flash_paged_kernel": kernel,
            "speculative": spec,
            "spec_k": K if spec else 0,
            "speedup_vs_dense": round(tps / base_tps, 2),
            "greedy_tokens_match_dense": equal,
            "steady_state_recompiles":
                eng.compile_guard.steady_state_recompiles,
            "batch": B, "prompt_bucket": bucket, "decode_tokens": T,
            "target_layers": L, "draft_layers": 1, "units": units,
        }
        row.update({k: v for k, v in infer_fields().items()
                    if k not in row})
        print(json.dumps(row))
    gate = next((r for r in results
                 if r[0] == "paged+spec" and not r[1]), None)
    for name, kernel, spec, tps, _t, _l, _e in results:
        tag = name + ("+kernel" if kernel else "")
        print(f"  {tag:<18} {tps:>9.1f} tok/s "
              f"({tps / base_tps:.2f}x dense)")
    ok = all_equal and recompiles == 0
    if spec_on:
        ok = ok and gate is not None and gate[3] >= 2 * base_tps
    if not ok:
        print("FAIL: speculative decoding must be >= 2x the dense "
              "engine at bit-identical greedy output with zero steady-"
              "state recompiles (and every kernel row must match too)",
              file=sys.stderr)
    return 0 if ok else 1


# ------------------------------------------------------- amp/auto-batch mode
def amp_auto_batch_main(args):
    """HBM-aware compute ablation: fp32 no-remat vs amp(+remat), each at
    the LARGEST batch its compiled step fits under one shared HBM budget
    (``plan_batch`` over ``memory_analysis`` — nothing materialized
    during planning). The amp+remat step must fit a strictly larger
    batch and hold ZERO steady-state recompiles after warmup; steady
    tokens/sec at the planned batches is the headline. Budget: device
    HBM (or MXTPU_HBM_BYTES) under MXTPU_HBM_HEADROOM; rigs with no
    limit at all fall back to the fp32 step's peak at 4x --batch-size so
    the ablation stays runnable on the CPU rig."""
    import mxnet_tpu as mx
    from mxnet_tpu import nd, optimizer as opt
    from mxnet_tpu.gluon.model_zoo.transformer import TransformerModel
    from mxnet_tpu.ndarray.ndarray import NDArray
    from mxnet_tpu.parallel import TrainStep, hbm_budget_bytes, plan_batch
    import jax
    import jax.numpy as jnp

    V, key = args.vocab, args.max_len
    amp_dtype = args.amp or "bfloat16"
    remat = args.remat or "dots_saveable"

    class MaskedCE:
        def __call__(self, logits, label):
            x = logits.data.astype(jnp.float32)
            y = label.data
            mask = y >= 0
            safe = jnp.where(mask, y, 0).astype(jnp.int32)
            logp = jax.nn.log_softmax(x, axis=-1)
            nll = -jnp.take_along_axis(logp, safe[..., None],
                                       axis=-1)[..., 0]
            row = jnp.where(mask, nll, 0.0).sum(axis=-1)
            return NDArray(row.sum() / mask.sum())

    def make_step(**kw):
        net = TransformerModel(
            src_vocab=V, tgt_vocab=V, units=args.units,
            hidden_size=args.units * 2, num_layers=args.layers,
            num_heads=2, max_length=args.max_len + 8, dropout=0.0)
        net.initialize(mx.initializer.Xavier())
        net._probe_shapes(nd.zeros((2, 8), dtype="int32"),
                          nd.zeros((2, 8), dtype="int32"))
        return TrainStep(net, MaskedCE(), opt.AdamW(learning_rate=1e-4),
                         **kw)

    def sig(bs):
        return (((bs, key), "int32"), ((bs, key), "int32"),
                ((bs, key), "int32"))

    step32 = make_step()
    budget = hbm_budget_bytes()
    if budget is None:
        budget = step32.memory_analysis(
            sig(4 * args.batch_size))["peak_bytes_estimate"]
    b32, peak32 = plan_batch(step32, sig, budget, start=1,
                             max_batch=args.max_batch)
    step_ar = make_step(amp=amp_dtype, remat=remat)
    bar, peakar = plan_batch(step_ar, sig, budget, start=1,
                             max_batch=args.max_batch)

    def measure(step, bs, tag):
        if bs <= 0:
            return {"batch": 0, "steady_tokens_per_sec": 0.0}
        rng = np.random.RandomState(args.seed)
        batches = [tuple(nd.array(rng.randint(1, V, (bs, key)), dtype="int32")
                         for _ in range(3)) for _ in range(4)]
        step.warmup([sig(bs)])
        out = run_varlen_mode(step, lambda ep: iter(batches),
                              tokens_per_epoch=len(batches) * bs * key,
                              epochs=args.epochs)
        out["batch"] = bs
        out["hbm"] = step.memory_analysis(sig(bs))
        return out

    base = measure(step32, b32, "fp32")
    tuned = measure(step_ar, bar, "amp")
    row = {
        "metric": "transformer_amp_auto_batch_tokens_per_sec",
        "value": tuned["steady_tokens_per_sec"],
        "unit": "tokens/sec",
        "amp": amp_dtype, "remat": remat,
        "budget_bytes": int(budget),
        "fp32": base, "amp_remat": tuned,
    }
    print(json.dumps(row))
    print(f"budget {budget/1e6:.0f} MB @ seq {key}: fp32 fits batch "
          f"{b32} ({base['steady_tokens_per_sec']} tok/s steady), "
          f"{amp_dtype}+{remat} fits batch {bar} "
          f"({tuned['steady_tokens_per_sec']} tok/s steady), "
          f"{tuned.get('steady_state_recompiles', 0)} steady recompiles")
    ok = (bar > b32 and tuned.get("steady_state_recompiles", 1) == 0)
    if not ok:
        print("FAIL: amp+remat must fit a strictly larger batch with "
              "zero steady-state recompiles", file=sys.stderr)
    return 0 if ok else 1


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--variable-length", action="store_true",
                    help="run the bucketed-vs-unbucketed compile ablation")
    ap.add_argument("--amp", nargs="?", const="bfloat16", default=None,
                    help="mixed precision dtype (bfloat16/float16)")
    ap.add_argument("--remat", nargs="?", const="dots_saveable",
                    default=None,
                    help="remat policy (mxnet_tpu.remat.POLICIES)")
    ap.add_argument("--auto-batch", action="store_true",
                    help="memory-guided batch planning ablation: fp32 "
                         "vs amp+remat at their largest fitting batches")
    ap.add_argument("--mesh", default=None,
                    help="device mesh for the fixed-config row: '4', "
                         "'2x2' (data x model) or 'data=2,model=2' — the "
                         "step runs SPMD over that many devices and the "
                         "row carries mesh_shape/sharding columns")
    ap.add_argument("--sharding", default=None,
                    help="sharding rules with --mesh: 'replicated' "
                         "(data parallel) or 'fsdp' (default)")
    ap.add_argument("--decode", action="store_true",
                    help="KV-cached vs naive re-forward decode ablation")
    ap.add_argument("--decode-tokens", type=int, default=32,
                    help="tokens generated per row in --decode mode")
    ap.add_argument("--speculative", action="store_true",
                    help="with --decode: speculative-decoding ablation — "
                         "dense baseline vs paged sequential vs draft+"
                         "wide-verify, each with the Pallas paged flash "
                         "kernels off and forced (gate: spec >= 2x dense "
                         "at bit-identical greedy output)")
    ap.add_argument("--flash-paged", action="store_true",
                    help="with --decode: the kernel-only ablation rows "
                         "(dense vs paged, kernels off vs forced) "
                         "without the speculative rows")
    ap.add_argument("--spec-k", type=int, default=7,
                    help="draft tokens proposed per speculative round")
    ap.add_argument("--spec-layers", type=int, default=8,
                    help="target depth for --speculative (tail layers "
                         "are zeroed to identity so the 1-layer oracle "
                         "draft matches the target exactly)")
    ap.add_argument("--open-loop", type=float, nargs="?", const=500.0,
                    default=None, metavar="RATE",
                    help="with --decode: Poisson open-loop load at RATE "
                         "req/s (default 500 = saturating on the CPU "
                         "rig) through ContinuousBatcher vs the fixed "
                         "DynamicBatcher at the same mixed-length "
                         "workload")
    ap.add_argument("--page-size", type=int, default=None,
                    help="KV pool page size for --open-loop "
                         "(MXTPU_PAGE_SIZE default)")
    ap.add_argument("--iter-tokens", type=int, default=None,
                    help="decode tokens per scheduler iteration for "
                         "--open-loop (MXTPU_ITER_TOKENS default)")
    ap.add_argument("--prefix-mix", action="store_true",
                    help="prefix caching ablation: a shared-system-"
                         "prompt + multi-turn chat mix through the same "
                         "engine with the prefix trie on vs off (TTFT "
                         "p50 on prefix turns, hit rate, COW copies, "
                         "bit-identity gate)")
    ap.add_argument("--serve-chaos", action="store_true",
                    help="self-healing serving ablation: hot weight swap "
                         "+ replica kill under sustained router load")
    ap.add_argument("--disagg", action="store_true",
                    help="disaggregated prefill/decode ablation: a "
                         "mixed interactive+batch open-loop stream "
                         "against a co-scheduled fleet vs a 1-prefill + "
                         "(N-1)-decode fleet of the same size (per-class "
                         "TTFT + aggregate tokens/sec); use with "
                         "--procs N")
    ap.add_argument("--procs", type=int, default=0,
                    help="with --serve-chaos/--disagg: spawn N REAL "
                         "serving worker processes (serving.worker) "
                         "behind RemoteReplicas — the kill becomes "
                         "SIGKILL of a process, the swap a cross-process "
                         "two-phase flip, plus a shed flood against the "
                         "degraded fleet (0 = in-process replicas, the "
                         "PR-7 mode)")
    ap.add_argument("--max-batch", type=int, default=1024)
    ap.add_argument("--buckets", type=int, default=4)
    ap.add_argument("--batch-size", type=int, default=8)
    ap.add_argument("--samples", type=int, default=192)
    ap.add_argument("--min-len", type=int, default=8)
    ap.add_argument("--max-len", type=int, default=48)
    ap.add_argument("--vocab", type=int, default=1000)
    ap.add_argument("--units", type=int, default=32)
    ap.add_argument("--layers", type=int, default=1)
    ap.add_argument("--ratio", type=float, default=0.5,
                    help="FixedBucketSampler batch-scaling knob")
    ap.add_argument("--epochs", type=int, default=2)
    ap.add_argument("--seed", type=int, default=0)
    args = ap.parse_args(argv)
    if args.prefix_mix:
        return prefix_mix_main(args)
    if args.disagg:
        return disagg_main(args)
    if args.serve_chaos:
        if args.procs >= 2:
            return serve_chaos_procs_main(args)
        return serve_chaos_main(args)
    if args.open_loop is not None:
        return open_loop_main(args)
    if args.speculative or args.flash_paged:
        return speculative_main(args)
    if args.decode:
        return decode_main(args)
    if args.auto_batch:
        return amp_auto_batch_main(args)
    if args.variable_length:
        return variable_length_main(args)
    return fixed_main(amp=args.amp, remat=args.remat, mesh=args.mesh,
                      sharding=args.sharding)


if __name__ == "__main__":
    sys.exit(main() or 0)
