"""BASELINE config 4: Transformer-base WMT En-De train step (the config
that exercises graph fusion: encoder+decoder+tied-logits in one XLA
program via TrainStep, bf16 + AdamW)."""

from __future__ import annotations

import numpy as np

from .common import run_bench

BATCH, SRC_LEN, TGT_LEN = 64, 64, 64
STEPS_PER_CALL = 40
VOCAB = 32768
# derived ceiling (BASELINE.md arithmetic style): ~61M non-embedding params
# => ~0.37 GFLOPs/token train cost; 45% of v4 peak 275T => ~3.3e5 tok/s.
CEILING = 3.3e5


def main():
    import mxnet_tpu as mx
    from mxnet_tpu import gluon, nd, optimizer as opt
    from mxnet_tpu.gluon.model_zoo.transformer import transformer_base
    from mxnet_tpu.parallel import TrainStep

    net = transformer_base(src_vocab=VOCAB, tgt_vocab=VOCAB, max_length=512,
                           dropout=0.1)
    net.initialize(mx.initializer.Xavier())
    net._probe_shapes(nd.zeros((2, 8), dtype="int32"),
                      nd.zeros((2, 8), dtype="int32"))
    ce = gluon.loss.SoftmaxCrossEntropyLoss()

    class _Loss:
        def __call__(self, logits, label):
            return ce(logits.reshape(-1, VOCAB), label.reshape(-1))

    # steps_per_call: STEPS_PER_CALL full optimizer steps on as many
    # DISTINCT microbatches per dispatch (device-side scan,
    # parallel/step.py) — amortizes tunnel dispatch latency like a real
    # input pipeline
    step_fn = TrainStep(net, _Loss(), opt.AdamW(learning_rate=1e-4),
                        compute_dtype="bfloat16", state_dtype="bfloat16",
                        steps_per_call=STEPS_PER_CALL)
    rng = np.random.RandomState(0)
    n = BATCH * STEPS_PER_CALL
    src = nd.array(rng.randint(0, VOCAB, (n, SRC_LEN)), dtype="int32")
    tgt = nd.array(rng.randint(0, VOCAB, (n, TGT_LEN)), dtype="int32")
    labels = nd.array(rng.randint(0, VOCAB, (n, TGT_LEN)), dtype="int32")

    run_bench(
        "transformer_wmt_tokens_per_sec_per_chip", "tokens/sec", CEILING,
        lambda: step_fn(src, tgt, labels),
        lambda loss: float(loss.asscalar()),
        STEPS_PER_CALL * BATCH * TGT_LEN,
        warmup=2, steps=16,
    )


if __name__ == "__main__":
    main()
