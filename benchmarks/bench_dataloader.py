"""DataLoader backends on a Python-heavy decode/augment pipeline:
serial vs thread pool vs forked processes (the round-3 addition).

The per-sample work mimics the reference's JPEG-decode+augment profile:
mostly Python/GIL-bound (byte munging, per-pixel python loops) with some
numpy. Threads can't parallelize the GIL-bound part; processes can —
GIVEN CORES. This benchmark machine has os.sched_getaffinity == 1 CPU,
so here processes only add IPC overhead and threads/serial tie; the
output records all three so multi-core hosts can see the crossover
(worker parallelism itself is covered by tests/test_dataloader_mp.py).

    python -m benchmarks.bench_dataloader
"""

from __future__ import annotations

import json
import time

import numpy as np

from mxnet_tpu.gluon import data as gdata

N, DIM, BATCH = 256, (32, 32, 3), 16


class _AugmentDataset(gdata.Dataset):
    def __len__(self):
        return N

    def __getitem__(self, i):
        rng = np.random.RandomState(i)
        img = rng.randint(0, 255, DIM).astype(np.uint8)
        # GIL-bound "decode": python-level byte shuffling sized like a
        # real JPEG entropy-decode loop (~100k python ops per image)
        rows = [bytes(img[r].tobytes()) for r in range(DIM[0])]
        acc = 0
        for _ in range(12):
            for r in rows:
                for b in r:
                    acc = (acc * 31 + b) & 0xFFFF
        # numpy augment: flip + normalize + crop
        out = img[:, ::-1].astype(np.float32) / 255.0
        out = (out - 0.5) + (acc % 7) * 1e-4
        return out[2:30, 2:30]


def _time(loader):
    # epoch 0 warms the pipeline (fork startup for the mp backend — its
    # workers persist across epochs); time the steady-state epoch
    for b in loader:
        pass
    t0 = time.perf_counter()
    n = 0
    for b in loader:
        n += b.shape[0]
    return n / (time.perf_counter() - t0)


def main():
    import os

    ds = _AugmentDataset()
    serial = _time(gdata.DataLoader(ds, batch_size=BATCH))
    threads = _time(gdata.DataLoader(ds, batch_size=BATCH, num_workers=4,
                                     thread_pool=True))
    procs = _time(gdata.DataLoader(ds, batch_size=BATCH, num_workers=4))
    best = max(serial, threads, procs)
    print(json.dumps({
        "metric": "dataloader_augment_images_per_sec",
        "value": round(best, 1),
        "unit": "images/sec",
        "vs_baseline": round(best / max(serial, 1e-9), 4),
        "serial": round(serial, 1),
        "threads_x4": round(threads, 1),
        "processes_x4": round(procs, 1),
        "cpus": len(os.sched_getaffinity(0)),
    }))


if __name__ == "__main__":
    main()
