"""Shared benchmark harness: warmup, timed windows, driver JSON line."""

from __future__ import annotations

import json
import statistics
import time


def _quantile(sorted_vals, p):
    if not sorted_vals:
        return None
    if len(sorted_vals) == 1:
        return sorted_vals[0]
    rank = (p / 100.0) * (len(sorted_vals) - 1)
    lo = int(rank)
    hi = min(lo + 1, len(sorted_vals) - 1)
    frac = rank - lo
    return sorted_vals[lo] * (1 - frac) + sorted_vals[hi] * frac


def telemetry_fields(step_times=None, compile_time_s=None):
    """Uniform bench-row telemetry columns, null-safe everywhere.

    ``step_time_p50/p95`` come from the measured ``step_times`` (seconds)
    when the caller timed its own steps, else from the telemetry
    registry's ``trainer/step_time_s`` histogram (populated when
    ``MXNET_TELEMETRY=1`` and the workload steps through a Trainer).
    ``compile_time_s`` falls back to the ``jax.monitoring`` compile-event
    total; ``hbm_peak_bytes`` is None on backends without memory stats
    (CPU).
    """
    fields = {
        "step_time_p50": None,
        "step_time_p95": None,
        "compile_time_s": compile_time_s,
        "hbm_peak_bytes": None,
        "hbm_headroom_bytes": None,
        "amp_dtype": None,
        "remat_policy": None,
        "mesh_shape": None,
        "sharding": None,
        "shard_param_bytes_per_shard": None,
    }
    report = None
    try:
        from mxnet_tpu import telemetry as _tel

        report = _tel.report()
        fields["hbm_peak_bytes"] = _tel.hbm_peak_bytes()
        fields["hbm_headroom_bytes"] = _tel.hbm_headroom_bytes()
        info = _tel.run_info()
        fields["amp_dtype"] = info.get("amp_dtype")
        fields["remat_policy"] = info.get("remat_policy")
        # SPMD sharding columns (parallel.sharding): the mesh/rules the
        # row ran under and one device's share of the parameter bytes
        fields["mesh_shape"] = info.get("mesh_shape")
        fields["sharding"] = info.get("sharding")
        fields["shard_param_bytes_per_shard"] = _tel.registry().gauge(
            "shard/param_bytes_per_shard").value
    except Exception:  # noqa: BLE001 - telemetry must never kill a bench
        _tel = None
    if step_times:
        s = sorted(step_times)
        fields["step_time_p50"] = round(_quantile(s, 50), 6)
        fields["step_time_p95"] = round(_quantile(s, 95), 6)
    elif report is not None:
        fields["step_time_p50"] = report.get("step_time_p50")
        fields["step_time_p95"] = report.get("step_time_p95")
    if fields["compile_time_s"] is None and report is not None:
        fields["compile_time_s"] = report.get("compile_time_s")
    return fields


def infer_fields():
    """Decode-bench row columns from the ``infer/`` metric family
    (null-safe: all None/0 when the registry is empty). The recompile
    figure is the serving acceptance gate — it must be 0 after
    ``InferStep.warmup`` across the prompt-bucket menu."""
    fields = {
        "prefill_ms_p50": None,
        "decode_ms_per_token_p50": None,
        "infer_tokens_per_sec": None,
        "batch_occupancy": None,
        "queue_wait_ms_p50": None,
        "steady_state_recompiles": None,
        # continuous batching / paged KV columns (serving.
        # ContinuousBatcher): time-to-first-token, pool pressure,
        # admission flow and the backpressure/preemption counters
        "ttft_ms_p50": None,
        "ttft_ms_p95": None,
        "pages_in_use": None,
        "page_fragmentation": None,
        "admitted_per_iter_p50": None,
        "rejected_backpressure": None,
        "preempted": None,
    }
    try:
        from mxnet_tpu import telemetry as _tel

        snap = _tel.registry().snapshot()
        h = snap["histograms"]
        g = snap["gauges"]
        if "infer/prefill_ms" in h:
            fields["prefill_ms_p50"] = h["infer/prefill_ms"]["p50"]
        if "infer/decode_ms_per_token" in h:
            fields["decode_ms_per_token_p50"] = \
                h["infer/decode_ms_per_token"]["p50"]
        if "infer/queue_wait_ms" in h:
            fields["queue_wait_ms_p50"] = h["infer/queue_wait_ms"]["p50"]
        if "infer/ttft_ms" in h:
            fields["ttft_ms_p50"] = h["infer/ttft_ms"]["p50"]
            fields["ttft_ms_p95"] = h["infer/ttft_ms"]["p95"]
        if "infer/admitted_per_iter" in h:
            fields["admitted_per_iter_p50"] = \
                h["infer/admitted_per_iter"]["p50"]
        fields["infer_tokens_per_sec"] = g.get("infer/tokens_per_sec")
        fields["batch_occupancy"] = g.get("infer/batch_occupancy")
        fields["pages_in_use"] = g.get("infer/pages_in_use")
        fields["page_fragmentation"] = g.get("infer/page_fragmentation")
        fields["rejected_backpressure"] = snap["counters"].get(
            "infer/rejected_backpressure", 0)
        fields["preempted"] = snap["counters"].get("infer/preempted", 0)
        fields["steady_state_recompiles"] = snap["counters"].get(
            "compile/steady_state_recompiles", 0)
    except Exception:  # noqa: BLE001 - telemetry must never kill a bench
        pass
    return fields


def disagg_fields():
    """Disaggregated-serving bench-row columns from the ``disagg/``
    metric family plus the scaler counters (null-safe). NOTE the
    router-side registry only sees the router's half (per-class TTFT,
    fallback re-prefills, scale actions); worker-side adoption/push
    figures live in the worker processes and ride the health verb —
    benches report those separately."""
    fields = {
        "disagg_re_prefills": 0,
        "disagg_handoffs": 0,
        "kv_push_ms_p50": None,
        "kv_bytes": 0,
        "ttft_interactive_ms_p50": None,
        "ttft_interactive_ms_p95": None,
        "ttft_batch_ms_p50": None,
        "ttft_batch_ms_p95": None,
        "scale_up": 0,
        "scale_down": 0,
    }
    try:
        from mxnet_tpu import telemetry as _tel

        snap = _tel.registry().snapshot()
        h = snap["histograms"]
        c = snap["counters"]
        if "disagg/kv_push_ms" in h:
            fields["kv_push_ms_p50"] = h["disagg/kv_push_ms"]["p50"]
        if "disagg/ttft_interactive_ms" in h:
            fields["ttft_interactive_ms_p50"] = \
                h["disagg/ttft_interactive_ms"]["p50"]
            fields["ttft_interactive_ms_p95"] = \
                h["disagg/ttft_interactive_ms"]["p95"]
        if "disagg/ttft_batch_ms" in h:
            fields["ttft_batch_ms_p50"] = h["disagg/ttft_batch_ms"]["p50"]
            fields["ttft_batch_ms_p95"] = h["disagg/ttft_batch_ms"]["p95"]
        fields["disagg_re_prefills"] = c.get("disagg/re_prefills", 0)
        fields["disagg_handoffs"] = c.get("disagg/handoffs", 0)
        fields["kv_bytes"] = c.get("disagg/kv_bytes", 0)
        fields["scale_up"] = c.get("serve/scale_up", 0)
        fields["scale_down"] = c.get("serve/scale_down", 0)
    except Exception:  # noqa: BLE001 - telemetry must never kill a bench
        pass
    return fields


def trace_overhead_fields(run_fn, gate_pct=2.0, pairs=3):
    """Measure the distributed-tracing tax on a serving workload.

    Runs ``run_fn`` (a zero-arg callable driving one fixed batch of
    load) ``pairs`` times each with tracing forced OFF and forced ON
    (``serving.tracing.force`` — overrides ``MXTPU_TRACE`` for this
    process), interleaved so drift hits both arms equally, and reports
    the median-over-median overhead. Negative deltas (noise) clamp to
    0. Null-safe: any failure returns None columns rather than killing
    the bench row. ``trace_overhead_ok`` is the ≤``gate_pct`` gate the
    serving rows are accepted on."""
    fields = {"trace_overhead_pct": None, "trace_overhead_ok": None}
    try:
        from mxnet_tpu.serving import tracing as _tracing

        offs, ons = [], []
        try:
            for _ in range(pairs):
                _tracing.force(False)
                t0 = time.perf_counter()
                run_fn()
                offs.append(time.perf_counter() - t0)
                _tracing.force(True)
                t0 = time.perf_counter()
                run_fn()
                ons.append(time.perf_counter() - t0)
        finally:
            _tracing.force(None)
        off = statistics.median(offs)
        on = statistics.median(ons)
        pct = max(0.0, (on - off) / off * 100.0) if off > 0 else 0.0
        fields["trace_overhead_pct"] = round(pct, 2)
        fields["trace_overhead_ok"] = pct <= gate_pct
    except Exception:  # noqa: BLE001 - tracing must never kill a bench
        pass
    return fields


def run_bench(metric, unit, ceiling, step_fn, sync_fn, items_per_step,
              warmup=3, steps=20, windows=4):
    """Time ``step_fn`` and print the driver JSON line.

    ``sync_fn`` must force completion via a host transfer — on the tunneled
    TPU backend ``block_until_ready`` does not actually block. The tunneled
    chip is shared and noisy, so the loop is split into ``windows`` windows;
    the MEDIAN window rate is the metric of record (the honest central
    figure), with the best window and the full list reported alongside
    (a best-only figure selects favorable noise; advisor round-2 finding).

    Every row also carries ``step_time_p50/p95`` (per-step wall from the
    timed windows), ``compile_time_s`` (warmup+compile wall) and
    ``hbm_peak_bytes`` (None on CPU) — the telemetry columns the perf
    roadmap diagnoses from.
    """
    try:
        t0 = time.perf_counter()
        for _ in range(warmup):
            out = step_fn()
        sync_fn(out)
        compile_s = time.perf_counter() - t0
        per = max(1, steps // windows)
        rates = []
        step_times = []
        for _ in range(windows):
            t0 = time.perf_counter()
            for _ in range(per):
                out = step_fn()
            sync_fn(out)
            elapsed = time.perf_counter() - t0
            rates.append(per * items_per_step / elapsed)
            step_times.append(elapsed / per)
        value = statistics.median(rates)
        row = {
            "metric": metric,
            "value": round(value, 1),
            "unit": unit,
            "vs_baseline": round(value / ceiling, 4),
            "best": round(max(rates), 1),
            "windows": [round(r, 1) for r in rates],
        }
        row.update(telemetry_fields(step_times=step_times,
                                    compile_time_s=round(compile_s, 3)))
        print(json.dumps(row))
        return value
    except Exception as e:  # noqa: BLE001 - driver wants a line either way
        row = {
            "metric": metric,
            "value": 0.0,
            "unit": unit,
            "vs_baseline": 0.0,
            "error": f"{type(e).__name__}: {e}"[:300],
        }
        row.update(telemetry_fields())
        print(json.dumps(row))
        return 0.0


def run_varlen_mode(step, epoch_batches, tokens_per_epoch, epochs=2):
    """Drive a variable-length workload through a ``TrainStep`` and
    account its compiles exactly.

    ``epoch_batches(epoch)`` yields ``(input0, ..., label)`` batch tuples;
    ``tokens_per_epoch`` is the valid-token count of one full pass. The
    step's ``compile_guard`` counts one signature per compiled program, so
    ``signatures_per_epoch`` is the compile count each epoch paid and the
    LAST epoch's rate is the steady-state figure (first epochs absorb the
    compiles unless the caller warmed up first)."""
    guard = step.compile_guard
    sig_marks = [guard.signatures]
    tps = None
    for ep in range(epochs):
        t0 = time.perf_counter()
        last = None
        for batch in epoch_batches(ep):
            last = step(*batch)
        if last is not None:
            float(last.asscalar())  # retire the epoch's async dispatches
        elapsed = time.perf_counter() - t0
        sig_marks.append(guard.signatures)
        tps = tokens_per_epoch / elapsed
    return {
        "signatures_per_epoch": [
            sig_marks[i + 1] - sig_marks[i] for i in range(epochs)],
        "signatures_total": sig_marks[-1],
        "steady_state_recompiles": guard.steady_state_recompiles,
        "steady_tokens_per_sec": round(tps, 1),
    }


def device_us(fn, args, iters=6):
    """Per-call DEVICE op time (us) by summing the profiler's device-lane
    events — the round-4 verdict's fix for opperf: wall columns on the
    tunneled chip sit at the ~10 ms dispatch floor, so only
    profiler-counted device time can see an op regression. Ported from
    benchmarks/bench_linear_ce.py (where it drove the CE regime sweep)."""
    import glob
    import gzip
    import json as _json
    import shutil
    import tempfile

    import jax

    out = fn(*args)
    jax.block_until_ready(out)
    d = tempfile.mkdtemp(prefix="opperf_")
    try:
        jax.profiler.start_trace(d)
        for _ in range(iters):
            out = fn(*args)
        jax.block_until_ready(out)
        jax.profiler.stop_trace()
        path = glob.glob(f"{d}/plugins/profile/*/*.trace.json.gz")[0]
        with gzip.open(path) as f:
            tr = _json.load(f)
        # locate the device op lane from the trace's OWN metadata
        # ('/device:...' process, 'XLA Ops' thread) instead of a
        # hardcoded pid/tid that silently reads 0.0 on other rigs
        dev_pids = set()
        ops_lanes = set()
        for e in tr["traceEvents"]:
            if e.get("ph") != "M":
                continue
            name = (e.get("args") or {}).get("name", "")
            if e.get("name") == "process_name" and \
                    name.startswith("/device:"):
                dev_pids.add(e.get("pid"))
            elif e.get("name") == "thread_name" and name == "XLA Ops":
                ops_lanes.add((e.get("pid"), e.get("tid")))
        lanes = {ln for ln in ops_lanes if ln[0] in dev_pids}
        if not lanes:
            return None  # no device lane found: report n/a, never 0.0
        tot = 0.0
        for e in tr["traceEvents"]:
            if e.get("ph") == "X" and \
                    (e.get("pid"), e.get("tid")) in lanes:
                tot += e.get("dur", 0)
        return tot / iters if tot > 0 else None
    finally:
        shutil.rmtree(d, ignore_errors=True)
