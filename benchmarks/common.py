"""Shared benchmark harness: warmup, timed windows, driver JSON line."""

from __future__ import annotations

import json
import statistics
import time


def run_bench(metric, unit, ceiling, step_fn, sync_fn, items_per_step,
              warmup=3, steps=20, windows=4):
    """Time ``step_fn`` and print the driver JSON line.

    ``sync_fn`` must force completion via a host transfer — on the tunneled
    TPU backend ``block_until_ready`` does not actually block. The tunneled
    chip is shared and noisy, so the loop is split into ``windows`` windows;
    the MEDIAN window rate is the metric of record (the honest central
    figure), with the best window and the full list reported alongside
    (a best-only figure selects favorable noise; advisor round-2 finding).
    """
    try:
        for _ in range(warmup):
            out = step_fn()
        sync_fn(out)
        per = max(1, steps // windows)
        rates = []
        for _ in range(windows):
            t0 = time.perf_counter()
            for _ in range(per):
                out = step_fn()
            sync_fn(out)
            rates.append(per * items_per_step / (time.perf_counter() - t0))
        value = statistics.median(rates)
        print(json.dumps({
            "metric": metric,
            "value": round(value, 1),
            "unit": unit,
            "vs_baseline": round(value / ceiling, 4),
            "best": round(max(rates), 1),
            "windows": [round(r, 1) for r in rates],
        }))
        return value
    except Exception as e:  # noqa: BLE001 - driver wants a line either way
        print(json.dumps({
            "metric": metric,
            "value": 0.0,
            "unit": unit,
            "vs_baseline": 0.0,
            "error": f"{type(e).__name__}: {e}"[:300],
        }))
        return 0.0
