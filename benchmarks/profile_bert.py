"""Ablation profiler for the BERT pretrain step (BASELINE config 3).

Times step variants to attribute the gap to the 45%-MFU ceiling:
baseline / no-dropout / rbg-prng / no-vocab-head / dense-attention /
batch-64. Run on the real chip: ``python -m benchmarks.profile_bert``.
Writes a row per variant; use alongside ``jax.profiler`` traces.

``--variable-length`` runs the shape-stability ablation instead: the
same variable-length token stream fed unbucketed (pad to batch max, one
compiled program per distinct length) vs bucketed
(``FixedBucketSampler`` + pad-to-bucket + ``TrainStep.warmup``), with
compile counts from the step's ``compile_guard`` and steady-state
tokens/sec. Size the model down for CPU runs (``--units 64 --layers 2
--vocab 1000``).
"""

from __future__ import annotations

import argparse
import json
import sys
import time

import numpy as np


def _time_step(step, ids, labels, warmup=3, iters=10):
    for _ in range(warmup):
        loss = step(ids, labels)
    float(loss.asscalar())
    t0 = time.perf_counter()
    for _ in range(iters):
        loss = step(ids, labels)
    float(loss.asscalar())
    return (time.perf_counter() - t0) / iters


def build_and_time(batch=32, seq=128, dropout=0.1, vocab_head=True,
                   dense_attn=False, iters=10, amp=None, remat=None):
    import mxnet_tpu as mx
    from mxnet_tpu import gluon, optimizer as opt
    from mxnet_tpu.gluon.model_zoo.bert import BERTModel
    from mxnet_tpu.parallel import TrainStep

    if dense_attn:
        import jax.numpy as jnp
        from mxnet_tpu.ops import registry as _reg

        def _dense(q, k, v, valid_length=None, causal=False, sm_scale=1.0, **kw):
            s = jnp.einsum("bhqd,bhkd->bhqk", q, k) * sm_scale
            if valid_length is not None:
                mask = jnp.arange(k.shape[2])[None, None, None, :] < \
                    valid_length.astype(jnp.int32)[:, None, None, None]
                s = jnp.where(mask, s, -1e30)
            p = jnp.exp(s - s.max(axis=-1, keepdims=True))
            p = p / p.sum(axis=-1, keepdims=True)
            return jnp.einsum("bhqk,bhkd->bhqd", p, v).astype(q.dtype)

        saved = _reg.get("flash_attention").fn
        _reg.get("flash_attention").fn = _dense
    try:
        net = BERTModel(
            vocab_size=30522, units=768, hidden_size=3072, num_layers=12,
            num_heads=12, max_length=512, dropout=dropout,
        )
        net.initialize()
        net._probe_shapes(mx.nd.zeros((2, 8), dtype="int32"))
        ce = gluon.loss.SoftmaxCrossEntropyLoss()
        word_w = net.word_embed.weight

        def loss_fn(seq_out, pooled, label):
            if vocab_head:
                w = word_w.data()
                logits = seq_out.reshape(-1, seq_out.shape[-1]).dot(w.T)
                return ce(logits, label.reshape(-1))
            return (seq_out * seq_out).mean()

        # legacy cast-everything bf16 by default; --amp selects the
        # lists-driven AMP pass, --remat arms whole-graph remat
        precision = ({"amp": amp} if amp else
                     {"compute_dtype": "bfloat16",
                      "state_dtype": "bfloat16"})
        step = TrainStep(net, loss_fn, opt.AdamW(learning_rate=1e-4),
                         remat=remat, **precision)
        rng = np.random.RandomState(0)
        ids = mx.nd.array(rng.randint(0, 30522, (batch, seq)), dtype="int32")
        labels = mx.nd.array(rng.randint(0, 30522, (batch, seq)), dtype="int32")
        dt = _time_step(step, ids, labels, iters=iters)
    finally:
        if dense_attn:
            _reg.get("flash_attention").fn = saved
    return dt, batch * seq / dt


VARIANTS = {
    "baseline": {},
    "no_dropout": {"dropout": 0.0},
    "no_vocab_head": {"vocab_head": False},
    "dense_attn": {"dense_attn": True},
    "batch64": {"batch": 64},
    "batch64_nodrop": {"batch": 64, "dropout": 0.0},
}


# ------------------------------------------------------------- decode mode
def decode_main(args):
    """--decode: the inference-engine ablation for the encoder workload
    (BERT has no autoregressive head — its serving role is the PREFILL /
    scoring half, incl. BERT-as-encoder generation memory). Naive = the
    hybridized net fed batches padded to their own max length (one jitted
    predict program per distinct length, compiling forever); engine =
    ``InferStep`` fed bucket-padded batches with ``valid_length``, warmed
    over the ``FixedBucketSampler.signatures()`` menu — must hold ZERO
    steady-state recompiles. Steady tokens/sec for both, plus program
    counts, in the row."""
    import mxnet_tpu as mx
    from mxnet_tpu.gluon.data import FixedBucketSampler
    from mxnet_tpu.gluon.model_zoo.bert import BERTModel
    from mxnet_tpu.parallel import InferStep
    from .common import infer_fields

    V = args.vocab
    rng = np.random.RandomState(args.seed)
    lengths = rng.randint(args.min_len, args.max_len + 1,
                          size=args.samples).tolist()
    seqs = [rng.randint(1, V, size=n).astype("int32") for n in lengths]
    tokens_per_epoch = int(sum(lengths))
    net = BERTModel(
        vocab_size=V, units=args.units, hidden_size=args.units * 4,
        num_layers=args.layers, num_heads=max(1, args.units // 32),
        max_length=args.max_len + 8, dropout=0.0)
    net.initialize()
    net._probe_shapes(mx.nd.zeros((2, 8), dtype="int32"))

    def pad_batch(idxs, to_len):
        ids = np.zeros((len(idxs), to_len), "int32")
        vl = np.zeros((len(idxs),), "int32")
        for r, i in enumerate(idxs):
            ids[r, : lengths[i]] = seqs[i]
            vl[r] = lengths[i]
        return ids, np.zeros_like(ids), vl

    def epoch_order(ep):
        order = np.random.RandomState(args.seed + 1 + ep).permutation(
            len(seqs))
        return [order[i: i + args.batch_size].tolist()
                for i in range(0, len(order) - args.batch_size + 1,
                               args.batch_size)]

    # ---- naive: per-batch max-length padding through the hybridized net
    net.hybridize()
    naive_sigs = set()
    naive_tps = None
    for ep in range(args.epochs):
        t0 = time.perf_counter()
        for idxs in epoch_order(ep):
            ml = max(lengths[i] for i in idxs)
            ids, types, vl = pad_batch(idxs, ml)
            naive_sigs.add((len(idxs), ml))
            out = net(mx.nd.array(ids), mx.nd.array(types),
                      mx.nd.array(vl, dtype="int32"))
        float(out[1].asnumpy()[0, 0])  # retire the epoch
        naive_tps = tokens_per_epoch / (time.perf_counter() - t0)
    net.hybridize(False)

    # ---- engine: bucket-padded InferStep with warmed signature menu
    sampler = FixedBucketSampler(lengths, args.batch_size,
                                 num_buckets=args.buckets,
                                 last_batch="discard")
    eng = InferStep(net, amp=args.amp)
    warm_sigs = [
        (((bs, key), "int32"), ((bs, key), "int32"), ((bs,), "int32"))
        for bs, key in sampler.signatures()
    ]
    warm = eng.warmup(warm_sigs)
    eng_tps = None
    for ep in range(args.epochs):
        t0 = time.perf_counter()
        for idxs in epoch_order(ep):
            ml = max(lengths[i] for i in idxs)
            key = next(k for k in sampler.bucket_keys if ml <= k)
            ids, types, vl = pad_batch(idxs, key)
            out = eng(ids[: args.batch_size], types[: args.batch_size],
                      vl[: args.batch_size])
        float(out[1].asnumpy()[0, 0])
        eng_tps = tokens_per_epoch / (time.perf_counter() - t0)

    recompiles = eng.compile_guard.steady_state_recompiles
    row = {
        "metric": "bert_infer_bucketed_tokens_per_sec",
        "value": round(eng_tps, 1),
        "unit": "tokens/sec",
        "naive_tokens_per_sec": round(naive_tps, 1),
        "naive_programs": len(naive_sigs),
        "warmup_compiles": warm,
        "steady_state_recompiles": recompiles,
        "n_buckets": len(sampler.bucket_keys),
    }
    row.update(infer_fields())
    row["steady_state_recompiles"] = recompiles
    print(json.dumps(row))
    print(f"naive: {len(naive_sigs)} predict programs, {naive_tps:.0f} "
          f"tok/s; engine: {warm} warmed programs, {recompiles} steady "
          f"recompiles, {eng_tps:.0f} tok/s")
    return 0 if recompiles == 0 else 1


# ------------------------------------------------------ variable-length mode
def variable_length_main(args):
    import jax
    import jax.numpy as jnp

    import mxnet_tpu as mx
    from mxnet_tpu import compile_cache, optimizer as opt
    from mxnet_tpu.gluon.data import FixedBucketSampler
    from mxnet_tpu.gluon.model_zoo.bert import BERTModel
    from mxnet_tpu.ndarray.ndarray import NDArray
    from mxnet_tpu.parallel import TrainStep

    from .common import run_varlen_mode

    V = args.vocab
    rng = np.random.RandomState(args.seed)
    lengths = rng.randint(args.min_len, args.max_len + 1,
                          size=args.samples).tolist()
    seqs = [rng.randint(1, V, size=n).astype("int32") for n in lengths]
    tokens_per_epoch = int(sum(lengths))

    def make_step():
        net = BERTModel(
            vocab_size=V, units=args.units, hidden_size=args.units * 4,
            num_layers=args.layers, num_heads=max(1, args.units // 32),
            max_length=args.max_len + 8, dropout=0.0)
        net.initialize()
        net._probe_shapes(mx.nd.zeros((2, 8), dtype="int32"))
        word_w = net.word_embed.weight

        def loss_fn(seq_out, pooled, label):
            # masked MLM-style CE over valid (label != -1) tokens only,
            # reduced per row then across rows (pad columns contribute
            # exact zeros -> padded == unpadded bit-identically)
            w = word_w.data().data
            x = seq_out.data.astype(jnp.float32)
            logits = x @ w.T.astype(jnp.float32)
            y = label.data
            mask = y >= 0
            safe = jnp.where(mask, y, 0).astype(jnp.int32)
            logp = jax.nn.log_softmax(logits, axis=-1)
            nll = -jnp.take_along_axis(logp, safe[..., None],
                                       axis=-1)[..., 0]
            row = jnp.where(mask, nll, 0.0).sum(axis=-1)
            return NDArray(row.sum() / mask.sum())

        return TrainStep(net, loss_fn, opt.AdamW(learning_rate=1e-4),
                         amp=args.amp, remat=args.remat)

    def pad_batch(idxs, to_len):
        ids = np.zeros((len(idxs), to_len), "int32")
        lab = np.full((len(idxs), to_len), -1, "int32")
        for r, i in enumerate(idxs):
            ids[r, : lengths[i]] = seqs[i]
            lab[r, : lengths[i]] = seqs[i]
        return mx.nd.array(ids), mx.nd.array(lab)

    def unbucketed_epochs(ep):
        order = np.random.RandomState(args.seed + 1 + ep).permutation(
            len(seqs))
        for i in range(0, len(order) - args.batch_size + 1,
                       args.batch_size):
            idxs = order[i: i + args.batch_size].tolist()
            yield pad_batch(idxs, max(lengths[i] for i in idxs))

    step_u = make_step()
    unbucketed = run_varlen_mode(step_u, unbucketed_epochs,
                                 tokens_per_epoch, epochs=args.epochs)

    sampler = FixedBucketSampler(
        lengths, args.batch_size, num_buckets=args.buckets,
        ratio=args.ratio, shuffle=True, last_batch="pad")

    def bucketed_epochs(ep):
        np.random.seed(args.seed + 100 + ep)
        for idxs in sampler:
            ml = max(lengths[i] for i in idxs)
            key = next(k for k in sampler.bucket_keys if ml <= k)
            yield pad_batch(idxs, key)

    step_b = make_step()
    warm_sigs = [(((bs, key), "int32"), ((bs, key), "int32"))
                 for bs, key in sampler.signatures()]
    warm_compiles = step_b.warmup(warm_sigs)
    bucketed = run_varlen_mode(step_b, bucketed_epochs, tokens_per_epoch,
                               epochs=args.epochs)
    bucketed["warmup_compiles"] = warm_compiles
    bucketed["n_buckets"] = len(sampler.bucket_keys)

    row = {
        "metric": "bert_varlen_bucketed_tokens_per_sec",
        "value": bucketed["steady_tokens_per_sec"],
        "unit": "tokens/sec",
        "unbucketed": unbucketed,
        "bucketed": bucketed,
        "compile_cache": compile_cache.cache_stats(),
    }
    print(json.dumps(row))
    print(f"unbucketed: {unbucketed['signatures_total']} programs "
          f"({unbucketed['signatures_per_epoch']}/epoch), "
          f"{unbucketed['steady_tokens_per_sec']} tok/s")
    print(f"bucketed:   {bucketed['signatures_total']} programs "
          f"(warmup {warm_compiles} <= {bucketed['n_buckets']} buckets), "
          f"{bucketed['steady_state_recompiles']} steady recompiles, "
          f"{bucketed['steady_tokens_per_sec']} tok/s")
    return 0 if bucketed["steady_state_recompiles"] == 0 else 1


def main(argv=None):
    ap = argparse.ArgumentParser()
    ap.add_argument("--variants", nargs="*", default=list(VARIANTS))
    ap.add_argument("--rbg", action="store_true", help="use rbg PRNG impl")
    ap.add_argument("--amp", nargs="?", const="bfloat16", default=None,
                    help="lists-driven mixed precision (bfloat16/float16) "
                         "instead of the legacy cast-everything bf16")
    ap.add_argument("--remat", nargs="?", const="dots_saveable",
                    default=None,
                    help="remat policy (mxnet_tpu.remat.POLICIES)")
    ap.add_argument("--variable-length", action="store_true",
                    help="bucketed-vs-unbucketed compile ablation")
    ap.add_argument("--decode", action="store_true",
                    help="inference-engine (InferStep prefill) ablation: "
                         "naive per-length predict programs vs warmed "
                         "bucketed engine")
    ap.add_argument("--buckets", type=int, default=4)
    ap.add_argument("--batch-size", type=int, default=8)
    ap.add_argument("--samples", type=int, default=128)
    ap.add_argument("--min-len", type=int, default=16)
    ap.add_argument("--max-len", type=int, default=128)
    ap.add_argument("--vocab", type=int, default=30522)
    ap.add_argument("--units", type=int, default=768)
    ap.add_argument("--layers", type=int, default=12)
    ap.add_argument("--ratio", type=float, default=0.5)
    ap.add_argument("--epochs", type=int, default=2)
    ap.add_argument("--seed", type=int, default=0)
    args = ap.parse_args(argv)
    if args.decode:
        return decode_main(args)
    if args.variable_length:
        return variable_length_main(args)
    if args.rbg:
        import jax

        jax.config.update("jax_default_prng_impl", "rbg")
    for name in args.variants:
        dt, tps = build_and_time(amp=args.amp, remat=args.remat,
                                 **VARIANTS[name])
        print(f"{name:18s} step={dt*1e3:7.2f} ms  tokens/s={tps:10.0f}",
              flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main() or 0)
