"""Ablation profiler for the BERT pretrain step (BASELINE config 3).

Times step variants to attribute the gap to the 45%-MFU ceiling:
baseline / no-dropout / rbg-prng / no-vocab-head / dense-attention /
batch-64. Run on the real chip: ``python -m benchmarks.profile_bert``.
Writes a row per variant; use alongside ``jax.profiler`` traces.
"""

from __future__ import annotations

import argparse
import time

import numpy as np


def _time_step(step, ids, labels, warmup=3, iters=10):
    for _ in range(warmup):
        loss = step(ids, labels)
    float(loss.asscalar())
    t0 = time.perf_counter()
    for _ in range(iters):
        loss = step(ids, labels)
    float(loss.asscalar())
    return (time.perf_counter() - t0) / iters


def build_and_time(batch=32, seq=128, dropout=0.1, vocab_head=True,
                   dense_attn=False, iters=10):
    import mxnet_tpu as mx
    from mxnet_tpu import gluon, optimizer as opt
    from mxnet_tpu.gluon.model_zoo.bert import BERTModel
    from mxnet_tpu.parallel import TrainStep

    if dense_attn:
        import jax.numpy as jnp
        from mxnet_tpu.ops import registry as _reg

        def _dense(q, k, v, valid_length=None, causal=False, sm_scale=1.0, **kw):
            s = jnp.einsum("bhqd,bhkd->bhqk", q, k) * sm_scale
            if valid_length is not None:
                mask = jnp.arange(k.shape[2])[None, None, None, :] < \
                    valid_length.astype(jnp.int32)[:, None, None, None]
                s = jnp.where(mask, s, -1e30)
            p = jnp.exp(s - s.max(axis=-1, keepdims=True))
            p = p / p.sum(axis=-1, keepdims=True)
            return jnp.einsum("bhqk,bhkd->bhqd", p, v).astype(q.dtype)

        saved = _reg.get("flash_attention").fn
        _reg.get("flash_attention").fn = _dense
    try:
        net = BERTModel(
            vocab_size=30522, units=768, hidden_size=3072, num_layers=12,
            num_heads=12, max_length=512, dropout=dropout,
        )
        net.initialize()
        net._probe_shapes(mx.nd.zeros((2, 8), dtype="int32"))
        ce = gluon.loss.SoftmaxCrossEntropyLoss()
        word_w = net.word_embed.weight

        def loss_fn(seq_out, pooled, label):
            if vocab_head:
                w = word_w.data()
                logits = seq_out.reshape(-1, seq_out.shape[-1]).dot(w.T)
                return ce(logits, label.reshape(-1))
            return (seq_out * seq_out).mean()

        step = TrainStep(net, loss_fn, opt.AdamW(learning_rate=1e-4),
                         compute_dtype="bfloat16", state_dtype="bfloat16")
        rng = np.random.RandomState(0)
        ids = mx.nd.array(rng.randint(0, 30522, (batch, seq)), dtype="int32")
        labels = mx.nd.array(rng.randint(0, 30522, (batch, seq)), dtype="int32")
        dt = _time_step(step, ids, labels, iters=iters)
    finally:
        if dense_attn:
            _reg.get("flash_attention").fn = saved
    return dt, batch * seq / dt


VARIANTS = {
    "baseline": {},
    "no_dropout": {"dropout": 0.0},
    "no_vocab_head": {"vocab_head": False},
    "dense_attn": {"dense_attn": True},
    "batch64": {"batch": 64},
    "batch64_nodrop": {"batch": 64, "dropout": 0.0},
}


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--variants", nargs="*", default=list(VARIANTS))
    ap.add_argument("--rbg", action="store_true", help="use rbg PRNG impl")
    args = ap.parse_args()
    if args.rbg:
        import jax

        jax.config.update("jax_default_prng_impl", "rbg")
    for name in args.variants:
        dt, tps = build_and_time(**VARIANTS[name])
        print(f"{name:18s} step={dt*1e3:7.2f} ms  tokens/s={tps:10.0f}",
              flush=True)


if __name__ == "__main__":
    main()
