"""BASELINE config 1: LeNet MNIST, single-chip IMPERATIVE NDArray path.

The point of this config is eager-dispatch overhead (the reference measured
the engine's per-op push cost; here it is per-op XLA dispatch): no
hybridize(), no fused TrainStep — autograd.record + backward + Trainer.step
per batch, exactly the reference ``example/gluon`` MNIST loop [unverified].
"""

from __future__ import annotations

import numpy as np

from .common import run_bench

BATCH = 128
# ceiling: LeNet is ~4.6 MFLOPs/image fwd (~14M train); at the BASELINE.md
# v4 45%-MFU framing that'd be ~9e6 img/s — absurd for an op-dispatch-bound
# eager loop, so the honest denominator is dispatch rate: ~60 engine pushes
# per step; the reference's imperative path sustained O(1e4) small-batch
# img/s on accelerators. Target 2e4 img/s.
CEILING = 2.0e4


def main():
    import mxnet_tpu as mx
    from mxnet_tpu import autograd, gluon, nd

    net = gluon.nn.HybridSequential()
    with net.name_scope():
        net.add(
            gluon.nn.Conv2D(20, kernel_size=5, activation="tanh"),
            gluon.nn.MaxPool2D(pool_size=2, strides=2),
            gluon.nn.Conv2D(50, kernel_size=5, activation="tanh"),
            gluon.nn.MaxPool2D(pool_size=2, strides=2),
            gluon.nn.Flatten(),
            gluon.nn.Dense(500, activation="tanh"),
            gluon.nn.Dense(10),
        )
    net.initialize(mx.initializer.Xavier())
    loss_fn = gluon.loss.SoftmaxCrossEntropyLoss()
    trainer = gluon.Trainer(net.collect_params(), "sgd",
                            {"learning_rate": 0.02, "momentum": 0.9})

    rng = np.random.RandomState(0)
    x = nd.array(rng.rand(BATCH, 1, 28, 28).astype(np.float32))
    y = nd.array(rng.randint(0, 10, BATCH).astype(np.float32))

    def step():
        with autograd.record():
            loss = loss_fn(net(x), y)
        loss.backward()
        trainer.step(BATCH)
        return loss

    run_bench(
        "lenet_mnist_imperative_images_per_sec", "images/sec", CEILING,
        step, lambda loss: float(loss.mean().asscalar()), BATCH,
        warmup=3, steps=120,
    )
    # steps=120 (round 5): with the host loop bulked to ~3.6 ms/step the
    # 4 windows were dominated by the fixed ~90 ms tunnel sync RTT each
    # pays on its single 4-byte fetch; longer windows amortize that fixed
    # cost the same way the training configs' steps_per_call scans do.
    # The sync still waits for the WINDOW'S ENTIRE queued work, so the
    # rate is sustained throughput, not queueing.


if __name__ == "__main__":
    main()
