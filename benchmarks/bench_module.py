"""Legacy Module/KVStore training loop (round-4 verdict weak #7: the
reference's §3.3/§3.4 path — symbol simple_bind executor +
forward/backward + per-param updater through the Module API — had no
perf floor; every other bench runs TrainStep).

    python -m benchmarks.bench_module
"""

from __future__ import annotations

import numpy as np

from .common import run_bench

BATCH = 128
# same config-1 dispatch-rate framing as bench_lenet (this is the same
# model on the LEGACY path; the delta between the two rows is the cost
# of the Module/executor machinery vs the gluon eager loop)
CEILING = 2.0e4


def main():
    import mxnet_tpu as mx
    from mxnet_tpu import sym, nd

    data = sym.var("data")
    c1 = sym.Convolution(data, sym.var("c1w"), sym.var("c1b"),
                         kernel=(5, 5), num_filter=20)
    t1 = sym.Activation(c1, act_type="tanh")
    p1 = sym.Pooling(t1, pool_type="max", kernel=(2, 2), stride=(2, 2))
    c2 = sym.Convolution(p1, sym.var("c2w"), sym.var("c2b"),
                         kernel=(5, 5), num_filter=50)
    t2 = sym.Activation(c2, act_type="tanh")
    p2 = sym.Pooling(t2, pool_type="max", kernel=(2, 2), stride=(2, 2))
    fl = sym.Flatten(p2)
    f1 = sym.FullyConnected(fl, sym.var("f1w"), sym.var("f1b"),
                            num_hidden=500)
    t3 = sym.Activation(f1, act_type="tanh")
    f2 = sym.FullyConnected(t3, sym.var("f2w"), sym.var("f2b"),
                            num_hidden=10)
    out = sym.SoftmaxOutput(f2, sym.var("softmax_label"))

    from mxnet_tpu.module import Module

    mod = Module(out, data_names=("data",),
                        label_names=("softmax_label",))
    mod.bind(data_shapes=[("data", (BATCH, 1, 28, 28))],
             label_shapes=[("softmax_label", (BATCH,))])
    mod.init_params()
    mod.init_optimizer(optimizer="sgd",
                       optimizer_params=(("learning_rate", 0.02),
                                         ("momentum", 0.9)))

    rng = np.random.RandomState(0)
    x = nd.array(rng.rand(BATCH, 1, 28, 28).astype(np.float32))
    y = nd.array(rng.randint(0, 10, BATCH).astype(np.float32))

    class _Batch:
        data = [x]
        label = [y]

    def step():
        mod.forward(_Batch)
        mod.backward()
        mod.update()
        return mod.get_outputs()[0]

    run_bench(
        "lenet_module_kvstore_images_per_sec", "images/sec", CEILING,
        step, lambda out: float(out.mean().asscalar()), BATCH,
        warmup=3, steps=30,
    )


if __name__ == "__main__":
    main()
