"""BASELINE config 5 (second half): Faster R-CNN two-stage training step
— backbone -> RPN -> Proposal (static-K NMS) -> deterministic sampler ->
batched ROIAlign -> RCNN heads, all in ONE jitted program.

SSD covers the one-stage half of config 5 (bench_ssd); this measures the
two-stage pipeline the reference ran via ``proposal.cc`` + the rcnn
example [unverified]."""

from __future__ import annotations

import numpy as np

from .common import run_bench

BATCH = 16
IMG = 256
# no reference number exists (BASELINE.json published={}); first-measured
# round-3 value becomes the regression floor, like bench_ssd's.
CEILING = 1.0e3


def main():
    import jax
    import jax.numpy as jnp

    import mxnet_tpu as mx
    from mxnet_tpu import gluon, nd
    from mxnet_tpu.gluon.block import _trace_scope
    from mxnet_tpu.gluon.model_zoo.faster_rcnn import FasterRCNN
    from mxnet_tpu.gluon.parameter import param_override
    from mxnet_tpu.ndarray.ndarray import NDArray
    from mxnet_tpu import autograd

    net = FasterRCNN(num_classes=20, channels=(32, 64, 128),
                     scales=(2, 4, 8), rpn_pre_nms_top_n=1024,
                     rpn_post_nms_top_n=128, num_sample=64,
                     top_units=256)
    net.initialize(mx.initializer.Xavier())
    rng = np.random.RandomState(0)
    x = nd.array(rng.rand(2, 3, IMG, IMG).astype(np.float32))
    gt_small = nd.array(
        np.tile([[0, 32, 32, 96, 96], [-1, 0, 0, 0, 0]], (2, 1, 1))
        .astype(np.float32))
    net(x, gt_small)  # resolve shapes

    params = list(net.collect_params().items())
    name2param = dict(params)
    vals = {n: p.data().data for n, p in params}
    ce = gluon.loss.SoftmaxCrossEntropyLoss()
    huber = gluon.loss.HuberLoss()

    def loss_fn(vals, xb, gtb):
        mapping = {name2param[n]: NDArray(v) for n, v in vals.items()}
        with param_override(mapping), _trace_scope(), \
                autograd._scope(False, True):
            (cls, box, cls_t, box_t, box_m, rpn_cls, rpn_box, _rois) = net(
                NDArray(xb), NDArray(gtb))
            feat_hw = (IMG // net._stride, IMG // net._stride)
            bt, bm, ct = net.rpn_dense_targets(
                NDArray(gtb), (IMG, IMG), feat_hw)
            logits, deltas = net.rpn_per_anchor(rpn_cls, rpn_box)
            L = (ce(logits.reshape(-1, 2), ct.reshape(-1)).mean()
                 + huber(deltas * bm, bt * bm).mean() / (bm.mean() + 1e-6)
                 + ce(cls.reshape(-1, cls.shape[-1]),
                      cls_t.reshape(-1)).mean()
                 + huber(box * box_m, box_t).mean()
                 / (box_m.mean() + 1e-6))
        return L.data.astype(jnp.float32)

    # full train step: forward + backward + SGD apply in ONE executable,
    # params donated — same contract as every other config's TrainStep;
    # STEPS_PER_CALL steps scanned per dispatch (tunnel amortization,
    # same as every other round-4 config)
    STEPS_PER_CALL = 20

    def one_step(vals, xb, gtb):
        L, grads = jax.value_and_grad(loss_fn)(vals, xb, gtb)
        new_vals = {n: v - 0.01 * grads[n] for n, v in vals.items()}
        return L, new_vals

    @jax.jit
    def train_step(vals, xb, gtb):
        def body(carry, i):
            L, nv = one_step(carry, xb, gtb)
            return nv, L

        vals2, Ls = jax.lax.scan(
            body, vals, jnp.arange(STEPS_PER_CALL, dtype=jnp.float32))
        return Ls.mean(), vals2

    xb = jnp.asarray(rng.rand(BATCH, 3, IMG, IMG).astype(np.float32))
    gtb = np.full((BATCH, 4, 5), -1, np.float32)
    for b in range(BATCH):
        cx, cy = rng.randint(48, IMG - 48, 2)
        gtb[b, 0] = [rng.randint(0, 20), cx - 32, cy - 32, cx + 32, cy + 32]
    gtb = jnp.asarray(gtb)

    state = {"vals": vals}

    def step():
        L, state["vals"] = train_step(state["vals"], xb, gtb)
        return L

    run_bench(
        "faster_rcnn_two_stage_train_images_per_sec", "images/sec",
        CEILING, step, lambda out: float(out), BATCH * STEPS_PER_CALL,
        warmup=2, steps=8,
    )


if __name__ == "__main__":
    main()
