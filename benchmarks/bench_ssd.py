"""BASELINE config 5: SSD/Faster-RCNN detection head — the custom CV ops
(box_decode -> box_nms -> ROIAlign over kept boxes), jitted end-to-end.

The backbone is config 2's job; this isolates the contrib detection ops
the reference implemented as CUDA kernels (``bounding_box.cc``,
``roi_align.cc`` [unverified])."""

from __future__ import annotations

import functools

import numpy as np

from .common import run_bench

BATCH = 32
NUM_ANCHORS = 4096
NUM_ROIS = 100
# no reference number exists (BASELINE.json published={}); target = first
# measured round-2 value (recorded in BASELINE.md) so regressions show.
CEILING = 3.9e3


def main():
    import jax
    import jax.numpy as jnp
    from mxnet_tpu.ops import contrib as C

    rng = np.random.RandomState(0)
    # synthetic head inputs: per-anchor box deltas, scores, FPN feature map
    deltas = jnp.asarray(rng.randn(BATCH, NUM_ANCHORS, 4).astype(np.float32))
    cx = rng.rand(BATCH, NUM_ANCHORS, 2).astype(np.float32)
    wh = (rng.rand(BATCH, NUM_ANCHORS, 2) * 0.2 + 0.05).astype(np.float32)
    anchors = jnp.asarray(
        np.concatenate([cx - wh / 2, cx + wh / 2], -1)
    )
    scores = jnp.asarray(rng.rand(BATCH, NUM_ANCHORS, 1).astype(np.float32))
    feats = jnp.asarray(rng.randn(BATCH, 256, 64, 64).astype(np.float32))

    def head(deltas, anchors, scores, feats):
        boxes = C.box_decode(deltas, anchors, format="corner")
        dets = jnp.concatenate([jnp.zeros_like(scores), scores, boxes], -1)
        kept = C.box_nms(dets, overlap_thresh=0.5, topk=NUM_ROIS,
                         coord_start=2, score_index=1, id_index=0)
        # box_nms is position-preserving (suppressed scores -> -1 in place),
        # so gather the actual survivors by top-k on the output scores
        _, idx = jax.lax.top_k(kept[:, :, 1], NUM_ROIS)
        survivors = jnp.take_along_axis(kept, idx[:, :, None], axis=1)
        # survivor rois per image -> batched ROIAlign (B, K, 4): rois stay
        # grouped by image, so no per-ROI whole-image gather (the flat
        # (R, 5) form moved ~4 MB of feature map per ROI through HBM)
        rois_xy = survivors[:, :, 2:6] * 64.0
        pooled = C.roi_align(feats, rois_xy, pooled_size=(7, 7),
                             spatial_scale=1.0, sample_ratio=2)
        return kept, pooled

    CALLS_PER_DISPATCH = 64

    @jax.jit
    def head_n(deltas, anchors, scores, feats):
        # CALLS_PER_DISPATCH full head evaluations per dispatch
        # (device-side scan, the same tunnel-latency amortization the
        # training configs use); scores are perturbed per iteration so
        # XLA cannot hoist the loop body
        def body(acc, i):
            kept, pooled = head(deltas, anchors,
                                scores + i * 1e-6, feats)
            return acc + jnp.sum(pooled[:1]) + jnp.sum(kept[:1, :1]), None

        acc, _ = jax.lax.scan(
            body, jnp.float32(0.0),
            jnp.arange(CALLS_PER_DISPATCH, dtype=jnp.float32))
        return acc

    run_bench(
        "ssd_head_box_decode_nms_roialign_images_per_sec", "images/sec",
        CEILING, functools.partial(head_n, deltas, anchors, scores, feats),
        # sync via the scalar the scan already reduced: a single 4-byte
        # fetch (pulling any tensor slice would time the tunnel instead)
        float, BATCH * CALLS_PER_DISPATCH,
        warmup=3, steps=8,
    )


if __name__ == "__main__":
    main()
