"""Roofline accounting for a BASELINE config's compiled training step.

Answers the round-3 verdict's ResNet-50 question with measurements
instead of hope: XLA's own ``cost_analysis`` (flops + bytes accessed) on
the exact compiled step vs the chip's peaks, side by side with the
traced device time and the top individual device ops.

    python -m benchmarks.roofline --config resnet50 [--layout NHWC]

v5e (TPU v5 lite) peaks used: 197 TFLOP/s bf16, 819 GB/s HBM.
"""

from __future__ import annotations

import argparse
import collections
import glob
import gzip
import json
import shutil
import tempfile

PEAK_FLOPS = 197e12
PEAK_BW = 819e9


def top_ops(trace_dir, steps, k=25):
    path = glob.glob(f"{trace_dir}/plugins/profile/*/*.trace.json.gz")[0]
    with gzip.open(path) as f:
        tr = json.load(f)
    agg = collections.Counter()
    tot = 0.0
    for e in tr["traceEvents"]:
        if e.get("ph") == "X" and e.get("pid") == 3 and e.get("tid") == 3:
            tot += e.get("dur", 0)
            agg[e["name"]] += e.get("dur", 0)
    print(f"device busy per step: {tot / steps / 1e3:.2f} ms; "
          f"top {k} individual ops:")
    for name, d in agg.most_common(k):
        print(f"{d / steps / 1e3:8.3f} ms  {name}")
    return tot / steps / 1e3


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--config", default="resnet50")
    ap.add_argument("--layout", default="NCHW")
    ap.add_argument("--batch", type=int, default=None)
    ap.add_argument("--steps", type=int, default=5)
    ap.add_argument("--top", type=int, default=25)
    ap.add_argument("--skip-trace", action="store_true")
    ap.add_argument("--remat", default=None, choices=(None, "full", "dots"))
    args = ap.parse_args()

    from . import trace_config as tc
    from .trace_bert import capture

    if args.config == "resnet50":
        step, x, y, items = tc.build_resnet50(args.batch or 64, args.layout)
    elif args.config == "transformer":
        step, x, y, items = tc.build_transformer(args.batch or 64,
                                                 remat=args.remat)
    else:
        raise SystemExit(f"unsupported config {args.config}")

    xs = x if isinstance(x, tuple) else (x,)
    float(step(*xs, y).asscalar())  # compile + stash avals
    spc = getattr(step, "_steps_per_call", 1)
    c = step.cost_analysis()
    flops = c.get("flops", 0.0) / spc
    bytes_ = c.get("bytes accessed", 0.0) / spc
    t_f = flops / PEAK_FLOPS * 1e3
    t_b = bytes_ / PEAK_BW * 1e3
    print(f"XLA cost_analysis (per optimizer step, steps_per_call={spc}): "
          f"{flops / 1e12:.3f} TFLOP, {bytes_ / 1e9:.3f} GB accessed")
    print(f"roofline floors: compute {t_f:.2f} ms, memory {t_b:.2f} ms "
          f"-> {max(t_f, t_b):.2f} ms")
    if args.skip_trace:
        return
    trace_dir = tempfile.mkdtemp(prefix="roofline_")
    capture(lambda a, b: step(*xs, y), x, y, trace_dir, args.steps)
    ms = top_ops(trace_dir, args.steps, args.top) / spc
    floor = max(t_f, t_b)
    print(f"per-step device busy: {ms:.2f} ms; measured/floor = "
          f"{ms / floor:.2f}x; device-bound items/s: {items / ms * 1e3:.0f}")
    shutil.rmtree(trace_dir, ignore_errors=True)


if __name__ == "__main__":
    main()
