"""Capture a device-op trace for any BASELINE config and print the
breakdown (the generalization of ``trace_bert`` the round-2 verdict asked
for — wall clock on the shared tunnel swings; device timelines do not).

    python -m benchmarks.trace_config --config resnet50|transformer|ssd|lenet
"""

from __future__ import annotations

import argparse
import shutil
import tempfile

import numpy as np

from .trace_bert import analyze


def build_resnet50(batch=64, layout="NCHW"):
    import mxnet_tpu as mx
    from mxnet_tpu import gluon, nd, optimizer as opt
    from mxnet_tpu.gluon.model_zoo.vision import get_model
    from mxnet_tpu.parallel import TrainStep

    net = get_model("resnet50_v1", layout=layout)
    net.initialize(mx.initializer.Xavier())
    shape = (2, 224, 224, 3) if layout == "NHWC" else (2, 3, 224, 224)
    net._probe_shapes(nd.zeros(shape))
    loss_fn = gluon.loss.SoftmaxCrossEntropyLoss()
    step = TrainStep(net, lambda o, l: loss_fn(o, l),
                     opt.SGD(learning_rate=0.1, momentum=0.9),
                     compute_dtype="bfloat16", state_dtype="bfloat16")
    rng = np.random.RandomState(0)
    xshape = (batch, 224, 224, 3) if layout == "NHWC" \
        else (batch, 3, 224, 224)
    x = nd.array(rng.rand(*xshape).astype(np.float32))
    y = nd.array(rng.randint(0, 1000, batch).astype(np.float32))
    return step, x, y, batch


def build_transformer(batch=32, seq=64, remat=None):
    import mxnet_tpu as mx
    from mxnet_tpu import gluon, nd, optimizer as opt
    from mxnet_tpu.gluon.model_zoo.transformer import transformer_base
    from mxnet_tpu.parallel import TrainStep

    net = transformer_base(src_vocab=32768, tgt_vocab=32768,
                           max_length=512, dropout=0.1)
    net.initialize(mx.initializer.Xavier())
    net._probe_shapes(nd.zeros((2, 8), dtype="int32"),
                      nd.zeros((2, 8), dtype="int32"))
    ce = gluon.loss.SoftmaxCrossEntropyLoss()

    def loss_fn(logits, label):
        return ce(logits.reshape(-1, logits.shape[-1]), label.reshape(-1))

    step = TrainStep(net, loss_fn, opt.Adam(learning_rate=1e-4),
                     compute_dtype="bfloat16", state_dtype="bfloat16",
                     remat=remat)
    rng = np.random.RandomState(0)
    src = nd.array(rng.randint(0, 32000, (batch, seq)), dtype="int32")
    tgt = nd.array(rng.randint(0, 32000, (batch, seq)), dtype="int32")
    return step, (src, tgt), tgt, batch * seq


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--config", default="resnet50",
                    choices=("resnet50", "transformer"))
    ap.add_argument("--batch", type=int, default=None)
    ap.add_argument("--layout", default="NCHW", choices=("NCHW", "NHWC"))
    ap.add_argument("--steps", type=int, default=5)
    ap.add_argument("--keep", default=None)
    args = ap.parse_args()
    if args.config == "resnet50":
        step, x, y, items = build_resnet50(args.batch or 64, args.layout)
        inputs = (x, y)
    else:
        step, srctgt, y, items = build_transformer(args.batch or 32)
        inputs = (*srctgt, y)
    trace_dir = args.keep or tempfile.mkdtemp(prefix=f"{args.config}_trace_")
    import jax
    for _ in range(3):
        loss = step(*inputs)
    float(loss.asscalar())
    jax.profiler.start_trace(trace_dir)
    for _ in range(args.steps):
        loss = step(*inputs)
    float(loss.asscalar())
    jax.profiler.stop_trace()
    ms = analyze(trace_dir, args.steps)
    print(f"device-bound items/s: {items / (ms / 1e3):.0f}")
    if not args.keep:
        shutil.rmtree(trace_dir, ignore_errors=True)


if __name__ == "__main__":
    main()
