"""BASELINE config 2: ResNet-50 synthetic-ImageNet train throughput,
hybridized (fused TrainStep: forward+backward+SGD in one XLA program,
donated buffers, bf16 compute / f32 masters)."""

from __future__ import annotations

import numpy as np

from .common import run_bench

BATCH = 64
STEPS_PER_CALL = 20
# BASELINE.md derived ceiling: ~1e4 images/s/chip at the (optimistic) 45%
# matmul-MFU framing on v4; ResNet is conv/memory-bound so well below.
CEILING = 1.0e4


def main():
    import mxnet_tpu as mx
    from mxnet_tpu import gluon, nd, optimizer as opt
    from mxnet_tpu.gluon.model_zoo.vision import get_model
    from mxnet_tpu.parallel import TrainStep

    net = get_model("resnet50_v1")
    net.initialize(mx.initializer.Xavier())
    net._probe_shapes(nd.zeros((2, 3, 224, 224)))
    loss_fn = gluon.loss.SoftmaxCrossEntropyLoss()

    class _Loss:
        def __call__(self, out, label):
            return loss_fn(out, label)

    # STEPS_PER_CALL full optimizer steps per dispatch on distinct microbatches
    # (device-side scan) — amortizes tunnel dispatch latency
    step_fn = TrainStep(net, _Loss(),
                        opt.SGD(learning_rate=0.1, momentum=0.9),
                        compute_dtype="bfloat16", state_dtype="bfloat16",
                        steps_per_call=STEPS_PER_CALL)
    rng = np.random.RandomState(0)
    n = BATCH * STEPS_PER_CALL
    x = nd.array(rng.rand(n, 3, 224, 224).astype(np.float32))
    y = nd.array(rng.randint(0, 1000, n).astype(np.float32))

    run_bench(
        "resnet50_synthetic_imagenet_images_per_sec", "images/sec", CEILING,
        lambda: step_fn(x, y), lambda loss: float(loss.asscalar()),
        STEPS_PER_CALL * BATCH,
        warmup=2, steps=24,
    )


if __name__ == "__main__":
    main()
