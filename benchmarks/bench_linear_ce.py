"""Regime benchmark: blocked ``linear_cross_entropy`` vs materialized
logits (round-3 verdict item 5: the op lost on BERT's V=30k — find the
regime where it wins, or prove there is none on this chip).

Sweeps V x (B*S), forward+backward per step, profiler device timing
(wall timing over the tunnel is untrustworthy — see traces/README).

    python -m benchmarks.bench_linear_ce [--quick]
"""

from __future__ import annotations

import argparse

import numpy as np


def device_ms(fn, args, iters=6):
    """Profiler-sum of device op time per call, in ms (shared helper:
    metadata-driven lane detection lives in benchmarks/common.py)."""
    from .common import device_us

    us = device_us(fn, args, iters=iters)
    return us / 1e3 if us is not None else float("nan")


def main():
    import jax
    import jax.numpy as jnp
    from mxnet_tpu.ops.fused_loss import linear_cross_entropy

    ap = argparse.ArgumentParser()
    ap.add_argument("--quick", action="store_true")
    args = ap.parse_args()

    D = 768
    Vs = [30522, 131072] if args.quick else [30522, 131072, 262144]
    Ns = [8192] if args.quick else [8192, 32768]
    rng = np.random.RandomState(0)
    print(f"| V | B*S | naive ms | fused ms | winner |")
    print(f"|---|---|---|---|---|")
    results = []
    for V in Vs:
        for N in Ns:
            x = jnp.asarray(rng.rand(N, D).astype(np.float32)).astype(jnp.bfloat16)
            w = jnp.asarray((rng.rand(V, D).astype(np.float32) - 0.5) * 0.02).astype(jnp.bfloat16)
            y = jnp.asarray(rng.randint(0, V, N).astype(np.int32))

            def naive_loss(x, w, y):
                logits = jnp.dot(x, w.T,
                                 preferred_element_type=jnp.float32)
                lse = jax.scipy.special.logsumexp(logits, axis=-1)
                lab = jnp.take_along_axis(logits, y[:, None], 1)[:, 0]
                return jnp.mean(lse - lab)

            def fused_loss(x, w, y):
                return jnp.mean(linear_cross_entropy(x, w, y))

            naive = jax.jit(jax.grad(naive_loss, argnums=(0, 1)))
            fused = jax.jit(jax.grad(fused_loss, argnums=(0, 1)))
            try:
                t_n = device_ms(naive, (x, w, y))
            except Exception as e:  # OOM at large V*N
                t_n = float("inf")
                print(f"naive failed at V={V} N={N}: {type(e).__name__}",
                      flush=True)
            t_f = device_ms(fused, (x, w, y))
            win = "fused" if t_f < t_n else "naive"
            print(f"| {V} | {N} | {t_n:.2f} | {t_f:.2f} | {win} |",
                  flush=True)
            results.append((V, N, t_n, t_f))
    return results


if __name__ == "__main__":
    main()
