"""Capture a profiler trace of the BERT train step and print the device-op
breakdown (noise-free device-busy time — wall clock on the shared tunnel
swings 2-3x, device timelines do not).

    python -m benchmarks.trace_bert [--batch 64] [--keep /tmp/dir]
"""

from __future__ import annotations

import argparse
import collections
import glob
import gzip
import json
import shutil
import tempfile

import numpy as np


def build_step(batch, seq=128, loss="fused"):
    import mxnet_tpu as mx
    from mxnet_tpu import gluon, optimizer as opt
    from mxnet_tpu.gluon.model_zoo.bert import BERTModel
    from mxnet_tpu.parallel import TrainStep

    net = BERTModel(vocab_size=30522, units=768, hidden_size=3072,
                    num_layers=12, num_heads=12, max_length=512, dropout=0.1)
    net.initialize()
    net._probe_shapes(mx.nd.zeros((2, 8), dtype="int32"))
    ce = gluon.loss.SoftmaxCrossEntropyLoss()
    word_w = net.word_embed.weight

    def loss_fn(seq_out, pooled, label):
        w = word_w.data()
        if loss == "fused":
            return mx.nd.linear_cross_entropy(seq_out, w, label)
        logits = seq_out.reshape(-1, seq_out.shape[-1]).dot(w.T)
        return ce(logits, label.reshape(-1))

    step = TrainStep(net, loss_fn, opt.AdamW(learning_rate=1e-4),
                     compute_dtype="bfloat16", state_dtype="bfloat16")
    rng = np.random.RandomState(0)
    ids = mx.nd.array(rng.randint(0, 30522, (batch, seq)), dtype="int32")
    labels = mx.nd.array(rng.randint(0, 30522, (batch, seq)), dtype="int32")
    return step, ids, labels


def capture(step, ids, labels, trace_dir, steps=5):
    import jax

    for _ in range(3):
        loss = step(ids, labels)
    float(loss.asscalar())
    jax.profiler.start_trace(trace_dir)
    for _ in range(steps):
        loss = step(ids, labels)
    float(loss.asscalar())
    jax.profiler.stop_trace()


def analyze(trace_dir, steps=5, top=12):
    path = glob.glob(f"{trace_dir}/plugins/profile/*/*.trace.json.gz")[0]
    with gzip.open(path) as f:
        tr = json.load(f)
    agg = collections.Counter()
    tot = 0.0
    for e in tr["traceEvents"]:
        # XLA Ops leaf timeline: pid 3 / tid 3 in jax's chrome export
        if e.get("ph") == "X" and e.get("pid") == 3 and e.get("tid") == 3:
            tot += e.get("dur", 0)
            agg[e["name"].split(".")[0]] += e.get("dur", 0)
    ms = tot / steps / 1e3
    print(f"device busy per step: {ms:.2f} ms")
    for c, d in agg.most_common(top):
        print(f"{d / steps / 1e3:8.3f} ms  {c}")
    return ms


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--batch", type=int, default=64)
    ap.add_argument("--steps", type=int, default=5)
    ap.add_argument("--keep", default=None,
                    help="keep the trace at this directory")
    ap.add_argument("--loss", default="fused", choices=("fused", "naive"))
    args = ap.parse_args()
    trace_dir = args.keep or tempfile.mkdtemp(prefix="bert_trace_")
    step, ids, labels = build_step(args.batch, loss=args.loss)
    capture(step, ids, labels, trace_dir, args.steps)
    ms = analyze(trace_dir, args.steps)
    tok = args.batch * 128 / (ms / 1e3)
    print(f"device-bound tokens/s: {tok:.0f}")
    if not args.keep:
        shutil.rmtree(trace_dir, ignore_errors=True)


if __name__ == "__main__":
    main()
