"""Run all five BASELINE configs; one driver JSON line each."""

from __future__ import annotations


def main():
    from . import (bench_frcnn, bench_lenet, bench_module, bench_resnet50,
                   bench_ssd, bench_transformer)

    bench_lenet.main()
    bench_resnet50.main()
    import bench as bench_bert  # repo-root bench.py = config 3

    bench_bert.main()
    bench_transformer.main()
    bench_ssd.main()
    bench_frcnn.main()
    bench_module.main()


if __name__ == "__main__":
    main()
