#!/usr/bin/env python
"""Memory-guided batch planning: the largest per-bucket batch that fits
HBM under a target headroom.

Walks a ``FixedBucketSampler``-style bucket menu (PR 3's
``signatures()`` shape contract) and, for each bucket key, searches the
largest global batch whose compiled ``TrainStep`` executable fits the
planning budget — ``TrainStep.memory_analysis`` over abstract avals, so
nothing is materialized and no step runs. The budget is the device HBM
limit (or ``--hbm-bytes`` / ``MXTPU_HBM_BYTES`` on rigs without memory
stats) shaved by ``MXTPU_HBM_HEADROOM``.

The demo model is the bench transformer (size it with ``--units``/
``--layers``/``--vocab``); ``--amp``/``--remat`` show how mixed
precision and rematerialization move the fitting batch — the numbers
``benchmarks/bench_transformer --amp --remat --auto-batch`` then turns
into a throughput win.

Example (CPU rig, synthetic 2 GB budget)::

    MXTPU_HBM_BYTES=2e9 python tools/hbm_plan.py --amp bfloat16 \
        --remat dots_saveable

Prints one JSON row per bucket plus a summary row.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

_HERE = os.path.dirname(os.path.abspath(__file__))
sys.path.insert(0, os.path.dirname(_HERE))  # repo root


def build_step(args, amp=None, remat=None, mesh=None, sharding=None):
    import numpy as np  # noqa: F401

    import jax
    import jax.numpy as jnp

    import mxnet_tpu as mx
    from mxnet_tpu import nd, optimizer as opt
    from mxnet_tpu.gluon.model_zoo.transformer import TransformerModel
    from mxnet_tpu.ndarray.ndarray import NDArray
    from mxnet_tpu.parallel import TrainStep

    net = TransformerModel(
        src_vocab=args.vocab, tgt_vocab=args.vocab, units=args.units,
        hidden_size=args.units * 2, num_layers=args.layers,
        num_heads=max(2, args.units // 32), max_length=args.max_len + 8,
        dropout=0.0)
    net.initialize(mx.initializer.Xavier())
    net._probe_shapes(nd.zeros((2, 8), dtype="int32"),
                      nd.zeros((2, 8), dtype="int32"))

    class MaskedCE:
        def __call__(self, logits, label):
            x = logits.data.astype(jnp.float32)
            y = label.data
            mask = y >= 0
            safe = jnp.where(mask, y, 0).astype(jnp.int32)
            logp = jax.nn.log_softmax(x, axis=-1)
            nll = -jnp.take_along_axis(logp, safe[..., None],
                                       axis=-1)[..., 0]
            row = jnp.where(mask, nll, 0.0).sum(axis=-1)
            return NDArray(row.sum() / mask.sum())

    return TrainStep(net, MaskedCE(), opt.AdamW(learning_rate=1e-4),
                     amp=amp, remat=remat, mesh=mesh, sharding=sharding)


def plan(step, bucket_keys, budget, start=1, max_batch=65536):
    """One row per bucket key: the largest batch whose compiled step
    fits ``budget`` bytes."""
    from mxnet_tpu.parallel import plan_batch

    rows = []
    for key in bucket_keys:
        def sig(bs, _key=key):
            return ((((bs, _key), "int32"),) * 2 + (((bs, _key), "int32"),))

        batch, peak = plan_batch(step, sig, budget, start=start,
                                 max_batch=max_batch)
        row = {"bucket_key": int(key), "max_batch": int(batch),
               "peak_bytes": int(peak) if peak is not None else None,
               "budget_bytes": int(budget)}
        mesh = getattr(step, "_mesh", None)
        if mesh is not None:
            # the budget is ONE device's HBM; with a mesh, plan_batch
            # bisected the PER-SHARD peak against it (the mesh splits
            # the working set mesh.size ways)
            row["mesh_devices"] = int(mesh.size)
            row["per_shard"] = int(mesh.size) > 1
        rows.append(row)
    return rows


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--buckets", type=int, nargs="*",
                    default=[16, 32, 48, 64],
                    help="bucket keys (sequence lengths) to plan for")
    ap.add_argument("--hbm-bytes", type=float, default=None,
                    help="HBM limit override (else device stats / "
                         "MXTPU_HBM_BYTES)")
    ap.add_argument("--amp", default=None,
                    help="bfloat16|float16 mixed precision")
    ap.add_argument("--remat", default=None,
                    help="remat policy (mxnet_tpu.remat.POLICIES)")
    ap.add_argument("--mesh", default=None,
                    help="device mesh spec ('4', '2x2', 'data=2,model=2',"
                         " 'auto'); the plan then bisects the PER-SHARD "
                         "peak against the per-device budget")
    ap.add_argument("--sharding", default=None,
                    help="sharding rules preset for --mesh: 'replicated' "
                         "(data parallel) or 'fsdp' (params+moments "
                         "sharded; default when --mesh is set)")
    ap.add_argument("--units", type=int, default=32)
    ap.add_argument("--layers", type=int, default=1)
    ap.add_argument("--vocab", type=int, default=1000)
    ap.add_argument("--max-len", type=int, default=64)
    ap.add_argument("--start", type=int, default=1)
    ap.add_argument("--max-batch", type=int, default=4096)
    args = ap.parse_args(argv)
    args.max_len = max(args.max_len, max(args.buckets))

    from mxnet_tpu.parallel import hbm_budget_bytes

    budget = hbm_budget_bytes(
        int(args.hbm_bytes) if args.hbm_bytes else None)
    if budget is None:
        print("no HBM limit known: pass --hbm-bytes or set "
              "MXTPU_HBM_BYTES (no device memory stats on this backend)",
              file=sys.stderr)
        return 2

    mesh = None
    sharding = args.sharding
    if args.mesh:
        from mxnet_tpu.parallel import sharding as _shard

        mesh = _shard.make_global_mesh(args.mesh)
        if sharding is None:
            sharding = "fsdp"
    step = build_step(args, amp=args.amp, remat=args.remat, mesh=mesh,
                      sharding=sharding)
    rows = plan(step, args.buckets, budget, start=args.start,
                max_batch=args.max_batch)
    mesh_str = None
    if mesh is not None:
        from mxnet_tpu.parallel import sharding as _shard

        mesh_str = _shard.mesh_shape_str(mesh)
    for r in rows:
        r.update({"amp": args.amp, "remat": args.remat,
                  "mesh": mesh_str, "sharding": sharding})
        print(json.dumps(r))
    fitting = [r for r in rows if r["max_batch"] > 0]
    print(json.dumps({
        "metric": "hbm_plan_max_batch",
        "value": max((r["max_batch"] for r in fitting), default=0),
        "unit": "samples",
        "budget_bytes": int(budget),
        "amp": args.amp, "remat": args.remat,
        "mesh": mesh_str, "sharding": sharding,
        "buckets_fitting": len(fitting), "buckets_total": len(rows),
    }))
    return 0 if fitting else 1


if __name__ == "__main__":
    sys.exit(main())
