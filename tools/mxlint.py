#!/usr/bin/env python
"""mxlint: run the unified static-analysis suite (mxnet_tpu.analysis).

Ten passes over two IRs (Python AST for host code, jaxpr for the real
jitted programs) plus two repo-consistency passes — three of the AST
passes interprocedural over the project call graph — the one lint entry
point CI runs:

    python tools/mxlint.py                 # human output, all passes
    python tools/mxlint.py --json          # machine output for CI
    python tools/mxlint.py --github        # GitHub workflow annotations
    python tools/mxlint.py --passes lock-order,donation
    python tools/mxlint.py --list          # show the pass roster
    python tools/mxlint.py --write-baseline --reason "why"  # grandfather
                                           # current findings
    python tools/mxlint.py --prune-baseline  # drop stale entries

Baseline workflow: findings whose fingerprint appears in
``tools/mxlint_baseline.json`` (with a mandatory reason) are reported as
suppressed and do not fail the run; everything else exits 1. A baseline
entry whose fingerprint no longer matches any finding is STALE — the
code it excused moved or was fixed — and also fails the run (the file
must stay honest); ``--prune-baseline`` deletes stale entries of the
executed passes and rewrites the file. jaxpr passes trace real
TrainStep/InferStep programs — on a bare CPU the script simulates a
4-device platform first (same trick as the old check_sharding.py).

Exit codes: 0 clean (or fully baselined), 1 findings or stale baseline
entries, 2 usage error.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

_HERE = os.path.dirname(os.path.abspath(__file__))
_ROOT = os.path.dirname(_HERE)
sys.path.insert(0, _ROOT)

DEFAULT_BASELINE = os.path.join(_HERE, "mxlint_baseline.json")


def _ensure_devices():
    """jaxpr passes need >= 4 devices (sharding-placement); simulate on
    CPU before jax imports, mirroring tests/conftest.py."""
    if "--xla_force_host_platform_device_count" not in \
            os.environ.get("XLA_FLAGS", ""):
        os.environ["XLA_FLAGS"] = (
            os.environ.get("XLA_FLAGS", "")
            + " --xla_force_host_platform_device_count=4").strip()
        os.environ.setdefault("JAX_PLATFORMS", "cpu")


def main(argv=None):
    ap = argparse.ArgumentParser(
        description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter)
    ap.add_argument("--json", action="store_true",
                    help="emit one JSON document for CI")
    ap.add_argument("--github", action="store_true",
                    help="emit GitHub workflow ::error annotations "
                    "(one per finding / stale baseline entry)")
    ap.add_argument("--passes", default=None,
                    help="comma-separated subset (default: all)")
    ap.add_argument("--baseline", default=DEFAULT_BASELINE,
                    help="baseline file (default tools/mxlint_baseline"
                    ".json); 'none' disables suppression")
    ap.add_argument("--write-baseline", action="store_true",
                    help="add every CURRENT finding to the baseline "
                    "with --reason and exit 0")
    ap.add_argument("--prune-baseline", action="store_true",
                    help="delete baseline entries (of the executed "
                    "passes) whose fingerprint no longer matches any "
                    "finding, rewrite the file, exit 0")
    ap.add_argument("--reason", default=None,
                    help="reason recorded with --write-baseline entries")
    ap.add_argument("--list", action="store_true",
                    help="list registered passes and exit")
    args = ap.parse_args(argv)

    _ensure_devices()
    from mxnet_tpu.analysis import Baseline, all_passes, run_passes

    registry = all_passes()
    if args.list:
        for name in sorted(registry):
            p = registry[name]
            print(f"{name:<22} [{p.ir:<5}] {p.description}")
        return 0

    names = None
    if args.passes:
        names = [n.strip() for n in args.passes.split(",") if n.strip()]
        unknown = [n for n in names if n not in registry]
        if unknown:
            print(f"unknown pass(es) {unknown}; have {sorted(registry)}",
                  file=sys.stderr)
            return 2

    baseline = None
    if args.baseline and args.baseline.lower() != "none":
        baseline = Baseline.load(args.baseline)

    t0 = time.perf_counter()
    timings = {}

    def progress(name):
        timings[name] = time.perf_counter()
        if not args.json:
            print(f"[mxlint] {name} ...", file=sys.stderr)

    findings, suppressed = run_passes(names, baseline=baseline,
                                      progress=progress)
    elapsed = time.perf_counter() - t0

    # stale = baselined fingerprints (for a pass we actually ran) that
    # matched nothing: the excused code moved or was fixed, so the entry
    # is noise and the reasoned-baseline file has stopped being honest.
    executed = set(registry) if names is None else set(names)
    matched = {f.fingerprint for f, _r in suppressed}
    stale = []
    if baseline is not None and not args.write_baseline:
        for fp, entry in sorted(baseline.entries.items()):
            pass_name = entry.get("pass")
            in_scope = (pass_name in executed) if pass_name \
                else names is None
            if in_scope and fp not in matched:
                stale.append(fp)

    if args.prune_baseline:
        if baseline is None:
            print("--prune-baseline needs a baseline file "
                  "(not --baseline none)", file=sys.stderr)
            return 2
        for fp in stale:
            del baseline.entries[fp]
        baseline.save(args.baseline)
        print(f"pruned {len(stale)} stale baseline entr"
              f"{'y' if len(stale) == 1 else 'ies'} from "
              f"{args.baseline}")
        return 0

    if args.write_baseline:
        if not args.reason:
            print("--write-baseline needs --reason (every grandfathered "
                  "violation must explain itself)", file=sys.stderr)
            return 2
        baseline = baseline or Baseline(path=args.baseline)
        for f in findings:
            baseline.entries[f.fingerprint] = {
                "reason": args.reason, "pass": f.pass_name,
                "rule": f.rule, "path": f.path,
            }
        baseline.save(args.baseline)
        print(f"baselined {len(findings)} finding(s) into "
              f"{args.baseline}")
        return 0

    if args.json:
        print(json.dumps({
            "ok": not findings and not stale,
            "elapsed_s": round(elapsed, 3),
            "passes_run": sorted(registry) if names is None else names,
            "findings": [f.to_dict() for f in findings],
            "suppressed": [dict(f.to_dict(), baseline_reason=r)
                           for f, r in suppressed],
            "stale_baseline": stale,
        }, indent=2))
    elif args.github:
        # one ::error per finding so the workflow UI pins each to its
        # file/line; summary goes to stderr to stay out of the stream
        rel_baseline = os.path.relpath(args.baseline, _ROOT)
        for f in findings:
            print(f"::error file={f.path},line={f.line}::"
                  f"[{f.pass_name}.{f.rule}] {f.message}")
        for fp in stale:
            print(f"::error file={rel_baseline}::stale baseline entry "
                  f"{fp} matches no finding — fix or --prune-baseline")
        print(f"mxlint: {len(findings)} finding(s), {len(stale)} stale, "
              f"{len(suppressed)} baselined in {elapsed:.1f}s",
              file=sys.stderr)
    else:
        for f, r in suppressed:
            print(f"BASELINED {f}  (reason: {r})")
        for f in findings:
            print(f)
        for fp in stale:
            print(f"STALE baseline entry {fp} matches no finding — "
                  f"delete it or run --prune-baseline")
        n = len(findings)
        print(f"mxlint: {n} finding(s), {len(suppressed)} baselined, "
              f"{len(registry) if names is None else len(names)} "
              f"pass(es) in {elapsed:.1f}s")
        if not findings and not stale:
            print("mxlint: clean")
    return 1 if findings or stale else 0


if __name__ == "__main__":
    sys.exit(main())
