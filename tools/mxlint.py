#!/usr/bin/env python
"""mxlint: run the unified static-analysis suite (mxnet_tpu.analysis).

Seven passes over two IRs (Python AST for host code, jaxpr for the real
jitted programs) plus two repo-consistency passes — the one lint entry
point CI runs:

    python tools/mxlint.py                 # human output, all passes
    python tools/mxlint.py --json          # machine output for CI
    python tools/mxlint.py --passes lock-order,donation
    python tools/mxlint.py --list          # show the pass roster
    python tools/mxlint.py --write-baseline --reason "why"  # grandfather
                                           # current findings

Baseline workflow: findings whose fingerprint appears in
``tools/mxlint_baseline.json`` (with a mandatory reason) are reported as
suppressed and do not fail the run; everything else exits 1. jaxpr
passes trace real TrainStep/InferStep programs — on a bare CPU the
script simulates a 4-device platform first (same trick as the old
check_sharding.py).

Exit codes: 0 clean (or fully baselined), 1 findings, 2 usage error.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

_HERE = os.path.dirname(os.path.abspath(__file__))
_ROOT = os.path.dirname(_HERE)
sys.path.insert(0, _ROOT)

DEFAULT_BASELINE = os.path.join(_HERE, "mxlint_baseline.json")


def _ensure_devices():
    """jaxpr passes need >= 4 devices (sharding-placement); simulate on
    CPU before jax imports, mirroring tests/conftest.py."""
    if "--xla_force_host_platform_device_count" not in \
            os.environ.get("XLA_FLAGS", ""):
        os.environ["XLA_FLAGS"] = (
            os.environ.get("XLA_FLAGS", "")
            + " --xla_force_host_platform_device_count=4").strip()
        os.environ.setdefault("JAX_PLATFORMS", "cpu")


def main(argv=None):
    ap = argparse.ArgumentParser(
        description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter)
    ap.add_argument("--json", action="store_true",
                    help="emit one JSON document for CI")
    ap.add_argument("--passes", default=None,
                    help="comma-separated subset (default: all)")
    ap.add_argument("--baseline", default=DEFAULT_BASELINE,
                    help="baseline file (default tools/mxlint_baseline"
                    ".json); 'none' disables suppression")
    ap.add_argument("--write-baseline", action="store_true",
                    help="add every CURRENT finding to the baseline "
                    "with --reason and exit 0")
    ap.add_argument("--reason", default=None,
                    help="reason recorded with --write-baseline entries")
    ap.add_argument("--list", action="store_true",
                    help="list registered passes and exit")
    args = ap.parse_args(argv)

    _ensure_devices()
    from mxnet_tpu.analysis import Baseline, all_passes, run_passes

    registry = all_passes()
    if args.list:
        for name in sorted(registry):
            p = registry[name]
            print(f"{name:<22} [{p.ir:<5}] {p.description}")
        return 0

    names = None
    if args.passes:
        names = [n.strip() for n in args.passes.split(",") if n.strip()]
        unknown = [n for n in names if n not in registry]
        if unknown:
            print(f"unknown pass(es) {unknown}; have {sorted(registry)}",
                  file=sys.stderr)
            return 2

    baseline = None
    if args.baseline and args.baseline.lower() != "none":
        baseline = Baseline.load(args.baseline)

    t0 = time.perf_counter()
    timings = {}

    def progress(name):
        timings[name] = time.perf_counter()
        if not args.json:
            print(f"[mxlint] {name} ...", file=sys.stderr)

    findings, suppressed = run_passes(names, baseline=baseline,
                                      progress=progress)
    elapsed = time.perf_counter() - t0

    if args.write_baseline:
        if not args.reason:
            print("--write-baseline needs --reason (every grandfathered "
                  "violation must explain itself)", file=sys.stderr)
            return 2
        baseline = baseline or Baseline(path=args.baseline)
        for f in findings:
            baseline.entries[f.fingerprint] = {
                "reason": args.reason, "pass": f.pass_name,
                "rule": f.rule, "path": f.path,
            }
        baseline.save(args.baseline)
        print(f"baselined {len(findings)} finding(s) into "
              f"{args.baseline}")
        return 0

    if args.json:
        print(json.dumps({
            "ok": not findings,
            "elapsed_s": round(elapsed, 3),
            "passes_run": sorted(registry) if names is None else names,
            "findings": [f.to_dict() for f in findings],
            "suppressed": [dict(f.to_dict(), baseline_reason=r)
                           for f, r in suppressed],
        }, indent=2))
    else:
        for f, r in suppressed:
            print(f"BASELINED {f}  (reason: {r})")
        for f in findings:
            print(f)
        n = len(findings)
        print(f"mxlint: {n} finding(s), {len(suppressed)} baselined, "
              f"{len(registry) if names is None else len(names)} "
              f"pass(es) in {elapsed:.1f}s")
        if not findings:
            print("mxlint: clean")
    return 1 if findings else 0


if __name__ == "__main__":
    sys.exit(main())
