#!/usr/bin/env python
"""Pack an image directory into RecordIO (reference: ``tools/im2rec.py``
[unverified]).

Two phases, same CLI shape as the reference:

1. ``--list``: walk ``root``, assign integer labels per subdirectory
   (sorted), write ``prefix.lst`` lines ``index\\tlabel\\trelpath``.
2. default: read ``prefix.lst`` (or generate in-memory if absent), encode
   each image (resize/quality options) and write ``prefix.rec`` +
   ``prefix.idx`` via MXIndexedRecordIO with IRHeader(label).

Usage:
    python tools/im2rec.py data/train images/ --list
    python tools/im2rec.py data/train images/ --resize 256 --quality 90
"""

from __future__ import annotations

import argparse
import os
import random
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

_EXTS = {".jpg", ".jpeg", ".png", ".bmp"}


def find_images(root):
    """[(relpath, label)] with labels assigned per sorted subdirectory."""
    classes = sorted(
        d for d in os.listdir(root)
        if os.path.isdir(os.path.join(root, d))
    )
    out = []
    if classes:
        for label, cls in enumerate(classes):
            for dirpath, _, files in os.walk(os.path.join(root, cls)):
                for f in sorted(files):
                    if os.path.splitext(f)[1].lower() in _EXTS:
                        rel = os.path.relpath(os.path.join(dirpath, f), root)
                        out.append((rel, float(label)))
    else:  # flat directory: label 0
        for f in sorted(os.listdir(root)):
            if os.path.splitext(f)[1].lower() in _EXTS:
                out.append((f, 0.0))
    return out


def write_list(prefix, items, shuffle=False):
    if shuffle:
        random.shuffle(items)
    path = prefix + ".lst"
    with open(path, "w") as f:
        for i, (rel, label) in enumerate(items):
            f.write(f"{i}\t{label}\t{rel}\n")
    return path


def read_list(path):
    items = []
    with open(path) as f:
        for line in f:
            parts = line.strip().split("\t")
            if len(parts) < 3:
                continue
            idx, label, rel = int(parts[0]), float(parts[1]), parts[2]
            items.append((idx, rel, label))
    return items


def pack_records(prefix, root, items, resize=0, quality=95, img_fmt=".jpg"):
    from mxnet_tpu import recordio
    import numpy as np
    from PIL import Image

    rec = recordio.MXIndexedRecordIO(prefix + ".idx", prefix + ".rec", "w")
    n = 0
    for idx, rel, label in items:
        path = os.path.join(root, rel)
        try:
            img = Image.open(path).convert("RGB")
        except Exception as e:  # noqa: BLE001
            print(f"skip {rel}: {e}", file=sys.stderr)
            continue
        if resize:
            w, h = img.size
            scale = resize / min(w, h)
            img = img.resize((max(1, int(w * scale)),
                              max(1, int(h * scale))))
        arr = np.asarray(img)[..., ::-1]  # RGB -> BGR (cv2 wire convention)
        header = recordio.IRHeader(0, label, idx, 0)
        packed = recordio.pack_img(header, arr, quality=quality,
                                   img_fmt=img_fmt)
        rec.write_idx(idx, packed)
        n += 1
    rec.close()
    return n


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("prefix", help="output prefix (prefix.lst/.rec/.idx)")
    ap.add_argument("root", help="image directory root")
    ap.add_argument("--list", action="store_true",
                    help="generate prefix.lst only")
    ap.add_argument("--shuffle", action="store_true")
    ap.add_argument("--resize", type=int, default=0,
                    help="resize shorter side to this many pixels")
    ap.add_argument("--quality", type=int, default=95)
    ap.add_argument("--encoding", default=".jpg", choices=[".jpg", ".png"])
    args = ap.parse_args(argv)

    if args.list:
        items = find_images(args.root)
        path = write_list(args.prefix, items, shuffle=args.shuffle)
        print(f"wrote {len(items)} entries to {path}")
        return 0

    lst = args.prefix + ".lst"
    if os.path.exists(lst):
        items = read_list(lst)
    else:
        items = [(i, rel, lab)
                 for i, (rel, lab) in enumerate(find_images(args.root))]
    n = pack_records(args.prefix, args.root, items, resize=args.resize,
                     quality=args.quality, img_fmt=args.encoding)
    print(f"packed {n} images into {args.prefix}.rec")
    return 0


if __name__ == "__main__":
    sys.exit(main())
