#!/usr/bin/env python
"""AMP purity lint: mixed precision must stay pure end to end.

This checker now lives on the unified analysis framework as the
``amp-purity`` pass (``mxnet_tpu/analysis/passes/amp_purity.py``) — run
``python tools/mxlint.py`` for the whole suite; this shim keeps the
historical standalone CLI and import surface.
"""

from __future__ import annotations

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

from mxnet_tpu.analysis.jaxpr_driver import (  # noqa: E402,F401
    find_mixed_dots, iter_jaxprs as _iter_jaxprs,
    build_train_step as build_tiny_amp_step,
)
from mxnet_tpu.analysis.passes.amp_purity import (  # noqa: E402,F401
    check_step_purity, find_overflow_sync_violations,
)
from mxnet_tpu.analysis.passes.no_sync import STEP_PY  # noqa: E402,F401


def main(argv=None):
    ast_violations = find_overflow_sync_violations()
    for lineno, msg in ast_violations:
        print(f"{STEP_PY}:{lineno}: {msg}")
    jaxpr_violations = check_step_purity()
    for msg in jaxpr_violations:
        print(f"amp jaxpr: {msg}")
    n = len(ast_violations) + len(jaxpr_violations)
    if n:
        print(f"{n} AMP purity violation(s)")
        return 1
    print("AMP purity: all low-precision dots are pure; overflow-skip "
          "path is sync-free")
    return 0


if __name__ == "__main__":
    sys.exit(main())
