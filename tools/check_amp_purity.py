#!/usr/bin/env python
"""AMP purity lint: mixed precision must stay pure end to end.

Two checks, both run by the tier-1 suite (``tests/test_amp_purity.py``):

1. **jaxpr check — no fp32 master feeds a low-precision dot.** Builds a
   tiny ``TrainStep(amp='bfloat16')`` over a transformer layer and walks
   the step program's jaxpr (recursing into pjit/scan/cond/remat
   sub-jaxprs): any ``dot_general`` whose two operands mix float32 with
   bfloat16/float16 means a master weight (or an un-downcast activation,
   e.g. a norm output that stopped being dtype-preserving) reached an
   MXU op without its cast — the exact bug class the reference's
   cast-insertion pass (``low_precision_pass.cc``) existed to prevent.
   Uniform-f32 dots are legal (optimizer math, losses); only MIXED dots
   are flagged.

2. **AST check — no host sync in the overflow-skip path.** The
   fp16 loss-scaling contract is that overflow steps cost no host
   round trip: the finite-check, ``lax.cond`` skip, and scale update
   all live inside ``TrainStep._build``'s traced step. This walks that
   method's AST and flags blocking calls (``float()``, ``.item()``,
   ``.asnumpy()``, ``block_until_ready`` — the
   ``check_no_sync_in_step`` rule set).

Run standalone (nonzero exit on violations)::

    python tools/check_amp_purity.py
"""

from __future__ import annotations

import ast
import os
import sys

_HERE = os.path.dirname(os.path.abspath(__file__))
sys.path.insert(0, _HERE)
sys.path.insert(0, os.path.dirname(_HERE))  # repo root: mxnet_tpu import
from check_no_sync_in_step import (  # noqa: E402
    BLOCKING_ATTRS, BLOCKING_BUILTINS, BLOCKING_QUALIFIED, STEP_PY,
)

_LOW = ("bfloat16", "float16")


# ------------------------------------------------------------- jaxpr check
def _iter_jaxprs(obj):
    """Yield every (sub-)jaxpr reachable from a jaxpr/ClosedJaxpr/eqn
    params value."""
    if obj is None:
        return
    if hasattr(obj, "jaxpr"):  # ClosedJaxpr
        yield from _iter_jaxprs(obj.jaxpr)
        return
    if hasattr(obj, "eqns"):  # Jaxpr
        yield obj
        for eqn in obj.eqns:
            for v in eqn.params.values():
                yield from _iter_jaxprs(v)
        return
    if isinstance(obj, (tuple, list)):
        for item in obj:
            yield from _iter_jaxprs(item)


def find_mixed_dots(closed_jaxpr):
    """[(primitive, operand dtypes)] for every dot_general mixing fp32
    with a low-precision operand anywhere in the program."""
    out = []
    for jaxpr in _iter_jaxprs(closed_jaxpr):
        for eqn in jaxpr.eqns:
            if eqn.primitive.name != "dot_general":
                continue
            dts = [str(v.aval.dtype) for v in eqn.invars[:2]
                   if hasattr(v.aval, "dtype")]
            if "float32" in dts and any(d in _LOW for d in dts):
                out.append((eqn.primitive.name, tuple(dts)))
    return out


def build_tiny_amp_step(amp="bfloat16", remat="dots_saveable"):
    """A minimal transformer TrainStep exercising the full AMP surface:
    cast params, fp32-pinned norms, attention + tied-embedding dots."""
    import numpy as np

    import jax
    import jax.numpy as jnp

    import mxnet_tpu as mx
    from mxnet_tpu import gluon, nd, optimizer as opt  # noqa: F401
    from mxnet_tpu.gluon.model_zoo.transformer import TransformerModel
    from mxnet_tpu.ndarray.ndarray import NDArray
    from mxnet_tpu.parallel import TrainStep

    net = TransformerModel(src_vocab=64, tgt_vocab=64, units=16,
                           hidden_size=32, num_layers=1, num_heads=2,
                           max_length=32, dropout=0.0)
    net.initialize(mx.initializer.Xavier())
    net._probe_shapes(nd.zeros((2, 8), dtype="int32"),
                      nd.zeros((2, 8), dtype="int32"))

    class CE:
        def __call__(self, logits, label):
            x = logits.data.astype(jnp.float32)
            logp = jax.nn.log_softmax(x, axis=-1)
            nll = -jnp.take_along_axis(
                logp, label.data.astype(jnp.int32)[..., None], axis=-1)
            return NDArray(nll.mean())

    step = TrainStep(net, CE(), opt.AdamW(learning_rate=1e-4), amp=amp,
                     remat=remat)
    rng = np.random.RandomState(0)
    src = nd.array(rng.randint(0, 64, (2, 8)), dtype="int32")
    tgt = nd.array(rng.randint(0, 64, (2, 8)), dtype="int32")
    lab = nd.array(rng.randint(0, 64, (2, 8)), dtype="int32")
    step(src, tgt, lab)  # populates _last_avals
    return step


def check_step_purity(step=None):
    """Return violations for check (1); builds the tiny step if none is
    given. Also asserts the amp program DOES contain low-precision dots
    at all — an all-f32 program means the cast pass silently stopped
    engaging, which is its own failure."""
    import jax

    if step is None:
        step = build_tiny_amp_step()
    jaxpr = jax.make_jaxpr(step._step_fn)(*step._last_avals)
    mixed = [f"dot_general with operands {dts} — fp32 feeds a "
             f"low-precision dot without a cast" for _, dts in
             find_mixed_dots(jaxpr)]
    low_dots = 0
    for j in _iter_jaxprs(jaxpr):
        for eqn in j.eqns:
            if eqn.primitive.name == "dot_general" and any(
                    str(v.aval.dtype) in _LOW for v in eqn.invars[:2]
                    if hasattr(v.aval, "dtype")):
                low_dots += 1
    if low_dots == 0:
        mixed.append(
            "amp step program contains NO low-precision dot_general at "
            "all — the cast pass is not engaging")
    return mixed


# --------------------------------------------------------------- AST check
def find_overflow_sync_violations(path: str = STEP_PY):
    """Blocking host calls inside the TRACED closures of
    ``TrainStep._build`` (``step_core``/``forward_loss``/... — the step
    body XLA compiles, including the fp16 overflow-skip path).
    ``_build``'s own top-level statements run once on host at build time
    and may legitimately coerce hyperparameters."""
    with open(path) as f:
        tree = ast.parse(f.read(), filename=path)
    out = []
    classes = [n for n in tree.body
               if isinstance(n, ast.ClassDef) and n.name == "TrainStep"]
    if not classes:
        return [(0, f"TrainStep class not found in {path}")]
    builds = [n for n in classes[0].body
              if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))
              and n.name == "_build"]
    if not builds:
        return [(classes[0].lineno, "_build method not found — update "
                 "check_amp_purity if the builder was renamed")]
    traced = [n for n in ast.walk(builds[0])
              if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))
              and n is not builds[0]]
    nodes = [node for fn in traced for node in ast.walk(fn)]
    for node in nodes:
        if not isinstance(node, ast.Call):
            continue
        f = node.func
        if isinstance(f, ast.Name) and f.id in BLOCKING_BUILTINS:
            out.append((node.lineno,
                        f"_build: host coercion {f.id}(...) would sync "
                        "the overflow-skip path"))
        elif isinstance(f, ast.Attribute):
            if f.attr in BLOCKING_ATTRS:
                out.append((node.lineno,
                            f"_build: .{f.attr}() forces a device->host "
                            "sync inside the traced step"))
            elif isinstance(f.value, ast.Name) and \
                    (f.value.id, f.attr) in BLOCKING_QUALIFIED:
                out.append((node.lineno,
                            f"_build: {f.value.id}.{f.attr}(...) "
                            "materializes/stalls on host"))
    return out


def main(argv=None):
    ast_violations = find_overflow_sync_violations()
    for lineno, msg in ast_violations:
        print(f"{STEP_PY}:{lineno}: {msg}")
    jaxpr_violations = check_step_purity()
    for msg in jaxpr_violations:
        print(f"amp jaxpr: {msg}")
    n = len(ast_violations) + len(jaxpr_violations)
    if n:
        print(f"{n} AMP purity violation(s)")
        return 1
    print("AMP purity: all low-precision dots are pure; overflow-skip "
          "path is sync-free")
    return 0


if __name__ == "__main__":
    sys.exit(main())
