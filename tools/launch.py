#!/usr/bin/env python
"""Multi-process / multi-host job launcher.

TPU-native analogue of the reference's ``tools/launch.py`` + dmlc-tracker
[unverified]: that stack started a ZMQ scheduler and spawned workers/servers
over ssh/mpi/yarn with ``DMLC_*`` env vars. Here there are no parameter
servers — every process is a worker that joins one JAX coordination service
(`jax.distributed`) — so the launcher's whole job is: pick a coordinator
address, spawn N processes with the ``MXNET_TPU_*`` rendezvous env vars
(read by ``mxnet_tpu.parallel.init_process_group`` and ``KVStoreDist``),
stream their output, and propagate failures.

Launchers:
  local  spawn all N processes on this machine (testing / single-host
         multi-process; the reference's ``--launcher local``).
  ssh    one process per line of --hostfile via ssh (multi-host; the
         reference's ssh tracker). Assumes a shared working directory and
         passwordless ssh, like the reference.

The same spawn machinery brings up a SERVING fleet: each
``mxnet_tpu.serving.worker`` process reads its rank from
``MXNET_TPU_PROC_ID`` to derive its name (``worker-<rank>``), its state
subdirectory and its port offset from ``MXTPU_SERVE_PORT``, so one
launch line starts N workers a router can front via
``serving.RemoteReplica``.

Examples:
  python tools/launch.py -n 4 -- python train.py --kv-store dist_sync
  python tools/launch.py -n 8 --launcher ssh -H hosts.txt -- python train.py
  MXTPU_SERVE_PORT=7070 python tools/launch.py -n 2 -- \\
      python -m mxnet_tpu.serving.worker --dir /tmp/fleet
"""

from __future__ import annotations

import argparse
import os
import shlex
import signal
import socket
import subprocess
import sys
import threading
import time


def find_free_port() -> int:
    with socket.socket(socket.AF_INET, socket.SOCK_STREAM) as s:
        s.bind(("", 0))
        return s.getsockname()[1]


def worker_env(coordinator: str, num_procs: int, proc_id: int) -> dict:
    env = dict(os.environ)
    env.update(
        {
            "MXNET_TPU_COORDINATOR": coordinator,
            "MXNET_TPU_NUM_PROCS": str(num_procs),
            "MXNET_TPU_PROC_ID": str(proc_id),
        }
    )
    return env


def _pump(proc: subprocess.Popen, tag: str):
    for line in iter(proc.stdout.readline, b""):
        sys.stdout.write(f"[{tag}] {line.decode(errors='replace')}")
        sys.stdout.flush()


def spawn_procs(num_procs: int, command, coordinator: str | None = None,
                env_extra: dict | None = None):
    """Spawn ``command`` num_procs times with the rendezvous env vars;
    returns ``(procs, pumps)`` — the reusable half of :func:`launch_local`
    (chaos drivers spawn serving-worker fleets through it and keep the
    per-process handles so they can SIGKILL/SIGTERM individuals)."""
    procs = []
    pumps = []
    for pid in range(num_procs):
        env = worker_env(coordinator, num_procs, pid)
        env.update(env_extra or {})
        p = subprocess.Popen(
            command,
            env=env,
            stdout=subprocess.PIPE,
            stderr=subprocess.STDOUT,
        )
        t = threading.Thread(target=_pump, args=(p, f"worker-{pid}"), daemon=True)
        t.start()
        procs.append(p)
        pumps.append(t)
    return procs, pumps


def launch_local(num_procs: int, command, coordinator: str | None = None,
                 timeout: float | None = None):
    """Spawn ``command`` num_procs times locally; returns max exit code.

    Failure PROPAGATES: when any worker exits nonzero (or dies on a
    signal), the remaining workers are terminated instead of being left
    hung in a collective that will never complete — the reference's
    tracker killed the job the same way. ``timeout`` (seconds) bounds the
    whole job; expiry kills all workers and returns 124."""
    coordinator = coordinator or f"localhost:{find_free_port()}"
    procs, pumps = spawn_procs(num_procs, command, coordinator)

    def _kill_all():
        for p in procs:
            if p.poll() is None:
                p.terminate()
        deadline = time.time() + 5
        for p in procs:
            if p.poll() is None:
                try:
                    p.wait(timeout=max(0.1, deadline - time.time()))
                except subprocess.TimeoutExpired:
                    pass
        # SIGKILL anything that survived the grace period — a worker
        # ignoring SIGTERM inside a collective must not outlive the job
        for p in procs:
            if p.poll() is None:
                p.kill()

    rc = 0
    start = time.time()
    try:
        live = set(range(num_procs))
        while live:
            if timeout is not None and time.time() - start > timeout:
                print(f"launch: job timed out after {timeout}s; killing "
                      f"workers {sorted(live)}")
                _kill_all()
                return 124
            for pid in sorted(live):
                code = procs[pid].poll()
                if code is None:
                    continue
                live.discard(pid)
                if code != 0:
                    print(f"launch: worker-{pid} exited with {code}; "
                          f"terminating remaining workers {sorted(live)}")
                    _kill_all()
                    return code
            time.sleep(0.05)
    except KeyboardInterrupt:
        for p in procs:
            p.send_signal(signal.SIGTERM)
        raise
    for t in pumps:
        t.join(timeout=5)
    return rc


def restart_backoff_s(default: float = 1.0) -> float:
    """``MXTPU_RESTART_BACKOFF_S``: base delay of the capped exponential
    backoff between elastic restart attempts (shared contract with the
    serving router's replica respawn)."""
    v = os.environ.get("MXTPU_RESTART_BACKOFF_S", "").strip()
    try:
        return float(v) if v else default
    except ValueError:
        return default


def scale_min(default: int = 1) -> int:
    """``MXTPU_SCALE_MIN``: the serving fleet's decode-worker floor —
    :class:`FleetScaler` never retires below it."""
    v = os.environ.get("MXTPU_SCALE_MIN", "").strip()
    try:
        return max(int(v), 1) if v else default
    except ValueError:
        return default


def scale_max(default: int = 4) -> int:
    """``MXTPU_SCALE_MAX``: the decode-worker ceiling —
    :class:`FleetScaler` never grows past it."""
    v = os.environ.get("MXTPU_SCALE_MAX", "").strip()
    try:
        return max(int(v), 1) if v else default
    except ValueError:
        return default


def scale_cooldown_s(default: float = 30.0) -> float:
    """``MXTPU_SCALE_COOLDOWN_S``: minimum seconds between scaling
    actions (either direction) — a spawn takes import+warmup time, so
    back-to-back decisions would thrash on a signal the previous action
    has not yet moved."""
    v = os.environ.get("MXTPU_SCALE_COOLDOWN_S", "").strip()
    try:
        return float(v) if v else default
    except ValueError:
        return default


def scale_wait_ms(default: float = 0.0) -> float:
    """``MXTPU_SCALE_WAIT_MS``: rolling queue-wait p50 (ms) above which
    a pool counts as hot regardless of occupancy — the PREFILL pool's
    primary pressure signal (prefill workers run one admission prefill
    per request, so occupancy says little; the queue wait the decode
    handoffs see says everything). 0 disables the wait gate."""
    v = os.environ.get("MXTPU_SCALE_WAIT_MS", "").strip()
    try:
        return max(float(v), 0.0) if v else default
    except ValueError:
        return default


class FleetScaler:
    """Serving-fleet elasticity supervisor: grow a worker pool on
    sustained pressure, drain and retire workers when idle. One scaler
    supervises ONE role pool (``role="decode"`` default); a
    disaggregated fleet runs a second instance with ``role="prefill"``
    over its prefill workers — same loop, different pressure signal.

    The scaler is deliberately decoupled from the serving package — it
    drives three callables, so the same loop supervises an in-process
    router fleet, a ``spawn_worker`` process fleet, or a test fake:

    ``pressure()``
        -> dict with ``size`` (current workers in this pool),
        ``occupancy`` (mean decode-batch occupancy, 0..1), ``shed``
        (CUMULATIVE router shed count; the scaler differences it) and
        optionally ``queue_wait_ms`` (the pool's rolling queue-wait
        p50 — for a prefill pool, the mean of the prefill replicas'
        worker-reported p50s; occupancy is meaningless for workers
        that run one admission prefill per request).
    ``spawn()``
        start one worker of this role and register it (e.g.
        ``spawn_worker(role=...)`` + ``RemoteReplica.spawning`` +
        ``Router.add_replica``).
    ``retire()``
        pick one idle worker of this role, ``Router.retire_replica``
        it and SIGTERM the process (the existing graceful drain) —
        return False when nothing is retirable (the scaler just waits).

    Policy: ``sustain`` consecutive samples of occupancy >= ``high``,
    queue-wait p50 >= ``wait_high_ms`` (``MXTPU_SCALE_WAIT_MS``; 0
    disables) or ANY shed growth scale UP; ``sustain`` samples of
    occupancy <= ``low`` with no sheds and the wait below the gate
    scale DOWN; every action is separated by ``cooldown_s``
    (``MXTPU_SCALE_COOLDOWN_S``) and clamped to [``MXTPU_SCALE_MIN``,
    ``MXTPU_SCALE_MAX``]. Actions are counted per role:
    ``serve/scale_up``/``serve/scale_down`` for the decode pool,
    ``serve/scale_up_prefill``/``serve/scale_down_prefill`` for a
    prefill pool (the ``serve.scale`` instant carries ``role`` too).

    Thread shape: decisions run under the scaler lock
    (``_decide_locked``); the spawn/retire callables — which may block
    for seconds — run OUTSIDE it, on whichever thread called
    :meth:`step` (the supervisor loop, or a test driving steps
    manually).
    """

    def __init__(self, pressure, spawn, retire,
                 min_workers: int | None = None,
                 max_workers: int | None = None,
                 cooldown_s: float | None = None,
                 interval_s: float = 1.0, high: float = 0.85,
                 low: float = 0.15, sustain: int = 3,
                 start: bool = False, role: str = "decode",
                 wait_high_ms: float | None = None):
        self._pressure = pressure
        self._spawn = spawn
        self._retire = retire
        self.role = str(role)
        self.min_workers = min_workers if min_workers is not None \
            else scale_min()
        self.max_workers = max_workers if max_workers is not None \
            else scale_max()
        self.cooldown_s = cooldown_s if cooldown_s is not None \
            else scale_cooldown_s()
        self.wait_high_ms = wait_high_ms if wait_high_ms is not None \
            else scale_wait_ms()
        self.interval_s = float(interval_s)
        self.high = float(high)
        self.low = float(low)
        self.sustain = max(int(sustain), 1)
        self._lock = threading.Lock()
        self._hot = 0           # consecutive high-pressure samples
        self._cold = 0          # consecutive idle samples
        self._last_shed = None  # previous cumulative shed count
        self._last_action_at = 0.0
        self.actions: list = []  # ("up"/"down", monotonic instant)
        self._stop_evt = threading.Event()
        self._thread = None
        if start:
            self.start()

    # ------------------------------------------------------------ lifecycle
    def start(self):
        if self._thread is not None and self._thread.is_alive():
            return
        self._stop_evt.clear()
        self._thread = threading.Thread(
            target=self._run, name="mxtpu-fleet-scaler", daemon=True)
        self._thread.start()

    def stop(self, timeout: float = 10.0):
        self._stop_evt.set()
        t, self._thread = self._thread, None
        if t is not None:
            t.join(timeout=timeout)

    def _run(self):
        while not self._stop_evt.wait(self.interval_s):
            try:
                self.step()
            except Exception:  # noqa: BLE001 - a scaler crash must never
                pass           # take the serving plane down

    # --------------------------------------------------------------- policy
    def _decide_locked(self, sample: dict, now: float):
        """Pure decision under the scaler lock: update the sustained
        counters and return 'up'/'down'/None. No callable (and nothing
        blocking) runs in here."""
        size = int(sample.get("size", 0))
        occ = float(sample.get("occupancy", 0.0))
        shed = sample.get("shed")
        shed_delta = 0
        if shed is not None:
            if self._last_shed is not None:
                shed_delta = max(int(shed) - self._last_shed, 0)
            self._last_shed = int(shed)
        wait = sample.get("queue_wait_ms")
        wait_hot = bool(self.wait_high_ms) and wait is not None \
            and float(wait) >= self.wait_high_ms
        hot = occ >= self.high or shed_delta > 0 or wait_hot
        cold = occ <= self.low and shed_delta == 0 and not wait_hot
        self._hot = self._hot + 1 if hot else 0
        self._cold = self._cold + 1 if cold else 0
        if now - self._last_action_at < self.cooldown_s:
            return None
        if self._hot >= self.sustain and size < self.max_workers:
            self._hot = 0
            self._cold = 0
            self._last_action_at = now
            self.actions.append(("up", now))
            return "up"
        if self._cold >= self.sustain and size > self.min_workers:
            self._hot = 0
            self._cold = 0
            self._last_action_at = now
            self.actions.append(("down", now))
            return "down"
        return None

    def step(self):
        """One supervision sample: read pressure, decide, act. Returns
        the action taken ('up'/'down'/None)."""
        sample = self._pressure()
        now = time.monotonic()
        with self._lock:
            action = self._decide_locked(dict(sample), now)
        if action == "up":
            self._spawn()
            self._count("serve/scale_up", sample)
        elif action == "down":
            if self._retire() is False:
                with self._lock:
                    # nothing retirable: undo the action record, spend
                    # no cooldown
                    self._last_action_at = 0.0
                    self.actions.pop()
                return None
            self._count("serve/scale_down", sample)
        return action

    def _count(self, counter: str, sample: dict):
        """Scaling accounting (best-effort — the launcher must run even
        where the package is not importable). Non-decode pools count
        under a role-suffixed name so the prefill pool's elasticity is
        visible separately from the decode pool's."""
        if self.role != "decode":
            counter = f"{counter}_{self.role}"
        try:
            from mxnet_tpu import telemetry as _tel

            _tel.registry().counter(counter).inc()
            _tel.instant("serve.scale", {
                "counter": counter,
                "role": self.role,
                "occupancy": sample.get("occupancy"),
                "queue_wait_ms": sample.get("queue_wait_ms"),
                "size": sample.get("size")})
        except Exception:  # noqa: BLE001
            pass


def _count_restart(attempt: int, rc: int, delay: float):
    """Restart accounting in the launcher's telemetry registry (the
    ``launch/`` family; best-effort — the launcher must run even where
    the package is not importable)."""
    try:
        from mxnet_tpu import telemetry as _tel

        _tel.registry().counter("launch/restarts").inc()
        _tel.instant("launch.restart",
                     {"attempt": attempt, "rc": rc, "backoff_s": delay})
    except Exception:  # noqa: BLE001
        pass


def launch_elastic(num_procs: int, command, max_restarts: int = 0,
                   coordinator: str | None = None,
                   timeout: float | None = None,
                   backoff_s: float | None = None,
                   max_backoff_s: float = 30.0,
                   _sleep=time.sleep):
    """Restart-based failure recovery (SURVEY §5: the reference
    ecosystem's answer to worker failure was checkpoint + full-job
    restart — there is no partial-membership mode in a bulk-synchronous
    collectives job, so ELASTIC here means: when any worker dies, tear
    the job down and relaunch ALL workers, which resume from the latest
    committed checkpoint (``mxnet_tpu.checkpoint`` /
    ``TrainStep.load_checkpoint``). Each attempt gets a fresh
    coordinator port (a user-supplied ``coordinator`` is honored on the
    FIRST attempt only — relaunching on the dead attempt's port could
    collide with TIME_WAIT sockets or stale coordination-service state);
    ``MXNET_TPU_RESTART_COUNT`` tells workers which attempt they are.

    Restarts are spaced by capped exponential backoff with jitter
    (``backoff_s`` base, ``MXTPU_RESTART_BACKOFF_S`` default 1.0,
    doubling per attempt up to ``max_backoff_s``): a job that dies
    instantly — bad binary, dead coordinator host, full disk — must not
    hammer the scheduler/rendezvous with back-to-back relaunches.
    Restarts are counted in the telemetry registry (``launch/restarts``)."""
    import random

    attempts = max_restarts + 1
    base = backoff_s if backoff_s is not None else restart_backoff_s()
    rc = 0
    for attempt in range(attempts):
        os.environ["MXNET_TPU_RESTART_COUNT"] = str(attempt)
        rc = launch_local(num_procs, command,
                          coordinator=coordinator if attempt == 0
                          else None, timeout=timeout)
        if rc == 0:
            return 0
        if attempt + 1 >= attempts:
            print(f"launch: attempt {attempt + 1}/{attempts} failed "
                  f"rc={rc}; giving up")
            break
        delay = min(base * (2.0 ** attempt), max_backoff_s) \
            * (1.0 + 0.25 * random.random())
        print(f"launch: attempt {attempt + 1}/{attempts} failed rc={rc}; "
              f"restarting from the latest checkpoint in {delay:.1f}s")
        _count_restart(attempt, rc, delay)
        if delay > 0:
            _sleep(delay)
    return rc


def launch_ssh(hosts, command, coordinator: str | None = None):
    """One process per host via ssh (reference ssh tracker semantics)."""
    num = len(hosts)
    coordinator = coordinator or f"{hosts[0]}:{find_free_port()}"
    cwd = os.getcwd()
    procs = []
    pumps = []
    for pid, host in enumerate(hosts):
        envs = " ".join(
            f"{k}={shlex.quote(v)}"
            for k, v in worker_env(coordinator, num, pid).items()
            if k.startswith(("MXNET_", "JAX_", "XLA_", "TPU_", "PYTHON"))
        )
        remote = f"cd {shlex.quote(cwd)} && env {envs} {' '.join(shlex.quote(c) for c in command)}"
        p = subprocess.Popen(
            ["ssh", "-o", "StrictHostKeyChecking=no", host, remote],
            stdout=subprocess.PIPE,
            stderr=subprocess.STDOUT,
        )
        t = threading.Thread(target=_pump, args=(p, host), daemon=True)
        t.start()
        procs.append(p)
        pumps.append(t)
    rc = 0
    for p in procs:
        rc = max(rc, p.wait())
    for t in pumps:
        t.join(timeout=5)
    return rc


def main(argv=None):
    ap = argparse.ArgumentParser(
        description=__doc__, formatter_class=argparse.RawDescriptionHelpFormatter
    )
    ap.add_argument("-n", "--num-workers", type=int, required=True)
    ap.add_argument(
        "--launcher", choices=["local", "ssh"], default="local",
    )
    ap.add_argument("-H", "--hostfile", help="one host per line (ssh launcher)")
    ap.add_argument(
        "--coordinator",
        help="host:port of the jax.distributed coordinator "
        "(default: this host, a free port)",
    )
    ap.add_argument(
        "--max-restarts", type=int, default=0,
        help="relaunch the whole job up to N times when a worker dies "
        "(workers resume from the latest committed checkpoint)",
    )
    ap.add_argument(
        "--restart-backoff", type=float, default=None,
        help="base seconds of the capped exponential backoff between "
        "restart attempts (default: MXTPU_RESTART_BACKOFF_S or 1.0)",
    )
    ap.add_argument("command", nargs=argparse.REMAINDER)
    args = ap.parse_args(argv)
    command = args.command
    if command and command[0] == "--":
        command = command[1:]
    if not command:
        ap.error("no worker command given")
    if args.launcher == "local":
        if args.max_restarts > 0:
            rc = launch_elastic(args.num_workers, command,
                                max_restarts=args.max_restarts,
                                coordinator=args.coordinator,
                                backoff_s=args.restart_backoff)
        else:
            rc = launch_local(args.num_workers, command, args.coordinator)
    else:
        if not args.hostfile:
            ap.error("--launcher ssh requires --hostfile")
        with open(args.hostfile) as f:
            hosts = [h.strip() for h in f if h.strip() and not h.startswith("#")]
        if len(hosts) < args.num_workers:
            ap.error(f"hostfile has {len(hosts)} hosts < -n {args.num_workers}")
        rc = launch_ssh(hosts[: args.num_workers], command, args.coordinator)
    sys.exit(rc)


if __name__ == "__main__":
    main()
