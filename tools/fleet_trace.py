#!/usr/bin/env python
"""Merge per-process event streams into ONE clock-aligned Chrome trace.

Every traced process (router, prefill/decode workers — see
``serving.tracing.maybe_enable_process``) appends spans to its OWN
``events.jsonl``, stamped on its OWN trace clock (µs since telemetry
init — ``telemetry.clock_us``). Those clocks share no origin, so the
raw streams cannot be overlaid. The router, however, records
``trace.clock_offset`` instants — one per ping/telemetry probe, each
carrying the probed worker's pid, the midpoint offset estimate and the
probe RTT. This tool:

1. discovers every ``events.jsonl`` under the trace root,
2. picks the stream containing the ``trace.clock_offset`` instants as
   the REFERENCE timeline (the router's),
3. per peer pid keeps the minimum-RTT probe (NTP's selection rule:
   the midpoint estimator's error is bounded by RTT/2), and
4. shifts every other stream onto the reference clock
   (``ts' = ts + offset``), emitting one Chrome-trace JSON with a
   ``process_name`` metadata record per process.

Spans tagged with a ``request_id`` (the distributed-tracing id minted
at ``Router.submit``) line up across processes: one request renders as
queue → handoff → prefill → kv_push → adopt/decode → request, each
segment in the process that actually ran it.

Usage:
  python tools/fleet_trace.py <trace-root> [-o fleet_trace.json]
  python tools/fleet_trace.py <trace-root> --request 1f2e3d4c5b6a7988
"""

from __future__ import annotations

import argparse
import glob
import json
import os
import sys

__all__ = ["discover_streams", "load_stream", "offsets_from_events",
           "merge_streams", "main"]


def discover_streams(root):
    """Every ``events.jsonl`` under ``root`` (root itself included),
    sorted for determinism. Returns ``[(label, path)]`` where the label
    is the stream's directory name (``<name>_<pid>``)."""
    out = []
    direct = os.path.join(root, "events.jsonl")
    if os.path.exists(direct):
        out.append((os.path.basename(os.path.normpath(root)), direct))
    for path in sorted(glob.glob(os.path.join(root, "*", "events.jsonl"))):
        out.append((os.path.basename(os.path.dirname(path)), path))
    return out


def load_stream(path):
    """Parsed JSONL records, torn trailing lines skipped (the stream is
    append-only and a SIGKILL'd worker may die mid-write — surviving
    whole lines are exactly what the chaos tests assert on)."""
    events = []
    with open(path) as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            try:
                events.append(json.loads(line))
            except ValueError:
                pass
    return events


def offsets_from_events(events):
    """Min-RTT clock offset per peer pid from a reference stream's
    ``trace.clock_offset`` instants. Returns
    ``{peer_pid: (offset_us, rtt_us, replica)}`` with
    ``peer_ts + offset ≈ reference_ts``."""
    best = {}
    for e in events:
        if e.get("name") != "trace.clock_offset" or e.get("ph") != "i":
            continue
        a = e.get("args") or {}
        pid, off, rtt = a.get("peer_pid"), a.get("offset_us"), \
            a.get("rtt_us")
        if pid is None or off is None or rtt is None:
            continue
        if pid not in best or rtt < best[pid][1]:
            best[pid] = (float(off), float(rtt), a.get("replica"))
    return best


def _pick_reference(streams):
    """The stream holding the most ``trace.clock_offset`` instants is
    the reference timeline (the router probes everyone; workers probe
    nobody). Returns its index, or None when no stream has any."""
    ref, ref_n = None, 0
    for i, (_, events) in enumerate(streams):
        n = sum(1 for e in events
                if e.get("name") == "trace.clock_offset")
        if n > ref_n:
            ref, ref_n = i, n
    return ref


def merge_streams(streams, request_id=None):
    """``streams`` is ``[(label, events)]``. Returns
    ``(trace_events, report)`` — the merged, clock-shifted Chrome event
    list plus a dict describing the alignment (reference stream, per-pid
    offsets, unaligned pids)."""
    ref = _pick_reference(streams)
    offsets = offsets_from_events(streams[ref][1]) if ref is not None \
        else {}
    merged = []
    names = {}  # pid -> label, for the metadata records
    unaligned = set()
    for i, (label, events) in enumerate(streams):
        for e in events:
            pid = e.get("pid")
            if pid is not None:
                names.setdefault(pid, label)
            shift = 0.0
            if i != ref:
                got = offsets.get(pid)
                if got is not None:
                    shift = got[0]
                elif pid is not None:
                    unaligned.add(pid)
            if request_id is not None:
                rid = (e.get("args") or {}).get("request_id")
                if rid != request_id:
                    continue
            ce = dict(e)
            if "ts" in ce:
                ce["ts"] = float(ce["ts"]) + shift
            merged.append(ce)
    merged.sort(key=lambda e: e.get("ts", 0.0))
    meta = [{"name": "process_name", "ph": "M", "pid": pid,
             "args": {"name": label}}
            for pid, label in sorted(names.items())]
    report = {
        "reference": streams[ref][0] if ref is not None else None,
        "offsets": {str(pid): {"offset_us": off, "rtt_us": rtt,
                               "replica": rep}
                    for pid, (off, rtt, rep) in sorted(offsets.items())},
        "unaligned_pids": sorted(unaligned),
        "streams": [label for label, _ in streams],
        "events": len(merged),
    }
    return meta + merged, report


def main(argv=None):
    ap = argparse.ArgumentParser(
        description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter)
    ap.add_argument("root", help="trace root directory (MXTPU_TRACE_DIR)")
    ap.add_argument("-o", "--out", default=None,
                    help="output path (default <root>/fleet_trace.json)")
    ap.add_argument("--request", default=None, metavar="RID",
                    help="keep only events tagged with this request_id")
    args = ap.parse_args(argv)

    found = discover_streams(args.root)
    if not found:
        print(f"no events.jsonl under {args.root}", file=sys.stderr)
        return 1
    streams = [(label, load_stream(path)) for label, path in found]
    events, report = merge_streams(streams, request_id=args.request)
    out = args.out or os.path.join(args.root, "fleet_trace.json")
    with open(out, "w") as f:
        json.dump({"traceEvents": events, "displayTimeUnit": "ms",
                   "otherData": report}, f)
    print(f"{out}: {report['events']} events from "
          f"{len(streams)} stream(s); reference={report['reference']}")
    for pid, o in report["offsets"].items():
        print(f"  pid {pid} ({o['replica']}): offset "
              f"{o['offset_us'] / 1e3:+.3f} ms, rtt {o['rtt_us']:.0f} µs")
    if report["unaligned_pids"]:
        print(f"  WARNING: no clock samples for pid(s) "
              f"{report['unaligned_pids']} — their timestamps are "
              "unshifted", file=sys.stderr)
    return 0


if __name__ == "__main__":
    sys.exit(main())
