#!/usr/bin/env python
"""Pretty-print a JSONL telemetry dump (``events.jsonl`` from
``mx.telemetry``).

Aggregates spans by name (count, total/mean/p50/p95/p99/max), lists
instant events (checkpoint commits, watchdog stalls), and — when pointed
at a telemetry DIRECTORY — also surfaces ``heartbeat.json`` and
``report.json`` if present.

Usage:
  python tools/telemetry_report.py telemetry/            # a dump dir
  python tools/telemetry_report.py telemetry/events.jsonl
  python tools/telemetry_report.py events.jsonl --top 20 --sort total
"""

from __future__ import annotations

import argparse
import json
import os
import sys

# Every metric family the package emits, and which section of this tool
# surfaces it. mxlint's telemetry-names pass fails CI when code emits a
# family missing here (it would silently vanish from every report) or
# when an entry here is dead. Families mapped to "Host-side training"
# print through _print_host_family below; the serving-era families have
# dedicated sections.
KNOWN_METRIC_FAMILIES = {
    "compile": "Compile (shape stability)",
    "infer": "Inference / serving",
    "serve": "Self-healing serving",
    "launch": "Self-healing serving",
    "transport": "Cross-process transport",
    "disagg": "Disaggregated serving",
    "shard": "SPMD sharding",
    "trainer": "Host-side training",
    "kvstore": "Host-side training",
    "input": "Host-side training",
    "device": "Host-side training",
    "watchdog": "Host-side training",
    "jax": "Compile (shape stability)",
    "fleet": "Fleet observability",
}

# Span/instant families (Chrome-trace names are dotted); spans aggregate
# generically in the Spans table, so membership here is the emitted
# surface the consistency pass checks, not a formatting choice.
KNOWN_SPAN_FAMILIES = {
    "checkpoint", "dataloader", "disagg", "estimator", "imperative",
    "infer", "input", "kvstore", "launch", "serve", "trace", "trainer",
    "trainstep", "transport", "watchdog",
}


def _quantile(sorted_vals, p):
    if not sorted_vals:
        return 0.0
    if len(sorted_vals) == 1:
        return sorted_vals[0]
    rank = (p / 100.0) * (len(sorted_vals) - 1)
    lo = int(rank)
    hi = min(lo + 1, len(sorted_vals) - 1)
    frac = rank - lo
    return sorted_vals[lo] * (1 - frac) + sorted_vals[hi] * frac


def load_events(path):
    """Yield parsed JSONL records, skipping torn lines (the stream is
    append-only and may end mid-write after a crash — that is the point
    of the format)."""
    with open(path) as f:
        for lineno, line in enumerate(f, 1):
            line = line.strip()
            if not line:
                continue
            try:
                yield json.loads(line)
            except ValueError:
                print(f"  (skipping torn line {lineno})", file=sys.stderr)


def summarize(events):
    spans = {}
    instants = []
    for e in events:
        ph = e.get("ph")
        if ph == "X":
            spans.setdefault(e.get("name", "?"), []).append(
                float(e.get("dur", 0.0)))
        elif ph == "i":
            instants.append(e)
    return spans, instants


def format_spans(spans, top=None, sort="total"):
    rows = []
    for name, durs in spans.items():
        s = sorted(durs)
        total = sum(durs)
        rows.append({
            "name": name,
            "count": len(durs),
            "total_ms": total / 1e3,
            "mean_ms": total / len(durs) / 1e3,
            "p50_ms": _quantile(s, 50) / 1e3,
            "p95_ms": _quantile(s, 95) / 1e3,
            "p99_ms": _quantile(s, 99) / 1e3,
            "max_ms": s[-1] / 1e3,
        })
    keys = {"total": "total_ms", "count": "count", "mean": "mean_ms",
            "p95": "p95_ms", "name": "name"}
    rev = sort != "name"
    rows.sort(key=lambda r: r[keys.get(sort, "total_ms")], reverse=rev)
    if top:
        rows = rows[:top]
    hdr = (f"{'Span':<32}{'Count':>8}{'Total(ms)':>12}{'Mean(ms)':>10}"
           f"{'p50':>9}{'p95':>9}{'p99':>9}{'Max':>9}")
    lines = [hdr, "-" * len(hdr)]
    for r in rows:
        lines.append(
            f"{r['name']:<32}{r['count']:>8}{r['total_ms']:>12.2f}"
            f"{r['mean_ms']:>10.3f}{r['p50_ms']:>9.3f}{r['p95_ms']:>9.3f}"
            f"{r['p99_ms']:>9.3f}{r['max_ms']:>9.3f}")
    return "\n".join(lines)


def _print_json_file(path, title):
    if not os.path.exists(path):
        return
    try:
        with open(path) as f:
            data = json.load(f)
    except ValueError:
        return
    print(f"\n== {title} ({path}) ==")
    print(json.dumps(data, indent=2, default=str)[:4000])


def _print_host_families(report_path):
    """Surface the host-side training families (trainer/, kvstore/,
    input/, device/, watchdog/) from a ``report.json`` registry
    snapshot — previously only visible in the raw report dump."""
    if not os.path.exists(report_path):
        return
    try:
        with open(report_path) as f:
            report = json.load(f)
    except ValueError:
        return
    fams = tuple(f + "/" for f, sec in KNOWN_METRIC_FAMILIES.items()
                 if sec == "Host-side training")
    counters = {k: v for k, v in report.get("counters", {}).items()
                if k.startswith(fams)}
    gauges = {k: v for k, v in report.get("gauges", {}).items()
              if k.startswith(fams)}
    hists = {k: v for k, v in report.get("histograms", {}).items()
             if k.startswith(fams)}
    if not counters and not gauges and not hists:
        return
    print("\n== Host-side training ==")
    for k in sorted(counters):
        print(f"  {k:<38} {counters[k]}")
    for k in sorted(gauges):
        print(f"  {k:<38} {gauges[k]}")
    for k in sorted(hists):
        h = hists[k]
        print(f"  {k:<38} p50={h.get('p50')} p95={h.get('p95')} "
              f"n={h.get('count')}")


def _print_compile_family(report_path):
    """Surface the ``compile/`` metric family (shape-stability spine:
    signatures compiled, post-warmup recompiles, persistent-cache reuse)
    from a ``report.json`` registry snapshot."""
    if not os.path.exists(report_path):
        return
    try:
        with open(report_path) as f:
            report = json.load(f)
    except ValueError:
        return
    counters = {k: v for k, v in report.get("counters", {}).items()
                if k.startswith("compile/")}
    gauges = {k: v for k, v in report.get("gauges", {}).items()
              if k.startswith("compile/")}
    jax_compile = report.get("histograms", {}).get("jax/compile_time_s")
    if not counters and not gauges and not jax_compile:
        return
    print("\n== Compile (shape stability) ==")
    for k in sorted(counters):
        print(f"  {k:<38} {counters[k]}")
    for k in sorted(gauges):
        print(f"  {k:<38} {gauges[k]}")
    if jax_compile:
        print(f"  {'jax/compile_time_s total':<38} "
              f"{jax_compile.get('sum', 0.0):.3f}s over "
              f"{jax_compile.get('count', 0)} events")
    recompiles = counters.get("compile/steady_state_recompiles", 0)
    if recompiles:
        print(f"  WARNING: {recompiles} steady-state recompile(s) — "
              "shape churn after warmup (bucket/pad inputs)")


def _print_infer_family(report_path):
    """Surface the ``infer/`` metric family (serving spine: prefill /
    per-token decode latency, throughput, batcher admission wait and slot
    occupancy) from a ``report.json`` registry snapshot."""
    if not os.path.exists(report_path):
        return
    try:
        with open(report_path) as f:
            report = json.load(f)
    except ValueError:
        return
    counters = {k: v for k, v in report.get("counters", {}).items()
                if k.startswith("infer/")}
    gauges = {k: v for k, v in report.get("gauges", {}).items()
              if k.startswith("infer/")}
    hists = {k: v for k, v in report.get("histograms", {}).items()
             if k.startswith("infer/")}
    if not counters and not gauges and not hists:
        return
    print("\n== Inference / serving ==")
    for k in sorted(counters):
        print(f"  {k:<38} {counters[k]}")
    for k in sorted(gauges):
        print(f"  {k:<38} {gauges[k]}")
    for k in sorted(hists):
        h = hists[k]
        print(f"  {k:<38} p50={h.get('p50')} p95={h.get('p95')} "
              f"n={h.get('count')}")
    rejected = counters.get("infer/rejected_backpressure", 0)
    if rejected:
        print(f"  WARNING: {rejected} request(s) rejected by admission "
              "control — raise MXTPU_PAGES or relax MXTPU_ADMIT_* "
              "thresholds if the pool is undersized")
    preempted = counters.get("infer/preempted", 0)
    if preempted:
        print(f"  WARNING: {preempted} mid-decode preemption(s) — the "
              "page pool oversubscribes more than the workload tolerates "
              "(MXTPU_PAGES / MXTPU_ADMIT_FREE_PAGES)")


def _print_serve_family(report_path):
    """Surface the ``serve/`` metric family (self-healing serving plane:
    hot weight swaps, replica failovers, transparent retries, dropped
    requests, injected faults) from a ``report.json`` snapshot."""
    if not os.path.exists(report_path):
        return
    try:
        with open(report_path) as f:
            report = json.load(f)
    except ValueError:
        return
    counters = {k: v for k, v in report.get("counters", {}).items()
                if k.startswith(("serve/", "launch/"))}
    gauges = {k: v for k, v in report.get("gauges", {}).items()
              if k.startswith("serve/")}
    version = report.get("weights_version")
    if not counters and not gauges and not version:
        return
    print("\n== Self-healing serving ==")
    if version:
        print(f"  {'weights_version':<38} {version}")
    for k in sorted(gauges):
        print(f"  {k:<38} {gauges[k]}")
    for k in sorted(counters):
        print(f"  {k:<38} {counters[k]}")
    dropped = counters.get("serve/dropped", 0)
    if dropped:
        print(f"  WARNING: {dropped} request(s) dropped after retry "
              "exhaustion — check replica health and MXTPU_RETRY_MAX")


def _print_transport_family(report_path):
    """Surface the ``transport/`` metric family (cross-process serving
    plane: per-call RPC latency, connect retries, dead connections) plus
    the router's worker-facing shed counters from a ``report.json``
    snapshot."""
    if not os.path.exists(report_path):
        return
    try:
        with open(report_path) as f:
            report = json.load(f)
    except ValueError:
        return
    counters = {k: v for k, v in report.get("counters", {}).items()
                if k.startswith("transport/")}
    gauges = {k: v for k, v in report.get("gauges", {}).items()
              if k.startswith("transport/")}
    hists = {k: v for k, v in report.get("histograms", {}).items()
             if k.startswith("transport/")}
    sheds = {k: v for k, v in report.get("counters", {}).items()
             if k.startswith("serve/shed_")}
    if not counters and not gauges and not hists and not sheds:
        return
    print("\n== Cross-process transport ==")
    for k in sorted(counters):
        print(f"  {k:<38} {counters[k]}")
    for k in sorted(gauges):
        print(f"  {k:<38} {gauges[k]}")
    for k in sorted(hists):
        h = hists[k]
        print(f"  {k:<38} p50={h.get('p50')} p95={h.get('p95')} "
              f"n={h.get('count')}")
    for k in sorted(sheds):
        print(f"  {k:<38} {sheds[k]}")
    shed_total = sum(sheds.values())
    if shed_total:
        print(f"  WARNING: {shed_total} request(s) shed at router "
              "admission — every replica was degraded; scale out or "
              "relax MXTPU_SHED_* thresholds")
    errors = counters.get("transport/errors", 0)
    if errors:
        print(f"  WARNING: {errors} dead worker connection(s) — check "
              "worker logs/heartbeats for crashes or partitions")


def _print_disagg_family(report_path):
    """Surface the ``disagg/`` metric family (disaggregated serving:
    KV handoffs adopted vs re-prefill fallbacks, push latency and
    bytes, per-class TTFT, scale actions) from a ``report.json``
    snapshot."""
    if not os.path.exists(report_path):
        return
    try:
        with open(report_path) as f:
            report = json.load(f)
    except ValueError:
        return
    counters = {k: v for k, v in report.get("counters", {}).items()
                if k.startswith("disagg/")
                or k in ("serve/scale_up", "serve/scale_down")}
    hists = {k: v for k, v in report.get("histograms", {}).items()
             if k.startswith("disagg/")}
    if not counters and not hists:
        return
    print("\n== Disaggregated serving ==")
    for k in sorted(counters):
        print(f"  {k:<38} {counters[k]}")
    for k in sorted(hists):
        h = hists[k]
        print(f"  {k:<38} p50={h.get('p50')} p95={h.get('p95')} "
              f"n={h.get('count')}")
    re_prefills = counters.get("disagg/re_prefills", 0)
    handoffs = counters.get("disagg/handoffs", 0)
    if re_prefills and re_prefills >= max(handoffs, 1):
        print(f"  WARNING: {re_prefills} re-prefill(s) vs {handoffs} "
              "adopted handoff(s) — pushes are failing (dead prefill "
              "workers, dropped links, or mismatched model geometry); "
              "the fleet is paying prefill twice")


def _print_prefix_section(report_path):
    """Surface the prefix-caching slice of the ``infer/``/``serve/``
    families (radix-trie hit rate, tokens served from cached KV, pages
    shared across requests, copy-on-write copies, affinity placements)
    from a ``report.json`` snapshot."""
    if not os.path.exists(report_path):
        return
    try:
        with open(report_path) as f:
            report = json.load(f)
    except ValueError:
        return
    names = ("infer/prefix_tokens_saved", "infer/prefix_cow_copies",
             "serve/prefix_affinity")
    counters = {k: v for k, v in report.get("counters", {}).items()
                if k in names}
    gauges = {k: v for k, v in report.get("gauges", {}).items()
              if k in ("infer/prefix_hit_rate", "infer/pages_shared")}
    if not counters and not gauges:
        return
    print("\n== Prefix caching ==")
    for k in sorted(gauges):
        print(f"  {k:<38} {gauges[k]}")
    for k in sorted(counters):
        print(f"  {k:<38} {counters[k]}")
    hit_rate = gauges.get("infer/prefix_hit_rate")
    saved = counters.get("infer/prefix_tokens_saved", 0)
    if saved:
        print(f"  prefill tokens served from cached KV: {saved}")
    if hit_rate is not None and hit_rate == 0.0 and saved == 0:
        print("  WARNING: the prefix cache is enabled but never hits — "
              "prompts may be unique per request (disable with "
              "MXTPU_PREFIX_CACHE=0 to reclaim pool pages)")


def _print_spec_section(report_path):
    """Surface the speculative-decoding slice of the ``infer/`` family
    (per-round accepted-draft length, draft-dispatch latency, and
    whether the Pallas paged flash kernels are active) from a
    ``report.json`` snapshot."""
    if not os.path.exists(report_path):
        return
    try:
        with open(report_path) as f:
            report = json.load(f)
    except ValueError:
        return
    hists = {k: v for k, v in report.get("histograms", {}).items()
             if k in ("infer/spec_accept_len", "infer/spec_draft_ms")}
    gauges = {k: v for k, v in report.get("gauges", {}).items()
              if k == "infer/flash_kernel"}
    if not hists and not gauges:
        return
    print("\n== Speculative decoding ==")
    for k in sorted(gauges):
        on = "on (Pallas paged flash)" if gauges[k] else "off (dense)"
        print(f"  {k:<38} {on}")
    for k in sorted(hists):
        h = hists[k]
        print(f"  {k:<38} p50={h.get('p50')} p95={h.get('p95')} "
              f"n={h.get('count')}")
    acc = hists.get("infer/spec_accept_len")
    if acc and acc.get("count") and acc.get("sum", 0.0) == 0.0:
        print("  WARNING: the draft model's proposals are NEVER accepted "
              "— the target re-scores every token and speculation only "
              "adds draft latency; check that the draft tracks the "
              "target (same tokenizer/data) or lower MXTPU_SPEC_K")


def _print_shard_family(report_path):
    """Surface the ``shard/`` metric family (SPMD sharding spine: mesh
    shape, global vs per-shard parameter bytes, collective-traffic
    estimate, host-allreduce skips) from a ``report.json`` snapshot."""
    if not os.path.exists(report_path):
        return
    try:
        with open(report_path) as f:
            report = json.load(f)
    except ValueError:
        return
    counters = {k: v for k, v in report.get("counters", {}).items()
                if k.startswith("shard/")}
    gauges = {k: v for k, v in report.get("gauges", {}).items()
              if k.startswith("shard/")}
    mesh = report.get("mesh_shape")
    if not counters and not gauges and not mesh:
        return
    print("\n== SPMD sharding ==")
    if mesh:
        print(f"  {'mesh_shape':<38} {mesh}")
    if report.get("sharding"):
        print(f"  {'sharding':<38} {report['sharding']}")
    for k in sorted(gauges):
        print(f"  {k:<38} {gauges[k]}")
    for k in sorted(counters):
        print(f"  {k:<38} {counters[k]}")
    total = gauges.get("shard/param_bytes_total")
    per = gauges.get("shard/param_bytes_per_shard")
    if total and per and per < total:
        print(f"  params per shard: {per / total:.1%} of the full tree "
              f"({total / 1e6:.1f} MB -> {per / 1e6:.1f} MB/device)")


def _print_fleet_family(report_path):
    """Surface the ``fleet/`` metric family (the telemetry scrape loop:
    scrapes completed, scrape errors, replicas seen, per-request SLO
    burn) from a ``report.json`` snapshot."""
    if not os.path.exists(report_path):
        return
    try:
        with open(report_path) as f:
            report = json.load(f)
    except ValueError:
        return
    counters = {k: v for k, v in report.get("counters", {}).items()
                if k.startswith("fleet/")
                or k.startswith("serve/slo_burn_")}
    gauges = {k: v for k, v in report.get("gauges", {}).items()
              if k.startswith("fleet/")}
    if not counters and not gauges:
        return
    print("\n== Fleet observability ==")
    for k in sorted(gauges):
        print(f"  {k:<38} {gauges[k]}")
    for k in sorted(counters):
        print(f"  {k:<38} {counters[k]}")
    errors = counters.get("fleet/scrape_errors", 0)
    scrapes = counters.get("fleet/scrapes", 0)
    if errors and errors >= max(scrapes, 1):
        print(f"  WARNING: {errors} scrape error(s) vs {scrapes} "
              "completed scrape(s) — workers are unreachable from the "
              "telemetry loop (check transport health)")
    burn = sum(v for k, v in counters.items()
               if k.startswith("serve/slo_burn_"))
    if burn:
        print(f"  WARNING: {burn} request(s) finished past their class "
              "SLO — inspect per-request phase breakdowns "
              "(GenerationResult.phases) to attribute the overrun")


def main(argv=None):
    ap = argparse.ArgumentParser(
        description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter)
    ap.add_argument("path", help="events.jsonl file or telemetry directory")
    ap.add_argument("--top", type=int, default=None,
                    help="show only the top N spans")
    ap.add_argument("--sort", default="total",
                    choices=["total", "count", "mean", "p95", "name"])
    args = ap.parse_args(argv)

    path = args.path
    directory = None
    if os.path.isdir(path):
        directory = path
        path = os.path.join(path, "events.jsonl")
    if not os.path.exists(path):
        ap.error(f"no events file at {path}")

    spans, instants = summarize(load_events(path))
    if not spans and not instants:
        print(f"{path}: no events")
        return 0
    print(f"== Spans ({path}) ==")
    if spans:
        print(format_spans(spans, top=args.top, sort=args.sort))
    else:
        print("(none)")
    if instants:
        print(f"\n== Instant events ({len(instants)}) ==")
        for e in instants:
            args_str = json.dumps(e.get("args", {}), default=str)
            print(f"  ts={e.get('ts', 0) / 1e6:>10.3f}s  "
                  f"{e.get('name', '?'):<28} {args_str}")
    if directory:
        _print_json_file(os.path.join(directory, "heartbeat.json"),
                         "Heartbeat")
        _print_json_file(os.path.join(directory, "report.json"), "Report")
        _print_host_families(os.path.join(directory, "report.json"))
        _print_compile_family(os.path.join(directory, "report.json"))
        _print_infer_family(os.path.join(directory, "report.json"))
        _print_prefix_section(os.path.join(directory, "report.json"))
        _print_spec_section(os.path.join(directory, "report.json"))
        _print_shard_family(os.path.join(directory, "report.json"))
        _print_serve_family(os.path.join(directory, "report.json"))
        _print_transport_family(os.path.join(directory, "report.json"))
        _print_disagg_family(os.path.join(directory, "report.json"))
        _print_fleet_family(os.path.join(directory, "report.json"))
    return 0


if __name__ == "__main__":
    sys.exit(main())
