#!/usr/bin/env python
"""Sharding placement lint: declared shardings must actually hold.

This checker now lives on the unified analysis framework as the
``sharding-placement`` pass
(``mxnet_tpu/analysis/passes/sharding_placement.py``) — run
``python tools/mxlint.py`` for the whole suite; this shim keeps the
historical standalone CLI and import surface.
"""

from __future__ import annotations

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

from mxnet_tpu.analysis.passes.sharding_placement import (  # noqa: E402,F401
    build_default_setup, check_post_step_placement, check_rules_coverage,
    check_step_placement, declared_shardings,
    ensure_devices as _ensure_devices, run_checks,
)


def main(argv=None):
    _ensure_devices()
    setup = build_default_setup()
    violations = run_checks(*setup)
    for v in violations:
        print(v)
    if violations:
        print(f"{len(violations)} sharding placement violation(s)")
        return 1
    print("sharding lint: every param carries its declared sharding; "
          "placements survive the step; no silent replication fallback")
    return 0


if __name__ == "__main__":
    sys.exit(main())
