#!/usr/bin/env python
"""Lint: the jitted hot paths must never block on the device.

This checker now lives on the unified analysis framework as the
``no-sync`` pass (``mxnet_tpu/analysis/passes/no_sync.py``) — run
``python tools/mxlint.py`` for the whole suite; this shim keeps the
historical standalone CLI and import surface
(``find_violations``/``find_all_violations``/``TARGETS``/rule sets).
"""

from __future__ import annotations

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

from mxnet_tpu.analysis.passes.no_sync import (  # noqa: E402,F401
    BATCHER_PY, BLOCKING_ATTRS, BLOCKING_BUILTINS, BLOCKING_QUALIFIED,
    FAST_PATH_FUNCS, INFER_PY, STEP_PY, TARGETS, find_all_violations,
    find_violations,
)


def main(argv=None):
    args = argv if argv is not None else sys.argv[1:]
    if args:
        violations = [(args[0], ln, msg)
                      for ln, msg in find_violations(args[0])]
    else:
        violations = find_all_violations()
    for path, lineno, msg in violations:
        print(f"{path}:{lineno}: {msg}")
    if violations:
        print(f"{len(violations)} blocking call(s) in jitted hot paths — "
              "move them off the dispatch path (stage in _stage/"
              "device_put_batch, sync in _resolve)")
        return 1
    print("train + inference hot paths are sync-free")
    return 0


if __name__ == "__main__":
    sys.exit(main())
