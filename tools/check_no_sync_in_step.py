#!/usr/bin/env python
"""Lint: the jitted hot paths must never block on the device.

Two pipelines depend on it:

- **Training** — the async device-feed overlap (``gluon.data.prefetch``)
  only works if ``TrainStep.__call__``'s pre-placed fast path (``__call__``
  + ``_dispatch``) stays pure dispatch.
- **Inference/serving** — the decode hot path (``InferStep.__call__`` /
  ``_dispatch`` / ``decode_n`` and ``DynamicBatcher._dispatch``) must
  fire prefill + the whole decode loop without a single host sync, or
  every generation call serializes against the device and the O(1)/token
  engine degrades back to host-latency-per-token.

Any host synchronization there (``.asnumpy()``, ``float(loss)``,
``np.asarray`` on a device array, ``block_until_ready``) silently un-does
the tentpole; this check walks the AST of the listed (file, class,
methods) targets and flags blocking calls.

Run standalone (nonzero exit on violations)::

    python tools/check_no_sync_in_step.py

or through the tier-1 suite (``tests/test_no_sync_lint.py`` imports
``find_violations``/``find_all_violations`` and asserts they return
nothing).
"""

from __future__ import annotations

import ast
import os
import sys

_ROOT = os.path.normpath(os.path.join(
    os.path.dirname(os.path.abspath(__file__)), os.pardir))
STEP_PY = os.path.join(_ROOT, "mxnet_tpu", "parallel", "step.py")
INFER_PY = os.path.join(_ROOT, "mxnet_tpu", "parallel", "infer.py")
BATCHER_PY = os.path.join(_ROOT, "mxnet_tpu", "serving", "batcher.py")

# the train-step fast-path bodies: __call__ (DeviceBatch detection +
# dispatch) and _dispatch (the staged-operand hot dispatch). _stage is
# deliberately NOT linted — it is the slow path the fast path skips.
FAST_PATH_FUNCS = ("__call__", "_dispatch")

# every linted (file, class, methods) hot path. The inference engine's
# decode_n is the whole generation dispatch and decode_iter/prefill_paged
# are the continuous-batching iteration dispatches; the batchers'
# _dispatch methods assemble and fire batches (DynamicBatcher._resolve /
# ContinuousBatcher._collect+_admit are the designated sync points and
# stay unlinted). ContinuousBatcher._step_once — the scheduler loop body
# — is linted too: its syncs must stay delegated to those named phases,
# never inlined next to a dispatch.
TARGETS = (
    (STEP_PY, "TrainStep", FAST_PATH_FUNCS),
    (INFER_PY, "InferStep", ("__call__", "_dispatch", "decode_n",
                             "decode_iter", "prefill_paged")),
    (BATCHER_PY, "DynamicBatcher", ("_dispatch",)),
    (BATCHER_PY, "ContinuousBatcher", ("_dispatch", "_step_once")),
)

# method attributes that force a device->host readback / host sync
BLOCKING_ATTRS = {
    "asnumpy", "asscalar", "item", "tolist", "block_until_ready",
    "copy_to_host_async",
}
# bare builtins that coerce a device scalar on the host
BLOCKING_BUILTINS = {"float", "int", "bool", "complex", "print"}
# module.attr calls that materialize device arrays on host (np.asarray on
# a device array round-trips it) or stall the thread
BLOCKING_QUALIFIED = {
    ("np", "asarray"), ("_np", "asarray"), ("numpy", "asarray"),
    ("np", "array"), ("_np", "array"), ("numpy", "array"),
    ("jax", "device_get"), ("time", "sleep"), ("_time", "sleep"),
}


def find_violations(path: str = STEP_PY, class_name: str = "TrainStep",
                    funcs=FAST_PATH_FUNCS):
    """Return [(lineno, message)] for blocking calls inside the given
    class's listed method bodies."""
    with open(path) as f:
        tree = ast.parse(f.read(), filename=path)
    out = []
    classes = [n for n in tree.body
               if isinstance(n, ast.ClassDef) and n.name == class_name]
    if not classes:
        return [(0, f"{class_name} class not found in {path}")]
    fns = [n for n in classes[0].body
           if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))
           and n.name in funcs]
    missing = set(funcs) - {f.name for f in fns}
    if missing:
        out.append((classes[0].lineno,
                    f"{class_name} hot-path method(s) {sorted(missing)} "
                    "not found — update TARGETS if the hot path was "
                    "renamed"))
    for fn in fns:
        for node in ast.walk(fn):
            if not isinstance(node, ast.Call):
                continue
            f = node.func
            if isinstance(f, ast.Name) and f.id in BLOCKING_BUILTINS:
                out.append((node.lineno,
                            f"{class_name}.{fn.name}: host coercion "
                            f"{f.id}(...) blocks on the device value"))
            elif isinstance(f, ast.Attribute):
                if f.attr in BLOCKING_ATTRS:
                    out.append((node.lineno,
                                f"{class_name}.{fn.name}: .{f.attr}() "
                                "forces a device->host sync"))
                elif isinstance(f.value, ast.Name) and \
                        (f.value.id, f.attr) in BLOCKING_QUALIFIED:
                    out.append((node.lineno,
                                f"{class_name}.{fn.name}: "
                                f"{f.value.id}.{f.attr}(...) "
                                "materializes/stalls on host"))
    return out


def find_all_violations():
    """Lint every TARGETS entry; returns [(path, lineno, message)]."""
    out = []
    for path, cls, funcs in TARGETS:
        for lineno, msg in find_violations(path, cls, funcs):
            out.append((path, lineno, msg))
    return out


def main(argv=None):
    args = argv if argv is not None else sys.argv[1:]
    if args:
        violations = [(args[0], ln, msg)
                      for ln, msg in find_violations(args[0])]
    else:
        violations = find_all_violations()
    for path, lineno, msg in violations:
        print(f"{path}:{lineno}: {msg}")
    if violations:
        print(f"{len(violations)} blocking call(s) in jitted hot paths — "
              "move them off the dispatch path (stage in _stage/"
              "device_put_batch, sync in _resolve)")
        return 1
    print("train + inference hot paths are sync-free")
    return 0


if __name__ == "__main__":
    sys.exit(main())
