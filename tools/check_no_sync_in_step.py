#!/usr/bin/env python
"""Lint: TrainStep's dispatch fast path must never block on the device.

The async device-feed pipeline (``gluon.data.prefetch``) only overlaps
input with compute if ``TrainStep.__call__``'s pre-placed fast path —
``__call__`` itself plus ``_dispatch`` — stays pure dispatch: any host
synchronization there (``.asnumpy()``, ``float(loss)``, ``np.asarray`` on
a device array, ``block_until_ready``) serializes the step against the
transfer and silently un-does the tentpole. This check walks the AST of
``mxnet_tpu/parallel/step.py`` and flags blocking calls in those bodies.

Run standalone (nonzero exit on violations)::

    python tools/check_no_sync_in_step.py

or through the tier-1 suite (``tests/test_no_sync_lint.py`` imports
``find_violations`` and asserts it returns nothing).
"""

from __future__ import annotations

import ast
import os
import sys

STEP_PY = os.path.normpath(os.path.join(
    os.path.dirname(os.path.abspath(__file__)), os.pardir,
    "mxnet_tpu", "parallel", "step.py"))

# the fast-path bodies: __call__ (DeviceBatch detection + dispatch) and
# _dispatch (the staged-operand hot dispatch). _stage is deliberately NOT
# linted — it is the slow path the fast path exists to skip.
FAST_PATH_FUNCS = ("__call__", "_dispatch")

# method attributes that force a device->host readback / host sync
BLOCKING_ATTRS = {
    "asnumpy", "asscalar", "item", "tolist", "block_until_ready",
    "copy_to_host_async",
}
# bare builtins that coerce a device scalar on the host
BLOCKING_BUILTINS = {"float", "int", "bool", "complex", "print"}
# module.attr calls that materialize device arrays on host (np.asarray on
# a device array round-trips it) or stall the thread
BLOCKING_QUALIFIED = {
    ("np", "asarray"), ("_np", "asarray"), ("numpy", "asarray"),
    ("np", "array"), ("_np", "array"), ("numpy", "array"),
    ("jax", "device_get"), ("time", "sleep"), ("_time", "sleep"),
}


def find_violations(path: str = STEP_PY):
    """Return [(lineno, message)] for blocking calls inside the fast-path
    bodies of TrainStep."""
    with open(path) as f:
        tree = ast.parse(f.read(), filename=path)
    out = []
    classes = [n for n in tree.body
               if isinstance(n, ast.ClassDef) and n.name == "TrainStep"]
    if not classes:
        return [(0, f"TrainStep class not found in {path}")]
    funcs = [n for n in classes[0].body
             if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))
             and n.name in FAST_PATH_FUNCS]
    missing = set(FAST_PATH_FUNCS) - {f.name for f in funcs}
    if missing:
        out.append((classes[0].lineno,
                    f"fast-path method(s) {sorted(missing)} not found — "
                    "update FAST_PATH_FUNCS if the hot path was renamed"))
    for fn in funcs:
        for node in ast.walk(fn):
            if not isinstance(node, ast.Call):
                continue
            f = node.func
            if isinstance(f, ast.Name) and f.id in BLOCKING_BUILTINS:
                out.append((node.lineno,
                            f"{fn.name}: host coercion {f.id}(...) blocks "
                            "on the device value"))
            elif isinstance(f, ast.Attribute):
                if f.attr in BLOCKING_ATTRS:
                    out.append((node.lineno,
                                f"{fn.name}: .{f.attr}() forces a "
                                "device->host sync"))
                elif isinstance(f.value, ast.Name) and \
                        (f.value.id, f.attr) in BLOCKING_QUALIFIED:
                    out.append((node.lineno,
                                f"{fn.name}: {f.value.id}.{f.attr}(...) "
                                "materializes/stalls on host"))
    return out


def main(argv=None):
    path = (argv or sys.argv[1:] or [STEP_PY])[0]
    violations = find_violations(path)
    for lineno, msg in violations:
        print(f"{path}:{lineno}: {msg}")
    if violations:
        print(f"{len(violations)} blocking call(s) in the TrainStep fast "
              "path — move them off the dispatch path or stage them in "
              "_stage/device_put_batch")
        return 1
    print("TrainStep fast path is sync-free")
    return 0


if __name__ == "__main__":
    sys.exit(main())
