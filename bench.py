"""Driver benchmark: BERT-base pretrain throughput on one chip.

Measures tokens/sec through the fully-jitted sharded TrainStep (forward +
backward + optimizer in ONE XLA executable, donated buffers) — BASELINE.md
config 3, the metric of record "tokens/sec/chip BERT-base pretrain".

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.
vs_baseline is measured/derived-ceiling where the ceiling is the 45%-MFU
param-matmul bound from BASELINE.md (~1.9e5 tok/s/chip on v4); the
reference mount shipped no published numbers (BASELINE.json published={}).
"""

from __future__ import annotations

import json
import time

import numpy as np


def _build(batch, seq):
    import mxnet_tpu as mx
    from mxnet_tpu import gluon, optimizer as opt
    from mxnet_tpu.gluon.model_zoo.bert import BERTModel
    from mxnet_tpu.parallel import TrainStep

    net = BERTModel(
        vocab_size=30522, units=768, hidden_size=3072, num_layers=12,
        num_heads=12, max_length=512, dropout=0.1,
    )
    net.initialize()
    net._probe_shapes(mx.nd.zeros((2, 8), dtype="int32"))
    ce = gluon.loss.SoftmaxCrossEntropyLoss()
    word_w = net.word_embed.weight

    class _PretrainLoss:
        """MLM-style CE against the tied embedding (exercises the full
        encoder + vocab-size matmul like real pretraining)."""

        def __call__(self, seq_out, pooled, label):
            w = word_w.data()
            logits = seq_out.reshape(-1, seq_out.shape[-1]).dot(w.T)
            return ce(logits, label.reshape(-1))

    # bf16 compute + f32 masters = the reference's "BERT + AMP" config 3
    step = TrainStep(net, _PretrainLoss(), opt.AdamW(learning_rate=1e-4),
                     compute_dtype="bfloat16", state_dtype="bfloat16")
    rng = np.random.RandomState(0)
    ids = mx.nd.array(rng.randint(0, 30522, (batch, seq)), dtype="int32")
    labels = mx.nd.array(rng.randint(0, 30522, (batch, seq)), dtype="int32")
    return step, ids, labels


def main():
    seq = 128
    # windows of 10: the end-of-window loss sync costs a full tunnel round
    # trip (~20 ms), so short windows understate throughput
    measure_steps = 40
    # import ONCE up front: a structural failure (bad module, registry bug)
    # must surface as itself, not as a re-import artifact from a retry
    try:
        import mxnet_tpu  # noqa: F401
    except Exception as e:  # noqa: BLE001
        print(json.dumps({
            "metric": "bert_base_pretrain_tokens_per_sec_per_chip",
            "value": 0.0,
            "unit": "tokens/sec",
            "vs_baseline": 0.0,
            "error": f"import failed: {type(e).__name__}: {e}"[:300],
        }))
        return
    first_err = None
    for attempt_batch in (64, 32, 16):
        try:
            step, ids, labels = _build(attempt_batch, seq)
            # warmup / compile; sync via host transfer — block_until_ready
            # does not actually block on the tunneled TPU backend
            for _ in range(3):
                loss = step(ids, labels)
            float(loss.asscalar())
            # the tunneled chip is shared and noisy (2-3x swings observed);
            # report the best of several windows — closest to unperturbed hw
            per = max(1, measure_steps // 4)
            best = float("inf")
            for _ in range(4):
                t0 = time.perf_counter()
                for _ in range(per):
                    loss = step(ids, labels)
                float(loss.asscalar())
                best = min(best, time.perf_counter() - t0)
            tokens = per * attempt_batch * seq
            tok_per_s = tokens / best
            ceiling = 1.9e5  # BASELINE.md derived 45%-MFU bound (v4)
            print(json.dumps({
                "metric": "bert_base_pretrain_tokens_per_sec_per_chip",
                "value": round(tok_per_s, 1),
                "unit": "tokens/sec",
                "vs_baseline": round(tok_per_s / ceiling, 4),
            }))
            return
        except Exception as e:  # noqa: BLE001 - retry smaller batch (OOM)
            if first_err is None:
                first_err = e
    print(json.dumps({
        "metric": "bert_base_pretrain_tokens_per_sec_per_chip",
        "value": 0.0,
        "unit": "tokens/sec",
        "vs_baseline": 0.0,
        "error": f"{type(first_err).__name__}: {first_err}"[:300],
    }))


if __name__ == "__main__":
    main()
