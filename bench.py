"""Driver benchmark: BERT-base pretrain throughput on one chip.

Measures tokens/sec through the fully-jitted sharded TrainStep (forward +
backward + optimizer in ONE XLA executable, donated buffers) — BASELINE.md
config 3, the metric of record "tokens/sec/chip BERT-base pretrain".
``steps_per_call=STEPS_PER_CALL`` runs that many full optimizer steps on
distinct microbatches per dispatch via a device-side lax.scan
(parallel/step.py),
so host/tunnel dispatch latency is amortized the way a real input pipeline
would.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.
``value`` is the MEDIAN of the timing windows (the honest central figure on
the shared, noisy tunnel); the best window and the full per-window list are
included as extra keys. vs_baseline is value/ceiling where the ceiling is
the 45%-MFU param-matmul bound from BASELINE.md (~1.9e5 tok/s/chip on v4);
the reference mount shipped no published numbers (BASELINE.json
published={}). See BASELINE.md for the measured-FLOPs MFU accounting on
the actual chip.
"""

from __future__ import annotations

import json
import statistics
import time

import numpy as np

STEPS_PER_CALL = 40
SEQ = 128
WINDOWS = 4
CALLS_PER_WINDOW = 4


def _build(batch, seq):
    import mxnet_tpu as mx
    from mxnet_tpu import gluon, optimizer as opt
    from mxnet_tpu.gluon.model_zoo.bert import BERTModel
    from mxnet_tpu.parallel import TrainStep

    net = BERTModel(
        vocab_size=30522, units=768, hidden_size=3072, num_layers=12,
        num_heads=12, max_length=512, dropout=0.1,
    )
    net.initialize()
    net._probe_shapes(mx.nd.zeros((2, 8), dtype="int32"))
    ce = gluon.loss.SoftmaxCrossEntropyLoss()
    word_w = net.word_embed.weight

    class _PretrainLoss:
        """MLM-style CE against the tied embedding (exercises the full
        encoder + vocab-size matmul like real pretraining).

        Materialized logits beat the blocked linear_cross_entropy op here:
        at B*S=8192, V=30522 the whole head costs 10.2 ms (~113 TFLOP/s,
        near roofline) and XLA fuses the softmax passes, while the blocked
        scan serializes and recomputes (63.1 vs 50.6 ms/step measured) —
        see benchmarks/traces/README.md. Use linear_cross_entropy when the
        logits don't fit (bigger vocab / longer batch), not here."""

        def __call__(self, seq_out, pooled, label):
            w = word_w.data()
            logits = seq_out.reshape(-1, seq_out.shape[-1]).dot(w.T)
            return ce(logits, label.reshape(-1))

    # bf16 compute + f32 masters = the reference's "BERT + AMP" config 3
    step = TrainStep(net, _PretrainLoss(), opt.AdamW(learning_rate=1e-4),
                     compute_dtype="bfloat16", state_dtype="bfloat16",
                     steps_per_call=STEPS_PER_CALL)
    rng = np.random.RandomState(0)
    n = batch * STEPS_PER_CALL  # STEPS_PER_CALL DISTINCT microbatches per dispatch
    ids = mx.nd.array(rng.randint(0, 30522, (n, seq)), dtype="int32")
    labels = mx.nd.array(rng.randint(0, 30522, (n, seq)), dtype="int32")
    return step, ids, labels


def _telemetry_fields(step_times=None, compile_time_s=None):
    """step_time_p50/p95, compile_time_s, hbm_peak_bytes — null-safe on
    CPU and on telemetry import failure (the bench must still print its
    line)."""
    try:
        from benchmarks.common import telemetry_fields

        return telemetry_fields(step_times=step_times,
                                compile_time_s=compile_time_s)
    except Exception:  # noqa: BLE001 - schema stays stable regardless
        return {"step_time_p50": None, "step_time_p95": None,
                "compile_time_s": compile_time_s, "hbm_peak_bytes": None}


def _preflight(timeout_s=None):
    """Fast device/tunnel probe: run a tiny matmul + host readback on a
    watchdog thread budget. An UNREACHABLE rig (the BENCH_r05 failure:
    even an 8x8 matmul hangs in the tunnel's C RPC forever) fails here in
    seconds with a DISTINCT error row instead of burning the full 540 s
    watchdog window. The probe runs on a daemon thread because a hung
    tunnel call cannot be interrupted from within."""
    import os
    import threading

    if timeout_s is None:
        timeout_s = float(os.environ.get("MXTPU_PREFLIGHT_TIMEOUT_S", "45"))
    if timeout_s <= 0:
        return  # explicit opt-out
    result = {}

    def probe():
        try:
            import jax
            import jax.numpy as jnp

            x = jnp.ones((8, 8), jnp.float32)
            result["value"] = float((x @ x).sum())  # forces a round trip
        except Exception as e:  # noqa: BLE001 - reported below
            result["error"] = f"{type(e).__name__}: {e}"[:200]

    th = threading.Thread(target=probe, daemon=True)
    th.start()
    th.join(timeout_s)
    if not th.is_alive() and "error" not in result:
        return  # healthy rig
    reason = (f"preflight: device unreachable (no tiny-op result within "
              f"{timeout_s:.0f}s)" if th.is_alive()
              else f"preflight: tiny op failed: {result['error']}")
    row = {
        "metric": "bert_base_pretrain_tokens_per_sec_per_chip",
        "value": 0.0,
        "unit": "tokens/sec",
        "vs_baseline": 0.0,
        "error": reason,
    }
    row.update(_telemetry_fields())
    print(json.dumps(row), flush=True)
    os._exit(1)  # status must agree with the error row (ADVICE round 5)


def main():
    # import ONCE up front: a structural failure (bad module, registry bug)
    # must surface as itself, not as a re-import artifact from a retry
    try:
        import mxnet_tpu  # noqa: F401
    except Exception as e:  # noqa: BLE001
        row = {
            "metric": "bert_base_pretrain_tokens_per_sec_per_chip",
            "value": 0.0,
            "unit": "tokens/sec",
            "vs_baseline": 0.0,
            "error": f"import failed: {type(e).__name__}: {e}"[:300],
        }
        row.update(_telemetry_fields())
        print(json.dumps(row))
        return
    _preflight()
    first_err = None
    for attempt_batch in (64, 32, 16):
        try:
            step, ids, labels = _build(attempt_batch, SEQ)
            # warmup / compile; sync via host transfer — block_until_ready
            # does not actually block on the tunneled TPU backend
            t0 = time.perf_counter()
            for _ in range(3):
                loss = step(ids, labels)
            float(loss.asscalar())
            compile_s = time.perf_counter() - t0
            tokens_per_window = (
                CALLS_PER_WINDOW * STEPS_PER_CALL * attempt_batch * SEQ
            )
            rates = []
            step_times = []  # per-optimizer-step wall, from SYNCED windows
            for _ in range(WINDOWS):
                t0 = time.perf_counter()
                for _ in range(CALLS_PER_WINDOW):
                    loss = step(ids, labels)
                float(loss.asscalar())
                elapsed = time.perf_counter() - t0
                rates.append(tokens_per_window / elapsed)
                # async dispatch returns immediately, so only the synced
                # window total is an honest wall figure; per-call splits
                # would report dispatch latency as step time
                step_times.append(
                    elapsed / (CALLS_PER_WINDOW * STEPS_PER_CALL))
            value = statistics.median(rates)
            ceiling = 1.9e5  # BASELINE.md derived 45%-MFU bound (v4)
            row = {
                "metric": "bert_base_pretrain_tokens_per_sec_per_chip",
                "value": round(value, 1),
                "unit": "tokens/sec",
                "vs_baseline": round(value / ceiling, 4),
                "best": round(max(rates), 1),
                "windows": [round(r, 1) for r in rates],
            }
            row.update(_telemetry_fields(
                step_times=step_times,
                compile_time_s=round(compile_s, 3)))
            print(json.dumps(row))
            return
        except Exception as e:  # noqa: BLE001 - retry smaller batch (OOM)
            if first_err is None:
                first_err = e
    row = {
        "metric": "bert_base_pretrain_tokens_per_sec_per_chip",
        "value": 0.0,
        "unit": "tokens/sec",
        "vs_baseline": 0.0,
        "error": f"{type(first_err).__name__}: {first_err}"[:300],
    }
    row.update(_telemetry_fields())
    print(json.dumps(row))


def _watchdog(seconds=540):
    """The tunneled chip sometimes becomes UNREACHABLE (observed
    2026-07-31: even an 8x8 matmul hangs indefinitely); a hang would
    leave the driver with NO line at all. A daemon THREAD (signal
    handlers can't preempt a main thread blocked inside the tunnel's C
    RPC) emits the error JSON and hard-exits if the bench exceeds the
    budget — good windows finish in ~2-5 minutes including compile."""
    import os
    import threading

    def boom():
        row = {
            "metric": "bert_base_pretrain_tokens_per_sec_per_chip",
            "value": 0.0,
            "unit": "tokens/sec",
            "vs_baseline": 0.0,
            "error": f"watchdog: no result within {seconds}s "
                     "(tunnel unreachable or pathologically slow)",
        }
        row.update(_telemetry_fields())
        print(json.dumps(row), flush=True)
        # nonzero: the error JSON and the process status must agree — a
        # hung run exiting 0 recorded tunnel outages as clean runs
        # (ADVICE round 5, observed in BENCH_r05)
        os._exit(1)

    t = threading.Timer(seconds, boom)
    t.daemon = True
    t.start()
    return t


if __name__ == "__main__":
    _timer = _watchdog()
    main()
    # a legitimately slow-but-successful run must not be shot mid-teardown
    _timer.cancel()
