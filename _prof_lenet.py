"""Attribute the LeNet eager-step host cost: cProfile + wall split."""
import cProfile, pstats, io, time
import numpy as np
import mxnet_tpu as mx
from mxnet_tpu import autograd, gluon, nd

net = gluon.nn.HybridSequential()
with net.name_scope():
    net.add(
        gluon.nn.Conv2D(20, kernel_size=5, activation="tanh"),
        gluon.nn.MaxPool2D(pool_size=2, strides=2),
        gluon.nn.Conv2D(50, kernel_size=5, activation="tanh"),
        gluon.nn.MaxPool2D(pool_size=2, strides=2),
        gluon.nn.Flatten(),
        gluon.nn.Dense(500, activation="tanh"),
        gluon.nn.Dense(10),
    )
net.initialize(mx.initializer.Xavier())
loss_fn = gluon.loss.SoftmaxCrossEntropyLoss()
trainer = gluon.Trainer(net.collect_params(), "sgd",
                        {"learning_rate": 0.02, "momentum": 0.9})
rng = np.random.RandomState(0)
x = nd.array(rng.rand(128, 1, 28, 28).astype(np.float32))
y = nd.array(rng.randint(0, 10, 128).astype(np.float32))

def step():
    with autograd.record():
        loss = loss_fn(net(x), y)
    loss.backward()
    trainer.step(128)
    return loss

for _ in range(5):
    float(step().mean().asscalar())  # warmup + compile

N = 30
t0 = time.perf_counter()
for _ in range(N):
    step()
# do NOT sync inside the window; sync once at the end
t1 = time.perf_counter()
float(step().mean().asscalar())
print(f"async wall/step: {(t1-t0)/N*1e3:.2f} ms")

t0 = time.perf_counter()
for _ in range(N):
    float(step().mean().asscalar())
t1 = time.perf_counter()
print(f"synced wall/step: {(t1-t0)/N*1e3:.2f} ms  ({128*N/(t1-t0):.0f} img/s)")

pr = cProfile.Profile()
pr.enable()
for _ in range(N):
    step()
pr.disable()
s = io.StringIO()
ps = pstats.Stats(pr, stream=s).sort_stats("cumulative")
ps.print_stats(35)
print(s.getvalue())
