"""INT8 post-training quantization walkthrough (reference:
``example/quantization`` [unverified]).

Trains a small CNN on a learnable synthetic task, quantizes it with
``quantize_net`` (per-channel weight scales, Conv+BN+relu fusion, int8
chaining), prints the per-layer coverage report, and compares float vs
int8 accuracy.

    python examples/int8_inference.py [--calib-mode naive|entropy]
"""

from __future__ import annotations

import argparse
import os
import sys

import numpy as np

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

import mxnet_tpu as mx
from mxnet_tpu import autograd, gluon, nd
from mxnet_tpu.contrib.quantization import quantize_net


def synthetic(n, seed=0):
    rng = np.random.RandomState(seed)
    x = rng.rand(n, 1, 8, 8).astype(np.float32) * 0.3
    y = rng.randint(0, 4, n)
    for i, cls in enumerate(y):
        r, c = divmod(int(cls), 2)
        x[i, 0, r * 4:r * 4 + 4, c * 4:c * 4 + 4] += 1.0
    return x, y


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--calib-mode", default="naive",
                    choices=("naive", "entropy"))
    ap.add_argument("--steps", type=int, default=30)
    args = ap.parse_args()

    mx.random.seed(0)
    net = gluon.nn.HybridSequential()
    with net.name_scope():
        net.add(gluon.nn.Conv2D(8, 3, padding=1, in_channels=1),
                gluon.nn.BatchNorm(in_channels=8),
                gluon.nn.Activation("relu"),
                gluon.nn.Conv2D(16, 3, padding=1, in_channels=8,
                                activation="relu"),
                gluon.nn.MaxPool2D(2, 2),
                gluon.nn.Flatten(),
                gluon.nn.Dense(4))
    net.initialize(mx.initializer.Xavier())
    x, y = synthetic(256)
    xt, yt = nd.array(x), nd.array(y.astype(np.float32))
    loss_fn = gluon.loss.SoftmaxCrossEntropyLoss()
    trainer = gluon.Trainer(net.collect_params(), "sgd",
                            {"learning_rate": 0.1, "momentum": 0.9})
    for _ in range(args.steps):
        with autograd.record():
            loss = loss_fn(net(xt), yt)
        loss.backward()
        trainer.step(256)

    xe, ye = synthetic(512, seed=1)
    float_acc = (net(nd.array(xe)).asnumpy().argmax(1) == ye).mean()

    qnet = quantize_net(net, calib_data=[xt], calib_mode=args.calib_mode,
                        verbose=True)
    int8_acc = (qnet(nd.array(xe)).asnumpy().argmax(1) == ye).mean()
    print(f"float accuracy: {float_acc:.3f}")
    print(f"int8 accuracy:  {int8_acc:.3f} (calib={args.calib_mode})")


if __name__ == "__main__":
    main()
