// Example mxtpu operator extension library (ABI v1).
//
// TPU-native analogue of the reference's custom-op example
// (example/extensions/lib_custom_op [unverified]): exports two float32
// operators through the C ABI documented in mxnet_tpu/library.py:
//   - my_relu6(x): min(max(x, 0), 6); with an exported backward
//   - my_scaled_add(a, b): a + 0.5 * b; forward-only
//
// Build:
//   g++ -O2 -shared -fPIC -o libcustom_ops.so custom_ops.cc
// Use:
//   import mxnet_tpu as mx
//   mx.library.load("./libcustom_ops.so")
//   mx.nd.my_relu6(mx.nd.array([-1., 3., 9.]))

#include <algorithm>
#include <cstdint>

extern "C" {

int mxtpu_abi_version() { return 1; }

int mxtpu_op_count() { return 2; }

const char* mxtpu_op_name(int op) {
  switch (op) {
    case 0: return "my_relu6";
    case 1: return "my_scaled_add";
    default: return "";
  }
}

int mxtpu_op_num_inputs(int op) { return op == 1 ? 2 : 1; }

void mxtpu_op_compute(int op, const float** ins, const long long* lens,
                      int nin, float* out, long long out_len) {
  if (op == 0) {
    const float* x = ins[0];
    for (long long i = 0; i < out_len; ++i)
      out[i] = std::min(std::max(x[i], 0.0f), 6.0f);
  } else if (op == 1) {
    const float* a = ins[0];
    const float* b = ins[1];
    for (long long i = 0; i < out_len; ++i) out[i] = a[i] + 0.5f * b[i];
  }
}

int mxtpu_op_has_backward(int op) { return op == 0 ? 1 : 0; }

void mxtpu_op_backward(int op, const float* out_grad, const float** ins,
                       const long long* lens, int nin, float* grad0,
                       long long len) {
  if (op == 0) {
    const float* x = ins[0];
    for (long long i = 0; i < len; ++i)
      grad0[i] = (x[i] > 0.0f && x[i] < 6.0f) ? out_grad[i] : 0.0f;
  }
}

}  // extern "C"
