// mxtpu extension library, ABI VERSION 2: shape/dtype inference,
// multi-output, non-f32 dtypes, scalar params.
//
// Ops:
//   scaled_rowsum  f32 (N, D) -> f32 (N,)  out[n] = alpha * sum_d in[n,d]
//                  (param alpha, default 1; has backward)
//   minmax_i32     i32 (N,) -> (i32 (1,), i32 (1,))  min and max
//                  (multi-output, integer dtype, no backward)
//
// Build:
//   g++ -O2 -shared -fPIC -o libcustom_v2.so custom_ops_v2.cc

#include <cstring>
#include <cstdint>
#include <cstdlib>
#include <string>

extern "C" {

int mxtpu_abi_version() { return 2; }
int mxtpu_op_count() { return 2; }

const char* mxtpu_op_name(int op) {
  return op == 0 ? "scaled_rowsum" : "minmax_i32";
}

int mxtpu_op_num_inputs(int op) { (void)op; return 1; }
int mxtpu_op_num_outputs(int op) { return op == 0 ? 1 : 2; }
int mxtpu_op_has_backward(int op) { return op == 0 ? 1 : 0; }

static double param_alpha(const char* params) {
  if (!params) return 1.0;
  std::string s(params);
  auto pos = s.find("alpha=");
  if (pos == std::string::npos) return 1.0;
  return std::atof(s.c_str() + pos + 6);
}

int mxtpu_op_infer(int op, const long long* in_shapes, const int* in_ndims,
                   const int* in_dtypes, int nin, long long* out_shapes,
                   int* out_ndims, int* out_dtypes, int max_ndim,
                   const char* params) {
  (void)nin; (void)params;
  if (op == 0) {  // (N, D) f32 -> (N,) f32
    if (in_ndims[0] != 2 || in_dtypes[0] != 0) return 1;
    out_ndims[0] = 1;
    out_shapes[0 * max_ndim + 0] = in_shapes[0];
    out_dtypes[0] = 0;
    return 0;
  }
  // minmax: (N,) i32 -> ((1,), (1,)) i32
  if (in_ndims[0] != 1 || in_dtypes[0] != 2) return 1;
  out_ndims[0] = 1; out_shapes[0 * max_ndim + 0] = 1; out_dtypes[0] = 2;
  out_ndims[1] = 1; out_shapes[1 * max_ndim + 0] = 1; out_dtypes[1] = 2;
  return 0;
}

void mxtpu_op_compute2(int op, const void** ins, const long long* in_shapes,
                       const int* in_ndims, const int* in_dtypes, int nin,
                       void** outs, const long long* out_shapes,
                       const int* out_ndims, const int* out_dtypes, int nout,
                       const char* params) {
  (void)in_ndims; (void)in_dtypes; (void)nin;
  (void)out_shapes; (void)out_ndims; (void)out_dtypes; (void)nout;
  if (op == 0) {
    const float* x = static_cast<const float*>(ins[0]);
    float* y = static_cast<float*>(outs[0]);
    long long n = in_shapes[0], d = in_shapes[1];
    float alpha = static_cast<float>(param_alpha(params));
    for (long long i = 0; i < n; ++i) {
      float acc = 0.f;
      for (long long j = 0; j < d; ++j) acc += x[i * d + j];
      y[i] = alpha * acc;
    }
    return;
  }
  const int32_t* x = static_cast<const int32_t*>(ins[0]);
  long long n = in_shapes[0];
  int32_t mn = x[0], mx = x[0];
  for (long long i = 1; i < n; ++i) {
    if (x[i] < mn) mn = x[i];
    if (x[i] > mx) mx = x[i];
  }
  static_cast<int32_t*>(outs[0])[0] = mn;
  static_cast<int32_t*>(outs[1])[0] = mx;
}

void mxtpu_op_backward2(int op, const void** out_grads, const void** ins,
                        const long long* in_shapes, const int* in_ndims,
                        const int* in_dtypes, int nin, void** in_grads,
                        const char* params) {
  (void)in_ndims; (void)in_dtypes; (void)nin;
  if (op != 0) return;
  // d(alpha * rowsum)/dx[i,j] = alpha * og[i]
  const float* og = static_cast<const float*>(out_grads[0]);
  (void)ins;
  float* gx = static_cast<float*>(in_grads[0]);
  long long n = in_shapes[0], d = in_shapes[1];
  float alpha = static_cast<float>(param_alpha(params));
  for (long long i = 0; i < n; ++i)
    for (long long j = 0; j < d; ++j)
      gx[i * d + j] = alpha * og[i];
}

}  // extern "C"
