"""ONNX export/import walkthrough (reference: ``example`` ONNX tutorials
[unverified]).

Builds a small symbolic CNN, exports it to a standard ONNX ModelProto
file (no onnx package needed — the vendored wire-compatible schema
serializes it), imports it back, and checks numeric parity.

    python examples/onnx_interchange.py [--out model.onnx]
"""

from __future__ import annotations

import argparse
import os
import sys

import numpy as np

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

import mxnet_tpu as mx
from mxnet_tpu import nd, sym
from mxnet_tpu import onnx as mxonnx


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--out", default="/tmp/mxtpu_model.onnx")
    args = ap.parse_args()

    rng = np.random.RandomState(0)
    data = sym.var("data")
    w1, b1 = sym.var("conv_w"), sym.var("conv_b")
    fcw, fcb = sym.var("fc_w"), sym.var("fc_b")
    net = sym.Convolution(data, w1, b1, kernel=(3, 3), num_filter=8,
                          pad=(1, 1))
    net = sym.Activation(net, act_type="relu")
    net = sym.Pooling(net, kernel=(2, 2), stride=(2, 2), pool_type="max")
    net = sym.FullyConnected(net, fcw, fcb, num_hidden=10)
    net = sym.softmax(net)

    params = {
        "conv_w": rng.rand(8, 1, 3, 3).astype(np.float32) * 0.1,
        "conv_b": rng.rand(8).astype(np.float32) * 0.1,
        "fc_w": rng.rand(10, 8 * 4 * 4).astype(np.float32) * 0.1,
        "fc_b": rng.rand(10).astype(np.float32) * 0.1,
    }
    path = mxonnx.export_model(net, params, input_shapes=[(2, 1, 8, 8)],
                               onnx_file_path=args.out, verbose=True)
    print(f"exported: {path}")

    sym2, arg_params, aux_params = mxonnx.import_model(path)
    x = rng.rand(2, 1, 8, 8).astype(np.float32)
    ref = net.eval(data=nd.array(x),
                   **{k: nd.array(v) for k, v in params.items()})[0]
    got = sym2.eval(data=nd.array(x), **arg_params, **aux_params)[0]
    err = float(np.abs(ref.asnumpy() - got.asnumpy()).max())
    print(f"round-trip max abs error: {err:.2e}")
    assert err < 1e-5
    print("onnx interchange OK")


if __name__ == "__main__":
    main()
