"""Train LeNet through the legacy Symbol/Module API (reference:
``example/image-classification/train_mnist.py`` [unverified]).

Demonstrates: mx.sym graph construction, Module.fit with Speedometer and
checkpoint callbacks, score().

    python examples/module_lenet.py --epochs 2
"""

from __future__ import annotations

import argparse
import os
import sys

import numpy as np

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

import mxnet_tpu as mx  # noqa: E402


def lenet_symbol():
    data = mx.sym.var("data")
    c1 = mx.sym.Convolution(data, kernel=(5, 5), num_filter=8, name="c1")
    a1 = mx.sym.Activation(c1, act_type="relu")
    p1 = mx.sym.Pooling(a1, kernel=(2, 2), stride=(2, 2), pool_type="max")
    c2 = mx.sym.Convolution(p1, kernel=(5, 5), num_filter=16, name="c2")
    a2 = mx.sym.Activation(c2, act_type="relu")
    p2 = mx.sym.Pooling(a2, kernel=(2, 2), stride=(2, 2), pool_type="max")
    f = mx.sym.Flatten(p2)
    fc1 = mx.sym.FullyConnected(f, num_hidden=64, name="fc1")
    a3 = mx.sym.Activation(fc1, act_type="relu")
    fc2 = mx.sym.FullyConnected(a3, num_hidden=10, name="fc2")
    return mx.sym.SoftmaxOutput(fc2, name="softmax")


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--epochs", type=int, default=2)
    ap.add_argument("--batch-size", type=int, default=64)
    ap.add_argument("--num-examples", type=int, default=640)
    ap.add_argument("--prefix", default=None, help="checkpoint prefix")
    args = ap.parse_args()

    np.random.seed(0)  # NDArrayIter shuffle order (deterministic runs)
    mx.random.seed(0)
    rng = np.random.RandomState(0)
    # learnable synthetic data: class-dependent 4x4 patch (a training
    # loop must drive val accuracy well above the 0.1 chance floor)
    X = rng.rand(args.num_examples, 1, 28, 28).astype(np.float32) * 0.3
    y = rng.randint(0, 10, args.num_examples).astype(np.float32)
    for i, cls in enumerate(y):
        r, c = divmod(int(cls), 5)
        X[i, 0, 4 + r * 12:8 + r * 12, 2 + c * 5:6 + c * 5] += 1.0
    train = mx.io.NDArrayIter(X, y, args.batch_size, shuffle=True,
                              label_name="softmax_label")
    val = mx.io.NDArrayIter(X[:128], y[:128], args.batch_size,
                            label_name="softmax_label")

    mod = mx.module.Module(lenet_symbol(), data_names=("data",),
                           label_names=("softmax_label",))
    callbacks = [mx.callback.Speedometer(args.batch_size, frequent=5)]
    epoch_cbs = []
    if args.prefix:
        epoch_cbs.append(mx.callback.do_checkpoint(args.prefix))
    mod.fit(
        train, eval_data=val, num_epoch=args.epochs,
        optimizer="adam", optimizer_params={"learning_rate": 1e-3},
        batch_end_callback=callbacks,
        epoch_end_callback=epoch_cbs or None,
    )
    print("validation:", mod.score(val, "acc"))


if __name__ == "__main__":
    main()
