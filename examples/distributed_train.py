"""Data/tensor-parallel training with the fused sharded TrainStep
(reference workload: ``example/distributed_training*`` + KVStore sync
[unverified]; TPU-native: GSPMD mesh instead of ps-lite).

Single-process multi-device (the default here, virtual CPU mesh for
demonstration):

    XLA_FLAGS=--xla_force_host_platform_device_count=8 \\
    JAX_PLATFORMS=cpu python examples/distributed_train.py

Multi-host: launch one process per host via tools/launch.py; the
MXNET_TPU_* env vars drive ``parallel.init_process_group`` rendezvous:

    python tools/launch.py -n 2 -- python examples/distributed_train.py
"""

from __future__ import annotations

import argparse
import os
import sys

import numpy as np

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--steps", type=int, default=20)
    ap.add_argument("--batch-size", type=int, default=32)
    ap.add_argument("--tp", type=int, default=1,
                    help="tensor-parallel degree (mesh 'model' axis)")
    ap.add_argument("--force-cpu", action="store_true",
                    help="use the virtual CPU mesh (set XLA_FLAGS="
                         "--xla_force_host_platform_device_count=N too)")
    args = ap.parse_args()

    if args.force_cpu:
        import jax

        jax.config.update("jax_platforms", "cpu")

    # join the coordinator when launched via tools/launch.py
    coord = os.environ.get("MXNET_TPU_COORDINATOR")
    if coord:
        import jax

        jax.config.update("jax_platforms", "cpu")
        from mxnet_tpu.parallel import init_process_group

        init_process_group(coord, int(os.environ["MXNET_TPU_NUM_PROCS"]),
                           int(os.environ["MXNET_TPU_PROC_ID"]))

    import jax

    import mxnet_tpu as mx  # noqa: E402
    from mxnet_tpu import gluon, optimizer as opt, parallel
    from mxnet_tpu.gluon import nn
    from mxnet_tpu.parallel import PartitionSpec as P

    n_dev = len(jax.devices())
    dp = n_dev // args.tp
    mesh = parallel.make_mesh({"data": dp, "model": args.tp}) \
        if args.tp > 1 else parallel.make_mesh({"data": n_dev})
    print(f"devices={n_dev} mesh={dict(zip(mesh.axis_names, mesh.devices.shape))}")

    net = nn.HybridSequential()
    net.add(nn.Dense(256, activation="relu", prefix="up_"),
            nn.Dense(10, prefix="head_"))
    net.initialize()
    net(mx.nd.ones((2, 64)))

    rules = [("up_weight$", P("model", None))] if args.tp > 1 else []
    step = parallel.TrainStep(
        net, gluon.loss.SoftmaxCrossEntropyLoss(),
        opt.Adam(learning_rate=1e-3), mesh=mesh, data_spec=P("data"),
        param_rules=rules, compute_dtype="bfloat16",
    )

    rng = np.random.RandomState(jax.process_index())
    for i in range(args.steps):
        # learnable synthetic task: feature block y*6..y*6+6 lights up
        xb = rng.rand(args.batch_size, 64).astype(np.float32) * 0.3
        yb = rng.randint(0, 10, args.batch_size)
        for j, cls in enumerate(yb):
            xb[j, cls * 6:cls * 6 + 6] += 1.0
        x = mx.nd.array(xb)
        y = mx.nd.array(yb)
        loss = step(x, y)
        if i % 5 == 0 or i == args.steps - 1:
            print(f"step {i}: loss={float(loss.asscalar()):.4f}")
    print("done")


if __name__ == "__main__":
    main()
