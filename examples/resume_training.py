"""Resumable training with sharded checkpoints (round-5).

The flagship pattern: a TrainStep training run checkpoints every step
through ``CheckpointManager`` (commit-marker protocol, keep-K rolling
cleanup) and — killed at any point — resumes bit-compatibly: parameters,
optimizer moments, the device PRNG key and the step counter all restore.
Multi-host runs write per-process shards (no gather); see
``tools/launch.py --max-restarts`` for automatic relaunch.

    python examples/resume_training.py --steps 8 --ckpt-dir /tmp/ck
    # simulate a crash, then run the SAME command again to resume:
    python examples/resume_training.py --steps 8 --ckpt-dir /tmp/ck \
        --interrupt-at 4
"""

from __future__ import annotations

import argparse
import os
import sys

import numpy as np

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--steps", type=int, default=8)
    ap.add_argument("--ckpt-dir", required=True)
    ap.add_argument("--interrupt-at", type=int, default=None,
                    help="exit (simulating a crash) after this step")
    ap.add_argument("--keep", type=int, default=3)
    args = ap.parse_args()

    import mxnet_tpu as mx
    from mxnet_tpu import checkpoint as ck, gluon, nd, optimizer as opt
    from mxnet_tpu.gluon import nn
    from mxnet_tpu.parallel import TrainStep

    mx.random.seed(0)
    rng = np.random.RandomState(0)
    X = rng.rand(64, 8).astype(np.float32)
    Y = (X @ rng.rand(8, 1).astype(np.float32))

    net = nn.HybridSequential()
    with net.name_scope():
        net.add(nn.Dense(32, activation="relu"), nn.Dense(1))
    net.initialize()
    net(nd.array(X))
    step = TrainStep(net, gluon.loss.L2Loss(),
                     opt.Adam(learning_rate=0.05))

    mgr = ck.CheckpointManager(args.ckpt_dir, keep=args.keep)
    meta = mgr.restore_latest(train_step=step)
    start = step._t
    if meta is not None:
        print(f"resumed from committed step {meta['step']} "
              f"(train step counter {start})")
    else:
        print("no checkpoint found; starting fresh")

    for t in range(start + 1, args.steps + 1):
        loss = step(nd.array(X), nd.array(Y))
        lv = float(loss.asscalar())
        mgr.save(t, train_step=step)
        print(f"step {t}: loss {lv:.6f}")
        if args.interrupt_at is not None and t == args.interrupt_at:
            print("simulating crash (checkpoint committed; rerun the "
                  "same command to resume)")
            raise SystemExit(17)

    print(f"done at step {step._t}: final loss {lv:.6f}")


if __name__ == "__main__":
    main()
