"""Long-context attention walkthrough: the beyond-reference capability
(SURVEY §5 — the reference's attention materialized the O(L^2) score
matrix; this build's flash kernel is O(S), and ring/Ulysses shard the
sequence over a device mesh).

Runs the same MultiHeadAttention layer three ways and checks parity:
1. dense exact attention (short-seq path),
2. Pallas flash kernel (O(S) memory, long-context path),
3. ring attention over a sequence-sharded device mesh.

    python examples/long_context_attention.py [--seq 1024] [--devices 4]
"""

from __future__ import annotations

import argparse
import os
import sys

import numpy as np

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--seq", type=int, default=1024)
    ap.add_argument("--devices", type=int, default=4)
    ap.add_argument("--tpu", action="store_true",
                    help="run on real devices instead of the virtual CPU mesh")
    args = ap.parse_args()

    # a virtual CPU mesh is enough to demonstrate the sharded path
    os.environ["XLA_FLAGS"] = (os.environ.get("XLA_FLAGS", "")
                               + f" --xla_force_host_platform_device_count="
                                 f"{args.devices}").strip()
    import jax

    if not args.tpu:
        jax.config.update("jax_platforms", "cpu")

    import mxnet_tpu as mx
    from mxnet_tpu import nd
    from mxnet_tpu.gluon.nn.attention import MultiHeadAttention
    from mxnet_tpu.parallel import make_mesh, mesh_scope

    B, S, H, U = 2, args.seq, 4, 128
    rng = np.random.RandomState(0)
    x = nd.array(rng.randn(B, S, U).astype(np.float32) * 0.1)

    mx.random.seed(0)
    attn = MultiHeadAttention(U, H, self_attention=True)
    attn.initialize()

    # 1. dense exact path (force it regardless of S)
    os.environ["MXTPU_ATTN_DENSE_MAX"] = str(10 ** 9)
    dense = attn(x).asnumpy()
    # 2. O(S)-memory flash kernel
    os.environ["MXTPU_ATTN_DENSE_MAX"] = "0"
    flash = attn(x).asnumpy()
    del os.environ["MXTPU_ATTN_DENSE_MAX"]
    err_flash = np.abs(dense - flash).max()
    print(f"flash vs dense max abs err: {err_flash:.2e}")

    # 3. ring attention: sequence axis sharded over the mesh
    mesh = make_mesh({"seq": args.devices})
    ring_attn = MultiHeadAttention(U, H, self_attention=True,
                                   ring_axis="seq")
    ring_attn.initialize()
    # share weights with the single-device layer for parity
    for (_, p_src), (_, p_dst) in zip(
            sorted(attn.collect_params().items()),
            sorted(ring_attn.collect_params().items())):
        p_dst.set_data(p_src.data())
    with mesh_scope(mesh):
        ring = ring_attn(x).asnumpy()
    err_ring = np.abs(dense - ring).max()
    print(f"ring({args.devices} devices) vs dense max abs err: "
          f"{err_ring:.2e}")
    assert err_flash < 5e-5 and err_ring < 5e-5
    print(f"long-context attention parity OK at S={S}")


if __name__ == "__main__":
    main()
