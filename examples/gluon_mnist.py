"""Train a small CNN classifier with the Gluon API (reference:
``example/gluon/mnist.py`` [unverified]).

Runs on synthetic MNIST-shaped data (no network access in this
environment). Demonstrates: HybridBlock, hybridize, Trainer, autograd,
metric tracking, and parameter checkpointing.

    python examples/gluon_mnist.py --epochs 2
"""

from __future__ import annotations

import argparse
import os
import sys

import numpy as np

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

import mxnet_tpu as mx  # noqa: E402
from mxnet_tpu import autograd, gluon, nd  # noqa: E402
from mxnet_tpu.gluon import nn  # noqa: E402


def build_net():
    net = nn.HybridSequential()
    net.add(
        nn.Conv2D(16, kernel_size=5, activation="relu"),
        nn.MaxPool2D(pool_size=2, strides=2),
        nn.Conv2D(32, kernel_size=5, activation="relu"),
        nn.MaxPool2D(pool_size=2, strides=2),
        nn.Flatten(),
        nn.Dense(128, activation="relu"),
        nn.Dense(10),
    )
    return net


def synthetic_batches(batch_size, num_batches, seed=0):
    """MNIST-shaped LEARNABLE synthetic data: each class lights a fixed
    4x4 patch, so a working training loop visibly converges (and a
    broken one visibly does not)."""
    rng = np.random.RandomState(seed)
    for _ in range(num_batches):
        x = rng.rand(batch_size, 1, 28, 28).astype(np.float32) * 0.3
        y = rng.randint(0, 10, batch_size)
        for i, cls in enumerate(y):
            r, c = divmod(int(cls), 5)
            x[i, 0, 4 + r * 12:8 + r * 12, 2 + c * 5:6 + c * 5] += 1.0
        yield nd.array(x), nd.array(y)


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--epochs", type=int, default=2)
    ap.add_argument("--batch-size", type=int, default=64)
    ap.add_argument("--batches-per-epoch", type=int, default=20)
    ap.add_argument("--lr", type=float, default=1e-3)
    ap.add_argument("--save", default=None, help="param checkpoint path")
    args = ap.parse_args()

    net = build_net()
    net.initialize(mx.init.Xavier())
    net.hybridize()
    trainer = gluon.Trainer(net.collect_params(), "adam",
                            {"learning_rate": args.lr})
    loss_fn = gluon.loss.SoftmaxCrossEntropyLoss()
    metric = mx.metric.Accuracy()

    for epoch in range(args.epochs):
        metric.reset()
        total_loss = 0.0
        for x, y in synthetic_batches(args.batch_size,
                                      args.batches_per_epoch, seed=epoch):
            with autograd.record():
                out = net(x)
                loss = loss_fn(out, y)
            loss.backward()
            trainer.step(args.batch_size)
            total_loss += float(loss.mean().asscalar())
            metric.update(y, out)
        name, acc = metric.get()
        print(f"epoch {epoch}: loss={total_loss / args.batches_per_epoch:.4f} "
              f"{name}={acc:.3f}")

    if args.save:
        net.save_parameters(args.save)
        print(f"saved parameters to {args.save}")


if __name__ == "__main__":
    main()
