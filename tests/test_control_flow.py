"""Control-flow ops: foreach / while_loop / cond across execution modes.

Covers the three dispatch modes of ``mxnet_tpu/ops/control_flow.py``:
eager inference (fused lax), eager recording (python loop, reference
imperative semantics incl. closure gradients), and staged inside
``hybridize()`` (lax primitive under the CachedOp jit).
Reference behaviors: ``python/mxnet/ndarray/contrib.py`` [unverified].
"""

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import autograd, nd
from mxnet_tpu import gluon


def _rng(*shape):
    return np.random.RandomState(sum(shape) + 7).uniform(-1, 1, shape).astype(np.float32)


# ------------------------------------------------------------------- foreach
class TestForeach:
    def test_cumsum_eager(self):
        data = nd.array(_rng(5, 3))
        init = nd.zeros((3,))
        outs, final = nd.contrib.foreach(
            lambda x, s: (x + s, x + s), data, init
        )
        expect = np.cumsum(data.asnumpy(), axis=0)
        np.testing.assert_allclose(outs.asnumpy(), expect, rtol=1e-6)
        np.testing.assert_allclose(final.asnumpy(), expect[-1], rtol=1e-6)

    def test_multiple_states_and_outputs(self):
        data = nd.array(_rng(4, 2))
        s0, s1 = nd.ones((2,)), nd.zeros((2,))

        def body(x, states):
            a, b = states
            return [x * a, x + b], [a + 1, b + x]

        outs, (fa, fb) = nd.contrib.foreach(body, data, [s0, s1])
        assert outs[0].shape == (4, 2) and outs[1].shape == (4, 2)
        np.testing.assert_allclose(fa.asnumpy(), np.full((2,), 5.0), rtol=1e-6)
        np.testing.assert_allclose(
            fb.asnumpy(), data.asnumpy().sum(axis=0), rtol=1e-5
        )

    def test_grad_through_data_and_state(self):
        data = nd.array(_rng(6, 3))
        init = nd.array(_rng(3))
        data.attach_grad()
        init.attach_grad()
        with autograd.record():
            outs, final = nd.contrib.foreach(
                lambda x, s: (x * s, s + x), data, init
            )
            loss = (outs.sum() + final.sum())
        loss.backward()
        # numeric check on init grad
        eps = 1e-3
        base = init.asnumpy().copy()

        def f(v):
            s = v.copy()
            tot = 0.0
            for i in range(6):
                x = data.asnumpy()[i]
                tot += (x * s).sum()
                s = s + x
            return tot + s.sum()

        num = np.zeros(3, np.float32)
        for j in range(3):
            vp, vm = base.copy(), base.copy()
            vp[j] += eps
            vm[j] -= eps
            num[j] = (f(vp) - f(vm)) / (2 * eps)
        np.testing.assert_allclose(init.grad.asnumpy(), num, rtol=1e-2, atol=1e-2)

    def test_grad_closure_weights(self):
        """Recording path must see gradients for closed-over tracked arrays."""
        w = nd.array(_rng(3, 3))
        w.attach_grad()
        data = nd.array(_rng(4, 3))
        init = nd.zeros((3,))
        with autograd.record():
            outs, final = nd.contrib.foreach(
                lambda x, s: (nd.dot(x, w) + s, s), data, init
            )
            outs.sum().backward()
        expect = np.outer(data.asnumpy().sum(axis=0), np.ones(3))
        np.testing.assert_allclose(w.grad.asnumpy(), expect, rtol=1e-5)

    def test_inside_hybridize(self):
        class Scanner(gluon.HybridBlock):
            def hybrid_forward(self, F, x):
                outs, final = nd.contrib.foreach(
                    lambda xi, s: (xi * 2, s + xi), x, nd.zeros((3,))
                )
                return outs, final

        blk = Scanner()
        blk.hybridize()
        x = nd.array(_rng(5, 3))
        outs, final = blk(x)
        np.testing.assert_allclose(outs.asnumpy(), x.asnumpy() * 2, rtol=1e-6)
        np.testing.assert_allclose(
            final.asnumpy(), x.asnumpy().sum(axis=0), rtol=1e-5
        )

    def test_state_shape_mismatch_raises(self):
        data = nd.array(_rng(3, 2))
        init = nd.zeros((2,))
        with pytest.raises(mx.base.MXNetError):
            nd.contrib.foreach(
                lambda x, s: (x, nd.zeros((4,))), data, init
            )


# ---------------------------------------------------------------- while_loop
class TestWhileLoop:
    def test_eager_fused_trims(self):
        i = nd.array(np.array([0.0], np.float32))
        acc = nd.array(np.array([0.0], np.float32))
        outs, (fi, facc) = nd.contrib.while_loop(
            lambda i, a: (i < 4).sum(),
            lambda i, a: ([i * 10], [i + 1, a + i]),
            [i, acc],
            max_iterations=10,
        )
        assert outs[0].shape[0] == 4  # trimmed to realized steps
        np.testing.assert_allclose(
            outs[0].asnumpy()[:, 0], [0, 10, 20, 30], rtol=1e-6
        )
        np.testing.assert_allclose(facc.asnumpy(), [6.0], rtol=1e-6)

    def test_recording_python_loop(self):
        x = nd.array(np.array([2.0], np.float32))
        x.attach_grad()
        with autograd.record():
            outs, (final,) = nd.contrib.while_loop(
                lambda v: (v.sum() < 100).sum(),
                lambda v: ([v], [v * 2]),
                [x],
            )
            final.backward()
        # 2 -> 4 -> 8 ... doubles until >=100: 2*2^6=128, 6 steps, d final/dx = 64
        np.testing.assert_allclose(x.grad.asnumpy(), [64.0], rtol=1e-6)
        assert outs[0].shape[0] == 6

    def test_inside_hybridize_padded(self):
        class Loop(gluon.HybridBlock):
            def hybrid_forward(self, F, x):
                outs, (v,) = nd.contrib.while_loop(
                    lambda v: (v.sum() < 10).sum(),
                    lambda v: ([v], [v + 1]),
                    [x],
                    max_iterations=8,
                )
                return outs[0], v

        blk = Loop()
        blk.hybridize()
        out, v = blk(nd.array(np.array([7.0], np.float32)))
        assert out.shape == (8, 1)  # padded under jit
        np.testing.assert_allclose(v.asnumpy(), [10.0], rtol=1e-6)
        np.testing.assert_allclose(out.asnumpy()[:3, 0], [7, 8, 9], rtol=1e-6)
        np.testing.assert_allclose(out.asnumpy()[3:, 0], np.zeros(5), atol=0)

    def test_requires_max_iterations_outside_record(self):
        x = nd.ones((1,))
        with pytest.raises(mx.base.MXNetError):
            nd.contrib.while_loop(
                lambda v: (v.sum() < 3).sum(), lambda v: ([v], [v + 1]), [x]
            )


# ---------------------------------------------------------------------- cond
class TestCond:
    def test_eager_branches(self):
        x = nd.array(np.array([3.0], np.float32))
        out = nd.contrib.cond(
            (x.sum() > 1).sum(), lambda: x * 2, lambda: x - 1
        )
        np.testing.assert_allclose(out.asnumpy(), [6.0], rtol=1e-6)
        out = nd.contrib.cond(
            (x.sum() > 5).sum(), lambda: x * 2, lambda: x - 1
        )
        np.testing.assert_allclose(out.asnumpy(), [2.0], rtol=1e-6)

    def test_eager_grad_through_taken_branch(self):
        x = nd.array(np.array([3.0], np.float32))
        x.attach_grad()
        with autograd.record():
            out = nd.contrib.cond(
                (x.sum() > 1).sum(), lambda: x * 5, lambda: x - 1
            )
            out.backward()
        np.testing.assert_allclose(x.grad.asnumpy(), [5.0], rtol=1e-6)

    def test_inside_hybridize(self):
        class Branch(gluon.HybridBlock):
            def hybrid_forward(self, F, x):
                return nd.contrib.cond(
                    (x.sum() > 0).sum(), lambda: x * 2, lambda: -x
                )

        blk = Branch()
        blk.hybridize()
        np.testing.assert_allclose(
            blk(nd.array(np.array([2.0], np.float32))).asnumpy(), [4.0]
        )
        np.testing.assert_allclose(
            blk(nd.array(np.array([-2.0], np.float32))).asnumpy(), [2.0]
        )


# -------------------------------------------------------- review regressions
class TestEdgeCases:
    def test_foreach_zero_length_data(self):
        data = nd.zeros((0, 3))
        init = nd.ones((3,))
        init.attach_grad()
        with autograd.record():
            outs, final = nd.contrib.foreach(
                lambda x, s: (x * s, s + x), data, init
            )
        assert outs.shape == (0, 3)
        np.testing.assert_allclose(final.asnumpy(), np.ones(3))

    def test_while_loop_false_on_entry_eager_fused(self):
        x = nd.array(np.array([100.0], np.float32))
        with pytest.raises(mx.base.MXNetError):
            nd.contrib.while_loop(
                lambda v: (v.sum() < 4).sum(),
                lambda v: ([v], [v + 1]),
                [x],
                max_iterations=4,
            )

    def test_cond_structure_mismatch_raises(self):
        class Bad(gluon.HybridBlock):
            def hybrid_forward(self, F, x):
                return nd.contrib.cond(
                    (x.sum() > 0).sum(),
                    lambda: {"a": x, "b": x * 2},
                    lambda: [x, x * 3],
                )

        blk = Bad()
        blk.hybridize()
        with pytest.raises(mx.base.MXNetError):
            blk(nd.ones((2,)))
