"""Bucketing input path: FixedBucketSampler + PadToBucket (shape-stable
variable-length batches) and the masked-loss padding invariant."""

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu.base import MXNetError
from mxnet_tpu.gluon.data import (DataLoader, FixedBucketSampler,
                                  PadToBucket)


def _lengths(n=120, lo=4, hi=40, seed=0):
    return np.random.RandomState(seed).randint(lo, hi + 1, size=n).tolist()


class TestFixedBucketSampler:
    def test_deterministic_without_shuffle(self):
        lengths = _lengths()
        s = FixedBucketSampler(lengths, batch_size=8, num_buckets=4)
        assert list(s) == list(s)

    def test_deterministic_under_seed_with_shuffle(self):
        lengths = _lengths()
        np.random.seed(7)
        a = list(FixedBucketSampler(lengths, 8, 4, shuffle=True))
        np.random.seed(7)
        b = list(FixedBucketSampler(lengths, 8, 4, shuffle=True))
        assert a == b

    def test_keep_covers_every_index_once(self):
        lengths = _lengths()
        s = FixedBucketSampler(lengths, 8, 4, last_batch="keep")
        got = sorted(i for batch in s for i in batch)
        assert got == sorted(range(len(lengths)))
        assert len(list(s)) == len(s)

    def test_discard_drops_ragged_batches(self):
        lengths = _lengths()
        s = FixedBucketSampler(lengths, 8, 4, last_batch="discard")
        batches = list(s)
        assert all(len(b) == 8 for b in batches)
        assert len(batches) == len(s)

    def test_pad_is_shape_stable_and_covers_all(self):
        lengths = _lengths()
        s = FixedBucketSampler(lengths, 8, 4, last_batch="pad")
        batches = list(s)
        assert all(len(b) == 8 for b in batches)
        # every index still appears at least once
        assert set(i for b in batches for i in b) == set(range(len(lengths)))

    def test_bucket_membership(self):
        lengths = _lengths()
        s = FixedBucketSampler(lengths, 8, 4)
        for batch in s:
            ml = max(lengths[i] for i in batch)
            key = next(k for k in s.bucket_keys if ml <= k)
            # every sample in the batch belongs to the same bucket: its
            # length is above the previous boundary
            ki = s.bucket_keys.index(key)
            lo = s.bucket_keys[ki - 1] if ki else 0
            assert all(lo < lengths[i] <= key for i in batch)

    def test_ratio_scales_short_buckets_up(self):
        lengths = _lengths()
        s = FixedBucketSampler(lengths, 8, 4, ratio=0.5)
        assert s.batch_sizes[0] > s.batch_sizes[-1]
        assert s.batch_sizes[-1] == 8
        s0 = FixedBucketSampler(lengths, 8, 4, ratio=0.0)
        assert set(s0.batch_sizes) == {8}

    def test_signatures_match_emitted_shapes(self):
        lengths = _lengths()
        for last in ("keep", "discard", "pad"):
            s = FixedBucketSampler(lengths, 8, 4, ratio=0.5,
                                   last_batch=last)
            p = PadToBucket(s.bucket_keys)
            emitted = set()
            for batch in s:
                data, vl = p([np.zeros(lengths[i], "int32")
                              for i in batch])
                emitted.add(tuple(data.shape))
            assert emitted == {(bs, k) for bs, k in s.signatures()}, last

    def test_too_long_sample_raises(self):
        with pytest.raises(MXNetError):
            FixedBucketSampler([4, 8, 100], 2, bucket_keys=[8, 16])

    def test_stats_renders(self):
        s = FixedBucketSampler(_lengths(), 8, 4)
        assert "FixedBucketSampler" in s.stats()


class TestPadToBucket:
    def test_pads_to_bucket_boundary_with_valid_length(self):
        p = PadToBucket([8, 16], pad_val=0)
        data, vl = p([np.arange(1, 6, dtype="int32"),
                      np.arange(1, 10, dtype="int32")])
        assert data.shape == (2, 16)
        assert vl.asnumpy().tolist() == [5, 9]
        got = data.asnumpy()
        assert got[0, 5:].tolist() == [0] * 11
        assert got[1, 9:].tolist() == [0] * 7

    def test_tuple_samples_per_field_pad_values(self):
        p = PadToBucket([8], pad_val=0, label_pad_val=[0, -1])
        seqs = [np.ones(3, "int32"), np.ones(5, "int32")]
        samples = [(s, s * 2, s * 3) for s in seqs]
        data, vl, tgt, lab = p(samples)
        assert data.shape == tgt.shape == lab.shape == (2, 8)
        assert tgt.asnumpy()[0, 3:].tolist() == [0] * 5
        assert lab.asnumpy()[0, 3:].tolist() == [-1] * 5

    def test_scalar_fields_stack_unpadded(self):
        p = PadToBucket([8])
        data, vl, label = p([(np.ones(3, "int32"), 7),
                             (np.ones(6, "int32"), 9)])
        assert label.shape == (2,)
        assert label.asnumpy().tolist() == [7, 9]

    def test_valid_length_false_matches_step_contract(self):
        p = PadToBucket([8], valid_length=False, label_pad_val=[-1])
        out = p([(np.ones(3, "int32"), np.ones(3, "int32"))])
        assert len(out) == 2  # (data, label) only

    def test_numpy_mode_returns_numpy(self):
        p = PadToBucket([8], numpy=True)
        data, vl = p([np.ones(3, "int32")])
        assert isinstance(data, np.ndarray) and isinstance(vl, np.ndarray)

    def test_overlong_batch_raises(self):
        p = PadToBucket([8])
        with pytest.raises(MXNetError):
            p([np.ones(9, "int32")])


def _masked_ce(logits, label):
    """Masked CE reduced per row then across rows — the benches' loss
    formulation; pad columns contribute exact zeros to each row."""
    import jax
    import jax.numpy as jnp

    x = jnp.asarray(logits).astype(jnp.float32)
    y = jnp.asarray(label)
    mask = y >= 0
    safe = jnp.where(mask, y, 0).astype(jnp.int32)
    logp = jax.nn.log_softmax(x, axis=-1)
    nll = -jnp.take_along_axis(logp, safe[..., None], axis=-1)[..., 0]
    row = jnp.where(mask, nll, 0.0).sum(axis=-1)
    return row.sum() / mask.sum()


class TestMaskedLossPaddingInvariant:
    def test_padded_vs_unpadded_bit_identical(self):
        import jax

        f = jax.jit(_masked_ce)
        rng = np.random.RandomState(0)
        B, S, S2, V = 4, 11, 16, 13
        logits = rng.randn(B, S, V).astype("float32")
        label = rng.randint(0, V, (B, S)).astype("int32")
        lens = [5, 11, 8, 3]
        for i, n in enumerate(lens):
            label[i, n:] = -1
        # pad with GARBAGE logits and -1 labels: the loss may not see any
        # of it, bit for bit
        logits_p = np.concatenate(
            [logits, rng.randn(B, S2 - S, V).astype("float32")], axis=1)
        label_p = np.concatenate(
            [label, np.full((B, S2 - S), -1, "int32")], axis=1)
        a = np.asarray(f(logits, label))
        b = np.asarray(f(logits_p, label_p))
        assert a.tobytes() == b.tobytes()

    def test_trainstep_losses_bit_identical_padded_vs_unpadded(self):
        """End to end through TrainStep: the same sentences fed at their
        natural length and padded to a larger bucket give bitwise equal
        losses (identical params; masked loss; no dropout)."""
        import jax.numpy as jnp

        from mxnet_tpu import gluon, nd, optimizer as opt
        from mxnet_tpu.ndarray.ndarray import NDArray
        from mxnet_tpu.parallel import TrainStep

        class _Loss:
            def __call__(self, pred, label):
                return NDArray(_masked_ce(pred.data, label.data))

        def build():
            mx.random.seed(5)
            np.random.seed(5)
            net = gluon.nn.Dense(8, flatten=False)
            net.initialize()
            net(nd.zeros((2, 4, 3)))
            return TrainStep(net, _Loss(),
                             opt.SGD(learning_rate=0.0), donate=False)

        rng = np.random.RandomState(1)
        x = rng.randn(2, 5, 3).astype("float32")
        y = rng.randint(0, 8, (2, 5)).astype("int32")
        y[0, 3:] = -1
        x_p = np.concatenate(
            [x, rng.randn(2, 3, 3).astype("float32")], axis=1)
        y_p = np.concatenate([y, np.full((2, 3), -1, "int32")], axis=1)
        l1 = build()(nd.array(x), nd.array(y)).asnumpy()
        l2 = build()(nd.array(x_p), nd.array(y_p)).asnumpy()
        assert l1.tobytes() == l2.tobytes()


class TestDataLoaderComposition:
    def test_bucketed_loader_emits_only_signature_shapes(self):
        lengths = _lengths(n=80)
        rng = np.random.RandomState(0)
        dataset = [(rng.randint(1, 50, size=n).astype("int32"),
                    rng.randint(0, 5)) for n in lengths]
        s = FixedBucketSampler(lengths, 8, 4, ratio=0.5, last_batch="pad")
        loader = DataLoader(dataset, batch_sampler=s,
                            batchify_fn=PadToBucket(s.bucket_keys))
        shapes = set()
        for data, vl, label in loader:
            shapes.add(tuple(data.shape))
            assert int(vl.asnumpy().max()) <= data.shape[1]
        assert shapes == {(bs, k) for bs, k in s.signatures()}

    def test_composes_with_prefetch_to_device(self):
        lengths = _lengths(n=40)
        rng = np.random.RandomState(0)
        dataset = [rng.randint(1, 50, size=n).astype("int32")
                   for n in lengths]
        s = FixedBucketSampler(lengths, 8, 2, last_batch="discard")
        loader = DataLoader(dataset, batch_sampler=s,
                            batchify_fn=PadToBucket(s.bucket_keys),
                            prefetch_to_device=2)
        n = 0
        for data, vl in loader:
            assert data.shape[1] in s.bucket_keys
            n += 1
        assert n == len(s)
