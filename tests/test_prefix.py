"""Prefix caching: COW KV pages, radix-trie matching, affinity (ISSUE 13).

Contracts under test:

- TRIE: ``PrefixCache.insert`` registers page-aligned blocks under an
  exact-prompt root (dedup on re-insert, partial tail as a leaf, no root
  without cross frames), ``match`` returns the longest cached cover
  capped at ``len(target) - 1`` with the partial page flagged for COW,
  and ``check_invariants`` proves the trie's page ledger exact.
- REFCOUNTS: every page's refcount equals its slot mappings plus cache
  membership through arbitrary alloc / adopt_ref / cache_acquire /
  release / evict interleavings — ``PagePool.check_invariants(...,
  cache_pages=cache.pages())`` passes after every step and pages only
  return to the free list at refcount 0.
- EVICTION: ``evict`` frees LRU sole-ref leaves only (pages a live slot
  still maps survive), ``flush`` returns every cached page, and a full
  pool evicts cached-but-idle pages to admit new work instead of
  refusing it.
- BIT-IDENTITY: greedy decode through a cache hit (adopted pages + COW
  tail + suffix replay) emits exactly the tokens of an uncached batcher
  forced with the same history — including after COW divergence, which
  must not corrupt the shared page for the original history.
- ZERO RECOMPILES: the warmed engine serves cold, hit, and COW paths
  without a single steady-state recompile.
- AFFINITY: the router narrows placement to replicas advertising the
  prompt digest, falls back to predicted-wait placement when none does
  (or when ``MXTPU_PREFIX_AFFINITY=0``), and prefix requests bypass the
  disaggregated KV handoff.
- DISAGG SEEDING: adopting pushed prefill frames registers the prompt
  in the decode-side trie, so the next turn hits the cache.
"""

import queue
import time

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import nd
from mxnet_tpu.gluon.model_zoo.transformer import TransformerModel
from mxnet_tpu.parallel import InferStep
from mxnet_tpu.serving import (ContinuousBatcher, PagePool, PrefillEngine,
                               PrefixCache, Replica, Router, prompt_digest)
from mxnet_tpu.serving.batcher import GenerationResult
from mxnet_tpu.serving.pages import TRASH_PAGE, pages_for

V = 61


def _make_net(seed=0, prefix="pfx_net_"):
    np.random.seed(seed)
    mx.random.seed(seed)
    net = TransformerModel(src_vocab=V, tgt_vocab=V, units=16,
                           hidden_size=32, num_layers=1, num_heads=2,
                           max_length=64, dropout=0.0, prefix=prefix)
    net.initialize(mx.initializer.Xavier())
    net._probe_shapes(nd.zeros((2, 8), dtype="int32"),
                      nd.zeros((2, 8), dtype="int32"))
    return net


@pytest.fixture(scope="module")
def engine():
    return InferStep(_make_net(0), max_len=64)


def _batcher(engine, cache_on, name):
    return ContinuousBatcher(engine, (8,), slots=2, max_new_tokens=6,
                             page_size=4, iter_tokens=2,
                             max_prefix_tokens=16, prefix_cache=cache_on,
                             warmup=True, name=name)


@pytest.fixture(scope="module")
def cached_batcher(engine):
    bat = _batcher(engine, True, "pfx-cached")
    yield bat
    bat.stop()


@pytest.fixture(scope="module")
def cold_batcher(engine):
    # identical weights + bucket/suffix menus, no trie: the bitwise
    # reference for every cache-hit path
    bat = _batcher(engine, False, "pfx-cold")
    yield bat
    bat.stop()


def _pool_cache(num_pages=12, page_size=4, slots=3, pages_per_slot=6,
                **kw):
    pool = PagePool(num_pages, page_size, slots, pages_per_slot)
    cache = PrefixCache(pool, page_size, enabled=True, **kw)
    return pool, cache


def _frames():
    return dict(mem_vl=3, ck=np.zeros((1, 3, 16), np.float32),
                cv=np.zeros((1, 3, 16), np.float32))


def _audit(pool, cache, live=()):
    cache.check_invariants()
    pool.check_invariants(live_slots=live, cache_pages=cache.pages())


class TestTrie:
    def test_insert_without_frames_creates_no_root(self):
        pool, cache = _pool_cache()
        assert pool.alloc(0, 2)
        assert cache.insert([5, 6], range(1, 8), pool.owned(0)) == 0
        assert not cache.has_root([5, 6])
        assert cache.match([5, 6], range(1, 8)) is None  # counted miss
        assert cache.snapshot()["misses"] == 1
        _audit(pool, cache, live=(0,))

    def test_insert_match_roundtrip_with_cow_tail(self):
        pool, cache = _pool_cache()
        prompt, target = [5, 9, 11], [1, 2, 3, 4, 5, 6, 7]  # 1 full + tail
        assert pool.alloc(0, pages_for(len(target), 4))
        pages = pool.owned(0)
        assert cache.insert(prompt, target, pages, **_frames()) == 2
        assert cache.has_root(prompt)
        assert prompt_digest(prompt) in cache.digests()
        hit = cache.match(prompt, target)
        # positions 0..5 adopted (cap at len-1): one full page + 2 of
        # the 3-token tail via COW
        assert hit.matched == 6
        assert hit.full_pages == (pages[0],)
        assert hit.cow == (pages[1], 2)
        assert hit.mem_vl == 3 and hit.ck is not None
        _audit(pool, cache, live=(0,))

    def test_reinsert_dedups_blocks(self):
        pool, cache = _pool_cache()
        target = list(range(1, 9))  # exactly 2 full blocks
        assert pool.alloc(0, 2)
        assert cache.insert([7], target, pool.owned(0), **_frames()) == 2
        assert pool.alloc(1, 2)
        # same prompt+target from another slot: nothing new is cached
        assert cache.insert([7], target, pool.owned(1), **_frames()) == 0
        assert cache.total_pages == 2
        pool.release(1)  # its pages were never adopted by the trie
        assert pool.free_pages == 12 - 2
        _audit(pool, cache, live=(0,))

    def test_divergent_second_block_branches(self):
        pool, cache = _pool_cache()
        a = [1, 2, 3, 4, 5, 6, 7, 8]
        b = [1, 2, 3, 4, 9, 9, 9, 9]  # shares block 0 only
        assert pool.alloc(0, 2) and pool.alloc(1, 2)
        assert cache.insert([7], a, pool.owned(0), **_frames()) == 2
        # block 0 dedups against slot 0's page; block 1 branches
        assert cache.insert([7], b, pool.owned(1), **_frames()) == 1
        assert cache.total_pages == 3
        ha, hb = cache.match([7], a), cache.match([7], b)
        assert ha.full_pages[0] == hb.full_pages[0]
        assert ha.matched == hb.matched == 7  # cap at len(target) - 1
        assert ha.cow[0] != hb.cow[0] and ha.cow[1] == hb.cow[1] == 3
        _audit(pool, cache, live=(0, 1))

    def test_partial_tail_extends_in_place_same_page(self):
        pool, cache = _pool_cache()
        assert pool.alloc(0, 2)
        p0, p1 = pool.owned(0)
        # a short handoff seeds a 1-token tail; the slot keeps filling
        # that SAME page and re-registers the grown chain at retire —
        # the longer block supersedes the node instead of
        # double-acquiring its page
        assert cache.insert([5], [1], (p0,), **_frames()) == 1
        assert cache.insert([5], [1, 2, 3, 4, 9], (p0, p1)) == 1
        assert cache.total_pages == 2 and pool.ref(p0) == 2
        hit = cache.match([5], [1, 2, 3, 4, 9])
        assert hit.matched == 4 and hit.full_pages == (p0,)
        _audit(pool, cache, live=(0,))

    def test_match_caps_below_full_cover(self):
        pool, cache = _pool_cache()
        target = [1, 2, 3, 4]  # one exactly-full block
        assert pool.alloc(0, 1)
        assert cache.insert([3], target, pool.owned(0), **_frames()) == 1
        hit = cache.match([3], target)
        # the final position must still run to produce first-token
        # logits: a full-block cover degrades to a 3-token COW
        assert hit.matched == 3
        assert hit.full_pages == () and hit.cow[1] == 3
        _audit(pool, cache, live=(0,))

    def test_max_roots_evicts_lru_root(self):
        pool, cache = _pool_cache(max_roots=2)
        for i in range(3):
            assert pool.alloc(i, 1)
            assert cache.insert([i], [1, 2, 3], pool.owned(i),
                                **_frames()) == 1
            pool.release(i)
            _audit(pool, cache)
        assert len(cache) == 2
        assert not cache.has_root([0])  # LRU root dropped, pages freed
        assert cache.snapshot()["evicted_roots"] == 1
        assert pool.free_pages == 12 - 2
        _audit(pool, cache)

    def test_flush_returns_every_page(self):
        pool, cache = _pool_cache()
        assert pool.alloc(0, 3)
        cache.insert([5], list(range(1, 12)), pool.owned(0), **_frames())
        pool.release(0)
        assert pool.free_pages == 12 - 3
        assert cache.flush() == 1
        assert pool.free_pages == 12 and cache.total_pages == 0
        _audit(pool, cache)


class TestRefcounts:
    def test_release_keeps_cached_pages_alive(self):
        pool, cache = _pool_cache()
        assert pool.alloc(0, 2)
        p0, p1 = pool.owned(0)
        cache.insert([9], list(range(1, 8)), (p0, p1), **_frames())
        assert pool.ref(p0) == pool.ref(p1) == 2
        _audit(pool, cache, live=(0,))
        assert pool.release(0) == 0  # cache still holds both
        assert pool.ref(p0) == 1 and p0 not in set(pool._free)
        _audit(pool, cache)

    def test_adopt_release_interleaving_is_ref_exact(self):
        pool, cache = _pool_cache()
        assert pool.alloc(0, 2)
        pages = pool.owned(0)
        cache.insert([9], list(range(1, 8)), pages, **_frames())
        pool.release(0)
        # two readers adopt the cached chain (shared, read-only) …
        for s in (1, 2):
            assert pool.adopt_ref(s, pages)
            _audit(pool, cache, live=(1, 2)[:s])
        assert pool.ref(pages[0]) == 3
        assert pool.shared_pages == 2
        # … then one grows privately and both retire (preempt-style)
        assert pool.alloc(1, 1)
        assert pool.release(1) == 1  # only the private page frees
        assert pool.release(2) == 0
        assert pool.ref(pages[0]) == 1
        _audit(pool, cache)

    def test_evict_skips_pages_live_slots_still_map(self):
        pool, cache = _pool_cache()
        assert pool.alloc(0, 2)
        pages = pool.owned(0)
        cache.insert([9], list(range(1, 8)), pages, **_frames())
        pool.release(0)
        assert pool.adopt_ref(1, pages)  # a live reader
        assert cache.evict(2) == 0  # nothing is sole-ref
        assert cache.total_pages == 2
        pool.release(1)
        assert cache.evict(2) == 2  # now LRU leaves free for real
        assert pool.free_pages == 12
        _audit(pool, cache)

    def test_double_acquire_and_trash_adopt_raise(self):
        from mxnet_tpu.base import MXNetError
        pool, _ = _pool_cache()
        assert pool.alloc(0, 1)
        page = pool.owned(0)[0]
        pool.cache_acquire((page,))
        with pytest.raises(MXNetError):
            pool.cache_acquire((page,))
        with pytest.raises(MXNetError):
            pool.adopt_ref(1, (TRASH_PAGE,))


class TestEviction:
    def test_lru_order_and_partial_progress(self):
        pool, cache = _pool_cache()
        held = {}
        for i in range(3):
            assert pool.alloc(i, 1)
            cache.insert([i], [1, 2, 3], pool.owned(i), **_frames())
            held[i] = pool.owned(i)[0]
            pool.release(i)
        cache.match([0], [1, 2, 3])  # refresh root 0: root 1 is now LRU
        assert cache.evict(1) == 1
        # root 1's page went back to the pool (the frame-only root
        # stays for encoder-skip); root 0's refreshed page survives
        assert held[1] not in cache.pages()
        assert held[0] in cache.pages()
        assert cache.match([1], [1, 2, 3]).matched == 0
        # asking for more than exists frees what it can
        assert cache.evict(10) == 2
        assert pool.free_pages == 12
        _audit(pool, cache)

    def test_max_pages_caps_trie_footprint(self):
        pool, cache = _pool_cache(max_pages=2)
        for i in range(3):
            assert pool.alloc(i, 1)
            cache.insert([i], [1, 2, 3], pool.owned(i), **_frames())
            pool.release(i)
            assert cache.total_pages <= 2
            _audit(pool, cache)
        assert cache.snapshot()["evicted_pages"] == 1


def _serve(bat, prompt, prefix=None, timeout=120):
    return list(bat.submit(prompt, max_new_tokens=6,
                           prefix_ids=prefix).result(timeout=timeout))


def _settled_audit(bat):
    """Audit once every slot has retired (the scheduler releases pages
    just after resolving the future)."""
    for _ in range(400):
        with bat._stats_lock:
            busy = any(s is not None for s in bat._slots)
        if not busy:
            break
        time.sleep(0.01)
    bat.cache.check_invariants()
    bat.pool.check_invariants(cache_pages=bat.cache.pages())


class TestEndToEnd:
    def test_hit_is_bit_identical_to_cold(self, cached_batcher,
                                          cold_batcher):
        cached_batcher.cache.flush()
        prompt = [5, 9, 11, 2, 7]
        turn1 = _serve(cached_batcher, prompt)
        assert cached_batcher.cache.has_root(prompt)  # retire seeded it
        base = cached_batcher.prefix_stats()
        turn2 = _serve(cached_batcher, prompt, prefix=turn1)
        stats = cached_batcher.prefix_stats()
        assert stats["hits"] == base["hits"] + 1
        assert stats["tokens_saved"] > base["tokens_saved"]
        assert turn2 == _serve(cold_batcher, prompt, prefix=turn1)
        # deeper history: trie now holds turn1+turn2; still bit-exact
        hist = turn1 + turn2
        assert _serve(cached_batcher, prompt, prefix=hist) \
            == _serve(cold_batcher, prompt, prefix=hist)
        _settled_audit(cached_batcher)

    def test_cow_divergence_preserves_shared_page(self, cached_batcher,
                                                  cold_batcher):
        cached_batcher.cache.flush()
        prompt = [8, 3, 14, 6]
        turn1 = _serve(cached_batcher, prompt)
        out_a = _serve(cached_batcher, prompt, prefix=turn1)
        # client edits the last history token: partial-page divergence
        hist_b = list(turn1)
        hist_b[-1] = (hist_b[-1] + 1) % (V - 3) + 2
        base = cached_batcher.prefix_stats()
        out_b = _serve(cached_batcher, prompt, prefix=hist_b)
        stats = cached_batcher.prefix_stats()
        assert stats["cow_copies"] > base["cow_copies"]
        assert out_b == _serve(cold_batcher, prompt, prefix=hist_b)
        # the divergent write went to a private copy: the original
        # history must replay to the exact same tokens afterwards
        assert _serve(cached_batcher, prompt, prefix=turn1) == out_a
        _settled_audit(cached_batcher)

    def test_full_pool_evicts_idle_cache_to_admit(self, cached_batcher,
                                                  cold_batcher):
        cached_batcher.cache.flush()
        # each retired request caches pages_for(1+6, 4) = 2 pages; six
        # distinct prompts exhaust the 12-page pool entirely
        for i in range(6):
            _serve(cached_batcher, [2 + i, 30, 41])
        _settled_audit(cached_batcher)
        assert cached_batcher.pool.free_pages == 0
        base = cached_batcher.cache.snapshot()["evicted_pages"]
        prompt = [50, 51, 52]
        out = _serve(cached_batcher, prompt)
        assert out == _serve(cold_batcher, prompt)
        assert cached_batcher.cache.snapshot()["evicted_pages"] > base
        _settled_audit(cached_batcher)

    def test_zero_steady_state_recompiles(self, engine, cached_batcher):
        # runs after the cold/hit/COW/eviction traffic above: none of it
        # may have minted a new program on the warmed engine
        assert engine.compile_guard.steady
        assert engine.compile_guard.steady_state_recompiles == 0

    def test_suffix_wide_replay_bit_identical(self, cold_batcher):
        """ISSUE 14 follow-up: a batcher routing the prefix-hit suffix
        replay through the ONE-pass q_offset window program
        (``suffix_wide=True``) serves the same transcripts as the
        per-token teacher-forced replay, hit for hit."""
        eng = InferStep(_make_net(0, prefix="pfx_wide_"), max_len=64)
        bat = ContinuousBatcher(eng, (8,), slots=2, max_new_tokens=6,
                                page_size=4, iter_tokens=2,
                                max_prefix_tokens=16, prefix_cache=True,
                                suffix_wide=True, warmup=True,
                                name="pfx-wide")
        try:
            prompt = [4, 12, 9, 33, 6]
            turn1 = _serve(bat, prompt)
            assert bat.cache.has_root(prompt)
            base = bat.prefix_stats()
            turn2 = _serve(bat, prompt, prefix=turn1)
            assert bat.prefix_stats()["hits"] == base["hits"] + 1
            # same weights, wide replay vs the cold teacher-forced path
            assert turn1 == _serve(cold_batcher, prompt)
            assert turn2 == _serve(cold_batcher, prompt, prefix=turn1)
            hist = turn1 + turn2
            assert _serve(bat, prompt, prefix=hist) \
                == _serve(cold_batcher, prompt, prefix=hist)
            _settled_audit(bat)
            assert eng.compile_guard.steady_state_recompiles == 0
        finally:
            bat.stop()


class _StubBatcher:
    """Placement-only batcher stub: no engine, records submits."""

    healthy = True

    def __init__(self, name, digests=(), backlog=0):
        self.name = name
        self._digests = list(digests)
        self._queue = queue.Queue()
        for _ in range(backlog):
            self._queue.put(None)
        self.calls = []

    def prefix_digests(self, limit=None):
        return list(self._digests)

    def rolling_wait_ms(self):
        return None

    def submit(self, prompt, max_new, deadline_ms=None, prefix_ids=None,
               request_id=None):
        self.calls.append((list(prompt),
                           None if prefix_ids is None else list(prefix_ids)))
        return GenerationResult()


class TestAffinityPlacement:
    def _fleet(self, digest):
        # the digest holder carries MORE backlog: predicted-wait
        # placement alone would always pick "idle"
        holder = Replica("holder", _StubBatcher("holder", (digest,),
                                                backlog=3))
        idle = Replica("idle", _StubBatcher("idle"))
        return holder, idle, Router([holder, idle], start=False)

    def test_affinity_beats_predicted_wait(self):
        prompt, hist = [5, 6, 7], [9, 9]
        holder, idle, router = self._fleet(prompt_digest(prompt))
        router.submit(prompt, 4)  # no history: placement ignores the trie
        assert idle.batcher.calls == [([5, 6, 7], None)]
        router.submit(prompt, 4, prefix_ids=hist)
        assert holder.batcher.calls == [([5, 6, 7], [9, 9])]

    def test_fallback_when_no_replica_holds_digest(self):
        holder, idle, router = self._fleet(prompt_digest([1, 2, 3]))
        router.submit([5, 6, 7], 4, prefix_ids=[9])
        assert idle.batcher.calls and not holder.batcher.calls

    def test_env_disables_affinity(self, monkeypatch):
        monkeypatch.setenv("MXTPU_PREFIX_AFFINITY", "0")
        prompt = [5, 6, 7]
        holder, idle, router = self._fleet(prompt_digest(prompt))
        router.submit(prompt, 4, prefix_ids=[9])
        assert idle.batcher.calls and not holder.batcher.calls

    def test_prefix_requests_bypass_disagg_handoff(self):
        class _DisaggReplica(Replica):
            def __init__(self, name, batcher):
                super().__init__(name, batcher)
                self.handoffs = []

            def submit_disagg(self, pre, prompt, max_new,
                              deadline_ms=None, klass="interactive",
                              request_id=None):
                self.handoffs.append(list(prompt))
                return GenerationResult()

        dec = _DisaggReplica("dec", _StubBatcher("dec"))
        pre = Replica("pre", _StubBatcher("pre"), role="prefill")
        router = Router([dec, pre], start=False, disagg_min_prompt=4)
        long_prompt = list(range(2, 10))
        router.submit(long_prompt, 4)
        assert dec.handoffs == [long_prompt]  # handoff path
        router.submit(long_prompt, 4, prefix_ids=[9, 9])
        # forced history makes the KV handoff moot: direct submit
        assert dec.handoffs == [long_prompt]
        assert dec.batcher.calls == [(long_prompt, [9, 9])]


class TestDisaggSeeding:
    def test_adopted_frames_seed_the_trie(self, cached_batcher,
                                          cold_batcher):
        cached_batcher.cache.flush()
        pre = PrefillEngine(InferStep(_make_net(0), max_len=64), (8,),
                            rows=2, page_size=4, warmup=True)
        prompt = [4, 17, 33, 8, 21]
        frames = pre.prefill(prompt)
        out = list(cached_batcher.submit(
            prompt, max_new_tokens=6, frames=frames).result(timeout=120))
        assert out == _serve(cold_batcher, prompt)  # handoff bit-exact
        assert cached_batcher.cache.has_root(prompt)  # seeded at adopt
        base = cached_batcher.prefix_stats()
        turn2 = _serve(cached_batcher, prompt, prefix=out)
        assert cached_batcher.prefix_stats()["hits"] == base["hits"] + 1
        assert turn2 == _serve(cold_batcher, prompt, prefix=out)
        _settled_audit(cached_batcher)
