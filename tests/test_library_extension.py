"""C++ operator extension loading (reference: ``mx.library.load`` over
``lib_api.h`` custom ops [unverified]). Compiles the shipped example
extension with g++ and drives it through nd / autograd / hybridize."""

import os
import subprocess
import sys

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import autograd, gluon, nd

_SRC = os.path.join(os.path.dirname(__file__), "..", "examples",
                    "extensions", "custom_ops.cc")


@pytest.fixture(scope="module")
def ext_lib(tmp_path_factory):
    so = str(tmp_path_factory.mktemp("ext") / "libcustom_ops.so")
    subprocess.run(
        ["g++", "-O2", "-shared", "-fPIC", "-o", so, _SRC], check=True
    )
    names = mx.library.load(so, verbose=False)
    assert set(names) == {"my_relu6", "my_scaled_add"}
    return so


class TestExtension:
    def test_eager_compute(self, ext_lib):
        out = nd.my_relu6(nd.array(np.array([-1.0, 3.0, 9.0], np.float32)))
        np.testing.assert_allclose(out.asnumpy(), [0.0, 3.0, 6.0])
        out2 = nd.my_scaled_add(nd.ones((3,)), nd.ones((3,)) * 4)
        np.testing.assert_allclose(out2.asnumpy(), [3.0, 3.0, 3.0])

    def test_autograd_backward(self, ext_lib):
        x = nd.array(np.array([-1.0, 3.0, 9.0], np.float32))
        x.attach_grad()
        with autograd.record():
            y = nd.my_relu6(x)
            y.sum().backward()
        np.testing.assert_allclose(x.grad.asnumpy(), [0.0, 1.0, 0.0])

    def test_inside_hybridize(self, ext_lib):
        class Net(gluon.HybridBlock):
            def hybrid_forward(self, F, x):
                return F.my_relu6(x * 2)

        net = Net()
        net.hybridize()
        out = net(nd.array(np.array([-1.0, 2.0, 5.0], np.float32)))
        np.testing.assert_allclose(out.asnumpy(), [0.0, 4.0, 6.0])

    def test_bad_library_rejected(self, tmp_path):
        bad = tmp_path / "notalib.so"
        bad.write_bytes(b"not a shared object")
        with pytest.raises(mx.base.MXNetError):
            mx.library.load(str(bad))

    def test_missing_symbols_rejected(self, tmp_path):
        src = tmp_path / "empty.cc"
        src.write_text("extern \"C\" int unrelated() { return 0; }\n")
        so = str(tmp_path / "libempty.so")
        subprocess.run(
            ["g++", "-O2", "-shared", "-fPIC", "-o", so, str(src)],
            check=True,
        )
        with pytest.raises(mx.base.MXNetError):
            mx.library.load(so)
