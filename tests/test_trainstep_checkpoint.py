"""TrainStep resumability (round-4 verdict missing #1/#3).

The contract: a training run killed at step N and restored in a FRESH
process continues bit-compatibly — parameter values, optimizer moments,
the device-carried PRNG key and step counter all survive; under TP
sharding no process ever writes or reads a full copy of a sharded
array. Reference analogues: Trainer.save_states/load_states +
Module.save_checkpoint (``python/mxnet/gluon/trainer.py`` [unverified]),
extended with the SURVEY §5 "tensorstore-style" sharded layout.
"""

import json
import os
import subprocess
import sys

import numpy as np
import pytest

import jax
from jax.sharding import Mesh, PartitionSpec as P

import mxnet_tpu as mx
from mxnet_tpu import autograd, gluon, optimizer as opt, parallel
from mxnet_tpu.gluon import nn

rng = np.random.RandomState(3)
X = rng.randn(32, 16).astype("float32")
Y = rng.randn(32, 1).astype("float32")


def _build(seed=11):
    mx.random.seed(seed)
    net = nn.HybridSequential()
    with net.name_scope():
        net.add(nn.Dense(32, activation="relu"), nn.Dense(1))
    net.initialize()
    net(mx.nd.array(X))
    return net


def _mesh(shape, names):
    devs = np.array(jax.devices()[: int(np.prod(shape))]).reshape(shape)
    return Mesh(devs, names)


def _params(step):
    step.sync_params()
    s = step._struct_names()
    return {s[k]: v.data().asnumpy() for k, v in
            step._net.collect_params().items()}


TP_RULES = [(r"dense0.*weight", P("model", None)),
            (r"dense1.*weight", P(None, "model"))]


def _make_step(mesh=None, rules=(), seed=11):
    net = _build(seed)
    return parallel.TrainStep(
        net, gluon.loss.L2Loss(), opt.Adam(learning_rate=0.01),
        mesh=mesh, param_rules=rules)


def _run(step, n):
    for _ in range(n):
        L = step(mx.nd.array(X), mx.nd.array(Y))
    return L.asscalar()


def test_state_dict_roundtrip_single_device():
    """3 steps + save + fresh TrainStep + load + 3 steps == 6 straight."""
    ref = _make_step()
    _run(ref, 6)
    want = _params(ref)

    a = _make_step()
    _run(a, 3)
    sd = a.state_dict()
    b = _make_step(seed=99)  # different init: restore must overwrite all
    b.load_state_dict(sd)
    _run(b, 3)
    got = _params(b)
    for k in want:
        np.testing.assert_allclose(got[k], want[k], rtol=1e-6, atol=1e-7,
                                   err_msg=k)


def test_sharded_checkpoint_dp_tp_mesh(tmp_path):
    """Save under a dp=4 x tp=2 mesh, restore into a FRESH TrainStep on
    the same mesh, continue: matches the uninterrupted run. The on-disk
    pieces of TP-sharded weights must each be PARTIAL (no full-array
    write anywhere)."""
    mesh = _mesh((4, 2), ("data", "model"))
    ref = _make_step(mesh, TP_RULES)
    _run(ref, 6)
    want = _params(ref)

    a = _make_step(mesh, TP_RULES)
    _run(a, 3)
    a.save_checkpoint(str(tmp_path), step=3)

    # sharded layout honesty: every piece of a model-sharded param covers
    # strictly less than the full var; pieces tile it exactly
    with open(tmp_path / "step_3" / "index_p0.json") as f:
        index = json.load(f)
    shapes = {n: v.data().shape
              for n, v in a._net.collect_params().items()}
    tp_name = [n for n in shapes if "dense0" in n and "weight" in n][0]
    tp_struct = a._struct_names()[tp_name]
    pieces = [e for e in index if e["name"] == f"values/{tp_struct}"]
    assert len(pieces) == 2  # tp=2 distinct shards
    full = shapes[tp_name]
    for e in pieces:
        vol = np.prod([b[1] - b[0] for b in e["bounds"]])
        assert vol < np.prod(full)
    assert sum(np.prod([b[1] - b[0] for b in e["bounds"]])
               for e in pieces) == np.prod(full)

    b = _make_step(mesh, TP_RULES, seed=99)
    extra = b.load_checkpoint(str(tmp_path), step=3)
    assert extra["t_host"] == 3
    _run(b, 3)
    got = _params(b)
    for k in want:
        np.testing.assert_allclose(got[k], want[k], rtol=1e-5, atol=1e-6,
                                   err_msg=k)
    # restored opt state is placed per the step's rules, not replicated
    b_tp = {v: k for k, v in b._struct_names().items()}[tp_struct]
    st = b._opt_state[b_tp][0]
    assert not st.sharding.is_fully_replicated


def test_restore_onto_different_mesh(tmp_path):
    """Resharding restore: save from dp4xtp2, restore onto dp2xtp4 and
    onto a single device; both continue to the same result."""
    mesh_a = _mesh((4, 2), ("data", "model"))
    ref = _make_step(mesh_a, TP_RULES)
    _run(ref, 6)
    want = _params(ref)

    a = _make_step(mesh_a, TP_RULES)
    _run(a, 3)
    a.save_checkpoint(str(tmp_path / "ck"))

    mesh_b = _mesh((2, 4), ("data", "model"))
    b = _make_step(mesh_b, TP_RULES, seed=99)
    b.load_checkpoint(str(tmp_path / "ck"))
    _run(b, 3)
    got_b = _params(b)

    c = _make_step(seed=98)  # no mesh at all
    c.load_checkpoint(str(tmp_path / "ck"))
    _run(c, 3)
    got_c = _params(c)

    for k in want:
        np.testing.assert_allclose(got_b[k], want[k], rtol=1e-5, atol=1e-6)
        np.testing.assert_allclose(got_c[k], want[k], rtol=1e-5, atol=1e-6)


def test_restore_in_fresh_process(tmp_path):
    """The verdict's literal scenario: kill after 3 steps, restore in a
    brand-new python process, run 3 more, compare to 6 uninterrupted."""
    ref = _make_step(_mesh((4, 2), ("data", "model")), TP_RULES)
    _run(ref, 6)
    want = _params(ref)

    a = _make_step(_mesh((4, 2), ("data", "model")), TP_RULES)
    _run(a, 3)
    a.save_checkpoint(str(tmp_path / "ck"))

    script = tmp_path / "resume.py"
    script.write_text(f"""
import os
os.environ["XLA_FLAGS"] = os.environ.get("XLA_FLAGS", "") + \
    " --xla_force_host_platform_device_count=8"
os.environ["JAX_PLATFORMS"] = "cpu"
import jax
jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_default_matmul_precision", "highest")
import numpy as np
import sys
sys.path.insert(0, {str(os.getcwd())!r})
from tests.test_trainstep_checkpoint import (_make_step, _mesh, _run,
                                             _params, TP_RULES)
step = _make_step(_mesh((4, 2), ("data", "model")), TP_RULES, seed=99)
step.load_checkpoint({str(tmp_path / "ck")!r})
_run(step, 3)
np.savez({str(tmp_path / "out.npz")!r}, **_params(step))
""")
    env = {k: v for k, v in os.environ.items() if k != "PYTHONPATH"}
    r = subprocess.run([sys.executable, str(script)], cwd=os.getcwd(),
                       env=env, capture_output=True, text=True, timeout=600)
    assert r.returncode == 0, r.stderr[-2000:]
    got = np.load(tmp_path / "out.npz")
    for k in want:
        np.testing.assert_allclose(got[k], want[k], rtol=1e-5, atol=1e-6,
                                   err_msg=k)


def test_uncommitted_checkpoint_rejected(tmp_path):
    a = _make_step()
    _run(a, 1)
    a.save_checkpoint(str(tmp_path / "ck"))
    os.remove(tmp_path / "ck" / "DONE.p0")
    b = _make_step(seed=99)
    with pytest.raises(mx.base.MXNetError, match="not committed"):
        b.load_checkpoint(str(tmp_path / "ck"))


def test_trainer_interop_roundtrip():
    """Moments cross between the fused step and the eager Trainer: 3
    fused steps -> export -> 3 Trainer steps matches 6 fused steps; and
    3 Trainer steps -> import -> 3 fused steps matches too."""
    ref = _make_step()
    _run(ref, 6)
    want = _params(ref)

    # fused -> Trainer
    a = _make_step()
    _run(a, 3)
    a.sync_params()
    trainer = gluon.Trainer(a._net.collect_params(), "adam",
                            {"learning_rate": 0.01})
    a.export_trainer_states(trainer)
    loss_fn = gluon.loss.L2Loss()
    for _ in range(3):
        with autograd.record():
            L = loss_fn(a._net(mx.nd.array(X)), mx.nd.array(Y))
        L.backward()
        trainer.step(len(X))
    s = a._struct_names()
    got = {s[k]: v.data().asnumpy() for k, v in
           a._net.collect_params().items()}
    for k in want:
        np.testing.assert_allclose(got[k], want[k], rtol=1e-4, atol=1e-5,
                                   err_msg=k)

    # Trainer -> fused
    net = _build()
    trainer2 = gluon.Trainer(net.collect_params(), "adam",
                             {"learning_rate": 0.01})
    for _ in range(3):
        with autograd.record():
            L = loss_fn(net(mx.nd.array(X)), mx.nd.array(Y))
        L.backward()
        trainer2.step(len(X))
    b = parallel.TrainStep(net, gluon.loss.L2Loss(),
                           opt.Adam(learning_rate=0.01))
    b.import_trainer_states(trainer2)
    assert b._t == 3
    _run(b, 3)
    got2 = _params(b)
    for k in want:
        np.testing.assert_allclose(got2[k], want[k], rtol=1e-4, atol=1e-5,
                                   err_msg=k)


def test_checkpoint_facade_with_trainstep(tmp_path):
    """checkpoint.save_checkpoint(train_step=...) composes the sharded
    TrainStep layout with the commit-marker step directory, and
    CheckpointManager-style latest_step discovery still works."""
    from mxnet_tpu import checkpoint as ck

    mesh = _mesh((4, 2), ("data", "model"))
    ref = _make_step(mesh, TP_RULES)
    _run(ref, 6)
    want = _params(ref)

    a = _make_step(mesh, TP_RULES)
    _run(a, 3)
    ck.save_checkpoint(str(tmp_path), 3, train_step=a)
    assert ck.latest_step(str(tmp_path)) == 3

    b = _make_step(mesh, TP_RULES, seed=99)
    meta = ck.load_checkpoint(str(tmp_path), train_step=b)
    assert meta["step"] == 3 and meta["has_trainstep"]
    _run(b, 3)
    got = _params(b)
    for k in want:
        np.testing.assert_allclose(got[k], want[k], rtol=1e-5, atol=1e-6)


def test_state_dict_survives_donation():
    """state_dict must snapshot: the live buffers are donated to XLA by
    the next step, and the saved dict must not die with them."""
    a = _make_step()
    _run(a, 2)
    sd = a.state_dict()
    _run(a, 2)  # donates the buffers state_dict saw
    # every leaf still readable
    for v in sd["values"].values():
        np.asarray(v)
    for st in sd["opt_state"].values():
        for x in st:
            np.asarray(x)
    np.asarray(sd["key"])
    np.asarray(sd["t_dev"])

    b = _make_step(seed=99)
    b.load_state_dict(sd)
    assert b._t == 2


def test_facade_rejects_missing_trainstep_payload(tmp_path):
    """Loading train_step from a checkpoint saved without one must be a
    clean MXNetError, not a FileNotFoundError."""
    from mxnet_tpu import checkpoint as ck

    net = _build()
    ck.save_checkpoint(str(tmp_path), 1, net=net)
    b = _make_step(seed=99)
    with pytest.raises(mx.base.MXNetError, match="without a TrainStep"):
        ck.load_checkpoint(str(tmp_path), train_step=b)


def test_partial_shard_write_not_latest(tmp_path):
    """A step whose sharded payload lacks a process's DONE marker must
    be invisible to latest_step (restart falls back to the older good
    step instead of wedging)."""
    from mxnet_tpu import checkpoint as ck

    a = _make_step()
    _run(a, 1)
    ck.save_checkpoint(str(tmp_path), 1, train_step=a)
    _run(a, 1)
    ck.save_checkpoint(str(tmp_path), 2, train_step=a)
    os.remove(tmp_path / "step_2" / "trainstep" / "DONE.p0")
    assert ck.latest_step(str(tmp_path)) == 1
    b = _make_step(seed=99)
    meta = ck.load_checkpoint(str(tmp_path), train_step=b)
    assert meta["step"] == 1


def test_manager_rolls_trainstep_checkpoints(tmp_path):
    from mxnet_tpu import checkpoint as ck

    mgr = ck.CheckpointManager(str(tmp_path), keep=2)
    a = _make_step()
    for s in (1, 2, 3):
        _run(a, 1)
        mgr.save(s, train_step=a)
    assert not (tmp_path / "step_1").exists()
    b = _make_step(seed=99)
    meta = mgr.restore_latest(train_step=b)
    assert meta["step"] == 3 and b._t == 3
