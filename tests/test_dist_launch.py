"""Multi-process distributed rendezvous + KVStoreDist sync over localhost.

The reference validated its dist kvstore by launching N local worker
processes through ``tools/launch.py`` (``tests/nightly/dist_sync_kvstore.py``
[unverified]); this does the same: 2 CPU processes join one
``jax.distributed`` coordinator and push/pull through ``dist_sync``.
"""

import os
import sys

import pytest

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", "tools"))
import launch  # noqa: E402  (tools/launch.py)

_WORKER = os.path.join(os.path.dirname(__file__), "dist_worker.py")


def test_two_process_dist_sync_kvstore():
    rc = launch.launch_local(2, [sys.executable, _WORKER])
    assert rc == 0


def test_worker_env_vars():
    env = launch.worker_env("localhost:9999", 4, 2)
    assert env["MXNET_TPU_COORDINATOR"] == "localhost:9999"
    assert env["MXNET_TPU_NUM_PROCS"] == "4"
    assert env["MXNET_TPU_PROC_ID"] == "2"


def test_free_port_is_bindable():
    import socket

    port = launch.find_free_port()
    with socket.socket() as s:
        s.bind(("localhost", port))
