"""Multi-process distributed rendezvous + KVStoreDist sync over localhost.

The reference validated its dist kvstore by launching N local worker
processes through ``tools/launch.py`` (``tests/nightly/dist_sync_kvstore.py``
[unverified]); this does the same: 2 CPU processes join one
``jax.distributed`` coordinator and push/pull through ``dist_sync``.
"""

import os
import sys

import pytest

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", "tools"))
import launch  # noqa: E402  (tools/launch.py)

_WORKER = os.path.join(os.path.dirname(__file__), "dist_worker.py")


def test_two_process_dist_sync_kvstore():
    rc = launch.launch_local(2, [sys.executable, _WORKER])
    assert rc == 0


def test_worker_env_vars():
    env = launch.worker_env("localhost:9999", 4, 2)
    assert env["MXNET_TPU_COORDINATOR"] == "localhost:9999"
    assert env["MXNET_TPU_NUM_PROCS"] == "4"
    assert env["MXNET_TPU_PROC_ID"] == "2"


def test_free_port_is_bindable():
    import socket

    port = launch.find_free_port()
    with socket.socket() as s:
        s.bind(("localhost", port))


def test_four_process_compression_and_updater():
    """4 workers, 2-bit compression + updater-on-store over dist_sync —
    the reference's nightly dist_sync_kvstore pattern at 4 ranks."""
    env = dict(os.environ, DIST_TEST_MODE="full")
    rc = _launch_with_env(4, [sys.executable, _WORKER], env)
    assert rc == 0


def test_worker_crash_propagates():
    """A dying worker must fail the whole job quickly (launcher kills the
    survivors) — not leave them hung in a never-completing collective."""
    import time

    env = dict(os.environ, DIST_TEST_MODE="crash")
    t0 = time.time()
    rc = _launch_with_env(2, [sys.executable, _WORKER], env)
    took = time.time() - t0
    assert rc == 17, f"crash exit code not propagated: {rc}"
    # the surviving worker sleeps 30s; propagation must beat that
    assert took < 28, f"propagation too slow: {took:.1f}s"


def _launch_with_env(n, command, env):
    """launch_local with a custom base environment for the workers."""
    import unittest.mock as mock

    def patched_env(coordinator, num_procs, proc_id):
        e = dict(env)
        e.update({
            "MXNET_TPU_COORDINATOR": coordinator,
            "MXNET_TPU_NUM_PROCS": str(num_procs),
            "MXNET_TPU_PROC_ID": str(proc_id),
        })
        return e

    with mock.patch.object(launch, "worker_env", patched_env):
        return launch.launch_local(n, command, timeout=240)
