"""Multi-process distributed rendezvous + KVStoreDist sync over localhost.

The reference validated its dist kvstore by launching N local worker
processes through ``tools/launch.py`` (``tests/nightly/dist_sync_kvstore.py``
[unverified]); this does the same: 2 CPU processes join one
``jax.distributed`` coordinator and push/pull through ``dist_sync``.
"""

import os
import sys

import numpy as np
import pytest

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", "tools"))
import launch  # noqa: E402  (tools/launch.py)

_WORKER = os.path.join(os.path.dirname(__file__), "dist_worker.py")


def test_two_process_dist_sync_kvstore():
    rc = launch.launch_local(2, [sys.executable, _WORKER])
    assert rc == 0


def test_worker_env_vars():
    env = launch.worker_env("localhost:9999", 4, 2)
    assert env["MXNET_TPU_COORDINATOR"] == "localhost:9999"
    assert env["MXNET_TPU_NUM_PROCS"] == "4"
    assert env["MXNET_TPU_PROC_ID"] == "2"


def test_free_port_is_bindable():
    import socket

    port = launch.find_free_port()
    with socket.socket() as s:
        s.bind(("localhost", port))


def test_four_process_compression_and_updater():
    """4 workers, 2-bit compression + updater-on-store over dist_sync —
    the reference's nightly dist_sync_kvstore pattern at 4 ranks."""
    env = dict(os.environ, DIST_TEST_MODE="full")
    rc = _launch_with_env(4, [sys.executable, _WORKER], env)
    assert rc == 0


def test_worker_crash_propagates():
    """A dying worker must fail the whole job quickly (launcher kills the
    survivors) — not leave them hung in a never-completing collective."""
    import time

    env = dict(os.environ, DIST_TEST_MODE="crash")
    t0 = time.time()
    rc = _launch_with_env(2, [sys.executable, _WORKER], env)
    took = time.time() - t0
    assert rc == 17, f"crash exit code not propagated: {rc}"
    # the surviving worker sleeps 30s; propagation must beat that
    assert took < 28, f"propagation too slow: {took:.1f}s"


def _launch_with_env(n, command, env):
    """launch_local with a custom base environment for the workers."""
    import unittest.mock as mock

    def patched_env(coordinator, num_procs, proc_id):
        e = dict(env)
        e.update({
            "MXNET_TPU_COORDINATOR": coordinator,
            "MXNET_TPU_NUM_PROCS": str(num_procs),
            "MXNET_TPU_PROC_ID": str(proc_id),
        })
        return e

    with mock.patch.object(launch, "worker_env", patched_env):
        return launch.launch_local(n, command, timeout=240)


def test_two_process_global_mesh_trainstep(tmp_path):
    """Round-4 verdict missing #2: 2 processes x 4 local CPU devices form
    ONE global 8-device mesh (jax.distributed -> jax.devices() global)
    and execute the dp x tp BERT TrainStep as a single GSPMD program
    spanning processes — with a cross-process sharded checkpoint
    save/restore. Loss must match the single-process 8-device run."""
    import json
    import subprocess

    _MESH_WORKER = os.path.join(os.path.dirname(__file__),
                                "dist_mesh_worker.py")
    out = str(tmp_path / "losses")
    env = dict(os.environ, DIST_MESH_OUT=out,
               DIST_MESH_CKPT=str(tmp_path / "ck"))
    rc = _launch_with_env(2, [sys.executable, _MESH_WORKER], env)
    assert rc == 0

    ranks = []
    for k in (0, 1):
        with open(f"{out}.{k}") as f:
            ranks.append(json.load(f))
    assert all(r["global_devices"] == 8 for r in ranks)
    # both processes observed the SAME global program
    assert np.allclose(ranks[0]["losses"], ranks[1]["losses"], atol=1e-6)

    # single-process reference on the same 8-device topology
    ref = subprocess.run(
        [sys.executable, "-c", f"""
import os, sys, json
os.environ["XLA_FLAGS"] = " ".join(
    [f for f in os.environ.get("XLA_FLAGS", "").split()
     if "host_platform_device_count" not in f]
    + ["--xla_force_host_platform_device_count=8"])
import jax
jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_default_matmul_precision", "highest")
sys.path.insert(0, {os.path.dirname(os.path.dirname(os.path.abspath(__file__)))!r})
sys.path.insert(0, {os.path.dirname(os.path.abspath(__file__))!r})
import numpy as np
from jax.sharding import Mesh
import dist_mesh_worker as W
mesh = Mesh(np.array(jax.devices()).reshape(4, 2), ("data", "model"))
step = W.build_step(mesh)
ids, labels = W.batch()
losses = [float(step(ids, labels).asscalar()) for _ in range(4)]
print("REF" + json.dumps(losses))
"""],
        capture_output=True, text=True, timeout=600,
        env={k: v for k, v in os.environ.items() if k != "PYTHONPATH"})
    assert ref.returncode == 0, ref.stderr[-2000:]
    ref_losses = json.loads(
        [ln for ln in ref.stdout.splitlines()
         if ln.startswith("REF")][0][3:])
    # cross-process collectives (gloo) vs single-process: same program,
    # reduction-order noise only
    np.testing.assert_allclose(ranks[0]["losses"], ref_losses,
                               rtol=1e-4, atol=1e-5)


def test_two_process_dist_async_bounded_staleness():
    """dist_async (round-5): pushes apply locally (replicas diverge —
    the stale-read contract), and the staleness bound triggers a
    parameter-averaging reconcile; workers assert the exact local,
    reconciled, and re-diverged values."""
    env = dict(os.environ, DIST_TEST_MODE="async",
               MXTPU_ASYNC_STALENESS_BOUND="2")
    rc = _launch_with_env(2, [sys.executable, _WORKER], env)
    assert rc == 0


def test_elastic_restart_resumes_from_checkpoint(tmp_path):
    """Failure recovery end-to-end (SURVEY §5): rank 1 dies at step 3 of
    a 2-process global-mesh training job; launch_elastic tears the job
    down, relaunches, the workers restore the latest COMMITTED sharded
    checkpoint and finish — and the final weights match an uninterrupted
    6-step run (the half-written step-4 checkpoint is correctly ignored
    by the commit protocol)."""
    import json as _json

    _ELASTIC = os.path.join(os.path.dirname(__file__), "elastic_worker.py")
    out = str(tmp_path / "final.npz")
    env_save = {k: os.environ.get(k)
                for k in ("ELASTIC_CKPT", "ELASTIC_OUT")}
    os.environ["ELASTIC_CKPT"] = str(tmp_path / "ck")
    os.environ["ELASTIC_OUT"] = out
    try:
        rc = launch.launch_elastic(2, [sys.executable, _ELASTIC],
                                   max_restarts=2, timeout=300)
    finally:
        for k, v in env_save.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v
    assert rc == 0
    got = np.load(out)

    # uninterrupted reference on the same 8-device topology, in process
    from tests.test_trainstep_checkpoint import (_make_step, _mesh, _run,
                                                 _params, TP_RULES)
    ref = _make_step(_mesh((4, 2), ("data", "model")), TP_RULES, seed=11)
    _run(ref, 6)
    want = _params(ref)
    for k in want:
        np.testing.assert_allclose(got[k], want[k], rtol=1e-4, atol=1e-5,
                                   err_msg=k)
