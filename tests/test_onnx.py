"""ONNX export/import round trips (reference: mx2onnx/onnx2mx converter
tests [unverified]). The vendored schema subset writes standard
wire-format ModelProto files; parity is import(export(sym)) == sym on
real evaluated graphs."""

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import nd, sym
from mxnet_tpu import onnx as mxonnx

rng = np.random.RandomState(7)


def _roundtrip(out_sym, params, feeds, tmp_path, rtol=1e-4, atol=1e-5):
    path = str(tmp_path / "m.onnx")
    mxonnx.export_model(out_sym, params,
                        input_shapes=[v.shape for v in feeds.values()],
                        onnx_file_path=path)
    sym2, args2, aux2 = mxonnx.import_model(path)
    kw = {k: nd.array(v) for k, v in params.items()}
    ref = out_sym.eval(**{k: nd.array(v) for k, v in feeds.items()}, **kw)
    got = sym2.eval(**{k: nd.array(v) for k, v in feeds.items()},
                    **args2, **aux2)
    ref = ref[0] if isinstance(ref, (list, tuple)) else ref
    got = got[0] if isinstance(got, (list, tuple)) else got
    np.testing.assert_allclose(got.asnumpy(), ref.asnumpy(), rtol=rtol,
                               atol=atol)
    return sym2, args2, aux2


def test_is_available():
    assert mxonnx.is_available()


def test_cnn_roundtrip(tmp_path):
    x = sym.var("data")
    w1, b1 = sym.var("conv_w"), sym.var("conv_b")
    g, be, mu, va = (sym.var(n) for n in ["bn_g", "bn_b", "bn_m", "bn_v"])
    fcw, fcb = sym.var("fc_w"), sym.var("fc_b")
    c = sym.Convolution(x, w1, b1, kernel=(3, 3), num_filter=4, pad=(1, 1))
    bn = sym.BatchNorm(c, g, be, mu, va, fix_gamma=False,
                       use_global_stats=True)[0]
    r = sym.Activation(bn, act_type="relu")
    p = sym.Pooling(r, kernel=(2, 2), stride=(2, 2), pool_type="max")
    fc = sym.FullyConnected(p, fcw, fcb, num_hidden=10)
    out = sym.softmax(fc)
    params = {
        "conv_w": rng.rand(4, 1, 3, 3).astype(np.float32),
        "conv_b": rng.rand(4).astype(np.float32),
        "bn_g": rng.rand(4).astype(np.float32) + 0.5,
        "bn_b": rng.rand(4).astype(np.float32),
        "bn_m": rng.rand(4).astype(np.float32),
        "bn_v": rng.rand(4).astype(np.float32) + 0.5,
        "fc_w": rng.rand(10, 64).astype(np.float32) * 0.1,
        "fc_b": rng.rand(10).astype(np.float32),
    }
    feeds = {"data": rng.rand(2, 1, 8, 8).astype(np.float32)}
    sym2, args2, aux2 = _roundtrip(out, params, feeds, tmp_path)
    # BN moving stats land in aux_params (reference contract)
    assert sorted(aux2) == ["bn_m", "bn_v"]
    assert len(args2) == 6


def test_elementwise_reduce_roundtrip(tmp_path):
    a, b = sym.var("a"), sym.var("b")
    out = sym.sum(sym.broadcast_mul(sym.Activation(a + b, act_type="tanh"),
                                    a), axis=1, keepdims=True)
    feeds = {"a": rng.rand(3, 4).astype(np.float32),
             "b": rng.rand(3, 4).astype(np.float32)}
    _roundtrip(out, {}, feeds, tmp_path)


def test_structural_ops_roundtrip(tmp_path):
    x = sym.var("x")
    y = sym.transpose(sym.Reshape(x, shape=(2, 6)), axes=(1, 0))
    z = sym.concat(y, y, dim=1)
    out = sym.clip(sym.slice_axis(z, axis=0, begin=1, end=5),
                   a_min=0.1, a_max=0.8)
    feeds = {"x": rng.rand(3, 4).astype(np.float32)}
    _roundtrip(out, {}, feeds, tmp_path)


def test_embedding_layernorm_roundtrip(tmp_path):
    ids = sym.var("ids")
    emb_w = sym.var("emb_w")
    g, be = sym.var("ln_g"), sym.var("ln_b")
    e = sym.Embedding(ids, emb_w, input_dim=20, output_dim=8)
    out = sym.LayerNorm(e, g, be, axis=-1)
    params = {"emb_w": rng.rand(20, 8).astype(np.float32),
              "ln_g": rng.rand(8).astype(np.float32) + 0.5,
              "ln_b": rng.rand(8).astype(np.float32)}
    feeds = {"ids": rng.randint(0, 20, (2, 5)).astype(np.float32)}
    _roundtrip(out, params, feeds, tmp_path)


def test_wire_format_parses_independently(tmp_path):
    """The written bytes parse through a FRESH protobuf parse of the
    vendored schema (i.e. the file is self-contained wire data, not a
    python-object artifact)."""
    from mxnet_tpu.onnx import onnx_subset_pb2 as P

    a = sym.var("a")
    out = sym.Activation(a, act_type="relu")
    path = str(tmp_path / "t.onnx")
    mxonnx.export_model(out, {}, input_shapes=[(2, 2)],
                        onnx_file_path=path)
    m = P.ModelProto()
    with open(path, "rb") as f:
        m.ParseFromString(f.read())
    assert m.producer_name == "mxnet_tpu"
    assert m.opset_import[0].version == 17
    assert m.graph.node[0].op_type == "Relu"
    # every node input is a graph input, initializer, or prior output
    known = {v.name for v in m.graph.input} | \
        {t.name for t in m.graph.initializer}
    for node in m.graph.node:
        for i in node.input:
            assert i in known, f"undefined input {i}"
        known.update(node.output)
    assert m.graph.output[0].name in known


def test_unsupported_op_errors_cleanly(tmp_path):
    x = sym.var("x")
    out = sym.gamma(x)  # no ONNX counterpart in the converter set
    with pytest.raises(mx.base.MXNetError, match="no converter"):
        mxonnx.export_model(out, {}, input_shapes=[(2,)],
                            onnx_file_path=str(tmp_path / "x.onnx"))


def test_gemm_flatten_true_roundtrip(tmp_path):
    x = sym.var("x")
    w, b = sym.var("w"), sym.var("b")
    out = sym.FullyConnected(x, w, b, num_hidden=3)  # flatten=True
    params = {"w": rng.rand(3, 24).astype(np.float32),
              "b": rng.rand(3).astype(np.float32)}
    feeds = {"x": rng.rand(2, 2, 3, 4).astype(np.float32)}
    _roundtrip(out, params, feeds, tmp_path)


def test_bn_fix_gamma_default_roundtrip(tmp_path):
    """Review round-4: fix_gamma=True (the default) must export gamma as
    ones, matching mx inference, whatever the stored param holds."""
    x = sym.var("data")
    g, be, mu, va = (sym.var(n) for n in ["g", "b2", "m", "v"])
    out = sym.BatchNorm(x, g, be, mu, va, use_global_stats=True)[0]
    params = {"g": rng.rand(3).astype(np.float32) + 2.0,  # != 1 on purpose
              "b2": rng.rand(3).astype(np.float32),
              "m": rng.rand(3).astype(np.float32),
              "v": rng.rand(3).astype(np.float32) + 0.5}
    feeds = {"data": rng.rand(2, 3, 4, 4).astype(np.float32)}
    _roundtrip(out, params, feeds, tmp_path)


def test_input_types_honored(tmp_path):
    from mxnet_tpu.onnx import onnx_subset_pb2 as P

    ids = sym.var("ids")
    w = sym.var("w")
    out = sym.Embedding(ids, w, input_dim=5, output_dim=2)
    path = str(tmp_path / "t.onnx")
    mxonnx.export_model(out, {"w": rng.rand(5, 2).astype(np.float32)},
                        input_shapes=[(3,)], input_types=[np.int32],
                        onnx_file_path=path)
    m = P.ModelProto()
    m.ParseFromString(open(path, "rb").read())
    assert m.graph.input[0].type.tensor_type.elem_type == P.TensorProto.INT32


def test_deep_chain_export(tmp_path):
    """Iterative DAG walk: 1500 chained ops must not hit the recursion
    limit."""
    x = sym.var("x")
    out = x
    for _ in range(1500):
        out = sym.relu(out)
    path = mxonnx.export_model(out, {}, input_shapes=[(2,)],
                               onnx_file_path=str(tmp_path / "d.onnx"))
    sym2, _, _ = mxonnx.import_model(path)
    got = sym2.eval(x=nd.array(np.asarray([-1.0, 2.0], np.float32)))
    got = got[0] if isinstance(got, (list, tuple)) else got
    np.testing.assert_allclose(got.asnumpy(), [0.0, 2.0])


def test_clip_one_sided_and_softmax_output_label_dropped(tmp_path):
    """Review round-4 batch 2: one-sided clip stays unbounded; loss-head
    label vars must not become required graph inputs; fix_gamma's dead
    gamma must not resurface as an arg_param."""
    from mxnet_tpu.onnx import onnx_subset_pb2 as P

    x = sym.var("x")
    out = sym.clip(x, a_max=0.5)  # a_min unbounded
    feeds = {"x": (rng.rand(2, 3).astype(np.float32) - 0.5) * 4}
    _roundtrip(out, {}, feeds, tmp_path)

    # SoftmaxOutput auto-creates a label var; export must not demand it
    fcw = sym.var("w")
    fc = sym.FullyConnected(sym.var("data"), fcw, num_hidden=4,
                            no_bias=True)
    head = sym.SoftmaxOutput(fc, sym.var("softmax_label"))
    path = str(tmp_path / "s.onnx")
    mxonnx.export_model(head, {"w": rng.rand(4, 6).astype(np.float32)},
                        input_shapes=[(2, 6)], onnx_file_path=path)
    m = P.ModelProto()
    m.ParseFromString(open(path, "rb").read())
    assert [v.name for v in m.graph.input] == ["data"]

    # fix_gamma: stale gamma initializer dropped from the file
    g2, be, mu, va = (sym.var(n) for n in ["g2", "b3", "m2", "v2"])
    bn = sym.BatchNorm(sym.var("d2"), g2, be, mu, va,
                       use_global_stats=True)[0]
    path2 = str(tmp_path / "bn.onnx")
    mxonnx.export_model(bn, {"g2": rng.rand(3).astype(np.float32) + 5,
                             "b3": rng.rand(3).astype(np.float32),
                             "m2": rng.rand(3).astype(np.float32),
                             "v2": rng.rand(3).astype(np.float32) + 0.5},
                        input_shapes=[(2, 3, 4, 4)],
                        onnx_file_path=path2)
    _, args2, _ = mxonnx.import_model(path2)
    assert "g2" not in args2


def test_no_bias_gemm_reimport(tmp_path):
    """Advisor round 4 (medium): the exporter emits Gemm beta=0.0 for
    no_bias FullyConnected; with only two inputs beta scales nothing and
    the importer must accept it. Full round trip, not export-only."""
    w = sym.var("w")
    fc = sym.FullyConnected(sym.var("data"), w, num_hidden=4, no_bias=True,
                            flatten=True)
    params = {"w": rng.rand(4, 6).astype(np.float32)}
    feeds = {"data": rng.rand(2, 6).astype(np.float32)}
    _roundtrip(fc, params, feeds, tmp_path)


def test_output_shape_not_scalar(tmp_path):
    """Advisor round 4 (low): shape=None must leave the shape field unset
    (unknown rank), not emit an empty TensorShapeProto (a rank-0 scalar
    declaration strict checkers reject)."""
    from mxnet_tpu.onnx import onnx_subset_pb2 as P

    fc = sym.FullyConnected(sym.var("data"), sym.var("w"), num_hidden=4,
                            no_bias=True)
    path = str(tmp_path / "o.onnx")
    mxonnx.export_model(fc, {"w": rng.rand(4, 6).astype(np.float32)},
                        input_shapes=[(2, 6)], onnx_file_path=path)
    m = P.ModelProto()
    m.ParseFromString(open(path, "rb").read())
    for v in m.graph.output:
        assert not v.type.tensor_type.HasField("shape")
