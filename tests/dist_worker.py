"""Worker body for the multi-process KVStoreDist test (run via
tools/launch.py local launcher; reference tested dist kvstore exactly this
way — localhost multi-process, ``tests/nightly/dist_sync_kvstore.py``
[unverified])."""

import os
import sys

import numpy as np

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

# pin the CPU platform through the config API — the session's TPU-tunnel
# plugin overrides the JAX_PLATFORMS env var (same trick as conftest.py)
import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")


def main():
    import mxnet_tpu as mx
    from mxnet_tpu import nd

    kv = mx.kv.create("dist_sync")
    rank, nworkers = kv.rank, kv.num_workers
    assert nworkers >= 2, f"expected >=2 workers, got {nworkers}"

    # init must be identical on all workers (reference requirement)
    kv.init("0", nd.zeros((4, 3)))
    kv.init("big", nd.ones((8,)) * 100)

    # each worker pushes rank+1; dist_sync must deliver sum over workers
    kv.push("0", nd.ones((4, 3)) * (rank + 1))
    out = nd.zeros((4, 3))
    kv.pull("0", out=out)
    expect = sum(r + 1 for r in range(nworkers))
    np.testing.assert_allclose(out.asnumpy(), np.full((4, 3), expect), rtol=1e-6)

    # barrier then second round on another key to check repeated sync
    kv.barrier()
    kv.push("big", nd.ones((8,)) * rank)
    out2 = nd.zeros((8,))
    kv.pull("big", out=out2)
    expect2 = sum(range(nworkers))
    np.testing.assert_allclose(out2.asnumpy(), np.full((8,), expect2), rtol=1e-6)

    print(f"worker {rank}/{nworkers}: dist kvstore OK")
    return 0


if __name__ == "__main__":
    sys.exit(main())
