"""Worker body for the multi-process KVStoreDist test (run via
tools/launch.py local launcher; reference tested dist kvstore exactly this
way — localhost multi-process, ``tests/nightly/dist_sync_kvstore.py``
[unverified])."""

import os
import sys

import numpy as np

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

# pin the CPU platform through the config API — the session's TPU-tunnel
# plugin overrides the JAX_PLATFORMS env var (same trick as conftest.py)
import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")


def main():
    import mxnet_tpu as mx
    from mxnet_tpu import nd

    mode = os.environ.get("DIST_TEST_MODE", "basic")
    kv = mx.kv.create("dist_sync")
    rank, nworkers = kv.rank, kv.num_workers
    assert nworkers >= 2, f"expected >=2 workers, got {nworkers}"

    if mode == "crash":
        # worker 1 dies mid-job; the launcher must propagate the failure
        # and terminate the others rather than leave them hung
        kv.init("0", nd.zeros((2,)))
        if rank == 1:
            print("worker 1: simulating crash")
            os._exit(17)
        import time as _t
        _t.sleep(30)  # would hang forever without launcher propagation
        return 0

    if mode == "full":
        # compression + updater-on-store over dist_sync (the reference's
        # nightly dist_sync_kvstore coverage at 4 workers)
        from mxnet_tpu import optimizer as opt

        kv.set_gradient_compression({"type": "2bit", "threshold": 0.5})
        kv.set_optimizer(opt.SGD(learning_rate=0.5))
        kv.init("w", nd.ones((6, 2)))
        for step in range(3):
            kv.push("w", nd.ones((6, 2)))  # grad 1 (above threshold)
            out = nd.zeros((6, 2))
            kv.pull("w", out=out)
        # updater-on-store arithmetic is fully deterministic here: the
        # 2-bit compressor quantizes grad 1.0 (>= threshold 0.5) to +0.5
        # per worker, the store sums nworkers * 0.5 = 2.0 and applies
        # w <- w - lr * 2.0 per step: 1 - 3 * 0.5 * 2 = -2 after 3 steps.
        # Every worker asserting the exact value IS the cross-worker
        # agreement check (a plain push/pull comparison would itself go
        # through the updater).
        expect_w = 1.0 - 3 * 0.5 * (0.5 * nworkers)
        np.testing.assert_allclose(out.asnumpy(),
                                   np.full((6, 2), expect_w), rtol=1e-5)
        # round-4 wire-byte check: the cross-host transfer must carry
        # PACKED 2-bit codes, not floats — 12 values -> 3 uint8 bytes
        # per worker (vs 48 f32 bytes uncompressed)
        assert getattr(kv, "last_push_wire_bytes", None) == 3, \
            f"wire bytes {getattr(kv, 'last_push_wire_bytes', None)} != 3"
        print(f"worker {rank}/{nworkers}: full-mode dist kvstore OK "
              f"(wire bytes/worker: {kv.last_push_wire_bytes})")
        return 0

    if mode == "async":
        # bounded-staleness dist_async (round-5): local apply, stale
        # reads, parameter-averaging reconcile at the bound
        from mxnet_tpu import optimizer as opt

        lr = 0.1
        bound = int(os.environ["MXTPU_ASYNC_STALENESS_BOUND"])
        assert bound == 2
        kv2 = mx.kv.create("dist_async")
        kv2.set_optimizer(opt.SGD(learning_rate=lr))
        kv2.init("w", nd.ones((3,)))
        g = rank + 1.0  # workers push DIFFERENT gradients

        # push 1: applied locally, NO reconcile -> replicas DIVERGE
        kv2.push("w", nd.ones((3,)) * g)
        out = nd.zeros((3,))
        kv2.pull("w", out=out)
        np.testing.assert_allclose(out.asnumpy(), 1.0 - lr * g, rtol=1e-5)

        # push 2 hits the bound: local apply THEN average across workers
        kv2.push("w", nd.ones((3,)) * g)
        kv2.pull("w", out=out)
        locals_ = [1.0 - lr * 2 * (r + 1) for r in range(nworkers)]
        want = sum(locals_) / nworkers
        np.testing.assert_allclose(out.asnumpy(), want, rtol=1e-5)

        # push 3: diverges again from the common reconciled base
        kv2.push("w", nd.ones((3,)) * g)
        kv2.pull("w", out=out)
        np.testing.assert_allclose(out.asnumpy(), want - lr * g, rtol=1e-5)
        print(f"worker {rank}/{nworkers}: dist_async bounded-staleness OK")
        return 0

    # init must be identical on all workers (reference requirement)
    kv.init("0", nd.zeros((4, 3)))
    kv.init("big", nd.ones((8,)) * 100)

    # each worker pushes rank+1; dist_sync must deliver sum over workers
    kv.push("0", nd.ones((4, 3)) * (rank + 1))
    out = nd.zeros((4, 3))
    kv.pull("0", out=out)
    expect = sum(r + 1 for r in range(nworkers))
    np.testing.assert_allclose(out.asnumpy(), np.full((4, 3), expect), rtol=1e-6)

    # barrier then second round on another key to check repeated sync
    kv.barrier()
    kv.push("big", nd.ones((8,)) * rank)
    out2 = nd.zeros((8,))
    kv.pull("big", out=out2)
    expect2 = sum(range(nworkers))
    np.testing.assert_allclose(out2.asnumpy(), np.full((8,), expect2), rtol=1e-6)

    print(f"worker {rank}/{nworkers}: dist kvstore OK")
    return 0


if __name__ == "__main__":
    sys.exit(main())
