"""HybridBlock.export -> SymbolBlock.imports round-trip over StableHLO.

Reference flow: ``HybridBlock.export`` writes model-symbol.json +
model-0000.params, ``SymbolBlock.imports`` reloads a runnable graph
(``python/mxnet/gluon/block.py`` [unverified]). Here the graph artifact is a
``jax.export`` StableHLO serialization.
"""

import os

import numpy as np

import mxnet_tpu as mx
from mxnet_tpu import nd, gluon
from mxnet_tpu.gluon import nn


def _model():
    net = nn.HybridSequential()
    net.add(nn.Dense(16, activation="relu"))
    net.add(nn.BatchNorm())
    net.add(nn.Dense(4))
    net.initialize()
    return net


class TestExportRoundTrip:
    def test_export_writes_all_artifacts(self, tmp_path):
        net = _model()
        net.hybridize()
        x = nd.array(np.random.RandomState(0).rand(2, 8).astype(np.float32))
        net(x)
        prefix = str(tmp_path / "model")
        sym_file, params_file = net.export(prefix)
        assert os.path.exists(sym_file)
        assert os.path.exists(params_file)
        assert os.path.exists(prefix + "-symbol.stablehlo")

    def test_roundtrip_same_outputs(self, tmp_path):
        net = _model()
        net.hybridize()
        x = nd.array(np.random.RandomState(1).rand(3, 8).astype(np.float32))
        ref = net(x).asnumpy()
        prefix = str(tmp_path / "model")
        net.export(prefix)

        blk = gluon.SymbolBlock.imports(prefix + "-symbol.json", ["data"])
        out = blk(x)
        np.testing.assert_allclose(out.asnumpy(), ref, rtol=1e-5, atol=1e-6)

    def test_roundtrip_multi_output(self, tmp_path):
        class TwoHead(gluon.HybridBlock):
            def __init__(self):
                super().__init__()
                with self.name_scope():
                    self.a = nn.Dense(3)
                    self.b = nn.Dense(5)

            def hybrid_forward(self, F, x):
                return self.a(x), self.b(x)

        net = TwoHead()
        net.initialize()
        net.hybridize()
        x = nd.array(np.random.RandomState(2).rand(2, 6).astype(np.float32))
        r1, r2 = net(x)
        prefix = str(tmp_path / "twohead")
        net.export(prefix)
        blk = gluon.SymbolBlock.imports(prefix + "-symbol.json", ["data"])
        o1, o2 = blk(x)
        np.testing.assert_allclose(o1.asnumpy(), r1.asnumpy(), rtol=1e-5)
        np.testing.assert_allclose(o2.asnumpy(), r2.asnumpy(), rtol=1e-5)

    def test_import_params_only_fallback(self, tmp_path):
        """Manifest without the stablehlo artifact still loads params."""
        net = _model()
        net.hybridize()
        x = nd.array(np.random.RandomState(3).rand(2, 8).astype(np.float32))
        net(x)
        prefix = str(tmp_path / "model")
        net.export(prefix)
        os.remove(prefix + "-symbol.stablehlo")
        blk = gluon.SymbolBlock.imports(prefix + "-symbol.json", ["data"])
        assert blk._loaded  # params present
        try:
            blk(x)
            assert False, "expected MXNetError"
        except mx.base.MXNetError:
            pass


class TestExportModes:
    def test_export_requires_predict_trace(self, tmp_path):
        from mxnet_tpu import autograd

        net = _model()
        net.hybridize()
        x = nd.array(np.random.RandomState(4).rand(2, 8).astype(np.float32))
        with autograd.record():
            net(x)
        try:
            net.export(str(tmp_path / "m"))
            assert False, "expected MXNetError"
        except mx.base.MXNetError as e:
            assert "predict-mode" in str(e)

    def test_export_uses_latest_shapes(self, tmp_path):
        net = _model()
        net.hybridize()
        net(nd.array(np.random.RandomState(5).rand(2, 8).astype(np.float32)))
        x = nd.array(np.random.RandomState(6).rand(32, 8).astype(np.float32))
        ref = net(x).asnumpy()  # same treedef, larger batch
        prefix = str(tmp_path / "m")
        net.export(prefix)
        blk = gluon.SymbolBlock.imports(prefix + "-symbol.json", ["data"])
        np.testing.assert_allclose(blk(x).asnumpy(), ref, rtol=1e-5, atol=1e-6)

    def test_import_relocated_artifacts(self, tmp_path):
        import shutil

        net = _model()
        net.hybridize()
        x = nd.array(np.random.RandomState(7).rand(2, 8).astype(np.float32))
        ref = net(x).asnumpy()
        src = tmp_path / "src"
        dst = tmp_path / "dst"
        src.mkdir()
        dst.mkdir()
        net.export(str(src / "m"))
        for f in src.iterdir():
            shutil.copy(f, dst / f.name)
        shutil.rmtree(src)  # the originals are gone: only dst may be read
        blk = gluon.SymbolBlock.imports(str(dst / "m-symbol.json"), ["data"])
        np.testing.assert_allclose(blk(x).asnumpy(), ref, rtol=1e-5, atol=1e-6)

    def test_import_rejects_wrong_arity(self, tmp_path):
        net = _model()
        net.hybridize()
        x = nd.array(np.random.RandomState(8).rand(2, 8).astype(np.float32))
        net(x)
        prefix = str(tmp_path / "m")
        net.export(prefix)
        blk = gluon.SymbolBlock.imports(prefix + "-symbol.json", ["data"])
        try:
            blk(x, x)
            assert False, "expected MXNetError"
        except mx.base.MXNetError as e:
            assert "input array" in str(e)
