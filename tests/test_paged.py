"""Continuous batching over a paged KV cache (ISSUE 8 tentpole).

Contracts under test:

- **Page-pool invariants**: alloc/free round-trips leave the free list
  EXACT (free + owned partition the pool), no page is ever aliased by two
  live requests, the trash page is never allocated.
- **Paged read parity**: at equal logical capacity the gather-through-
  the-table attention read is BIT-identical to the dense
  ``(max_len, B, H, D)`` path at fp32 — layer level and end-to-end
  (``ContinuousBatcher`` greedy tokens == ``InferStep.decode_n``).
- **Iteration-level scheduling**: retired rows free their slots/pages
  mid-stream, the warmed program menu holds zero steady-state
  recompiles, tokens stream per iteration, deadlines retire rows
  mid-decode, pool exhaustion preempts (and restarts) rather than
  wedging, admission control rejects with ``Backpressure``.
- **Self-healing interop**: a replica crash with paged requests in
  flight frees its pages and fails over through the Router (chaos
  marker); a hot weight swap lands between iterations with zero lost
  requests.
"""

import os
import time

import numpy as np
import pytest

import jax
import jax.numpy as jnp

import mxnet_tpu as mx
from mxnet_tpu import nd
from mxnet_tpu.base import MXNetError
from mxnet_tpu.gluon.model_zoo.transformer import TransformerModel
from mxnet_tpu.gluon.nn import MultiHeadAttention
from mxnet_tpu.parallel import InferStep
from mxnet_tpu.serving import (Backpressure, ContinuousBatcher,
                               DeadlineExceeded, DynamicBatcher, PagePool,
                               Replica, Router, faults, make_batcher)
from mxnet_tpu.serving import pages as pages_mod


def _make_transformer(V=61, units=16, layers=2, seed=0, **kw):
    np.random.seed(seed)
    net = TransformerModel(src_vocab=V, tgt_vocab=V, units=units,
                           hidden_size=2 * units, num_layers=layers,
                           num_heads=2, max_length=64, dropout=0.0, **kw)
    net.initialize(mx.initializer.Xavier())
    net._probe_shapes(nd.zeros((2, 8), dtype="int32"),
                      nd.zeros((2, 8), dtype="int32"))
    return net


@pytest.fixture(scope="module")
def tmodel():
    return _make_transformer()


# ------------------------------------------------------------- page pool
class TestPagePool:
    def test_alloc_free_round_trip_exact(self):
        pool = PagePool(num_pages=8, page_size=4, slots=3,
                        pages_per_slot=3)
        assert pool.free_pages == 8 and pool.pages_in_use == 0
        assert pool.alloc(0, 2) and pool.alloc(1, 3) and pool.alloc(2, 1)
        assert pool.pages_in_use == 6 and pool.free_pages == 2
        pool.check_invariants({0, 1, 2})
        assert pool.release(1) == 3
        assert pool.free_pages == 5
        pool.check_invariants({0, 2})
        assert pool.release(0) == 2 and pool.release(2) == 1
        assert pool.free_pages == 8 and pool.pages_in_use == 0
        pool.check_invariants(set())
        # table fully pointed back at trash
        assert (pool.table == pages_mod.TRASH_PAGE).all()

    def test_no_page_aliased_by_two_slots(self):
        pool = PagePool(num_pages=6, page_size=2, slots=3,
                        pages_per_slot=3)
        pool.alloc(0, 3)
        pool.alloc(1, 3)
        owned = set(pool.owned(0)) | set(pool.owned(1))
        assert len(owned) == 6  # disjoint
        assert pages_mod.TRASH_PAGE not in owned
        assert not pool.alloc(2, 1)  # exhausted: state unchanged
        assert pool.owned(2) == ()
        pool.check_invariants({0, 1})
        # freed pages are reusable, still exclusive
        pool.release(0)
        assert pool.alloc(2, 2)
        assert not set(pool.owned(2)) & set(pool.owned(1))
        pool.check_invariants({1, 2})

    def test_ensure_grows_on_demand(self):
        pool = PagePool(num_pages=4, page_size=4, slots=1,
                        pages_per_slot=4)
        pool.alloc(0, 1)
        assert pool.ensure(0, 4)  # fits the first page
        assert pool.pages_in_use == 1
        assert pool.ensure(0, 5)  # crosses the boundary
        assert pool.pages_in_use == 2
        assert not pool.ensure(0, 17)  # table row can hold only 4 pages
        pool.check_invariants({0})

    def test_fragmentation(self):
        pool = PagePool(num_pages=4, page_size=8, slots=2,
                        pages_per_slot=2)
        assert pool.fragmentation([0, 0]) == 0.0
        pool.alloc(0, 1)
        assert pool.fragmentation([2, 0]) == pytest.approx(0.75)

    def test_env_defaults(self, monkeypatch):
        monkeypatch.setenv("MXTPU_PAGE_SIZE", "32")
        monkeypatch.setenv("MXTPU_PAGES", "7")
        monkeypatch.setenv("MXTPU_ADMIT_MAX_QUEUE", "5")
        assert pages_mod.page_size_default() == 32
        assert pages_mod.num_pages_default(4, 10) == 7
        assert pages_mod.admit_max_queue() == 5
        monkeypatch.delenv("MXTPU_PAGES")
        assert pages_mod.num_pages_default(4, 10) == 40  # full provision


# ------------------------------------------------------- bit-parity reads
class TestPagedParity:
    def test_paged_step_bitwise_vs_dense_step(self):
        """Layer level: gather-through-table attention == the dense
        (max_len, B, H, D) cache path, bit for bit, at equal capacity."""
        mha = MultiHeadAttention(16, 2, dropout=0.0, causal=True)
        mha.initialize()
        B, S, cap = 2, 8, 8  # capacity 8 = 2 pages x 4
        x = nd.array(np.random.RandomState(1).randn(B, S, 16)
                     .astype(np.float32))
        _, k, v = mha.prefill(x[:, :1])
        kc, vc = mha.init_cache(B, cap)
        kc = jax.lax.dynamic_update_slice(kc, jnp.swapaxes(k, 0, 1),
                                          (0, 0, 0, 0))
        vc = jax.lax.dynamic_update_slice(vc, jnp.swapaxes(v, 0, 1),
                                          (0, 0, 0, 0))
        kp, vp = mha.init_page_pool(5, 4)
        table = jnp.asarray(np.array([[1, 2], [3, 4]], np.int32))
        kp = kp.at[table[:, 0], 0].set(k[:, 0])
        vp = vp.at[table[:, 0], 0].set(v[:, 0])
        for p in range(1, S):
            od, kc, vc = mha.step(x[:, p:p + 1], kc, vc, jnp.int32(p))
            op, kp, vp = mha.paged_step(
                x[:, p:p + 1], kp, vp, table,
                jnp.full((B,), p, jnp.int32), jnp.ones((B,), bool))
            np.testing.assert_array_equal(od.asnumpy(), op.asnumpy(),
                                          err_msg=f"position {p}")
        # dense cache contents == gathered view, bit for bit
        np.testing.assert_array_equal(
            np.asarray(jnp.swapaxes(kc, 0, 1)),
            np.asarray(kp[table].reshape(B, cap, 2, 8)))

    def test_inactive_rows_write_trash_only(self):
        """A masked (inactive) row must never touch an allocated page —
        its write lands in the reserved trash page 0."""
        mha = MultiHeadAttention(16, 2, dropout=0.0, causal=True)
        mha.initialize()
        kp, vp = mha.init_page_pool(3, 4)
        table = jnp.asarray(np.array([[1], [2]], np.int32))
        x = nd.array(np.random.RandomState(0).randn(2, 1, 16)
                     .astype(np.float32))
        before_k = np.asarray(kp[1:])
        _, kp2, _ = mha.paged_step(x, kp, vp, table,
                                   jnp.zeros((2,), jnp.int32),
                                   jnp.zeros((2,), bool))
        np.testing.assert_array_equal(before_k, np.asarray(kp2[1:]))
        assert np.abs(np.asarray(kp2[0])).sum() > 0  # trash took the write

    def test_continuous_greedy_bitwise_vs_decode_n(self, tmodel):
        """End to end: every request's greedy tokens through the paged
        scheduler == the PR-5 dense engine, per request (single-bucket
        menu => identical program shapes => bitwise logits)."""
        eng = InferStep(tmodel, max_len=24)
        rng = np.random.RandomState(3)
        B, Ls, T = 3, 8, 6
        src = rng.randint(3, 61, (B, Ls)).astype(np.int32)
        vl = np.array([4, 7, 8], np.int32)
        toks_d, lens_d = eng.decode_n(src, vl, max_new_tokens=T)
        toks_d, lens_d = toks_d.asnumpy(), lens_d.asnumpy()
        bat = ContinuousBatcher(eng, bucket_keys=(Ls,), slots=2,
                                max_new_tokens=T, page_size=4,
                                iter_tokens=2, warmup=True)
        try:
            futs = [bat.submit(src[i, :vl[i]]) for i in range(B)]
            got = [f.result(timeout=120) for f in futs]
        finally:
            bat.stop()
        for i in range(B):
            assert got[i] == toks_d[i, :int(lens_d[i])].tolist(), f"row {i}"
        assert eng.compile_guard.steady_state_recompiles == 0
        # every page returned: free list exact after full drain
        assert bat.pool.free_pages == bat.pool.num_pages
        bat.pool.check_invariants(set())


# ------------------------------------------- Pallas kernels & speculation
class TestFlashPagedKernel:
    """ISSUE 14: the Pallas paged flash kernels (interpret mode on the
    CPU rig) against their dense references, and speculative decoding
    through the batcher against the dense engine."""

    def _pools(self, rng, num_pages=5, ps=4, H=2, D=8):
        kp = jnp.asarray(rng.randn(num_pages, ps, H, D).astype(np.float32))
        vp = jnp.asarray(rng.randn(num_pages, ps, H, D).astype(np.float32))
        return kp, vp

    def test_decode_kernel_matches_reference(self):
        from mxnet_tpu.ops.pallas import paged_flash_attention as pfa
        rng = np.random.RandomState(0)
        kp, vp = self._pools(rng)
        q = jnp.asarray(rng.randn(2, 2, 8).astype(np.float32))
        table = jnp.asarray(np.array([[1, 2], [3, 4]], np.int32))
        pos = jnp.asarray(np.array([2, 6], np.int32))  # mid-page tails
        got = pfa.paged_decode_attention(q, kp, vp, table, pos,
                                         sm_scale=8 ** -0.5)
        want = pfa.paged_decode_reference(q, kp, vp, table, pos,
                                          sm_scale=8 ** -0.5)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   rtol=1e-5, atol=1e-5)

    def test_window_kernel_matches_reference_offset_and_padding(self):
        from mxnet_tpu.ops.pallas import paged_flash_attention as pfa
        rng = np.random.RandomState(1)
        kp, vp = self._pools(rng)
        S = 3
        q = jnp.asarray(rng.randn(2, S, 2, 8).astype(np.float32))
        table = jnp.asarray(np.array([[1, 2], [3, 4]], np.int32))
        off = jnp.asarray(np.array([0, 5], np.int32))  # suffix replay row
        vl = jnp.asarray(np.array([3, 2], np.int32))   # row 1 pads query 2
        got = pfa.paged_window_attention(q, kp, vp, table, off, vl,
                                         sm_scale=8 ** -0.5)
        want = pfa.paged_window_reference(q, kp, vp, table, off, vl,
                                          sm_scale=8 ** -0.5)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   rtol=1e-5, atol=1e-5)
        # padded query rows finalize to exact zero in both
        assert np.abs(np.asarray(got)[1, 2]).sum() == 0.0

    def test_forced_kernel_paged_step_matches_fallback(self, monkeypatch):
        """Layer level: ``paged_step`` with the kernel forced (interpret
        mode here) == the dense gather fallback to fp tolerance."""
        mha = MultiHeadAttention(16, 2, dropout=0.0, causal=True)
        mha.initialize()
        rng = np.random.RandomState(2)
        table = jnp.asarray(np.array([[1, 2], [3, 4]], np.int32))
        x = nd.array(rng.randn(2, 1, 16).astype(np.float32))
        x0 = nd.array(rng.randn(2, 1, 16).astype(np.float32))
        outs = {}
        for mode in ("0", "force"):
            monkeypatch.setenv("MXTPU_FLASH_PAGED", mode)
            kp, vp = mha.init_page_pool(5, 4)
            _, k, v = mha.prefill(x0)
            kp = kp.at[table[:, 0], 0].set(k[:, 0])
            vp = vp.at[table[:, 0], 0].set(v[:, 0])
            o, _, _ = mha.paged_step(x, kp, vp, table,
                                     jnp.ones((2,), jnp.int32),
                                     jnp.ones((2,), bool))
            outs[mode] = o.asnumpy()
        np.testing.assert_allclose(outs["force"], outs["0"],
                                   rtol=1e-5, atol=1e-5)

    def test_kernel_active_rows_isolated_from_trash_page(self, monkeypatch):
        """Inactive rows park their table on trash page 0; the kernel's
        in-place page walk must give active rows identical output no
        matter what garbage page 0 holds."""
        monkeypatch.setenv("MXTPU_FLASH_PAGED", "force")
        mha = MultiHeadAttention(16, 2, dropout=0.0, causal=True)
        mha.initialize()
        rng = np.random.RandomState(3)
        table = jnp.asarray(np.array([[1, 2], [0, 0]], np.int32))
        active = jnp.asarray(np.array([True, False]))
        x = nd.array(rng.randn(2, 1, 16).astype(np.float32))
        kp, vp = mha.init_page_pool(5, 4)
        _, k, v = mha.prefill(nd.array(
            rng.randn(2, 1, 16).astype(np.float32)))
        kp = kp.at[table[:, 0], 0].set(k[:, 0])
        vp = vp.at[table[:, 0], 0].set(v[:, 0])
        o_clean, kp2, _ = mha.paged_step(x, kp, vp, table,
                                         jnp.ones((2,), jnp.int32), active)
        # poison the trash page with huge values and replay
        kp_bad = kp.at[0].set(1e9)
        vp_bad = vp.at[0].set(-1e9)
        o_bad, _, _ = mha.paged_step(x, kp_bad, vp_bad, table,
                                     jnp.ones((2,), jnp.int32), active)
        np.testing.assert_array_equal(o_clean.asnumpy()[0],
                                      o_bad.asnumpy()[0])
        # the inactive row's write landed on trash page 0 only: every
        # page beyond the active row's current one is untouched
        np.testing.assert_array_equal(np.asarray(kp[2:]),
                                      np.asarray(kp2[2:]))

    def test_spec_batcher_bitwise_vs_decode_n(self, tmodel):
        """End to end: speculative rounds through the scheduler emit the
        SAME greedy tokens as the dense engine — with an oracle draft
        (weight copy, full acceptance) AND a garbage draft (near-zero
        acceptance): the acceptance rule only sets the burst length."""
        rng = np.random.RandomState(5)
        B, Ls, T = 3, 8, 6
        src = rng.randint(3, 61, (B, Ls)).astype(np.int32)
        vl = np.array([4, 7, 8], np.int32)
        ref_eng = InferStep(tmodel, max_len=24)
        toks_d, lens_d = ref_eng.decode_n(src, vl, max_new_tokens=T)
        toks_d, lens_d = toks_d.asnumpy(), lens_d.asnumpy()
        ref = [toks_d[i, :int(lens_d[i])].tolist() for i in range(B)]

        oracle = _make_transformer(seed=0)   # same seed = same weights
        tp = {n.split("_", 1)[1]: p
              for n, p in tmodel.collect_params().items()}
        for name, p in oracle.collect_params().items():
            p.set_data(nd.NDArray(tp[name.split("_", 1)[1]]._data.data))
        garbage = _make_transformer(seed=7)
        for draft, tag in ((oracle, "oracle"), (garbage, "garbage")):
            eng = InferStep(tmodel, max_len=24)
            eng.attach_draft(draft)
            bat = ContinuousBatcher(eng, bucket_keys=(Ls,), slots=2,
                                    max_new_tokens=T, page_size=4,
                                    iter_tokens=2, spec_k=3, warmup=True)
            assert bat._spec_on
            try:
                futs = [bat.submit(src[i, :vl[i]]) for i in range(B)]
                got = [f.result(timeout=120) for f in futs]
            finally:
                bat.stop()
            assert got == ref, tag
            assert eng.compile_guard.steady_state_recompiles == 0, tag
            assert bat.pool.free_pages == bat.pool.num_pages, tag
            bat.pool.check_invariants(set())


# ------------------------------------------------- scheduler behaviour
class TestContinuousBatcher:
    def _batcher(self, tmodel, **kw):
        eng = InferStep(tmodel, max_len=24)
        cfg = dict(bucket_keys=(8,), slots=2, max_new_tokens=6,
                   page_size=4, iter_tokens=2, warmup=True)
        cfg.update(kw)
        return ContinuousBatcher(eng, **cfg), eng

    def test_requires_paged_protocol(self):
        from mxnet_tpu.gluon.model_zoo.bert import BERTModel

        bert = BERTModel(vocab_size=31, units=16, hidden_size=32,
                         num_layers=1, num_heads=2, max_length=32,
                         dropout=0.0)
        bert.initialize()
        bert._probe_shapes(nd.zeros((2, 8), dtype="int32"))
        with pytest.raises(MXNetError):
            ContinuousBatcher(InferStep(bert), bucket_keys=(8,))

    def test_pool_too_small_for_one_request_raises(self, tmodel):
        eng = InferStep(tmodel, max_len=24)
        with pytest.raises(MXNetError, match="pages"):
            ContinuousBatcher(eng, bucket_keys=(8,), slots=1,
                              max_new_tokens=32, page_size=2, num_pages=3)

    def test_streaming_tokens_iter(self, tmodel):
        bat, _ = self._batcher(tmodel)
        try:
            fut = bat.submit(np.array([5, 6, 7], np.int32))
            chunks = list(fut.tokens_iter(timeout=60))
        finally:
            bat.stop()
        flat = [t for c in chunks for t in c]
        assert flat == fut.result()
        # per-iteration granularity: more than one chunk for 6 tokens at
        # iter_tokens=2 (first from admission, the rest per iteration)
        assert len(chunks) >= 2
        assert fut.first_token_at is not None
        assert fut.first_token_at >= fut.enqueued_at

    def test_slot_reuse_keeps_occupancy(self, tmodel):
        """More requests than slots: retired rows hand their slots to
        queued requests mid-stream (iterations << what a fixed batcher
        would need) and the pool ends exact."""
        bat, eng = self._batcher(tmodel, slots=2)
        rng = np.random.RandomState(0)
        try:
            futs = [bat.submit(rng.randint(3, 61, (5,)).astype(np.int32),
                               max_new_tokens=2 + (i % 5))
                    for i in range(8)]
            for f in futs:
                f.result(timeout=120)
        finally:
            bat.stop()
        assert bat.stats["retired"] == 8
        assert bat.stats["admitted"] == 8
        assert bat.pool.free_pages == bat.pool.num_pages
        assert eng.compile_guard.steady_state_recompiles == 0

    def test_deadline_retires_mid_decode(self, tmodel):
        """A deadline passing DURING decode retires the row at the next
        iteration boundary (DeadlineExceeded), frees its pages, and the
        other slots keep decoding."""
        bat, _ = self._batcher(tmodel, max_new_tokens=32, page_size=4,
                               iter_tokens=1)
        try:
            doomed = bat.submit([5, 6, 7], deadline_ms=1.0)
            ok = bat.submit([8, 9, 10], max_new_tokens=4)
            with pytest.raises(DeadlineExceeded):
                doomed.result(timeout=60)
            assert len(ok.result(timeout=60)) <= 4
        finally:
            bat.stop()
        assert bat.pool.free_pages == bat.pool.num_pages

    def test_preemption_restarts_and_completes(self, tmodel):
        """Pool oversubscription: the youngest row is preempted (pages
        freed, request restarted) and every request still completes with
        the full greedy result."""
        eng = InferStep(tmodel, max_len=24)
        bat = ContinuousBatcher(eng, bucket_keys=(8,), slots=2,
                                max_new_tokens=8, page_size=2,
                                num_pages=5, iter_tokens=2, warmup=True)
        rng = np.random.RandomState(3)
        try:
            futs = [bat.submit(rng.randint(3, 61, (6,)).astype(np.int32),
                               max_new_tokens=8) for _ in range(3)]
            got = [f.result(timeout=120) for f in futs]
        finally:
            bat.stop()
        assert all(len(g) == 8 for g in got)
        assert bat.stats["preempted"] >= 1
        assert bat.pool.free_pages == bat.pool.num_pages
        bat.pool.check_invariants(set())

    def test_backpressure_rejects_at_submit(self, tmodel):
        bat, _ = self._batcher(tmodel, admit_max_queue=0)
        try:
            fut = bat.submit([5, 6, 7])
            assert isinstance(fut.exception(), Backpressure)
            assert bat.stats["rejected"] == 1
        finally:
            bat.stop()

    def test_free_page_watermark_defers_admission(self, tmodel):
        """With a watermark covering the whole pool, admission defers
        while pages are in use (the queued request waits its turn instead
        of fragmenting the pool)."""
        eng = InferStep(tmodel, max_len=24)
        bat = ContinuousBatcher(eng, bucket_keys=(8,), slots=2,
                                max_new_tokens=4, page_size=2,
                                num_pages=6, iter_tokens=1,
                                admit_free_pages=3, warmup=True)
        rng = np.random.RandomState(1)
        try:
            futs = [bat.submit(rng.randint(3, 61, (5,)).astype(np.int32))
                    for _ in range(4)]
            for f in futs:
                assert len(f.result(timeout=120)) <= 4
        finally:
            bat.stop()
        assert bat.pool.free_pages == bat.pool.num_pages

    def test_submit_after_stop_fails_fast(self, tmodel):
        bat, _ = self._batcher(tmodel)
        bat.stop()
        fut = bat.submit([3, 4, 5])
        assert isinstance(fut.exception(), RuntimeError)
        assert "not accepting" in str(fut.exception())
        assert bat.pool.free_pages == bat.pool.num_pages

    def test_dispatch_error_fails_slots_not_thread(self, tmodel):
        """An engine error mid-iteration fails the in-flight futures,
        rebuilds the pools, and the scheduler keeps serving."""
        bat, _ = self._batcher(tmodel)
        try:
            faults.inject("batcher.dispatch", times=1, after=1)
            fut = bat.submit([3, 4, 5], max_new_tokens=6)
            with pytest.raises(faults.FaultInjected):
                fut.result(timeout=60)
            assert bat.healthy
            ok = bat.submit([6, 7, 8], max_new_tokens=2)
            assert len(ok.result(timeout=60)) <= 2
        finally:
            faults.clear()
            bat.stop()
        assert bat.pool.free_pages == bat.pool.num_pages

    def test_telemetry_fields(self, tmodel):
        mx.telemetry.reset()
        mx.telemetry.enable()
        try:
            bat, _ = self._batcher(tmodel)
            fut = bat.submit([5, 6, 7])
            fut.result(timeout=60)
            bat.stop()
            rep = mx.telemetry.report()
            assert rep["infer_ttft_ms_p50"] is not None
            assert rep["infer_pages_in_use"] is not None
            assert rep["infer_page_fragmentation"] is not None
            assert rep["infer_admitted_per_iter_p50"] is not None
            assert rep["infer_rejected_backpressure"] == 0
            assert rep["infer_requests"] >= 1
        finally:
            mx.telemetry.reset()

    def test_sustained_occupancy_stat(self, tmodel):
        bat, _ = self._batcher(tmodel)
        rng = np.random.RandomState(5)
        try:
            futs = [bat.submit(rng.randint(3, 61, (5,)).astype(np.int32))
                    for _ in range(6)]
            for f in futs:
                f.result(timeout=120)
        finally:
            bat.stop()
        assert 0.0 < bat.sustained_occupancy <= 1.0
        assert bat.stats["iterations"] > 0


# ------------------------------------------------------- API routing
class TestRouting:
    def test_make_batcher_default_and_fixed(self, tmodel, monkeypatch):
        eng = InferStep(tmodel, max_len=24)
        bat = make_batcher(eng, bucket_keys=(8,), slots=2,
                           max_new_tokens=4, start=False)
        assert isinstance(bat, ContinuousBatcher)
        monkeypatch.setenv("MXTPU_BATCHER", "fixed")
        bat2 = make_batcher(eng, bucket_keys=(8,), slots=2,
                            max_new_tokens=4, start=False)
        assert type(bat2) is DynamicBatcher

    def test_generate_routes_through_continuous(self, tmodel, monkeypatch):
        src = np.random.RandomState(2).randint(3, 61, (2, 7)) \
            .astype(np.int32)
        toks_c, lens_c = tmodel.generate(src, max_new_tokens=4, max_len=24)
        assert getattr(tmodel, "_batchers", None), \
            "greedy generate must route through the ContinuousBatcher"
        monkeypatch.setenv("MXTPU_BATCHER", "fixed")
        toks_d, lens_d = tmodel.generate(src, max_new_tokens=4, max_len=24)
        np.testing.assert_array_equal(toks_c.asnumpy(), toks_d.asnumpy())
        np.testing.assert_array_equal(lens_c.asnumpy(), lens_d.asnumpy())

    def test_generate_sampling_seed_stays_direct(self, tmodel):
        src = np.random.RandomState(2).randint(3, 61, (2, 7)) \
            .astype(np.int32)
        before = dict(getattr(tmodel, "_batchers", {}) or {})
        a, _ = tmodel.generate(src, max_new_tokens=3, max_len=24,
                               method="top_k", top_k=4, seed=9)
        b, _ = tmodel.generate(src, max_new_tokens=3, max_len=24,
                               method="top_k", top_k=4, seed=9)
        np.testing.assert_array_equal(a.asnumpy(), b.asnumpy())
        after = dict(getattr(tmodel, "_batchers", {}) or {})
        assert before == after  # no batcher built for seeded sampling

    def test_estimator_predict_through_batcher(self, tmodel):
        from mxnet_tpu.gluon.contrib.estimator import Estimator
        from mxnet_tpu.gluon.loss import SoftmaxCrossEntropyLoss

        eng = InferStep(tmodel, max_len=24)
        bat = ContinuousBatcher(eng, bucket_keys=(8,), slots=2,
                                max_new_tokens=4, page_size=4,
                                iter_tokens=2, warmup=True)
        rng = np.random.RandomState(3)
        src = rng.randint(3, 61, (2, 7)).astype(np.int32)
        vl = np.array([5, 7], np.int32)
        est = Estimator(tmodel, SoftmaxCrossEntropyLoss())
        try:
            outs = est.predict([(src, vl)], engine=bat)
        finally:
            bat.stop()
        assert len(outs) == 1
        toks, lengths = outs[0]
        assert toks.shape == (2, 4) and lengths.shape == (2,)
        ref_t, ref_l = eng.decode_n(src, vl, max_new_tokens=4)
        np.testing.assert_array_equal(toks.asnumpy(), ref_t.asnumpy())


# --------------------------------------------------- self-healing interop
class TestPagedResilience:
    @pytest.mark.chaos
    def test_replica_crash_frees_pages_and_fails_over(self, tmodel):
        """Kill one replica's scheduler mid-decode: its pages return to
        the free list, its in-flight/queued requests fail over through
        the Router, and every future still resolves."""

        def make_replica(name):
            eng = InferStep(tmodel, max_len=24)
            bat = ContinuousBatcher(eng, bucket_keys=(8,), slots=2,
                                    max_new_tokens=8, page_size=4,
                                    iter_tokens=1, warmup=True, name=name)
            return Replica(name, bat)

        mx.telemetry.reset()
        r0, r1 = make_replica("pg-r0"), make_replica("pg-r1")
        router = Router([r0, r1], retry_backoff_s=0.01,
                        health_interval_s=0.02)
        rng = np.random.RandomState(7)
        # let r1 run a couple of scheduler iterations, then die mid-decode
        faults.inject("batcher.thread", times=1, after=3, match="pg-r1")
        try:
            futs = [router.submit(rng.randint(3, 61, (6,))
                                  .astype(np.int32), max_new_tokens=8)
                    for _ in range(10)]
            results = [f.result(timeout=120) for f in futs]
        finally:
            faults.clear()
            router.stop()
        assert all(len(r) == 8 for r in results)
        reg = mx.telemetry.registry()
        assert reg.counter("serve/failovers").value >= 1
        # the dead replica's pool is exact again: eviction freed its pages
        for rep in (r0, r1):
            assert rep.batcher.pool.free_pages == rep.batcher.pool.num_pages
            rep.batcher.pool.check_invariants(set())
        mx.telemetry.reset()

    def test_admission_failure_poisons_not_kills(self, tmodel):
        """ISSUE 15 regression (mxlint resource-leak.leak-on-raise): an
        exception during admission — a partial ``_stage_slot`` that
        already adopted prefix pages — must hit the poison path (fail
        slots, reset the pool, keep the scheduler alive), not unwind the
        thread with pages still referenced. Before the fix, _retire and
        _admit ran OUTSIDE _step_once's try and the scheduler died."""
        eng = InferStep(tmodel, max_len=24)
        bat = ContinuousBatcher(eng, bucket_keys=(8,), slots=2,
                                max_new_tokens=4, page_size=4,
                                iter_tokens=2, warmup=True)
        try:
            armed = [True]
            orig_admit = bat._admit

            def flaky_admit():
                if armed[0] and bat._pending:
                    armed[0] = False
                    raise RuntimeError("admission blew up")
                return orig_admit()

            bat._admit = flaky_admit
            src = np.arange(3, 9, dtype=np.int32)
            # first request trips the fault; poison keeps it pending, so
            # the surviving scheduler re-admits and serves it
            f1 = bat.submit(src)
            r1 = f1.result(timeout=120)
            assert isinstance(r1, list) and len(r1) == 4
            assert not armed[0]  # the fault really fired
            # thread survived: a second request decodes normally
            f2 = bat.submit(src)
            assert f2.result(timeout=120) == r1
        finally:
            bat.stop()
        assert bat.pool.free_pages == bat.pool.num_pages
        bat.pool.check_invariants(set())

    def test_hot_swap_with_paged_requests_in_flight(self, tmodel):
        """A weight swap between iterations: zero lost requests and both
        versions appear in the served stream."""
        other = _make_transformer(seed=11, prefix=tmodel.prefix)
        eng = InferStep(tmodel, max_len=24)
        staged = eng.stage_params(
            {n: p._data.data for n, p in other.collect_params().items()})
        bat = ContinuousBatcher(eng, bucket_keys=(8,), slots=2,
                                max_new_tokens=6, page_size=4,
                                iter_tokens=1, warmup=True)
        rng = np.random.RandomState(9)
        futs = []
        try:
            for i in range(12):
                futs.append(bat.submit(
                    rng.randint(3, 61, (6,)).astype(np.int32)))
                if i == 5:
                    # guarantee at least one pre-swap completion, then
                    # flip between iterations with requests in flight
                    futs[0].result(timeout=60)
                    eng.swap_params(staged=staged, version="v-next")
                time.sleep(0.002)
            results = [f.result(timeout=120) for f in futs]
        finally:
            bat.stop()
        assert all(len(r) >= 1 for r in results)
        versions = {f.weights_version for f in futs}
        assert "v-next" in versions and len(versions) >= 2
        assert bat.pool.free_pages == bat.pool.num_pages


# ------------------------------------------------------------ no regress
def test_dynamic_batcher_still_fixed_path(tmodel):
    """The fallback engine path survives the base-class refactor: same
    construction surface, same whole-batch semantics."""
    eng = InferStep(tmodel, max_len=24)
    bat = DynamicBatcher(eng, bucket_keys=(8, 12), slots=2,
                         timeout_ms=40.0, max_new_tokens=4)
    try:
        fut = bat.submit([7, 8, 9, 10], max_new_tokens=2)
        out = fut.result(timeout=60)
        assert len(out) <= 2
        if out:
            # streaming degenerates to one final chunk
            assert list(fut.tokens_iter(timeout=10)) == [out]
    finally:
        bat.stop()
