"""gluon.data tests (reference: tests/python/unittest/test_gluon_data.py
[unverified])."""

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu.gluon import data
from mxnet_tpu.gluon.data import vision


def test_array_dataset():
    x = np.arange(20).reshape(10, 2)
    y = np.arange(10)
    ds = data.ArrayDataset(x, y)
    assert len(ds) == 10
    sample_x, sample_y = ds[3]
    np.testing.assert_allclose(sample_x, [6, 7])
    assert sample_y == 3


def test_dataset_transform():
    ds = data.SimpleDataset(list(range(5))).transform(lambda x: x * 2)
    assert ds[2] == 4
    ds2 = data.ArrayDataset(np.arange(4), np.arange(4)).transform_first(
        lambda x: x + 10
    )
    assert ds2[1] == (11, 1)


def test_dataset_shard_take_filter():
    ds = data.SimpleDataset(list(range(10)))
    assert len(ds.shard(3, 0)) == 4
    assert len(ds.shard(3, 2)) == 3
    assert len(ds.take(4)) == 4
    assert len(ds.filter(lambda x: x % 2 == 0)) == 5


def test_samplers():
    seq = list(data.SequentialSampler(5))
    assert seq == [0, 1, 2, 3, 4]
    rnd = list(data.RandomSampler(100))
    assert sorted(rnd) == list(range(100)) and rnd != list(range(100))
    batches = list(data.BatchSampler(data.SequentialSampler(7), 3, "keep"))
    assert batches == [[0, 1, 2], [3, 4, 5], [6]]
    batches = list(data.BatchSampler(data.SequentialSampler(7), 3, "discard"))
    assert len(batches) == 2


def test_dataloader_basic():
    x = np.random.randn(17, 3).astype("float32")
    y = np.arange(17).astype("float32")
    loader = data.DataLoader(data.ArrayDataset(x, y), batch_size=5)
    batches = list(loader)
    assert len(batches) == 4
    assert batches[0][0].shape == (5, 3)
    assert batches[-1][0].shape == (2, 3)
    np.testing.assert_allclose(batches[0][1].asnumpy(), y[:5])


def test_dataloader_shuffle_covers_all():
    y = np.arange(30)
    loader = data.DataLoader(
        data.ArrayDataset(y), batch_size=10, shuffle=True
    )
    seen = np.concatenate([b.asnumpy() for b in loader])
    assert sorted(seen.tolist()) == y.tolist()


def test_dataloader_workers():
    x = np.random.randn(23, 4).astype("float32")
    loader = data.DataLoader(
        data.ArrayDataset(x), batch_size=4, num_workers=2
    )
    batches = list(loader)
    assert sum(b.shape[0] for b in batches) == 23
    got = np.concatenate([b.asnumpy() for b in batches])
    np.testing.assert_allclose(got, x)


def test_dataloader_last_batch_modes():
    ds = data.ArrayDataset(np.arange(10))
    assert len(list(data.DataLoader(ds, 3, last_batch="keep"))) == 4
    assert len(list(data.DataLoader(ds, 3, last_batch="discard"))) == 3


def test_transforms_totensor_normalize():
    t = vision.transforms.Compose(
        [
            vision.transforms.ToTensor(),
            vision.transforms.Normalize(0.5, 0.25),
        ]
    )
    img = (np.ones((4, 4, 3)) * 255).astype("uint8")
    out = t(mx.nd.array(img))
    assert out.shape == (3, 4, 4)
    np.testing.assert_allclose(out.asnumpy(), 2.0, rtol=1e-5)


def test_transforms_resize_crop():
    img = np.random.randint(0, 255, (10, 8, 3)).astype("uint8")
    out = vision.transforms.Resize(4)(mx.nd.array(img))
    assert out.shape == (4, 4, 3)
    out = vision.transforms.CenterCrop(6)(mx.nd.array(img))
    assert out.shape == (6, 6, 3)
    out = vision.transforms.RandomResizedCrop(5)(mx.nd.array(img))
    assert out.shape == (5, 5, 3)


def test_transforms_flip_deterministic_shape():
    img = np.random.randint(0, 255, (6, 6, 3)).astype("uint8")
    out = vision.transforms.RandomFlipLeftRight()(mx.nd.array(img))
    assert out.shape == (6, 6, 3)
