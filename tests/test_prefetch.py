"""Async device-feed pipeline (gluon.data.prefetch) under JAX_PLATFORMS=cpu.

Covers the tentpole's contracts: ordering/determinism vs the raw loader,
the bounded device-resident queue, clean teardown (idle close and
mid-iteration abandonment — no leaked staging threads), worker-side error
propagation, and the TrainStep pre-placed fast path producing BIT-IDENTICAL
loss sequences to the raw numpy feed.
"""

import gc
import threading
import time

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import gluon, optimizer as opt
from mxnet_tpu import parallel
from mxnet_tpu.gluon import nn
from mxnet_tpu.gluon import data as gdata
from mxnet_tpu.gluon.data.prefetch import PrefetchIterator, prefetch_to_device

X = np.random.RandomState(0).randn(16, 8).astype("float32")
Y = np.random.RandomState(1).randn(16, 1).astype("float32")


def _prefetch_threads():
    return [t for t in threading.enumerate()
            if t.name == "mxtpu-prefetch" and t.is_alive()]


def _wait_no_threads(timeout=3.0):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if not _prefetch_threads():
            return True
        time.sleep(0.02)
    return False


def _build_step(**kw):
    mx.random.seed(11)
    net = nn.HybridSequential()
    with net.name_scope():
        net.add(nn.Dense(16, activation="relu"), nn.Dense(1))
    net.initialize()
    net(mx.nd.array(X))
    return parallel.TrainStep(net, gluon.loss.L2Loss(),
                              opt.AdamW(learning_rate=1e-2), **kw)


# ----------------------------------------------------------- ordering
def test_ordering_matches_raw_loader():
    ds = gdata.ArrayDataset(
        np.arange(40, dtype=np.float32).reshape(20, 2),
        np.arange(20, dtype=np.float32))
    loader = gdata.DataLoader(ds, batch_size=4, shuffle=False)
    raw = [(x.asnumpy(), y.asnumpy()) for x, y in loader]
    pf = [(x.asnumpy(), y.asnumpy())
          for x, y in prefetch_to_device(loader, size=2)]
    assert len(raw) == len(pf) == 5
    for (rx, ry), (px, py) in zip(raw, pf):
        np.testing.assert_array_equal(rx, px)
        np.testing.assert_array_equal(ry, py)
    assert _wait_no_threads()


def test_default_placement_is_device_resident():
    src = [np.full((2, 2), i, np.float32) for i in range(3)]
    out = list(prefetch_to_device(iter(src), size=1))
    assert all(isinstance(b, mx.nd.NDArray) for b in out)
    np.testing.assert_array_equal(out[2].asnumpy(), src[2])


# ------------------------------------------------------ bounded queue
def test_bounded_queue_depth():
    produced = [0]

    def src():
        for i in range(50):
            produced[0] += 1
            yield np.full((2,), i, np.float32)

    pf = prefetch_to_device(src(), size=2)
    next(pf)
    time.sleep(0.5)  # let the worker run as far ahead as it can
    # consumed(1) + size staged + one being put + one pulled-not-yet-put
    assert produced[0] <= 1 + 2 + 2, f"queue not bounded: {produced[0]}"
    pf.close()
    assert _wait_no_threads()


def test_size_validation():
    with pytest.raises(ValueError):
        PrefetchIterator(iter([]), 0)
    with pytest.raises(TypeError):
        prefetch_to_device(iter([]), size=1, feed=object())


def test_default_size_env(monkeypatch):
    monkeypatch.setenv("MXTPU_PREFETCH_DEFAULT", "3")
    pf = prefetch_to_device(iter([np.zeros(1, np.float32)]))
    assert pf._size == 3
    pf.close()


# ------------------------------------------------------------ teardown
def test_teardown_idle_close():
    pf = prefetch_to_device(
        iter([np.ones(2, np.float32)] * 30), size=2)
    next(pf)
    pf.close()
    assert _wait_no_threads()
    # closed iterator terminates cleanly
    with pytest.raises(StopIteration):
        next(pf)
    pf.close()  # idempotent


def test_teardown_midstream_abandon():
    pf = prefetch_to_device(
        iter([np.ones(2, np.float32)] * 30), size=2)
    for _ in range(3):
        next(pf)
    del pf  # no close(): the worker must not keep the iterator alive
    gc.collect()
    assert _wait_no_threads(), "abandoned prefetcher leaked its thread"


def test_teardown_context_manager_break():
    with prefetch_to_device(
            iter([np.ones(2, np.float32)] * 30), size=2) as pf:
        for i, _b in enumerate(pf):
            if i == 1:
                break
    assert _wait_no_threads()


def test_exhaustion_retires_thread():
    out = list(prefetch_to_device(iter([np.ones(2, np.float32)] * 4),
                                  size=2))
    assert len(out) == 4
    assert _wait_no_threads()


# ------------------------------------------------------------- errors
def test_worker_error_propagates():
    def bad():
        yield np.ones(2, np.float32)
        raise ValueError("boom in the loader")

    pf = prefetch_to_device(bad(), size=2)
    next(pf)
    with pytest.raises(ValueError, match="boom in the loader"):
        next(pf)
    assert _wait_no_threads()
    with pytest.raises(StopIteration):
        next(pf)  # closed after the error


def test_consumer_error_unblocks_worker():
    def src():
        for i in range(100):
            yield np.full((2,), i, np.float32)

    def consume():
        with prefetch_to_device(src(), size=1) as pf:
            next(pf)
            raise RuntimeError("consumer died")

    with pytest.raises(RuntimeError):
        consume()
    assert _wait_no_threads()


# --------------------------------------------------- TrainStep fast path
def test_trainstep_fast_path_bit_identical():
    mx.random.seed(42)
    sa = _build_step()
    la = [float(sa(mx.nd.array(X), mx.nd.array(Y)).asscalar())
          for _ in range(5)]

    mx.random.seed(42)
    sb = _build_step()
    lb = [float(sb(sb.device_put_batch((X, Y))).asscalar())
          for _ in range(5)]
    assert la == lb, "pre-placed fast path diverged from raw feed"

    sa.sync_params()
    sb.sync_params()
    pa = {k.split("dense", 1)[-1]: v.data().asnumpy()
          for k, v in sa._net.collect_params().items()}
    pb = {k.split("dense", 1)[-1]: v.data().asnumpy()
          for k, v in sb._net.collect_params().items()}
    for k in pa:
        np.testing.assert_array_equal(pa[k], pb[k])


def test_trainstep_fast_path_split_axes():
    """steps_per_call/grad_accum leading-axis split must be applied
    identically by device_put_batch."""
    Xb, Yb = np.tile(X, (4, 1)), np.tile(Y, (4, 1))
    mx.random.seed(42)
    sa = _build_step(steps_per_call=2, grad_accum=2)
    la = [float(sa(mx.nd.array(Xb), mx.nd.array(Yb)).asscalar())
          for _ in range(3)]
    mx.random.seed(42)
    sb = _build_step(steps_per_call=2, grad_accum=2)
    lb = [float(sb(sb.device_put_batch((Xb, Yb))).asscalar())
          for _ in range(3)]
    assert la == lb


def test_trainstep_prefetch_end_to_end():
    mx.random.seed(42)
    sa = _build_step()
    la = [float(sa(mx.nd.array(X), mx.nd.array(Y)).asscalar())
          for _ in range(5)]

    mx.random.seed(42)
    sb = _build_step()
    src = ((X, Y) for _ in range(5))
    lb = [float(sb(db).asscalar())
          for db in prefetch_to_device(src, size=2, feed=sb)]
    assert la == lb
    assert _wait_no_threads()


def test_device_batch_wrong_owner_rejected():
    sa = _build_step()
    sb = _build_step()
    db = sa.device_put_batch((X, Y))
    with pytest.raises(mx.base.MXNetError, match="different TrainStep"):
        sb(db)


def test_feed_spec_contract():
    s = _build_step(steps_per_call=2, grad_accum=3)
    spec = s.feed_spec()
    assert spec["steps_per_call"] == 2
    assert spec["grad_accum"] == 3
    assert spec["lead"] == (2, 3)
    assert spec["split"] == 6


def test_resident_path_no_per_step_dict_rebuild():
    """The per-call host work must reuse the persistent train/frozen
    partition (acceptance: no dict rebuilds per step on the resident
    path) — new device values land in the SAME dict objects."""
    s = _build_step()
    s(mx.nd.array(X), mx.nd.array(Y))
    frozen_before = s._frozen_vals
    s(mx.nd.array(X), mx.nd.array(Y))
    assert s._frozen_vals is frozen_before
    assert set(s._train_vals) == set(s._train_set)


# ------------------------------------------------- wiring + telemetry
def test_dataloader_prefetch_to_device_arg():
    ds = gdata.ArrayDataset(
        np.arange(24, dtype=np.float32).reshape(12, 2),
        np.arange(12, dtype=np.float32))
    raw = gdata.DataLoader(ds, batch_size=4, shuffle=False)
    wrapped = gdata.DataLoader(ds, batch_size=4, shuffle=False,
                               prefetch_to_device=2)
    it = iter(wrapped)
    assert isinstance(it, PrefetchIterator)
    got = [(x.asnumpy(), y.asnumpy()) for x, y in it]
    want = [(x.asnumpy(), y.asnumpy()) for x, y in raw]
    for (gx, gy), (wx, wy) in zip(got, want):
        np.testing.assert_array_equal(gx, wx)
        np.testing.assert_array_equal(gy, wy)
    # re-iterable: each epoch builds a fresh single-use pipeline
    assert len(list(iter(wrapped))) == 3
    assert _wait_no_threads()


def test_estimator_fit_prefetch():
    mx.random.seed(5)
    net = nn.Dense(1)
    net.initialize()
    net(mx.nd.array(X))
    ds = gdata.ArrayDataset(X, Y)
    loader = gdata.DataLoader(ds, batch_size=8, shuffle=False)
    est = gluon.contrib.estimator.Estimator(
        net, gluon.loss.L2Loss(),
        trainer=gluon.Trainer(net.collect_params(), "sgd",
                              {"learning_rate": 0.05}))
    est.fit(loader, epochs=2, prefetch=2)
    assert _wait_no_threads()
    assert est.train_loss_metric.get()[1] > 0


def test_input_wait_telemetry_recorded():
    reg = mx.telemetry.registry()
    before = reg.histogram("input/wait_ms").count
    list(prefetch_to_device(iter([np.ones(2, np.float32)] * 3), size=1))
    assert reg.histogram("input/wait_ms").count >= before + 3
    rep = mx.telemetry.report()
    assert rep["input_wait_ms"] is not None
    assert rep["input_wait_ms_p50"] is not None
    assert "input_queue_depth" in rep
