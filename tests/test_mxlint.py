"""Tier-1 wiring for mxlint, the unified static-analysis framework
(``mxnet_tpu/analysis/`` + ``tools/mxlint.py``).

Absorbs the three pre-framework lint tests — test_no_sync_lint.py,
test_amp_purity.py, test_sharding_lint.py — keeping their full case
coverage, and adds the violation self-tests for the four new passes
(lock-order, donation, recompile-hazard, collective-placement) plus the
two consistency passes (env-vars, telemetry-names): every pass gets a
seeded positive control (synthetic deadlock cycle, use-after-donate,
recompile hazard, unguarded host allreduce...) and a clean negative
control, and the WHOLE suite must run green at HEAD (modulo the
committed baseline) inside the runtime budget.
"""

import json
import os
import sys
import textwrap
import threading
import time

import numpy as np
import pytest

REPO = os.path.normpath(os.path.join(os.path.dirname(__file__), ".."))
sys.path.insert(0, os.path.join(REPO, "tools"))

from mxnet_tpu.analysis import (  # noqa: E402
    Baseline, Context, Finding, all_passes, get_pass, run_passes,
)
from mxnet_tpu.analysis import ast_driver, jaxpr_driver  # noqa: E402
from mxnet_tpu.analysis import callgraph  # noqa: E402
from mxnet_tpu.analysis.passes import (  # noqa: E402
    amp_purity, collectives, donation, env_vars, lock_order, no_sync,
    recompile, resource_leak, rpc_protocol, sharding_placement,
    swap_barrier, telemetry_names,
)

BASELINE_PATH = os.path.join(REPO, "tools", "mxlint_baseline.json")

ALL_PASSES = {"no-sync", "amp-purity", "sharding-placement", "lock-order",
              "donation", "recompile-hazard", "collective-placement",
              "env-vars", "telemetry-names", "resource-leak",
              "rpc-protocol", "swap-barrier"}


@pytest.fixture(scope="module")
def ctx():
    """One shared Context: the jaxpr passes reuse its cached real
    TrainStep/InferStep programs (built once per module)."""
    return Context()


@pytest.fixture(scope="module")
def sharding_setup():
    return sharding_placement.build_default_setup()


def _write_module(tmp_path, source, name="mod.py"):
    p = tmp_path / name
    p.write_text(textwrap.dedent(source))
    return ast_driver.AstIndex(str(tmp_path)), name


# ================================================================ framework
class TestFramework:
    def test_registry_has_the_full_roster(self):
        assert set(all_passes()) == ALL_PASSES

    def test_fingerprint_excludes_line_numbers(self):
        a = Finding("p", "r", "x/y.py", 10, "K", "m1")
        b = Finding("p", "r", "x/y.py", 99, "K", "reworded")
        assert a.fingerprint == b.fingerprint
        assert a.fingerprint != Finding("p", "r", "x/y.py", 10, "K2",
                                        "m1").fingerprint

    def test_baseline_requires_reasons(self, tmp_path):
        p = tmp_path / "b.json"
        p.write_text(json.dumps({"entries": {"x": {"reason": ""}}}))
        with pytest.raises(ValueError):
            Baseline.load(str(p))

    def test_baseline_suppresses_by_fingerprint(self):
        f = Finding("p", "r", "x.py", 1, "K", "m")
        b = Baseline({f.fingerprint: {"reason": "known"}})
        assert b.reason(f) == "known"
        assert b.reason(Finding("p", "r", "x.py", 1, "other", "m")) is None

    def test_full_suite_green_at_head_within_budget(self, ctx):
        """THE acceptance gate: all passes (including the three
        interprocedural ones), real programs, committed baseline — zero
        unbaselined findings, zero stale baseline entries, under the
        90 s budget."""
        t0 = time.perf_counter()
        baseline = Baseline.load(BASELINE_PATH)
        findings, suppressed = run_passes(baseline=baseline, ctx=ctx)
        elapsed = time.perf_counter() - t0
        assert not findings, "\n".join(repr(f) for f in findings)
        for f, reason in suppressed:
            assert reason.strip()
        # the baseline file stays honest: every entry matched a finding
        matched = {f.fingerprint for f, _ in suppressed}
        stale = set(baseline.entries) - matched
        assert not stale, f"stale baseline entries: {sorted(stale)}"
        # and the ISSUE-15 passes grandfathered NOTHING: the serving
        # plane is clean under the interprocedural model at head
        assert not any(
            e.get("pass") in ("resource-leak", "rpc-protocol",
                              "swap-barrier")
            for e in baseline.entries.values())
        assert elapsed < 90.0, f"lint suite took {elapsed:.1f}s"

    def test_cli_json_output(self, capsys):
        import mxlint

        rc = mxlint.main(["--passes", "no-sync,env-vars,telemetry-names",
                          "--json"])
        out = json.loads(capsys.readouterr().out)
        assert rc == 0 and out["ok"] is True
        assert out["passes_run"] == ["no-sync", "env-vars",
                                     "telemetry-names"]

    def test_cli_lists_passes(self, capsys):
        import mxlint

        assert mxlint.main(["--list"]) == 0
        listed = capsys.readouterr().out
        for name in ALL_PASSES:
            assert name in listed

    def test_cli_stale_baseline_fails_then_prunes(self, tmp_path,
                                                  capsys):
        """A baseline entry matching no finding fails the default run
        (exit 1) and --prune-baseline deletes exactly it."""
        import mxlint

        bl = json.loads(open(BASELINE_PATH).read())
        stale_fp = ("lock-order.shared-state:"
                    "mxnet_tpu/serving/batcher.py:Gone.attr")
        bl["entries"][stale_fp] = {
            "reason": "code this excused was deleted", "pass":
            "lock-order", "rule": "shared-state",
            "path": "mxnet_tpu/serving/batcher.py"}
        p = tmp_path / "b.json"
        p.write_text(json.dumps(bl))
        rc = mxlint.main(["--passes", "lock-order",
                          "--baseline", str(p)])
        out = capsys.readouterr().out
        assert rc == 1
        assert "STALE" in out and stale_fp in out
        rc = mxlint.main(["--passes", "lock-order", "--baseline",
                          str(p), "--prune-baseline"])
        assert rc == 0
        capsys.readouterr()
        entries = json.loads(p.read_text())["entries"]
        assert stale_fp not in entries
        assert len(entries) == 2  # the real grandfathered pair survives
        assert mxlint.main(["--passes", "lock-order",
                            "--baseline", str(p)]) == 0
        capsys.readouterr()

    def test_cli_stale_scoped_to_executed_passes(self, tmp_path,
                                                 capsys):
        """An entry belonging to a pass we did NOT run is not stale —
        a --passes subset must not invalidate the rest of the file."""
        import mxlint

        bl = json.loads(open(BASELINE_PATH).read())
        bl["entries"]["donation.fake:x.py:K"] = {
            "reason": "other pass", "pass": "donation",
            "rule": "fake", "path": "x.py"}
        p = tmp_path / "b.json"
        p.write_text(json.dumps(bl))
        assert mxlint.main(["--passes", "lock-order",
                            "--baseline", str(p)]) == 0
        capsys.readouterr()

    def test_cli_github_annotations(self, tmp_path, capsys):
        """--github emits one ::error per finding, pinned to file/line
        (and per stale baseline entry); a clean run emits none."""
        import mxlint

        rc = mxlint.main(["--passes", "lock-order", "--baseline",
                          "none", "--github"])
        out = capsys.readouterr().out
        assert rc == 1  # the two baselined races are findings sans file
        lines = [ln for ln in out.splitlines()
                 if ln.startswith("::error ")]
        assert len(lines) == 2
        for ln in lines:
            assert ln.startswith(
                "::error file=mxnet_tpu/serving/batcher.py,line=")
            assert "[lock-order.shared-state]" in ln
        rc = mxlint.main(["--passes", "lock-order", "--github"])
        out = capsys.readouterr().out
        assert rc == 0
        assert "::error" not in out


# ============================================== no-sync (ported coverage)
class TestNoSync:
    def test_fast_path_is_sync_free(self):
        violations = no_sync.find_violations()
        assert not violations, "\n".join(
            f"step.py:{ln}: {msg}" for ln, msg in violations)

    def test_all_hot_paths_are_sync_free(self):
        violations = no_sync.find_all_violations()
        assert not violations, "\n".join(
            f"{path}:{ln}: {msg}" for path, ln, msg in violations)

    def test_targets_cover_inference_engine(self):
        covered = {(os.path.basename(p), cls): set(funcs)
                   for p, cls, funcs in no_sync.TARGETS}
        assert "decode_n" in covered[("infer.py", "InferStep")]
        assert "_dispatch" in covered[("batcher.py", "DynamicBatcher")]

    def test_targets_cover_continuous_batching(self):
        covered = {(os.path.basename(p), cls): set(funcs)
                   for p, cls, funcs in no_sync.TARGETS}
        assert "decode_iter" in covered[("infer.py", "InferStep")]
        assert "prefill_paged" in covered[("infer.py", "InferStep")]
        cont = covered[("batcher.py", "ContinuousBatcher")]
        assert "_dispatch" in cont
        assert "_step_once" in cont  # the scheduler loop body

    def test_lint_catches_a_violation(self, tmp_path):
        bad = tmp_path / "step_bad.py"
        bad.write_text(
            "class TrainStep:\n"
            "    def __call__(self, x):\n"
            "        return float(self._dispatch(x))\n"
            "    def _dispatch(self, x):\n"
            "        return x.asnumpy()\n"
        )
        violations = no_sync.find_violations(str(bad))
        assert len(violations) == 2
        assert any("float" in m for _, m in violations)
        assert any("asnumpy" in m for _, m in violations)

    def test_lint_catches_decode_violation(self, tmp_path):
        bad = tmp_path / "infer_bad.py"
        bad.write_text(
            "class InferStep:\n"
            "    def decode_n(self, src):\n"
            "        import jax\n"
            "        out = self._fn(src)\n"
            "        jax.block_until_ready(out)\n"
            "        return out\n"
        )
        violations = no_sync.find_violations(
            str(bad), "InferStep", ("decode_n",))
        assert len(violations) == 1
        assert "block_until_ready" in violations[0][1]

    def test_lint_catches_decode_iter_violation(self, tmp_path):
        bad = tmp_path / "infer_bad_paged.py"
        bad.write_text(
            "class InferStep:\n"
            "    def decode_iter(self, state, tables, tokens):\n"
            "        buf, state = self._fn(state, tables, tokens)\n"
            "        return buf.asnumpy(), state\n"
            "    def prefill_paged(self, state, src):\n"
            "        tok0, state = self._fn(state, src)\n"
            "        return int(tok0[0]), state\n"
        )
        violations = no_sync.find_violations(
            str(bad), "InferStep", ("decode_iter", "prefill_paged"))
        assert len(violations) == 2
        assert any("asnumpy" in m for _, m in violations)
        assert any("int" in m for _, m in violations)

    def test_lint_catches_scheduler_loop_violation(self, tmp_path):
        bad = tmp_path / "batcher_bad.py"
        bad.write_text(
            "import time\n"
            "class ContinuousBatcher:\n"
            "    def _step_once(self):\n"
            "        time.sleep(0.01)\n"
            "        return True\n"
            "    def _dispatch(self, live):\n"
            "        out = self._engine.decode_iter(live)\n"
            "        return out[0].tolist()\n"
        )
        violations = no_sync.find_violations(
            str(bad), "ContinuousBatcher", ("_step_once", "_dispatch"))
        assert len(violations) == 2
        assert any("sleep" in m for _, m in violations)
        assert any("tolist" in m for _, m in violations)


# =========================================== amp-purity (ported coverage)
class TestAmpPurity:
    def test_amp_step_has_no_mixed_dots(self, ctx):
        violations = amp_purity.check_step_purity(
            jaxpr=ctx.programs.train_jaxpr)
        assert not violations, "\n".join(violations)

    def test_overflow_skip_path_is_sync_free(self):
        violations = amp_purity.find_overflow_sync_violations()
        assert not violations, "\n".join(
            f"step.py:{ln}: {msg}" for ln, msg in violations)

    def test_lint_detects_a_mixed_dot(self):
        import jax
        import jax.numpy as jnp

        # mixed dot written deliberately: f32 x bf16
        def worse(w32, x16):
            return jax.lax.dot_general(
                w32, x16, (((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32).sum()

        jaxpr = jax.make_jaxpr(worse)(
            jax.ShapeDtypeStruct((4, 8), jnp.float32),
            jax.ShapeDtypeStruct((8, 4), jnp.bfloat16))
        assert jaxpr_driver.find_mixed_dots(jaxpr)

    def test_lint_detects_a_sync_in_traced_closure(self, tmp_path):
        bad = tmp_path / "step_bad.py"
        bad.write_text(
            "class TrainStep:\n"
            "    def _build(self, donate):\n"
            "        n = float(self._optimizer.wd)  # host-side: legal\n"
            "        def step(vals):\n"
            "            return float(vals)  # traced closure: violation\n"
            "        return step\n"
        )
        violations = amp_purity.find_overflow_sync_violations(str(bad))
        assert len(violations) == 1
        assert "float" in violations[0][1]


# ==================================== sharding-placement (ported coverage)
class TestShardingPlacement:
    def test_sharding_lint_passes(self, sharding_setup):
        violations = sharding_placement.run_checks(*sharding_setup)
        assert not violations, "\n".join(violations)

    def test_lint_flags_inert_rule(self, sharding_setup):
        from mxnet_tpu.parallel import sharding as shard
        from mxnet_tpu.parallel import PartitionSpec as P

        mesh, _, _, _, _, shapes = sharding_setup
        bad = shard.ShardingRules.fsdp(min_size=32, rules=[
            (r"matches_nothing$", P("data"))])
        violations = sharding_placement.check_rules_coverage(
            bad, shapes, mesh)
        assert any("matched NO parameter" in v for v in violations)

    def test_lint_flags_indivisible_fsdp(self, sharding_setup):
        from mxnet_tpu.parallel import sharding as shard

        mesh = sharding_setup[0]
        rules = shard.ShardingRules.fsdp(min_size=8)
        violations = sharding_placement.check_rules_coverage(
            rules, {"odd_weight": (7, 9)}, mesh)
        assert any("silently fully replicated" in v for v in violations)

    def test_lint_flags_fully_replicated_fsdp(self, sharding_setup):
        from mxnet_tpu.parallel import sharding as shard

        mesh = sharding_setup[0]
        rules = shard.ShardingRules.fsdp(min_size=10**9)
        violations = sharding_placement.check_rules_coverage(
            rules, {"w": (64, 16)}, mesh)
        assert any("partitioned NOTHING" in v for v in violations)

    def test_lint_detects_misplacement(self, sharding_setup):
        import jax
        from jax.sharding import NamedSharding
        from mxnet_tpu.parallel import PartitionSpec as P

        mesh, rules, step, eng, batch, shapes = sharding_setup
        name = next(n for n in step._train_vals
                    if step._param_sharding(n).spec != P())
        orig = step._train_vals[name]
        try:
            step._train_vals[name] = jax.device_put(
                jax.numpy.asarray(orig), NamedSharding(mesh, P()))
            violations = sharding_placement.check_step_placement(step)
            assert any(name in v for v in violations)
        finally:
            step._train_vals[name] = orig


# ================================================= lock-order self-tests
def _analyze(tmp_path, source):
    index, name = _write_module(tmp_path, source)
    return lock_order.analyze(index, [name])


class TestLockOrder:
    def test_detects_two_lock_deadlock_cycle(self, tmp_path):
        """Acceptance: a seeded two-lock cycle in serving-plane shape."""
        cycles, _, _ = _analyze(tmp_path, """
            import threading
            class Router:
                def __init__(self):
                    self._lock = threading.Lock()
                    self._hb_lock = threading.Lock()
                def submit(self, r):
                    with self._lock:
                        with self._hb_lock:
                            return r
                def _health_pass(self):
                    with self._hb_lock:
                        with self._lock:
                            return 1
            """)
        assert cycles, "two-lock cycle not detected"
        locks = {f"{c}.{a}" for comp, _ in cycles for c, a in comp}
        assert {"Router._lock", "Router._hb_lock"} <= locks

    def test_detects_self_deadlock(self, tmp_path):
        cycles, _, _ = _analyze(tmp_path, """
            import threading
            class W:
                def __init__(self):
                    self._lock = threading.Lock()
                def poke(self):
                    with self._lock:
                        with self._lock:
                            pass
            """)
        assert any(len(comp) == 2 and comp[0] == comp[1]
                   for comp, _ in cycles)

    def test_detects_blocking_dispatch_under_lock(self, tmp_path):
        """Acceptance: a blocking engine dispatch fired under a lock."""
        _, blocking, _ = _analyze(tmp_path, """
            import threading
            class Batcher:
                def __init__(self):
                    self._lock = threading.Lock()
                def fire(self, reqs, fut):
                    with self._lock:
                        out = self._engine.decode_n(reqs)
                        return fut.result()
            """)
        msgs = [m for _, _, _, _, m, _ in blocking]
        assert any("decode_n" in m for m in msgs)
        assert any("result" in m for m in msgs)

    def test_detects_blocking_via_self_call(self, tmp_path):
        _, blocking, _ = _analyze(tmp_path, """
            import threading, time
            class B:
                def __init__(self):
                    self._lock = threading.Lock()
                def outer(self):
                    with self._lock:
                        self._inner()
                def _inner(self):
                    time.sleep(1.0)
            """)
        assert any("_inner" in m for _, _, _, _, m, _ in blocking)

    def test_cond_wait_on_held_condition_is_legal(self, tmp_path):
        _, blocking, _ = _analyze(tmp_path, """
            import threading
            class R:
                def __init__(self):
                    self._cond = threading.Condition()
                def wait_tokens(self):
                    with self._cond:
                        self._cond.wait(1.0)
            """)
        assert not blocking

    def test_detects_unsynchronized_shared_state(self, tmp_path):
        _, _, shared = _analyze(tmp_path, """
            import threading
            class B:
                def __init__(self):
                    self.stats = {}
                    self._thread = threading.Thread(target=self._run)
                def _run(self):
                    self.stats["n"] = 1
                def submit(self):
                    return sorted(self.stats)
            """)
        assert any(attr == "stats" for _, _, _, attr, _ in shared)

    def test_locked_writes_are_clean(self, tmp_path):
        cycles, blocking, shared = _analyze(tmp_path, """
            import threading
            class B:
                def __init__(self):
                    self.stats = {}
                    self._lock = threading.Lock()
                    self._thread = threading.Thread(target=self._run)
                def _run(self):
                    with self._lock:
                        self.stats["n"] = 1
                def submit(self):
                    with self._lock:
                        return sorted(self.stats)
            """)
        assert not cycles and not blocking and not shared

    def test_serving_plane_at_head_only_baselined_findings(self, ctx):
        findings = get_pass("lock-order").run(ctx)
        baseline = Baseline.load(BASELINE_PATH)
        fresh = [f for f in findings if baseline.reason(f) is None]
        assert not fresh, "\n".join(repr(f) for f in fresh)
        # the two grandfathered single-writer findings stay visible
        assert {f.key for f in findings} <= {
            "ContinuousBatcher._pending", "ContinuousBatcher._slots"}

    def test_cross_process_modules_in_scope(self):
        """The ISSUE-10 modules are part of the serving-plane set the
        pass walks at HEAD (the head test above then proves them
        finding-free)."""
        assert {"mxnet_tpu/serving/transport.py",
                "mxnet_tpu/serving/worker.py",
                "mxnet_tpu/serving/remote.py"} <= set(lock_order.MODULES)


class TestLockOrderTransport:
    """Seeded controls in the RPC client's thread shape: a socket READER
    thread routes responses while caller threads register calls — the
    call table is cross-domain state."""

    def test_unlocked_call_table_across_reader_flagged(self, tmp_path):
        """Positive: the reader thread rebuilds the call table while
        `call()` iterates it — the torn-table shape the real client must
        lock against."""
        _, _, shared = _analyze(tmp_path, """
            import threading
            class Client:
                def __init__(self):
                    self._calls = {}
                    self._reader = threading.Thread(
                        target=self._read_loop)
                def _read_loop(self):
                    self._calls = {}
                def call(self, verb):
                    return sorted(self._calls)
            """)
        assert any(attr == "_calls" for _, _, _, attr, _ in shared)

    def test_locked_call_table_clean(self, tmp_path):
        """Negative: every call-table touch under the client lock (the
        real `RpcClient` shape) is clean — including a send lock that is
        never nested with it."""
        cycles, blocking, shared = _analyze(tmp_path, """
            import threading
            class Client:
                def __init__(self):
                    self._lock = threading.Lock()
                    self._send_lock = threading.Lock()
                    self._calls = {}
                    self._reader = threading.Thread(
                        target=self._read_loop)
                def _read_loop(self):
                    with self._lock:
                        self._calls = {}
                def call(self, verb):
                    with self._lock:
                        pending = list(self._calls.values())
                    with self._send_lock:
                        self._sock.sendall(verb)
                    return pending
            """)
        assert not cycles and not blocking and not shared


class TestLockOrderWorker:
    """Seeded controls in the worker's thread shape: per-request
    streamer threads relaying futures while handler/caller threads
    manage shared staging state."""

    def test_blocking_future_wait_under_lock_flagged(self, tmp_path):
        """Positive: a streamer waiting on a future's result while
        holding the worker lock couples every handler to decode
        latency — the hung-worker shape."""
        _, blocking, _ = _analyze(tmp_path, """
            import threading
            class Worker:
                def __init__(self):
                    self._lock = threading.Lock()
                def relay(self, fut):
                    with self._lock:
                        return fut.result()
            """)
        assert any("result" in m for _, _, _, _, m, _ in blocking)

    def test_locked_staging_with_waits_outside_clean(self, tmp_path):
        """Negative: the real worker shape — staged-swap state touched
        only under the lock, future waits outside any lock, streamer
        threads tracked under the lock — is clean."""
        cycles, blocking, shared = _analyze(tmp_path, """
            import threading
            class Worker:
                def __init__(self):
                    self._lock = threading.Lock()
                    self._staged = None
                    self._streamers = []
                    self._thread = threading.Thread(
                        target=self._stream_result)
                def _stream_result(self):
                    with self._lock:
                        self._streamers.append(1)
                    return self._fut.result()
                def handle_stage(self, arrays):
                    with self._lock:
                        self._staged = arrays
                def handle_swap(self):
                    with self._lock:
                        staged, self._staged = self._staged, None
                    return staged
            """)
        assert not cycles and not blocking and not shared


class TestLockOrderKvPush:
    """Seeded controls in the kv-push arrival path (ISSUE 11): transport
    reader threads stash pushed frames while submit handler/caller
    threads claim them — the stash is cross-domain state."""

    def test_unlocked_stash_across_reader_flagged(self, tmp_path):
        """Positive: the reader thread appends to the arrival order
        while callers iterate it unlocked — the torn-stash shape the
        real HandoffStash must lock against."""
        _, _, shared = _analyze(tmp_path, """
            import threading
            class Stash:
                def __init__(self):
                    self._frames = {}
                    self._order = []
                    self._reader = threading.Thread(
                        target=self._read_loop)
                def _read_loop(self):
                    self._order.append("h")
                def pop(self, handoff):
                    return sorted(self._order)
            """)
        assert any(attr == "_order" for _, _, _, attr, _ in shared)

    def test_locked_stash_clean(self, tmp_path):
        """Negative: the real HandoffStash shape — every frames/order
        touch under the stash lock, nothing blocking under it."""
        cycles, blocking, shared = _analyze(tmp_path, """
            import threading
            class Stash:
                def __init__(self):
                    self._lock = threading.Lock()
                    self._frames = {}
                    self._order = []
                    self._reader = threading.Thread(
                        target=self._read_loop)
                def _read_loop(self):
                    with self._lock:
                        self._order.append("h")
                def pop(self, handoff):
                    with self._lock:
                        order = sorted(self._order)
                        return self._frames.pop(handoff, None)
            """)
        assert not cycles and not blocking and not shared


class TestLockOrderScaler:
    """Seeded controls in the fleet-scaler's thread shape (ISSUE 11): a
    supervisor loop thread mutating decision state that public ``step``
    callers also touch."""

    def test_unlocked_decision_state_flagged(self, tmp_path):
        """Positive: the loop thread appends action records while
        callers iterate them unlocked."""
        _, _, shared = _analyze(tmp_path, """
            import threading
            class Scaler:
                def __init__(self):
                    self.actions = []
                    self._thread = threading.Thread(target=self._run)
                def _run(self):
                    self.actions.append("up")
                def history(self):
                    return sorted(self.actions)
            """)
        assert any(attr == "actions" for _, _, _, attr, _ in shared)

    def test_decide_under_lock_act_outside_clean(self, tmp_path):
        """Negative: the real FleetScaler shape — decisions (and every
        state write) under the scaler lock via a ``*_locked`` helper,
        the potentially-blocking spawn/retire callables OUTSIDE it."""
        cycles, blocking, shared = _analyze(tmp_path, """
            import threading
            class Scaler:
                def __init__(self):
                    self._lock = threading.Lock()
                    self.actions = []
                    self._hot = 0
                    self._thread = threading.Thread(target=self._run)
                def _run(self):
                    self.step()
                def step(self):
                    with self._lock:
                        action = self._decide_locked()
                    if action is not None:
                        self._spawn()
                    return action
                def _decide_locked(self):
                    self._hot += 1
                    if self._hot >= 2:
                        self.actions.append("up")
                        return "up"
                    return None
            """)
        assert not cycles and not blocking and not shared

    def test_disagg_modules_in_scope(self):
        """The ISSUE-11 modules are part of the serving-plane set the
        lock-order pass walks at HEAD (the head test above then proves
        them finding-free)."""
        assert {"mxnet_tpu/serving/disagg.py",
                "tools/launch.py"} <= set(lock_order.MODULES)


# ================================================== donation self-tests
class TestDonation:
    def test_real_modules_satisfy_contract(self, ctx):
        for path, req in ((donation.STEP_PY, donation.REQUIRED_STEP),
                          (donation.INFER_PY, donation.REQUIRED_INFER)):
            out = donation.check_contract(ctx.ast.module(path), req, path)
            assert not out, out

    def test_contract_catches_missing_donation(self, tmp_path):
        index, name = _write_module(tmp_path, """
            import jax
            class TrainStep:
                def _build(self):
                    def step(train_vals, opt_state, batch, key, t):
                        return train_vals, opt_state, key, t
                    return jax.jit(step, donate_argnums=(0,))
            """)
        out = donation.check_contract(
            index.module(name), donation.REQUIRED_STEP, name)
        assert any("opt_state" in m for _, _, m in out)

    def test_contract_catches_forbidden_donation(self, tmp_path):
        index, name = _write_module(tmp_path, """
            import jax
            class TrainStep:
                def _build(self):
                    def step(train_vals, opt_state, batch, key, t):
                        return train_vals, opt_state, key, t
                    return jax.jit(step, donate_argnums=(0, 1, 2, 3, 4))
            """)
        out = donation.check_contract(
            index.module(name), donation.REQUIRED_STEP, name)
        assert any("batch" in m for _, _, m in out)

    def test_catches_host_read_of_donated_pool_after_decode_iter(
            self, tmp_path):
        """Acceptance: a seeded host read of a donated pool after
        decode_iter."""
        index, name = _write_module(tmp_path, """
            class Batcher:
                def _dispatch(self, live):
                    buf, self._state = self._engine.decode_iter(
                        self._state, self.tables, live)
                    return buf
                def _peek(self):
                    out = self._engine.decode_iter(self._state, self.t, 1)
                    pool = self._state["k_pools"]
                    return out, pool
            """)
        out = donation.check_use_after_donate(index.module(name))
        assert any("use-after" in key for _, key, _ in out)
        # the rebind-in-same-statement pattern (_dispatch) is NOT flagged
        assert not any("_dispatch" in key for _, key, _ in out)

    def test_catches_lost_carry(self, tmp_path):
        index, name = _write_module(tmp_path, """
            class Batcher:
                def _fire(self, live):
                    buf = self._engine.decode_iter(self._state, live)
                    return buf
            """)
        out = donation.check_use_after_donate(index.module(name))
        assert any("lost" in key for _, key, _ in out)

    def test_serving_scheduler_clean_at_head(self, ctx):
        out = donation.check_use_after_donate(
            ctx.ast.module(donation.BATCHER_PY))
        assert not out, out

    def test_real_programs_donations_consumed_and_aliasable(self, ctx):
        msgs = donation.run_jaxpr_checks(ctx.programs)
        assert not msgs, "\n".join(msgs)


# ========================================== recompile-hazard self-tests
class TestRecompileHazard:
    def test_real_modules_clean(self, ctx):
        for path in (recompile.STEP_PY, recompile.INFER_PY):
            mod = ctx.ast.module(path)
            assert not recompile.check_cfg_hygiene(mod)
            assert not recompile.check_traced_closures(
                mod, recompile.TRACED_BUILDERS[path])
            assert not recompile.check_guard_accounting(
                mod, recompile.GUARDED_DISPATCHES[path])

    def test_catches_float_in_cfg_key(self, tmp_path):
        index, name = _write_module(tmp_path, """
            class InferStep:
                def _decode_cfg(self, max_new, method, temperature):
                    return int(max_new), str(method), float(temperature)
            """)
        out = recompile.check_cfg_hygiene(index.module(name))
        assert any("float" in key for _, key, _ in out)

    def test_catches_shape_branch_in_traced_closure(self, tmp_path):
        """Acceptance: a seeded recompile hazard."""
        index, name = _write_module(tmp_path, """
            class InferStep:
                def _get_decode_fn(self, cfg):
                    def decode(values, state, tokens):
                        if len(tokens) > 4:
                            return state
                        return values
                    return decode
            """)
        out = recompile.check_traced_closures(
            index.module(name), ("_get_decode_fn",))
        assert any("shape-branch" in key for _, key, _ in out)

    def test_catches_host_entropy_in_traced_closure(self, tmp_path):
        index, name = _write_module(tmp_path, """
            import time
            class InferStep:
                def _get_decode_fn(self, cfg):
                    def decode(values, tokens):
                        return values * time.time()
                    return decode
            """)
        out = recompile.check_traced_closures(
            index.module(name), ("_get_decode_fn",))
        assert any("host-entropy" in key for _, key, _ in out)

    def test_catches_unaccounted_dispatch(self, tmp_path):
        index, name = _write_module(tmp_path, """
            class InferStep:
                def decode_n(self, src):
                    fn = self._get_decode_fn(4)
                    return fn(self._values, src)
            """)
        out = recompile.check_guard_accounting(
            index.module(name), ("decode_n",))
        assert any("unaccounted" in key for _, key, _ in out)

    def test_guard_crosscheck_on_real_engine(self, ctx):
        msgs = recompile.run_guard_crosscheck(ctx.programs)
        assert not msgs, "\n".join(msgs)


# ================================= prefix-caching pass extensions (ISSUE 13)
class TestPrefixCachingPassScope:
    """The prefix-caching surface (``serving/prefix.py``, the
    ``prefill_suffix_paged`` replay dispatch, the ``_get_suffix_fn``
    builder) sits inside every relevant pass's scope — coverage
    assertions plus seeded positive/negative controls. The at-HEAD
    cleanliness of the real modules rides the existing full-suite and
    lock-order head tests."""

    def test_new_surface_is_in_scope(self):
        assert "mxnet_tpu/serving/prefix.py" in lock_order.MODULES
        covered = {(os.path.basename(p), cls): set(funcs)
                   for p, cls, funcs in no_sync.TARGETS}
        assert "prefill_suffix_paged" in covered[("infer.py", "InferStep")]
        assert "prefill_suffix_paged" in donation.DONATING_CALLS
        assert "prefill_suffix_paged" in \
            recompile.GUARDED_DISPATCHES[recompile.INFER_PY]
        assert "_get_suffix_fn" in \
            recompile.TRACED_BUILDERS[recompile.INFER_PY]

    def test_unlocked_trie_across_health_reader_flagged(self, tmp_path):
        """Positive: trie state shared between the scheduler and a
        health-verb reader thread without the cache lock."""
        _, _, shared = _analyze(tmp_path, """
            import threading
            class PrefixCache:
                def __init__(self):
                    self._roots = {}
                    self._reader = threading.Thread(target=self._health)
                def _health(self):
                    self._roots = {}
                def insert(self, key):
                    return sorted(self._roots)
            """)
        assert any(attr == "_roots" for _, _, _, attr, _ in shared)

    def test_locked_trie_clean(self, tmp_path):
        """Negative: every trie touch under the cache lock (the real
        ``PrefixCache`` shape) is clean."""
        cycles, blocking, shared = _analyze(tmp_path, """
            import threading
            class PrefixCache:
                def __init__(self):
                    self._lock = threading.Lock()
                    self._roots = {}
                    self._reader = threading.Thread(target=self._health)
                def _health(self):
                    with self._lock:
                        self._roots = {}
                def insert(self, key):
                    with self._lock:
                        return sorted(self._roots)
            """)
        assert not cycles and not blocking and not shared

    def test_sync_in_suffix_replay_flagged(self, tmp_path):
        bad = tmp_path / "infer_suffix_bad.py"
        bad.write_text(
            "class InferStep:\n"
            "    def prefill_suffix_paged(self, state, rows):\n"
            "        buf, state = self._fn(state, rows)\n"
            "        return buf.asnumpy(), state\n"
        )
        violations = no_sync.find_violations(
            str(bad), "InferStep", ("prefill_suffix_paged",))
        assert len(violations) == 1
        assert "asnumpy" in violations[0][1]

    def test_suffix_replay_lost_carry_flagged(self, tmp_path):
        """Positive: dropping the donated state carry of the suffix
        replay is a use-after-donate bug."""
        index, name = _write_module(tmp_path, """
            class Batcher:
                def _replay(self, rows):
                    buf = self._engine.prefill_suffix_paged(
                        self._state, rows)
                    return buf
            """)
        out = donation.check_use_after_donate(index.module(name))
        assert any("lost" in key for _, key, _ in out)

    def test_unaccounted_suffix_dispatch_flagged(self, tmp_path):
        index, name = _write_module(tmp_path, """
            class InferStep:
                def prefill_suffix_paged(self, state, rows):
                    fn = self._get_suffix_fn(8)
                    return fn(self._values, state, rows)
            """)
        out = recompile.check_guard_accounting(
            index.module(name), ("prefill_suffix_paged",))
        assert any("unaccounted" in key for _, key, _ in out)


# =============================== speculative-decoding pass extensions (ISSUE 14)
class TestSpeculativePassScope:
    """The speculative surface (``spec_draft``/``spec_verify`` dispatches,
    their traced builders, the batcher's spec round) sits inside every
    relevant pass's scope — coverage assertions plus seeded positive/
    negative controls. At-HEAD cleanliness of the real modules rides the
    existing full-suite and per-pass head tests."""

    def test_new_surface_is_in_scope(self):
        covered = {(os.path.basename(p), cls): set(funcs)
                   for p, cls, funcs in no_sync.TARGETS}
        infer = covered[("infer.py", "InferStep")]
        assert {"spec_draft", "spec_verify"} <= infer
        assert {"spec_draft", "spec_verify"} <= \
            set(donation.DONATING_CALLS)
        assert {"spec_draft", "spec_verify"} <= \
            set(recompile.GUARDED_DISPATCHES[recompile.INFER_PY])
        assert {"_get_spec_draft_fn", "_get_spec_verify_fn"} <= \
            set(recompile.TRACED_BUILDERS[recompile.INFER_PY])
        assert {"spec_draft", "spec_verify"} <= lock_order.DISPATCH_ATTRS

    def test_sync_in_spec_round_flagged(self, tmp_path):
        """Positive: host syncs inside the draft/verify dispatches."""
        bad = tmp_path / "infer_spec_bad.py"
        bad.write_text(
            "class InferStep:\n"
            "    def spec_draft(self, dstate, tables, tokens):\n"
            "        buf, dstate = self._fn(dstate, tables, tokens)\n"
            "        return buf.asnumpy(), dstate\n"
            "    def spec_verify(self, state, tables, drafts):\n"
            "        buf, state = self._fn(state, tables, drafts)\n"
            "        return int(buf[0, -1]), state\n"
        )
        violations = no_sync.find_violations(
            str(bad), "InferStep", ("spec_draft", "spec_verify"))
        assert len(violations) == 2
        assert any("asnumpy" in m for _, m in violations)
        assert any("int" in m for _, m in violations)

    def test_clean_spec_dispatch_passes(self, tmp_path):
        """Negative: the real shape — dispatch returns device buffers,
        carry rebinds in the same statement — is sync-free."""
        good = tmp_path / "infer_spec_good.py"
        good.write_text(
            "class InferStep:\n"
            "    def spec_verify(self, state, tables, drafts):\n"
            "        fn = self._get_spec_verify_fn(4)\n"
            "        self.compile_guard.observe(('spec_verify', 4))\n"
            "        buf, state = fn(self._values, state, tables, drafts)\n"
            "        return buf, state\n"
        )
        assert not no_sync.find_violations(
            str(good), "InferStep", ("spec_verify",))

    def test_spec_lost_carry_flagged(self, tmp_path):
        """Positive: dropping the donated draft-state carry of
        spec_draft is a use-after-donate bug."""
        index, name = _write_module(tmp_path, """
            class Batcher:
                def _spec_round(self, tokens):
                    dbuf = self._engine.spec_draft(
                        self._dstate, self.tables, tokens)
                    return dbuf
            """)
        out = donation.check_use_after_donate(index.module(name))
        assert any("lost" in key for _, key, _ in out)

    def test_unaccounted_spec_dispatch_flagged(self, tmp_path):
        index, name = _write_module(tmp_path, """
            class InferStep:
                def spec_verify(self, state, tables, drafts):
                    fn = self._get_spec_verify_fn(4)
                    return fn(self._values, state, tables, drafts)
            """)
        out = recompile.check_guard_accounting(
            index.module(name), ("spec_verify",))
        assert any("unaccounted" in key for _, key, _ in out)

    def test_shape_branch_in_spec_builder_flagged(self, tmp_path):
        """Positive: a data-dependent shape branch inside the traced
        verify closure is a per-round recompile."""
        index, name = _write_module(tmp_path, """
            class InferStep:
                def _get_spec_verify_fn(self, k):
                    def verify(values, state, drafts):
                        if len(drafts) > 2:
                            return state
                        return values
                    return verify
            """)
        out = recompile.check_traced_closures(
            index.module(name), ("_get_spec_verify_fn",))
        assert any("shape-branch" in key for _, key, _ in out)


# ===================================== collective-placement self-tests
class TestCollectivePlacement:
    def test_decode_programs_dispatch_no_collectives(self, ctx):
        """Acceptance: no psum/all_gather in the default decode path."""
        msgs = collectives.check_decode_collectives(ctx.programs)
        assert not msgs, "\n".join(msgs)

    def test_collective_primitives_are_detectable(self):
        import jax

        jaxpr = jax.make_jaxpr(
            lambda x: jax.lax.psum(x, "i"), axis_env=[("i", 2)])(1.0)
        hit = jaxpr_driver.primitive_names(jaxpr) & \
            collectives.COLLECTIVE_PRIMITIVES
        assert "psum" in hit

    def test_host_allreduce_guards_present_at_head(self, ctx):
        out = collectives.check_host_allreduce_guard(ctx.ast)
        assert not out, out

    def test_catches_unguarded_host_allreduce(self, tmp_path):
        index, name = _write_module(tmp_path, """
            class Trainer:
                def _allreduce_grads(self):
                    for k in self._grad_keys:
                        self._kvstore.push(k, self._grads[k])
                        self._kvstore.pull(k, self._grads[k])
            """)
        out = collectives.check_host_allreduce_guard(
            index, sites=((name, "Trainer", "_allreduce_grads",
                           "return-guard"),))
        assert any("unguarded" in key for _, key, _ in out)


# ============================================= env-vars / telemetry-names
class TestConsistencyPasses:
    def test_env_vars_consistent_at_head(self, ctx):
        findings = get_pass("env-vars").run(ctx)
        assert not findings, "\n".join(repr(f) for f in findings)

    def test_detects_undocumented_and_dead_vars(self, tmp_path):
        (tmp_path / "mxnet_tpu").mkdir()
        (tmp_path / "mxnet_tpu" / "mod.py").write_text(
            "import os\n"
            "A = os.environ.get('MXTPU_SECRET_KNOB', '1')\n")
        (tmp_path / "docs").mkdir()
        (tmp_path / "docs" / "ENV_VARS.md").write_text(
            "| `MXTPU_GHOST_KNOB` | `1` | long gone |\n")
        index = ast_driver.AstIndex(str(tmp_path))
        code = env_vars.collect_code_vars(index)
        doc = env_vars.collect_doc_vars(str(tmp_path))
        assert "MXTPU_SECRET_KNOB" in code
        assert not env_vars._doc_covers("MXTPU_SECRET_KNOB", doc)
        assert not env_vars._code_covers("MXTPU_GHOST_KNOB", set(code))

    def test_prefix_rows_cover_prefix_uses(self):
        doc = {"MXTPU_FAULT_": 1}
        assert env_vars._doc_covers("MXTPU_FAULT_BATCHER_HANG", doc)
        assert env_vars._code_covers("MXTPU_FAULT_",
                                     {"MXTPU_FAULT_", "MXTPU_X"})

    def test_telemetry_names_consistent_at_head(self, ctx):
        findings = get_pass("telemetry-names").run(ctx)
        assert not findings, "\n".join(repr(f) for f in findings)

    def test_report_tool_declares_every_emitted_family(self, ctx):
        metrics, spans = telemetry_names.collect_emissions(ctx.ast)
        known_m, known_s, _ = telemetry_names.declared_families(ctx.ast)
        assert set(metrics) <= known_m
        assert set(spans) <= known_s


# ==================================== interprocedural layer (ISSUE 15)
class TestCallGraph:
    """The shared layer under the three new passes: resolution,
    exception summaries, thread entries."""

    def test_resolves_self_attr_and_module_calls(self, tmp_path):
        index, name = _write_module(tmp_path, """
            def helper():
                return 1

            class A:
                def top(self):
                    self.mid()
                    helper()

                def mid(self):
                    pass
            """)
        g = callgraph.ProjectGraph(index, (name,))
        tops = dict(g.nodes[("A", "top")].calls)
        callees = {c for c in tops.values() if c is not None}
        assert ("A", "mid") in callees
        assert (name, "helper") in callees
        assert [k for k, _ in g.callers_of(("A", "mid"))] == [("A", "top")]

    def test_may_raise_propagates_and_broad_catch_stops_it(
            self, tmp_path):
        index, name = _write_module(tmp_path, """
            class A:
                def deep(self):
                    raise ValueError("boom")

                def mid(self):
                    self.deep()

                def caught(self):
                    try:
                        self.deep()
                    except Exception:
                        pass

                def rethrown(self):
                    try:
                        self.deep()
                    except Exception:
                        raise
            """)
        g = callgraph.ProjectGraph(index, (name,))
        assert g.may_raise(("A", "deep"))
        assert g.may_raise(("A", "mid"))      # transitively
        assert not g.may_raise(("A", "caught"))
        assert g.may_raise(("A", "rethrown"))  # handler re-raises

    def test_typed_attrs_resolve_cross_class(self, tmp_path):
        index, name = _write_module(tmp_path, """
            class Pool:
                def free(self):
                    raise RuntimeError("x")

            class User:
                def __init__(self):
                    self.pool = Pool()

                def use(self):
                    self.pool.free()
            """)
        g = callgraph.ProjectGraph(index, (name,))
        calls = dict(g.nodes[("User", "use")].calls)
        assert ("Pool", "free") in calls.values()
        assert g.may_raise(("User", "use"))

    def test_thread_entries_found(self, tmp_path):
        index, name = _write_module(tmp_path, """
            import threading

            class W:
                def start(self):
                    t = threading.Thread(target=self._run, daemon=True)
                    t.start()

                def _run(self):
                    pass
            """)
        g = callgraph.ProjectGraph(index, (name,))
        assert ("W", "_run") in g.thread_entries


class TestResourceLeakPass:
    """Seeded positive/negative controls (ISSUE 15 pattern: leaked page
    on raise vs balanced release), plus the head gate."""

    LEAKY = """
        class Worker:
            def __init__(self):
                self.pool = PagePool(16)

            def grab(self):
                page = self.pool.alloc(1)
                self.validate(page)
                self.pool.release(page)

            def validate(self, page):
                if page is None:
                    raise ValueError("bad page")
        """

    def test_detects_page_leak_on_exception_edge(self, tmp_path):
        index, name = _write_module(tmp_path, self.LEAKY)
        leaks, futures, stashes = resource_leak.analyze(
            index, rel_paths=(name,))
        assert len(leaks) == 1
        path, line, where, kind, recv, msg = leaks[0]
        assert (path, kind, recv) == (name, "pool-page", "pool")
        assert "Worker.grab" in where
        # stable fingerprint: a second run reproduces it exactly
        assert resource_leak.analyze(index, rel_paths=(name,))[0] == leaks

    def test_balanced_release_is_clean(self, tmp_path):
        index, name = _write_module(tmp_path, """
            class Worker:
                def __init__(self):
                    self.pool = PagePool(16)

                def grab(self):
                    page = self.pool.alloc(1)
                    try:
                        self.validate(page)
                    finally:
                        self.pool.release(page)

                def validate(self, page):
                    if page is None:
                        raise ValueError("bad page")
            """)
        leaks, futures, stashes = resource_leak.analyze(
            index, rel_paths=(name,))
        assert leaks == [] and futures == [] and stashes == []

    def test_broad_handler_in_caller_discharges(self, tmp_path):
        """The _step_once shape: a broad no-re-raise handler anywhere up
        the call chain owns the cleanup (the poison contract)."""
        index, name = _write_module(tmp_path, self.LEAKY + """
        class Sched:
            def __init__(self):
                self.w = Worker()

            def step(self):
                try:
                    self.w.grab()
                except Exception as e:
                    self.poison(e)

            def poison(self, e):
                pass
        """)
        leaks, _f, _s = resource_leak.analyze(index, rel_paths=(name,))
        # Worker.grab is no longer a root (Sched.step calls it and
        # catches): nothing reaches an uncaught root
        assert leaks == []

    def test_detects_unfailed_future_and_failed_is_clean(self, tmp_path):
        index, name = _write_module(tmp_path, """
            class Bad:
                def kick(self, p):
                    fut = GenerationResult()
                    self.check(p)
                    return fut

                def check(self, p):
                    if not p:
                        raise ValueError("empty")

            class Good:
                def kick(self, p):
                    fut = GenerationResult()
                    try:
                        self.check(p)
                    except Exception as e:
                        fut._fail(e)
                        raise
                    return fut

                def check(self, p):
                    if not p:
                        raise ValueError("empty")
            """)
        _l, futures, _s = resource_leak.analyze(index, rel_paths=(name,))
        assert len(futures) == 1
        assert "Bad.kick" in futures[0][2]

    def test_detects_clockless_stash(self, tmp_path):
        index, name = _write_module(tmp_path, """
            class FrameStash:
                def put(self, k, v):
                    self.d[k] = v

                def pop(self, k):
                    return self.d.pop(k, None)
            """)
        _l, _f, stashes = resource_leak.analyze(index, rel_paths=(name,))
        assert len(stashes) == 1 and "FrameStash" in stashes[0][2]

    def test_expiring_stash_is_clean(self, tmp_path):
        index, name = _write_module(tmp_path, """
            import time

            class FrameStash:
                def put(self, k, v):
                    now = time.monotonic()
                    self.d[k] = (v, now)

                def pop(self, k):
                    self.expire(time.monotonic())
                    return self.d.pop(k, None)

                def expire(self, now):
                    pass
            """)
        _l, _f, stashes = resource_leak.analyze(index, rel_paths=(name,))
        assert stashes == []

    def test_serving_plane_clean_at_head(self, ctx):
        findings = get_pass("resource-leak").run(ctx)
        assert not findings, "\n".join(repr(f) for f in findings)


class TestRpcProtocolPass:
    """Seeded controls: orphan verb + reply-key drift in BOTH directions
    vs a clean verb pair, plus the head gate."""

    BAD = """
        class RpcServer:
            def __init__(self, handlers):
                self.handlers = handlers

        class Server:
            def start(self):
                self.srv = RpcServer({"ping": self._handle_ping})

            def _handle_ping(self, msg, respond):
                respond(pong=True, extra=1)

        class Client:
            def check(self):
                out = self.conn.call("ping", {}, timeout_s=1.0)
                return out["latency"]

            def poke(self):
                self.conn.call("pong", {})
        """

    def test_detects_orphan_drift_and_timeout(self, tmp_path):
        index, name = _write_module(tmp_path, self.BAD)
        facts = rpc_protocol.analyze(index, server_paths=(name,),
                                     client_paths=(name,))
        assert set(facts["verbs"]) == {"ping"}
        assert [(v, w) for v, _p, _ln, w in facts["orphans"]] == \
            [("pong", "Client.poke")]
        # drift, read direction: caller reads a key never responded
        assert [(v, k) for v, k, _p, _ln in facts["missing_reply"]] == \
            [("ping", "latency")]
        # drift, respond direction: keys sent that nobody reads
        assert facts["unread"] == {"ping": ["extra", "pong"]}
        # the orphan send also carries no timeout
        assert [(v, w) for v, _p, _ln, w in
                facts["missing_timeout"]] == [("pong", "Client.poke")]
        # no fault point anywhere reaches the verb
        assert facts["unreachable_fault"] == ["ping"]
        # stability
        again = rpc_protocol.analyze(index, server_paths=(name,),
                                     client_paths=(name,))
        assert again["orphans"] == facts["orphans"]
        assert again["missing_reply"] == facts["missing_reply"]

    def test_clean_pair_is_clean(self, tmp_path):
        index, name = _write_module(tmp_path, """
            class RpcServer:
                def __init__(self, handlers):
                    self.handlers = handlers

            class Server:
                def start(self):
                    _faults.fire("transport.send")
                    _faults.fire("transport.recv")
                    self.srv = RpcServer({"ping": self._handle_ping})

                def _handle_ping(self, msg, respond):
                    respond(pong=True)

            class Client:
                def check(self):
                    out = self.conn.call("ping", {}, timeout_s=1.0)
                    return out["pong"]
            """)
        facts = rpc_protocol.analyze(index, server_paths=(name,),
                                     client_paths=(name,))
        assert facts["orphans"] == [] and facts["dead"] == []
        assert facts["missing_reply"] == [] and facts["unread"] == {}
        assert facts["missing_timeout"] == []
        assert facts["unreachable_fault"] == []

    def test_dead_verb_needs_a_caller_somewhere(self, tmp_path):
        index, name = _write_module(tmp_path, """
            class RpcServer:
                def __init__(self, handlers):
                    self.handlers = handlers

            class Server:
                def start(self):
                    self.srv = RpcServer({"ghost": self._handle_ghost})

                def _handle_ghost(self, msg, respond):
                    respond(ok=True)
            """)
        facts = rpc_protocol.analyze(index, server_paths=(name,),
                                     client_paths=(name,))
        assert facts["dead"] == ["ghost"]
        # a test-suite send keeps it alive (the liveness scan)
        (tmp_path / "test_x.py").write_text(
            "def test_g(c):\n    c.call('ghost', {})\n")
        facts = rpc_protocol.analyze(index, server_paths=(name,),
                                     client_paths=(name,),
                                     liveness_paths=("test_x.py",))
        assert facts["dead"] == []

    def test_worker_protocol_clean_at_head(self, ctx):
        findings = get_pass("rpc-protocol").run(ctx)
        assert not findings, "\n".join(repr(f) for f in findings)

    def test_head_verb_table_extracted(self, ctx):
        facts = rpc_protocol.analyze(ctx.ast)
        assert {"ping", "health", "submit", "prefill", "kv_push",
                "stage", "swap", "drain"} <= set(facts["verbs"])


class TestSwapBarrierPass:
    """Seeded controls: flip-before-stage reorder + stale engine set +
    unguarded flip vs the correct two-phase barrier, plus the head
    gate."""

    def test_detects_flip_before_stage(self, tmp_path):
        index, name = _write_module(tmp_path, """
            class Watcher:
                def poll_once_locked(self):
                    engines = list(self.engines)
                    for eng in engines:
                        eng.swap_params(staged=self.staged, version="v")
                    staged = [e.stage_params({}) for e in engines]
            """)
        got = swap_barrier.analyze(index, rel_paths=(name,))
        assert [r for r, *_ in got] == ["flip-before-stage"]
        assert swap_barrier.analyze(index, rel_paths=(name,)) == got

    def test_detects_stale_engine_set(self, tmp_path):
        index, name = _write_module(tmp_path, """
            class Watcher:
                def poll_once_locked(self):
                    staged = [e.stage_params({}) for e in self.local()]
                    for eng in self.engines():
                        eng.swap_params(staged=staged, version="v")
            """)
        got = swap_barrier.analyze(index, rel_paths=(name,))
        assert "stale-engine-set" in [r for r, *_ in got]

    def test_detects_stage_fallthrough_and_unguarded_flip(
            self, tmp_path):
        index, name = _write_module(tmp_path, """
            class Watcher:
                def poll_once_locked(self):
                    engines = list(self.engines)
                    try:
                        staged = [e.stage_params({}) for e in engines]
                    except Exception:
                        staged = []
                    for eng, v in zip(engines, staged):
                        eng.swap_params(staged=v, version="x")

            class Handle:
                def flip(self, version):
                    self.eng.swap_staged(version)
            """)
        rules = [r for r, *_ in
                 swap_barrier.analyze(index, rel_paths=(name,))]
        assert "stage-fallthrough" in rules
        assert "unguarded-flip" in rules

    def test_correct_barrier_is_clean(self, tmp_path):
        index, name = _write_module(tmp_path, """
            class GoodWatcher:
                def poll_once_locked(self):
                    engines = list(self.engines)
                    staged = [e.stage_params({}) for e in engines]
                    for eng, vals in zip(engines, staged):
                        eng.swap_params(staged=vals, version="v")

            class GoodHandle:
                def swap_staged(self, version):
                    self.eng.swap_staged(version)

                def handle_swap(self, msg):
                    staged = self.staged
                    if staged is None:
                        raise ValueError("no staged weights")
                    self.eng.swap_params(staged=staged, version=msg)
            """)
        assert swap_barrier.analyze(index, rel_paths=(name,)) == []

    def test_watcher_clean_at_head(self, ctx):
        findings = get_pass("swap-barrier").run(ctx)
        assert not findings, "\n".join(repr(f) for f in findings)


# ===================================== regression tests for fixed races
class TestServingRaceFixes:
    def test_admission_control_races_scheduler_safely(self, ctx):
        """PR fix: ContinuousBatcher.stats/_recent_waits are written by
        the scheduler thread and read by submit-side admission control;
        unsynchronized, sorted() over the live deque raises 'deque
        mutated during iteration'. Hammer admission from several caller
        threads while the scheduler streams decodes."""
        from mxnet_tpu.serving.batcher import ContinuousBatcher

        eng = ctx.programs.infer_engine
        b = ContinuousBatcher(eng, bucket_keys=(8,), slots=2,
                              max_new_tokens=4,
                              admit_max_wait_ms=10_000.0)
        errors = []
        rng = np.random.RandomState(0)
        prompts = [rng.randint(3, 60, (5,)).astype(np.int32)
                   for _ in range(24)]

        def feed(chunk):
            try:
                futs = [b.submit(p) for p in chunk]
                for f in futs:
                    try:
                        f.result(timeout=120)
                    except Exception:  # noqa: BLE001 - Backpressure ok
                        pass
            except BaseException as e:  # noqa: BLE001
                errors.append(e)

        threads = [threading.Thread(target=feed, args=(prompts[i::4],))
                   for i in range(4)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=180)
        b.stop()
        assert not errors, errors
        with b._stats_lock:
            assert b.stats["retired"] + b.stats["rejected"] >= 1

    def test_watcher_concurrent_polls_swap_once(self, ctx, tmp_path):
        """PR fix: poll_once is serialized — N concurrent polls of one
        newly committed checkpoint produce exactly ONE swap (previously
        both threads could pass the token check and double-stage)."""
        from mxnet_tpu import checkpoint_sharded as cs
        from mxnet_tpu.serving import CheckpointWatcher

        eng = ctx.programs.infer_engine
        cs.save_sharded(
            str(tmp_path),
            {n: p._data.data
             for n, p in eng._net.collect_params().items()})
        swaps = []
        w = CheckpointWatcher(eng, str(tmp_path), start=False,
                              on_swap=lambda v, p: swaps.append(v))
        results = []
        barrier = threading.Barrier(4)

        def poll():
            barrier.wait()
            results.append(w.poll_once())

        threads = [threading.Thread(target=poll) for _ in range(4)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=60)
        assert sum(1 for r in results if r is not None) == 1
        assert len(swaps) == 1

    def test_router_replica_list_reads_are_snapshots(self, ctx):
        """PR fix: Router._replicas iteration sites read a lock-held
        snapshot (the lock-order pass verifies statically; this pins
        the helper's behavior)."""
        findings = get_pass("lock-order").run(ctx)
        assert not any(f.key == "Router._replicas" for f in findings)


# ==================================================== tool shim compat
class TestToolShims:
    def test_shims_reexport_the_framework(self):
        import check_amp_purity
        import check_no_sync_in_step
        import check_sharding

        assert check_no_sync_in_step.find_violations is \
            no_sync.find_violations
        assert check_amp_purity.check_step_purity is \
            amp_purity.check_step_purity
        assert check_sharding.run_checks is sharding_placement.run_checks
