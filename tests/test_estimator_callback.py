"""gluon.contrib.Estimator + mx.callback + contrib layers.

Reference surfaces: ``python/mxnet/gluon/contrib/estimator/``,
``python/mxnet/callback.py``, ``gluon/contrib/nn`` [unverified].
"""

import logging
import os

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import gluon, nd
from mxnet_tpu.gluon import nn
from mxnet_tpu.gluon.contrib import Estimator
from mxnet_tpu.gluon.contrib.estimator import (
    EarlyStoppingHandler, LoggingHandler, CheckpointHandler, StoppingHandler,
)


def _toy_data(n=64, d=8, classes=4, batch=16):
    rng = np.random.RandomState(0)
    X = rng.rand(n, d).astype(np.float32)
    y = rng.randint(0, classes, n)
    return [
        (nd.array(X[i:i + batch]), nd.array(y[i:i + batch]))
        for i in range(0, n, batch)
    ]


def _net(classes=4):
    net = nn.HybridSequential()
    net.add(nn.Dense(16, activation="relu"), nn.Dense(classes))
    net.initialize()
    return net


class TestEstimator:
    def test_fit_runs_and_learns(self):
        net = _net()
        est = Estimator(
            net, gluon.loss.SoftmaxCrossEntropyLoss(),
            train_metrics=mx.metric.Accuracy(),
            trainer=gluon.Trainer(net.collect_params(), "adam",
                                  {"learning_rate": 5e-3}),
        )
        data = _toy_data()
        est.fit(data, epochs=3)
        l0 = float(est.train_loss_metric.get()[1])
        est.fit(data, epochs=10)
        l1 = float(est.train_loss_metric.get()[1])
        assert l1 < l0

    def test_validation_handler(self):
        net = _net()
        est = Estimator(
            net, gluon.loss.SoftmaxCrossEntropyLoss(),
            train_metrics=mx.metric.Accuracy(),
        )
        val = est.evaluate(_toy_data(n=32))
        names = [m.get()[0] for m in val]
        assert "val_loss" in names and "accuracy" in names

    def test_early_stopping(self):
        net = _net()
        est = Estimator(net, gluon.loss.SoftmaxCrossEntropyLoss())
        monitor = est.train_loss_metric

        class _Worse(EarlyStoppingHandler):
            def _improved(self, value):
                return False  # never improves

        h = _Worse(monitor, patience=1)
        est.fit(_toy_data(), epochs=50, event_handlers=[h])
        assert h.stop_training
        assert h.current_epoch < 50

    def test_checkpoint_handler(self, tmp_path):
        net = _net()
        est = Estimator(net, gluon.loss.SoftmaxCrossEntropyLoss())
        h = CheckpointHandler(str(tmp_path), epoch_period=1,
                              max_checkpoints=2)
        est.fit(_toy_data(), epochs=4, event_handlers=[h])
        files = sorted(os.listdir(tmp_path))
        assert len(files) == 2  # rolling window
        assert files[-1].endswith("epoch4.params")

    def test_batches_stop(self):
        net = _net()
        est = Estimator(net, gluon.loss.SoftmaxCrossEntropyLoss())
        seen = []

        class Counter(StoppingHandler):
            def batch_end(self, estimator, *a, **kw):
                seen.append(1)
                super().batch_end(estimator, *a, **kw)

        est.fit(_toy_data(), batches=3,
                event_handlers=[Counter(max_batch=3)])
        assert len(seen) == 3


class TestCallbacks:
    def test_speedometer_logs(self, caplog):
        sp = mx.callback.Speedometer(batch_size=16, frequent=2)
        m = mx.metric.Accuracy()
        m.update(nd.array([1, 1]), nd.array([[0.1, 0.9], [0.8, 0.2]]))

        class P:
            pass

        with caplog.at_level(logging.INFO, logger="mxnet_tpu.callback"):
            for nbatch in range(5):
                p = P()
                p.epoch, p.nbatch, p.eval_metric = 0, nbatch, m
                sp(p)
        assert any("samples/sec" in r.message for r in caplog.records)

    def test_do_checkpoint(self, tmp_path):
        from mxnet_tpu import symbol as sym

        x = sym.var("data")
        y = sym.FullyConnected(x, num_hidden=2, name="fc")
        cb = mx.callback.do_checkpoint(str(tmp_path / "m"))
        arg = {"fc_weight": nd.ones((2, 3)), "fc_bias": nd.zeros((2,))}
        cb(0, y, arg, {})
        assert os.path.exists(str(tmp_path / "m-symbol.json"))
        assert os.path.exists(str(tmp_path / "m-0001.params"))


class TestContribNN:
    def test_hybrid_concurrent(self):
        from mxnet_tpu.gluon.contrib.nn import HybridConcurrent, Identity

        blk = HybridConcurrent(axis=-1)
        blk.add(nn.Dense(3), nn.Dense(5), Identity())
        blk.initialize()
        x = nd.array(np.random.RandomState(0).rand(4, 7).astype(np.float32))
        out = blk(x)
        assert out.shape == (4, 3 + 5 + 7)
        blk.hybridize()
        out2 = blk(x)
        np.testing.assert_allclose(out.asnumpy(), out2.asnumpy(), rtol=2e-3,
                                   atol=1e-5)
