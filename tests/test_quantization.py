"""INT8 quantization (reference: ``python/mxnet/contrib/quantization.py``
naive-calibration flow [unverified])."""

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import nd
from mxnet_tpu.contrib import quantization as q
from mxnet_tpu import gluon
from mxnet_tpu.gluon import nn


def _r(*shape, seed=0):
    return np.random.RandomState(seed).randn(*shape).astype(np.float32)


class TestOps:
    def test_quantize_dequantize_roundtrip(self):
        x = nd.array(_r(16, 16))
        qx, mn, mx_ = nd._contrib_quantize_v2(x)
        assert qx.asnumpy().dtype == np.int8
        back = nd._contrib_dequantize(qx, mn, mx_)
        # int8 symmetric: error bounded by one quantum
        quantum = max(abs(float(mn.asnumpy())), abs(float(mx_.asnumpy()))) / 127
        assert np.abs(back.asnumpy() - x.asnumpy()).max() <= quantum + 1e-6

    def test_calib_range_clips(self):
        # 0.6 avoids the .5 rounding boundary (TPU f32 division lands a
        # hair below 63.5 and rounds differently than host)
        x = nd.array(np.array([[-10.0, 0.6, 10.0]], np.float32))
        qx, mn, mx_ = nd._contrib_quantize_v2(
            x, min_calib_range=-1.0, max_calib_range=1.0
        )
        np.testing.assert_array_equal(
            qx.asnumpy(), np.array([[-127, 76, 127]], np.int8)
        )


class TestQuantizeNet:
    def _net(self):
        net = nn.HybridSequential()
        net.add(nn.Dense(32, activation="relu"), nn.Dense(8))
        net.initialize()
        return net

    def test_quantized_forward_close_to_float(self):
        net = self._net()
        calib = [nd.array(_r(16, 12, seed=s)) for s in range(4)]
        ref = net(calib[0]).asnumpy()
        q.quantize_net(net, calib_data=[(c,) for c in calib])
        out = net(calib[0]).asnumpy()
        # int8 per-tensor keeps ~1% relative error on random data
        err = np.abs(out - ref).max() / (np.abs(ref).max() + 1e-9)
        assert err < 0.05, err

    def test_quantized_weights_are_int8(self):
        net = self._net()
        calib = [nd.array(_r(8, 12))]
        q.quantize_net(net, calib_data=[(c,) for c in calib])
        qd = list(net._children.values())[0]._q
        assert np.asarray(qd._w_q_t).dtype == np.int8

    def test_requires_calib_data(self):
        net = self._net()
        net(nd.array(_r(2, 12)))
        with pytest.raises(mx.base.MXNetError):
            q.quantize_net(net)

    def test_no_dense_raises(self):
        # conv layers are quantizable since round 3 — a net with NO
        # quantizable layer at all is what must raise now
        net = nn.HybridSequential()
        net.add(nn.Activation("relu"))
        with pytest.raises(mx.base.MXNetError):
            q.quantize_net(net, calib_data=[])


class TestReviewRegressions:
    def test_attribute_style_block_quantized(self):
        """Blocks calling children via attributes (self.fc) must actually
        run the quantized layer after quantize_net."""
        from mxnet_tpu import gluon

        class Net(gluon.Block):
            def __init__(self):
                super().__init__()
                with self.name_scope():
                    self.fc = nn.Dense(8)

            def forward(self, x):
                return self.fc(x)

        net = Net()
        net.initialize()
        calib = [nd.array(_r(16, 4, seed=s) * 3) for s in range(2)]
        ref = net(calib[0]).asnumpy()
        q.quantize_net(net, calib_data=[(c,) for c in calib])
        out = net(calib[0]).asnumpy()
        assert not np.array_equal(out, ref)  # int8 path actually ran
        err = np.abs(out - ref).max() / (np.abs(ref).max() + 1e-9)
        assert err < 0.05, err

    def test_save_parameters_after_quantize(self, tmp_path):
        net = nn.HybridSequential()
        net.add(nn.Dense(8, activation="relu"), nn.Dense(4))
        net.initialize()
        calib = [nd.array(_r(8, 4))]
        q.quantize_net(net, calib_data=[(c,) for c in calib])
        net.save_parameters(str(tmp_path / "q.params"))  # must not raise


class TestQuantizedConv:
    def _cnn(self):
        net = gluon.nn.HybridSequential()
        with net.name_scope():
            net.add(gluon.nn.Conv2D(8, 3, padding=1, activation="relu"))
            net.add(gluon.nn.Conv2D(16, 3, padding=1, strides=2,
                                    activation="relu"))
            net.add(gluon.nn.Dense(4))
        net.initialize(mx.initializer.Xavier())
        return net

    def test_conv_int8_close_to_float(self):
        from mxnet_tpu.contrib.quantization import quantize_net

        rng = np.random.RandomState(0)
        net = self._cnn()
        x = nd.array(rng.rand(4, 3, 8, 8).astype(np.float32))
        ref = net(x).asnumpy()
        quantize_net(net, calib_data=[x])
        out = net(x).asnumpy()
        # int8 with per-tensor scales: within a few percent of f32
        scale = np.abs(ref).max()
        assert np.abs(out - ref).max() < 0.1 * scale, \
            np.abs(out - ref).max() / scale

    def test_entropy_calibration_mode(self):
        from mxnet_tpu.contrib.quantization import (calib_ranges,
                                                    quantize_net)

        rng = np.random.RandomState(1)
        net = self._cnn()
        # heavy-tailed activations: entropy clips tighter than min/max
        x = nd.array((rng.randn(8, 3, 8, 8) ** 3).astype(np.float32))
        convs = [c for c in net if hasattr(c, "weight")]
        naive = calib_ranges(net, [x], convs, mode="naive")
        entropy = calib_ranges(net, [x], convs, mode="entropy")
        for k in naive:
            lo_n, hi_n = naive[k]
            lo_e, hi_e = entropy[k]
            assert hi_e > 0 and lo_e == -hi_e  # symmetric threshold
            assert hi_e <= max(abs(lo_n), abs(hi_n)) + 1e-6
        # e2e error check on MODERATE-tail data (entropy ~ naive there);
        # the cubed-gaussian asserts above already cover tail clipping
        x2 = nd.array(rng.randn(8, 3, 8, 8).astype(np.float32))
        ref = net(x2).asnumpy()
        quantize_net(net, calib_data=[x2], calib_mode="entropy")
        out = net(x2).asnumpy()
        scale = np.abs(ref).max()
        # threshold choice is near-naive on gaussians (sanity-checked at
        # ~4.2 sigma); the residual error is per-tensor int8 compounding
        # through 3 layers, same as naive mode would give. The tight
        # bound holds on the CPU suite (conftest pins matmul precision
        # to 'highest'); on the chip the float REFERENCE itself computes
        # at the TPU's default bf16-ish precision, so only the looser
        # execution-sanity bound applies there
        import jax as _jax
        tight = _jax.default_backend() == "cpu"
        bound = 0.35 if tight else 0.6
        assert np.percentile(np.abs(out - ref), 90) < bound * scale

    def test_entropy_threshold_clips_outliers(self):
        from mxnet_tpu.contrib.quantization import entropy_threshold

        # mass concentrated near zero + one far outlier: the KL-optimal
        # threshold should land well below the outlier
        hist = np.zeros(2048)
        hist[:256] = 1000.0
        hist[-1] = 1.0
        t = entropy_threshold(hist, bin_width=0.01)
        assert t < 0.5 * 2048 * 0.01, t

    def test_entropy_multi_batch_differing_ranges(self):
        # regression: batches with very different dynamic ranges must
        # merge onto one histogram grid — the threshold must be able to
        # exceed the FIRST batch's max
        from mxnet_tpu.contrib.quantization import calib_ranges

        net = nn.HybridSequential()
        with net.name_scope():
            net.add(nn.Conv2D(4, 1))
        net.initialize(mx.initializer.Xavier())
        rng = np.random.RandomState(0)
        small = nd.array((rng.rand(4, 3, 6, 6) * 0.9 + 0.05)
                         .astype(np.float32))
        big = nd.array((rng.rand(4, 3, 6, 6) * 9.0 + 0.5)
                       .astype(np.float32))
        conv = net[0]
        r = calib_ranges(net, [small, big], [conv], mode="entropy")
        (_, hi), = r.values()
        assert hi > 2.0, f"threshold {hi} stuck at first batch's range"


class TestQuantDepthRound4:
    """Per-channel conv scales, BN folding, int8 requantize chains, and
    the per-layer coverage report (round-4 depth items)."""

    def _make_cnn(self):
        import mxnet_tpu as mx
        from mxnet_tpu import gluon

        net = gluon.nn.HybridSequential()
        with net.name_scope():
            net.add(
                gluon.nn.Conv2D(8, kernel_size=3, padding=1, in_channels=1),
                gluon.nn.BatchNorm(in_channels=8),
                gluon.nn.Activation("relu"),
                gluon.nn.Conv2D(16, kernel_size=3, padding=1,
                                in_channels=8, activation="relu"),
                gluon.nn.MaxPool2D(2, 2),
                gluon.nn.Flatten(),
                gluon.nn.Dense(4),
            )
        net.initialize(mx.initializer.Xavier())
        return net

    def _synthetic(self, n=256, seed=0):
        # 4-class synthetic: quadrant of the bright blob in an 8x8 image
        rng = np.random.RandomState(seed)
        x = rng.rand(n, 1, 8, 8).astype(np.float32) * 0.3
        y = rng.randint(0, 4, n)
        for i, cls in enumerate(y):
            r, c = divmod(int(cls), 2)
            x[i, 0, r * 4:r * 4 + 4, c * 4:c * 4 + 4] += 1.0
        return x, y.astype(np.float32)

    def test_int8_chain_accuracy_within_1pct(self):
        import mxnet_tpu as mx
        from mxnet_tpu import autograd, gluon, nd
        from mxnet_tpu.contrib.quantization import quantize_net

        mx.random.seed(0)
        net = self._make_cnn()
        x, y = self._synthetic(256)
        xt, yt = nd.array(x), nd.array(y)
        loss_fn = gluon.loss.SoftmaxCrossEntropyLoss()
        trainer = gluon.Trainer(net.collect_params(), "sgd",
                                {"learning_rate": 0.1, "momentum": 0.9})
        for _ in range(30):
            with autograd.record():
                loss = loss_fn(net(xt), yt)
            loss.backward()
            trainer.step(256)
        xe, ye = self._synthetic(512, seed=1)
        float_pred = net(nd.array(xe)).asnumpy().argmax(1)
        float_acc = (float_pred == ye).mean()
        assert float_acc > 0.95, f"float net undertrained: {float_acc}"

        qnet = quantize_net(net, calib_data=[xt], verbose=True)
        report = qnet._quantization_report
        # both convs int8, first one chained into the second
        conv_rows = [r for r in report if r[1] == "Conv2D"]
        assert len(conv_rows) == 2
        assert conv_rows[0][2] == "int8-chained", conv_rows
        assert conv_rows[1][2] == "int8", conv_rows
        assert "fused bn+act" in conv_rows[0][3]
        assert "fused pool" in conv_rows[1][3] or "pool" in conv_rows[1][3]
        dense_rows = [r for r in report if r[1] == "Dense"]
        assert len(dense_rows) == 1 and dense_rows[0][2] == "int8"

        q_pred = qnet(nd.array(xe)).asnumpy().argmax(1)
        q_acc = (q_pred == ye).mean()
        assert q_acc >= float_acc - 0.01, \
            f"int8 accuracy {q_acc} dropped >1% below float {float_acc}"

    def test_report_names_float_leftovers(self):
        import mxnet_tpu as mx
        from mxnet_tpu import gluon, nd
        from mxnet_tpu.contrib.quantization import quantize_net

        net = gluon.nn.HybridSequential()
        with net.name_scope():
            net.add(gluon.nn.Conv2D(4, kernel_size=3, in_channels=1,
                                    activation="tanh"),  # not fusable
                    gluon.nn.Flatten(),
                    gluon.nn.Dense(3))
        net.initialize()
        x = nd.array(np.random.rand(4, 1, 6, 6).astype(np.float32))
        net(x)
        qnet = quantize_net(net, calib_data=[x])
        report = qnet._quantization_report
        # non-relu act convs quantize with the activation in f32 after
        # dequant (review round-4: the fusion rewrite must not LOSE the
        # pre-existing int8 coverage)
        act_rows = [r for r in report if "f32 activation" in r[3]]
        assert act_rows and act_rows[0][2] == "int8"

    def test_per_channel_scales_beat_per_tensor_on_outlier_filters(self):
        from mxnet_tpu.contrib.quantization import (_quantize_per_channel,
                                                    _quantize_symmetric)

        rng = np.random.RandomState(0)
        w = rng.randn(8, 4, 3, 3).astype(np.float32) * 0.01
        w[0] *= 100.0  # outlier filter destroys the per-tensor scale
        import jax.numpy as jnp

        qc, sc = _quantize_per_channel(jnp.asarray(w))
        qt, st = _quantize_symmetric(jnp.asarray(w))
        rec_c = np.asarray(qc, np.float32) * np.asarray(sc).reshape(-1, 1, 1, 1)
        rec_t = np.asarray(qt, np.float32) * st
        err_c = np.abs(rec_c[1:] - w[1:]).max()
        err_t = np.abs(rec_t[1:] - w[1:]).max()
        assert err_c < err_t / 10


class TestQuantChainSafety:
    """Review round-4: chaining must only happen where execution order
    is child order (Sequential); ceil_mode pools must not fold."""

    def test_parallel_branch_container_does_not_chain(self):
        import mxnet_tpu as mx
        from mxnet_tpu import nd
        from mxnet_tpu.contrib.quantization import quantize_net
        from mxnet_tpu.gluon import nn
        from mxnet_tpu.gluon.block import Block

        class TwoBranch(Block):
            def __init__(self):
                super().__init__()
                with self.name_scope():
                    self.a = nn.Conv2D(4, kernel_size=1, in_channels=2)
                    self.b = nn.Conv2D(4, kernel_size=1, in_channels=2)

            def forward(self, x, *args):
                return nd.concat(self.a(x), self.b(x), dim=1)

        net = TwoBranch()
        net.initialize()
        x = nd.array(np.random.rand(2, 2, 5, 5).astype(np.float32))
        net(x)
        qnet = quantize_net(net, calib_data=[x])
        # both convs int8 but NOT chained (parallel branches) — and the
        # rewritten net must run without a QTensor reaching concat
        out = qnet(x)
        assert out.shape == (2, 8, 5, 5)
        assert all(r[2] == "int8" for r in qnet._quantization_report
                   if r[1] == "Conv2D")

    def test_ceil_mode_pool_not_folded(self):
        import mxnet_tpu as mx
        from mxnet_tpu import nd
        from mxnet_tpu.contrib.quantization import quantize_net
        from mxnet_tpu.gluon import nn

        net = nn.HybridSequential()
        with net.name_scope():
            net.add(nn.Conv2D(4, kernel_size=3, in_channels=1,
                              activation="relu"),
                    nn.MaxPool2D(pool_size=3, strides=2, ceil_mode=True),
                    nn.Flatten(), nn.Dense(3))
        net.initialize()
        x = nd.array(np.random.rand(2, 1, 12, 12).astype(np.float32))
        ref = net(x).asnumpy()
        qnet = quantize_net(net, calib_data=[x])
        conv_row = [r for r in qnet._quantization_report
                    if r[1] == "Conv2D"][0]
        assert "pool" not in conv_row[3]  # ceil_mode pool left unfolded
        out = qnet(x).asnumpy()
        assert out.shape == ref.shape  # 'full' convention preserved

    def test_excluded_bn_not_folded(self):
        import mxnet_tpu as mx
        from mxnet_tpu import nd
        from mxnet_tpu.contrib.quantization import quantize_net
        from mxnet_tpu.gluon import nn

        net = nn.HybridSequential()
        with net.name_scope():
            net.add(nn.Conv2D(4, kernel_size=3, in_channels=1),
                    nn.BatchNorm(in_channels=4),
                    nn.Flatten(), nn.Dense(3))
        net.initialize()
        bn = net[1]
        x = nd.array(np.random.rand(2, 1, 8, 8).astype(np.float32))
        net(x)
        qnet = quantize_net(net, calib_data=[x], exclude=(bn,))
        conv_row = [r for r in qnet._quantization_report
                    if r[1] == "Conv2D"][0]
        assert "bn" not in conv_row[3]  # stayed a separate float BN
        assert qnet._children[list(qnet._children.keys())[1]] is bn


def test_quantized_op_forms():
    """Reference INT8 op names as registry ops: quantized_dense /
    quantized_conv / requantize with (data, min, max) range operands."""
    import jax.numpy as jnp

    rng = np.random.RandomState(0)
    xf = rng.randn(4, 8).astype(np.float32)
    wf = (rng.randn(3, 8) * 0.1).astype(np.float32)
    b = rng.randn(3).astype(np.float32)
    xs = np.abs(xf).max() / 127.0
    ws = np.abs(wf).max() / 127.0
    xq = np.clip(np.round(xf / xs), -127, 127).astype(np.int8)
    wq = np.clip(np.round(wf / ws), -127, 127).astype(np.int8)
    out, lo, hi = mx.nd._contrib_quantized_dense(
        nd.array(xq), nd.array(wq), nd.array(b),
        nd.array(np.float32(-np.abs(xf).max())),
        nd.array(np.float32(np.abs(xf).max())),
        nd.array(np.float32(-np.abs(wf).max())),
        nd.array(np.float32(np.abs(wf).max())), num_hidden=3)
    ref = xf @ wf.T + b
    np.testing.assert_allclose(out.asnumpy(), ref, rtol=0.1, atol=0.05)
    assert float(lo.asnumpy()) < 0 < float(hi.asnumpy())

    # requantize to int8 at a calibrated range
    q, qlo, qhi = mx.nd._contrib_requantize(
        out, lo, hi, min_calib_range=-3.0, max_calib_range=3.0)
    assert q.asnumpy().dtype == np.int8
    back = q.asnumpy().astype(np.float32) * (3.0 / 127.0)
    np.testing.assert_allclose(back, np.clip(ref, -3, 3), atol=0.1)

    # quantized conv
    imgf = rng.randn(2, 3, 6, 6).astype(np.float32)
    kf = (rng.randn(4, 3, 3, 3) * 0.1).astype(np.float32)
    is_, ks = np.abs(imgf).max() / 127.0, np.abs(kf).max() / 127.0
    iq = np.clip(np.round(imgf / is_), -127, 127).astype(np.int8)
    kq = np.clip(np.round(kf / ks), -127, 127).astype(np.int8)
    co, clo, chi = mx.nd._contrib_quantized_conv(
        nd.array(iq), nd.array(kq), None,
        nd.array(np.float32(-np.abs(imgf).max())),
        nd.array(np.float32(np.abs(imgf).max())),
        nd.array(np.float32(-np.abs(kf).max())),
        nd.array(np.float32(np.abs(kf).max())),
        kernel=(3, 3), num_filter=4, no_bias=True)
    import jax
    refc = np.asarray(jax.lax.conv_general_dilated(
        jnp.asarray(imgf), jnp.asarray(kf), (1, 1), [(0, 0), (0, 0)],
        dimension_numbers=("NCHW", "OIHW", "NCHW")))
    np.testing.assert_allclose(co.asnumpy(), refc, rtol=0.15, atol=0.1)


def test_quantized_dense_no_bias_reference_arity():
    """Review fix: the 6-input no_bias form (bias operand omitted) must
    bind correctly — reference-derived graphs use this arity."""
    rng = np.random.RandomState(1)
    xf = rng.randn(2, 4).astype(np.float32)
    wf = (rng.randn(3, 4) * 0.1).astype(np.float32)
    xs, ws = np.abs(xf).max() / 127.0, np.abs(wf).max() / 127.0
    xq = np.clip(np.round(xf / xs), -127, 127).astype(np.int8)
    wq = np.clip(np.round(wf / ws), -127, 127).astype(np.int8)
    out, _, _ = mx.nd._contrib_quantized_dense(
        nd.array(xq), nd.array(wq),
        nd.array(np.float32(-np.abs(xf).max())),
        nd.array(np.float32(np.abs(xf).max())),
        nd.array(np.float32(-np.abs(wf).max())),
        nd.array(np.float32(np.abs(wf).max())), no_bias=True)
    np.testing.assert_allclose(out.asnumpy(), xf @ wf.T, rtol=0.1,
                               atol=0.05)
