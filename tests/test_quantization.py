"""INT8 quantization (reference: ``python/mxnet/contrib/quantization.py``
naive-calibration flow [unverified])."""

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import nd
from mxnet_tpu.contrib import quantization as q
from mxnet_tpu.gluon import nn


def _r(*shape, seed=0):
    return np.random.RandomState(seed).randn(*shape).astype(np.float32)


class TestOps:
    def test_quantize_dequantize_roundtrip(self):
        x = nd.array(_r(16, 16))
        qx, mn, mx_ = nd._contrib_quantize_v2(x)
        assert qx.asnumpy().dtype == np.int8
        back = nd._contrib_dequantize(qx, mn, mx_)
        # int8 symmetric: error bounded by one quantum
        quantum = max(abs(float(mn.asnumpy())), abs(float(mx_.asnumpy()))) / 127
        assert np.abs(back.asnumpy() - x.asnumpy()).max() <= quantum + 1e-6

    def test_calib_range_clips(self):
        # 0.6 avoids the .5 rounding boundary (TPU f32 division lands a
        # hair below 63.5 and rounds differently than host)
        x = nd.array(np.array([[-10.0, 0.6, 10.0]], np.float32))
        qx, mn, mx_ = nd._contrib_quantize_v2(
            x, min_calib_range=-1.0, max_calib_range=1.0
        )
        np.testing.assert_array_equal(
            qx.asnumpy(), np.array([[-127, 76, 127]], np.int8)
        )


class TestQuantizeNet:
    def _net(self):
        net = nn.HybridSequential()
        net.add(nn.Dense(32, activation="relu"), nn.Dense(8))
        net.initialize()
        return net

    def test_quantized_forward_close_to_float(self):
        net = self._net()
        calib = [nd.array(_r(16, 12, seed=s)) for s in range(4)]
        ref = net(calib[0]).asnumpy()
        q.quantize_net(net, calib_data=[(c,) for c in calib])
        out = net(calib[0]).asnumpy()
        # int8 per-tensor keeps ~1% relative error on random data
        err = np.abs(out - ref).max() / (np.abs(ref).max() + 1e-9)
        assert err < 0.05, err

    def test_quantized_weights_are_int8(self):
        net = self._net()
        calib = [nd.array(_r(8, 12))]
        q.quantize_net(net, calib_data=[(c,) for c in calib])
        qd = list(net._children.values())[0]._q
        assert np.asarray(qd._w_q_t).dtype == np.int8

    def test_requires_calib_data(self):
        net = self._net()
        net(nd.array(_r(2, 12)))
        with pytest.raises(mx.base.MXNetError):
            q.quantize_net(net)

    def test_no_dense_raises(self):
        net = nn.HybridSequential()
        net.add(nn.Conv2D(4, kernel_size=1))
        net.initialize()
        with pytest.raises(mx.base.MXNetError):
            q.quantize_net(net, calib_data=[])


class TestReviewRegressions:
    def test_attribute_style_block_quantized(self):
        """Blocks calling children via attributes (self.fc) must actually
        run the quantized layer after quantize_net."""
        from mxnet_tpu import gluon

        class Net(gluon.Block):
            def __init__(self):
                super().__init__()
                with self.name_scope():
                    self.fc = nn.Dense(8)

            def forward(self, x):
                return self.fc(x)

        net = Net()
        net.initialize()
        calib = [nd.array(_r(16, 4, seed=s) * 3) for s in range(2)]
        ref = net(calib[0]).asnumpy()
        q.quantize_net(net, calib_data=[(c,) for c in calib])
        out = net(calib[0]).asnumpy()
        assert not np.array_equal(out, ref)  # int8 path actually ran
        err = np.abs(out - ref).max() / (np.abs(ref).max() + 1e-9)
        assert err < 0.05, err

    def test_save_parameters_after_quantize(self, tmp_path):
        net = nn.HybridSequential()
        net.add(nn.Dense(8, activation="relu"), nn.Dense(4))
        net.initialize()
        calib = [nd.array(_r(8, 4))]
        q.quantize_net(net, calib_data=[(c,) for c in calib])
        net.save_parameters(str(tmp_path / "q.params"))  # must not raise
