"""Tier-1 compile-count lint: NO jitted step path may recompile on a
repeated identical-shape call.

Counts real XLA ``backend_compile`` events (``jax.monitoring``) around a
second call with bit-identical avals — any nonzero count is a trace-cache
regression (object identity leaking into a cache key, a fresh callable
per call, env flags read mid-trace, ...), the exact class of bug that
turns into a TPU compile storm at scale.
"""

import numpy as np

import jax

import mxnet_tpu as mx

_COMPILES = []
jax.monitoring.register_event_duration_secs_listener(
    lambda e, d, **kw: _COMPILES.append(e) if "backend_compile" in e
    else None)


def _compiles_during(fn):
    n0 = len(_COMPILES)
    fn()
    return len(_COMPILES) - n0


def test_trainstep_repeated_identical_shape_never_recompiles():
    from mxnet_tpu import gluon, nd, optimizer as opt
    from mxnet_tpu.parallel import TrainStep

    net = gluon.nn.Dense(4)
    net.initialize()
    net(nd.zeros((2, 8)))
    step = TrainStep(net, gluon.loss.L2Loss(), opt.SGD(learning_rate=0.1))
    x = mx.nd.array(np.ones((4, 8), "float32"))
    y = mx.nd.array(np.ones((4, 4), "float32"))
    float(step(x, y).asscalar())  # first call compiles
    assert _compiles_during(lambda: float(step(x, y).asscalar())) == 0
    assert step.compile_guard.signatures == 1


def test_cachedop_repeated_identical_shape_never_recompiles():
    from mxnet_tpu import autograd, gluon, nd

    net = gluon.nn.Dense(4)
    net.initialize()
    net.hybridize()
    x = nd.array(np.ones((4, 8), "float32"))
    net(x)  # first call compiles

    def fwd():
        net(x).asnumpy()

    assert _compiles_during(fwd) == 0

    def fwd_bwd():
        xg = nd.array(np.ones((4, 8), "float32"))
        xg.attach_grad()
        with autograd.record():
            y = net(xg).sum()
        y.backward()
        xg.grad.asnumpy()

    fwd_bwd()  # first recorded call compiles the vjp program
    assert _compiles_during(fwd_bwd) == 0
    assert net._cached_op._guard.steady_state_recompiles == 0


def test_amp_remat_trainstep_adds_zero_steady_state_recompiles():
    """amp + remat must not change the shape-stability contract: after
    warmup over one signature, repeated identical-shape steps emit ZERO
    backend_compile events and zero steady-state recompiles — the
    dynamic loss-scale state rides as an operand, never a retrace."""
    from mxnet_tpu import amp, gluon, nd, optimizer as opt
    from mxnet_tpu.gluon import nn
    from mxnet_tpu.parallel import TrainStep

    def build(**kw):
        net = nn.HybridSequential()
        net.add(nn.Dense(16, flatten=False),
                nn.LayerNorm(in_channels=16),
                nn.Dense(4, flatten=False))
        net.initialize()
        net(nd.zeros((2, 8)))
        return TrainStep(net, gluon.loss.L2Loss(),
                         opt.AdamW(learning_rate=1e-3), **kw)

    x = mx.nd.array(np.ones((4, 8), "float32"))
    y = mx.nd.array(np.ones((4, 4), "float32"))
    for kw in ({"amp": "bfloat16", "remat": "dots_saveable"},
               {"amp": "float16",
                "loss_scaler": amp.LossScaler(scale_window=2)}):
        step = build(**kw)
        step.warmup([(((4, 8), "float32"), ((4, 4), "float32"))])
        float(step(x, y).asscalar())  # first real call: warmed, no compile
        assert _compiles_during(lambda: float(step(x, y).asscalar())) == 0
        assert step.compile_guard.steady_state_recompiles == 0
        assert step.compile_guard.signatures == 1


def test_eager_op_repeated_identical_shape_never_recompiles():
    a = mx.nd.array(np.ones((8, 8), "float32"))
    b = mx.nd.array(np.ones((8, 8), "float32"))
    (a * b + 1).sum().asnumpy()  # first call compiles (bulk segment)
    assert _compiles_during(
        lambda: (a * b + 1).sum().asnumpy()) == 0
