"""Extension ABI v2 (shape/dtype inference, multi-output, params) and the
pure-Python CustomOp path (reference ``lib_api.h`` v2 surface +
``custom.cc`` [unverified])."""

import os
import subprocess

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import autograd, nd

_SRC = os.path.join(os.path.dirname(__file__), "..", "examples",
                    "extensions", "custom_ops_v2.cc")


@pytest.fixture(scope="module")
def v2_lib(tmp_path_factory):
    so = str(tmp_path_factory.mktemp("ext") / "libcustom_v2.so")
    try:
        subprocess.run(["g++", "-O2", "-shared", "-fPIC", "-o", so, _SRC],
                       check=True, capture_output=True, timeout=120)
    except Exception as e:  # noqa: BLE001
        pytest.skip(f"no C++ toolchain: {e}")
    if "scaled_rowsum" not in [n for n in dir(nd)]:
        mx.library.load(so, verbose=False)
    return so


class TestAbiV2:
    def test_shape_inference_and_param(self, v2_lib):
        x = nd.array(np.arange(12, dtype=np.float32).reshape(3, 4))
        out = nd.scaled_rowsum(x, alpha=2.0)
        assert out.shape == (3,)  # (N, D) -> (N,): NOT elementwise
        np.testing.assert_allclose(
            out.asnumpy(), 2.0 * np.arange(12).reshape(3, 4).sum(1)
        )

    def test_default_param(self, v2_lib):
        x = nd.ones((2, 5))
        np.testing.assert_allclose(nd.scaled_rowsum(x).asnumpy(), [5., 5.])

    def test_multi_output_int_dtype(self, v2_lib):
        x = nd.array(np.array([7, -3, 12, 0], np.int32), dtype="int32")
        mn, mx_ = nd.minmax_i32(x)
        assert mn.asnumpy()[0] == -3
        assert mx_.asnumpy()[0] == 12
        assert mn.dtype == np.int32

    def test_backward_through_tape(self, v2_lib):
        x = nd.array(np.ones((2, 3), np.float32))
        x.attach_grad()
        with autograd.record():
            y = nd.scaled_rowsum(x, alpha=3.0)
            loss = (y * nd.array(np.array([1.0, 2.0]))).sum()
        loss.backward()
        want = np.repeat(np.array([[3.0], [6.0]]), 3, axis=1)
        np.testing.assert_allclose(x.grad.asnumpy(), want)


class TestPythonCustomOp:
    @classmethod
    def setup_class(cls):
        if "sigmoid2x" in mx.operator.get_all_registered():
            return

        @mx.operator.register("sigmoid2x")
        class Sigmoid2xProp(mx.operator.CustomOpProp):
            def create_operator(self, ctx, shapes, dtypes):
                return _Sigmoid2x()

        class _Sigmoid2x(mx.operator.CustomOp):
            def forward(self, is_train, req, in_data, out_data, aux):
                y = 2.0 / (1.0 + np.exp(-in_data[0].asnumpy()))
                self.assign(out_data[0], req[0], nd.array(y))

            def backward(self, req, out_grad, in_data, out_data, in_grad,
                         aux):
                y = out_data[0].asnumpy() / 2.0
                g = out_grad[0].asnumpy() * 2.0 * y * (1.0 - y)
                self.assign(in_grad[0], req[0], nd.array(g))

    def test_forward_both_entry_points(self):
        x = nd.array(np.zeros((2, 2), np.float32))
        np.testing.assert_allclose(nd.sigmoid2x(x).asnumpy(), 1.0)
        np.testing.assert_allclose(
            nd.Custom(x, op_type="sigmoid2x").asnumpy(), 1.0
        )

    def test_backward(self):
        rng = np.random.RandomState(0)
        x = nd.array(rng.randn(3, 4).astype(np.float32))
        x.attach_grad()
        with autograd.record():
            y = nd.sigmoid2x(x)
            loss = y.sum()
        loss.backward()
        s = 1.0 / (1.0 + np.exp(-x.asnumpy()))
        np.testing.assert_allclose(x.grad.asnumpy(), 2 * s * (1 - s),
                                   rtol=1e-5, atol=1e-6)

    def test_unknown_op_type_raises(self):
        with pytest.raises(mx.MXNetError, match="unknown op_type"):
            nd.Custom(nd.zeros((1,)), op_type="nope")

    def test_multi_output_prop(self):
        if "split_halves" not in mx.operator.get_all_registered():
            @mx.operator.register("split_halves")
            class SplitProp(mx.operator.CustomOpProp):
                def list_outputs(self):
                    return ["lo", "hi"]

                def infer_shape(self, in_shape):
                    n = in_shape[0][0] // 2
                    return in_shape, [[n], [n]], []

                def create_operator(self, ctx, shapes, dtypes):
                    return _Split()

            class _Split(mx.operator.CustomOp):
                def forward(self, is_train, req, in_data, out_data, aux):
                    a = in_data[0].asnumpy()
                    n = len(a) // 2
                    self.assign(out_data[0], req[0], nd.array(a[:n]))
                    self.assign(out_data[1], req[1], nd.array(a[n:]))

        lo, hi = nd.split_halves(nd.array(np.arange(6, dtype=np.float32)))
        np.testing.assert_allclose(lo.asnumpy(), [0, 1, 2])
        np.testing.assert_allclose(hi.asnumpy(), [3, 4, 5])
