"""Sparse NDArray facade (VERDICT weak #6): row_sparse/csr creation,
metadata, conversion, retain, sparse dot, kvstore interplay, and the
sparse-embedding training path (dense scatter-add on TPU replacing the
reference's row_sparse gradient machinery,
``src/operator/tensor/dot.cc`` + embedding sparse-grad [unverified])."""

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import autograd, gluon, nd
from mxnet_tpu.ndarray import sparse
from mxnet_tpu.test_utils import rand_ndarray


class TestCreation:
    def test_row_sparse_from_values_indices(self):
        vals = np.array([[1.0, 2.0], [3.0, 4.0]], np.float32)
        rs = sparse.row_sparse_array((vals, [1, 3]), shape=(5, 2))
        assert rs.stype == "row_sparse"
        assert rs.shape == (5, 2)
        dense = rs.asnumpy()
        np.testing.assert_allclose(dense[1], [1.0, 2.0])
        np.testing.assert_allclose(dense[3], [3.0, 4.0])
        np.testing.assert_allclose(dense[0], [0.0, 0.0])
        np.testing.assert_array_equal(rs.indices.asnumpy(), [1, 3])
        np.testing.assert_allclose(rs.values.asnumpy(), vals)

    def test_csr_metadata(self):
        dense = np.array([[0, 1, 0], [2, 0, 3], [0, 0, 0]], np.float32)
        csr = sparse.CSRNDArray(mx.nd.array(dense).data)
        np.testing.assert_array_equal(csr.indptr.asnumpy(), [0, 1, 3, 3])
        np.testing.assert_array_equal(csr.indices.asnumpy(), [1, 0, 2])
        np.testing.assert_allclose(csr.values.asnumpy(), [1, 2, 3])

    def test_tostype_round_trip(self):
        rs = rand_ndarray((6, 3), "row_sparse", density=0.5)
        d = rs.tostype("default")
        np.testing.assert_allclose(d.asnumpy(), rs.asnumpy())
        with pytest.raises(mx.base.MXNetError):
            rs.tostype("csr")

    def test_rand_ndarray_sparse(self):
        rs = rand_ndarray((50, 4), "row_sparse", density=0.3)
        frac = (np.abs(rs.asnumpy()).sum(axis=1) > 0).mean()
        assert 0.05 < frac < 0.65
        csr = rand_ndarray((20, 20), "csr", density=0.2)
        nnz_frac = (csr.asnumpy() != 0).mean()
        assert 0.05 < nnz_frac < 0.4


class TestOpsOverSparse:
    def test_retain(self):
        rs = sparse.row_sparse_array(
            (np.ones((3, 2), np.float32), [0, 2, 4]), shape=(5, 2)
        )
        kept = rs.retain([0, 4])
        out = kept.asnumpy()
        np.testing.assert_allclose(out[0], [1, 1])
        np.testing.assert_allclose(out[2], [0, 0])  # dropped
        np.testing.assert_allclose(out[4], [1, 1])

    def test_dense_dot_with_csr(self):
        csr = rand_ndarray((8, 5), "csr", density=0.4)
        w = rand_ndarray((5, 3))
        out = nd.dot(csr, w)
        np.testing.assert_allclose(
            out.asnumpy(), csr.asnumpy() @ w.asnumpy(), rtol=1e-5
        )

    def test_kvstore_push_sparse_facade(self):
        kv = mx.kv.create("local")
        kv.init("e", nd.zeros((6, 2)))
        g = sparse.row_sparse_array(
            (np.ones((2, 2), np.float32), [1, 4]), shape=(6, 2)
        )
        kv.push("e", g)
        out = nd.zeros((6, 2))
        kv.pull("e", out=out)
        np.testing.assert_allclose(out.asnumpy()[1], [1, 1])
        np.testing.assert_allclose(out.asnumpy()[0], [0, 0])


class TestSparseEmbeddingTraining:
    def test_embedding_grad_is_scatter(self):
        """The reference's row_sparse embedding gradient == our dense
        scatter-add: only looked-up rows receive gradient."""
        emb = gluon.nn.Embedding(10, 4)
        emb.initialize()
        ids = nd.array(np.array([1, 3, 3], np.int32), dtype="int32")
        emb.weight.data()  # materialize
        trainer = gluon.Trainer(emb.collect_params(), "sgd",
                                {"learning_rate": 1.0})
        before = emb.weight.data().asnumpy().copy()
        with autograd.record():
            out = emb(ids)
            out.sum().backward()
        g = emb.weight.grad().asnumpy()
        assert np.all(g[1] == 1.0)
        assert np.all(g[3] == 2.0)  # id 3 appears twice: accumulated
        untouched = [i for i in range(10) if i not in (1, 3)]
        assert np.all(g[untouched] == 0.0)
        trainer.step(1)
        after = emb.weight.data().asnumpy()
        np.testing.assert_allclose(after[untouched], before[untouched])
        assert not np.allclose(after[1], before[1])


class TestDebugAndOnnx:
    def test_check_nan(self):
        from mxnet_tpu import debug

        debug.check_nan(nd.ones((2, 2)))  # clean passes
        bad = nd.array(np.array([1.0, np.nan], np.float32))
        with pytest.raises(mx.base.MXNetError):
            debug.check_nan(bad, name="loss")

    def test_nan_guard_restores_flag(self):
        import jax
        from mxnet_tpu import debug

        prev = jax.config.jax_debug_nans
        with debug.nan_guard():
            assert jax.config.jax_debug_nans
        assert jax.config.jax_debug_nans == prev

    def test_onnx_available_round4(self):
        # round 4 replaced the availability gate with real converters
        # over the vendored schema subset (tests/test_onnx.py covers
        # round trips); the gate assertion flips accordingly
        from mxnet_tpu import onnx as mxonnx

        assert mxonnx.is_available()
        with pytest.raises(mx.base.MXNetError, match="expects a Symbol"):
            mxonnx.export_model(None, {})
