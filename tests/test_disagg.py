"""Disaggregated prefill/decode over the cross-process plane (ISSUE 11).

Contracts under test:

- KV HANDOFF: ``PrefillEngine`` frames adopted by a ``ContinuousBatcher``
  produce BIT-identical greedy tokens to the co-scheduled path — through
  the in-process adopt API, through ``pack_frames``/``unpack_frames``
  (the ``kv_push`` wire format), and through the ``MXTPU_KV_SPILL_DIR``
  filesystem fallback; any unusable handoff re-prefills from the prompt
  (``disagg/re_prefills``) and the request is served anyway.
- SLO-AWARE PLACEMENT: the router scores replicas by predicted wait
  (rolling p50 × backlog) instead of raw backlog, equal scores rotate
  round-robin (the PR-7 docstring promised this; ``min()`` never did
  it), request classes carry per-class deadline defaults, and batch
  traffic sheds before interactive under a degraded fleet.
- FAULT POINTS: ``transport.kv_push`` and ``router.place`` ride the
  standard ``times/after/delay/match`` grammar; a kv_push failure
  degrades to re-prefill, a placement failure retries.
- ELASTICITY: ``tools.launch.FleetScaler`` grows on sustained
  occupancy/shed pressure and retires when idle under
  ``MXTPU_SCALE_MIN/MAX/COOLDOWN_S``; ``Router.retire_replica`` excludes
  the replica from placement and its eviction schedules no respawn.
- CHAOS (cross-process): SIGKILL a prefill worker mid-handoff under
  load — 0/60 requests lost, post-recovery greedy tokens bit-identical
  to a co-scheduled fleet.
"""

import json
import os
import threading
import time

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import nd
from mxnet_tpu.base import MXNetError
from mxnet_tpu.gluon.model_zoo.transformer import TransformerModel
from mxnet_tpu.parallel import InferStep
from mxnet_tpu.serving import (Backpressure, ContinuousBatcher,
                               DeadlineExceeded, DynamicBatcher,
                               PrefillEngine, RemoteReplica, Replica,
                               ReplicaUnavailable, Router, RpcClient,
                               disagg, faults)
from mxnet_tpu.serving.disagg import (HandoffStash, load_spilled,
                                      pack_frames, spill_frames,
                                      unpack_frames)
from mxnet_tpu.serving.worker import (ServingWorker, make_transformer_net,
                                      spawn_worker)

WORKER_ENV = {"JAX_PLATFORMS": os.environ.get("MXTPU_TEST_PLATFORM",
                                              "cpu")}


def _make_net(seed=0, prefix="serve_net_"):
    np.random.seed(seed)
    mx.random.seed(seed)
    net = TransformerModel(src_vocab=61, tgt_vocab=61, units=16,
                           hidden_size=32, num_layers=1, num_heads=2,
                           max_length=64, dropout=0.0, prefix=prefix)
    net.initialize(mx.initializer.Xavier())
    net._probe_shapes(nd.zeros((2, 8), dtype="int32"),
                      nd.zeros((2, 8), dtype="int32"))
    return net


def _prompts(rng, n, lmin=3, lmax=8):
    return [rng.randint(3, 61, (rng.randint(lmin, lmax + 1),))
            .astype(np.int32) for _ in range(n)]


@pytest.fixture(scope="module")
def prefill_engine():
    eng = InferStep(_make_net(0), max_len=24)
    return PrefillEngine(eng, (8,), warmup=True)


@pytest.fixture(scope="module")
def decode_batcher():
    eng = InferStep(_make_net(0), max_len=24)
    bat = ContinuousBatcher(eng, (8,), slots=2, max_new_tokens=4,
                            warmup=True, name="disagg-dec")
    yield bat
    bat.stop()


@pytest.fixture(scope="module")
def shared_engine():
    eng = InferStep(_make_net(0), max_len=24)
    eng.warmup([(2, 8)], max_new_tokens=4)
    return eng


@pytest.fixture(autouse=True)
def _clean_faults():
    faults.clear()
    yield
    faults.clear()


def _batcher(engine, **kw):
    cfg = dict(bucket_keys=(8,), slots=2, timeout_ms=5.0,
               max_new_tokens=4)
    cfg.update(kw)
    return DynamicBatcher(engine, **cfg)


# ----------------------------------------------------------------- frames
class TestFrames:
    def _frames(self, prefill_engine, prompt):
        return prefill_engine.prefill(prompt)

    def test_prefill_frames_shape_contract(self, prefill_engine):
        fr = self._frames(prefill_engine,
                          np.array([5, 6, 7], dtype=np.int32))
        assert fr["length"] == 1 and fr["mem_vl"] == 3
        assert fr["emitted"] == [fr["carry"]]
        for g in ("k", "v"):
            assert all(a.shape[0] == 1 for a in fr[g])
        for g in ("ck", "cv"):
            assert all(a.shape[0] == 3 for a in fr[g])

    def test_pack_unpack_roundtrip_bit_exact(self, prefill_engine):
        fr = self._frames(prefill_engine,
                          np.array([9, 10, 11, 12], dtype=np.int32))
        meta, bufs = pack_frames(fr)
        assert len(bufs) == len(meta["arrays"])
        fr2 = unpack_frames(meta, bufs)
        assert fr2["length"] == fr["length"]
        assert fr2["carry"] == fr["carry"]
        assert fr2["mem_vl"] == fr["mem_vl"]
        for g in ("k", "v", "ck", "cv"):
            for a, b in zip(fr[g], fr2[g]):
                assert a.dtype == b.dtype
                assert np.array_equal(np.asarray(a), np.asarray(b))

    def test_unpack_mismatch_raises(self, prefill_engine):
        fr = self._frames(prefill_engine,
                          np.array([3, 4], dtype=np.int32))
        meta, bufs = pack_frames(fr)
        with pytest.raises(MXNetError):
            unpack_frames(meta, bufs[:-1])

    def test_spill_roundtrip_and_consume(self, prefill_engine, tmp_path):
        fr = self._frames(prefill_engine,
                          np.array([7, 8, 9], dtype=np.int32))
        path = spill_frames(str(tmp_path), "h1", fr)
        assert os.path.exists(path)
        fr2 = load_spilled(str(tmp_path), "h1")
        assert fr2 is not None and fr2["carry"] == fr["carry"]
        for g in ("k", "v", "ck", "cv"):
            for a, b in zip(fr[g], fr2[g]):
                assert np.array_equal(np.asarray(a), np.asarray(b))
        # consumed: the spill file is gone, a second load is None
        assert not os.path.exists(path)
        assert load_spilled(str(tmp_path), "h1") is None

    def test_load_spilled_missing_or_torn_is_none(self, tmp_path):
        assert load_spilled(str(tmp_path), "nope") is None
        (tmp_path / "torn.npz").write_bytes(b"not an npz")
        assert load_spilled(str(tmp_path), "torn") is None

    def test_stash_bounded_oldest_dropped(self):
        stash = HandoffStash(capacity=2)
        stash.put("a", {"x": 1})
        stash.put("b", {"x": 2})
        stash.put("c", {"x": 3})
        assert stash.pop("a") is None  # oldest evicted
        assert stash.pop("b") == {"x": 2}
        assert stash.pop("c") == {"x": 3}
        assert stash.dropped == 1 and len(stash) == 0

    def test_stash_ttl_expires_unclaimed_entries(self, monkeypatch):
        """ISSUE 15 regression (mxlint resource-leak.stash-expiry): a
        push whose submit never arrives must expire on the next touch,
        not pin KV bytes until 64 later pushes shove it out."""
        import types

        from mxnet_tpu.serving import disagg as _disagg_mod
        now = [100.0]
        monkeypatch.setattr(_disagg_mod, "time",
                            types.SimpleNamespace(monotonic=lambda: now[0]))
        stash = HandoffStash(capacity=8, ttl_s=5.0)
        stash.put("a", {"x": 1})
        now[0] += 2.0
        stash.put("b", {"x": 2})
        now[0] += 4.0  # "a" is now 6 s old (expired); "b" 4 s (alive)
        assert stash.pop("a") is None
        assert stash.pop("b") == {"x": 2}
        assert stash.expired == 1 and len(stash) == 0
        # a re-put refreshes the stamp: the entry survives a further wait
        stash.put("c", {"x": 3})
        now[0] += 4.0
        stash.put("c", {"x": 33})
        now[0] += 4.0  # 8 s since first put, 4 s since refresh
        assert stash.pop("c") == {"x": 33}
        assert stash.expired == 1

    def test_stash_ttl_zero_disables_expiry(self, monkeypatch):
        import types

        from mxnet_tpu.serving import disagg as _disagg_mod
        now = [0.0]
        monkeypatch.setattr(_disagg_mod, "time",
                            types.SimpleNamespace(monotonic=lambda: now[0]))
        stash = HandoffStash(capacity=4, ttl_s=0)
        stash.put("a", {"x": 1})
        now[0] += 1e9
        assert stash.pop("a") == {"x": 1}
        assert stash.expired == 0

    def test_stash_ttl_env_knob(self, monkeypatch):
        from mxnet_tpu.serving.disagg import handoff_ttl_s
        monkeypatch.delenv("MXTPU_HANDOFF_TTL_S", raising=False)
        assert handoff_ttl_s() == 120.0
        assert HandoffStash().ttl_s == 120.0
        monkeypatch.setenv("MXTPU_HANDOFF_TTL_S", "7.5")
        assert handoff_ttl_s() == 7.5
        assert HandoffStash().ttl_s == 7.5
        monkeypatch.setenv("MXTPU_HANDOFF_TTL_S", "not-a-number")
        assert handoff_ttl_s() == 120.0


# --------------------------------------------------------------- adoption
class TestAdoption:
    def test_adopted_tokens_bit_identical(self, prefill_engine,
                                          decode_batcher):
        """THE handoff contract: prefill on engine A, adopt on engine B
        (same weights) — greedy tokens bit-identical to B prefilling
        locally, every handoff adopted (no silent re-prefill)."""
        rng = np.random.RandomState(7)
        prompts = _prompts(rng, 6)
        ref = [decode_batcher.submit(p).result(timeout=120)
               for p in prompts]
        with decode_batcher._stats_lock:
            adopted0 = decode_batcher.stats["adopted"]
        outs = []
        for p in prompts:
            fr = prefill_engine.prefill(p)
            meta, bufs = pack_frames(fr)  # through the wire format
            outs.append(decode_batcher.submit(
                p, frames=unpack_frames(meta, bufs)).result(timeout=120))
        assert outs == ref
        with decode_batcher._stats_lock:
            assert decode_batcher.stats["adopted"] - adopted0 == 6

    def test_corrupt_frames_re_prefill_same_tokens(self, prefill_engine,
                                                   decode_batcher):
        mx.telemetry.reset()
        rng = np.random.RandomState(8)
        p = _prompts(rng, 1)[0]
        ref = decode_batcher.submit(p).result(timeout=120)
        fr = prefill_engine.prefill(p)
        fr["k"][0] = fr["k"][0][:, :1]  # wrong head geometry
        with decode_batcher._stats_lock:
            before = decode_batcher.stats["re_prefills"]
        out = decode_batcher.submit(p, frames=fr).result(timeout=120)
        assert out == ref
        with decode_batcher._stats_lock:
            assert decode_batcher.stats["re_prefills"] == before + 1
        assert mx.telemetry.registry().counter(
            "disagg/re_prefills").value >= 1
        mx.telemetry.reset()

    def test_spilled_frames_adopt_bit_identical(self, prefill_engine,
                                                decode_batcher, tmp_path):
        rng = np.random.RandomState(9)
        p = _prompts(rng, 1)[0]
        ref = decode_batcher.submit(p).result(timeout=120)
        spill_frames(str(tmp_path), "h9", prefill_engine.prefill(p))
        fr = load_spilled(str(tmp_path), "h9")
        assert decode_batcher.submit(
            p, frames=fr).result(timeout=120) == ref

    def test_dynamic_batcher_ignores_frames(self, shared_engine,
                                            prefill_engine):
        """The fixed batcher has no paged pool: frames are dropped and
        the request decodes from its prompt — served either way."""
        bat = _batcher(shared_engine, name="fixed-frames")
        rng = np.random.RandomState(10)
        p = _prompts(rng, 1)[0]
        try:
            ref = bat.submit(p).result(timeout=120)
            fr = prefill_engine.prefill(p)
            assert bat.submit(p, frames=fr).result(timeout=120) == ref
        finally:
            bat.stop()


# ---------------------------------------------------------- SLO placement
class TestSloPlacement:
    def test_equal_load_placement_cycles_replicas(self, shared_engine):
        """Regression (satellite): the PR-7 docstring promised
        round-robin ties but ``min()`` always picked the first replica —
        equal-score placement must now CYCLE through the fleet."""
        reps = [Replica(f"rr-{i}", _batcher(shared_engine, name=f"rr-{i}"))
                for i in range(3)]
        router = Router(reps, health_interval_s=0.02)
        try:
            placed = []
            for _ in range(6):  # sequential: loads are all-zero ties
                rng_p = np.array([5, 6, 7], dtype=np.int32)
                f = router.submit(rng_p)
                f.result(timeout=120)
                placed.append(f.replica)
            assert placed == ["rr-0", "rr-1", "rr-2"] * 2, placed
        finally:
            router.stop()

    def test_predicted_wait_beats_raw_backlog(self, shared_engine):
        """A replica with 3 queued-but-fast requests (p50 10 ms) must
        win over an empty-but-slow one (p50 500 ms) — the PR-10 backlog
        count chose the slow one."""
        class Stub(Replica):
            def __init__(self, name, batcher, p50, backlog):
                super().__init__(name, batcher)
                self._p50 = p50
                self._backlog = backlog

            def queue_wait_p50_ms(self):
                return self._p50

            def load(self):
                return self._backlog

        slow = Stub("slow", _batcher(shared_engine, name="slow"),
                    p50=500.0, backlog=0)
        fast = Stub("fast", _batcher(shared_engine, name="fast"),
                    p50=10.0, backlog=3)
        router = Router([slow, fast], start=False)
        try:
            assert slow.predicted_wait_ms() == 500.0
            assert fast.predicted_wait_ms() == 40.0
            with router._lock:
                assert router._pick_locked([slow, fast]) is fast
        finally:
            router.stop(stop_replicas=True)

    def test_unknown_class_rejected(self, shared_engine):
        router = Router([Replica("k1", _batcher(shared_engine))],
                        start=False)
        try:
            with pytest.raises(MXNetError):
                router.submit(np.array([3], dtype=np.int32),
                              klass="bulk")
        finally:
            router.stop()

    def test_class_default_deadline_applies(self, shared_engine,
                                            monkeypatch):
        """MXTPU_SLO_INTERACTIVE_MS is the interactive class's default
        deadline: a hung fleet fails the request with DeadlineExceeded
        at that budget instead of waiting forever."""
        monkeypatch.setenv("MXTPU_SLO_INTERACTIVE_MS", "120")
        faults.inject("batcher.hang", times=None, delay=0.5,
                      match="slo-hang")
        router = Router([Replica("slo-hang",
                                 _batcher(shared_engine,
                                          name="slo-hang"))],
                        health_interval_s=0.02)
        try:
            f = router.submit(np.array([4, 5], dtype=np.int32))
            with pytest.raises(DeadlineExceeded):
                f.result(timeout=60)
        finally:
            router.stop()

    def test_batch_sheds_before_interactive(self, shared_engine):
        """Under a degraded fleet batch requests shed at HALF the
        backlog bound: with shed_max_queue=8, batch sheds at backlog 4
        while interactive is still admitted."""
        mx.telemetry.reset()
        faults.inject("batcher.hang", times=None, delay=1.0,
                      match="cls-shed")
        rep = Replica("cls-shed", _batcher(shared_engine,
                                           name="cls-shed"))
        router = Router([rep], health_interval_s=0.02,
                        shed_queue_depth=1, shed_max_queue=8)
        rng = np.random.RandomState(21)
        try:
            admitted_batch = [router.submit(p, klass="batch")
                              for p in _prompts(rng, 4)]
            assert not any(f.done() and isinstance(f.exception(),
                                                   Backpressure)
                           for f in admitted_batch)
            doomed = router.submit(_prompts(rng, 1)[0], klass="batch")
            assert isinstance(doomed.exception(), Backpressure)
            ok = router.submit(_prompts(rng, 1)[0], klass="interactive")
            assert not (ok.done()
                        and isinstance(ok.exception(), Backpressure))
            assert mx.telemetry.registry().counter(
                "serve/shed_queue_full").value == 1
        finally:
            router.stop()
            mx.telemetry.reset()

    def test_router_place_fault_retries(self, shared_engine):
        """router.place raise-mode: the placement pass places nothing
        once, the monitor retries, the request still completes."""
        mx.telemetry.reset()
        faults.inject("router.place", times=1)
        router = Router([Replica("pl-1", _batcher(shared_engine,
                                                  name="pl-1"))],
                        health_interval_s=0.02)
        try:
            out = router.submit(np.array([5, 6, 7], dtype=np.int32)) \
                .result(timeout=120)
            assert isinstance(out, list)
            assert mx.telemetry.registry().counter(
                "serve/faults_injected").value >= 1
        finally:
            router.stop()
            mx.telemetry.reset()

    def test_per_class_ttft_recorded(self, shared_engine):
        mx.telemetry.reset()
        router = Router([Replica("ttft-1", _batcher(shared_engine,
                                                    name="ttft-1"))],
                        health_interval_s=0.02)
        rng = np.random.RandomState(22)
        try:
            router.submit(_prompts(rng, 1)[0],
                          klass="interactive").result(timeout=120)
            router.submit(_prompts(rng, 1)[0],
                          klass="batch").result(timeout=120)
            deadline = time.perf_counter() + 30
            reg = mx.telemetry.registry()
            while time.perf_counter() < deadline:
                snap = reg.snapshot()["histograms"]
                if "disagg/ttft_interactive_ms" in snap and \
                        "disagg/ttft_batch_ms" in snap:
                    break
                time.sleep(0.02)
            snap = reg.snapshot()["histograms"]
            assert snap["disagg/ttft_interactive_ms"]["count"] >= 1
            assert snap["disagg/ttft_batch_ms"]["count"] >= 1
        finally:
            router.stop()
            mx.telemetry.reset()


def _launch_mod():
    import sys

    sys.path.insert(0, os.path.join(
        os.path.dirname(__file__), "..", "tools"))
    import launch

    return launch


# -------------------------------------------------------------- elasticity
class TestElasticity:
    def _scaler(self, state, **kw):
        FleetScaler = _launch_mod().FleetScaler

        calls = {"spawn": 0, "retire": 0}

        def pressure():
            return {"size": state["size"],
                    "occupancy": state["occ"], "shed": state["shed"]}

        def spawn():
            calls["spawn"] += 1
            state["size"] += 1

        def retire():
            calls["retire"] += 1
            state["size"] -= 1
            return True

        cfg = dict(min_workers=1, max_workers=3, cooldown_s=0.0,
                   sustain=2)
        cfg.update(kw)
        return FleetScaler(pressure, spawn, retire, **cfg), calls

    def test_sustained_occupancy_scales_up_to_max(self):
        mx.telemetry.reset()
        state = {"size": 1, "occ": 0.95, "shed": 0}
        sc, calls = self._scaler(state)
        assert sc.step() is None      # 1 hot sample: not sustained yet
        assert sc.step() == "up"
        assert sc.step() is None and sc.step() == "up"
        assert state["size"] == 3
        for _ in range(4):            # at the ceiling: no more spawns
            sc.step()
        assert state["size"] == 3 and calls["spawn"] == 2
        assert mx.telemetry.registry().counter(
            "serve/scale_up").value == 2
        mx.telemetry.reset()

    def test_shed_growth_counts_as_pressure(self):
        state = {"size": 1, "occ": 0.0, "shed": 0}
        sc, calls = self._scaler(state)
        sc.step()                      # shed baseline
        state["shed"] = 5              # sheds grew: hot despite idle occ
        assert sc.step() is None
        state["shed"] = 9
        assert sc.step() == "up"
        assert calls["spawn"] == 1

    def test_idle_retires_down_to_min(self):
        mx.telemetry.reset()
        state = {"size": 3, "occ": 0.01, "shed": 0}
        sc, calls = self._scaler(state)
        acts = [sc.step() for _ in range(6)]
        assert acts.count("down") == 2 and state["size"] == 1
        for _ in range(3):
            sc.step()
        assert state["size"] == 1 and calls["retire"] == 2
        assert mx.telemetry.registry().counter(
            "serve/scale_down").value == 2
        mx.telemetry.reset()

    def test_cooldown_spaces_actions(self):
        state = {"size": 1, "occ": 1.0, "shed": 0}
        sc, calls = self._scaler(state, cooldown_s=3600.0)
        assert sc.step() is None
        assert sc.step() == "up"
        for _ in range(5):             # inside the cooldown window
            assert sc.step() is None
        assert calls["spawn"] == 1

    def test_retire_refusal_refunds_cooldown(self):
        state = {"size": 2, "occ": 0.0, "shed": 0}
        FleetScaler = _launch_mod().FleetScaler

        def pressure():
            return {"size": state["size"], "occupancy": state["occ"],
                    "shed": 0}

        sc = FleetScaler(pressure, lambda: None, lambda: False,
                         min_workers=1, max_workers=3,
                         cooldown_s=3600.0, sustain=1)
        assert sc.step() is None       # decided "down" but nothing
        assert sc.actions == []        # retirable: no action recorded
        with sc._lock:
            assert sc._last_action_at == 0.0  # cooldown refunded

    def test_env_knobs_configure_defaults(self, monkeypatch):
        FleetScaler = _launch_mod().FleetScaler

        monkeypatch.setenv("MXTPU_SCALE_MIN", "2")
        monkeypatch.setenv("MXTPU_SCALE_MAX", "7")
        monkeypatch.setenv("MXTPU_SCALE_COOLDOWN_S", "11.5")
        sc = FleetScaler(lambda: {}, lambda: None, lambda: True)
        assert sc.min_workers == 2
        assert sc.max_workers == 7
        assert sc.cooldown_s == 11.5

    def test_retired_replica_excluded_and_never_respawned(
            self, shared_engine):
        """Router.retire_replica: no further placements, and its
        eventual eviction schedules NO respawn even with a factory."""
        made = []

        def factory():
            made.append(1)
            return Replica("resp", _batcher(shared_engine, name="resp"))

        reps = [Replica(f"ret-{i}",
                        _batcher(shared_engine, name=f"ret-{i}"))
                for i in range(2)]
        router = Router(reps, health_interval_s=0.02,
                        replica_factory=factory)
        rng = np.random.RandomState(23)
        try:
            router.retire_replica(reps[0])
            futs = [router.submit(p) for p in _prompts(rng, 4)]
            for f in futs:
                f.result(timeout=120)
            assert all(f.replica == "ret-1" for f in futs)
            # kill the retired replica's batcher: eviction, no respawn
            reps[0].batcher.stop(drain=False, timeout=5.0)
            deadline = time.perf_counter() + 30
            while time.perf_counter() < deadline and not reps[0].evicted:
                time.sleep(0.02)
            assert reps[0].evicted
            time.sleep(0.2)
            assert router._respawn_at is None and not made
        finally:
            router.stop()


# --------------------------------------------- in-process worker verb path
@pytest.fixture(scope="module")
def worker_trio(tmp_path_factory):
    """One prefill-role + one decode-role ServingWorker IN-PROCESS (real
    sockets, no process spawn cost), behind a router with
    RemoteReplicas."""
    root = tmp_path_factory.mktemp("disagg_workers")
    pre = ServingWorker(make_transformer_net(), str(root / "pre"),
                        "pre0", role="prefill", warmup=True,
                        heartbeat_s=0.2)
    dec = ServingWorker(make_transformer_net(), str(root / "dec"),
                        "dec0", role="decode", warmup=True,
                        heartbeat_s=0.2)
    pre.server.start()
    dec.server.start()
    yield pre, dec
    pre.shutdown()
    dec.shutdown()


def _trio_router(pre, dec, **kw):
    reps = [RemoteReplica("pre0", address=(pre.server.host,
                                           pre.server.port),
                          role="prefill"),
            RemoteReplica("dec0", address=(dec.server.host,
                                           dec.server.port),
                          role="decode")]
    cfg = dict(health_interval_s=0.05, no_replica_timeout_s=60.0,
               disagg_min_prompt=1)  # test prompts are short: hand off
    cfg.update(kw)                   # everything unless a test says not
    return Router(reps, **cfg), reps


class TestWorkerVerbs:
    def test_health_reports_role_and_slo_fields(self, worker_trio):
        pre, dec = worker_trio
        client = RpcClient((dec.server.host,
                            dec.server.port)).connect(budget_s=10.0)
        try:
            info = client.call("health")
            assert info["role"] == "decode"
            assert "queue_wait_p50_ms" in info
            assert "disagg_adopted" in info
        finally:
            client.close()

    def test_prefill_worker_refuses_submit(self, worker_trio):
        pre, _ = worker_trio
        client = RpcClient((pre.server.host, pre.server.port),
                           dead_error=ReplicaUnavailable) \
            .connect(budget_s=10.0)
        try:
            fut = client.submit(np.array([4, 5], dtype=np.int32))
            with pytest.raises(ReplicaUnavailable):
                fut.result(timeout=60)
        finally:
            client.close()

    def test_router_disagg_submit_adopts_and_matches_plain(
            self, worker_trio):
        """Full verb path: router → prefill verb → kv_push binary
        frames → decode submit with handoff → adoption. Tokens equal
        the plain (no-handoff) path on the same worker; every handoff
        adopted."""
        pre, dec = worker_trio
        rng = np.random.RandomState(11)
        prompts = _prompts(rng, 5)
        client = RpcClient((dec.server.host,
                            dec.server.port)).connect(budget_s=10.0)
        try:
            ref = [client.submit(p).result(timeout=120) for p in prompts]
        finally:
            client.close()
        with dec.batcher._stats_lock:
            adopted0 = dec.batcher.stats["adopted"]
        router, _ = _trio_router(pre, dec)
        try:
            futs = [router.submit(p) for p in prompts]
            outs = [f.result(timeout=120) for f in futs]
            assert outs == ref
            assert all(f.replica == "dec0" for f in futs)
        finally:
            router.stop()
        with dec.batcher._stats_lock:
            assert dec.batcher.stats["adopted"] - adopted0 == 5

    def test_kv_push_fault_degrades_to_re_prefill(self, worker_trio):
        """transport.kv_push raise-mode: the push fails, the router
        submits WITHOUT a handoff, the decode worker prefills locally —
        same tokens, disagg/re_prefills counted."""
        mx.telemetry.reset()
        pre, dec = worker_trio
        rng = np.random.RandomState(12)
        p = _prompts(rng, 1)[0]
        client = RpcClient((dec.server.host,
                            dec.server.port)).connect(budget_s=10.0)
        try:
            ref = client.submit(p).result(timeout=120)
        finally:
            client.close()
        faults.inject("transport.kv_push", times=1)
        router, _ = _trio_router(pre, dec)
        try:
            out = router.submit(p).result(timeout=120)
            assert out == ref
            assert mx.telemetry.registry().counter(
                "disagg/re_prefills").value >= 1
        finally:
            router.stop()
            mx.telemetry.reset()

    def test_short_prompts_prefill_in_place(self, worker_trio):
        """MXTPU_DISAGG_MIN_PROMPT: prompts below the threshold skip
        the handoff — the decode worker prefills locally and the
        prefill worker is never asked."""
        pre, dec = worker_trio
        before = pre.prefiller.prefills
        with dec.batcher._stats_lock:
            adopted0 = dec.batcher.stats["adopted"]
        router, _ = _trio_router(pre, dec, disagg_min_prompt=64)
        try:
            out = router.submit(
                np.array([5, 6, 7], dtype=np.int32)).result(timeout=120)
            assert isinstance(out, list)
        finally:
            router.stop()
        assert pre.prefiller.prefills == before
        with dec.batcher._stats_lock:
            assert dec.batcher.stats["adopted"] == adopted0

    def test_spill_dir_handoff(self, worker_trio, tmp_path, monkeypatch):
        """MXTPU_KV_SPILL_DIR: frames ride the filesystem instead of a
        worker-to-worker socket; adoption still happens."""
        pre, dec = worker_trio
        monkeypatch.setenv("MXTPU_KV_SPILL_DIR", str(tmp_path))
        rng = np.random.RandomState(13)
        p = _prompts(rng, 1)[0]
        client = RpcClient((dec.server.host,
                            dec.server.port)).connect(budget_s=10.0)
        try:
            ref = client.submit(p).result(timeout=120)
        finally:
            client.close()
        with dec.batcher._stats_lock:
            adopted0 = dec.batcher.stats["adopted"]
        router, _ = _trio_router(pre, dec)
        try:
            assert router.submit(p).result(timeout=120) == ref
        finally:
            router.stop()
        with dec.batcher._stats_lock:
            assert dec.batcher.stats["adopted"] == adopted0 + 1


# ---------------------------------------------------------------- reporting
class TestDisaggTelemetry:
    def test_report_tool_prints_disagg_section(self, tmp_path, capsys):
        import sys

        sys.path.insert(0, os.path.join(
            os.path.dirname(__file__), "..", "tools"))
        import telemetry_report

        report = {
            "counters": {"disagg/handoffs": 3, "disagg/re_prefills": 5,
                         "disagg/kv_bytes": 4096,
                         "serve/scale_up": 2, "serve/scale_down": 1},
            "histograms": {
                "disagg/kv_push_ms": {"p50": 1.5, "p95": 3.0,
                                      "count": 3},
                "disagg/ttft_interactive_ms": {"p50": 40.0, "p95": 90.0,
                                               "count": 8}},
        }
        p = tmp_path / "report.json"
        p.write_text(json.dumps(report))
        telemetry_report._print_disagg_family(str(p))
        out = capsys.readouterr().out
        assert "Disaggregated serving" in out
        assert "disagg/kv_push_ms" in out
        assert "serve/scale_up" in out
        assert "paying prefill twice" in out  # re_prefills >= handoffs

    def test_disagg_family_registered(self):
        import sys

        sys.path.insert(0, os.path.join(
            os.path.dirname(__file__), "..", "tools"))
        import telemetry_report

        assert telemetry_report.KNOWN_METRIC_FAMILIES.get("disagg") \
            == "Disaggregated serving"
        assert "disagg" in telemetry_report.KNOWN_SPAN_FAMILIES


# ------------------------------------------------------------------- chaos
@pytest.mark.chaos
class TestDisaggChaos:
    def test_sigkill_prefill_mid_handoff_zero_lost_bit_identical(
            self, tmp_path):
        """THE disaggregation chaos scenario (ISSUE-11 acceptance):
        1 prefill + 2 decode REAL worker processes under a 60-request
        load; the prefill worker is SIGKILL'd mid-handoff. Zero lost
        requests (handoff failures degrade to decode-side re-prefill)
        and every token bit-identical to a co-scheduled fleet from the
        same seed."""
        mx.telemetry.reset()
        wkw = dict(model=dict(seed=0), max_len=24, bucket_keys=(8,),
                   slots=2, max_new=4, extra_env=WORKER_ENV,
                   heartbeat_s=0.1)
        rng = np.random.RandomState(41)
        prompts = _prompts(rng, 60)

        # reference: one co-scheduled worker, same seed
        ref_h = spawn_worker(str(tmp_path / "ref"), name="ref", **wkw)
        ref_rep = RemoteReplica("ref", address=ref_h.address,
                                heartbeat_path=ref_h.heartbeat_path)
        ref_router = Router([ref_rep], health_interval_s=0.05,
                            no_replica_timeout_s=120.0)
        try:
            ref = [ref_router.submit(p).result(timeout=240)
                   for p in prompts]
        finally:
            ref_router.stop()
            ref_h.terminate()

        handles = [
            spawn_worker(str(tmp_path / "pre0"), name="pre0",
                         role="prefill", **wkw),
            spawn_worker(str(tmp_path / "dec0"), name="dec0",
                         role="decode", **wkw),
            spawn_worker(str(tmp_path / "dec1"), name="dec1",
                         role="decode", **wkw),
        ]
        roles = ["prefill", "decode", "decode"]
        reps = [RemoteReplica(h.name, address=h.address,
                              heartbeat_path=h.heartbeat_path,
                              heartbeat_stale_s=2.0, role=r)
                for h, r in zip(handles, roles)]
        router = Router(reps, retry_backoff_s=0.02,
                        health_interval_s=0.05,
                        no_replica_timeout_s=120.0,
                        disagg_min_prompt=1)  # short prompts: hand off
        futs = []
        try:
            for i, p in enumerate(prompts):
                futs.append(router.submit(p))
                if i == 25:
                    handles[0].kill()  # SIGKILL the prefill worker
                time.sleep(0.005)
            outs, errors = [], 0
            for f in futs:
                try:
                    outs.append(f.result(timeout=240))
                except Exception:  # noqa: BLE001 - counted as lost
                    errors += 1
                    outs.append(None)
            assert errors == 0, f"{errors}/60 requests lost"
            assert outs == ref, "post-recovery tokens diverged"
            # the decode fleet really adopted handoffs before the kill
            adopted = 0
            for rep in reps[1:]:
                try:
                    info = rep.client.call("health")
                except Exception:  # noqa: BLE001
                    continue
                adopted += info.get("disagg_adopted") or 0
            assert adopted >= 1, "no handoff was ever adopted"
            # and the kill produced at least one observable failover or
            # re-prefill fallback
            reg = mx.telemetry.registry()
            assert (reg.counter("disagg/re_prefills").value
                    + reg.counter("serve/failovers").value) >= 1
        finally:
            router.stop()
            for h in handles:
                if h.alive():
                    h.terminate()
            for h in handles:
                try:
                    h.wait(timeout=60)
                except Exception:  # noqa: BLE001
                    h.kill()
            mx.telemetry.reset()
