"""Round-5 op tail, second batch: AMP guards (amp_cast/amp_multicast/
all_finite/multi_all_finite), shape/size/moments/STE/contrib misc, and
the optimizer-op tail (ftml, group_adagrad, multi_adamw, preloaded
multi-sgd, lans). Reference: ``src/operator/tensor/amp_cast.cc``,
``all_finite.cc``, ``contrib/optimizer_op.cc``, ``contrib/adamw.cc``
[unverified]."""

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import nd, autograd
from mxnet_tpu.test_utils import check_numeric_gradient

rng = np.random.RandomState(1)


def test_shape_size_array():
    x = nd.array(rng.rand(3, 5).astype(np.float32))
    np.testing.assert_array_equal(nd.shape_array(x).asnumpy(), [3, 5])
    assert int(nd.size_array(x).asnumpy()) == 15


def test_moments():
    x = rng.rand(4, 6).astype(np.float32)
    mean, var = nd.moments(nd.array(x), axes=(1,))
    np.testing.assert_allclose(mean.asnumpy(), x.mean(1), rtol=1e-5)
    np.testing.assert_allclose(var.asnumpy(), x.var(1), rtol=1e-5)
    mean2, var2 = nd.moments(nd.array(x), axes=(0,), keepdims=True)
    assert var2.shape == (1, 6)


def test_amp_cast_and_multicast():
    x = nd.array(rng.rand(2, 3).astype(np.float32))
    y = nd.amp_cast(x, dtype="float16")
    assert y.dtype == np.float16
    a = nd.array(rng.rand(2, 2).astype(np.float16))
    b = nd.array(rng.rand(2, 2).astype(np.float32))
    ca, cb = nd.amp_multicast(a, b, num_outputs=2)
    assert ca.dtype == np.float32 and cb.dtype == np.float32


def test_all_finite_probes():
    ok = nd.array(np.ones((4,), np.float32))
    bad = nd.array(np.asarray([1.0, np.inf, 0.0], np.float32))
    assert float(nd.all_finite(ok).asnumpy()[0]) == 1.0
    assert float(nd.all_finite(bad).asnumpy()[0]) == 0.0
    assert float(nd.multi_all_finite(ok, ok, num_arrays=2)
                 .asnumpy()[0]) == 1.0
    assert float(nd.multi_all_finite(ok, bad, num_arrays=2)
                 .asnumpy()[0]) == 0.0


def test_quadratic_and_gradient():
    x = rng.rand(3, 3).astype(np.float64)
    out = nd.contrib.quadratic(nd.array(x), a=2.0, b=-1.0, c=0.5)
    np.testing.assert_allclose(out.asnumpy(), 2 * x * x - x + 0.5,
                               rtol=1e-6)
    check_numeric_gradient(
        lambda d: nd.contrib.quadratic(d, a=2.0, b=-1.0, c=0.5), [x])


def test_allclose_op():
    a = nd.array(np.ones((3,), np.float32))
    b = nd.array(np.ones((3,), np.float32) + 1e-7)
    assert float(nd.contrib.allclose(a, b).asnumpy()[0]) == 1.0
    c = nd.array(np.ones((3,), np.float32) + 1.0)
    assert float(nd.contrib.allclose(a, c).asnumpy()[0]) == 0.0


def test_index_copy_and_gradient():
    old = rng.rand(5, 3).astype(np.float64)
    new = rng.rand(2, 3).astype(np.float64)
    idx = nd.array(np.asarray([1, 3], np.int32))
    out = nd.contrib.index_copy(nd.array(old), idx, nd.array(new))
    want = old.copy()
    want[[1, 3]] = new
    np.testing.assert_allclose(out.asnumpy(), want, rtol=1e-6)
    check_numeric_gradient(
        lambda o, n: nd.contrib.index_copy(o, idx, n), [old, new])


def test_index_array():
    x = nd.array(np.zeros((2, 3), np.float32))
    out = nd.contrib.index_array(x).asnumpy()
    assert out.shape == (2, 3, 2)
    assert out[1, 2, 0] == 1 and out[1, 2, 1] == 2
    out_ax = nd.contrib.index_array(x, axes=(1,)).asnumpy()
    assert out_ax.shape == (2, 3, 1)
    np.testing.assert_array_equal(out_ax[:, :, 0], [[0, 1, 2], [0, 1, 2]])


def test_gradientmultiplier_scales_only_gradient():
    x = nd.array(rng.rand(4).astype(np.float32))
    x.attach_grad()
    with autograd.record():
        y = nd.contrib.gradientmultiplier(x, scalar=-0.5)
        loss = (y * 3.0).sum()
    loss.backward()
    np.testing.assert_allclose(y.asnumpy(), x.asnumpy(), rtol=1e-6)
    np.testing.assert_allclose(x.grad.asnumpy(), -1.5 * np.ones(4),
                               rtol=1e-6)


def test_straight_through_estimators():
    x = nd.array(np.asarray([-1.2, 0.4, 2.6], np.float32))
    x.attach_grad()
    with autograd.record():
        loss = nd.contrib.round_ste(x).sum()
    loss.backward()
    np.testing.assert_allclose(x.grad.asnumpy(), np.ones(3))  # identity
    with autograd.record():
        loss2 = nd.contrib.sign_ste(x).sum()
    loss2.backward()
    np.testing.assert_allclose(x.grad.asnumpy(), np.ones(3))


def test_boolean_mask_dynamic_shape():
    data = nd.array(np.arange(12, dtype=np.float32).reshape(4, 3))
    idx = nd.array(np.asarray([1, 0, 1, 0], np.float32))
    out = nd.contrib.boolean_mask(data, idx).asnumpy()
    assert out.shape == (2, 3)
    np.testing.assert_array_equal(out, [[0, 1, 2], [6, 7, 8]])


def test_edge_id():
    adj = np.zeros((4, 4), np.float32)
    adj[0, 1] = 7.0
    adj[2, 3] = 9.0
    u = nd.array(np.asarray([0, 2, 1], np.int32))
    v = nd.array(np.asarray([1, 3, 1], np.int32))
    out = nd.contrib.edge_id(nd.array(adj), u, v).asnumpy()
    np.testing.assert_array_equal(out, [7.0, 9.0, -1.0])


# ------------------------------------------------------- optimizer ops
def test_ftml_optimizer_converges():
    from mxnet_tpu import gluon
    from mxnet_tpu.gluon import nn

    mx.random.seed(5)
    net = nn.Dense(1)
    net.initialize()
    trainer = gluon.Trainer(net.collect_params(), "ftml",
                            {"learning_rate": 0.05})
    X = rng.rand(32, 4).astype(np.float32)
    Y = (X @ np.asarray([[1.0], [-2.0], [0.5], [3.0]], np.float32))
    loss_fn = gluon.loss.L2Loss()
    first = None
    for _ in range(60):
        with autograd.record():
            L = loss_fn(net(nd.array(X)), nd.array(Y))
        L.backward()
        trainer.step(32)
        v = float(L.mean().asscalar())
        first = first if first is not None else v
    assert v < first * 0.3, (first, v)


def test_group_adagrad_rowwise_history():
    w = nd.array(np.ones((3, 4), np.float32))
    g = nd.array(np.full((3, 4), 2.0, np.float32))
    h = nd.array(np.zeros((3,), np.float32))
    nw, nh = nd.contrib.group_adagrad_update(w, g, h, lr=0.1)
    np.testing.assert_allclose(nh.asnumpy(), [4.0, 4.0, 4.0])  # mean g^2
    np.testing.assert_allclose(nw.asnumpy(),
                               1.0 - 0.1 * 2.0 / (2.0 + 1e-5),
                               rtol=1e-4)


def test_multi_adamw_matches_single():
    w = rng.rand(4, 4).astype(np.float32)
    g = rng.rand(4, 4).astype(np.float32)
    m = np.zeros_like(w)
    v = np.zeros_like(w)
    outs = nd.contrib.multi_adamw_update(
        nd.array(w), nd.array(g), nd.array(m), nd.array(v),
        lrs=0.01, wds=0.1, etas=1.0, num_weights=1)
    nw = outs[0].asnumpy()
    # hand-rolled single AdamW step (beta defaults)
    nm = 0.1 * g
    nv = 0.001 * g * g
    want = w - 0.01 * (nm / (np.sqrt(nv) + 1e-8) + 0.1 * w)
    np.testing.assert_allclose(nw, want, rtol=1e-4)


def test_preloaded_multi_sgd_device_hypers():
    w1 = rng.rand(3).astype(np.float32)
    g1 = rng.rand(3).astype(np.float32)
    w2 = rng.rand(2).astype(np.float32)
    g2 = rng.rand(2).astype(np.float32)
    lrs = nd.array(np.asarray([0.1, 0.2], np.float32))
    wds = nd.array(np.zeros(2, np.float32))
    o1, o2 = nd.preloaded_multi_sgd_update(
        nd.array(w1), nd.array(g1), nd.array(w2), nd.array(g2),
        lrs, wds, num_weights=2)
    np.testing.assert_allclose(o1.asnumpy(), w1 - 0.1 * g1, rtol=1e-5)
    np.testing.assert_allclose(o2.asnumpy(), w2 - 0.2 * g2, rtol=1e-5)


def test_lans_two_phase():
    w = rng.rand(4, 4).astype(np.float32)
    g = rng.rand(4, 4).astype(np.float32)
    m = np.zeros_like(w)
    v = np.zeros_like(w)
    pair, nm, nv = nd.contrib.lans_update_phase1(
        nd.array(w), nd.array(g), nd.array(m), nd.array(v), t=1, wd=0.01)
    assert pair.shape == (2, 4, 4)
    wnorm = nd.array(np.asarray(np.linalg.norm(w), np.float32))
    p = pair.asnumpy()
    gnorms = nd.array(np.asarray(
        [np.linalg.norm(p[0]), np.linalg.norm(p[1])], np.float32))
    nw = nd.contrib.lans_update_phase2(
        nd.array(w), pair, wnorm, gnorms, lr=0.01)
    assert nw.shape == w.shape
    assert np.isfinite(nw.asnumpy()).all()
    assert not np.allclose(nw.asnumpy(), w)
