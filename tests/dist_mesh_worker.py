"""Worker body for the multi-process GLOBAL-MESH test: 2 processes x 4
virtual CPU devices form ONE 8-device mesh; the dp x tp BERT TrainStep
runs as a single GSPMD program spanning both processes (the multi-host
pod story — reference analogue: multi-node KVStoreDist +
DataParallelExecutorGroup, ``src/kvstore/kvstore_dist.h`` [unverified]).

Also exercises the sharded checkpoint across processes: each process
writes only its own shards + DONE marker, restore resumes bit-compatibly.

Writes per-step losses as JSON to $DIST_MESH_OUT.{rank} for the parent
to compare against the single-process reference run.
"""

import json
import os
import sys

import numpy as np

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

# 4 LOCAL devices per process (the parent pytest env says 8; override) —
# but ONLY when running as a launched worker: the single-process
# reference run imports this module for build_step/batch and must keep
# its own device count
if "MXNET_TPU_PROC_ID" in os.environ and __name__ == "__main__":
    flags = [f for f in os.environ.get("XLA_FLAGS", "").split()
             if "xla_force_host_platform_device_count" not in f]
    flags.append("--xla_force_host_platform_device_count=4")
    os.environ["XLA_FLAGS"] = " ".join(flags)

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_default_matmul_precision", "highest")


def build_step(mesh):
    import mxnet_tpu as mx
    from mxnet_tpu import gluon, optimizer as opt
    from mxnet_tpu.parallel import PartitionSpec as P, TrainStep

    mx.random.seed(0)  # identical init on every process AND the reference
    from mxnet_tpu.gluon.model_zoo.bert import BERTModel

    net = BERTModel(vocab_size=128, units=64, hidden_size=256,
                    num_layers=1, num_heads=2, max_length=32,
                    type_vocab_size=2, dropout=0.1)
    net.initialize()
    net._probe_shapes(mx.nd.zeros((2, 8), dtype="int32"))
    ce = gluon.loss.SoftmaxCrossEntropyLoss()

    class _MLMLoss:
        def __call__(self, seq, pooled, label):
            return ce(seq.reshape(-1, seq.shape[-1]), label.reshape(-1))

    rules = [
        (r"(qkv|ffn1)_weight$", P("model", None)),
        (r"(out|ffn2)_weight$", P(None, "model")),
        (r"word_weight$", P("model", None)),
    ]
    return TrainStep(net, _MLMLoss(), opt.Adam(learning_rate=1e-3),
                     mesh=mesh, data_spec=P("data"), param_rules=rules)


def batch():
    import mxnet_tpu as mx

    rng = np.random.RandomState(5)
    ids = mx.nd.array(rng.randint(0, 128, (8, 16)), dtype="int32")
    labels = mx.nd.array(rng.randint(0, 64, (8, 16)), dtype="int32")
    return ids, labels


def main():
    from mxnet_tpu.parallel import init_process_group
    from jax.sharding import Mesh

    coord = os.environ["MXNET_TPU_COORDINATOR"]
    nproc = int(os.environ["MXNET_TPU_NUM_PROCS"])
    pid = int(os.environ["MXNET_TPU_PROC_ID"])
    init_process_group(coord, nproc, pid)

    assert jax.process_count() == nproc, jax.process_count()
    assert len(jax.local_devices()) == 4, jax.local_devices()
    assert len(jax.devices()) == 4 * nproc, \
        f"global mesh not formed: {len(jax.devices())} devices"

    # ONE global mesh over every device of every process
    devs = np.array(jax.devices()).reshape(4 * nproc // 2, 2)
    mesh = Mesh(devs, ("data", "model"))

    step = build_step(mesh)
    ids, labels = batch()
    losses = []
    for _ in range(3):
        L = step(ids, labels)
        losses.append(float(L.asscalar()))
    assert all(np.isfinite(v) for v in losses), losses

    # sharded checkpoint ACROSS processes: save (each process its own
    # shards), restore into a fresh step, run one more step — must match
    # the uninterrupted 4th step (key + moments + t all survive)
    ckdir = os.environ["DIST_MESH_CKPT"]
    step.save_checkpoint(ckdir)
    cont = float(step(ids, labels).asscalar())

    step2 = build_step(mesh)
    step2.load_checkpoint(ckdir)
    resumed = float(step2(ids, labels).asscalar())
    assert abs(cont - resumed) < 1e-5, (cont, resumed)
    losses.append(cont)

    out = os.environ["DIST_MESH_OUT"] + f".{pid}"
    with open(out, "w") as f:
        json.dump({"losses": losses, "rank": pid,
                   "global_devices": len(jax.devices())}, f)
    print(f"worker {pid}: losses {losses}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
