"""Model zoo tests (reference: tests/python/unittest/test_gluon_model_zoo.py
[unverified]). Shape checks run abstractly (jax.eval_shape via the deferred-
init probe) so every family is covered without paying CPU conv time; small
models additionally run real forwards."""

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import autograd, gluon
from mxnet_tpu.gluon.model_zoo import vision
from mxnet_tpu.gluon.model_zoo.bert import BERTModel, BERTForPretraining
from mxnet_tpu.gluon.model_zoo.transformer import TransformerModel


def _count_params(net):
    return sum(
        int(np.prod(p.shape)) for p in net.collect_params().values()
        if p._shape_known()
    )


def _probe(net, shape):
    """Resolve all deferred shapes without running any FLOPs."""
    net.initialize()
    net._probe_shapes(mx.nd.zeros(shape))


@pytest.mark.parametrize(
    "name,shape,approx_params",
    [
        ("resnet18_v1", (1, 3, 224, 224), 11.7e6),
        ("resnet50_v1", (1, 3, 224, 224), 25.6e6),
        ("resnet50_v2", (1, 3, 224, 224), 25.5e6),
        ("resnet101_v1", (1, 3, 224, 224), 44.5e6),
        ("vgg16", (1, 3, 224, 224), 138e6),
        ("alexnet", (1, 3, 224, 224), 61e6),
        ("densenet121", (1, 3, 224, 224), 8.0e6),
        ("mobilenet1_0", (1, 3, 224, 224), 4.2e6),
        ("mobilenet_v2_1_0", (1, 3, 224, 224), 3.5e6),
        ("mobilenet_v3_large", (1, 3, 224, 224), 5.5e6),
        ("squeezenet1_1", (1, 3, 224, 224), 1.2e6),
        ("inception_v3", (1, 3, 299, 299), 23.9e6),
    ],
)
def test_zoo_param_counts(name, shape, approx_params):
    net = vision.get_model(name, classes=1000)
    _probe(net, shape)
    n = _count_params(net)
    assert abs(n - approx_params) / approx_params < 0.15, (name, n)


def test_get_model_unknown():
    with pytest.raises(mx.MXNetError):
        vision.get_model("resnet9000")


def test_resnet_small_forward_and_train():
    net = vision.get_model("resnet18_v1", thumbnail=True, classes=10)
    net.initialize()
    net.hybridize()
    x = mx.nd.array(np.random.randn(2, 3, 32, 32).astype("float32"))
    y = mx.nd.array(np.random.randint(0, 10, 2))
    loss_fn = gluon.loss.SoftmaxCrossEntropyLoss()
    trainer = gluon.Trainer(net.collect_params(), "sgd",
                            {"learning_rate": 0.1})
    with autograd.record():
        L = loss_fn(net(x), y)
    L.backward()
    trainer.step(2)
    assert np.isfinite(float(L.mean().asscalar()))
    # eval mode uses BN running stats
    out = net(x)
    assert out.shape == (2, 10)


def test_bert_tiny_forward():
    net = BERTModel(vocab_size=100, units=32, hidden_size=64, num_layers=2,
                    num_heads=2, max_length=32)
    net.initialize()
    ids = mx.nd.array(np.random.randint(0, 100, (2, 8)), dtype="int32")
    seq, pooled = net(ids)
    assert seq.shape == (2, 8, 32)
    assert pooled.shape == (2, 32)


def test_bert_pretrain_heads_tied():
    net = BERTForPretraining(vocab_size=50, units=16, hidden_size=32,
                             num_layers=1, num_heads=2, max_length=16)
    net.initialize()
    ids = mx.nd.array(np.random.randint(0, 50, (2, 4)), dtype="int32")
    mlm, nsp = net(ids)
    assert mlm.shape == (2, 4, 50)
    assert nsp.shape == (2, 2)
    # decoder tied to embedding: grads reach the embedding through the head
    with autograd.record():
        mlm, _ = net(ids)
        loss = mlm.sum()
    loss.backward()
    g = net.bert.word_embed.weight.grad().asnumpy()
    assert not np.allclose(g, 0)


def test_transformer_tiny_causal():
    net = TransformerModel(src_vocab=60, tgt_vocab=60, units=32,
                           hidden_size=64, num_layers=1, num_heads=2,
                           max_length=32)
    net.initialize()
    src = mx.nd.array(np.random.randint(0, 60, (2, 6)), dtype="int32")
    tgt = mx.nd.array(np.random.randint(0, 60, (2, 5)), dtype="int32")
    logits = net(src, tgt)
    assert logits.shape == (2, 5, 60)
    # causality: changing a later tgt token must not affect earlier logits
    tgt2 = tgt.asnumpy().copy()
    tgt2[:, -1] = (tgt2[:, -1] + 1) % 60
    logits2 = net(src, mx.nd.array(tgt2, dtype="int32"))
    np.testing.assert_allclose(
        logits.asnumpy()[:, :-1], logits2.asnumpy()[:, :-1], rtol=2e-4,
        atol=1e-5,
    )


def test_bert_remat_matches_plain():
    """remat=True must be numerically identical (dropout off) in loss and
    gradients — it only changes what the backward rematerializes."""
    import numpy as np
    import mxnet_tpu as mx
    from mxnet_tpu import gluon, optimizer as opt
    from mxnet_tpu.gluon.model_zoo.bert import BERTModel
    from mxnet_tpu.parallel import TrainStep

    def run(remat):
        mx.random.seed(5)
        net = BERTModel(vocab_size=50, units=16, hidden_size=32,
                        num_layers=2, num_heads=2, max_length=32,
                        dropout=0.0, remat=remat)
        net.initialize()
        net._probe_shapes(mx.nd.zeros((2, 8), dtype="int32"))
        ce = gluon.loss.SoftmaxCrossEntropyLoss()

        def loss_fn(seq_out, pooled, label):
            return ce(seq_out.reshape(-1, seq_out.shape[-1]), label.reshape(-1))

        step = TrainStep(net, loss_fn, opt.SGD(learning_rate=0.1))
        rng = np.random.RandomState(0)
        ids = mx.nd.array(rng.randint(0, 50, (4, 8)), dtype="int32")
        labels = mx.nd.array(rng.randint(0, 16, (4, 8)), dtype="int32")
        losses = [float(step(ids, labels).asscalar()) for _ in range(3)]
        step.sync_params()
        return losses, {k: v.data().asnumpy()
                        for k, v in net.collect_params().items()}

    la, pa = run(False)
    lb, pb = run(True)
    np.testing.assert_allclose(la, lb, rtol=1e-5)
    ka = {k.split("_", 1)[-1]: v for k, v in pa.items()}
    kb = {k.split("_", 1)[-1]: v for k, v in pb.items()}
    for k in ka:
        np.testing.assert_allclose(ka[k], kb[k], rtol=1e-4, atol=1e-6,
                                   err_msg=k)
