"""NDArray core semantics: creation, mutation, views, ops, async API.

Models the reference's ``tests/python/unittest/test_ndarray.py`` [unverified]
coverage: mutability (in-place ops, setitem), storage-sharing views, dtype
and context handling, operator parity vs NumPy.
"""

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import nd


def assert_close(a, b, rtol=1e-5, atol=1e-6):
    np.testing.assert_allclose(
        a.asnumpy() if isinstance(a, mx.NDArray) else a,
        b.asnumpy() if isinstance(b, mx.NDArray) else b,
        rtol=rtol, atol=atol,
    )


class TestCreation:
    def test_array_roundtrip(self):
        x = np.random.rand(3, 4).astype(np.float32)
        a = nd.array(x)
        assert a.shape == (3, 4)
        assert a.dtype == np.float32
        assert_close(a, x)

    def test_zeros_ones_full(self):
        assert_close(nd.zeros((2, 3)), np.zeros((2, 3)))
        assert_close(nd.ones((2, 3)), np.ones((2, 3)))
        assert_close(nd.full((2, 2), 7.0), np.full((2, 2), 7.0))

    def test_arange_linspace(self):
        assert_close(nd.arange(0, 10, 2), np.arange(0, 10, 2, dtype=np.float32))
        assert_close(nd.linspace(0, 1, 5), np.linspace(0, 1, 5, dtype=np.float32))

    def test_float64_demotes_to_default_dtype(self):
        a = nd.array(np.random.rand(3))  # float64 input
        assert a.dtype == np.float32

    def test_ctx_placement(self):
        a = nd.ones((2, 2), ctx=mx.cpu(0))
        assert a.ctx.device_type == "cpu"


class TestMutability:
    def test_setitem_full(self):
        a = nd.zeros((3, 3))
        a[:] = 5.0
        assert_close(a, np.full((3, 3), 5.0))

    def test_setitem_slice(self):
        a = nd.zeros((4, 4))
        a[1:3, 1:3] = 1.0
        expect = np.zeros((4, 4), np.float32)
        expect[1:3, 1:3] = 1.0
        assert_close(a, expect)

    def test_inplace_add(self):
        a = nd.ones((2, 2))
        b = a  # same handle
        a += 1.0
        assert_close(b, np.full((2, 2), 2.0))

    def test_view_write_back(self):
        """Writing through a slice view updates the base (storage sharing)."""
        a = nd.zeros((4, 4))
        v = a[1:3]
        v[:] = 3.0
        expect = np.zeros((4, 4), np.float32)
        expect[1:3] = 3.0
        assert_close(a, expect)

    def test_view_sees_base_mutation(self):
        a = nd.zeros((4,))
        v = a[1:3]
        a[:] = 2.0
        assert_close(v, np.full((2,), 2.0))

    def test_reshape_view_write_back(self):
        a = nd.zeros((2, 3))
        r = a.reshape(6)
        r[0] = 9.0
        assert float(a[0, 0].asscalar()) == 9.0

    def test_sibling_views(self):
        a = nd.zeros((4,))
        v1, v2 = a[0:2], a[1:3]
        v1[:] = 1.0
        assert_close(v2, np.array([1.0, 0.0], np.float32))

    def test_out_kwarg(self):
        a, b = nd.ones((2, 2)), nd.ones((2, 2))
        c = nd.zeros((2, 2))
        nd.broadcast_add(a, b, out=c)
        assert_close(c, np.full((2, 2), 2.0))


class TestOps:
    def test_arith_matches_numpy(self):
        x = np.random.rand(3, 4).astype(np.float32)
        y = np.random.rand(3, 4).astype(np.float32) + 0.5
        a, b = nd.array(x), nd.array(y)
        assert_close(a + b, x + y)
        assert_close(a - b, x - y)
        assert_close(a * b, x * y)
        assert_close(a / b, x / y, rtol=1e-4)
        assert_close(a ** 2, x ** 2)
        assert_close(-a, -x)
        assert_close(2.0 - a, 2.0 - x)

    def test_dot(self):
        x = np.random.rand(3, 4).astype(np.float32)
        y = np.random.rand(4, 5).astype(np.float32)
        assert_close(nd.dot(nd.array(x), nd.array(y)), x @ y, rtol=1e-4)
        assert_close(
            nd.dot(nd.array(x), nd.array(y.T), transpose_b=True), x @ y, rtol=1e-4
        )

    def test_batch_dot(self):
        x = np.random.rand(2, 3, 4).astype(np.float32)
        y = np.random.rand(2, 4, 5).astype(np.float32)
        assert_close(nd.batch_dot(nd.array(x), nd.array(y)), x @ y, rtol=1e-4)

    def test_reductions(self):
        x = np.random.rand(3, 4, 5).astype(np.float32)
        a = nd.array(x)
        assert_close(nd.sum(a, axis=1), x.sum(axis=1), rtol=1e-4)
        assert_close(nd.mean(a), x.mean(), rtol=1e-4)
        assert_close(nd.max(a, axis=(0, 2)), x.max(axis=(0, 2)))
        assert_close(nd.sum(a, axis=1, exclude=True), x.sum(axis=(0, 2)), rtol=1e-4)

    def test_unary(self):
        x = np.random.rand(10).astype(np.float32) + 0.1
        a = nd.array(x)
        assert_close(nd.sqrt(a), np.sqrt(x), rtol=1e-4)
        assert_close(nd.exp(a), np.exp(x), rtol=1e-4)
        assert_close(nd.log(a), np.log(x), rtol=1e-3, atol=1e-4)
        assert_close(nd.sigmoid(a), 1 / (1 + np.exp(-x)), rtol=1e-4)
        assert_close(nd.relu(nd.array(x - 0.5)), np.maximum(x - 0.5, 0))

    def test_softmax(self):
        x = np.random.rand(2, 5).astype(np.float32)
        e = np.exp(x - x.max(axis=-1, keepdims=True))
        assert_close(nd.softmax(nd.array(x)), e / e.sum(-1, keepdims=True), rtol=1e-4)

    def test_concat_split_stack(self):
        x = np.random.rand(2, 3).astype(np.float32)
        y = np.random.rand(2, 3).astype(np.float32)
        assert_close(nd.concat(nd.array(x), nd.array(y), dim=1),
                     np.concatenate([x, y], 1))
        assert_close(nd.stack(nd.array(x), nd.array(y), axis=0), np.stack([x, y]))
        parts = nd.split(nd.array(x), num_outputs=3, axis=1)
        assert len(parts) == 3
        assert_close(parts[0], x[:, 0:1])

    def test_reshape_special_codes(self):
        x = np.random.rand(2, 3, 4).astype(np.float32)
        assert nd.reshape(nd.array(x), shape=(0, -1)).shape == (2, 12)
        assert nd.reshape(nd.array(x), shape=(-1,)).shape == (24,)
        assert nd.reshape(nd.array(x), shape=(0, 0, 2, 2)).shape == (2, 3, 2, 2)

    def test_take_embedding(self):
        w = np.random.rand(10, 4).astype(np.float32)
        idx = np.array([1, 3, 5], np.float32)
        out = nd.Embedding(nd.array(idx), nd.array(w), input_dim=10, output_dim=4)
        assert_close(out, w[idx.astype(int)])

    def test_topk_sort(self):
        x = np.array([[3.0, 1.0, 2.0]], np.float32)
        idx = nd.topk(nd.array(x), k=2)
        np.testing.assert_array_equal(idx.asnumpy(), [[0.0, 2.0]])
        v, i = nd.topk(nd.array(x), k=2, ret_typ="both")
        np.testing.assert_array_equal(v.asnumpy(), [[3.0, 2.0]])
        assert_close(nd.sort(nd.array(x)), np.sort(x))

    def test_where_clip(self):
        x = np.random.randn(3, 3).astype(np.float32)
        a = nd.array(x)
        assert_close(nd.clip(a, 0.0, 0.5), np.clip(x, 0.0, 0.5))
        cond = nd.array((x > 0).astype(np.float32))
        assert_close(nd.where(cond, a, a * 0), np.where(x > 0, x, 0))

    def test_comparison_dtype(self):
        a = nd.array([1.0, 2.0, 3.0])
        b = nd.array([2.0, 2.0, 2.0])
        out = a > b
        assert out.dtype == np.float32
        np.testing.assert_array_equal(out.asnumpy(), [0.0, 0.0, 1.0])

    def test_sequence_mask(self):
        x = np.ones((4, 2, 3), np.float32)
        out = nd.SequenceMask(nd.array(x), nd.array([2.0, 4.0]),
                              use_sequence_length=True, value=-1.0)
        o = out.asnumpy()
        assert (o[:2, 0] == 1).all() and (o[2:, 0] == -1).all()
        assert (o[:, 1] == 1).all()


class TestAsync:
    def test_wait_to_read_and_waitall(self):
        a = nd.ones((100, 100))
        b = nd.dot(a, a)
        b.wait_to_read()
        mx.waitall()
        assert_close(b[0, 0], np.array(100.0, np.float32))

    def test_naive_engine_mode(self, monkeypatch):
        import mxnet_tpu.engine as eng

        prev = eng.engine().is_async()
        eng.engine().set_async(False)
        try:
            a = nd.ones((4, 4))
            c = a * 2
            assert_close(c, np.full((4, 4), 2.0))
        finally:
            eng.engine().set_async(prev)


class TestSaveLoad:
    def test_save_load_dict(self, tmp_path):
        f = str(tmp_path / "params")
        d = {"w": nd.ones((2, 2)), "b": nd.zeros((3,))}
        nd.save(f, d)
        loaded = nd.load(f)
        assert set(loaded) == {"w", "b"}
        assert_close(loaded["w"], np.ones((2, 2)))

    def test_save_load_list(self, tmp_path):
        f = str(tmp_path / "arrays")
        nd.save(f, [nd.ones((2,)), nd.zeros((3,))])
        loaded = nd.load(f)
        assert isinstance(loaded, list) and len(loaded) == 2


class TestSparseFacade:
    def test_row_sparse(self):
        vals = np.array([[1.0, 2.0], [3.0, 4.0]], np.float32)
        rs = mx.nd.sparse.row_sparse_array((vals, [1, 3]), shape=(5, 2))
        assert rs.stype == "row_sparse"
        assert rs.shape == (5, 2)
        np.testing.assert_array_equal(rs.indices.asnumpy(), [1, 3])
        dense = rs.tostype("default")
        assert dense.stype == "default"
        assert float(dense[1, 0].asscalar()) == 1.0
