"""Gluon Block/HybridBlock/Parameter/Trainer tests (modeled on the
reference's tests/python/unittest/test_gluon.py [unverified])."""

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import autograd, gluon
from mxnet_tpu.gluon import nn


def test_parameter_basic():
    p = gluon.Parameter("weight", shape=(10, 10))
    p.initialize(init="xavier")
    assert p.data().shape == (10, 10)
    assert p.grad().shape == (10, 10)
    assert len(p.list_data()) == 1


def test_parameter_invalid_grad_req():
    with pytest.raises(mx.MXNetError):
        gluon.Parameter("w", shape=(1,), grad_req="bogus")


def test_parameter_dict_sharing():
    shared = gluon.ParameterDict("net_")
    shared.get("dense0_weight", shape=(4, 4))
    child = gluon.ParameterDict("net_", shared=shared)
    p = child.get("dense0_weight")
    assert p is shared["net_dense0_weight"]


def test_constant_parameter():
    c = gluon.Constant("c", mx.nd.array([[1.0, 2.0]]))
    c.initialize()
    np.testing.assert_allclose(c.data().asnumpy(), [[1.0, 2.0]])
    assert c.grad_req == "null"


def test_dense_forward_shape():
    layer = nn.Dense(8, in_units=4)
    layer.initialize()
    out = layer(mx.nd.ones((2, 4)))
    assert out.shape == (2, 8)


def test_dense_deferred_init():
    layer = nn.Dense(8)
    layer.initialize()
    out = layer(mx.nd.ones((2, 5)))
    assert out.shape == (2, 8)
    assert layer.weight.shape == (8, 5)


def test_dense_no_flatten():
    layer = nn.Dense(7, flatten=False)
    layer.initialize()
    out = layer(mx.nd.ones((2, 3, 5)))
    assert out.shape == (2, 3, 7)


def test_block_name_scope():
    net = nn.HybridSequential(prefix="model_")
    with net.name_scope():
        d = nn.Dense(4)
    assert d.prefix.startswith("model_")


def test_collect_params_select():
    net = nn.HybridSequential()
    with net.name_scope():
        net.add(nn.Dense(4, in_units=4), nn.Dense(2, in_units=4))
    net.initialize()
    weights = net.collect_params(".*weight")
    assert all(k.endswith("weight") for k in weights.keys())
    assert len(weights) == 2


def _make_mlp():
    net = nn.HybridSequential()
    with net.name_scope():
        net.add(nn.Dense(16, activation="relu"), nn.Dense(4))
    return net


def test_hybridize_matches_eager():
    x = mx.nd.array(np.random.randn(3, 8))
    net = _make_mlp()
    net.initialize()
    eager = net(x).asnumpy()
    net.hybridize()
    staged = net(x).asnumpy()
    np.testing.assert_allclose(eager, staged, rtol=1e-5, atol=1e-6)


def test_hybridize_grads_match_eager():
    x = mx.nd.array(np.random.randn(4, 8))
    net = _make_mlp()
    net.initialize()
    with autograd.record():
        loss = (net(x) ** 2).sum()
    loss.backward()
    eager_grads = {
        k: v.grad().asnumpy() for k, v in net.collect_params().items()
    }
    net.hybridize()
    with autograd.record():
        loss = (net(x) ** 2).sum()
    loss.backward()
    for k, v in net.collect_params().items():
        np.testing.assert_allclose(
            eager_grads[k], v.grad().asnumpy(), rtol=1e-4, atol=1e-5
        )


def test_hybridize_retrace_on_shape_change():
    net = _make_mlp()
    net.initialize()
    net.hybridize()
    assert net(mx.nd.ones((2, 8))).shape == (2, 4)
    assert net(mx.nd.ones((5, 8))).shape == (5, 4)


def test_batchnorm_moving_stats_update_eager_and_hybrid():
    for hybridize in (False, True):
        bn = nn.BatchNorm(in_channels=3)
        bn.initialize()
        if hybridize:
            bn.hybridize()
        x = mx.nd.array(np.random.randn(8, 3, 4, 4) * 2 + 5)
        with autograd.record():
            bn(x)
        rm = bn.running_mean.data().asnumpy()
        assert not np.allclose(rm, 0), f"hybridize={hybridize}"
        # eval mode: uses running stats, no update
        rm_before = bn.running_mean.data().asnumpy()
        bn(x)
        np.testing.assert_allclose(
            rm_before, bn.running_mean.data().asnumpy()
        )


def test_batchnorm_normalizes():
    bn = nn.BatchNorm(in_channels=4)
    bn.initialize()
    x = mx.nd.array(np.random.randn(16, 4, 3, 3) * 3 + 7)
    with autograd.record():
        y = bn(x)
    yn = y.asnumpy()
    assert abs(yn.mean()) < 1e-2
    assert abs(yn.std() - 1) < 1e-1


def test_dropout_train_vs_eval():
    do = nn.Dropout(0.5)
    do.initialize()
    x = mx.nd.ones((100, 100))
    with autograd.record():
        y_train = do(x)
    y_eval = do(x)
    np.testing.assert_allclose(y_eval.asnumpy(), 1.0)
    zeros = (y_train.asnumpy() == 0).mean()
    assert 0.3 < zeros < 0.7


def test_dropout_hybrid_varies_across_calls():
    do = nn.Dropout(0.5)
    do.initialize()
    do.hybridize()
    x = mx.nd.ones((40, 40))
    with autograd.record():
        m1 = do(x).asnumpy()
        m2 = do(x).asnumpy()
    assert not np.allclose(m1, m2), "dropout mask must differ per call"


def test_conv2d_shapes():
    conv = nn.Conv2D(12, kernel_size=3, padding=1, strides=2)
    conv.initialize()
    out = conv(mx.nd.ones((2, 3, 8, 8)))
    assert out.shape == (2, 12, 4, 4)
    assert conv.weight.shape == (12, 3, 3, 3)


def test_conv_groups():
    conv = nn.Conv2D(8, kernel_size=1, groups=4, in_channels=8)
    conv.initialize()
    assert conv.weight.shape == (8, 2, 1, 1)
    out = conv(mx.nd.ones((1, 8, 4, 4)))
    assert out.shape == (1, 8, 4, 4)


def test_conv_transpose():
    deconv = nn.Conv2DTranspose(4, kernel_size=2, strides=2)
    deconv.initialize()
    out = deconv(mx.nd.ones((1, 3, 5, 5)))
    assert out.shape == (1, 4, 10, 10)


def test_pooling_layers():
    x = mx.nd.array(np.random.randn(2, 3, 8, 8))
    assert nn.MaxPool2D(2, 2)(x).shape == (2, 3, 4, 4)
    assert nn.AvgPool2D(2, 2)(x).shape == (2, 3, 4, 4)
    assert nn.GlobalAvgPool2D()(x).shape == (2, 3, 1, 1)
    assert nn.GlobalMaxPool2D()(x).shape == (2, 3, 1, 1)


def test_embedding():
    emb = nn.Embedding(10, 4)
    emb.initialize()
    idx = mx.nd.array(np.array([[1, 2], [3, 4]]))
    out = emb(idx)
    assert out.shape == (2, 2, 4)
    with autograd.record():
        loss = emb(idx).sum()
    loss.backward()
    g = emb.weight.grad().asnumpy()
    assert g[1].sum() != 0 and g[0].sum() == 0


def test_layernorm_layer():
    ln = nn.LayerNorm(in_channels=6)
    ln.initialize()
    x = mx.nd.array(np.random.randn(4, 6) * 3 + 2)
    y = ln(x).asnumpy()
    np.testing.assert_allclose(y.mean(axis=-1), 0, atol=1e-5)


def test_activations():
    x = mx.nd.array(np.array([-2.0, -0.5, 0.0, 1.0]))
    np.testing.assert_allclose(
        nn.LeakyReLU(0.1)(x).asnumpy(), [-0.2, -0.05, 0.0, 1.0], rtol=1e-6
    )
    prelu = nn.PReLU()
    prelu.initialize()
    np.testing.assert_allclose(
        prelu(x).asnumpy(), [-0.5, -0.125, 0.0, 1.0], rtol=1e-6
    )
    gelu = nn.GELU()
    assert gelu(x).asnumpy()[3] == pytest.approx(0.8413, rel=1e-3)


def test_sequential_indexing():
    net = nn.HybridSequential()
    net.add(nn.Dense(4), nn.Dense(3), nn.Dense(2))
    assert len(net) == 3
    assert isinstance(net[1], nn.Dense)


def test_save_load_parameters(tmp_path):
    net = _make_mlp()
    net.initialize()
    x = mx.nd.ones((2, 8))
    expected = net(x).asnumpy()
    fname = str(tmp_path / "mlp.params")
    net.save_parameters(fname)
    net2 = _make_mlp()
    net2.load_parameters(fname)
    np.testing.assert_allclose(net2(x).asnumpy(), expected, rtol=1e-6)


def test_trainer_sgd_step():
    net = nn.Dense(1, in_units=2)
    net.initialize()
    trainer = gluon.Trainer(net.collect_params(), "sgd",
                            {"learning_rate": 0.1})
    x = mx.nd.array([[1.0, 2.0]])
    w_before = net.weight.data().asnumpy().copy()
    with autograd.record():
        loss = net(x).sum()
    loss.backward()
    trainer.step(1)
    expected = w_before - 0.1 * np.array([[1.0, 2.0]])
    np.testing.assert_allclose(net.weight.data().asnumpy(), expected, rtol=1e-5)


def test_trainer_learning_rate_set():
    net = nn.Dense(1, in_units=1)
    net.initialize()
    trainer = gluon.Trainer(net.collect_params(), "sgd", {"learning_rate": 0.5})
    assert trainer.learning_rate == 0.5
    trainer.set_learning_rate(0.2)
    assert trainer.learning_rate == 0.2


def test_trainer_save_load_states(tmp_path):
    net = nn.Dense(2, in_units=2)
    net.initialize()
    trainer = gluon.Trainer(net.collect_params(), "sgd",
                            {"learning_rate": 0.1, "momentum": 0.9})
    x = mx.nd.ones((1, 2))
    with autograd.record():
        loss = net(x).sum()
    loss.backward()
    trainer.step(1)
    fname = str(tmp_path / "trainer.states")
    trainer.save_states(fname)
    trainer2 = gluon.Trainer(net.collect_params(), "sgd",
                             {"learning_rate": 0.1, "momentum": 0.9})
    trainer2._init_kvstore()  # load is deferred until kvstore init
    trainer2.load_states(fname)
    s1 = trainer._updaters[0].states
    s2 = trainer2._updaters[0].states
    assert set(s1.keys()) == set(s2.keys())


def test_forward_hooks():
    net = nn.Dense(2, in_units=2)
    net.initialize()
    calls = []
    h1 = net.register_forward_pre_hook(lambda blk, ins: calls.append("pre"))
    h2 = net.register_forward_hook(lambda blk, ins, out: calls.append("post"))
    net(mx.nd.ones((1, 2)))
    assert calls == ["pre", "post"]
    h1.detach()
    h2.detach()
    net(mx.nd.ones((1, 2)))
    assert calls == ["pre", "post"]


def test_lambda_blocks():
    lam = nn.Lambda("relu")
    out = lam(mx.nd.array([-1.0, 1.0]))
    np.testing.assert_allclose(out.asnumpy(), [0.0, 1.0])
    hlam = nn.HybridLambda(lambda F, x: F.relu(x) + 1)
    out = hlam(mx.nd.array([-1.0, 1.0]))
    np.testing.assert_allclose(out.asnumpy(), [1.0, 2.0])


def test_mlp_training_converges():
    np.random.seed(0)
    x = np.random.randn(64, 4).astype("float32")
    w_true = np.random.randn(4, 1).astype("float32")
    y = x @ w_true
    net = nn.Dense(1)
    net.initialize()
    trainer = gluon.Trainer(net.collect_params(), "adam",
                            {"learning_rate": 0.05})
    loss_fn = gluon.loss.L2Loss()
    xs, ys = mx.nd.array(x), mx.nd.array(y)
    first = None
    for i in range(60):
        with autograd.record():
            L = loss_fn(net(xs), ys)
        L.backward()
        trainer.step(64)
        if first is None:
            first = float(L.mean().asscalar())
    last = float(L.mean().asscalar())
    assert last < first * 0.1, (first, last)


def test_batchnorm_eager_training_grads():
    # regression: the fused BN backward must work through the EAGER tape
    # (jax.vjp), not only under hybridize/TrainStep tracing — a non-array
    # residual in the custom_vjp broke exactly (and only) this path
    import numpy as np

    from mxnet_tpu import autograd, gluon, nd

    net = gluon.nn.HybridSequential()
    with net.name_scope():
        net.add(gluon.nn.Conv2D(4, 3, padding=1))
        net.add(gluon.nn.BatchNorm())
        net.add(gluon.nn.Dense(2))
    net.initialize()
    tr = gluon.Trainer(net.collect_params(), "sgd", {"learning_rate": 0.1})
    ce = gluon.loss.SoftmaxCrossEntropyLoss()
    x = nd.array(np.random.RandomState(0).rand(4, 3, 6, 6)
                 .astype(np.float32))
    y = nd.array(np.array([0, 1, 0, 1]))
    with autograd.record():
        loss = ce(net(x), y).mean()
    loss.backward()
    bn = net[1]
    assert float(abs(bn.gamma.grad()).sum().asscalar()) > 0
    assert float(abs(bn.beta.grad()).sum().asscalar()) > 0
    tr.step(4)
