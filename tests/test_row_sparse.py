"""Real row_sparse path: compressed-pair storage, sparse embedding
gradients, lazy sparse optimizer updates, kvstore.row_sparse_pull
(reference: ``src/kvstore/`` row_sparse push/pull + Embedding
sparse_grad + sparse optimizer kernels [unverified])."""

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import autograd, gluon, nd
from mxnet_tpu.ndarray.sparse import RowSparseNDArray


class TestPairStorage:
    def test_from_pair_and_densify(self):
        rs = RowSparseNDArray.from_pair([1, 3, 1], [[1., 2.], [3., 4.], [5., 6.]],
                                        (5, 2))
        assert rs.stype == "row_sparse"
        assert rs.shape == (5, 2)
        d = rs.asnumpy()
        np.testing.assert_allclose(d[1], [6., 8.])  # duplicates sum
        np.testing.assert_allclose(d[3], [3., 4.])
        assert d[0].sum() == 0

    def test_pair_add_concat(self):
        a = RowSparseNDArray.from_pair([0], [[1., 1.]], (3, 2))
        b = RowSparseNDArray.from_pair([2], [[2., 2.]], (3, 2))
        c = a + b
        assert isinstance(c, RowSparseNDArray)
        np.testing.assert_allclose(c.asnumpy(), [[1, 1], [0, 0], [2, 2]])


class TestSparseEmbeddingGrad:
    def test_backward_writes_compressed_pair(self):
        emb = gluon.nn.Embedding(10, 4, sparse_grad=True)
        emb.initialize()
        emb.collect_params().setattr("grad_req", "write")
        x = nd.array(np.array([[1, 3], [1, 7]]), dtype="int32")
        with autograd.record():
            out = emb(x)
            loss = (out * out).sum()
        loss.backward()
        g = emb.weight.grad()
        assert isinstance(g, RowSparseNDArray)
        rows = np.sort(np.unique(g.indices.asnumpy()))
        np.testing.assert_array_equal(rows, [1, 3, 7])
        # value check vs dense: d(sum w[i]^2)/dw[i] = 2*w[i] per occurrence
        w = emb.weight.data().asnumpy()
        dense = np.zeros_like(w)
        for ids in [1, 3, 1, 7]:
            dense[ids] += 2 * w[ids]
        np.testing.assert_allclose(g.asnumpy(), dense, rtol=1e-5, atol=1e-6)

    @pytest.mark.parametrize("optimizer,kw", [
        ("sgd", {"learning_rate": 0.1, "momentum": 0.0}),
        ("adam", {"learning_rate": 0.05}),
    ])
    def test_sparse_training_matches_dense(self, optimizer, kw):
        """The whole point: Embedding(sparse_grad=True) + Trainer must
        track dense training to tolerance (reference parity claim)."""
        rng = np.random.RandomState(0)
        V, D, B, S = 20, 8, 4, 3
        init_w = rng.randn(V, D).astype(np.float32)

        # NOTE: lazy adam (the sparse path, reference ``lazy_update=True``)
        # deliberately skips moment decay on rows absent from a step, so
        # exact dense parity only holds when the same rows appear every
        # step — adam uses a fixed token batch; sgd (memoryless) varies it
        fixed = optimizer == "adam"
        r0 = np.random.RandomState(7)
        x_fixed = r0.randint(0, V, (B, S))

        def run(sparse):
            emb = gluon.nn.Embedding(V, D, sparse_grad=sparse)
            emb.initialize()
            emb.weight.set_data(nd.array(init_w))
            tr = gluon.Trainer(emb.collect_params(), optimizer, dict(kw))
            r = np.random.RandomState(7)
            for i in range(8):
                x_np = x_fixed if fixed else r.randint(0, V, (B, S))
                x = nd.array(x_np, dtype="int32")
                y = nd.array(r.randn(B, S, D).astype(np.float32))
                with autograd.record():
                    out = emb(x)
                    loss = ((out - y) ** 2).mean()
                loss.backward()
                tr.step(B)
            return emb.weight.data().asnumpy()

        w_sparse = run(True)
        w_dense = run(False)
        np.testing.assert_allclose(w_sparse, w_dense, rtol=2e-4, atol=2e-4)

    def test_untouched_rows_have_no_state_updates(self):
        # lazy adam: rows never seen keep zero moments and exact weights
        V, D = 12, 4
        emb = gluon.nn.Embedding(V, D, sparse_grad=True)
        emb.initialize()
        w0 = emb.weight.data().asnumpy().copy()
        tr = gluon.Trainer(emb.collect_params(), "adam",
                           {"learning_rate": 0.1})
        x = nd.array(np.array([[2, 5]]), dtype="int32")
        for _ in range(3):
            with autograd.record():
                loss = (emb(x) ** 2).sum()
            loss.backward()
            tr.step(1)
        w1 = emb.weight.data().asnumpy()
        touched = [2, 5]
        untouched = [i for i in range(V) if i not in touched]
        np.testing.assert_array_equal(w1[untouched], w0[untouched])
        assert not np.allclose(w1[touched], w0[touched])


class TestRowSparsePull:
    def test_pull_requested_rows_only(self):
        kv = mx.kv.create("local")
        val = nd.array(np.arange(12, dtype=np.float32).reshape(6, 2))
        kv.init("emb", val)
        out = RowSparseNDArray.from_pair([0], [[0., 0.]], (6, 2))
        kv.row_sparse_pull("emb", out=out, row_ids=nd.array([1, 4]))
        np.testing.assert_array_equal(out.indices.asnumpy(), [1, 4])
        np.testing.assert_allclose(out.values.asnumpy(),
                                   [[2., 3.], [8., 9.]])
        dense = out.asnumpy()
        assert dense[0].sum() == 0 and dense[2].sum() == 0


def test_weight_used_twice_accumulates():
    # two applications of the same sparse-grad embedding in ONE recorded
    # graph must SUM their gradients (write semantics reset per step, not
    # per apply) — regression for the overwrite bug
    emb = gluon.nn.Embedding(10, 4, sparse_grad=True)
    emb.initialize()
    x1 = nd.array(np.array([[1, 2]]), dtype="int32")
    x2 = nd.array(np.array([[2, 3]]), dtype="int32")
    with autograd.record():
        loss = emb(x1).sum() + emb(x2).sum()
    loss.backward()
    g = emb.weight.grad()
    assert isinstance(g, RowSparseNDArray)
    dense = g.asnumpy()
    np.testing.assert_allclose(dense[1], 1.0)
    np.testing.assert_allclose(dense[2], 2.0)  # appears in both uses
    np.testing.assert_allclose(dense[3], 1.0)
    # next step's forward drops the stale grad (write semantics)
    with autograd.record():
        loss = emb(x1).sum()
    loss.backward()
    dense2 = emb.weight.grad().asnumpy()
    np.testing.assert_allclose(dense2[3], 0.0)
    np.testing.assert_allclose(dense2[1], 1.0)
