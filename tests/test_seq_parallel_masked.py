"""Ragged-batch (valid_length) parity for sequence-parallel attention.

Ring attention translates the GLOBAL per-row key budget into
per-visiting-chunk local budgets (parallel/ring_attention.py:_local_vl);
Ulysses applies it unchanged after the head<->seq all_to_all. Both must
match the single-device masked kernel exactly, for values and gradients,
including rows whose budget ends inside or before a chunk."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec

from mxnet_tpu.parallel.ring_attention import ring_flash_attention
from mxnet_tpu.parallel.ulysses import ulysses_attention
from mxnet_tpu.ops.pallas.flash_attention import flash_attention


@pytest.fixture(scope="module")
def seq_mesh():
    devs = np.array(jax.devices()[:4])
    return Mesh(devs, ("seq",))


def _data(B=4, H=8, S=256, D=16, seed=0):
    rng = np.random.RandomState(seed)
    q = jnp.asarray(rng.randn(B, H, S, D), jnp.float32)
    k = jnp.asarray(rng.randn(B, H, S, D), jnp.float32)
    v = jnp.asarray(rng.randn(B, H, S, D), jnp.float32)
    # budgets straddling chunk boundaries: inside chunk 0, exactly at a
    # boundary, inside chunk 2, full length  (4 devices x 64 keys/chunk)
    vl = jnp.asarray([37, 64, 170, 256], jnp.int32)
    return q, k, v, vl


def _ref(q, k, v, vl, causal=False):
    # the single-device masked flash kernel: the parity claim is
    # "seq-parallel masked == single-chip masked", same arithmetic
    return flash_attention(q, k, v, vl, causal=causal,
                           sm_scale=1.0 / np.sqrt(q.shape[-1]))


@pytest.mark.parametrize("causal", [False, True])
def test_ring_masked_fwd(seq_mesh, causal):
    q, k, v, vl = _data()
    out = ring_flash_attention(q, k, v, seq_mesh, "seq", causal=causal,
                               valid_length=vl)
    want = _ref(q, k, v, vl, causal=causal)
    np.testing.assert_allclose(np.asarray(out), np.asarray(want),
                               rtol=2e-4, atol=2e-4)


@pytest.mark.parametrize("causal", [False, True])
def test_ulysses_masked_fwd(seq_mesh, causal):
    q, k, v, vl = _data()
    out = ulysses_attention(q, k, v, seq_mesh, "seq", causal=causal,
                            valid_length=vl)
    want = _ref(q, k, v, vl, causal=causal)
    np.testing.assert_allclose(np.asarray(out), np.asarray(want),
                               rtol=2e-4, atol=2e-4)


def test_ring_masked_grads(seq_mesh):
    q, k, v, vl = _data(S=128, seed=1)

    def ring_loss(q, k, v):
        o = ring_flash_attention(q, k, v, seq_mesh, "seq", valid_length=vl)
        return jnp.sum(o * o)

    def ref_loss(q, k, v):
        o = _ref(q, k, v, vl)
        return jnp.sum(o * o)

    g1 = jax.grad(ring_loss, argnums=(0, 1, 2))(q, k, v)
    g2 = jax.grad(ref_loss, argnums=(0, 1, 2))(q, k, v)
    for a, b, name in zip(g1, g2, "qkv"):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=5e-3, atol=5e-3, err_msg=f"d{name}")
    # grads w.r.t. keys past the budget must be exactly zero
    dk = np.asarray(g1[1])
    assert np.allclose(dk[0, :, 37:], 0.0)
    assert np.allclose(dk[1, :, 64:], 0.0)


def test_ulysses_masked_grads(seq_mesh):
    q, k, v, vl = _data(S=128, seed=2)

    def uly_loss(q, k, v):
        o = ulysses_attention(q, k, v, seq_mesh, "seq", valid_length=vl)
        return jnp.sum(o * o)

    def ref_loss(q, k, v):
        o = _ref(q, k, v, vl)
        return jnp.sum(o * o)

    g1 = jax.grad(uly_loss, argnums=(0, 1, 2))(q, k, v)
    g2 = jax.grad(ref_loss, argnums=(0, 1, 2))(q, k, v)
    for a, b, name in zip(g1, g2, "qkv"):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=5e-3, atol=5e-3, err_msg=f"d{name}")


def test_ring_masked_under_jit_with_dp(seq_mesh):
    # composes under jit; also exercises a chunk that is fully masked for
    # every row (vl max 100 < 128: chunk 2 and 3 of 4x64 never attended)
    q, k, v, _ = _data(S=256, seed=3)
    vl = jnp.asarray([10, 100, 64, 1], jnp.int32)

    @jax.jit
    def f(q, k, v):
        return ring_flash_attention(q, k, v, seq_mesh, "seq",
                                    valid_length=vl)

    out = f(q, k, v)
    want = _ref(q, k, v, vl)
    np.testing.assert_allclose(np.asarray(out), np.asarray(want),
                               rtol=2e-4, atol=2e-4)
    assert np.isfinite(np.asarray(out)).all()
