"""linalg_* family, spatial transformer group, im2col/col2im, multi-tensor
optimizer kernels (round-4 op-breadth tail; reference la_op.cc,
spatial_transformer.cc, correlation.cc, optimizer_op.cc [unverified])."""

import numpy as np
import jax
import jax.numpy as jnp
import pytest

import mxnet_tpu as mx
from mxnet_tpu import nd


rng = np.random.default_rng(42)


def _spd(n, b=()):
    a = rng.normal(size=b + (n, n)).astype(np.float32)
    return a @ np.swapaxes(a, -1, -2) + n * np.eye(n, dtype=np.float32)


# ------------------------------------------------------------------ linalg
def test_gemm_gemm2():
    A = rng.normal(size=(2, 3, 4)).astype(np.float32)
    B = rng.normal(size=(2, 4, 5)).astype(np.float32)
    C = rng.normal(size=(2, 3, 5)).astype(np.float32)
    out = nd.linalg_gemm(nd.array(A), nd.array(B), nd.array(C),
                         alpha=2.0, beta=0.5).asnumpy()
    np.testing.assert_allclose(out, 2.0 * A @ B + 0.5 * C, rtol=1e-5)
    out2 = nd.linalg_gemm2(nd.array(A), nd.array(np.swapaxes(B, 1, 2)),
                           transpose_b=True).asnumpy()
    np.testing.assert_allclose(out2, A @ B, rtol=1e-5)


def test_potrf_potri_sumlogdiag():
    A = _spd(5, (3,))
    L = nd.linalg_potrf(nd.array(A)).asnumpy()
    np.testing.assert_allclose(L @ np.swapaxes(L, -1, -2), A, rtol=1e-3,
                               atol=1e-3)
    Ainv = nd.linalg_potri(nd.array(L)).asnumpy()
    np.testing.assert_allclose(Ainv, np.linalg.inv(A), rtol=1e-2, atol=1e-3)
    sld = nd.linalg_sumlogdiag(nd.array(L)).asnumpy()
    np.testing.assert_allclose(2 * sld, np.linalg.slogdet(A)[1], rtol=1e-4)


@pytest.mark.parametrize("transpose", [False, True])
@pytest.mark.parametrize("rightside", [False, True])
def test_trsm(transpose, rightside):
    L = np.tril(rng.normal(size=(4, 4))).astype(np.float32) \
        + 4 * np.eye(4, dtype=np.float32)
    B = rng.normal(size=(4, 4)).astype(np.float32)
    X = nd.linalg_trsm(nd.array(L), nd.array(B), transpose=transpose,
                       rightside=rightside, alpha=2.0).asnumpy()
    opA = L.T if transpose else L
    got = X @ opA if rightside else opA @ X
    np.testing.assert_allclose(got, 2.0 * B, rtol=1e-3, atol=1e-3)


@pytest.mark.parametrize("transpose", [False, True])
@pytest.mark.parametrize("rightside", [False, True])
def test_trmm(transpose, rightside):
    L = np.tril(rng.normal(size=(4, 4))).astype(np.float32)
    B = rng.normal(size=(4, 4)).astype(np.float32)
    out = nd.linalg_trmm(nd.array(L), nd.array(B), transpose=transpose,
                         rightside=rightside, alpha=0.5).asnumpy()
    opA = L.T if transpose else L
    ref = 0.5 * (B @ opA if rightside else opA @ B)
    np.testing.assert_allclose(out, ref, rtol=1e-5, atol=1e-5)


def test_syrk_det_slogdet_inverse_syevd_gelqf():
    A = rng.normal(size=(3, 4)).astype(np.float32)
    np.testing.assert_allclose(nd.linalg_syrk(nd.array(A)).asnumpy(),
                               A @ A.T, rtol=1e-5)
    S = _spd(4)
    np.testing.assert_allclose(nd.linalg_det(nd.array(S)).asnumpy(),
                               np.linalg.det(S), rtol=1e-3)
    sign, logab = nd.linalg_slogdet(nd.array(S))
    np.testing.assert_allclose(logab.asnumpy(), np.linalg.slogdet(S)[1],
                               rtol=1e-4)
    np.testing.assert_allclose(nd.linalg_inverse(nd.array(S)).asnumpy(),
                               np.linalg.inv(S), rtol=1e-3, atol=1e-4)
    U, lam = nd.linalg_syevd(nd.array(S))
    U, lam = U.asnumpy(), lam.asnumpy()
    np.testing.assert_allclose(U.T @ np.diag(lam) @ U, S, rtol=1e-3,
                               atol=1e-3)
    A2 = rng.normal(size=(3, 5)).astype(np.float32)
    Lq, Q = nd.linalg_gelqf(nd.array(A2))
    Lq, Q = Lq.asnumpy(), Q.asnumpy()
    np.testing.assert_allclose(Lq @ Q, A2, rtol=1e-4, atol=1e-4)
    np.testing.assert_allclose(Q @ Q.T, np.eye(3), atol=1e-5)


def test_diag_trian_roundtrip():
    A = rng.normal(size=(2, 4, 4)).astype(np.float32)
    d = nd.linalg_extractdiag(nd.array(A)).asnumpy()
    np.testing.assert_allclose(d, np.diagonal(A, axis1=-2, axis2=-1))
    D = nd.linalg_makediag(nd.array(d)).asnumpy()
    np.testing.assert_allclose(np.diagonal(D, axis1=-2, axis2=-1), d)
    t = nd.linalg_extracttrian(nd.array(A)).asnumpy()
    T = nd.linalg_maketrian(nd.array(t)).asnumpy()
    np.testing.assert_allclose(T, np.tril(A), atol=1e-6)
    # band offsets round-trip in BOTH directions (review finding: the
    # positive-offset inversion was broken)
    for off in (-1, 1, 2):
        for lower in (True, False):
            tt = nd.linalg_extracttrian(nd.array(A), offset=off,
                                        lower=lower).asnumpy()
            TT = nd.linalg_maketrian(nd.array(tt), offset=off,
                                     lower=lower).asnumpy()
            ref = np.tril(A, off) if lower else np.triu(A, off)
            np.testing.assert_allclose(TT, ref, atol=1e-6,
                                       err_msg=f"off={off} lower={lower}")


def test_potrf_gradient():
    from mxnet_tpu.test_utils import check_numeric_gradient

    S = _spd(3)

    def f(a):
        return mx.nd.linalg_sumlogdiag(mx.nd.linalg_potrf(a))

    check_numeric_gradient(f, [S], rtol=3e-2, atol=1e-3)


# ----------------------------------------------------------------- spatial
def test_bilinear_sampler_identity():
    data = rng.normal(size=(2, 3, 8, 8)).astype(np.float32)
    ys = np.linspace(-1, 1, 8, dtype=np.float32)
    xs = np.linspace(-1, 1, 8, dtype=np.float32)
    gy, gx = np.meshgrid(ys, xs, indexing="ij")
    grid = np.broadcast_to(np.stack([gx, gy])[None], (2, 2, 8, 8)).copy()
    out = nd.BilinearSampler(nd.array(data), nd.array(grid)).asnumpy()
    np.testing.assert_allclose(out, data, atol=1e-5)


def test_bilinear_sampler_shift_and_oob():
    data = np.arange(16, dtype=np.float32).reshape(1, 1, 4, 4)
    # shift by exactly one pixel right: x' = x + 2/(W-1)
    ys = np.linspace(-1, 1, 4, dtype=np.float32)
    gy, gx = np.meshgrid(ys, ys, indexing="ij")
    grid = np.stack([gx + 2.0 / 3.0, gy])[None].astype(np.float32)
    out = nd.BilinearSampler(nd.array(data), nd.array(grid)).asnumpy()
    np.testing.assert_allclose(out[0, 0, :, :3], data[0, 0, :, 1:],
                               atol=1e-5)
    np.testing.assert_allclose(out[0, 0, :, 3], 0.0, atol=1e-5)  # zero pad


def test_grid_generator_affine_identity_and_spatial_transformer():
    theta = np.tile(np.array([1, 0, 0, 0, 1, 0], np.float32), (2, 1))
    grid = nd.GridGenerator(nd.array(theta), transform_type="affine",
                            target_shape=(5, 7)).asnumpy()
    assert grid.shape == (2, 2, 5, 7)
    np.testing.assert_allclose(grid[0, 0, 0], np.linspace(-1, 1, 7),
                               atol=1e-6)
    np.testing.assert_allclose(grid[0, 1, :, 0], np.linspace(-1, 1, 5),
                               atol=1e-6)
    data = rng.normal(size=(2, 3, 5, 7)).astype(np.float32)
    out = nd.SpatialTransformer(nd.array(data), nd.array(theta),
                                target_shape=(5, 7)).asnumpy()
    np.testing.assert_allclose(out, data, atol=1e-5)


def test_grid_generator_warp_zero_flow():
    flow = np.zeros((1, 2, 4, 6), np.float32)
    grid = nd.GridGenerator(nd.array(flow), transform_type="warp").asnumpy()
    np.testing.assert_allclose(grid[0, 0, 0], np.linspace(-1, 1, 6),
                               atol=1e-6)


def test_bilinear_sampler_gradient():
    from mxnet_tpu.test_utils import check_numeric_gradient

    data = rng.normal(size=(1, 2, 5, 5)).astype(np.float32)
    grid = (rng.uniform(-0.8, 0.8, (1, 2, 3, 3))).astype(np.float32)

    def f(d, g):
        return mx.nd.BilinearSampler(d, g)

    check_numeric_gradient(f, [data, grid], rtol=3e-2, atol=1e-3)


def test_correlation_numpy_parity():
    """out[d](q) = mean_c d1[q] * d2[q + d] with zero padding; output
    spatial size is the reference's border-cropped grid
    (Hp - 2*(max_displacement + kernel_radius))."""
    d1 = rng.normal(size=(1, 3, 5, 5)).astype(np.float32)
    d2 = rng.normal(size=(1, 3, 5, 5)).astype(np.float32)
    p, md = 1, 1
    out = nd.Correlation(nd.array(d1), nd.array(d2), kernel_size=1,
                         max_displacement=md, pad_size=p).asnumpy()
    # padded 7x7 grid, border md+kr=1 cropped on each side -> 5x5
    assert out.shape == (1, 9, 5, 5)
    a = np.pad(d1, ((0, 0), (0, 0), (p, p), (p, p)))
    b = np.pad(d2, ((0, 0), (0, 0), (p, p), (p, p)))
    Hp = 5 + 2 * p
    border = md
    ch = 0
    for dy in (-1, 0, 1):
        for dx in (-1, 0, 1):
            ref = np.zeros((Hp, Hp), np.float32)
            for y in range(Hp):
                for x in range(Hp):
                    yy, xx = y + dy, x + dx
                    if 0 <= yy < Hp and 0 <= xx < Hp:
                        ref[y, x] = np.dot(a[0, :, y, x], b[0, :, yy, xx]) / 3
            np.testing.assert_allclose(
                out[0, ch], ref[border:Hp - border, border:Hp - border],
                atol=1e-5, err_msg=f"disp ({dy},{dx})")
            ch += 1
    # self-correlation: zero displacement dominates globally (C-S)
    outs = nd.Correlation(nd.array(d1), nd.array(d1), kernel_size=1,
                          max_displacement=1, pad_size=1).asnumpy()
    sums = outs.sum(axis=(0, 2, 3))
    assert sums[4] >= sums.max() - 1e-5


def test_im2col_col2im():
    x = rng.normal(size=(2, 3, 6, 6)).astype(np.float32)
    cols = nd.im2col(nd.array(x), kernel=(3, 3), stride=(1, 1),
                     pad=(1, 1)).asnumpy()
    assert cols.shape == (2, 27, 36)
    # parity vs a conv: conv(x, W) == W_flat @ im2col(x)
    W = rng.normal(size=(4, 3, 3, 3)).astype(np.float32)
    ref = np.asarray(jax.lax.conv_general_dilated(
        jnp.asarray(x), jnp.asarray(W), (1, 1), [(1, 1), (1, 1)],
        dimension_numbers=("NCHW", "OIHW", "NCHW")))
    got = np.einsum("ok,nkl->nol", W.reshape(4, 27), cols).reshape(ref.shape)
    np.testing.assert_allclose(got, ref, rtol=1e-4, atol=1e-4)
    # col2im is the exact adjoint: <col2im(c), y> == <c, im2col(y)>
    c = rng.normal(size=(2, 27, 36)).astype(np.float32)
    y = rng.normal(size=(2, 3, 6, 6)).astype(np.float32)
    back = nd.col2im(nd.array(c), input_shape=(2, 3, 6, 6), kernel=(3, 3),
                     stride=(1, 1), pad=(1, 1)).asnumpy()
    lhs = np.sum(back * y)
    rhs = np.sum(c * nd.im2col(nd.array(y), kernel=(3, 3), stride=(1, 1),
                               pad=(1, 1)).asnumpy())
    np.testing.assert_allclose(lhs, rhs, rtol=1e-4)


# ------------------------------------------------------------ multi-tensor
def test_multi_sum_sq_and_lars():
    ws = [rng.normal(size=(4, 5)).astype(np.float32) for _ in range(3)]
    out = nd.multi_sum_sq(*[nd.array(w) for w in ws],
                          num_arrays=3).asnumpy()
    np.testing.assert_allclose(out, [np.sum(w * w) for w in ws], rtol=1e-5)
    lrs = np.array([0.1, 0.2, 0.3], np.float32)
    wds = np.array([1e-4, 0.0, 1e-4], np.float32)
    wss = np.array([np.sum(w * w) for w in ws], np.float32)
    gss = wss * 0.5
    got = nd.multi_lars(nd.array(lrs), nd.array(wss), nd.array(gss),
                        nd.array(wds), eta=0.01).asnumpy()
    coef = 0.01 * np.sqrt(wss) / (np.sqrt(gss) + wds * np.sqrt(wss) + 1e-8)
    np.testing.assert_allclose(got, lrs * coef, rtol=1e-5)


def test_multi_sgd_parity_with_single():
    ws = [rng.normal(size=(3, 3)).astype(np.float32) for _ in range(2)]
    gs = [rng.normal(size=(3, 3)).astype(np.float32) for _ in range(2)]
    outs = nd.multi_sgd_update(
        nd.array(ws[0]), nd.array(gs[0]), nd.array(ws[1]), nd.array(gs[1]),
        lrs=(0.1, 0.2), wds=(0.0, 1e-3), num_weights=2)
    for i, o in enumerate(outs):
        ref = nd.sgd_update(nd.array(ws[i]), nd.array(gs[i]),
                            lr=(0.1, 0.2)[i], wd=(0.0, 1e-3)[i]).asnumpy()
        np.testing.assert_allclose(o.asnumpy(), ref, rtol=1e-6)


def test_multi_sgd_mom_and_mp():
    w = rng.normal(size=(4,)).astype(np.float32)
    g = rng.normal(size=(4,)).astype(np.float32)
    m = np.zeros(4, np.float32)
    w_, m_ = nd.multi_sgd_mom_update(nd.array(w), nd.array(g), nd.array(m),
                                     lrs=0.1, momentum=0.9, num_weights=1)
    ref_w, ref_m = nd.sgd_mom_update(nd.array(w), nd.array(g), nd.array(m),
                                     lr=0.1, momentum=0.9)
    np.testing.assert_allclose(w_.asnumpy(), ref_w.asnumpy(), rtol=1e-6)
    wb = w.astype(jnp.bfloat16)
    outs = nd.multi_mp_sgd_update(nd.array(np.asarray(wb, np.float32)
                                           .astype(np.float32)),
                                  nd.array(g), nd.array(w),
                                  lrs=0.1, num_weights=1)
    np.testing.assert_allclose(outs[1].asnumpy(), w - 0.1 * g, rtol=1e-6)


def test_add_n_swapaxes_reshape_like():
    a = rng.normal(size=(2, 3)).astype(np.float32)
    b = rng.normal(size=(2, 3)).astype(np.float32)
    np.testing.assert_allclose(nd.add_n(nd.array(a), nd.array(b)).asnumpy(),
                               a + b, rtol=1e-6)
    x = rng.normal(size=(2, 3, 4)).astype(np.float32)
    np.testing.assert_allclose(
        nd.swapaxes(nd.array(x), dim1=0, dim2=2).asnumpy(),
        np.swapaxes(x, 0, 2))
    np.testing.assert_allclose(
        nd.reshape_like(nd.array(x), nd.array(np.zeros((4, 6)))).asnumpy(),
        x.reshape(4, 6))
