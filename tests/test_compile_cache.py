"""Shape-stable execution: persistent compilation cache, AOT warmup,
recompile guard, and the tier-1 compile-count lint."""

import os
import subprocess
import sys

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import compile_cache
from mxnet_tpu.base import MXNetError


def _counters():
    return mx.telemetry.registry().snapshot()["counters"]


class TestRecompileGuard:
    def test_counts_distinct_signatures(self):
        g = compile_cache.RecompileGuard("t")
        assert g.observe(("a",)) is True
        assert g.observe(("a",)) is False
        assert g.observe(("b",)) is True
        assert g.signatures == 2
        assert g.steady_state_recompiles == 0

    def test_steady_state_recompile_warns(self):
        g = compile_cache.RecompileGuard("t")
        g.observe(("a",))
        g.mark_steady()
        with pytest.warns(RuntimeWarning, match="shape-churn"):
            g.observe(("b",))
        assert g.steady_state_recompiles == 1

    def test_limit_raises(self, monkeypatch):
        monkeypatch.setenv("MXTPU_RECOMPILE_LIMIT", "0")
        g = compile_cache.RecompileGuard("t")
        g.observe(("a",))
        g.mark_steady()
        with pytest.raises(MXNetError, match="MXTPU_RECOMPILE_LIMIT"):
            g.observe(("b",))

    def test_negative_limit_silences(self, monkeypatch):
        import warnings

        monkeypatch.setenv("MXTPU_RECOMPILE_LIMIT", "-1")
        g = compile_cache.RecompileGuard("t")
        g.observe(("a",))
        g.mark_steady()
        with warnings.catch_warnings():
            warnings.simplefilter("error")
            g.observe(("b",))  # counted, not warned
        assert g.steady_state_recompiles == 1

    def test_unbounded_signature_warning(self, monkeypatch):
        monkeypatch.setenv("MXTPU_RECOMPILE_LIMIT", "3")
        g = compile_cache.RecompileGuard("t")
        with pytest.warns(RuntimeWarning, match="staged signatures"):
            for i in range(5):
                g.observe((i,))

    def test_info_summaries(self):
        g = compile_cache.RecompileGuard("t")
        g.observe(("a",), "sigA")
        g.observe(("a",))
        info = g.info()
        assert info["signatures"] == 1
        assert info["entries"][0]["signature"] == "sigA"
        assert info["entries"][0]["count"] == 2


def _tiny_step(donate=True):
    from mxnet_tpu import gluon, nd, optimizer as opt
    from mxnet_tpu.parallel import TrainStep

    net = gluon.nn.Dense(4)
    net.initialize()
    net(nd.zeros((2, 8)))
    return TrainStep(net, gluon.loss.L2Loss(),
                     opt.SGD(learning_rate=0.1), donate=donate)


class TestTrainStepWarmup:
    def test_warmup_then_zero_recompiles(self):
        import jax

        compiles = []
        jax.monitoring.register_event_duration_secs_listener(
            lambda e, d, **kw: compiles.append(e)
            if "backend_compile" in e else None)
        step = _tiny_step()
        sigs = [(((4, 8), "float32"), ((4, 4), "float32")),
                (((8, 8), "float32"), ((8, 4), "float32"))]
        assert step.warmup(sigs) == 2
        assert step.compile_guard.steady
        assert step.compile_guard.signatures == 2
        x4 = mx.nd.array(np.zeros((4, 8), "float32"))
        y4 = mx.nd.array(np.zeros((4, 4), "float32"))
        x8 = mx.nd.array(np.zeros((8, 8), "float32"))
        y8 = mx.nd.array(np.zeros((8, 4), "float32"))
        float(x4.sum().asscalar())  # retire eager array setup compiles
        n0 = len(compiles)
        for _ in range(2):
            step(x4, y4)
            step(x8, y8)
        assert step.compile_guard.steady_state_recompiles == 0
        assert len(compiles) == n0, "post-warmup steps recompiled"

    def test_warmup_duplicate_signatures_compile_once(self):
        step = _tiny_step()
        sig = (((4, 8), "float32"), ((4, 4), "float32"))
        assert step.warmup([sig, sig]) == 1

    def test_warmup_preserves_training_state(self):
        step = _tiny_step()
        before = {n: np.asarray(v)
                  for n, v in step._values.items()}
        t_before = step._t
        step.warmup([(((4, 8), "float32"), ((4, 4), "float32"))])
        for n, v in step._values.items():
            assert np.asarray(v).tobytes() == before[n].tobytes(), n
        assert step._t == t_before

    def test_warmed_and_cold_first_losses_match(self):
        rng = np.random.RandomState(3)
        x = rng.randn(4, 8).astype("float32")
        y = rng.randn(4, 4).astype("float32")

        def first_loss(warm):
            mx.random.seed(11)
            np.random.seed(11)
            step = _tiny_step()
            if warm:
                step.warmup([(((4, 8), "float32"), ((4, 4), "float32"))])
            return float(step(mx.nd.array(x), mx.nd.array(y)).asscalar())

        assert first_loss(False) == first_loss(True)

    def test_accum_split_signatures(self):
        from mxnet_tpu import gluon, nd, optimizer as opt
        from mxnet_tpu.parallel import TrainStep

        net = gluon.nn.Dense(4)
        net.initialize()
        net(nd.zeros((2, 8)))
        step = TrainStep(net, gluon.loss.L2Loss(),
                         opt.SGD(learning_rate=0.1), grad_accum=2)
        step.warmup([(((8, 8), "float32"), ((8, 4), "float32"))])
        step(mx.nd.array(np.zeros((8, 8), "float32")),
             mx.nd.array(np.zeros((8, 4), "float32")))
        assert step.compile_guard.signatures == 1
        assert step.compile_guard.steady_state_recompiles == 0

    def test_steady_recompile_raises_under_limit(self, monkeypatch):
        monkeypatch.setenv("MXTPU_RECOMPILE_LIMIT", "0")
        step = _tiny_step()
        step.warmup([(((4, 8), "float32"), ((4, 4), "float32"))])
        with pytest.raises(MXNetError, match="MXTPU_RECOMPILE_LIMIT"):
            step(mx.nd.array(np.zeros((6, 8), "float32")),
                 mx.nd.array(np.zeros((6, 4), "float32")))

    def test_cache_info(self):
        step = _tiny_step()
        step(mx.nd.array(np.zeros((4, 8), "float32")),
             mx.nd.array(np.zeros((4, 4), "float32")))
        info = step.cache_info()
        assert info["signatures"] == 1
        assert "float32[4x8]" in info["entries"][0]["signature"]


class TestCachedOpWarmup:
    def _net(self):
        from mxnet_tpu import gluon, nd

        net = gluon.nn.HybridSequential()
        with net.name_scope():
            net.add(gluon.nn.Dense(8, activation="relu"),
                    gluon.nn.Dense(4))
        net.initialize()
        net.hybridize()
        net(nd.zeros((2, 6)))
        return net

    def test_forward_warmup_then_zero_recompiles(self):
        from mxnet_tpu import nd

        net = self._net()
        co = net._cached_op
        assert co.warmup((((4, 6), "float32"),)) == 1
        net(nd.zeros((4, 6)))
        assert co._guard.steady_state_recompiles == 0

    def test_backward_warmup_covers_recorded_path(self):
        from mxnet_tpu import autograd, nd

        net = self._net()
        co = net._cached_op
        co.warmup((((4, 6), "float32"),), backward=True)
        x = nd.zeros((4, 6))
        x.attach_grad()
        with autograd.record():
            y = net(x).sum()
        y.backward()
        assert co._guard.steady_state_recompiles == 0

    def test_cache_info_tracks_modes(self):
        from mxnet_tpu import nd

        net = self._net()
        co = net._cached_op
        co.warmup((((4, 6), "float32"),), backward=True)
        info = co.cache_info()
        sigs = [e["signature"] for e in info["entries"]]
        assert any("train vjp" in s for s in sigs)
        assert any("train fwd" in s for s in sigs)
        assert info["staged_programs"] >= 1


class TestEstimatorWarmup:
    def test_fit_warmup_true_precompiles_loader_shapes(self):
        from mxnet_tpu import gluon
        from mxnet_tpu.gluon.contrib.estimator import Estimator

        rng = np.random.RandomState(0)
        ds = [(rng.rand(6).astype("float32"),
               rng.rand(4).astype("float32")) for _ in range(12)]
        loader = gluon.data.DataLoader(ds, batch_size=4)
        net = gluon.nn.Dense(4)
        net.initialize()
        est = Estimator(net, gluon.loss.L2Loss())
        before = _counters().get("compile/warmup_compiles", 0)
        est.fit(loader, epochs=1, warmup=True)
        assert _counters()["compile/warmup_compiles"] == before + 1

    def test_fit_warmup_explicit_signatures(self):
        from mxnet_tpu import gluon
        from mxnet_tpu.gluon.contrib.estimator import Estimator

        rng = np.random.RandomState(0)
        ds = [(rng.rand(6).astype("float32"),
               rng.rand(4).astype("float32")) for _ in range(8)]
        loader = gluon.data.DataLoader(ds, batch_size=4)
        net = gluon.nn.Dense(4)
        net.initialize()
        est = Estimator(net, gluon.loss.L2Loss())
        before = _counters().get("compile/warmup_compiles", 0)
        est.fit(loader, epochs=1,
                warmup=[(((4, 6), "float32"), ((4, 4), "float32"))])
        assert _counters()["compile/warmup_compiles"] == before + 1

    def test_fit_warmup_marks_hybridized_guard_steady(self):
        from mxnet_tpu import gluon
        from mxnet_tpu.gluon.contrib.estimator import Estimator

        rng = np.random.RandomState(0)
        ds = [(rng.rand(6).astype("float32"),
               rng.rand(4).astype("float32")) for _ in range(8)]
        loader = gluon.data.DataLoader(ds, batch_size=4)
        net = gluon.nn.Dense(4)
        net.initialize()
        net.hybridize()
        est = Estimator(net, gluon.loss.L2Loss())
        est.fit(loader, epochs=1, warmup=True)
        assert net._cached_op is not None
        assert net._cached_op._guard.steady


_CHILD = r"""
import jax, jax.numpy as jnp
import mxnet_tpu as mx
f = jax.jit(lambda x: (x * 3 + 1).sum())
f(jnp.arange(16.0))
s = mx.compile_cache.cache_stats()
print("STATS", s["enabled"], s["hits"], s["misses"])
"""


class TestPersistentCache:
    def test_env_setup_modes(self, monkeypatch):
        assert compile_cache.recompile_limit() is None or isinstance(
            compile_cache.recompile_limit(), int)
        # default-on convention dir (set up at import)
        assert compile_cache.is_enabled()
        assert compile_cache.cache_dir()

    def test_subprocess_warm_start_hits(self, tmp_path):
        env = dict(os.environ)
        env["MXTPU_COMPILE_CACHE_DIR"] = str(tmp_path)
        env["JAX_PLATFORMS"] = "cpu"
        outs = []
        for _ in range(2):
            r = subprocess.run([sys.executable, "-c", _CHILD],
                               capture_output=True, text=True, env=env,
                               timeout=240, cwd=os.path.dirname(
                                   os.path.dirname(
                                       os.path.abspath(__file__))))
            assert r.returncode == 0, r.stderr[-2000:]
            line = [ln for ln in r.stdout.splitlines()
                    if ln.startswith("STATS")][0]
            outs.append(line.split())
        first, second = outs
        assert first[1] == "True"
        assert int(first[3]) > 0, "first process should miss (and write)"
        assert int(second[2]) > 0, "second process should hit the cache"


class TestTelemetrySurface:
    def test_report_carries_compile_family(self):
        rep = mx.telemetry.report()
        for k in ("compile_signatures", "compile_steady_state_recompiles",
                  "compile_warmup_compiles", "compile_cache_hits",
                  "compile_cache_misses"):
            assert k in rep

    def test_telemetry_report_tool_prints_compile_family(self, tmp_path,
                                                         capsys):
        import json
        sys.path.insert(0, os.path.join(
            os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
            "tools"))
        try:
            import telemetry_report
        finally:
            sys.path.pop(0)
        (tmp_path / "events.jsonl").write_text(
            '{"ph": "X", "name": "estimator.epoch", "dur": 1000}\n')
        (tmp_path / "report.json").write_text(json.dumps({
            "counters": {"compile/signatures": 5,
                         "compile/steady_state_recompiles": 2,
                         "compile/cache_hits": 3},
            "gauges": {"compile/persistent_cache_enabled": 1},
            "histograms": {"jax/compile_time_s":
                           {"sum": 1.5, "count": 4}},
        }))
        telemetry_report.main([str(tmp_path)])
        out = capsys.readouterr().out
        assert "Compile (shape stability)" in out
        assert "compile/signatures" in out
        assert "WARNING: 2 steady-state recompile(s)" in out
