"""Autograd: record/backward semantics + finite-difference gradient checks.

Models the reference's ``tests/python/unittest/test_autograd.py`` and the
``check_numeric_gradient`` harness from ``mx.test_utils`` [unverified].
"""

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import autograd, nd


def assert_close(a, b, rtol=1e-4, atol=1e-5):
    np.testing.assert_allclose(
        a.asnumpy() if isinstance(a, mx.NDArray) else a,
        b.asnumpy() if isinstance(b, mx.NDArray) else b,
        rtol=rtol, atol=atol,
    )


def numeric_grad(f, x, eps=1e-3):
    """Central finite differences of scalar-valued f at numpy point x."""
    g = np.zeros_like(x)
    it = np.nditer(x, flags=["multi_index"])
    while not it.finished:
        i = it.multi_index
        xp, xm = x.copy(), x.copy()
        xp[i] += eps
        xm[i] -= eps
        g[i] = (f(xp) - f(xm)) / (2 * eps)
        it.iternext()
    return g


class TestBasics:
    def test_square_grad(self):
        x = nd.array([1.0, 2.0, 3.0])
        x.attach_grad()
        with autograd.record():
            y = x * x
        y.backward()
        assert_close(x.grad, np.array([2.0, 4.0, 6.0]))

    def test_chain(self):
        x = nd.array([[1.0, 2.0], [3.0, 4.0]])
        x.attach_grad()
        with autograd.record():
            y = nd.exp(x).sum()
        y.backward()
        assert_close(x.grad, np.exp(x.asnumpy()), rtol=1e-3)

    def test_dot_grads(self):
        a = nd.array(np.random.rand(3, 4).astype(np.float32))
        b = nd.array(np.random.rand(4, 2).astype(np.float32))
        a.attach_grad()
        b.attach_grad()
        with autograd.record():
            loss = nd.dot(a, b).sum()
        loss.backward()
        assert_close(a.grad, np.ones((3, 2)) @ b.asnumpy().T, rtol=1e-3)
        assert_close(b.grad, a.asnumpy().T @ np.ones((3, 2)), rtol=1e-3)

    def test_not_recorded_outside_scope(self):
        x = nd.array([1.0])
        x.attach_grad()
        y = x * x  # outside record
        with pytest.raises(mx.MXNetError):
            y.backward()

    def test_head_grad(self):
        x = nd.array([1.0, 2.0])
        x.attach_grad()
        with autograd.record():
            y = 3 * x
        y.backward(out_grad=nd.array([10.0, 20.0]))
        assert_close(x.grad, np.array([30.0, 60.0]))

    def test_grad_req_add(self):
        x = nd.array([2.0])
        x.attach_grad(grad_req="add")
        for _ in range(3):
            with autograd.record():
                y = x * x
            y.backward()
        assert_close(x.grad, np.array([12.0]))  # 3 * 2x

    def test_grad_req_write_overwrites(self):
        x = nd.array([2.0])
        x.attach_grad()  # write
        for _ in range(2):
            with autograd.record():
                y = x * x
            y.backward()
        assert_close(x.grad, np.array([4.0]))

    def test_retain_graph(self):
        x = nd.array([3.0])
        x.attach_grad()
        with autograd.record():
            y = x * x
        y.backward(retain_graph=True)
        y.backward()
        assert_close(x.grad, np.array([6.0]))

    def test_double_backward_without_retain_raises(self):
        x = nd.array([3.0])
        x.attach_grad()
        with autograd.record():
            y = x * x
        y.backward()
        with pytest.raises(mx.MXNetError):
            y.backward()

    def test_fan_out_accumulation(self):
        x = nd.array([2.0])
        x.attach_grad()
        with autograd.record():
            y = x * x + x * 3
        y.backward()
        assert_close(x.grad, np.array([7.0]))  # 2x + 3

    def test_multi_output_op(self):
        x = nd.array(np.random.rand(2, 6).astype(np.float32))
        x.attach_grad()
        with autograd.record():
            parts = nd.split(x, num_outputs=2, axis=1)
            loss = (parts[0] * 2).sum() + (parts[1] * 3).sum()
        loss.backward()
        expect = np.concatenate([np.full((2, 3), 2.0), np.full((2, 3), 3.0)], axis=1)
        assert_close(x.grad, expect)

    def test_detach_blocks_grad(self):
        x = nd.array([2.0])
        x.attach_grad()
        with autograd.record():
            y = x * x
            z = y.detach() * x
        z.backward()
        assert_close(x.grad, np.array([4.0]))  # only d(z)/dx via second factor

    def test_stop_gradient_op(self):
        x = nd.array([2.0])
        x.attach_grad()
        with autograd.record():
            y = nd.BlockGrad(x * x) + x
        y.backward()
        assert_close(x.grad, np.array([1.0]))

    def test_grad_function(self):
        x = nd.array([1.0, 2.0])
        x.attach_grad()
        with autograd.record():
            y = (x * x).sum()
        (gx,) = autograd.grad(y, [x], retain_graph=False)
        assert_close(gx, np.array([2.0, 4.0]))

    def test_training_flags(self):
        assert not autograd.is_training()
        with autograd.record(train_mode=True):
            assert autograd.is_training()
            assert autograd.is_recording()
            with autograd.predict_mode():
                assert not autograd.is_training()
        assert not autograd.is_recording()


class TestNumericGradients:
    """Finite-difference checks: the reference's core op-test technique."""

    @pytest.mark.parametrize("opname,fn", [
        ("tanh", np.tanh),
        ("sigmoid", lambda v: 1 / (1 + np.exp(-v))),
        ("log", np.log),
    ])
    def test_unary_numeric(self, opname, fn):
        x = np.random.rand(3, 3).astype(np.float32) + 0.5
        a = nd.array(x)
        a.attach_grad()
        with autograd.record():
            y = getattr(nd, opname)(a).sum()
        y.backward()
        num = numeric_grad(lambda v: fn(v).sum(), x.astype(np.float64))
        assert_close(a.grad, num.astype(np.float32), rtol=2e-2, atol=1e-3)

    def test_softmax_numeric(self):
        x = np.random.rand(2, 4).astype(np.float32)
        a = nd.array(x)
        a.attach_grad()
        w = np.random.rand(2, 4).astype(np.float32)
        with autograd.record():
            y = (nd.softmax(a) * nd.array(w)).sum()
        y.backward()

        def ref(v):
            e = np.exp(v - v.max(-1, keepdims=True))
            return ((e / e.sum(-1, keepdims=True)) * w).sum()

        num = numeric_grad(ref, x.astype(np.float64))
        assert_close(a.grad, num.astype(np.float32), rtol=2e-2, atol=1e-3)

    def test_layer_norm_numeric(self):
        x = np.random.rand(2, 5).astype(np.float32)
        g = np.random.rand(5).astype(np.float32) + 0.5
        b = np.random.rand(5).astype(np.float32)
        a = nd.array(x)
        a.attach_grad()
        with autograd.record():
            y = nd.LayerNorm(a, nd.array(g), nd.array(b), eps=1e-5).sum()
        y.backward()

        def ref(v):
            m = v.mean(-1, keepdims=True)
            s = v.var(-1, keepdims=True)
            return (((v - m) / np.sqrt(s + 1e-5)) * g + b).sum()

        num = numeric_grad(ref, x.astype(np.float64))
        assert_close(a.grad, num.astype(np.float32), rtol=5e-2, atol=2e-3)


class TestCustomFunction:
    def test_function_forward_backward(self):
        class Scale3(autograd.Function):
            def forward(self, x):
                return x * 3

            def backward(self, dy):
                return dy * 3

        x = nd.array([1.0, 2.0])
        x.attach_grad()
        f = Scale3()
        with autograd.record():
            y = f(x)
        y.backward()
        assert_close(y, np.array([3.0, 6.0]))
        assert_close(x.grad, np.array([3.0, 3.0]))

    def test_function_saved_tensors(self):
        class Square(autograd.Function):
            def forward(self, x):
                self.save_for_backward(x)
                return x * x

            def backward(self, dy):
                (x,) = self.saved_tensors
                return dy * 2 * x

        x = nd.array([2.0, 3.0])
        x.attach_grad()
        with autograd.record():
            y = Square()(x)
        y.backward()
        assert_close(x.grad, np.array([4.0, 6.0]))


class TestMarkVariables:
    def test_mark_variables(self):
        x = nd.array([1.0, 2.0])
        g = nd.zeros((2,))
        autograd.mark_variables([x], [g])
        with autograd.record():
            y = (x * x).sum()
        y.backward()
        assert_close(g, np.array([2.0, 4.0]))
