"""tools/check_amp_purity.py as a tier-1 unit test: under AMP no fp32
master weight may feed a low-precision dot directly (jaxpr walk over the
real compiled step), and the in-graph overflow-skip path must stay free
of host syncs (AST walk over TrainStep._build's traced closures)."""

import os
import sys

import pytest

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", "tools"))
import check_amp_purity  # noqa: E402


@pytest.fixture(scope="module")
def amp_step():
    return check_amp_purity.build_tiny_amp_step()


def test_amp_step_has_no_mixed_dots(amp_step):
    violations = check_amp_purity.check_step_purity(amp_step)
    assert not violations, "\n".join(violations)


def test_overflow_skip_path_is_sync_free():
    violations = check_amp_purity.find_overflow_sync_violations()
    assert not violations, "\n".join(
        f"step.py:{ln}: {msg}" for ln, msg in violations)


def test_lint_detects_a_mixed_dot():
    """Negative control: the jaxpr walk must actually flag an f32 operand
    feeding a bf16 dot (guards the checker against rotting into a
    no-op)."""
    import jax
    import jax.numpy as jnp

    def bad(w32, x16):
        return (w32 @ x16.astype(jnp.float32)).sum() + \
            jnp.dot(w32.astype(jnp.bfloat16), x16).sum()

    # mixed dot written deliberately: f32 × bf16
    def worse(w32, x16):
        return jax.lax.dot_general(
            w32, x16, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32).sum()

    jaxpr = jax.make_jaxpr(worse)(
        jax.ShapeDtypeStruct((4, 8), jnp.float32),
        jax.ShapeDtypeStruct((8, 4), jnp.bfloat16))
    assert check_amp_purity.find_mixed_dots(jaxpr)


def test_lint_detects_a_sync_in_traced_closure(tmp_path):
    bad = tmp_path / "step_bad.py"
    bad.write_text(
        "class TrainStep:\n"
        "    def _build(self, donate):\n"
        "        n = float(self._optimizer.wd)  # host-side: legal\n"
        "        def step(vals):\n"
        "            return float(vals)  # traced closure: violation\n"
        "        return step\n"
    )
    violations = check_amp_purity.find_overflow_sync_violations(str(bad))
    assert len(violations) == 1
    assert "float" in violations[0][1]
