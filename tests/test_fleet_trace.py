"""Fleet-scope observability (PR 16): distributed request tracing, the
telemetry scrape/aggregation plane, and per-request SLO attribution.

Contracts under test:

- CLOCK ALIGNMENT: ``estimate_offset`` recovers a synthetic true offset
  under symmetric delay and follows NTP's minimum-RTT selection rule;
  ``tools/fleet_trace.py`` shifts worker streams onto the reference
  (router) timeline using the ``trace.clock_offset`` instants.
- AGGREGATION: ``merge_summaries`` is identity on one summary and
  additive over several; ``aggregate_snapshots`` sums counters, merges
  histograms and keeps gauges per-replica; replaying the recorded
  ``fleet_telemetry.jsonl`` re-derives identical aggregates (the
  replayable-by-construction guarantee).
- END-TO-END (real processes): one disaggregated request through REAL
  prefill + decode worker processes with ``MXTPU_TRACE=1`` renders as a
  single request_id's spans across >= 2 distinct pids on one aligned
  timeline, with the ``GenerationResult.phases`` breakdown summing to
  the router-observed end-to-end latency; a ``FleetTelemetry`` scrape
  reaches every worker's registry.
- CHAOS: SIGKILL the only worker mid-stream — the merged trace shows
  the failover and the retry under ONE request_id with monotonic
  aligned timestamps, and the retried request's phases carry
  ``retry_ms``. The killed worker's append-only stream survives.
"""

import json
import os
import sys
import time

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu.serving import RemoteReplica, Router, faults, tracing
from mxnet_tpu.serving.tracing import (FleetTelemetry, aggregate_snapshots,
                                       estimate_offset, replay_scrapes)
from mxnet_tpu.serving.worker import spawn_worker
from mxnet_tpu.telemetry.metrics import merge_summaries

WORKER_ENV = {"JAX_PLATFORMS": os.environ.get("MXTPU_TEST_PLATFORM",
                                              "cpu")}


def _prompts(rng, n, lmin=3, lmax=8):
    return [rng.randint(3, 61, (rng.randint(lmin, lmax + 1),))
            .astype(np.int32) for _ in range(n)]


def _fleet_trace_mod():
    sys.path.insert(0, os.path.join(
        os.path.dirname(__file__), "..", "tools"))
    import fleet_trace

    return fleet_trace


def _merge_root(root, request_id=None):
    ft = _fleet_trace_mod()
    found = ft.discover_streams(str(root))
    streams = [(label, ft.load_stream(path)) for label, path in found]
    events, report = ft.merge_streams(streams, request_id=request_id)
    return events, report


# ------------------------------------------------------- clock alignment
class TestOffsetEstimation:
    def test_no_samples_is_none(self):
        assert estimate_offset([]) is None

    def test_single_sample_midpoint(self):
        off, rtt = estimate_offset([(100.0, 200.0, 1000.0)])
        assert off == 150.0 - 1000.0
        assert rtt == 100.0

    def test_symmetric_delay_recovers_true_offset(self):
        """Peer clock lags the caller by exactly 5000 µs; with symmetric
        one-way delay d the midpoint estimator is EXACT regardless of
        d: peer_ts + offset == caller_ts."""
        true_off = 5000.0
        samples = []
        for t0, d in ((10_000.0, 50.0), (20_000.0, 400.0),
                      (30_000.0, 10.0)):
            peer = t0 + d - true_off  # peer stamps mid-flight
            samples.append((t0, t0 + 2 * d, peer))
        off, rtt = estimate_offset(samples)
        assert off == pytest.approx(true_off)
        assert rtt == 20.0  # the d=10 probe won

    def test_min_rtt_sample_wins(self):
        """NTP's selection rule: a tight probe with a small offset beats
        a fat probe claiming a huge one."""
        off, rtt = estimate_offset([
            (0.0, 1000.0, -7.0),    # rtt 1000, offset 507
            (0.0, 100.0, 30.0),     # rtt 100, offset 20  <- wins
            (0.0, 5000.0, 99.0),    # rtt 5000
        ])
        assert rtt == 100.0
        assert off == 50.0 - 30.0


# ----------------------------------------------------------- aggregation
def _summary(values):
    from mxnet_tpu.telemetry.metrics import Histogram

    h = Histogram()
    for v in values:
        h.observe(v)
    return h.summary()


class TestAggregation:
    def test_merge_single_summary_is_identity(self):
        s = _summary([1.0, 2.0, 3.0, 10.0])
        m = merge_summaries([s])
        for k in ("count", "sum", "min", "max", "p50", "p95", "p99"):
            assert m[k] == pytest.approx(s[k]), k

    def test_merge_is_additive(self):
        a = _summary([1.0, 2.0, 3.0])
        b = _summary([10.0, 20.0, 30.0, 40.0, 50.0, 60.0])
        m = merge_summaries([a, b])
        assert m["count"] == 9
        assert m["sum"] == pytest.approx(a["sum"] + b["sum"])
        assert m["min"] == 1.0 and m["max"] == 60.0
        # count-weighted percentile merge: between the two p50s,
        # nearer the bigger population's
        assert a["p50"] < m["p50"] < b["p50"]
        expect = (a["p50"] * 3 + b["p50"] * 6) / 9
        assert m["p50"] == pytest.approx(expect)

    def test_aggregate_snapshots_sums_counters_keeps_gauges(self):
        snaps = {
            "w0": {"counters": {"serve/completed": 3},
                   "gauges": {"infer/tokens_per_sec": 10.0},
                   "histograms": {"infer/ttft_ms": _summary([5.0])}},
            "w1": {"counters": {"serve/completed": 4,
                                "serve/retries": 1},
                   "gauges": {"infer/tokens_per_sec": 20.0},
                   "histograms": {"infer/ttft_ms": _summary([15.0])}},
        }
        agg = aggregate_snapshots(snaps)
        assert agg["replicas"] == ["w0", "w1"]
        assert agg["counters"] == {"serve/completed": 7,
                                   "serve/retries": 1}
        assert agg["histograms"]["infer/ttft_ms"]["count"] == 2
        # gauges do NOT aggregate — they stay per-replica
        assert "infer/tokens_per_sec" not in agg.get("counters")
        assert agg["per_replica"]["w0"]["gauges"][
            "infer/tokens_per_sec"] == 10.0

    def test_replay_reproduces_aggregates(self, tmp_path):
        snaps = {
            "w0": {"counters": {"serve/completed": 2},
                   "histograms": {"infer/ttft_ms": _summary([1.0, 9.0])}},
            "router": {"counters": {"fleet/scrapes": 1}},
        }
        path = tmp_path / "fleet_telemetry.jsonl"
        with open(path, "w") as f:
            f.write(json.dumps({"t": 1.5, "snapshots": snaps}) + "\n")
            f.write("{torn line\n")  # append-only stream may tear
        replayed = replay_scrapes(str(path))
        assert len(replayed) == 1
        assert replayed[0]["t"] == 1.5
        assert replayed[0]["aggregate"] == aggregate_snapshots(snaps)


# -------------------------------------------------- tracing primitives
class TestTracingPrimitives:
    def test_request_scope_is_reentrant_and_restores(self):
        assert tracing.current_request_id() is None
        with tracing.request_scope("aaa"):
            assert tracing.current_request_id() == "aaa"
            with tracing.request_scope("bbb"):
                assert tracing.current_request_id() == "bbb"
            assert tracing.current_request_id() == "aaa"
            with tracing.request_scope(None):  # no-op scope
                assert tracing.current_request_id() == "aaa"
        assert tracing.current_request_id() is None

    def test_context_propagates_in_scope_id(self):
        assert tracing.context() is None
        with tracing.request_scope("ctx1"):
            assert tracing.context() == {"request_id": "ctx1"}
        assert tracing.context("explicit") == {"request_id": "explicit"}

    def test_force_overrides_env(self, monkeypatch):
        monkeypatch.delenv("MXTPU_TRACE", raising=False)
        assert not tracing.trace_enabled()
        try:
            tracing.force(True)
            assert tracing.trace_enabled()
            tracing.force(False)
            monkeypatch.setenv("MXTPU_TRACE", "1")
            assert not tracing.trace_enabled()
            tracing.force(None)
            assert tracing.trace_enabled()
        finally:
            tracing.force(None)

    def test_fault_instant_carries_spec_and_request_id(self, tmp_path):
        """Satellite: an armed fault's instant names the point, the
        firing spec (hit/fire counters included) and the in-scope
        request id."""
        mx.telemetry.reset()
        mx.telemetry.enable(str(tmp_path))
        faults.inject("router.place", times=1)
        try:
            with tracing.request_scope("deadbeef00000001"):
                with pytest.raises(faults.FaultInjected):
                    faults.fire("router.place", tag="interactive")
            events = [json.loads(ln) for ln in
                      open(mx.telemetry.jsonl_path())]
            fired = [e for e in events if e["name"] == "serve.fault"]
            assert len(fired) == 1
            args = fired[0]["args"]
            assert args["point"] == "router.place"
            assert args["request_id"] == "deadbeef00000001"
            assert args["spec"]["point"] == "router.place"
            assert args["spec"]["fired"] == 1
        finally:
            faults.clear()
            mx.telemetry.reset()


# ------------------------------------------------------- merge tool unit
class TestFleetTraceTool:
    def _streams(self):
        router = [
            {"name": "trace.clock_offset", "ph": "i", "ts": 50.0,
             "pid": 1, "tid": 1,
             "args": {"replica": "w0", "peer_pid": 2,
                      "offset_us": 999.0, "rtt_us": 900.0}},
            {"name": "trace.clock_offset", "ph": "i", "ts": 60.0,
             "pid": 1, "tid": 1,
             "args": {"replica": "w0", "peer_pid": 2,
                      "offset_us": 1_000_000.0, "rtt_us": 80.0}},
            {"name": "trace.request", "ph": "X", "ts": 2_000_000.0,
             "dur": 500_000.0, "pid": 1, "tid": 1,
             "args": {"request_id": "r1"}},
        ]
        worker = [
            {"name": "trace.decode", "ph": "X", "ts": 1_100_000.0,
             "dur": 1000.0, "pid": 2, "tid": 9,
             "args": {"request_id": "r1"}},
            {"name": "trace.queue", "ph": "X", "ts": 1_050_000.0,
             "dur": 10.0, "pid": 2, "tid": 9,
             "args": {"request_id": "r2"}},
        ]
        return [("router_1", router), ("w0_2", worker)]

    def test_min_rtt_offset_shifts_worker_stream(self):
        ft = _fleet_trace_mod()
        events, report = ft.merge_streams(self._streams())
        assert report["reference"] == "router_1"
        assert report["offsets"]["2"]["offset_us"] == 1_000_000.0
        assert report["offsets"]["2"]["rtt_us"] == 80.0  # min-RTT won
        assert report["unaligned_pids"] == []
        dec = [e for e in events if e["name"] == "trace.decode"][0]
        assert dec["ts"] == 1_100_000.0 + 1_000_000.0
        req = [e for e in events if e["name"] == "trace.request"][0]
        assert req["ts"] == 2_000_000.0  # reference stream: unshifted
        # aligned: the worker's decode now sits INSIDE the router's
        # request envelope
        assert req["ts"] <= dec["ts"] <= req["ts"] + req["dur"]

    def test_process_name_metadata_per_pid(self):
        ft = _fleet_trace_mod()
        events, _ = ft.merge_streams(self._streams())
        meta = {e["pid"]: e["args"]["name"] for e in events
                if e.get("ph") == "M"}
        assert meta == {1: "router_1", 2: "w0_2"}

    def test_request_filter(self):
        ft = _fleet_trace_mod()
        events, _ = ft.merge_streams(self._streams(), request_id="r1")
        names = [e["name"] for e in events if e.get("ph") == "X"]
        assert sorted(names) == ["trace.decode", "trace.request"]

    def test_unaligned_pid_reported(self):
        ft = _fleet_trace_mod()
        streams = self._streams()
        streams.append(("w9_9", [
            {"name": "trace.decode", "ph": "X", "ts": 5.0, "dur": 1.0,
             "pid": 9, "tid": 1, "args": {}}]))
        _, report = ft.merge_streams(streams)
        assert report["unaligned_pids"] == [9]

    def test_load_stream_skips_torn_lines(self, tmp_path):
        ft = _fleet_trace_mod()
        p = tmp_path / "events.jsonl"
        p.write_text('{"name": "a", "ph": "i", "ts": 1, "pid": 1}\n'
                     '{"name": "b", "ph"')
        events = ft.load_stream(str(p))
        assert [e["name"] for e in events] == ["a"]


# -------------------------------------------------------------- reporting
class TestFleetReporting:
    def test_fleet_family_registered(self):
        sys.path.insert(0, os.path.join(
            os.path.dirname(__file__), "..", "tools"))
        import telemetry_report

        assert telemetry_report.KNOWN_METRIC_FAMILIES.get("fleet") \
            == "Fleet observability"
        assert "trace" in telemetry_report.KNOWN_SPAN_FAMILIES

    def test_report_tool_prints_fleet_section(self, tmp_path, capsys):
        sys.path.insert(0, os.path.join(
            os.path.dirname(__file__), "..", "tools"))
        import telemetry_report

        report = {
            "counters": {"fleet/scrapes": 2, "fleet/scrape_errors": 5,
                         "serve/slo_burn_interactive": 3},
            "gauges": {"fleet/replicas": 2},
        }
        p = tmp_path / "report.json"
        p.write_text(json.dumps(report))
        telemetry_report._print_fleet_family(str(p))
        out = capsys.readouterr().out
        assert "Fleet observability" in out
        assert "fleet/scrapes" in out
        assert "serve/slo_burn_interactive" in out
        assert "unreachable" in out       # errors >= scrapes warning
        assert "phase breakdowns" in out  # slo burn warning


# --------------------------------------------- end-to-end, real processes
@pytest.fixture(scope="module")
def traced_fleet(tmp_path_factory):
    """A REAL traced disaggregated fleet: 1 prefill + 1 decode worker
    process with MXTPU_TRACE/MXTPU_TRACE_DIR, the router process tracing
    into its own subdirectory, three requests served, one telemetry
    scrape taken — torn down before the tests read the artifacts."""
    root = tmp_path_factory.mktemp("fleet_trace_e2e")
    mx.telemetry.reset()
    tracing.force(True)
    mx.telemetry.enable(str(root / "router_0"))
    env = dict(WORKER_ENV, MXTPU_TRACE="1", MXTPU_TRACE_DIR=str(root))
    wkw = dict(model=dict(seed=0), max_len=24, bucket_keys=(8,),
               slots=2, max_new=4, extra_env=env, heartbeat_s=0.1)
    handles = [
        spawn_worker(str(root / "pre"), name="pre0", role="prefill",
                     **wkw),
        spawn_worker(str(root / "dec"), name="dec0", role="decode",
                     **wkw),
    ]
    for h in handles:
        h.wait_ready(timeout=240)
    reps = [RemoteReplica(h.name, address=h.address,
                          heartbeat_path=h.heartbeat_path,
                          heartbeat_stale_s=10.0, role=r)
            for h, r in zip(handles, ["prefill", "decode"])]
    router = Router(reps, health_interval_s=0.05,
                    no_replica_timeout_s=120.0,
                    disagg_min_prompt=1)  # short prompts: hand off
    rng = np.random.RandomState(31)
    prompts = _prompts(rng, 3)
    scrape = None
    try:
        time.sleep(0.3)  # >= 1 clock sample per worker (health cadence)
        futs = [router.submit(p) for p in prompts]
        outs = [f.result(timeout=240) for f in futs]
        ft = FleetTelemetry(router._replica_snapshot, interval_s=0,
                            directory=str(root), rpc_timeout_s=10.0)
        snaps = ft.scrape_once()
        scrape = {"snaps": snaps, "aggregate": ft.aggregate(),
                  "path": ft.path}
        time.sleep(0.3)  # a final heartbeat carrying request counters
    finally:
        router.stop()
        for h in handles:
            if h.alive():
                h.terminate()
        for h in handles:
            try:
                h.wait(timeout=60)
            except Exception:  # noqa: BLE001
                h.kill()
        tracing.force(None)
        mx.telemetry.reset()
    yield {"root": root, "futs": futs, "outs": outs,
           "handles": handles, "scrape": scrape}


class TestFleetTraceE2E:
    def test_one_request_spans_multiple_processes_aligned(
            self, traced_fleet):
        """THE tentpole acceptance: one disaggregated request's spans,
        from >= 2 REAL processes, merge onto one aligned timeline under
        a single request_id — with every remote span inside the
        router's request envelope (alignment tolerance << the seconds
        of raw clock skew between process start times)."""
        root = traced_fleet["root"]
        fut = traced_fleet["futs"][0]
        assert fut.request_id is not None
        events, report = _merge_root(root, request_id=fut.request_id)
        assert report["reference"].startswith("router")
        assert report["unaligned_pids"] == []
        spans = [e for e in events if e.get("ph") == "X"]
        pids = {e["pid"] for e in spans}
        assert len(pids) >= 2, f"spans only from pids {pids}"
        names = {e["name"] for e in spans}
        assert "trace.request" in names
        assert "trace.queue" in names and "trace.decode" in names
        req = [e for e in spans if e["name"] == "trace.request"][0]
        slack = 50_000.0  # µs; loopback RTT error is well under this
        for e in spans:
            assert req["ts"] - slack <= e["ts"] \
                <= req["ts"] + req["dur"] + slack, \
                (e["name"], e["pid"], e["ts"], req["ts"], req["dur"])

    def test_prefill_and_kv_push_spans_from_prefill_worker(
            self, traced_fleet):
        root = traced_fleet["root"]
        events, _ = _merge_root(root)
        by_name = {}
        for e in events:
            if e.get("ph") == "X":
                by_name.setdefault(e["name"], []).append(e)
        assert "trace.prefill" in by_name
        assert "trace.kv_push" in by_name
        # the prefill worker's spans carry the router-minted ids
        rids = {f.request_id for f in traced_fleet["futs"]}
        assert any(e["args"].get("request_id") in rids
                   for e in by_name["trace.prefill"])

    def test_phase_breakdown_sums_to_observed_e2e(self, traced_fleet):
        """SLO attribution: GenerationResult.phases *_ms entries sum to
        the router-observed end-to-end latency EXACTLY (other_ms is the
        unclamped residual), cross-checked against the e2e_ms the
        trace.request span recorded."""
        root = traced_fleet["root"]
        for fut in traced_fleet["futs"]:
            phases = fut.phases
            assert phases is not None
            for key in ("queue_ms", "prefill_ms", "decode_ms",
                        "handoff_ms", "other_ms"):
                assert key in phases, (key, phases)
            total = sum(v for k, v in phases.items()
                        if k.endswith("_ms") and isinstance(v, float))
            events, _ = _merge_root(root, request_id=fut.request_id)
            req = [e for e in events if e["name"] == "trace.request"]
            assert len(req) == 1
            assert total == pytest.approx(req[0]["args"]["e2e_ms"],
                                          rel=1e-6)

    def test_scrape_reaches_every_worker_and_replays(self, traced_fleet):
        scrape = traced_fleet["scrape"]
        snaps = scrape["snaps"]
        assert set(snaps) >= {"pre0", "dec0", "router"}
        # the decode worker really served: its own registry says so
        dec = snaps["dec0"]["counters"]
        assert dec.get("infer/requests", 0) >= 3
        agg = scrape["aggregate"]
        assert agg["counters"], "fleet aggregate is empty"
        # replay identity: the recorded JSONL re-derives the aggregate
        replayed = replay_scrapes(scrape["path"])
        assert replayed
        assert replayed[-1]["aggregate"] == aggregate_snapshots(snaps)

    def test_worker_heartbeat_carries_request_fields(self, traced_fleet):
        """Satellite: the worker watchdog heartbeat now reports
        inflight / last_request_id / requests_completed."""
        dec = traced_fleet["handles"][1]
        hb = json.loads(open(dec.heartbeat_path).read())
        assert hb.get("requests_completed", 0) >= 3
        assert hb.get("last_request_id")
        assert "inflight" in hb

    def test_tokens_unaffected_by_tracing(self, traced_fleet):
        outs = traced_fleet["outs"]
        assert all(isinstance(o, list) and o for o in outs)


# ------------------------------------------------------------------- chaos
@pytest.mark.chaos
class TestTraceChaos:
    def test_sigkill_failover_and_retry_under_one_request_id(
            self, tmp_path):
        """Cross-process chaos: SIGKILL the only worker mid-stream. The
        factory respawns a real process, every request completes, and
        the MERGED trace shows the failover + the retry instants under
        ONE request_id with monotonic aligned timestamps — including
        spans recovered from the killed worker's surviving append-only
        stream."""
        mx.telemetry.reset()
        tracing.force(True)
        mx.telemetry.enable(str(tmp_path / "router_0"))
        env = dict(WORKER_ENV, MXTPU_TRACE="1",
                   MXTPU_TRACE_DIR=str(tmp_path))
        wkw = dict(model=dict(seed=0), max_len=24, bucket_keys=(8,),
                   slots=2, max_new=4, extra_env=env, heartbeat_s=0.1)
        handles = [spawn_worker(str(tmp_path / "w0"), name="w0", **wkw)]
        handles[0].wait_ready(timeout=240)
        spawned = [1]

        def factory():
            i = spawned[0]
            spawned[0] += 1
            h = spawn_worker(str(tmp_path / f"w{i}"), name=f"w{i}",
                             **wkw)
            handles.append(h)
            return RemoteReplica.spawning(h, heartbeat_stale_s=2.0)

        reps = [RemoteReplica("w0", address=handles[0].address,
                              heartbeat_path=handles[0].heartbeat_path,
                              heartbeat_stale_s=2.0)]
        router = Router(reps, retry_backoff_s=0.02,
                        health_interval_s=0.05, replica_factory=factory,
                        respawn_backoff_s=0.05,
                        no_replica_timeout_s=240.0)
        rng = np.random.RandomState(43)
        prompts = _prompts(rng, 10)
        try:
            time.sleep(0.3)  # >= 1 clock sample for w0 BEFORE the kill
            futs = [router.submit(p) for p in prompts]
            handles[0].kill()  # SIGKILL mid-stream: requests inflight
            outs = [f.result(timeout=240) for f in futs]
            assert all(isinstance(o, list) for o in outs)
            reg = mx.telemetry.registry()
            assert reg.counter("serve/failovers").value >= 1
            assert reg.counter("serve/retries").value >= 1
            time.sleep(1.2)  # a clock sample for the respawned worker
        finally:
            router.stop()
            for h in handles:
                if h.alive():
                    h.terminate()
            for h in handles:
                try:
                    h.wait(timeout=60)
                except Exception:  # noqa: BLE001
                    h.kill()
            tracing.force(None)
            mx.telemetry.reset()

        events, report = _merge_root(tmp_path)
        # the killed worker's stream survived the SIGKILL
        assert any(lbl.startswith("w0_") for lbl in report["streams"])
        assert report["unaligned_pids"] == []
        retries = [e for e in events if e["name"] == "trace.retry"]
        assert retries, "no trace.retry instant was recorded"
        rid = retries[0]["args"]["request_id"]
        assert rid is not None
        fut = next(f for f in futs if f.request_id == rid)
        assert fut.phases and "retry_ms" in fut.phases
        # the failover instant blames the dead replica and lists the
        # requests it took down
        failovers = [e for e in events if e["name"] == "serve.failover"]
        assert failovers and failovers[0]["args"]["replica"] == "w0"
        # the requests list is only non-empty when eviction catches the
        # inflight requests BEFORE the dead-socket retry path reassigns
        # them — either ordering is valid, so only check the shape
        assert "requests" in failovers[0]["args"]
        assert "n_requests" in failovers[0]["args"]
        # monotonic aligned timeline for THE retried request: its spans
        # and instants, from both worker processes, sit inside the
        # router's request envelope
        rid_events = [e for e in events
                      if (e.get("args") or {}).get("request_id") == rid
                      and e.get("ph") in ("X", "i")]
        req = [e for e in rid_events if e["name"] == "trace.request"]
        assert len(req) == 1
        req = req[0]
        slack = 50_000.0  # µs
        for e in rid_events:
            assert req["ts"] - slack <= e["ts"] \
                <= req["ts"] + req["dur"] + slack, \
                (e["name"], e.get("pid"), e["ts"])
        retry_ts = [e["ts"] for e in rid_events
                    if e["name"] == "trace.retry"]
        decode_spans = [e for e in rid_events
                        if e["name"] == "trace.decode"]
        assert decode_spans, "retried request never decoded"
        final_decode = max(decode_spans, key=lambda e: e["ts"])
        # the retry happened before the (respawned) decode finished
        assert min(retry_ts) <= final_decode["ts"] + final_decode["dur"]
