"""Channel-last (NHWC) layout support: op-level and model-level parity.

The TPU-preferred layout (channels ride the lane dimension). Weights keep
the (O, I/g, *k) reference layout in both, so checkpoints are
layout-portable; a model built NHWC must match its NCHW twin exactly when
fed the transposed input."""

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import nd
from mxnet_tpu.gluon.model_zoo.vision import get_model


def test_convolution_nhwc_matches_nchw():
    rng = np.random.RandomState(0)
    x = rng.randn(2, 3, 8, 8).astype(np.float32)
    w = rng.randn(5, 3, 3, 3).astype(np.float32)
    b = rng.randn(5).astype(np.float32)
    out_cf = nd.Convolution(nd.array(x), nd.array(w), nd.array(b),
                            kernel=(3, 3), stride=(2, 2), pad=(1, 1),
                            num_filter=5, no_bias=False)
    out_cl = nd.Convolution(nd.array(x.transpose(0, 2, 3, 1)), nd.array(w),
                            nd.array(b), kernel=(3, 3), stride=(2, 2),
                            pad=(1, 1), num_filter=5, no_bias=False,
                            layout="NHWC")
    np.testing.assert_allclose(
        out_cl.asnumpy().transpose(0, 3, 1, 2), out_cf.asnumpy(),
        rtol=1e-4, atol=1e-4,
    )


@pytest.mark.parametrize("pool_type,ceil", [("max", False), ("avg", True)])
def test_pooling_nhwc_matches_nchw(pool_type, ceil):
    rng = np.random.RandomState(1)
    x = rng.randn(2, 4, 9, 9).astype(np.float32)
    kw = dict(kernel=(3, 3), stride=(2, 2), pad=(1, 1),
              pool_type=pool_type,
              pooling_convention="full" if ceil else "valid")
    out_cf = nd.Pooling(nd.array(x), **kw)
    out_cl = nd.Pooling(nd.array(x.transpose(0, 2, 3, 1)), layout="NHWC",
                        **kw)
    np.testing.assert_allclose(
        out_cl.asnumpy().transpose(0, 3, 1, 2), out_cf.asnumpy(),
        rtol=1e-5, atol=1e-5,
    )


def test_global_pool_nhwc():
    rng = np.random.RandomState(2)
    x = rng.randn(2, 4, 5, 5).astype(np.float32)
    out = nd.Pooling(nd.array(x.transpose(0, 2, 3, 1)), global_pool=True,
                     pool_type="avg", layout="NHWC")
    np.testing.assert_allclose(
        out.asnumpy()[:, 0, 0, :], x.mean(axis=(2, 3)), rtol=1e-5, atol=1e-5
    )


def test_resnet18_nhwc_matches_nchw():
    rng = np.random.RandomState(3)
    x_nchw = rng.randn(2, 3, 32, 32).astype(np.float32)

    n1 = get_model("resnet18_v1")
    n1.initialize(mx.initializer.Xavier())
    o1 = n1(mx.nd.array(x_nchw))

    n2 = get_model("resnet18_v1", layout="NHWC")
    n2.initialize(mx.initializer.Xavier())
    n2(mx.nd.array(np.zeros((1, 32, 32, 3), np.float32)))
    items1 = list(n1.collect_params().items())
    items2 = list(n2.collect_params().items())
    assert len(items1) == len(items2)
    for (k1, v1), (k2, v2) in zip(items1, items2):
        assert v1.shape == v2.shape, (k1, v1.shape, k2, v2.shape)
        v2._data._rebind(v1.data().data)
    o2 = n2(mx.nd.array(x_nchw.transpose(0, 2, 3, 1)))
    np.testing.assert_allclose(o1.asnumpy(), o2.asnumpy(), rtol=2e-3,
                               atol=2e-3)


def test_deconv_channel_last_raises():
    with pytest.raises(NotImplementedError):
        nd.Deconvolution(nd.zeros((1, 4, 4, 2)), nd.zeros((2, 3, 2, 2)),
                         kernel=(2, 2), num_filter=3, layout="NHWC")


def test_batchnorm_bf16_large_mean_variance():
    # regression: one-pass E[x^2]-E[x]^2 stats cancel catastrophically for
    # |mean| >> std (47x variance error observed); the centered two-pass
    # form must stay accurate on bf16 activations
    import jax.numpy as jnp
    from mxnet_tpu.ops.nn import batch_norm

    rng = np.random.RandomState(0)
    x = (rng.randn(64, 8, 14, 14) * 0.1 + 20).astype(np.float32)
    xb = jnp.asarray(x, jnp.bfloat16)
    ones = jnp.ones((8,))
    zeros = jnp.zeros((8,))
    out, mean, var = batch_norm(xb, ones, zeros, zeros, ones,
                                training=True, fix_gamma=False)
    true_var = np.asarray(xb, np.float32).var(axis=(0, 2, 3))
    rel = np.abs(np.asarray(var) - true_var) / true_var
    assert rel.max() < 0.05, rel.max()
    # normalized output should be ~unit std; tolerance is wide because at
    # mean/std=200 the bf16 INPUT quantization step (~0.078 at magnitude
    # 20) is itself ~0.8 sigma of the signal — that noise is in the data,
    # not the BN math (the broken one-pass form gave std ~0.15 here)
    std = np.asarray(out, np.float32).std(axis=(0, 2, 3))
    assert np.allclose(std, 1.0, atol=0.4), std
