"""Jitted inference engine: KV-cached incremental decode.

Contracts under test (ISSUE 5 tentpole):

- the causal/valid-length mask accepts ``query_len=1`` with a nonzero
  cache offset (``q_offset``) instead of assuming square (L, L) scores;
- incremental ``decode_step`` over a cached prefix matches the
  full-sequence forward logits at float32 resolution (a few ULPs — XLA
  fuses the (B, 1, ·) decode matmuls differently from the (B, T, ·)
  full-forward ones, so strict bitwise equality across the two program
  shapes is not physical; greedy trajectories ARE identical, asserted
  end-to-end) and within tolerance under ``amp='bfloat16'`` — for both
  TransformerModel and the BERT-as-encoder prefill configuration;
- ``InferStep.warmup`` over the prompt-bucket menu leaves ZERO
  steady-state recompiles across the real prefill+decode programs.
"""

import warnings

import numpy as np
import pytest

import jax
import jax.numpy as jnp

import mxnet_tpu as mx
from mxnet_tpu import nd
from mxnet_tpu.base import MXNetError
from mxnet_tpu.gluon.model_zoo.bert import BERTEncoderForGeneration, \
    BERTModel
from mxnet_tpu.gluon.model_zoo.transformer import TransformerModel
from mxnet_tpu.gluon.nn import MultiHeadAttention
from mxnet_tpu.parallel import InferStep
from mxnet_tpu.serving import DynamicBatcher

# float32-resolution tolerance for incremental-vs-full logits parity
ATOL = 5e-6
RTOL = 1e-5


def _naive_attention(q, k, v, valid_length=None, causal=False,
                     q_offset=0, sm_scale=None):
    """Dense O(S^2) reference in f32 with absolute query positions."""
    if sm_scale is None:
        sm_scale = 1.0 / np.sqrt(q.shape[-1])
    s = jnp.einsum("bhqd,bhkd->bhqk", q.astype(jnp.float32),
                   k.astype(jnp.float32)) * sm_scale
    Sq, Sk = q.shape[2], k.shape[2]
    mask = jnp.ones((q.shape[0], 1, Sq, Sk), bool)
    if valid_length is not None:
        mask = mask & (jnp.arange(Sk)[None, None, None, :]
                       < valid_length[:, None, None, None])
    if causal:
        qpos = jnp.arange(Sq)[None, None, :, None] + \
            jnp.asarray(q_offset, jnp.int32).reshape((-1, 1, 1, 1))
        mask = mask & (jnp.arange(Sk)[None, None, None, :] <= qpos)
    s = jnp.where(mask, s, -1e30)
    p = jax.nn.softmax(s, axis=-1)
    p = jnp.where(mask, p, 0.0)
    return jnp.einsum("bhqk,bhkd->bhqd", p, v.astype(jnp.float32))


# --------------------------------------------------------------- mask fix
class TestQOffsetMask:
    """Satellite: single-token causal queries with a cache offset."""

    def test_scalar_offset_single_query(self):
        rng = np.random.RandomState(0)
        B, H, Sk, D = 2, 3, 24, 8
        q = jnp.asarray(rng.randn(B, H, 1, D).astype(np.float32))
        k = jnp.asarray(rng.randn(B, H, Sk, D).astype(np.float32))
        v = jnp.asarray(rng.randn(B, H, Sk, D).astype(np.float32))
        for off in (0, 5, 11, 23):
            out = mx.nd.flash_attention(q, k, v, causal=True, q_offset=off)
            ref = _naive_attention(q, k, v, causal=True, q_offset=off)
            np.testing.assert_allclose(out.asnumpy(), np.asarray(ref),
                                       rtol=2e-4, atol=2e-4,
                                       err_msg=f"offset {off}")

    def test_per_row_offset(self):
        rng = np.random.RandomState(1)
        B, H, Sk, D = 3, 2, 16, 4
        q = jnp.asarray(rng.randn(B, H, 1, D).astype(np.float32))
        k = jnp.asarray(rng.randn(B, H, Sk, D).astype(np.float32))
        v = jnp.asarray(rng.randn(B, H, Sk, D).astype(np.float32))
        off = jnp.asarray([2, 9, 15], jnp.int32)
        out = mx.nd.flash_attention(q, k, v, causal=True, q_offset=off)
        ref = _naive_attention(q, k, v, causal=True, q_offset=off)
        np.testing.assert_allclose(out.asnumpy(), np.asarray(ref),
                                   rtol=2e-4, atol=2e-4)

    def test_offset_with_valid_length(self):
        rng = np.random.RandomState(2)
        B, H, Sk, D = 2, 2, 16, 4
        q = jnp.asarray(rng.randn(B, H, 1, D).astype(np.float32))
        k = jnp.asarray(rng.randn(B, H, Sk, D).astype(np.float32))
        v = jnp.asarray(rng.randn(B, H, Sk, D).astype(np.float32))
        vl = jnp.asarray([6, 12], jnp.int32)
        out = mx.nd.flash_attention(q, k, v, vl, causal=True, q_offset=10)
        ref = _naive_attention(q, k, v, vl, causal=True, q_offset=10)
        np.testing.assert_allclose(out.asnumpy(), np.asarray(ref),
                                   rtol=2e-4, atol=2e-4)

    def test_offset_equals_square_mask_when_zero(self):
        """q_offset=0 with Sq=Sk must reproduce the historical square
        causal mask bit-for-bit (same dense path, same where-mask)."""
        rng = np.random.RandomState(3)
        q = jnp.asarray(rng.randn(1, 2, 12, 4).astype(np.float32))
        a = mx.nd.flash_attention(q, q, q, causal=True)
        b = mx.nd.flash_attention(q, q, q, causal=True, q_offset=0)
        np.testing.assert_array_equal(a.asnumpy(), b.asnumpy())

    def test_mha_rejects_offset_under_ring(self):
        mha = MultiHeadAttention(8, 2, ring_axis="seq")
        mha.initialize()
        x = nd.array(np.zeros((1, 4, 8), np.float32))
        with pytest.raises(MXNetError):
            mha(x, q_offset=2)


# ------------------------------------------------------- MHA incremental
class TestMHAIncremental:
    def _mha(self, causal=True):
        mha = MultiHeadAttention(16, 2, dropout=0.0, causal=causal)
        mha.initialize()
        return mha

    def test_prefill_output_is_bitwise_forward(self):
        mha = self._mha()
        x = nd.array(np.random.RandomState(0).randn(2, 9, 16)
                     .astype(np.float32))
        out_full = mha(x)
        out_pre, k, v = mha.prefill(x)
        np.testing.assert_array_equal(out_pre.asnumpy(), out_full.asnumpy())
        assert k.shape == (2, 9, 2, 8) and v.shape == (2, 9, 2, 8)

    def test_step_matches_full_forward(self):
        rng = np.random.RandomState(1)
        B, S = 2, 9
        mha = self._mha()
        x = nd.array(rng.randn(B, S, 16).astype(np.float32))
        full = mha(x).asnumpy()
        _, k, v = mha.prefill(x[:, :4])
        kc, vc = mha.init_cache(B, S)
        kc = jax.lax.dynamic_update_slice(kc, jnp.swapaxes(k, 0, 1),
                                          (0, 0, 0, 0))
        vc = jax.lax.dynamic_update_slice(vc, jnp.swapaxes(v, 0, 1),
                                          (0, 0, 0, 0))
        for p in range(4, S):
            out, kc, vc = mha.step(x[:, p:p + 1], kc, vc, jnp.int32(p))
            np.testing.assert_allclose(out.asnumpy()[:, 0], full[:, p],
                                       rtol=RTOL, atol=ATOL)

    def test_step_rejects_cross_attention(self):
        cross = MultiHeadAttention(16, 2, self_attention=False)
        cross.initialize()
        x = nd.array(np.zeros((1, 1, 16), np.float32))
        kc, vc = jnp.zeros((4, 1, 2, 8)), jnp.zeros((4, 1, 2, 8))
        with pytest.raises(MXNetError):
            cross.step(x, kc, vc, jnp.int32(0))
        with pytest.raises(MXNetError):
            self._mha().project_kv(x)


# ------------------------------------------------- model decode bit-parity
def _make_transformer(V=61, units=16, layers=2, dropout=0.0, **kw):
    net = TransformerModel(src_vocab=V, tgt_vocab=V, units=units,
                           hidden_size=2 * units, num_layers=layers,
                           num_heads=2, max_length=64, dropout=dropout,
                           **kw)
    net.initialize(mx.initializer.Xavier())
    net._probe_shapes(nd.zeros((2, 8), dtype="int32"),
                      nd.zeros((2, 8), dtype="int32"))
    return net


@pytest.fixture(scope="module")
def tmodel():
    np.random.seed(0)
    return _make_transformer()


@pytest.fixture(scope="module")
def bert_encdec():
    """TransformerModel with a BERT memory encoder (BERT-as-encoder)."""
    np.random.seed(1)
    bert = BERTModel(vocab_size=61, units=16, hidden_size=32, num_layers=2,
                     num_heads=2, max_length=64, dropout=0.0)
    net = TransformerModel(src_vocab=61, tgt_vocab=61, units=16,
                           hidden_size=32, num_layers=2, num_heads=2,
                           max_length=64, dropout=0.0,
                           encoder=BERTEncoderForGeneration(bert))
    net.initialize(mx.initializer.Xavier())
    net._probe_shapes(nd.zeros((2, 8), dtype="int32"),
                      nd.zeros((2, 8), dtype="int32"))
    return net


def _teacher_forced_parity(net, prefix_len=3, Ls=7, Lt=9, atol=ATOL):
    """Prefill a prefix, then teacher-force decode_step across the rest;
    compare every position's logits against ONE full re-forward."""
    rng = np.random.RandomState(7)
    B, V = 2, 61
    src = nd.array(rng.randint(3, V, (B, Ls)), dtype="int32")
    tgt = nd.array(rng.randint(3, V, (B, Lt)), dtype="int32")
    vl = nd.array(np.array([5, Ls]), dtype="int32")
    full = net(src, tgt, vl).asnumpy()
    logits, state = net.prefill(src, tgt[:, :prefix_len],
                                src_valid_length=vl, max_len=24)
    # prefill runs the IDENTICAL program shape per position => bitwise
    np.testing.assert_array_equal(logits.asnumpy(), full[:, prefix_len - 1])
    for p in range(prefix_len, Lt):
        tok = nd.array(tgt.asnumpy()[:, p], dtype="int32")
        logits, state = net.decode_step(tok, jnp.int32(p), state)
        got = logits.asnumpy()
        np.testing.assert_allclose(got, full[:, p], rtol=RTOL, atol=atol,
                                   err_msg=f"position {p}")
        assert (got.argmax(-1) == full[:, p].argmax(-1)).all(), \
            f"greedy token flipped at position {p}"


class TestDecodeParity:
    def test_transformer_fp32(self, tmodel):
        _teacher_forced_parity(tmodel)

    def test_bert_as_encoder_fp32(self, bert_encdec):
        _teacher_forced_parity(bert_encdec)

    def test_transformer_bf16_tolerance(self, tmodel):
        """amp='bfloat16' engine logits stay within bf16 tolerance of the
        fp32 full forward on a teacher-forced trajectory."""
        rng = np.random.RandomState(8)
        B, V, Ls, Lt = 2, 61, 7, 8
        src = rng.randint(3, V, (B, Ls)).astype(np.int32)
        vl = np.array([5, 7], np.int32)
        full32 = tmodel(nd.array(src), nd.array(
            rng.randint(3, V, (B, Lt)).astype(np.int32)),
            nd.array(vl, dtype="int32"))
        eng16 = InferStep(tmodel, amp="bfloat16", max_len=24)
        eng32 = InferStep(tmodel, max_len=24)
        t16, _ = eng16.decode_n(src, vl, max_new_tokens=6)
        t32, _ = eng32.decode_n(src, vl, max_new_tokens=6)
        assert t16.shape == t32.shape == (B, 6)
        # param cast audit: float params bf16 except pinned norm families
        from mxnet_tpu import amp as amp_mod

        pinned = amp_mod.fp32_param_names(tmodel)
        for name, v in eng16._values.items():
            if not jnp.issubdtype(v.dtype, jnp.floating):
                continue
            want = jnp.float32 if name in pinned else jnp.bfloat16
            assert v.dtype == want, (name, v.dtype)
        assert full32 is not None  # full fp32 forward stays runnable

    def test_bf16_logits_close_to_fp32(self, tmodel):
        rng = np.random.RandomState(9)
        B, V, Ls = 2, 61, 7
        src = nd.array(rng.randint(3, V, (B, Ls)), dtype="int32")
        tgt = nd.array(rng.randint(3, V, (B, 5)), dtype="int32")
        vl = nd.array(np.array([5, 7]), dtype="int32")
        full = tmodel(src, tgt, vl).asnumpy()
        # bf16-cast prefill of the same prefix: bf16-resolution tolerance
        from mxnet_tpu import amp as amp_mod

        pinned = amp_mod.fp32_param_names(tmodel)
        orig = {}
        for name, p in tmodel.collect_params().items():
            if name not in pinned and \
                    jnp.issubdtype(p._data.data.dtype, jnp.floating):
                orig[name] = p._data.data
                p._data._rebind(p._data.data.astype(jnp.bfloat16))
        try:
            logits, _ = tmodel.prefill(src, tgt, src_valid_length=vl,
                                       max_len=16)
            np.testing.assert_allclose(
                logits.asnumpy().astype(np.float32), full[:, -1],
                rtol=5e-2, atol=5e-2)
        finally:
            for name, p in tmodel.collect_params().items():
                if name in orig:
                    p._data._rebind(orig[name])


# ------------------------------------------------------------ InferStep
class TestInferStep:
    def test_greedy_decode_matches_naive_reforward(self, tmodel):
        """End-to-end: decode_n's greedy trajectory == the naive
        re-forward loop's (token-identical, per row up to its length)."""
        rng = np.random.RandomState(3)
        B, V, Ls, T = 2, 61, 7, 8
        src_np = rng.randint(3, V, (B, Ls)).astype(np.int32)
        vl_np = np.array([4, 7], np.int32)
        tgt = np.full((B, 1), 1, np.int32)
        for _ in range(T):
            logits = tmodel(nd.array(src_np), nd.array(tgt),
                            nd.array(vl_np, dtype="int32"))
            nxt = logits.asnumpy()[:, -1].argmax(-1).astype(np.int32)
            tgt = np.concatenate([tgt, nxt[:, None]], axis=1)
        naive = tgt[:, 1:]
        eng = InferStep(tmodel, max_len=24)
        toks, lengths = eng.decode_n(src_np, vl_np, max_new_tokens=T)
        toks, lengths = toks.asnumpy(), lengths.asnumpy()
        for i in range(B):
            n = int(lengths[i])
            np.testing.assert_array_equal(toks[i, :n], naive[i, :n])

    def test_eos_early_exit_and_lengths(self, tmodel):
        """Re-decoding with eos_id = the first greedily emitted token
        must stop every row at length 1 and pad the rest of the buffer."""
        rng = np.random.RandomState(4)
        src = rng.randint(3, 61, (2, 7)).astype(np.int32)
        probe = InferStep(tmodel, max_len=24)
        first = int(probe.decode_n(src, None, max_new_tokens=1)[0]
                    .asnumpy()[0, 0])
        eng = InferStep(tmodel, max_len=24, eos_id=first, pad_id=0)
        toks, lengths = eng.decode_n(src, None, max_new_tokens=6)
        toks, lengths = toks.asnumpy(), lengths.asnumpy()
        assert lengths[0] == 1
        assert toks[0, 0] == first
        assert (toks[0, 1:] == 0).all()

    def test_warmup_menu_zero_steady_recompiles(self, tmodel):
        eng = InferStep(tmodel, max_len=32)
        menu = [(2, 7), (2, 12)]
        compiled = eng.warmup(menu, max_new_tokens=5)
        assert compiled >= 2
        assert eng.compile_guard.steady
        for bs, bucket in menu:
            src = np.zeros((bs, bucket), np.int32)
            eng.decode_n(src, None, max_new_tokens=5)
        assert eng.compile_guard.steady_state_recompiles == 0

    def test_post_warmup_shape_churn_is_flagged(self, tmodel):
        eng = InferStep(tmodel, max_len=32)
        eng.warmup([(2, 7)], max_new_tokens=4)
        with warnings.catch_warnings(record=True) as w:
            warnings.simplefilter("always")
            eng.decode_n(np.zeros((2, 9), np.int32), None,
                         max_new_tokens=4)
        assert eng.compile_guard.steady_state_recompiles == 1
        assert any("recompile" in str(x.message) for x in w)

    def test_sampling_deterministic_and_in_topk(self, tmodel):
        src = np.random.RandomState(5).randint(3, 61, (2, 7)) \
            .astype(np.int32)
        eng = InferStep(tmodel, max_len=24)
        a, _ = eng.decode_n(src, None, max_new_tokens=5, method="top_k",
                            top_k=4, temperature=0.7, seed=11)
        b, _ = eng.decode_n(src, None, max_new_tokens=5, method="top_k",
                            top_k=4, temperature=0.7, seed=11)
        np.testing.assert_array_equal(a.asnumpy(), b.asnumpy())
        c, _ = eng.decode_n(src, None, max_new_tokens=5, method="sample",
                            temperature=1.3, seed=1)
        assert c.shape == (2, 5)

    def test_cache_capacity_guard(self, tmodel):
        eng = InferStep(tmodel, max_len=8)
        with pytest.raises(MXNetError):
            eng.decode_n(np.zeros((1, 4), np.int32), None,
                         max_new_tokens=20)

    def test_decode_requires_protocol(self):
        bert = BERTModel(vocab_size=31, units=16, hidden_size=32,
                         num_layers=1, num_heads=2, max_length=32,
                         dropout=0.0)
        bert.initialize()
        bert._probe_shapes(nd.zeros((2, 8), dtype="int32"))
        eng = InferStep(bert)
        with pytest.raises(MXNetError):
            eng.decode_n(np.zeros((1, 4), np.int32), None)

    def test_forward_engine_bert_prefill(self):
        """Generic jitted forward: BERT bucket-padded prefill through the
        engine matches the eager net on the valid region, and the warmed
        menu holds zero steady recompiles."""
        np.random.seed(6)
        bert = BERTModel(vocab_size=31, units=16, hidden_size=32,
                         num_layers=2, num_heads=2, max_length=32,
                         dropout=0.0)
        bert.initialize()
        bert._probe_shapes(nd.zeros((2, 8), dtype="int32"))
        eng = InferStep(bert)
        sigs = [(((2, key), "int32"), ((2, key), "int32"), ((2,), "int32"))
                for key in (8, 12)]
        eng.warmup(sigs)
        rng = np.random.RandomState(0)
        ids = rng.randint(0, 31, (2, 8)).astype(np.int32)
        types = np.zeros_like(ids)
        vl = np.array([5, 8], np.int32)
        seq_e, pooled_e = eng(ids, types, vl)
        seq_d, pooled_d = bert(nd.array(ids), nd.array(types),
                               nd.array(vl, dtype="int32"))
        np.testing.assert_allclose(seq_e.asnumpy(), seq_d.asnumpy(),
                                   rtol=RTOL, atol=ATOL)
        # bucket-pad to 12: valid region must not move
        ids12 = np.zeros((2, 12), np.int32)
        ids12[:, :8] = ids
        seq12, _ = eng(ids12, np.zeros_like(ids12), vl)
        np.testing.assert_allclose(seq12.asnumpy()[0, :5],
                                   seq_e.asnumpy()[0, :5],
                                   rtol=2e-4, atol=2e-4)
        assert eng.compile_guard.steady_state_recompiles == 0

    def test_model_generate_api(self, tmodel):
        src = np.random.RandomState(2).randint(3, 61, (2, 7)) \
            .astype(np.int32)
        toks, lengths = tmodel.generate(src, max_new_tokens=4, max_len=24)
        assert toks.shape == (2, 4)
        assert lengths.shape == (2,)
        # engine is cached per config
        assert len(tmodel._infer_steps) == 1
        tmodel.generate(src, max_new_tokens=3, max_len=24)
        assert len(tmodel._infer_steps) == 1

    def test_estimator_predict(self, tmodel):
        from mxnet_tpu.gluon.contrib.estimator import Estimator
        from mxnet_tpu.gluon.loss import SoftmaxCrossEntropyLoss

        est = Estimator(tmodel, SoftmaxCrossEntropyLoss())
        rng = np.random.RandomState(3)
        batches = [(nd.array(rng.randint(3, 61, (2, 7)), dtype="int32"),
                    nd.array(rng.randint(3, 61, (2, 5)), dtype="int32"))
                   for _ in range(2)]
        outs = est.predict(batches)
        assert len(outs) == 2
        assert outs[0].shape == (2, 5, 61)
        # with an engine: same results through the jitted forward
        eng = InferStep(tmodel)
        outs_e = est.predict(batches, engine=eng)
        np.testing.assert_allclose(outs_e[0].asnumpy(),
                                   outs[0].asnumpy(), rtol=RTOL, atol=ATOL)

    def test_infer_report_fields(self, tmodel):
        """mx.telemetry.report() carries the infer/ family (timed path)."""
        mx.telemetry.reset()
        mx.telemetry.enable()
        try:
            eng = InferStep(tmodel, max_len=24)
            src = np.random.RandomState(1).randint(3, 61, (2, 7)) \
                .astype(np.int32)
            eng.generate(src, max_new_tokens=4)
            rep = mx.telemetry.report()
            assert rep["infer_tokens"] > 0
            assert rep["infer_prefill_ms_p50"] is not None
            assert rep["infer_decode_ms_per_token_p50"] is not None
            assert rep["infer_tokens_per_sec"] is not None
        finally:
            mx.telemetry.reset()


# -------------------------------------------------- speculative decoding
class TestSpeculativeDecode:
    """ISSUE 14: draft-proposes / target-verifies greedy speculation.
    The acceptance rule (draft token j lands iff it equals the target
    argmax at its position) makes the emitted stream the target's greedy
    output BIT-identically for ANY draft — these tests pin that down for
    the degenerate (k=0), oracle (full acceptance), and garbage
    (full rejection) drafts, plus the swap plane's pair coherence."""

    def _prompts(self, seed=11, B=3, Ls=8):
        rng = np.random.RandomState(seed)
        src = rng.randint(3, 61, (B, Ls)).astype(np.int32)
        vl = np.array([5, 7, 8], np.int32)
        return src, vl

    def _ref(self, tmodel, src, vl, T):
        eng = InferStep(tmodel, max_len=32)
        toks, lens = eng.decode_n(src, vl, max_new_tokens=T)
        return toks.asnumpy(), lens.asnumpy()

    def _oracle_draft(self, tmodel):
        np.random.seed(0)
        draft = _make_transformer()
        tp = {n.split("_", 1)[1]: p
              for n, p in tmodel.collect_params().items()}
        for name, p in draft.collect_params().items():
            p.set_data(nd.NDArray(tp[name.split("_", 1)[1]]._data.data))
        return draft

    def test_k0_bit_identical_to_decode_n(self, tmodel):
        src, vl = self._prompts()
        T = 6
        toks_d, lens_d = self._ref(tmodel, src, vl, T)
        eng = InferStep(tmodel, max_len=32)
        eng.attach_draft(self._oracle_draft(tmodel))
        toks, lens = eng.decode_spec_n(src, vl, max_new_tokens=T, k=0,
                                       page_size=4)
        np.testing.assert_array_equal(lens.asnumpy(), lens_d)
        np.testing.assert_array_equal(toks.asnumpy(), toks_d)
        eng.compile_guard.mark_steady()
        eng.decode_spec_n(src, vl, max_new_tokens=T, k=0, page_size=4)
        assert eng.compile_guard.steady_state_recompiles == 0

    @pytest.mark.parametrize("wide", [False, True])
    def test_oracle_draft_bit_identical(self, tmodel, wide):
        src, vl = self._prompts()
        T = 6
        toks_d, lens_d = self._ref(tmodel, src, vl, T)
        eng = InferStep(tmodel, max_len=32)
        eng.attach_draft(self._oracle_draft(tmodel))
        toks, lens = eng.decode_spec_n(src, vl, max_new_tokens=T, k=3,
                                       wide=wide, page_size=4)
        np.testing.assert_array_equal(lens.asnumpy(), lens_d)
        np.testing.assert_array_equal(toks.asnumpy(), toks_d)
        eng.compile_guard.mark_steady()
        eng.decode_spec_n(src, vl, max_new_tokens=T, k=3, wide=wide,
                          page_size=4)
        assert eng.compile_guard.steady_state_recompiles == 0

    def test_garbage_draft_full_rejection_still_exact(self, tmodel):
        """A draft with unrelated weights rejects (almost) every
        proposal; the output must STILL be the target's greedy stream —
        acceptance only sets the per-round burst length."""
        np.random.seed(9)
        garbage = _make_transformer()
        src, vl = self._prompts()
        T = 6
        toks_d, lens_d = self._ref(tmodel, src, vl, T)
        eng = InferStep(tmodel, max_len=32)
        eng.attach_draft(garbage)
        for wide in (False, True):
            toks, lens = eng.decode_spec_n(src, vl, max_new_tokens=T,
                                           k=3, wide=wide, page_size=4)
            np.testing.assert_array_equal(lens.asnumpy(), lens_d)
            np.testing.assert_array_equal(toks.asnumpy(), toks_d)

    def test_spec_pair_swap_coherence(self, tmodel):
        """swap_params flips (target, draft, version) as ONE tuple:
        draft/ checkpoint keys land on the draft engine, the pair
        version tracks weights_version, and the pre-swap snapshot keeps
        serving the OLD pair."""
        eng = InferStep(tmodel, max_len=32)
        draft = self._oracle_draft(tmodel)
        eng.attach_draft(draft)
        pair0 = eng.spec_pair()
        assert pair0[2] == eng.weights_version
        arrays = {n: np.asarray(p._data.data)
                  for n, p in tmodel.collect_params().items()}
        np.random.seed(13)
        other = _make_transformer()
        # draft/ keys use the DRAFT engine's own param names; map the
        # donor net's params over by instance-prefix-stripped name
        donor = {n.split("_", 1)[1]: np.asarray(p._data.data)
                 for n, p in other.collect_params().items()}
        for n in eng.draft._values:
            arrays["draft/" + n] = donor[n.split("_", 1)[1]]
        ver = eng.swap_params(arrays)
        pair1 = eng.spec_pair()
        assert pair1[2] == ver == eng.weights_version
        assert pair1 is not pair0 and pair0[2] != ver
        # draft values actually flipped to the staged draft/ arrays
        name = next(iter(eng.draft._values))
        np.testing.assert_array_equal(
            np.asarray(pair1[1][name]), arrays["draft/" + name])
        # the old snapshot still holds the old values (in-flight safety)
        assert pair0[1] is not pair1[1]

    def test_spec_requires_attach_draft(self, tmodel):
        eng = InferStep(tmodel, max_len=32)
        assert not eng.has_draft
        with pytest.raises(MXNetError, match="attach_draft"):
            eng.spec_pair()


# ------------------------------------------------------- DynamicBatcher
class TestDynamicBatcher:
    def _batcher(self, tmodel, **kw):
        eng = InferStep(tmodel, max_len=24)
        cfg = dict(bucket_keys=(8, 12), slots=2, timeout_ms=40.0,
                   max_new_tokens=4)
        cfg.update(kw)
        return DynamicBatcher(eng, **cfg), eng

    def test_full_batch_matches_direct_dispatch(self, tmodel):
        """Two submits filling the batch == ONE hand-assembled
        (slots, bucket) decode_n dispatch, row for row."""
        rng = np.random.RandomState(10)
        bat, eng = self._batcher(tmodel, timeout_ms=2000.0)
        prompts = [rng.randint(3, 61, (n,)).astype(np.int32)
                   for n in (5, 7)]
        try:
            futs = [bat.submit(p) for p in prompts]
            got = [f.result(timeout=60) for f in futs]
        finally:
            bat.stop()
        src = np.zeros((2, 8), np.int32)
        vl = np.zeros((2,), np.int32)
        for i, p in enumerate(prompts):
            src[i, :p.shape[0]] = p
            vl[i] = p.shape[0]
        toks, lengths = eng.decode_n(src, vl, max_new_tokens=4)
        toks, lengths = toks.asnumpy(), lengths.asnumpy()
        for i in range(2):
            np.testing.assert_array_equal(np.asarray(got[i]),
                                          toks[i, :int(lengths[i])])

    def test_timeout_dispatch_occupancy_and_queue_wait(self, tmodel):
        """A lone request dispatches after the admission window with the
        empty slots padded out; occupancy/queue-wait telemetry lands."""
        mx.telemetry.reset()
        mx.telemetry.enable()
        bat, _ = self._batcher(tmodel, slots=4, timeout_ms=30.0)
        try:
            fut = bat.submit([5, 6, 7])
            out = fut.result(timeout=60)
            assert isinstance(out, list) and len(out) <= 4
            assert fut.queue_wait_ms is not None
            rep = mx.telemetry.report()
            assert rep["infer_batch_occupancy"] == 0.25
            assert rep["infer_requests"] == 1
            assert rep["infer_queue_wait_ms_p50"] is not None
        finally:
            bat.stop()
            mx.telemetry.reset()

    def test_per_request_max_new_trim(self, tmodel):
        """A request's own max_new_tokens (< the batcher's) trims its
        result even though the batch decodes the full length."""
        bat, _ = self._batcher(tmodel, timeout_ms=5.0)
        try:
            fut = bat.submit([7, 8, 9, 10], max_new_tokens=2)
            assert len(fut.result(timeout=60)) <= 2
        finally:
            bat.stop()

    def test_request_validation(self, tmodel):
        bat, _ = self._batcher(tmodel, start=False)
        with pytest.raises(MXNetError):
            bat.submit(np.zeros((13,), np.int32))  # > largest bucket
        with pytest.raises(MXNetError):
            bat.submit([3, 4], max_new_tokens=99)  # > batcher max_new
        with pytest.raises(MXNetError):
            DynamicBatcher(object(), bucket_keys=(8,))  # no decode protocol
        with pytest.raises(MXNetError):
            DynamicBatcher(bat._engine, bucket_keys=())

    def test_dispatch_error_fails_futures_not_thread(self, tmodel):
        """An engine-side error resolves the futures with the exception;
        the dispatcher thread survives for the next batch."""
        eng = InferStep(tmodel, max_len=8)  # too small for max_new=20
        bat = DynamicBatcher(eng, bucket_keys=(4,), slots=2,
                             timeout_ms=5.0, max_new_tokens=20)
        try:
            fut = bat.submit([3, 4])
            with pytest.raises(MXNetError):
                fut.result(timeout=60)
            assert isinstance(fut.exception(), MXNetError)
            assert bat._thread.is_alive()
        finally:
            bat.stop()

    def test_warmed_batcher_zero_steady_recompiles(self, tmodel):
        """warmup=True compiles the whole (slots, bucket) menu up front;
        serving traffic across both buckets then never compiles."""
        bat, eng = self._batcher(tmodel, timeout_ms=5.0, warmup=True)
        assert eng.compile_guard.steady
        rng = np.random.RandomState(11)
        try:
            for n in (5, 10, 8, 12):  # both buckets, repeated
                fut = bat.submit(rng.randint(3, 61, (n,)).astype(np.int32))
                fut.result(timeout=60)
        finally:
            bat.stop()
        assert eng.compile_guard.steady_state_recompiles == 0
