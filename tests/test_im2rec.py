"""tools/im2rec.py end-to-end: images dir -> .lst/.rec/.idx -> ImageIter.

Reference flow: ``tools/im2rec.py`` then ``mx.image.ImageIter`` over the
.rec (the reference's standard data-prep path [unverified])."""

import os
import sys

import numpy as np
import pytest

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", "tools"))
import im2rec  # noqa: E402


@pytest.fixture()
def image_tree(tmp_path):
    from PIL import Image

    rng = np.random.RandomState(0)
    for cls in ("cat", "dog"):
        d = tmp_path / "imgs" / cls
        d.mkdir(parents=True)
        for i in range(3):
            arr = (rng.rand(40, 48, 3) * 255).astype(np.uint8)
            Image.fromarray(arr).save(d / f"{cls}{i}.jpg")
    return str(tmp_path / "imgs"), str(tmp_path / "data")


def test_list_generation(image_tree):
    root, prefix = image_tree
    assert im2rec.main([prefix, root, "--list"]) == 0
    lines = open(prefix + ".lst").read().strip().splitlines()
    assert len(lines) == 6
    labels = {float(l.split("\t")[1]) for l in lines}
    assert labels == {0.0, 1.0}  # cat=0, dog=1


def test_pack_and_read_back(image_tree):
    root, prefix = image_tree
    im2rec.main([prefix, root, "--list"])
    assert im2rec.main([prefix, root, "--resize", "32"]) == 0
    assert os.path.exists(prefix + ".rec")
    assert os.path.exists(prefix + ".idx")

    from mxnet_tpu import recordio

    rec = recordio.MXIndexedRecordIO(prefix + ".idx", prefix + ".rec", "r")
    header, img = recordio.unpack_img(rec.read_idx(0))
    assert header.label in (0.0, 1.0)
    assert img.ndim == 3 and min(img.shape[:2]) == 32
    rec.close()


def test_imageiter_over_rec(image_tree):
    root, prefix = image_tree
    im2rec.main([prefix, root, "--list"])
    im2rec.main([prefix, root, "--resize", "36"])

    from mxnet_tpu import image as mx_image

    it = mx_image.ImageIter(
        batch_size=2, data_shape=(3, 32, 32), path_imgrec=prefix + ".rec",
        path_imgidx=prefix + ".idx", rand_crop=False, shuffle=False,
    )
    batch = next(iter(it))
    assert batch.data[0].shape == (2, 3, 32, 32)
    assert batch.label[0].shape == (2,)
