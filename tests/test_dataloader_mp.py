"""Multiprocessing DataLoader: forked workers + shared-memory batches +
worker-death handling (reference: gluon dataloader worker processes
rebuilding NDArrays in shared memory [unverified])."""

import os
import signal
import time

import numpy as np
import pytest

from mxnet_tpu import gluon, nd
from mxnet_tpu.gluon import data as gdata


class _SquareDataset(gdata.Dataset):
    """Python-heavy __getitem__ (holds the GIL) returning numpy."""

    def __init__(self, n=64, dim=8):
        self._n, self._dim = n, dim

    def __len__(self):
        return self._n

    def __getitem__(self, i):
        return np.full((self._dim,), float(i) ** 2, np.float32)


def test_mp_loader_matches_serial():
    ds = _SquareDataset(40)
    serial = [b.asnumpy() for b in gdata.DataLoader(ds, batch_size=8)]
    mp = [b.asnumpy() for b in
          gdata.DataLoader(ds, batch_size=8, num_workers=3)]
    assert len(serial) == len(mp)
    for a, b in zip(serial, mp):
        np.testing.assert_array_equal(a, b)  # order preserved


def test_mp_loader_tuple_samples():
    x = np.arange(24, dtype=np.float32).reshape(12, 2)
    y = np.arange(12, dtype=np.float32)
    loader = gdata.DataLoader(gdata.ArrayDataset(x, y), batch_size=4,
                              num_workers=2)
    xs, ys = [], []
    for bx, by in loader:
        xs.append(bx.asnumpy())
        ys.append(by.asnumpy())
    np.testing.assert_array_equal(np.concatenate(xs), x)
    np.testing.assert_array_equal(np.concatenate(ys), y)


def test_mp_loader_custom_numpy_batchify():
    ds = _SquareDataset(16, dim=4)

    def batchify(samples):
        return np.stack(samples) * 2.0  # numpy-only, fork-inherited

    out = [b.asnumpy() for b in
           gdata.DataLoader(ds, batch_size=4, num_workers=2,
                            batchify_fn=batchify)]
    np.testing.assert_allclose(out[0][1], np.full((4,), 2.0))


def test_mp_worker_exception_propagates():
    class _Boom(gdata.Dataset):
        def __len__(self):
            return 8

        def __getitem__(self, i):
            if i == 5:
                raise ValueError("bad sample 5")
            return np.zeros((2,), np.float32)

    loader = gdata.DataLoader(_Boom(), batch_size=4, num_workers=2)
    with pytest.raises(ValueError, match="bad sample 5"):
        list(loader)


def test_mp_worker_death_detected():
    class _Suicide(gdata.Dataset):
        def __len__(self):
            return 64

        def __getitem__(self, i):
            if i >= 16:  # first prefetched batches succeed, then die
                os.kill(os.getpid(), signal.SIGKILL)
            time.sleep(0.01)
            return np.zeros((2,), np.float32)

    loader = gdata.DataLoader(_Suicide(), batch_size=8, num_workers=2,
                              timeout=30)
    with pytest.raises(RuntimeError, match="died"):
        list(loader)


def test_pin_memory_yields_device_arrays():
    ds = _SquareDataset(8)
    for b in gdata.DataLoader(ds, batch_size=4, num_workers=2,
                              pin_memory=True):
        assert hasattr(b, "data")
        assert np.isfinite(b.asnumpy()).all()


def test_thread_pool_path_still_works():
    ds = _SquareDataset(24)
    out = [b.asnumpy() for b in
           gdata.DataLoader(ds, batch_size=8, num_workers=2,
                            thread_pool=True)]
    assert len(out) == 3
    np.testing.assert_allclose(out[0][3], np.full((8,), 9.0))
