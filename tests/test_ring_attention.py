"""Ring attention (sequence parallelism) on the 8-device virtual CPU mesh:
sharded forward/backward must match the single-device flash kernel exactly
(same math, different communication schedule)."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec

from mxnet_tpu.ops.pallas import flash_attention
from mxnet_tpu.parallel import make_mesh
from mxnet_tpu.parallel.ring_attention import ring_flash_attention

N_DEV = 8


def _qkv(B=2, H=2, S=64, D=8, seed=0):
    rng = np.random.RandomState(seed)
    mk = lambda: jnp.asarray(rng.randn(B, H, S, D).astype(np.float32))  # noqa: E731
    return mk(), mk(), mk()


@pytest.fixture(scope="module")
def seq_mesh():
    if len(jax.devices()) < N_DEV:
        pytest.skip("needs 8 virtual devices")
    return make_mesh({"seq": N_DEV})


def test_ring_forward_matches_flash(seq_mesh):
    q, k, v = _qkv()
    out_ring = ring_flash_attention(q, k, v, seq_mesh, "seq")
    out_ref = flash_attention(q, k, v)
    np.testing.assert_allclose(np.asarray(out_ring), np.asarray(out_ref),
                               rtol=2e-4, atol=2e-4)


def test_ring_forward_causal_matches_flash(seq_mesh):
    q, k, v = _qkv(seed=1)
    out_ring = ring_flash_attention(q, k, v, seq_mesh, "seq", causal=True)
    out_ref = flash_attention(q, k, v, None, True)
    np.testing.assert_allclose(np.asarray(out_ring), np.asarray(out_ref),
                               rtol=2e-4, atol=2e-4)


def test_ring_grads_match_flash(seq_mesh):
    q, k, v = _qkv(seed=2)

    def loss_ring(q, k, v):
        return jnp.sum(ring_flash_attention(q, k, v, seq_mesh, "seq") ** 2)

    def loss_ref(q, k, v):
        return jnp.sum(flash_attention(q, k, v) ** 2)

    gr = jax.grad(loss_ring, argnums=(0, 1, 2))(q, k, v)
    gf = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(gr, gf):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=2e-3, atol=2e-3)


def test_ring_grads_causal_match_flash(seq_mesh):
    q, k, v = _qkv(seed=3)

    def loss_ring(q, k, v):
        return jnp.sum(
            ring_flash_attention(q, k, v, seq_mesh, "seq", causal=True) ** 2
        )

    def loss_ref(q, k, v):
        return jnp.sum(flash_attention(q, k, v, None, True) ** 2)

    gr = jax.grad(loss_ring, argnums=(0, 1, 2))(q, k, v)
    gf = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(gr, gf):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=2e-3, atol=2e-3)


def test_ring_under_jit_with_sharded_inputs(seq_mesh):
    """The production shape: inputs device_put sharded over seq, the whole
    thing inside jit (the TrainStep composition path)."""
    q, k, v = _qkv(seed=4)
    spec = NamedSharding(seq_mesh, PartitionSpec(None, None, "seq", None))
    qs, ks, vs = (jax.device_put(x, spec) for x in (q, k, v))

    @jax.jit
    def f(q, k, v):
        return ring_flash_attention(q, k, v, seq_mesh, "seq")

    out = f(qs, ks, vs)
    out_ref = flash_attention(q, k, v)
    np.testing.assert_allclose(np.asarray(out), np.asarray(out_ref),
                               rtol=2e-4, atol=2e-4)


def test_ring_memory_is_sharded(seq_mesh):
    """Each shard of the output lives on its own device with S/n rows."""
    q, k, v = _qkv(seed=5)
    out = ring_flash_attention(q, k, v, seq_mesh, "seq")
    shard_shapes = {s.data.shape for s in out.addressable_shards}
    assert shard_shapes == {(2, 2, 64 // N_DEV, 8)}


def test_trainstep_with_ring_attention_matches_dense():
    """Full composition: TrainStep over a (data, seq) mesh with the model's
    attention in ring mode == the same model/step without ring (single
    device), for identical inits."""
    import mxnet_tpu as mx
    from mxnet_tpu import gluon, nd, optimizer as opt
    from mxnet_tpu.parallel import TrainStep

    B, S, units, H = 4, 32, 16, 2

    def build(ring_axis):
        mx.random.seed(7)
        net = gluon.nn.HybridSequential()
        with net.name_scope():
            net.add(gluon.nn.MultiHeadAttention(units, H, causal=True,
                                                ring_axis=ring_axis))
            net.add(gluon.nn.Dense(4, flatten=False))
        net.initialize()
        net._probe_shapes(nd.zeros((2, S, units)))
        return net

    ce = gluon.loss.SoftmaxCrossEntropyLoss()

    class _Loss:
        def __call__(self, out, label):
            return ce(out.reshape(-1, 4), label.reshape(-1))

    rng = np.random.RandomState(0)
    x = rng.randn(B, S, units).astype(np.float32)
    y = rng.randint(0, 4, (B, S)).astype(np.float32)

    mesh = make_mesh({"data": 2, "seq": 4})
    from mxnet_tpu.parallel import PartitionSpec as P

    net_ring = build("seq")
    step_ring = TrainStep(net_ring, _Loss(), opt.SGD(learning_rate=0.1),
                          mesh=mesh, data_spec=P("data", "seq"))
    net_ref = build(None)
    step_ref = TrainStep(net_ref, _Loss(), opt.SGD(learning_rate=0.1))

    for i in range(3):
        l_ring = float(step_ring(nd.array(x), nd.array(y)).asscalar())
        l_ref = float(step_ref(nd.array(x), nd.array(y)).asscalar())
        np.testing.assert_allclose(l_ring, l_ref, rtol=2e-4, atol=2e-5)


def test_ring_batch_axis_sharding():
    """On a dp x sp mesh the batch dim must shard over 'data' inside the
    ring region (replication would double per-device attention FLOPs)."""
    mesh = make_mesh({"data": 2, "seq": 4})
    q, k, v = _qkv(B=4, S=32)
    spec = NamedSharding(mesh, PartitionSpec("data", None, "seq", None))
    qs, ks, vs = (jax.device_put(x, spec) for x in (q, k, v))
    out = ring_flash_attention(q, k, v, mesh, "seq")
    shard_shapes = {s.data.shape for s in out.addressable_shards}
    assert shard_shapes == {(2, 2, 8, 8)}  # B/2, S/4
    out_ref = flash_attention(q, k, v)
    np.testing.assert_allclose(np.asarray(out), np.asarray(out_ref),
                               rtol=2e-4, atol=2e-4)


def test_ring_net_evals_densely_without_mesh():
    """A ring-configured net must run plain single-device inference."""
    from mxnet_tpu import gluon, nd

    mha = gluon.nn.MultiHeadAttention(16, 2, ring_axis="seq")
    mha.initialize()
    x = nd.array(np.random.RandomState(0).randn(2, 8, 16).astype("float32"))
    out = mha(x)  # no mesh scope active -> dense fallback
    assert out.shape == (2, 8, 16)
