"""Smoke-run the shipped examples (reference kept examples runnable in CI
via small synthetic configs [unverified])."""

import os
import subprocess
import sys

_EX = os.path.join(os.path.dirname(__file__), "..", "examples")


def _run(script, *args):
    env = dict(os.environ)
    r = subprocess.run(
        [sys.executable, os.path.join(_EX, script), *args],
        capture_output=True, text=True, env=env, timeout=420,
    )
    assert r.returncode == 0, r.stdout[-2000:] + r.stderr[-2000:]
    return r.stdout


def test_gluon_mnist():
    out = _run("gluon_mnist.py", "--epochs", "1", "--batches-per-epoch", "3",
               "--batch-size", "8")
    assert "epoch 0" in out


def test_module_lenet():
    out = _run("module_lenet.py", "--epochs", "1", "--num-examples", "64",
               "--batch-size", "32")
    assert "validation" in out


def test_distributed_train():
    out = _run("distributed_train.py", "--steps", "6", "--batch-size", "8")
    assert "done" in out


def test_distributed_train_tp():
    out = _run("distributed_train.py", "--steps", "4", "--batch-size", "8",
               "--tp", "2", "--force-cpu")
    assert "done" in out
