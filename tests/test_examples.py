"""Run the shipped examples with PLANTED CONVERGENCE assertions (round-3
verdict weak #8: smoke-only example tests keep nothing honest — the
reference's examples are its de-facto tutorial surface). The synthetic
tasks carry a class-dependent pattern, so a working training loop must
LEARN it: losses fall across epochs (fresh batches each epoch — this is
generalization on the planted pattern, not memorization) and
train-subset accuracy beats chance."""

import os
import re
import subprocess
import sys

_EX = os.path.join(os.path.dirname(__file__), "..", "examples")


def _run(script, *args):
    env = dict(os.environ)
    r = subprocess.run(
        [sys.executable, os.path.join(_EX, script), *args],
        capture_output=True, text=True, env=env, timeout=420,
    )
    assert r.returncode == 0, r.stdout[-2000:] + r.stderr[-2000:]
    return r.stdout


def test_gluon_mnist_converges():
    out = _run("gluon_mnist.py", "--epochs", "4", "--batches-per-epoch", "5",
               "--batch-size", "16", "--lr", "3e-3")
    losses = [float(m) for m in re.findall(r"loss=([0-9.]+)", out)]
    assert len(losses) == 4
    # the planted class pattern is learnable across fresh batches
    assert losses[-1] < losses[0] * 0.8, f"no convergence: {losses}"


def test_module_lenet_learns_train_subset():
    out = _run("module_lenet.py", "--epochs", "10", "--num-examples", "128",
               "--batch-size", "32")
    m = re.search(r"validation:.*?([0-9.]+)\)", out)
    assert m, out[-500:]
    acc = float(m.group(1))
    # val IS a train subset; memorizing 128 examples must beat the 0.1
    # chance floor decisively
    assert acc > 0.25, f"Module.fit failed to memorize: acc={acc}\n{out[-400:]}"


def test_distributed_train_loss_falls():
    out = _run("distributed_train.py", "--steps", "12", "--batch-size", "8")
    assert "done" in out
    losses = [float(m) for m in re.findall(r"loss=([0-9.]+)", out)]
    assert len(losses) >= 2
    assert losses[-1] < losses[0], f"dist loop did not learn: {losses}"


def test_distributed_train_tp():
    out = _run("distributed_train.py", "--steps", "4", "--batch-size", "8",
               "--tp", "2", "--force-cpu")
    assert "done" in out


def test_int8_inference_example():
    out = _run("int8_inference.py", "--steps", "25")
    assert "quantized 3/3" in out
    m = re.search(r"int8 accuracy:\s+([0-9.]+)", out)
    assert m and float(m.group(1)) > 0.9


def test_onnx_interchange_example(tmp_path):
    out = _run("onnx_interchange.py", "--out",
               str(tmp_path / "m.onnx"))
    assert "onnx interchange OK" in out


def test_long_context_attention_example():
    out = _run("long_context_attention.py", "--seq", "512")
    assert "long-context attention parity OK" in out


def test_resume_training_example(tmp_path):
    """Crash at step 4, rerun the same command, resume to step 8; the
    resumed run must pick up the committed step and the loss must keep
    falling across the interruption."""
    env = dict(os.environ)
    r1 = subprocess.run(
        [sys.executable, os.path.join(_EX, "resume_training.py"),
         "--steps", "8", "--ckpt-dir", str(tmp_path / "ck"),
         "--interrupt-at", "4"],
        capture_output=True, text=True, env=env, timeout=420)
    assert r1.returncode == 17, r1.stdout[-1500:] + r1.stderr[-1500:]
    assert "simulating crash" in r1.stdout
    l1 = [float(m) for m in re.findall(r"loss ([0-9.]+)", r1.stdout)]

    out = _run("resume_training.py", "--steps", "8",
               "--ckpt-dir", str(tmp_path / "ck"))
    assert "resumed from committed step 4" in out
    assert "done at step 8" in out
    l2 = [float(m) for m in re.findall(r"loss ([0-9.]+)", out)]
    assert l2[-1] < l1[0] * 0.5, (l1, l2)
