"""Trainer (eager per-param) vs TrainStep (fused jitted) optimizer parity.

VERDICT weak #9: the two training paths must agree for every fused
optimizer, not just SGD. Also covers the multi-precision AMP path
(compute-dtype grads + f32 masters, the reference ``mp_*_update`` scheme)
and the narrow optimizer-state option.
"""

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import autograd, gluon, optimizer as opt
from mxnet_tpu.gluon import nn
from mxnet_tpu import parallel

X = np.random.RandomState(0).randn(16, 8).astype("float32")
Y = np.random.RandomState(1).randn(16, 1).astype("float32")


def _build():
    mx.random.seed(11)
    net = nn.HybridSequential()
    with net.name_scope():
        net.add(nn.Dense(16, activation="relu"), nn.Dense(1))
    net.initialize()
    net(mx.nd.array(X))
    return net


def _norm(params):
    # prefixes auto-increment per construction (hybridsequential0, 1, ...);
    # compare on the stable suffix
    return {k.split("dense", 1)[-1]: v for k, v in params.items()}


def _run_trainer(optimizer_name, kwargs, steps=5):
    net = _build()
    trainer = gluon.Trainer(net.collect_params(), optimizer_name, dict(kwargs))
    loss_fn = gluon.loss.L2Loss()
    for _ in range(steps):
        with autograd.record():
            L = loss_fn(net(mx.nd.array(X)), mx.nd.array(Y))
        L.backward()
        trainer.step(16)
    return _norm({k: v.data().asnumpy()
                  for k, v in net.collect_params().items()})


def _run_step(optimizer, steps=5, **step_kw):
    net = _build()
    step = parallel.TrainStep(net, gluon.loss.L2Loss(), optimizer, **step_kw)
    for _ in range(steps):
        step(mx.nd.array(X), mx.nd.array(Y))
    step.sync_params()
    return _norm({k: v.data().asnumpy()
                  for k, v in net.collect_params().items()})


def _compare(pa, pb, rtol, atol):
    assert set(pa) == set(pb)
    for k in pa:
        np.testing.assert_allclose(pa[k], pb[k], rtol=rtol, atol=atol,
                                   err_msg=k)


@pytest.mark.parametrize(
    "name,kwargs,make",
    [
        ("adam", {"learning_rate": 1e-2},
         lambda: opt.Adam(learning_rate=1e-2)),
        ("adamw", {"learning_rate": 1e-2, "wd": 0.01},
         lambda: opt.AdamW(learning_rate=1e-2, wd=0.01)),
        ("lamb", {"learning_rate": 1e-2, "wd": 0.01},
         lambda: opt.LAMB(learning_rate=1e-2, wd=0.01)),
        ("sgd", {"learning_rate": 0.05, "momentum": 0.9, "wd": 1e-4},
         lambda: opt.SGD(learning_rate=0.05, momentum=0.9, wd=1e-4)),
    ],
)
def test_trainer_vs_trainstep(name, kwargs, make):
    pa = _run_trainer(name, kwargs)
    pb = _run_step(make())
    _compare(pa, pb, rtol=5e-4, atol=2e-5)


def test_mp_bf16_grads_track_f32():
    """compute_dtype=bf16 (bf16 grads, f32 masters) must track the f32 run
    to bf16-resolution tolerance."""
    pa = _run_step(opt.AdamW(learning_rate=1e-2))
    pb = _run_step(opt.AdamW(learning_rate=1e-2), compute_dtype="bfloat16")
    # Adam normalizes updates, so bf16 grad noise drifts weights by O(lr)
    # per step on near-zero entries — tolerance reflects 5 steps of that
    _compare(pa, pb, rtol=5e-2, atol=2e-2)


def test_state_dtype_bf16_tracks_f32():
    pa = _run_step(opt.AdamW(learning_rate=1e-2))
    pb = _run_step(opt.AdamW(learning_rate=1e-2), state_dtype="bfloat16")
    _compare(pa, pb, rtol=5e-2, atol=5e-3)
    # states actually stored narrow
    net = _build()
    st = parallel.TrainStep(net, gluon.loss.L2Loss(),
                            opt.AdamW(learning_rate=1e-2),
                            state_dtype="bfloat16")
    import jax.numpy as jnp

    for name, states in st._opt_state.items():
        for s in states:
            assert s.dtype == jnp.bfloat16


def test_mp_still_learns():
    net = _build()
    step = parallel.TrainStep(net, gluon.loss.L2Loss(),
                              opt.AdamW(learning_rate=1e-2),
                              compute_dtype="bfloat16",
                              state_dtype="bfloat16")
    l0 = float(step(mx.nd.array(X), mx.nd.array(Y)).asscalar())
    for _ in range(20):
        L = step(mx.nd.array(X), mx.nd.array(Y))
    l1 = float(L.asscalar())
    assert l1 < l0 * 0.7


def test_remat_parity():
    """TrainStep(remat=...) must not change numerics — only the
    recompute schedule (round-5: the transformer roofline's negative
    result keeps the option for long-sequence regimes)."""
    base = _run_step(opt.Adam(learning_rate=0.01))
    for mode in ("dots", "full"):
        got = _run_step(opt.Adam(learning_rate=0.01), remat=mode)
        for k in base:
            np.testing.assert_allclose(got[k], base[k], rtol=1e-5,
                                       atol=1e-6, err_msg=f"{mode}:{k}")
