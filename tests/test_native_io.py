"""Native C++ IO library (src/librecordio.cc): framing-scan parity with the
Python reader and libjpeg decode parity with PIL.

Reference analogue: dmlc-core RecordIOReader + the C++ image pipeline
(``src/io`` [unverified]) — here built on demand and always paired with a
pure-Python fallback."""

import io
import os

import numpy as np
import pytest

from mxnet_tpu import recordio, _native


@pytest.fixture(scope="module")
def native_lib():
    lib = _native.lib()
    if lib is None:
        pytest.skip("native IO library unavailable (no g++/libjpeg)")
    return lib


@pytest.fixture()
def rec_file(tmp_path):
    path = str(tmp_path / "t.rec")
    rec = recordio.MXRecordIO(path, "w")
    payloads = [
        b"hello",
        b"x" * 1,
        b"y" * 1024,
        np.random.RandomState(0).bytes(7777),
        b"",  # empty record
    ]
    for p in payloads:
        rec.write(p)
    rec.close()
    return path, payloads


class TestNativeReader:
    def test_scan_count_and_parity(self, native_lib, rec_file):
        path, payloads = rec_file
        nr = _native.NativeRecordReader(path)
        assert len(nr) == len(payloads)
        for i, expect in enumerate(payloads):
            assert nr.read(i) == expect
        nr.close()

    def test_read_at_offsets(self, native_lib, rec_file):
        path, payloads = rec_file
        # offsets as the .idx file would record them (tell() before write)
        nr = _native.NativeRecordReader(path)
        payload, end = nr.read_at(0)
        assert payload == payloads[0]
        assert end == 8 + len(payloads[0]) + (-len(payloads[0])) % 4
        nr.close()

    def test_indexed_recordio_uses_native(self, native_lib, tmp_path):
        prefix = str(tmp_path / "d")
        w = recordio.MXIndexedRecordIO(prefix + ".idx", prefix + ".rec", "w")
        blobs = [os.urandom(100 + 13 * i) for i in range(10)]
        for i, b in enumerate(blobs):
            w.write_idx(i, b)
        w.close()
        r = recordio.MXIndexedRecordIO(prefix + ".idx", prefix + ".rec", "r")
        for i in (3, 0, 9, 5):
            assert r.read_idx(i) == blobs[i]
        assert r._native_reader() is not None  # fast path active
        r.close()

    def test_large_chunked_record(self, native_lib, tmp_path):
        # force the multi-chunk framing path (cflag 1/2/3)
        import mxnet_tpu.recordio as rio

        old = rio._K_MAX
        rio._K_MAX = 64
        try:
            path = str(tmp_path / "chunk.rec")
            w = recordio.MXRecordIO(path, "w")
            blob = os.urandom(300)
            w.write(blob)
            w.close()
        finally:
            rio._K_MAX = old
        nr = _native.NativeRecordReader(path)
        assert len(nr) == 1
        assert nr.read(0) == blob


class TestNativeJpeg:
    def test_decode_matches_pil(self, native_lib, tmp_path):
        from PIL import Image

        rng = np.random.RandomState(0)
        arr = (rng.rand(32, 48, 3) * 255).astype(np.uint8)
        buf = io.BytesIO()
        Image.fromarray(arr).save(buf, format="JPEG", quality=95)
        data = buf.getvalue()
        out = _native.jpeg_decode(data)
        assert out is not None and out.shape == (32, 48, 3)
        ref = np.asarray(Image.open(io.BytesIO(data)))[..., ::-1]  # BGR
        # libjpeg versions may differ in IDCT rounding by a few counts
        assert np.mean(np.abs(out.astype(int) - ref.astype(int))) < 3.0

    def test_decode_non_jpeg_returns_none(self, native_lib):
        assert _native.jpeg_decode(b"not a jpeg") is None

    def test_decode_image_integration(self, native_lib, tmp_path):
        from PIL import Image

        arr = (np.random.RandomState(1).rand(20, 20, 3) * 255).astype(
            np.uint8
        )
        buf = io.BytesIO()
        Image.fromarray(arr).save(buf, format="JPEG")
        img = recordio._decode_image(buf.getvalue())
        assert img.shape == (20, 20, 3)


class TestReviewRegressions:
    def test_read_idx_then_sequential_read(self, native_lib, tmp_path):
        """read_idx must position the stream like seek+read (reference
        semantics), so a following read() returns the NEXT record."""
        prefix = str(tmp_path / "seq")
        w = recordio.MXIndexedRecordIO(prefix + ".idx", prefix + ".rec", "w")
        blobs = [b"A" * 10, b"B" * 20, b"C" * 30]
        for i, b in enumerate(blobs):
            w.write_idx(i, b)
        w.close()
        r = recordio.MXIndexedRecordIO(prefix + ".idx", prefix + ".rec", "r")
        assert r.read_idx(1) == blobs[1]
        assert r.read() == blobs[2]  # sequential continues after record 1
        r.close()

    def test_grayscale_unchanged_stays_2d(self, tmp_path):
        from PIL import Image

        arr = (np.random.RandomState(2).rand(16, 16) * 255).astype(np.uint8)
        buf = io.BytesIO()
        Image.fromarray(arr, mode="L").save(buf, format="JPEG")
        img = recordio._decode_image(buf.getvalue(), iscolor=-1)
        assert img.ndim == 2  # "unchanged" decode keeps grayscale 2-D
