"""tools/check_sharding.py as a tier-1 unit test: every parameter
entering the jitted train/infer step carries its declared NamedSharding,
placements survive a real (donated) dispatch, and no sharding rule
silently falls back to full replication."""

import os
import sys

import pytest

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", "tools"))
import check_sharding  # noqa: E402


@pytest.fixture(scope="module")
def setup():
    return check_sharding.build_default_setup()


def test_sharding_lint_passes(setup):
    violations = check_sharding.run_checks(*setup)
    assert not violations, "\n".join(violations)


def test_lint_flags_inert_rule(setup):
    """Negative control: a pattern matching no parameter must be
    reported (guards the checker against rotting into a no-op)."""
    from mxnet_tpu.parallel import sharding as shard
    from mxnet_tpu.parallel import PartitionSpec as P

    mesh, _, _, _, _, shapes = setup
    bad = shard.ShardingRules.fsdp(min_size=32, rules=[
        (r"matches_nothing$", P("data"))])
    violations = check_sharding.check_rules_coverage(bad, shapes, mesh)
    assert any("matched NO parameter" in v for v in violations)


def test_lint_flags_indivisible_fsdp(setup):
    """A param large enough to shard but with no dim divisible by the
    axis is a silent full-replication fallback — must be flagged."""
    from mxnet_tpu.parallel import sharding as shard

    mesh, _, _, _, _, _ = setup
    rules = shard.ShardingRules.fsdp(min_size=8)
    violations = check_sharding.check_rules_coverage(
        rules, {"odd_weight": (7, 9)}, mesh)
    assert any("silently fully replicated" in v for v in violations)


def test_lint_flags_fully_replicated_fsdp(setup):
    """An fsdp policy that partitions NOTHING (everything under
    min_size) is itself a violation."""
    from mxnet_tpu.parallel import sharding as shard

    mesh, _, _, _, _, _ = setup
    rules = shard.ShardingRules.fsdp(min_size=10**9)
    violations = check_sharding.check_rules_coverage(
        rules, {"w": (64, 16)}, mesh)
    assert any("partitioned NOTHING" in v for v in violations)


def test_lint_detects_misplacement(setup):
    """Negative control: an array placed differently from its declared
    sharding must be reported."""
    import jax
    from jax.sharding import NamedSharding
    from mxnet_tpu.parallel import PartitionSpec as P

    mesh, rules, step, eng, batch, shapes = setup
    name = next(n for n in step._train_vals
                if step._param_sharding(n).spec != P())
    orig = step._train_vals[name]
    try:
        step._train_vals[name] = jax.device_put(
            jax.numpy.asarray(orig), NamedSharding(mesh, P()))
        violations = check_sharding.check_step_placement(step)
        assert any(name in v for v in violations)
    finally:
        step._train_vals[name] = orig
