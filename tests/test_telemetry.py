"""Unified telemetry subsystem: spans, metrics, watchdog, integrations.

Covers the ISSUE-1 acceptance surface: span nesting + disabled-mode
no-op, histogram percentiles, heartbeat progress + simulated-stall
detection, Chrome-trace/JSONL dump round-trip, trainer-step metric
emission on a tiny model (with dataloader + kvstore spans in the same
trace), and the bench watchdog nonzero-exit regression.
"""

import json
import os
import subprocess
import sys
import threading
import time

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import autograd, gluon
from mxnet_tpu import telemetry as tel
from mxnet_tpu.gluon import nn
from mxnet_tpu.telemetry.metrics import Histogram
from mxnet_tpu.telemetry.watchdog import Watchdog

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(autouse=True)
def _telemetry_sandbox(tmp_path, monkeypatch):
    """Each test gets a fresh telemetry dir and a clean global state."""
    monkeypatch.setenv("MXNET_TELEMETRY_DIR", str(tmp_path / "tel"))
    monkeypatch.delenv("MXNET_TELEMETRY_WATCHDOG", raising=False)
    tel.reset()
    yield
    tel.reset()


# ----------------------------------------------------------------- events
def test_disabled_mode_is_noop(tmp_path):
    assert not tel.enabled()
    # the disabled span is ONE shared singleton — no per-call allocation
    assert tel.span("a") is tel.NULL_SPAN
    assert tel.span("b", {"k": 1}) is tel.NULL_SPAN
    with tel.span("a"):
        pass
    tel.instant("marker")
    assert tel.jsonl_path() is None
    assert tel.dump() is None
    assert not (tmp_path / "tel").exists()


def test_span_nesting_and_dump_roundtrip(tmp_path):
    tel.enable(watchdog=False)
    with tel.span("outer", {"k": "v"}):
        with tel.span("inner"):
            time.sleep(0.005)
    tel.instant("phase.marker", {"step": 3})

    # JSONL: depth/parent recorded, stream is one JSON object per line
    lines = [json.loads(l) for l in open(tel.jsonl_path())]
    outer = next(l for l in lines if l["name"] == "outer")
    inner = next(l for l in lines if l["name"] == "inner")
    assert inner["depth"] == 1 and inner["parent"] == "outer"
    assert outer["depth"] == 0 and outer["parent"] is None
    # containment: inner lies within outer on the same tid
    assert inner["tid"] == outer["tid"]
    assert outer["ts"] <= inner["ts"]
    assert inner["ts"] + inner["dur"] <= outer["ts"] + outer["dur"] + 1e-3

    # Chrome-trace dump loads and holds the same spans + the instant
    trace = json.load(open(tel.dump()))
    events = trace["traceEvents"]
    names = {e["name"] for e in events}
    assert {"outer", "inner", "phase.marker"} <= names
    for e in events:
        if e["name"] == "inner":
            assert e["ph"] == "X" and e["dur"] >= 4000  # >= 4ms in us
        if e["name"] == "phase.marker":
            assert e["ph"] == "i" and e["args"]["step"] == 3


def test_span_nesting_is_thread_local():
    tel.enable(watchdog=False)
    seen = {}

    def worker():
        with tel.span("t2.outer"):
            with tel.span("t2.inner"):
                pass

    with tel.span("main.outer"):
        t = threading.Thread(target=worker)
        t.start()
        t.join()
    lines = [json.loads(l) for l in open(tel.jsonl_path())]
    for l in lines:
        seen[l["name"]] = l
    # the worker thread's stack does not see main's open span
    assert seen["t2.outer"]["depth"] == 0
    assert seen["t2.outer"]["parent"] is None
    assert seen["t2.inner"]["parent"] == "t2.outer"


def test_non_serializable_span_args_survive():
    tel.enable(watchdog=False)
    with tel.span("odd", {"obj": object()}):
        pass
    lines = [json.loads(l) for l in open(tel.jsonl_path())]
    assert any(l["name"] == "odd" for l in lines)


# ---------------------------------------------------------------- metrics
def test_histogram_percentiles():
    h = Histogram(window=1024)
    for v in range(1, 101):
        h.observe(v / 100.0)
    assert h.count == 100
    assert abs(h.percentile(50) - 0.505) < 1e-9
    assert abs(h.percentile(95) - 0.9505) < 1e-3
    assert abs(h.percentile(99) - 0.9901) < 1e-3
    s = h.summary()
    assert s["min"] == 0.01 and s["max"] == 1.0
    assert abs(s["mean"] - 0.505) < 1e-9


def test_histogram_rolling_window_with_cumulative_totals():
    h = Histogram(window=10)
    for v in range(100):
        h.observe(float(v))
    # percentiles reflect only the last 10 observations (90..99) ...
    assert h.percentile(50) >= 90.0
    # ... while count/sum stay cumulative
    assert h.count == 100
    assert h.sum == sum(range(100))


def test_empty_histogram_is_null_safe():
    h = Histogram()
    assert h.percentile(50) is None
    assert h.summary()["p95"] is None


def test_registry_get_or_create_and_clear():
    reg = tel.registry()
    c = reg.counter("test/c")
    assert reg.counter("test/c") is c
    c.inc(5)
    reg.gauge("test/g").max(10)
    reg.gauge("test/g").max(3)  # high-water mark keeps 10
    snap = reg.snapshot()
    assert snap["counters"]["test/c"] == 5
    assert snap["gauges"]["test/g"] == 10
    reg.clear(prefix="test/")
    assert "test/c" not in reg.snapshot()["counters"]


def test_report_step_metrics():
    tel.enable(watchdog=False)
    for dt in (0.01, 0.02, 0.03, 0.04, 0.05):
        tel.record_step(samples=32, seconds=dt)
    r = tel.report()
    assert r["steps"] == 5
    assert abs(r["step_time_p50"] - 0.03) < 1e-9
    assert r["step_time_p95"] is not None
    # 160 samples over 0.15s of recorded step time
    assert abs(r["samples_per_sec"] - 160 / 0.15) < 1e-6
    # null-safe accelerator columns on CPU
    assert r["hbm_peak_bytes"] is None


def test_profiler_rebased_on_registry():
    mx.profiler.record_host_op("myop", 0.002)
    mx.profiler.record_host_op("myop", 0.004)
    table = mx.profiler.dumps()
    assert "myop" in table
    hist = tel.registry().histograms_with_prefix("op/")["op/myop"]
    assert hist.count == 2 and abs(hist.sum - 0.006) < 1e-9
    mx.profiler.dumps(reset=True)
    assert "myop" not in mx.profiler.dumps()


# --------------------------------------------------------------- watchdog
def test_watchdog_heartbeat_progress(tmp_path):
    wd = Watchdog(str(tmp_path), interval=0.05, stall_factor=10,
                  min_stall_s=30)
    wd.start()
    try:
        for _ in range(3):
            wd.notify_step(seconds=0.01)
        time.sleep(0.2)
        hb = json.load(open(wd.heartbeat_path))
        assert hb["step"] == 3
        assert hb["status"] == "alive"
        assert hb["median_step_s"] == 0.01
    finally:
        wd.stop()
    assert json.load(open(wd.heartbeat_path))["status"] == "stopped"


def test_watchdog_detects_simulated_stall(tmp_path):
    stalls = []
    wd = Watchdog(str(tmp_path), interval=0.05, stall_factor=3,
                  min_stall_s=0.1, on_stall=stalls.append)
    wd.start()
    try:
        for _ in range(4):
            wd.notify_step(seconds=0.01)
        # simulated stalled step: sleep far beyond 3x the 10ms median
        deadline = time.time() + 5.0
        while not stalls and time.time() < deadline:
            time.sleep(0.05)
    finally:
        wd.stop()
    assert stalls, "watchdog never fired on a stalled step"
    state = stalls[0]
    assert state["step"] == 4
    # the stall dumps every thread's stack
    assert state["stacks"] and os.path.exists(state["stacks"])
    dump_txt = open(state["stacks"]).read()
    assert "Thread" in dump_txt
    assert json.load(open(wd.heartbeat_path))["status"] == "stopped"
    # one stall episode, not one per interval tick
    assert wd.stall_count == 1


def test_watchdog_hard_hang_exits_nonzero(tmp_path):
    codes = []
    wd = Watchdog(str(tmp_path), interval=0.05, stall_factor=100,
                  min_stall_s=100, hard_timeout_s=0.2, exit_code=43,
                  _exit_fn=codes.append)
    wd.start()
    try:
        deadline = time.time() + 5.0
        while not codes and time.time() < deadline:
            time.sleep(0.05)
        assert codes == [43]
        # heartbeat flushed BEFORE the exit call (in production os._exit
        # ends the process here; stop() below is test-only teardown)
        assert json.load(open(wd.heartbeat_path))["status"] == "hard_hang"
    finally:
        wd.stop()


def test_watchdog_no_stall_before_first_step(tmp_path):
    # a run still compiling has no step times: stall detection stays
    # quiet (the hard timeout is the backstop for that phase)
    stalls = []
    wd = Watchdog(str(tmp_path), interval=0.05, stall_factor=1,
                  min_stall_s=0.05, on_stall=stalls.append)
    wd.start()
    time.sleep(0.3)
    wd.stop()
    assert not stalls


def test_record_step_feeds_watchdog():
    tel.enable(watchdog=False)
    wd = tel.start_watchdog(interval=0.05, stall_factor=10,
                            min_stall_s=30)
    try:
        tel.record_step(samples=8, seconds=0.01)
        tel.record_step(samples=8, seconds=0.01)
        time.sleep(0.15)
        hb = json.load(open(wd.heartbeat_path))
        assert hb["step"] == 2
    finally:
        tel.stop_watchdog()


# ----------------------------------------------------- trainer integration
def _toy_training_run(steps=5):
    """5-step toy run exercising trainer + dataloader + kvstore spans."""
    net = nn.Dense(2, in_units=4)
    net.initialize()
    # update_on_kvstore routes the optimizer through kvstore push/pull —
    # the single-process path that emits kvstore spans
    trainer = gluon.Trainer(net.collect_params(), "sgd",
                            {"learning_rate": 0.1},
                            update_on_kvstore=True)
    xs = np.random.RandomState(0).randn(steps * 2, 4).astype(np.float32)
    ys = np.zeros((steps * 2,), dtype=np.float32)
    dataset = gluon.data.ArrayDataset(mx.nd.array(xs), mx.nd.array(ys))
    loader = gluon.data.DataLoader(dataset, batch_size=2)
    n = 0
    for data, label in loader:
        if n >= steps:
            break
        with autograd.record():
            loss = (net(data).sum() - label.sum()) ** 2
        loss.backward()
        trainer.step(2)
        n += 1
    return net


def test_trainer_step_emits_spans_and_metrics():
    tel.enable(watchdog=False)
    _toy_training_run(steps=5)
    r = tel.report()
    assert r["steps"] == 5
    assert r["step_time_p50"] is not None
    assert r["step_time_p95"] is not None
    assert r["samples_per_sec"] is not None and r["samples_per_sec"] > 0
    assert r["counters"]["trainer/samples"] == 10
    # Chrome-trace dump is loadable and carries all three span families
    trace = json.load(open(tel.dump()))
    names = {e["name"] for e in trace["traceEvents"]}
    assert "trainer.step" in names
    assert "trainer.update" in names
    assert "dataloader.load" in names
    assert "kvstore.push" in names
    assert "kvstore.pull" in names
    # kvstore metrics recorded alongside the spans
    assert r["counters"]["kvstore/push_bytes"] > 0


def test_trainer_disabled_telemetry_records_nothing():
    assert not tel.enabled()
    _toy_training_run(steps=2)
    snap = tel.registry().snapshot()
    assert snap["counters"].get("trainer/steps", 0) == 0
    assert "trainer/step_time_s" not in snap["histograms"]
    assert tel.jsonl_path() is None


def test_env_var_enables_telemetry(tmp_path):
    out_dir = tmp_path / "envtel"
    code = (
        "import json\n"
        "import mxnet_tpu as mx\n"
        "assert mx.telemetry.enabled()\n"
        "with mx.telemetry.span('probe'):\n"
        "    pass\n"
        "print(json.dumps({'trace': mx.telemetry.dump()}))\n"
    )
    env = dict(os.environ, MXNET_TELEMETRY="1",
               MXNET_TELEMETRY_DIR=str(out_dir), JAX_PLATFORMS="cpu")
    proc = subprocess.run([sys.executable, "-c", code], cwd=REPO_ROOT,
                          env=env, capture_output=True, text=True,
                          timeout=240)
    assert proc.returncode == 0, proc.stderr[-2000:]
    trace_path = json.loads(proc.stdout.strip().splitlines()[-1])["trace"]
    names = {e["name"]
             for e in json.load(open(trace_path))["traceEvents"]}
    assert "probe" in names


# ----------------------------------------------------- bench watchdog rc
def test_bench_watchdog_exits_nonzero():
    """Regression (ADVICE bench.py:153): a hard bench hang must exit
    nonzero AND still print the error JSON line."""
    code = (
        "import time\n"
        "import bench\n"
        "bench._watchdog(seconds=0.5)\n"
        "time.sleep(30)\n"  # simulated hang: never reaches a result
    )
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    proc = subprocess.run([sys.executable, "-c", code], cwd=REPO_ROOT,
                          env=env, capture_output=True, text=True,
                          timeout=120)
    assert proc.returncode == 1, (proc.returncode, proc.stderr[-500:])
    row = json.loads(proc.stdout.strip().splitlines()[-1])
    assert "watchdog" in row["error"]
    assert row["value"] == 0.0
    # schema carries the telemetry columns even on the error path
    assert "step_time_p50" in row and "hbm_peak_bytes" in row


def test_bench_watchdog_cancelled_on_success():
    """main() completing normally cancels the timer: no late os._exit."""
    code = (
        "import bench\n"
        "t = bench._watchdog(seconds=0.3)\n"
        "t.cancel()\n"
        "import time; time.sleep(0.6)\n"
        "print('clean')\n"
    )
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    proc = subprocess.run([sys.executable, "-c", code], cwd=REPO_ROOT,
                          env=env, capture_output=True, text=True,
                          timeout=120)
    assert proc.returncode == 0, proc.stderr[-500:]
    assert "clean" in proc.stdout


# ------------------------------------------------------------ CLI report
def test_telemetry_report_cli(tmp_path):
    tel.enable(watchdog=False)
    with tel.span("cli.span"):
        pass
    tel.instant("cli.marker", {"step": 1})
    tel.dump()
    jsonl = tel.jsonl_path()
    sys.path.insert(0, os.path.join(REPO_ROOT, "tools"))
    try:
        import telemetry_report
    finally:
        sys.path.pop(0)
    # file mode
    assert telemetry_report.main([jsonl]) == 0
    # directory mode (picks up events.jsonl + report.json)
    assert telemetry_report.main([os.path.dirname(jsonl)]) == 0
    spans, instants = telemetry_report.summarize(
        telemetry_report.load_events(jsonl))
    assert "cli.span" in spans
    assert any(e["name"] == "cli.marker" for e in instants)
    out = telemetry_report.format_spans(spans)
    assert "cli.span" in out
