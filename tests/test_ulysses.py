"""Ulysses (all-to-all) sequence-parallel attention on the 8-device
virtual CPU mesh: must match the single-device flash kernel exactly —
same math, one all_to_all pair instead of the K/V ring."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

import mxnet_tpu as mx
from mxnet_tpu.ops.pallas.flash_attention import flash_attention
from mxnet_tpu.parallel import make_mesh
from mxnet_tpu.parallel.ulysses import ulysses_attention

N_DEV = 8


def _qkv(B=2, H=8, S=64, D=8, seed=0):
    rng = np.random.RandomState(seed)
    mk = lambda: jnp.asarray(rng.randn(B, H, S, D).astype(np.float32))  # noqa: E731
    return mk(), mk(), mk()


@pytest.fixture(scope="module")
def seq_mesh():
    return make_mesh({"seq": N_DEV})


@pytest.fixture(scope="module")
def dp_sp_mesh():
    return make_mesh({"data": 2, "seq": 4})


class TestUlyssesForward:
    def test_matches_single_device(self, seq_mesh):
        q, k, v = _qkv()
        ref = flash_attention(q, k, v, None, causal=False, sm_scale=0.25)
        out = ulysses_attention(q, k, v, seq_mesh, "seq", sm_scale=0.25)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   rtol=2e-4, atol=2e-5)

    def test_causal_matches(self, seq_mesh):
        q, k, v = _qkv(seed=1)
        ref = flash_attention(q, k, v, None, causal=True, sm_scale=0.25)
        out = ulysses_attention(q, k, v, seq_mesh, "seq", causal=True,
                                sm_scale=0.25)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   rtol=2e-4, atol=2e-5)

    def test_dp_sp_mesh(self, dp_sp_mesh):
        q, k, v = _qkv(B=4, H=4, seed=2)
        ref = flash_attention(q, k, v, None, causal=False, sm_scale=0.25)
        out = ulysses_attention(q, k, v, dp_sp_mesh, "seq", sm_scale=0.25)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   rtol=2e-4, atol=2e-5)

    def test_head_divisibility_enforced(self, seq_mesh):
        q, k, v = _qkv(H=4)  # 4 heads over 8 devices
        with pytest.raises(mx.base.MXNetError, match="ring attention"):
            ulysses_attention(q, k, v, seq_mesh, "seq")


class TestUlyssesBackward:
    def test_grads_match_single_device(self, seq_mesh):
        q, k, v = _qkv(seed=3)
        dy = jnp.asarray(
            np.random.RandomState(9).randn(*q.shape).astype(np.float32)
        )

        def loss_sp(q, k, v):
            return (ulysses_attention(q, k, v, seq_mesh, "seq",
                                      sm_scale=0.25) * dy).sum()

        def loss_ref(q, k, v):
            return (flash_attention(q, k, v, None, causal=False,
                                    sm_scale=0.25) * dy).sum()

        gs = jax.grad(loss_sp, argnums=(0, 1, 2))(q, k, v)
        gr = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
        for a, r, name in zip(gs, gr, "qkv"):
            np.testing.assert_allclose(np.asarray(a), np.asarray(r),
                                       rtol=5e-4, atol=5e-5, err_msg=name)


class TestAttentionLayerUlysses:
    def test_mha_seq_mode_ulysses_trains(self, dp_sp_mesh):
        from mxnet_tpu import gluon, nd, optimizer as opt, parallel
        from mxnet_tpu.parallel import PartitionSpec as P, TrainStep

        S, units, heads = 32, 32, 4
        net = gluon.nn.HybridSequential()
        with net.name_scope():
            net.add(gluon.nn.MultiHeadAttention(
                units, heads, causal=True, ring_axis="seq",
                seq_mode="ulysses",
            ))
            net.add(gluon.nn.Dense(8, flatten=False))
        net.initialize()
        net._probe_shapes(nd.zeros((2, S, units)))
        ce = gluon.loss.SoftmaxCrossEntropyLoss()

        class _L:
            def __call__(self, out, label):
                return ce(out.reshape(-1, 8), label.reshape(-1))

        step = TrainStep(net, _L(), opt.SGD(learning_rate=0.1),
                         mesh=dp_sp_mesh, data_spec=P("data", "seq"))
        rng = np.random.RandomState(0)
        x = nd.array(rng.randn(4, S, units).astype(np.float32))
        y = nd.array(rng.randint(0, 8, (4, S)), dtype="int32")
        l1 = float(step(x, y).asscalar())
        l2 = float(step(x, y).asscalar())
        assert np.isfinite(l1) and np.isfinite(l2) and l2 < l1
