"""Loss + metric tests vs NumPy references (reference strategy:
tests/python/unittest/test_loss.py, test_metric.py [unverified])."""

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import autograd, gluon, metric
from mxnet_tpu.ndarray import array as nd


# ------------------------------------------------------------------- losses
def test_l2_loss():
    pred = nd(np.array([[1.0, 2.0], [3.0, 4.0]]))
    label = nd(np.array([[1.5, 2.0], [3.0, 3.0]]))
    loss = gluon.loss.L2Loss()(pred, label).asnumpy()
    np.testing.assert_allclose(loss, [0.0625, 0.25], rtol=1e-6)


def test_l1_loss():
    pred = nd(np.array([[1.0, 2.0]]))
    label = nd(np.array([[2.0, 4.0]]))
    loss = gluon.loss.L1Loss()(pred, label).asnumpy()
    np.testing.assert_allclose(loss, [1.5], rtol=1e-6)


def test_softmax_ce_loss_sparse():
    logits = np.random.randn(4, 5).astype("float32")
    labels = np.array([0, 2, 1, 4])
    loss = gluon.loss.SoftmaxCrossEntropyLoss()(nd(logits), nd(labels)).asnumpy()
    p = np.exp(logits) / np.exp(logits).sum(-1, keepdims=True)
    expected = -np.log(p[np.arange(4), labels])
    np.testing.assert_allclose(loss, expected, rtol=1e-4)


def test_softmax_ce_loss_dense_label():
    logits = np.random.randn(3, 4).astype("float32")
    onehot = np.eye(4, dtype="float32")[[1, 2, 0]]
    l_sparse = gluon.loss.SoftmaxCrossEntropyLoss()(
        nd(logits), nd(np.array([1, 2, 0]))
    ).asnumpy()
    l_dense = gluon.loss.SoftmaxCrossEntropyLoss(sparse_label=False)(
        nd(logits), nd(onehot)
    ).asnumpy()
    np.testing.assert_allclose(l_sparse, l_dense, rtol=1e-5)


def test_sigmoid_bce_loss():
    pred = np.random.randn(4, 3).astype("float32")
    label = (np.random.rand(4, 3) > 0.5).astype("float32")
    loss = gluon.loss.SigmoidBCELoss()(nd(pred), nd(label)).asnumpy()
    x, z = pred, label
    expected = (np.maximum(x, 0) - x * z + np.log1p(np.exp(-np.abs(x)))).mean(-1)
    np.testing.assert_allclose(loss, expected, rtol=1e-4)


def test_kl_div_loss():
    logp = np.log(np.array([[0.25, 0.25, 0.5]], dtype="float32"))
    label = np.array([[0.25, 0.25, 0.5]], dtype="float32")
    loss = gluon.loss.KLDivLoss()(nd(logp), nd(label)).asnumpy()
    np.testing.assert_allclose(loss, [0.0], atol=1e-6)


def test_huber_loss():
    pred = nd(np.array([[0.0]]))
    label = nd(np.array([[2.0]]))
    loss = gluon.loss.HuberLoss(rho=1.0)(pred, label).asnumpy()
    np.testing.assert_allclose(loss, [1.5], rtol=1e-6)  # 2 - 0.5*1


def test_hinge_loss():
    pred = nd(np.array([[0.5], [2.0]]))
    label = nd(np.array([[1.0], [1.0]]))
    loss = gluon.loss.HingeLoss()(pred, label).asnumpy()
    np.testing.assert_allclose(loss, [0.5, 0.0], rtol=1e-6)


def test_triplet_loss():
    a = nd(np.zeros((2, 3), dtype="float32"))
    p = nd(np.zeros((2, 3), dtype="float32"))
    n = nd(np.ones((2, 3), dtype="float32"))
    loss = gluon.loss.TripletLoss(margin=1.0)(a, p, n).asnumpy()
    np.testing.assert_allclose(loss, [0.0, 0.0])  # dist to neg=3 > margin


def test_ctc_loss_simple():
    # single frame, single label: loss = -log P(label)
    T, N, C, L = 4, 2, 5, 2
    logits = np.random.randn(N, T, C).astype("float32")
    labels = np.array([[1, 2], [3, 4]], dtype="float32")
    loss = gluon.loss.CTCLoss()(nd(logits), nd(labels)).asnumpy()
    assert loss.shape == (N,)
    assert (loss > 0).all()


def test_loss_gradients_flow():
    net_pred = nd(np.random.randn(4, 3).astype("float32"))
    net_pred.attach_grad()
    label = nd(np.array([0, 1, 2, 0]))
    with autograd.record():
        L = gluon.loss.SoftmaxCrossEntropyLoss()(net_pred, label)
    L.backward()
    assert not np.allclose(net_pred.grad.asnumpy(), 0)


# ------------------------------------------------------------------ metrics
def test_accuracy():
    acc = metric.Accuracy()
    pred = nd(np.array([[0.3, 0.7], [0.9, 0.1], [0.4, 0.6]]))
    label = nd(np.array([1, 0, 0]))
    acc.update([label], [pred])
    assert acc.get() == ("accuracy", pytest.approx(2.0 / 3))


def test_topk_accuracy():
    topk = metric.TopKAccuracy(top_k=2)
    pred = nd(np.array([[0.1, 0.2, 0.7], [0.6, 0.3, 0.1]]))
    label = nd(np.array([1, 2]))
    topk.update([label], [pred])
    name, val = topk.get()
    assert val == pytest.approx(0.5)


def test_mse_rmse_mae():
    label = nd(np.array([1.0, 2.0]))
    pred = nd(np.array([1.5, 2.5]))
    for m, expected in [(metric.MSE(), 0.25), (metric.RMSE(), 0.5),
                        (metric.MAE(), 0.5)]:
        m.update([label], [pred])
        assert m.get()[1] == pytest.approx(expected)


def test_f1():
    f1 = metric.F1()
    pred = nd(np.array([[0.2, 0.8], [0.8, 0.2], [0.3, 0.7]]))
    label = nd(np.array([1, 0, 0]))
    f1.update([label], [pred])
    # tp=1 fp=1 fn=0 -> p=0.5 r=1 -> f1=2/3
    assert f1.get()[1] == pytest.approx(2.0 / 3)


def test_perplexity():
    ppl = metric.Perplexity()
    pred = nd(np.array([[0.5, 0.5], [0.9, 0.1]]))
    label = nd(np.array([0, 0]))
    ppl.update([label], [pred])
    expected = np.exp(-(np.log(0.5) + np.log(0.9)) / 2)
    assert ppl.get()[1] == pytest.approx(expected, rel=1e-5)


def test_composite_and_create():
    comp = metric.create(["acc", "mse"])
    assert isinstance(comp, metric.CompositeEvalMetric)
    pred = nd(np.array([[0.0, 1.0]]))
    label = nd(np.array([1]))
    comp.update([label], [pred])
    names, values = comp.get()
    assert "accuracy" in names


def test_custom_metric():
    m = metric.np(lambda label, pred: float((label == pred).mean()))
    m.update(nd(np.array([1.0, 0.0])), nd(np.array([1.0, 1.0])))
    assert m.get()[1] == pytest.approx(0.5)


def test_loss_metric():
    m = metric.Loss()
    m.update(None, nd(np.array([2.0, 4.0])))
    assert m.get()[1] == pytest.approx(3.0)


# -------------------------------------------------------------- initializer
def test_initializers():
    from mxnet_tpu import initializer as init
    from mxnet_tpu.ndarray.ndarray import NDArray
    import jax.numpy as jnp

    arr = NDArray(jnp.zeros((50, 20)))
    init.Xavier()(init.InitDesc("fc_weight"), arr)
    a = arr.asnumpy()
    bound = np.sqrt(3.0 / ((50 + 20) / 2))
    assert abs(a).max() <= bound + 1e-6
    assert a.std() > 0.1 * bound

    init.Constant(3.0)("w_weight", arr)
    np.testing.assert_allclose(arr.asnumpy(), 3.0)

    # suffix dispatch
    init.Xavier()("fc_bias", arr)
    np.testing.assert_allclose(arr.asnumpy(), 0.0)

    mixed = init.Mixed([".*bias", ".*"], [init.One(), init.Zero()])
    mixed("fc_bias", arr)
    np.testing.assert_allclose(arr.asnumpy(), 1.0)


def test_initializer_create_by_name():
    from mxnet_tpu import initializer as init

    assert isinstance(init.create("xavier"), init.Xavier)
    assert isinstance(init.create("normal", sigma=0.5), init.Normal)
    with pytest.raises(mx.MXNetError):
        init.create("bogus_init")
