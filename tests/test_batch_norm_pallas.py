"""Parity: fused Pallas BatchNorm reductions vs the two-pass jnp path.

The round-3 one-pass BN was reverted for catastrophic cancellation at
|mean| >> std; these tests pin the shifted one-pass kernel in exactly that
regime, plus full fwd+bwd parity of the channel-last BatchNorm op with the
flag on/off.
"""

import numpy as np
import jax
import jax.numpy as jnp
import pytest

import mxnet_tpu as mx
from mxnet_tpu.ops import nn as ops_nn
from mxnet_tpu.ops.pallas import batch_norm as pbn


@pytest.mark.parametrize("shape", [(4, 7, 7, 8), (8, 14, 14, 64),
                                   (2, 5, 3, 16), (2, 1, 49, 160),
                                   (16, 3, 3, 600), (64, 2, 2, 2048)])
@pytest.mark.parametrize("mean_scale", [0.0, 200.0])
def test_bn_stats_parity(shape, mean_scale):
    rng = np.random.default_rng(0)
    C = shape[-1]
    x = rng.normal(mean_scale, 0.7, shape).astype(np.float32)
    mean, var = pbn.bn_stats(jnp.asarray(x).reshape(-1, C))
    xr = x.reshape(-1, C)
    np.testing.assert_allclose(np.asarray(mean), xr.mean(0), rtol=0,
                               atol=1e-4 * max(1.0, mean_scale))
    np.testing.assert_allclose(np.asarray(var), xr.var(0), rtol=1e-4,
                               atol=1e-6)


def test_bn_stats_cancellation_regime():
    # mean/std = 2000: E[x^2]-E[x]^2 in f32 is useless here; the shifted
    # kernel must stay at ~1e-4 relative error
    rng = np.random.default_rng(1)
    x = rng.normal(1000.0, 0.5, (8, 16, 16, 8)).astype(np.float32)
    _, var = pbn.bn_stats(jnp.asarray(x).reshape(-1, 8))
    ref = x.reshape(-1, 8).var(0)
    np.testing.assert_allclose(np.asarray(var), ref, rtol=1e-4)


def test_bn_bwd_reduce_parity():
    rng = np.random.default_rng(4)
    for shape in [(4, 7, 7, 8), (8, 6, 6, 64), (2, 3, 3, 300)]:
        C = shape[-1]
        x = rng.normal(2.0, 1.0, shape).astype(np.float32).reshape(-1, C)
        dy = rng.normal(0, 1, shape).astype(np.float32).reshape(-1, C)
        mean = x.mean(0)
        inv = (1.0 / np.sqrt(x.var(0) + 1e-3)).astype(np.float32)
        sd, sdx = pbn.bn_bwd_reduce(jnp.asarray(x), jnp.asarray(dy),
                                    jnp.asarray(mean), jnp.asarray(inv))
        xhat = (x - mean) * inv
        np.testing.assert_allclose(np.asarray(sd), dy.sum(0), rtol=1e-4,
                                   atol=1e-4)
        np.testing.assert_allclose(np.asarray(sdx), (dy * xhat).sum(0),
                                   rtol=1e-4, atol=1e-4)


def test_bn_shifted_onepass_cancellation(monkeypatch):
    """The default jnp mode ('1') must survive the |mean| >> std regime
    that killed the round-3 one-pass."""
    from mxnet_tpu.ops.nn import _bn_stats

    monkeypatch.setenv("MXTPU_FUSED_BN", "1")
    rng = np.random.default_rng(7)
    x = rng.normal(1000.0, 0.5, (8, 16, 16, 8)).astype(np.float32)
    _, var, _, _ = _bn_stats(jnp.asarray(x), -1)
    ref = x.reshape(-1, 8).var(0)
    np.testing.assert_allclose(np.asarray(var), ref, rtol=1e-4)
    # and for channel-first too (the shift works in any layout)
    xc = np.moveaxis(x, -1, 1).copy()
    _, var1, _, _ = _bn_stats(jnp.asarray(xc), 1)
    np.testing.assert_allclose(np.asarray(var1), ref, rtol=1e-4)


@pytest.mark.parametrize("mode", ["1", "pallas"])
@pytest.mark.parametrize("dtype", [np.float32, jnp.bfloat16])
def test_batch_norm_op_fwd_bwd_parity_flag(monkeypatch, dtype, mode):
    """Full op (channel-last axis): shifted-jnp and Pallas modes vs the
    two-pass reference mode ('0'), fwd + grads."""
    rng = np.random.default_rng(2)
    shape = (4, 6, 6, 16)
    x = rng.normal(1.5, 1.0, shape).astype(np.float32)
    g = rng.normal(1.0, 0.1, (16,)).astype(np.float32)
    b = rng.normal(0.0, 0.1, (16,)).astype(np.float32)
    dy = rng.normal(0, 1, shape).astype(np.float32)

    def run():
        def f(x_, g_, b_):
            out, m, v = ops_nn.batch_norm(
                x_, g_, b_, jnp.zeros(16), jnp.ones(16),
                eps=1e-3, fix_gamma=False, training=True, axis=-1)
            return out, (m, v)

        out, vjp, (m, v) = jax.vjp(f, jnp.asarray(x, dtype),
                                   jnp.asarray(g), jnp.asarray(b),
                                   has_aux=True)
        dx, dg, db = vjp(jnp.asarray(dy, dtype))
        return [np.asarray(t, np.float32) for t in (out, m, v, dx, dg, db)]

    monkeypatch.setenv("MXTPU_FUSED_BN", mode)
    fused = run()
    monkeypatch.setenv("MXTPU_FUSED_BN", "0")
    ref = run()
    tol = 1e-5 if dtype == np.float32 else 2e-2
    for a, r, name in zip(fused, ref, ["out", "mean", "var", "dx", "dg", "db"]):
        np.testing.assert_allclose(a, r, rtol=tol, atol=tol,
                                   err_msg=f"mismatch in {name}")


def test_batch_norm_grad_vs_autodiff_reference():
    """Custom-vjp closed-form grads vs jax autodiff of a plain jnp BN.

    (Finite differences are useless here: d sum(BN)/dx is ~0 by
    normalization symmetry, far below f32 FD noise.)"""
    rng = np.random.default_rng(3)
    x = rng.normal(0.5, 1.0, (4, 5, 5, 8)).astype(np.float32)
    g = rng.normal(1, 0.1, (8,)).astype(np.float32)
    b = rng.normal(0, 0.1, (8,)).astype(np.float32)
    w = rng.normal(0, 1, x.shape).astype(np.float32)   # non-degenerate loss

    def ref(x_, g_, b_):
        m = jnp.mean(x_, axis=(0, 1, 2), keepdims=True)
        v = jnp.mean(jnp.square(x_ - m), axis=(0, 1, 2), keepdims=True)
        out = (x_ - m) * jax.lax.rsqrt(v + 1e-3) * g_.reshape(1, 1, 1, -1) \
            + b_.reshape(1, 1, 1, -1)
        return jnp.sum(out * w)

    def mine(x_, g_, b_):
        out, _, _ = ops_nn.batch_norm(
            x_, g_, b_, jnp.zeros(8), jnp.ones(8), eps=1e-3,
            fix_gamma=False, training=True, axis=-1)
        return jnp.sum(out * w)

    ga = jax.grad(ref, argnums=(0, 1, 2))(jnp.asarray(x), jnp.asarray(g),
                                          jnp.asarray(b))
    gm = jax.grad(mine, argnums=(0, 1, 2))(jnp.asarray(x), jnp.asarray(g),
                                           jnp.asarray(b))
    for a, m_, name in zip(ga, gm, ["dx", "dgamma", "dbeta"]):
        np.testing.assert_allclose(np.asarray(m_), np.asarray(a), rtol=1e-4,
                                   atol=1e-4, err_msg=name)


def test_batch_norm_nchw_grad_unchanged():
    """NCHW (axis=1) takes the jnp path and must keep exact round-3
    behavior regardless of the flag."""
    rng = np.random.default_rng(5)
    x = rng.normal(0.5, 1.0, (4, 8, 5, 5)).astype(np.float32)

    def f(x_):
        out, _, _ = ops_nn.batch_norm(
            x_, jnp.ones(8), jnp.zeros(8), jnp.zeros(8), jnp.ones(8),
            eps=1e-3, fix_gamma=False, training=True, axis=1)
        return jnp.sum(out * out)

    g = jax.grad(f)(jnp.asarray(x))
    assert np.isfinite(np.asarray(g)).all()


def test_supports_gate():
    assert pbn.supports(jnp.zeros((4, 7, 7, 8)), 3)
    assert pbn.supports(jnp.zeros((4, 7, 7, 8)), -1)
    assert not pbn.supports(jnp.zeros((4, 8, 7, 7)), 1)   # channel-first
    assert not pbn.supports(jnp.zeros((1, 8)), -1)        # M < 2
