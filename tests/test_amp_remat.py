"""HBM-aware compute: AMP (bf16/fp16) in TrainStep, in-graph loss
scaling with overflow skip, activation rematerialization parity, fused
multi-precision Adam, and memory-guided batch planning.

Contracts locked here:

- remat on/off/policy is a MEMORY choice, never a numerics choice:
  losses are bit-identical across every policy and the per-layer grain;
- bf16 AMP tracks the fp32 loss curve within tolerance on a tiny net;
- an fp16 overflow step is skipped ENTIRELY in-graph: params, moments,
  and the bias-correction clock are untouched, the scale halves, and
  the schedule re-grows after the configured window;
- the host LossScaler implements the documented tolerance-based skip
  accounting (grow / halve / skip sequencing);
- the fused multi-tensor Adam covers the multi-precision (fp32 master +
  fp16 weight) layout and matches the per-param reference path;
- memory_analysis/plan_batch cost hypothetical batches without running.
"""

import os

import numpy as np
import pytest

import jax
import jax.numpy as jnp

import mxnet_tpu as mx
from mxnet_tpu import amp, autograd, gluon, nd, optimizer as opt
from mxnet_tpu.gluon import nn
from mxnet_tpu.ndarray.ndarray import NDArray
from mxnet_tpu.parallel import TrainStep, plan_batch


# --------------------------------------------------------------- helpers
def _tiny_transformer_step(seed=0, **step_kw):
    from mxnet_tpu.gluon.model_zoo.transformer import TransformerModel

    np.random.seed(seed)
    mx.random.seed(seed)
    net = TransformerModel(src_vocab=50, tgt_vocab=50, units=16,
                           hidden_size=32, num_layers=1, num_heads=2,
                           max_length=32, dropout=0.0)
    net.initialize(mx.initializer.Xavier())
    net._probe_shapes(nd.zeros((2, 8), dtype="int32"),
                      nd.zeros((2, 8), dtype="int32"))
    hyb_remat = step_kw.pop("hybridize_remat", None)
    if hyb_remat:
        net.hybridize(active=False, remat=hyb_remat)

    class CE:
        def __call__(self, logits, label):
            x = logits.data.astype(jnp.float32)
            logp = jax.nn.log_softmax(x, axis=-1)
            nll = -jnp.take_along_axis(
                logp, label.data.astype(jnp.int32)[..., None], axis=-1)
            return NDArray(nll.mean())

    return TrainStep(net, CE(), opt.AdamW(learning_rate=1e-3), **step_kw)


def _tok_batch(n=4, s=10, v=50, seed=1):
    rng = np.random.RandomState(seed)
    return (nd.array(rng.randint(0, v, (n, s)), dtype="int32"),
            nd.array(rng.randint(0, v, (n, s)), dtype="int32"),
            nd.array(rng.randint(0, v, (n, s)), dtype="int32"))


def _dense_step(seed=0, **step_kw):
    np.random.seed(seed)
    mx.random.seed(seed)
    net = nn.HybridSequential()
    net.add(nn.Dense(16, flatten=False),
            nn.LayerNorm(in_channels=16),
            nn.Dense(4, flatten=False))
    net.initialize()
    net(nd.zeros((2, 8)))
    return TrainStep(net, gluon.loss.L2Loss(),
                     opt.AdamW(learning_rate=1e-2), **step_kw)


# ---------------------------------------------------------- remat parity
REMAT_POLICIES = [None, "nothing_saveable", "dots_saveable",
                  "dots_with_no_batch_dims_saveable",
                  "names:attn_out,ffn_out"]


def test_remat_policies_bit_identical_losses():
    batch = _tok_batch()
    base = None
    for policy in REMAT_POLICIES:
        step = _tiny_transformer_step(remat=policy)
        losses = [float(step(*batch).asscalar()) for _ in range(3)]
        if base is None:
            base = losses
        else:
            assert losses == base, f"remat={policy} diverged: " \
                f"{losses} vs {base}"


def test_per_layer_remat_bit_identical_losses():
    batch = _tok_batch()
    base = _tiny_transformer_step()
    per_layer = _tiny_transformer_step(hybridize_remat="dots_saveable")
    l0 = [float(base(*batch).asscalar()) for _ in range(3)]
    l1 = [float(per_layer(*batch).asscalar()) for _ in range(3)]
    assert l0 == l1


def test_remat_policy_validation():
    from mxnet_tpu.base import MXNetError

    with pytest.raises(MXNetError):
        _tiny_transformer_step(remat="bogus_policy")


def test_hybridize_remat_arms_only_remat_units():
    from mxnet_tpu.gluon.model_zoo.transformer import TransformerModel

    net = TransformerModel(src_vocab=20, tgt_vocab=20, units=8,
                           hidden_size=16, num_layers=1, num_heads=2,
                           max_length=16, dropout=0.0)
    net.hybridize(active=False, remat="dots_saveable")
    layer = net.encoder.layers._children["0"]
    assert layer._remat_policy == "dots_saveable"
    assert net.encoder._remat_policy is None  # stack is not a unit
    assert net.src_embed._remat_policy is None
    net.hybridize(active=False, remat=False)
    assert layer._remat_policy is None


# -------------------------------------------------------------- bf16 AMP
def test_bf16_amp_tracks_fp32_loss_curve():
    x = nd.array(np.random.RandomState(0).rand(8, 8).astype("float32"))
    y = nd.array(np.random.RandomState(1).rand(8, 4).astype("float32"))
    s32 = _dense_step()
    s16 = _dense_step(amp="bfloat16")
    l32 = [float(s32(x, y).asscalar()) for _ in range(20)]
    l16 = [float(s16(x, y).asscalar()) for _ in range(20)]
    assert l32[-1] < l32[0]  # both actually learn
    assert l16[-1] < l16[0]
    np.testing.assert_allclose(l16, l32, rtol=0.1, atol=5e-3)


def test_amp_masters_stay_fp32_and_norms_pinned():
    s = _dense_step(amp="bfloat16")
    # master values and optimizer state live in f32 regardless of amp
    assert all(v.dtype == jnp.float32 for v in s._train_vals.values())
    # norm params are excluded from the cast set
    ln = [n for n in s._train_vals if "layernorm" in n]
    assert ln and all(n in s._amp_fp32 for n in ln)
    dense = [n for n in s._train_vals if "dense" in n]
    assert dense and all(n not in s._amp_fp32 for n in dense)


def test_amp_and_compute_dtype_are_exclusive():
    from mxnet_tpu.base import MXNetError

    with pytest.raises(MXNetError):
        _dense_step(amp="bfloat16", compute_dtype="bfloat16")
    with pytest.raises(MXNetError):
        _dense_step(amp="int8")


def test_amp_init_sets_trainstep_default():
    try:
        amp.init("bfloat16")
        s = _dense_step()
        assert s._amp == "bfloat16"
    finally:
        amp.reset()
    s2 = _dense_step()
    assert s2._amp is None


def test_mxtpu_amp_env_default():
    os.environ["MXTPU_AMP"] = "bfloat16"
    try:
        assert amp.default_amp() == "bfloat16"
        s = _dense_step()
        assert s._amp == "bfloat16"
    finally:
        del os.environ["MXTPU_AMP"]
    assert amp.default_amp() is None


# ------------------------------------------------- fp16 in-graph scaling
def _scaled_step(**scaler_kw):
    scaler_kw.setdefault("init_scale", 2.0 ** 10)
    scaler_kw.setdefault("scale_window", 3)
    scaler_kw.setdefault("scale_factor", 2.0)
    return _dense_step(amp="float16",
                       loss_scaler=amp.LossScaler(**scaler_kw))


def test_fp16_overflow_skip_leaves_state_untouched():
    s = _scaled_step()
    y = nd.array(np.random.RandomState(1).rand(4, 4).astype("float32"))
    bad = nd.array(np.full((4, 8), 1e30, "float32"))  # inf in f16
    w0 = {n: np.asarray(v) for n, v in s._train_vals.items()}
    o0 = {n: tuple(np.asarray(x) for x in st)
          for n, st in s._opt_state.items()}
    float(s(bad, y).asscalar())
    st = s.scaler_stats()
    assert st["skipped_steps"] == 1
    assert st["loss_scale"] == 512.0  # halved from 1024
    assert int(s._t_dev) == 0  # bias-correction clock untouched
    for n, v in s._train_vals.items():
        np.testing.assert_array_equal(w0[n], np.asarray(v))
    for n, stt in s._opt_state.items():
        for a, b in zip(o0[n], stt):
            np.testing.assert_array_equal(a, np.asarray(b))


def test_fp16_scale_regrows_after_window():
    s = _scaled_step()
    x = nd.array(np.random.RandomState(0).rand(4, 8).astype("float32"))
    y = nd.array(np.random.RandomState(1).rand(4, 4).astype("float32"))
    bad = nd.array(np.full((4, 8), 1e30, "float32"))
    float(s(bad, y).asscalar())
    assert s.loss_scale == 512.0
    w_skip = {n: np.asarray(v) for n, v in s._train_vals.items()}
    for i in range(3):  # scale_window=3 clean steps
        float(s(x, y).asscalar())
    st = s.scaler_stats()
    assert st["loss_scale"] == 1024.0  # doubled back
    assert int(s._t_dev) == 3  # only clean steps advance t
    assert any((np.asarray(v) != w_skip[n]).any()
               for n, v in s._train_vals.items())


def test_fp16_scaler_state_roundtrips_through_state_dict():
    s = _scaled_step()
    x = nd.array(np.random.RandomState(0).rand(4, 8).astype("float32"))
    y = nd.array(np.random.RandomState(1).rand(4, 4).astype("float32"))
    float(s(x, y).asscalar())
    sd = s.state_dict()
    assert "scaler" in sd
    s2 = _scaled_step()
    s2.load_state_dict(sd)
    assert s2.scaler_stats() == s.scaler_stats()


# ------------------------------------------------------ LossScaler (host)
def test_loss_scaler_grows_after_window():
    ls = amp.LossScaler(init_scale=8.0, scale_factor=2.0, scale_window=4)
    for _ in range(3):
        ls.update_scale(False)
    assert ls.loss_scale == 8.0
    ls.update_scale(False)
    assert ls.loss_scale == 16.0  # 4th clean step doubles
    assert ls.stats()["unskipped_streak"] == 0


def test_loss_scaler_zero_tolerance_halves_every_overflow():
    ls = amp.LossScaler(init_scale=8.0, scale_factor=2.0, scale_window=10,
                        tolerance=0.0)
    ls.update_scale(True)
    assert ls.loss_scale == 4.0
    ls.update_scale(True)
    assert ls.loss_scale == 2.0
    assert ls.total_skipped == 2


def test_loss_scaler_tolerance_absorbs_rare_overflow():
    # one overflow in 100 steps at tolerance 5%: skip but DON'T halve
    ls = amp.LossScaler(init_scale=8.0, scale_factor=2.0,
                        scale_window=1000, tolerance=0.05)
    for _ in range(99):
        ls.update_scale(False)
    ls.update_scale(True)
    assert ls.total_skipped == 1
    assert ls.loss_scale == 8.0  # 1/100 = 1% < 5% tolerance
    # a sustained burst of overflows crosses the 5% rate and halves
    while ls.loss_scale == 8.0:
        ls.update_scale(True)
        assert ls.stats()["steps"] < 150, "tolerance never tripped"
    assert ls.loss_scale == 4.0
    # ...exactly once: the rate accounting reset at the rescale
    ls.update_scale(False)
    assert ls.loss_scale == 4.0


def test_loss_scaler_floors_at_one():
    ls = amp.LossScaler(init_scale=2.0, scale_factor=4.0, tolerance=0.0)
    ls.update_scale(True)
    assert ls.loss_scale == 1.0
    ls.update_scale(True)
    assert ls.loss_scale == 1.0


def test_loss_scaler_grow_resets_after_overflow():
    # the clean-step streak resets on overflow: no growth until a FULL
    # window of consecutive clean steps follows
    ls = amp.LossScaler(init_scale=8.0, scale_factor=2.0, scale_window=3,
                        tolerance=0.0)
    ls.update_scale(False)
    ls.update_scale(False)
    ls.update_scale(True)  # halve, streak resets
    assert ls.loss_scale == 4.0
    ls.update_scale(False)
    ls.update_scale(False)
    assert ls.loss_scale == 4.0
    ls.update_scale(False)
    assert ls.loss_scale == 8.0


# ----------------------------------------------- fused multi-precision Adam
@pytest.mark.parametrize("optimizer", ["adam", "adamw"])
def test_fused_adam_multi_precision_matches_per_param(optimizer):
    def run(eager_jit):
        os.environ["MXTPU_EAGER_JIT"] = eager_jit
        try:
            np.random.seed(0)
            mx.random.seed(0)
            net = nn.Dense(4, in_units=8)
            net.cast("float16")
            net.initialize(mx.initializer.Constant(0.5))
            cls = opt.Adam if optimizer == "adam" else opt.AdamW
            tr = gluon.Trainer(net.collect_params(),
                               cls(learning_rate=1e-2,
                                   multi_precision=True))
            x = nd.array(np.random.RandomState(0).rand(4, 8)
                         .astype("float16"))
            for _ in range(3):
                with autograd.record():
                    y = net(x)
                    loss = (y * y).mean()
                loss.backward()
                tr.step(1)
            ws = [np.asarray(p.data().data, dtype="float32")
                  for _, p in sorted(net.collect_params().items())]
            return ws, tr
        finally:
            os.environ.pop("MXTPU_EAGER_JIT", None)

    w_fused, tr = run("1")
    # the fused path must actually have engaged on the mp layout
    st = tr._updaters[0].states[0]
    assert isinstance(st, tuple) and isinstance(st[0], tuple), \
        "expected multi-precision ((m, v), master) state"
    w_ref, _ = run("0")
    for a, b in zip(w_fused, w_ref):
        np.testing.assert_array_equal(a, b)
    # weights stayed fp16 on the param (master is separate)
    assert all(p.data().dtype == np.float16 for p in tr._params)


# -------------------------------------------------- memory-guided planning
def test_memory_analysis_reports_and_scales_with_batch():
    s = _dense_step()

    def sig(bs):
        return (((bs, 8), "float32"), ((bs, 4), "float32"))

    ma4 = s.memory_analysis(sig(4))
    ma64 = s.memory_analysis(sig(64))
    for k in ("argument_bytes", "output_bytes", "temp_bytes",
              "peak_bytes_estimate"):
        assert ma4[k] >= 0
    assert ma64["peak_bytes_estimate"] > ma4["peak_bytes_estimate"]


def test_memory_analysis_requires_call_or_signature():
    from mxnet_tpu.base import MXNetError

    s = _dense_step()
    with pytest.raises(MXNetError):
        s.memory_analysis()
    x = nd.array(np.random.rand(4, 8).astype("float32"))
    y = nd.array(np.random.rand(4, 4).astype("float32"))
    s(x, y)
    assert s.memory_analysis()["peak_bytes_estimate"] > 0


def test_plan_batch_finds_largest_fitting_batch():
    s = _dense_step()

    def sig(bs):
        return (((bs, 8), "float32"), ((bs, 4), "float32"))

    budget = s.memory_analysis(sig(16))["peak_bytes_estimate"]
    b, peak = plan_batch(s, sig, budget, start=2, max_batch=256)
    assert b >= 16
    assert peak <= budget
    # and one past the answer must NOT fit
    assert s.memory_analysis(sig(b + 1))["peak_bytes_estimate"] > budget


def test_plan_batch_returns_zero_when_nothing_fits():
    s = _dense_step()

    def sig(bs):
        return (((bs, 8), "float32"), ((bs, 4), "float32"))

    b, peak = plan_batch(s, sig, budget_bytes=16, start=2)
    assert (b, peak) == (0, None)


def test_hbm_budget_env_headroom(monkeypatch):
    from mxnet_tpu.parallel import hbm_budget_bytes

    monkeypatch.setenv("MXTPU_HBM_BYTES", "1000000")
    monkeypatch.setenv("MXTPU_HBM_HEADROOM", "0.8")
    assert hbm_budget_bytes() == 800000
    monkeypatch.setenv("MXTPU_HBM_HEADROOM", "250000")  # absolute reserve
    assert hbm_budget_bytes() == 750000


def test_telemetry_reports_amp_and_remat_fields():
    from mxnet_tpu import telemetry as tel

    _tiny_transformer_step(remat="dots_saveable", amp="bfloat16")
    rep = tel.report()
    assert rep["amp_dtype"] == "bfloat16"
    assert rep["remat_policy"] == "dots_saveable"
    assert "hbm_headroom_bytes" in rep
