"""Sharding / TrainStep / dryrun tests on the 8-device virtual CPU mesh
(reference strategy: distributed behavior tested in-process, SURVEY.md §4)."""

import numpy as np
import pytest

import jax

import mxnet_tpu as mx
from mxnet_tpu import autograd, gluon, optimizer as opt
from mxnet_tpu.gluon import nn
from mxnet_tpu import parallel
from mxnet_tpu.parallel import PartitionSpec as P


def test_make_mesh_default():
    mesh = parallel.make_mesh()
    assert mesh.devices.size == 8
    assert mesh.axis_names == ("data",)


def test_make_mesh_2d():
    mesh = parallel.make_mesh({"data": 4, "model": 2})
    assert mesh.axis_names == ("data", "model")
    with pytest.raises(mx.MXNetError):
        parallel.make_mesh({"data": 3})


def test_shard_and_replicate():
    mesh = parallel.make_mesh()
    with parallel.mesh_scope(mesh):
        x = mx.nd.array(np.arange(16.0).reshape(8, 2))
        xs = parallel.shard(x, P("data"))
        assert len(xs.data.sharding.device_set) == 8
        xr = parallel.replicate(x)
        np.testing.assert_allclose(xr.asnumpy(), x.asnumpy())


def test_trainstep_matches_trainer():
    """Fused sharded step must produce the same weights as the per-param
    Trainer path (same seed, deterministic data, no dropout)."""
    np.random.seed(0)
    x = np.random.randn(16, 8).astype("float32")
    y = np.random.randn(16, 1).astype("float32")

    def build():
        mx.random.seed(7)
        net = nn.HybridSequential()
        with net.name_scope():
            net.add(nn.Dense(16, activation="relu"), nn.Dense(1))
        net.initialize()
        net(mx.nd.array(x))  # materialize
        return net

    # reference: eager Trainer path
    net_a = build()
    trainer = gluon.Trainer(net_a.collect_params(), "sgd",
                            {"learning_rate": 0.05, "momentum": 0.9})
    loss_fn = gluon.loss.L2Loss()
    for _ in range(5):
        with autograd.record():
            L = loss_fn(net_a(mx.nd.array(x)), mx.nd.array(y))
        L.backward()
        # step(16): rescale 1/16 turns the tape's per-sample grad SUM into
        # the mean — matching TrainStep's mean-loss objective
        trainer.step(16)

    # fused path (rescale_grad matches: L2Loss.mean over batch == step loss)
    net_b = build()
    step = parallel.TrainStep(
        net_b, loss_fn, opt.SGD(learning_rate=0.05, momentum=0.9)
    )
    for _ in range(5):
        step(mx.nd.array(x), mx.nd.array(y))
    step.sync_params()

    pa = {k.split("dense")[-1]: v for k, v in net_a.collect_params().items()}
    pb = {k.split("dense")[-1]: v for k, v in net_b.collect_params().items()}
    for k in pa:
        np.testing.assert_allclose(
            pa[k].data().asnumpy(), pb[k].data().asnumpy(), rtol=2e-4,
            atol=1e-5,
        )


def test_trainstep_data_parallel_mesh():
    mesh = parallel.make_mesh({"data": 8})
    net = nn.HybridSequential()
    with net.name_scope():
        net.add(nn.Dense(32, activation="relu"), nn.Dense(4))
    net.initialize()
    net(mx.nd.ones((8, 16)))
    step = parallel.TrainStep(
        net, gluon.loss.SoftmaxCrossEntropyLoss(),
        opt.Adam(learning_rate=1e-3), mesh=mesh, data_spec=P("data"),
    )
    x = mx.nd.array(np.random.randn(16, 16).astype("float32"))
    y = mx.nd.array(np.random.randint(0, 4, 16))
    l1 = float(step(x, y).asscalar())
    l2 = float(step(x, y).asscalar())
    assert np.isfinite(l1) and np.isfinite(l2)
    assert l2 < l1  # learning


def test_trainstep_tensor_parallel_rules():
    mesh = parallel.make_mesh({"data": 2, "model": 4})
    net = nn.HybridSequential()
    with net.name_scope():
        net.add(nn.Dense(64, activation="relu", prefix="up_"),
                nn.Dense(8, prefix="down_"))
    net.initialize()
    net(mx.nd.ones((4, 16)))
    step = parallel.TrainStep(
        net, gluon.loss.SoftmaxCrossEntropyLoss(),
        opt.SGD(learning_rate=0.1), mesh=mesh, data_spec=P("data"),
        param_rules=[
            (r"up_weight$", P("model", None)),
            (r"down_weight$", P(None, "model")),
        ],
    )
    # weight actually sharded over the model axis
    up_w = step._values[[n for n in step._values if n.endswith("up_weight")][0]]
    assert len(up_w.sharding.device_set) == 8
    x = mx.nd.array(np.random.randn(8, 16).astype("float32"))
    y = mx.nd.array(np.random.randint(0, 8, 8))
    loss = step(x, y)
    assert np.isfinite(float(loss.asscalar()))


def test_trainstep_grad_accum():
    np.random.seed(1)
    x = np.random.randn(16, 8).astype("float32")
    y = np.random.randn(16, 1).astype("float32")

    def build():
        mx.random.seed(3)
        net = nn.Dense(1)
        net.initialize()
        net(mx.nd.array(x))
        return net

    net_a = build()
    step_a = parallel.TrainStep(net_a, gluon.loss.L2Loss(),
                                opt.SGD(learning_rate=0.1))
    step_a(mx.nd.array(x), mx.nd.array(y))
    step_a.sync_params()

    net_b = build()
    step_b = parallel.TrainStep(net_b, gluon.loss.L2Loss(),
                                opt.SGD(learning_rate=0.1), grad_accum=4)
    step_b(mx.nd.array(x), mx.nd.array(y))
    step_b.sync_params()
    np.testing.assert_allclose(
        net_a.weight.data().asnumpy(), net_b.weight.data().asnumpy(),
        rtol=1e-4, atol=1e-6,
    )


def test_graft_entry_single_chip():
    import importlib.util

    spec = importlib.util.spec_from_file_location(
        "__graft_entry__", "/root/repo/__graft_entry__.py"
    )
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    fn, args = mod.entry()
    out = jax.jit(fn)(*args)
    seq, pooled = out
    assert np.isfinite(np.asarray(seq)).all()


def test_graft_entry_dryrun_multichip():
    import importlib.util

    spec = importlib.util.spec_from_file_location(
        "__graft_entry__", "/root/repo/__graft_entry__.py"
    )
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    mod.dryrun_multichip(8)


def test_kvstore_local_push_pull():
    kv = mx.kv.create("local")
    kv.init("w", mx.nd.ones((3,)))
    kv.push("w", [mx.nd.ones((3,)) * 2, mx.nd.ones((3,)) * 3])
    out = mx.nd.zeros((3,))
    kv.pull("w", out)
    np.testing.assert_allclose(out.asnumpy(), 5.0)


def test_kvstore_update_on_kvstore():
    kv = mx.kv.create("device")
    kv.set_optimizer(opt.SGD(learning_rate=0.5))
    kv.init(0, mx.nd.ones((2,)))
    kv.push(0, mx.nd.ones((2,)))
    out = mx.nd.zeros((2,))
    kv.pull(0, out)
    np.testing.assert_allclose(out.asnumpy(), 0.5)  # 1 - 0.5*1


def test_flash_attention_op_namespace():
    q = mx.nd.array(np.random.randn(1, 2, 16, 8).astype("float32"))
    out = mx.nd.flash_attention(q, q, q)
    assert out.shape == (1, 2, 16, 8)
    with autograd.record():
        q.attach_grad()
        o = mx.nd.flash_attention(q, q, q, causal=True)
        o.sum().backward()
    assert q.grad is not None
