"""RNN layer/cell tests (reference: tests/python/unittest/test_gluon_rnn.py
[unverified])."""

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import autograd, gluon
from mxnet_tpu.gluon import rnn


@pytest.mark.parametrize("cls,nstates", [(rnn.LSTM, 2), (rnn.GRU, 1),
                                         (rnn.RNN, 1)])
def test_fused_layer_shapes(cls, nstates):
    layer = cls(8, num_layers=2, bidirectional=True)
    layer.initialize()
    x = mx.nd.array(np.random.randn(5, 3, 4).astype("float32"))  # TNC
    out = layer(x)
    assert out.shape == (5, 3, 16)
    out2, states = layer(x, layer.begin_state(3))
    assert len(states) == nstates
    assert states[0].shape == (4, 3, 8)  # layers*dirs, N, H


def test_lstm_layer_ntc_layout():
    layer = rnn.LSTM(6, layout="NTC")
    layer.initialize()
    x = mx.nd.array(np.random.randn(3, 5, 4).astype("float32"))
    out = layer(x)
    assert out.shape == (3, 5, 6)


def test_lstm_layer_grads():
    layer = rnn.LSTM(8, num_layers=1)
    layer.initialize()
    x = mx.nd.array(np.random.randn(5, 3, 4).astype("float32"))
    with autograd.record():
        loss = layer(x).sum()
    loss.backward()
    g = layer.l0_i2h_weight.grad().asnumpy()
    assert not np.allclose(g, 0)


def test_lstm_cell_matches_fused_single_layer():
    """Cell unroll must equal the fused LSTM layer given the same weights."""
    T, N, I, H = 4, 2, 3, 5
    x = np.random.randn(T, N, I).astype("float32")
    fused = rnn.LSTM(H, input_size=I)
    fused.initialize()
    cell = rnn.LSTMCell(H, input_size=I)
    cell.initialize()
    # copy weights
    cell.i2h_weight.set_data(fused.l0_i2h_weight.data())
    cell.h2h_weight.set_data(fused.l0_h2h_weight.data())
    cell.i2h_bias.set_data(fused.l0_i2h_bias.data())
    cell.h2h_bias.set_data(fused.l0_h2h_bias.data())
    out_fused = fused(mx.nd.array(x)).asnumpy()
    out_cell, _ = cell.unroll(T, mx.nd.array(x), layout="TNC")
    np.testing.assert_allclose(out_fused, out_cell.asnumpy(), rtol=1e-4,
                               atol=1e-5)


def test_gru_cell_unroll_and_grads():
    cell = rnn.GRUCell(8)
    cell.initialize()
    x = mx.nd.array(np.random.randn(3, 7, 4).astype("float32"))
    with autograd.record():
        outs, states = cell.unroll(7, x, layout="NTC")
        loss = outs.sum()
    loss.backward()
    assert outs.shape == (3, 7, 8)
    assert not np.allclose(cell.i2h_weight.grad().asnumpy(), 0)


def test_sequential_and_residual_cells():
    sc = rnn.SequentialRNNCell()
    sc.add(rnn.GRUCell(8))
    sc.add(rnn.ResidualCell(rnn.GRUCell(8)))
    sc.initialize()
    x = mx.nd.array(np.random.randn(3, 5, 4).astype("float32"))
    outs, states = sc.unroll(5, x, layout="NTC")
    assert outs.shape == (3, 5, 8)
    assert len(states) == 2


def test_bidirectional_cell():
    bi = rnn.BidirectionalCell(rnn.GRUCell(6), rnn.GRUCell(6))
    bi.initialize()
    x = mx.nd.array(np.random.randn(3, 7, 4).astype("float32"))
    outs, states = bi.unroll(7, x, layout="NTC")
    assert outs.shape == (3, 7, 12)


def test_rnn_layer_in_hybrid_net():
    net = gluon.nn.HybridSequential()
    with net.name_scope():
        net.add(rnn.LSTM(8, layout="NTC"))
        net.add(gluon.nn.Dense(2))
    net.initialize()
    net.hybridize()
    x = mx.nd.array(np.random.randn(3, 5, 4).astype("float32"))
    out = net(x)
    assert out.shape == (3, 2)
    out2 = net(x)
    np.testing.assert_allclose(out.asnumpy(), out2.asnumpy(), rtol=1e-5)
