"""Optimizer tests: each update rule checked against a NumPy reference
implementation (the strategy the reference used in
tests/python/unittest/test_optimizer.py [unverified])."""

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import optimizer as opt
from mxnet_tpu.ndarray import array as nd


def _setup(shape=(5, 3), seed=0):
    rng = np.random.RandomState(seed)
    w = rng.randn(*shape).astype("float32")
    g = rng.randn(*shape).astype("float32")
    return w, g


def test_sgd_no_momentum():
    w, g = _setup()
    sgd = opt.SGD(learning_rate=0.1, wd=0.0)
    weight, grad = nd(w), nd(g)
    state = sgd.create_state(0, weight)
    sgd.update(0, weight, grad, state)
    np.testing.assert_allclose(weight.asnumpy(), w - 0.1 * g, rtol=1e-6)


def test_sgd_momentum_wd():
    w, g = _setup()
    sgd = opt.SGD(learning_rate=0.1, momentum=0.9, wd=0.01)
    weight, grad = nd(w), nd(g)
    state = sgd.create_state(0, weight)
    mom = np.zeros_like(w)
    for _ in range(3):
        sgd.update(0, weight, grad, state)
        gw = g + 0.01 * w
        mom = 0.9 * mom - 0.1 * gw
        w = w + mom
    np.testing.assert_allclose(weight.asnumpy(), w, rtol=1e-5)


def test_sgd_rescale_and_clip():
    w, g = _setup()
    sgd = opt.SGD(learning_rate=1.0, rescale_grad=0.5, clip_gradient=0.1)
    weight, grad = nd(w), nd(g)
    sgd.update(0, weight, grad, None)
    expected = w - np.clip(g * 0.5, -0.1, 0.1)
    np.testing.assert_allclose(weight.asnumpy(), expected, rtol=1e-6)


def test_adam():
    w, g = _setup()
    adam = opt.Adam(learning_rate=0.01)
    weight, grad = nd(w), nd(g)
    state = adam.create_state(0, weight)
    m = np.zeros_like(w)
    v = np.zeros_like(w)
    for t in range(1, 4):
        adam.update(0, weight, grad, state)
        lr = 0.01 * np.sqrt(1 - 0.999 ** t) / (1 - 0.9 ** t)
        m = 0.9 * m + 0.1 * g
        v = 0.999 * v + 0.001 * g * g
        w = w - lr * m / (np.sqrt(v) + 1e-8)
    np.testing.assert_allclose(weight.asnumpy(), w, rtol=1e-5)


def test_adamw_decoupled_wd():
    w, g = _setup()
    aw = opt.AdamW(learning_rate=0.01, wd=0.1)
    weight, grad = nd(w), nd(g)
    state = aw.create_state(0, weight)
    aw.update(0, weight, grad, state)
    # wd must NOT enter the moment estimates
    m = 0.1 * g
    v = 0.001 * g * g
    lr = 0.01 * np.sqrt(1 - 0.999) / (1 - 0.9)
    expected = w - lr * (m / (np.sqrt(v) + 1e-8) + 0.1 * w)
    np.testing.assert_allclose(weight.asnumpy(), expected, rtol=1e-5)


def test_nag():
    w, g = _setup()
    nag = opt.NAG(learning_rate=0.1, momentum=0.9)
    weight, grad = nd(w), nd(g)
    state = nag.create_state(0, weight)
    nag.update(0, weight, grad, state)
    mom = g  # first step: momentum*0 + grad
    expected = w - 0.1 * (g + 0.9 * mom)
    np.testing.assert_allclose(weight.asnumpy(), expected, rtol=1e-5)


def test_rmsprop():
    w, g = _setup()
    rms = opt.RMSProp(learning_rate=0.01, gamma1=0.9)
    weight, grad = nd(w), nd(g)
    state = rms.create_state(0, weight)
    rms.update(0, weight, grad, state)
    n = 0.1 * g * g
    expected = w - 0.01 * g / np.sqrt(n + 1e-8)
    np.testing.assert_allclose(weight.asnumpy(), expected, rtol=1e-4)


def test_adagrad():
    w, g = _setup()
    ada = opt.AdaGrad(learning_rate=0.1)
    weight, grad = nd(w), nd(g)
    state = ada.create_state(0, weight)
    ada.update(0, weight, grad, state)
    expected = w - 0.1 * g / (np.sqrt(g * g) + 1e-7)
    np.testing.assert_allclose(weight.asnumpy(), expected, rtol=1e-5)


def test_lamb_runs_and_trust_ratio():
    w, g = _setup()
    lamb = opt.LAMB(learning_rate=0.01)
    weight, grad = nd(w), nd(g)
    state = lamb.create_state(0, weight)
    w_before = weight.asnumpy().copy()
    lamb.update(0, weight, grad, state)
    assert not np.allclose(weight.asnumpy(), w_before)


def test_ftrl_sparse_zeroing():
    w, g = _setup()
    ftrl = opt.FTRL(learning_rate=0.1, lamda1=100.0)
    weight, grad = nd(w), nd(g)
    state = ftrl.create_state(0, weight)
    ftrl.update(0, weight, grad, state)
    # enormous l1 forces all coords to zero
    np.testing.assert_allclose(weight.asnumpy(), 0.0)


def test_signum():
    w, g = _setup()
    s = opt.Signum(learning_rate=0.1, momentum=0.0)
    weight, grad = nd(w), nd(g)
    s.update(0, weight, grad, None)
    np.testing.assert_allclose(weight.asnumpy(), w - 0.1 * np.sign(g), rtol=1e-6)


def test_lr_scheduler_factor():
    from mxnet_tpu.optimizer import lr_scheduler

    sched = lr_scheduler.FactorScheduler(step=10, factor=0.5, base_lr=1.0)
    assert sched(1) == 1.0
    assert sched(11) == pytest.approx(0.5)
    assert sched(21) == pytest.approx(0.25)


def test_lr_scheduler_warmup():
    from mxnet_tpu.optimizer import lr_scheduler

    sched = lr_scheduler.PolyScheduler(
        max_update=100, base_lr=1.0, pwr=1, warmup_steps=10
    )
    assert sched(0) == 0.0
    assert sched(5) == pytest.approx(0.5)
    assert sched(10) == pytest.approx(1.0)
    assert sched(100) == pytest.approx(0.0, abs=1e-6)


def test_lr_scheduler_cosine():
    from mxnet_tpu.optimizer import lr_scheduler

    sched = lr_scheduler.CosineScheduler(max_update=100, base_lr=2.0)
    assert sched(0) == pytest.approx(2.0)
    assert sched(50) == pytest.approx(1.0)
    assert sched(100) == pytest.approx(0.0, abs=1e-9)


def test_optimizer_registry_create():
    o = opt.create("adam", learning_rate=0.003)
    assert isinstance(o, opt.Adam)
    assert o.lr == 0.003
    with pytest.raises(mx.MXNetError):
        opt.create("nonexistent_opt")


def test_lr_wd_mult():
    w, g = _setup()
    sgd = opt.SGD(learning_rate=0.1, param_idx2name={0: "fc_weight"})
    sgd.set_lr_mult({"fc_weight": 0.0})
    weight, grad = nd(w), nd(g)
    sgd.update(0, weight, grad, None)
    np.testing.assert_allclose(weight.asnumpy(), w)  # lr_mult 0 freezes


def test_updater_serialization():
    w, g = _setup()
    sgd = opt.SGD(learning_rate=0.1, momentum=0.9)
    upd = opt.get_updater(sgd)
    weight, grad = nd(w), nd(g)
    upd(0, grad, weight)
    blob = upd.get_states()
    upd2 = opt.get_updater(opt.SGD(learning_rate=0.1, momentum=0.9))
    upd2.set_states(blob)
    np.testing.assert_allclose(
        upd.states[0].asnumpy(), upd2.states[0].asnumpy()
    )


def test_multi_precision_fp16():
    w = np.random.randn(4, 4).astype("float16")
    g = np.random.randn(4, 4).astype("float16")
    sgd = opt.SGD(learning_rate=0.1, momentum=0.9, multi_precision=True)
    weight, grad = nd(w, dtype="float16"), nd(g, dtype="float16")
    state = sgd.create_state_multi_precision(0, weight)
    assert state[1].dtype == np.float32  # master copy
    sgd.update_multi_precision(0, weight, grad, state)
    assert weight.dtype == np.float16


def test_rmsprop_centered_gamma1_neq_gamma2():
    # Graves 2013 / reference rmspropalex_update: BOTH n and the mean
    # accumulator g decay with gamma1; gamma2 is only delta's momentum
    w, g = _setup()
    rms = opt.RMSProp(learning_rate=0.01, gamma1=0.95, gamma2=0.8,
                      centered=True)
    weight, grad = nd(w), nd(g)
    state = rms.create_state(0, weight)
    n = np.zeros_like(w)
    gm = np.zeros_like(w)
    delta = np.zeros_like(w)
    for _ in range(3):
        rms.update(0, weight, grad, state)
        n = 0.95 * n + 0.05 * g * g
        gm = 0.95 * gm + 0.05 * g
        delta = 0.8 * delta - 0.01 * g / np.sqrt(n - gm * gm + 1e-8)
        w = w + delta
    assert np.isfinite(w).all()  # n - gm^2 >= 0 by Cauchy-Schwarz here
    np.testing.assert_allclose(weight.asnumpy(), w, rtol=1e-4)


def test_rescale_grad_change_no_retrace_semantics():
    # AMP folds 1/loss_scale into rescale_grad every scale change; the
    # update must honor the new value (dynamic operand, not baked static)
    w, g = _setup()
    sgd = opt.SGD(learning_rate=1.0, momentum=0.0)
    weight, grad = nd(w), nd(g)
    sgd.rescale_grad = 0.5
    sgd.update(0, weight, grad, None)
    expected = w - 0.5 * g
    np.testing.assert_allclose(weight.asnumpy(), expected, rtol=1e-6)
    w2 = weight.asnumpy().copy()
    sgd.rescale_grad = 0.25
    sgd.update(0, weight, grad, None)
    np.testing.assert_allclose(weight.asnumpy(), w2 - 0.25 * g, rtol=1e-6)
