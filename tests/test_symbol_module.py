"""Symbol / Executor / Module surface tests.

Models the reference's ``tests/python/unittest/test_symbol.py``,
``test_executor.py`` and ``test_module.py`` [unverified]: graph
construction + serialization round-trip, InferShape (incl. parameter-shape
rules), Executor forward/backward under each grad_req, and the legacy
``Module.fit`` loop training a LeNet end-to-end on synthetic MNIST-shaped
data to a decreasing loss.
"""

import json

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import autograd, nd, sym
from mxnet_tpu.base import MXNetError
from mxnet_tpu.io import DataBatch, NDArrayIter
from mxnet_tpu.module import BucketingModule, Module


def _softmax_np(x):
    e = np.exp(x - x.max(-1, keepdims=True))
    return e / e.sum(-1, keepdims=True)


# ===================================================================== Symbol
def test_variable_and_list_arguments_order():
    a = sym.var("a")
    b = sym.var("b")
    c = a + b * a
    assert c.list_arguments() == ["a", "b"]


def test_symbol_arithmetic_eval():
    a, b = sym.var("a"), sym.var("b")
    expr = (a + b) * a - b / a + 2.0 - (1.0 - a)
    av = np.array([1.0, 2.0, 4.0], np.float32)
    bv = np.array([2.0, 3.0, 8.0], np.float32)
    (out,) = expr.eval(a=nd.array(av), b=nd.array(bv))
    expected = (av + bv) * av - bv / av + 2.0 - (1.0 - av)
    np.testing.assert_allclose(out.asnumpy(), expected, rtol=1e-6)


def test_symbol_neg_pow():
    a = sym.var("a")
    expr = -(a ** 2.0)
    (out,) = expr.eval(a=nd.array(np.array([2.0, 3.0], np.float32)))
    # rtol covers the TPU's f32 pow approximation (9.000011 on v5e)
    np.testing.assert_allclose(out.asnumpy(), [-4.0, -9.0], rtol=1e-5)


def test_symbol_op_namespace_eval():
    x = sym.var("x")
    y = sym.relu(x)
    (out,) = y.eval(x=nd.array(np.array([-1.0, 0.5], np.float32)))
    np.testing.assert_allclose(out.asnumpy(), [0.0, 0.5])


def test_symbol_rejects_non_symbol_positional():
    x = nd.zeros((2, 2))
    with pytest.raises(TypeError):
        sym.relu(x)


def test_symbol_attrs_and_name():
    x = sym.var("x")
    fc = sym.FullyConnected(x, sym.var("w"), sym.var("b"),
                            num_hidden=7, name="fc1")
    assert fc.name == "fc1"
    assert fc.attr("num_hidden") == 7
    assert fc.list_attr()["num_hidden"] == 7


def test_symbol_getitem_errors():
    x = sym.var("x")
    y = sym.relu(x)
    assert y[0] is y
    with pytest.raises(MXNetError):
        y[1]
    with pytest.raises(MXNetError):
        y["nonexistent_output"]


def test_group_outputs_and_iter():
    a, b = sym.var("a"), sym.var("b")
    g = sym.Group([a + b, a * b])
    outs = g.list_outputs()
    assert len(outs) == 2
    av = nd.array(np.array([2.0], np.float32))
    bv = nd.array(np.array([3.0], np.float32))
    r = g.eval(a=av, b=bv)
    np.testing.assert_allclose(r[0].asnumpy(), [5.0])
    np.testing.assert_allclose(r[1].asnumpy(), [6.0])
    parts = list(g)
    assert len(parts) == 2


def test_get_internals_contains_all_nodes():
    x = sym.var("x")
    y = sym.relu(x + 1.0)
    names = [s.name for s in y.get_internals()._inputs]
    assert "x" in names and y.name in names


def test_infer_shape_simple():
    a = sym.var("a")
    b = sym.var("b")
    c = a + b
    arg_shapes, out_shapes, aux = c.infer_shape(a=(2, 3), b=(2, 3))
    assert out_shapes == [(2, 3)]
    assert arg_shapes == [(2, 3), (2, 3)]


def test_infer_shape_broadcasting():
    a, b = sym.var("a"), sym.var("b")
    _, out_shapes, _ = (a + b).infer_shape(a=(4, 1), b=(1, 5))
    assert out_shapes == [(4, 5)]


def test_infer_shape_failure_raises_mxneterror():
    a, b = sym.var("a"), sym.var("b")
    with pytest.raises(MXNetError):
        sym.dot(a, b).infer_shape(a=(2, 3), b=(2, 3))  # inner dims mismatch


def test_tojson_load_json_round_trip():
    x = sym.var("x")
    w = sym.var("w")
    b = sym.var("b")
    net = sym.Activation(
        sym.FullyConnected(x, w, b, num_hidden=3), act_type="tanh"
    )
    js = net.tojson()
    assert json.loads(js)["nodes"]  # valid JSON with nodes
    net2 = sym.load_json(js)
    assert net2.list_arguments() == net.list_arguments()
    rng = np.random.RandomState(0)
    vals = {
        "x": nd.array(rng.randn(2, 5).astype(np.float32)),
        "w": nd.array(rng.randn(3, 5).astype(np.float32)),
        "b": nd.array(rng.randn(3).astype(np.float32)),
    }
    (o1,) = net.eval(**vals)
    (o2,) = net2.eval(**vals)
    np.testing.assert_allclose(o1.asnumpy(), o2.asnumpy(), rtol=1e-6)


def test_symbol_save_load_file(tmp_path):
    x = sym.var("x")
    y = sym.relu(x * 2.0)
    f = str(tmp_path / "net-symbol.json")
    y.save(f)
    y2 = sym.load(f)
    v = nd.array(np.array([-2.0, 3.0], np.float32))
    np.testing.assert_allclose(
        y.eval(x=v)[0].asnumpy(), y2.eval(x=v)[0].asnumpy()
    )


# =================================================================== Executor
def test_simple_bind_explicit_shapes_forward():
    a, b = sym.var("a"), sym.var("b")
    ex = (a * b).simple_bind(a=(2, 2), b=(2, 2))
    ex.arg_dict["a"]._rebind(nd.ones((2, 2)).data * 3)
    ex.arg_dict["b"]._rebind(nd.ones((2, 2)).data * 4)
    (out,) = ex.forward()
    np.testing.assert_allclose(out.asnumpy(), 12 * np.ones((2, 2)))


def test_simple_bind_infers_param_shapes():
    x = sym.var("data")
    net = sym.FullyConnected(x, sym.var("fc_weight"), sym.var("fc_bias"), num_hidden=6)
    ex = net.simple_bind(data=(4, 10))
    assert ex.arg_dict["fc_weight"].shape == (6, 10)
    assert ex.arg_dict["fc_bias"].shape == (6,)
    (out,) = ex.forward()
    assert out.shape == (4, 6)


def test_simple_bind_conv_param_shapes():
    x = sym.var("data")
    net = sym.Convolution(x, sym.var("w"), sym.var("b"), num_filter=8,
                          kernel=(3, 3), pad=(1, 1))
    ex = net.simple_bind(data=(2, 3, 16, 16))
    assert ex.arg_dict["w"].shape == (8, 3, 3, 3)
    assert ex.arg_dict["b"].shape == (8,)
    (out,) = ex.forward()
    assert out.shape == (2, 8, 16, 16)


def test_simple_bind_missing_shape_raises():
    a, b = sym.var("a"), sym.var("b")
    with pytest.raises(MXNetError):
        (a + b).simple_bind(a=(2, 2))  # b not inferable for broadcast_add


def test_executor_backward_matches_analytic():
    a, b = sym.var("a"), sym.var("b")
    ex = (a * b).simple_bind(a=(3,), b=(3,))
    av = np.array([1.0, 2.0, 3.0], np.float32)
    bv = np.array([4.0, 5.0, 6.0], np.float32)
    ex.forward(is_train=True, a=nd.array(av), b=nd.array(bv))
    ex.backward()
    np.testing.assert_allclose(ex.grad_dict["a"].asnumpy(), bv)
    np.testing.assert_allclose(ex.grad_dict["b"].asnumpy(), av)


def test_executor_backward_out_grads():
    a = sym.var("a")
    ex = (a * 2.0).simple_bind(a=(3,))
    av = np.array([1.0, 2.0, 3.0], np.float32)
    g = np.array([1.0, 10.0, 100.0], np.float32)
    ex.forward(is_train=True, a=nd.array(av))
    ex.backward(nd.array(g))
    np.testing.assert_allclose(ex.grad_dict["a"].asnumpy(), 2.0 * g)


def test_executor_grad_req_add_accumulates():
    a = sym.var("a")
    ex = (a * 3.0).simple_bind(a=(2,), grad_req="add")
    av = nd.array(np.array([1.0, 1.0], np.float32))
    for _ in range(2):
        ex.forward(is_train=True, a=av)
        ex.backward()
    np.testing.assert_allclose(ex.grad_dict["a"].asnumpy(), [6.0, 6.0])


def test_executor_grad_req_null_no_grads():
    a = sym.var("a")
    ex = (a * 3.0).simple_bind(a=(2,), grad_req="null")
    assert ex.grad_dict == {}


def test_executor_backward_without_train_forward_raises():
    a = sym.var("a")
    ex = (a * 3.0).simple_bind(a=(2,))
    ex.forward(is_train=False, a=nd.ones((2,)))
    with pytest.raises(MXNetError):
        ex.backward()


def test_executor_softmax_output_backward():
    """The legacy loss-layer: backward emits softmax - onehot regardless of
    the incoming cotangent (reference SoftmaxOutput semantics)."""
    data = sym.var("data")
    label = sym.var("softmax_label")
    net = sym.SoftmaxOutput(data, label, name="softmax")
    ex = net.simple_bind(data=(4, 5), softmax_label=(4,))
    rng = np.random.RandomState(0)
    dv = rng.randn(4, 5).astype(np.float32)
    lv = rng.randint(0, 5, (4,)).astype(np.float32)
    ex.forward(is_train=True, data=nd.array(dv), softmax_label=nd.array(lv))
    prob = _softmax_np(dv)
    np.testing.assert_allclose(ex.outputs[0].asnumpy(), prob, rtol=1e-5)
    ex.backward()
    onehot = np.eye(5, dtype=np.float32)[lv.astype(int)]
    np.testing.assert_allclose(
        ex.grad_dict["data"].asnumpy(), prob - onehot, rtol=1e-5, atol=1e-6
    )


def test_executor_copy_params_from():
    a, b = sym.var("a"), sym.var("b")
    ex = (a + b).simple_bind(a=(2,), b=(2,))
    ex.copy_params_from({"a": nd.ones((2,)) * 5})
    np.testing.assert_allclose(ex.arg_dict["a"].asnumpy(), [5.0, 5.0])
    with pytest.raises(MXNetError):
        ex.copy_params_from({"zzz": nd.ones((2,))})
    ex.copy_params_from({"zzz": nd.ones((2,))}, allow_extra_params=True)


def test_executor_reshape():
    x = sym.var("data")
    net = sym.FullyConnected(x, sym.var("w"), sym.var("b"), num_hidden=3)
    ex = net.simple_bind(data=(4, 6))
    ex2 = ex.reshape(data=(8, 6))
    assert ex2.arg_dict["data"].shape == (8, 6)
    assert ex2.arg_dict["w"].shape == (3, 6)
    (out,) = ex2.forward()
    assert out.shape == (8, 3)


def test_bind_with_explicit_args():
    a, b = sym.var("a"), sym.var("b")
    av = nd.array(np.array([1.0, 2.0], np.float32))
    bv = nd.array(np.array([3.0, 4.0], np.float32))
    ex = (a * b).bind(args={"a": av, "b": bv})
    (out,) = ex.forward()
    np.testing.assert_allclose(out.asnumpy(), [3.0, 8.0])


# ===================================================================== Module
def _lenet_symbol():
    data = sym.var("data")
    c1 = sym.Convolution(data, sym.var("c1_weight"), sym.var("c1_bias"),
                         num_filter=8, kernel=(3, 3), name="c1")
    a1 = sym.Activation(c1, act_type="tanh")
    p1 = sym.Pooling(a1, pool_type="max", kernel=(2, 2), stride=(2, 2))
    c2 = sym.Convolution(p1, sym.var("c2_weight"), sym.var("c2_bias"),
                         num_filter=16, kernel=(3, 3), name="c2")
    a2 = sym.Activation(c2, act_type="tanh")
    p2 = sym.Pooling(a2, pool_type="max", kernel=(2, 2), stride=(2, 2))
    fl = sym.Flatten(p2)
    f1 = sym.FullyConnected(fl, sym.var("f1_weight"), sym.var("f1_bias"),
                            num_hidden=32, name="f1")
    a3 = sym.Activation(f1, act_type="tanh")
    f2 = sym.FullyConnected(a3, sym.var("f2_weight"), sym.var("f2_bias"),
                            num_hidden=10, name="f2")
    return sym.SoftmaxOutput(f2, sym.var("softmax_label"), name="softmax")


def _synthetic_mnist(n=64, seed=0):
    """Class-dependent blob patterns: learnable by LeNet in a few steps."""
    rng = np.random.RandomState(seed)
    y = rng.randint(0, 10, n)
    x = rng.randn(n, 1, 16, 16).astype(np.float32) * 0.1
    for i, yi in enumerate(y):
        r, c = divmod(yi, 4)
        x[i, 0, 3 * r:3 * r + 4, 3 * c:3 * c + 4] += 1.0
    return x, y.astype(np.float32)


def test_module_bind_init_forward():
    net = _lenet_symbol()
    mod = Module(net)
    mod.bind(data_shapes=[("data", (4, 1, 16, 16))],
             label_shapes=[("softmax_label", (4,))])
    mod.init_params()
    x, y = _synthetic_mnist(4)
    mod.forward(DataBatch([nd.array(x)], [nd.array(y)]), is_train=False)
    out = mod.get_outputs()[0]
    assert out.shape == (4, 10)
    np.testing.assert_allclose(out.asnumpy().sum(-1), np.ones(4), rtol=1e-5)


def test_module_fit_lenet_loss_decreases():
    x, y = _synthetic_mnist(64)
    it = NDArrayIter(x, y, batch_size=16, shuffle=True)
    mod = Module(_lenet_symbol())
    # SoftmaxOutput grads are unnormalized batch sums (reference
    # normalization='null' default), so lr is scaled down accordingly
    mod.fit(it, num_epoch=8, optimizer="sgd",
            optimizer_params=(("learning_rate", 0.03), ("momentum", 0.9)),
            initializer=mx.initializer.Xavier())
    score = mod.score(NDArrayIter(x, y, batch_size=16), "acc")
    acc = dict(score)["accuracy"]
    assert acc > 0.5, f"LeNet did not learn synthetic blobs: acc={acc}"


def test_module_manual_loop_updates_params():
    x, y = _synthetic_mnist(16)
    mod = Module(_lenet_symbol())
    mod.bind(data_shapes=[("data", (16, 1, 16, 16))],
             label_shapes=[("softmax_label", (16,))])
    mod.init_params()
    mod.init_optimizer(optimizer="sgd",
                       optimizer_params=(("learning_rate", 0.1),))
    w_before = mod._exec.arg_dict["f2_weight"].asnumpy().copy()
    batch = DataBatch([nd.array(x)], [nd.array(y)])
    mod.forward(batch, is_train=True)
    mod.backward()
    mod.update()
    assert not np.allclose(mod._exec.arg_dict["f2_weight"].asnumpy(), w_before)


def test_module_fixed_params_not_updated():
    x, y = _synthetic_mnist(16)
    mod = Module(_lenet_symbol(), fixed_param_names=["f2_weight"])
    mod.bind(data_shapes=[("data", (16, 1, 16, 16))],
             label_shapes=[("softmax_label", (16,))])
    mod.init_params()
    mod.init_optimizer()
    w_before = mod._exec.arg_dict["f2_weight"].asnumpy().copy()
    batch = DataBatch([nd.array(x)], [nd.array(y)])
    mod.forward(batch, is_train=True)
    mod.backward()
    mod.update()
    np.testing.assert_allclose(mod._exec.arg_dict["f2_weight"].asnumpy(), w_before)


def test_module_predict_merges_batches():
    x, y = _synthetic_mnist(32)
    mod = Module(_lenet_symbol())
    mod.bind(data_shapes=[("data", (8, 1, 16, 16))],
             label_shapes=[("softmax_label", (8,))])
    mod.init_params()
    preds = mod.predict(NDArrayIter(x, y, batch_size=8))
    assert preds.shape == (32, 10)


def test_module_save_load_checkpoint(tmp_path):
    x, y = _synthetic_mnist(16)
    prefix = str(tmp_path / "lenet")
    mod = Module(_lenet_symbol())
    mod.bind(data_shapes=[("data", (16, 1, 16, 16))],
             label_shapes=[("softmax_label", (16,))])
    mod.init_params()
    batch = DataBatch([nd.array(x)], [nd.array(y)])
    mod.forward(batch, is_train=False)
    ref_out = mod.get_outputs()[0].asnumpy()
    mod.save_checkpoint(prefix, 3)

    symbol, arg_params, aux_params = Module.load_checkpoint(prefix, 3)
    mod2 = Module(symbol)
    mod2.bind(data_shapes=[("data", (16, 1, 16, 16))],
              label_shapes=[("softmax_label", (16,))])
    mod2.init_params(arg_params=arg_params, aux_params=aux_params)
    mod2.forward(batch, is_train=False)
    np.testing.assert_allclose(
        mod2.get_outputs()[0].asnumpy(), ref_out, rtol=1e-5, atol=1e-6
    )


def _bucket_sym_gen(seq_len):
    """Mean-pooled embedding classifier over variable-length sequences."""
    data = sym.var("data")
    emb = sym.Embedding(data, sym.var("emb_weight"), input_dim=20, output_dim=8,
                        name="emb")
    pooled = sym.mean(emb, axis=1)
    fc = sym.FullyConnected(pooled, sym.var("fc_weight"), sym.var("fc_bias"),
                            num_hidden=4, name="fc")
    net = sym.SoftmaxOutput(fc, sym.var("softmax_label"), name="softmax")
    return net, ("data",), ("softmax_label",)


def test_bucketing_module_shares_params_across_buckets():
    rng = np.random.RandomState(0)
    bm = BucketingModule(_bucket_sym_gen, default_bucket_key=10)
    bm.bind(data_shapes=[("data", (4, 10))],
            label_shapes=[("softmax_label", (4,))])
    bm.init_params()
    bm.init_optimizer(optimizer="sgd",
                      optimizer_params=(("learning_rate", 0.1),))

    def run(seq_len):
        x = rng.randint(0, 20, (4, seq_len)).astype(np.float32)
        y = rng.randint(0, 4, (4,)).astype(np.float32)
        b = DataBatch([nd.array(x)], [nd.array(y)], bucket_key=seq_len)
        bm.forward(b, is_train=True)
        out = bm.get_outputs()[0]
        bm.backward()
        bm.update()
        return out

    out10 = run(10)
    assert out10.shape == (4, 4)
    out6 = run(6)  # different bucket; shares (and sees updated) params
    assert out6.shape == (4, 4)
    w_default = bm._modules[10]._exec.arg_dict["fc_weight"]
    w_small = bm._modules[6]._exec.arg_dict["fc_weight"]
    assert w_default is w_small  # same NDArray object: true weight sharing


def test_group_json_round_trip():
    a = sym.var("a")
    g = sym.Group([a * 2.0, a + 1.0])
    g2 = sym.load_json(g.tojson())
    av = nd.array(np.array([3.0], np.float32))
    r = g2.eval(a=av)
    assert len(r) == 2
    np.testing.assert_allclose(r[0].asnumpy(), [6.0])
    np.testing.assert_allclose(r[1].asnumpy(), [4.0])


def test_variable_attrs_json_round_trip():
    v = sym.Variable("x", shape=(2, 3), attr={"lr_mult": "2"})
    v2 = sym.load_json(v.tojson())
    assert v2.attr("lr_mult") == 2
    assert tuple(v2.attr("__shape__")) == (2, 3)


class TestBatchNormAux:
    """BatchNorm moving stats are aux states (reference FMutateInputs
    semantics), not trainable arguments."""

    def test_aux_excluded_from_arguments(self):
        import mxnet_tpu as mx
        x = sym.var("data")
        y = sym.Activation(sym.BatchNorm(x, name="bn"), act_type="relu")
        assert "bn_moving_mean" not in y.list_arguments()
        assert y.list_auxiliary_states() == ["bn_moving_mean",
                                             "bn_moving_var"]

    def test_simple_bind_inits_and_updates_aux(self):
        x = sym.var("data")
        y = sym.BatchNorm(x, name="bn", momentum=0.5)
        ex = y.simple_bind(data=(8, 3))
        np.testing.assert_allclose(ex.aux_dict["bn_moving_var"].asnumpy(),
                                   np.ones(3))
        np.testing.assert_allclose(ex.aux_dict["bn_moving_mean"].asnumpy(),
                                   np.zeros(3))
        rng = np.random.RandomState(0)
        data = (rng.rand(8, 3) * 4 + 2).astype(np.float32)
        ex.forward(is_train=True, data=nd.array(data))
        # moving = 0.5*init + 0.5*batch
        np.testing.assert_allclose(
            ex.aux_dict["bn_moving_mean"].asnumpy(),
            0.5 * data.mean(axis=0), rtol=1e-4,
        )
        np.testing.assert_allclose(
            ex.aux_dict["bn_moving_var"].asnumpy(),
            0.5 * 1.0 + 0.5 * data.var(axis=0), rtol=1e-4,
        )

    def test_train_uses_batch_stats_predict_uses_moving(self):
        x = sym.var("data")
        y = sym.BatchNorm(x, name="bn")
        ex = y.simple_bind(data=(16, 4))
        rng = np.random.RandomState(1)
        data = (rng.rand(16, 4) * 10).astype(np.float32)
        out_train = ex.forward(is_train=True, data=nd.array(data))[0].asnumpy()
        # train mode normalizes with batch stats -> ~zero mean, unit var
        np.testing.assert_allclose(out_train.mean(axis=0), np.zeros(4),
                                   atol=1e-4)
        ex2 = y.simple_bind(data=(16, 4))
        out_pred = ex2.forward(is_train=False,
                               data=nd.array(data))[0].asnumpy()
        # predict mode uses moving stats (mean 0, var 1) -> output ~ data
        np.testing.assert_allclose(out_pred, data, rtol=1e-2, atol=2e-2)

    def test_no_grad_on_aux(self):
        x = sym.var("data")
        y = sym.BatchNorm(sym.FullyConnected(x, num_hidden=4, name="fc"),
                          name="bn")
        ex = y.simple_bind(data=(8, 3))
        ex.forward(is_train=True, data=nd.array(_rand(8, 3)))
        ex.backward()
        assert "bn_moving_mean" not in ex.grad_dict

    def test_inference_bind_without_label(self):
        x = sym.var("data")
        out = sym.SoftmaxOutput(sym.FullyConnected(x, num_hidden=4,
                                                   name="fc"),
                                name="softmax")
        ex = out.simple_bind(grad_req="null", data=(2, 8))
        res = ex.forward(is_train=False, data=nd.array(_rand(2, 8)))
        np.testing.assert_allclose(res[0].asnumpy().sum(axis=1),
                                   np.ones(2), rtol=1e-5)

    def test_deconvolution_no_phantom_bias(self):
        d = sym.Deconvolution(sym.var("data"), kernel=(2, 2), num_filter=4,
                              name="dc")
        assert "dc_bias" not in d.list_arguments()


def _rand(*shape):
    return np.random.RandomState(sum(shape)).rand(*shape).astype(np.float32)


class TestAuxReviewRegressions:
    def test_bind_forwards_aux_states(self):
        x = sym.var("data")
        y = sym.BatchNorm(x, name="bn2")
        aux = {"bn2_moving_mean": nd.array(np.array([1.0, 2.0, 3.0], np.float32)),
               "bn2_moving_var": nd.ones((3,))}
        args = {"data": nd.array(_rand(4, 3)),
                "bn2_gamma": nd.ones((3,)), "bn2_beta": nd.zeros((3,))}
        ex = y.bind(args=args, aux_states=aux, grad_req="null")
        (out,) = ex.forward(is_train=False)
        expect = (args["data"].asnumpy() - np.array([1, 2, 3], np.float32)) \
            / np.sqrt(1.0 + 1e-3)
        np.testing.assert_allclose(out.asnumpy(), expect, rtol=1e-4)

    def test_module_set_params_loads_aux(self):
        x = sym.var("data")
        net = sym.SoftmaxOutput(sym.BatchNorm(
            sym.FullyConnected(x, num_hidden=4, name="fc"), name="bn3"),
            name="softmax")
        mod = Module(net, data_names=("data",), label_names=("softmax_label",))
        mod.bind(data_shapes=[("data", (2, 3))],
                 label_shapes=[("softmax_label", (2,))])
        aux = {"bn3_moving_mean": nd.ones((4,)) * 5,
               "bn3_moving_var": nd.ones((4,)) * 2}
        mod.init_params(aux_params=aux, allow_missing=True)
        np.testing.assert_allclose(
            mod._exec.aux_dict["bn3_moving_mean"].asnumpy(), np.full(4, 5.0)
        )

    def test_explicit_moving_stats_are_plain_args(self):
        mm, mv = sym.var("mm"), sym.var("mv")
        g, b = sym.var("g"), sym.var("b")
        y = sym.BatchNorm(sym.var("data"), g, b, mm, mv, name="bn4")
        assert "mm" in y.list_arguments()
        assert y.list_auxiliary_states() == []
        ex = y.simple_bind(data=(2, 3), g=(3,), b=(3,), mm=(3,), mv=(3,))
        ex.forward(is_train=True, data=nd.array(_rand(2, 3)),
                   g=nd.ones((3,)), b=nd.zeros((3,)),
                   mm=nd.zeros((3,)), mv=nd.ones((3,)))  # must not KeyError

    def test_multi_output_head_backward_single_cotangent(self):
        x = sym.var("data")
        y = sym.BatchNorm(sym.FullyConnected(x, num_hidden=2, name="fc5"),
                          name="bn5")
        ex = y.simple_bind(data=(4, 3))
        outs = ex.forward(is_train=True, data=nd.array(_rand(4, 3)))
        assert len(outs) == 1  # only the declared output surfaces
        ex.backward()  # ones cotangent for ONE output; no mean/var leakage


def test_group_with_batchnorm_member():
    """Group members with multi-output ops contribute first outputs only."""
    x = sym.var("data")
    g = sym.Group([sym.BatchNorm(x, name="bn6"),
                   sym.FullyConnected(x, num_hidden=2, name="fc6")])
    ex = g.simple_bind(data=(4, 3), grad_req="null")
    outs = ex.forward(is_train=False, data=nd.array(_rand(4, 3)))
    assert len(outs) == 2
    assert outs[0].shape == (4, 3) and outs[1].shape == (4, 2)


def test_batchnorm_head_eval_single_output():
    x = sym.var("data")
    y = sym.BatchNorm(x, name="bn7")
    outs = y.eval(data=nd.array(_rand(2, 3)),
                  bn7_gamma=nd.ones((3,)), bn7_beta=nd.zeros((3,)),
                  bn7_moving_mean=nd.zeros((3,)),
                  bn7_moving_var=nd.ones((3,)))
    assert len(outs) == 1  # matches list_outputs()


class TestModuleDataParallel:
    """context=[cpu(0)..cpu(7)] shards batches over a device mesh — the
    reference DataParallelExecutorGroup semantics via GSPMD."""

    def _fit(self, ctxs, seed=0):
        import mxnet_tpu as mx
        mx.random.seed(seed)
        np.random.seed(seed)
        x = sym.var("data")
        net = sym.SoftmaxOutput(
            sym.FullyConnected(x, num_hidden=4, name="fcdp"), name="softmax"
        )
        mod = Module(net, data_names=("data",),
                     label_names=("softmax_label",), context=ctxs)
        rng = np.random.RandomState(0)
        X = rng.rand(64, 8).astype(np.float32)
        y = rng.randint(0, 4, 64).astype(np.float32)
        it = NDArrayIter(X, y, batch_size=16, label_name="softmax_label")
        mod.fit(it, num_epoch=2, optimizer="sgd",
                optimizer_params={"learning_rate": 0.1})
        args, _ = mod.get_params()
        return {k: v.asnumpy() for k, v in args.items()}

    def test_multi_device_matches_single(self):
        import mxnet_tpu as mx

        single = self._fit(None)
        multi = self._fit([mx.cpu(i) for i in range(8)])
        for k in single:
            np.testing.assert_allclose(single[k], multi[k], rtol=1e-4,
                                       atol=1e-5, err_msg=k)

    def test_sharded_input_really_distributed(self):
        import mxnet_tpu as mx

        ctxs = [mx.cpu(i) for i in range(8)]
        x = sym.var("data")
        net = sym.FullyConnected(x, num_hidden=2, name="fcdp2")
        mod = Module(net, data_names=("data",), label_names=())
        mod._context = ctxs
        mod.bind(data_shapes=[("data", (16, 4))], for_training=False)
        sharded = mod._shard(nd.ones((16, 4)))
        assert len(sharded.data.sharding.device_set) == 8
