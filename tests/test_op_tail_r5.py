"""Round-5 op tail: PSROIPooling, ModulatedDeformableConvolution,
linalg_gesvd (nd-level SVD), sample_multinomial (reference:
``src/operator/contrib/psroi_pooling.cc``,
``modulated_deformable_convolution.cc``, ``tensor/la_op.cc``,
``random/multisample_op.cc`` [unverified])."""

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import nd
from mxnet_tpu.test_utils import check_numeric_gradient

rng = np.random.RandomState(0)


# --------------------------------------------------------- PSROIPooling
def test_psroi_pooling_selects_position_channels():
    """Each output bin must read its OWN channel slice: a feature map
    where channel c is constant c makes the expected output exactly the
    channel index of each (k, i, j) bin."""
    ps, gs, K = 3, 3, 2
    C = K * gs * gs
    data = np.broadcast_to(
        np.arange(C, dtype=np.float32)[None, :, None, None],
        (1, C, 12, 12)).copy()
    rois = np.asarray([[0, 0, 0, 11, 11]], np.float32)
    out = nd.contrib.PSROIPooling(
        nd.array(data), nd.array(rois), spatial_scale=1.0,
        output_dim=K, pooled_size=ps).asnumpy()
    assert out.shape == (1, K, ps, ps)
    gy = (np.arange(ps) * gs) // ps
    want = ((np.arange(K)[:, None, None] * gs + gy[None, :, None]) * gs
            + gy[None, None, :]).astype(np.float32)
    np.testing.assert_allclose(out[0], want)


def test_psroi_pooling_averages_bins():
    ps = 2
    C = 1 * ps * ps
    data = rng.rand(2, C, 8, 8).astype(np.float32)
    rois = np.asarray([[1, 0, 0, 7, 7]], np.float32)
    out = nd.contrib.PSROIPooling(
        nd.array(data), nd.array(rois), spatial_scale=1.0,
        output_dim=1, pooled_size=ps).asnumpy()
    # bin (0,0) of the only class reads channel 0, rows 0..3, cols 0..3
    np.testing.assert_allclose(out[0, 0, 0, 0],
                               data[1, 0, 0:4, 0:4].mean(), rtol=1e-5)
    # bin (1,1) reads channel 3, rows 4..7, cols 4..7
    np.testing.assert_allclose(out[0, 0, 1, 1],
                               data[1, 3, 4:8, 4:8].mean(), rtol=1e-5)


def test_psroi_pooling_gradient():
    ps = 2
    data = rng.rand(1, ps * ps, 6, 6).astype(np.float64)
    rois = nd.array(np.asarray([[0, 0, 0, 5, 5]], np.float32))

    def f(d):
        return nd.contrib.PSROIPooling(d, rois, spatial_scale=1.0,
                                       output_dim=1, pooled_size=ps)

    check_numeric_gradient(f, [data], rtol=3e-2, atol=1e-3)


def test_psroi_pooling_bad_channels_raises():
    with pytest.raises(Exception, match="output_dim"):
        nd.contrib.PSROIPooling(
            nd.array(np.zeros((1, 7, 4, 4), np.float32)),
            nd.array(np.asarray([[0, 0, 0, 3, 3]], np.float32)),
            output_dim=2, pooled_size=2)


# ------------------------------------- ModulatedDeformableConvolution
def _mdc_shapes(B=1, C=4, H=6, W=6, O=3, k=3, G=1):
    data = rng.rand(B, C, H, W).astype(np.float32)
    Ho = Wo = H - k + 1
    off = (rng.rand(B, 2 * G * k * k, Ho, Wo).astype(np.float32) - 0.5)
    m = 1.0 / (1.0 + np.exp(-rng.rand(B, G * k * k, Ho, Wo)
                            .astype(np.float32)))
    w = rng.rand(O, C, k, k).astype(np.float32) * 0.2
    return data, off, m, w


def test_modulated_matches_v1_with_unit_mask():
    data, off, m, w = _mdc_shapes()
    ones = np.ones_like(m)
    v2 = nd.contrib.ModulatedDeformableConvolution(
        nd.array(data), nd.array(off), nd.array(ones), nd.array(w),
        kernel=(3, 3), num_filter=3, no_bias=True).asnumpy()
    v1 = nd.contrib.DeformableConvolution(
        nd.array(data), nd.array(off), nd.array(w),
        kernel=(3, 3), num_filter=3, no_bias=True).asnumpy()
    np.testing.assert_allclose(v2, v1, rtol=1e-5, atol=1e-6)


def test_modulated_mask_scales_contributions():
    """mask==0 must zero the sampled columns: output becomes the bias
    (here zero)."""
    data, off, m, w = _mdc_shapes()
    zeros = np.zeros_like(m)
    v2 = nd.contrib.ModulatedDeformableConvolution(
        nd.array(data), nd.array(off), nd.array(zeros), nd.array(w),
        kernel=(3, 3), num_filter=3, no_bias=True).asnumpy()
    np.testing.assert_allclose(v2, 0.0, atol=1e-7)


def test_modulated_gradients():
    data, off, m, w = _mdc_shapes(C=2, O=2, H=5, W=5)

    def f(d, o, mm, ww):
        return nd.contrib.ModulatedDeformableConvolution(
            d, o, mm, ww, kernel=(3, 3), num_filter=2, no_bias=True)

    check_numeric_gradient(
        f, [data.astype(np.float64), off.astype(np.float64),
            m.astype(np.float64), w.astype(np.float64)],
        rtol=3e-2, atol=1e-3)


# --------------------------------------------------------- linalg_gesvd
def test_gesvd_reconstructs():
    A = rng.rand(3, 5).astype(np.float32)
    U, L, V = nd.linalg_gesvd(nd.array(A))
    rec = U.asnumpy() @ np.diag(L.asnumpy()) @ V.asnumpy()
    np.testing.assert_allclose(rec, A, rtol=1e-4, atol=1e-5)
    # V has orthonormal rows
    np.testing.assert_allclose(V.asnumpy() @ V.asnumpy().T, np.eye(3),
                               rtol=1e-4, atol=1e-5)
    # singular values descending, non-negative
    s = L.asnumpy()
    assert (s[:-1] >= s[1:] - 1e-6).all() and (s >= 0).all()


def test_gesvd_gradient():
    A = rng.rand(3, 4).astype(np.float64) + np.eye(3, 4)

    def f(a):
        return nd.linalg_gesvd(a)[1].sum()  # d(sum of singular values)

    check_numeric_gradient(f, [A], rtol=3e-2, atol=1e-3)


def test_svd_alias_resolves():
    from mxnet_tpu.ops import registry

    assert registry.maybe_get("SVD") is not None
    assert registry.maybe_get("SwapAxis") is not None  # round-4 probe fix


# --------------------------------------------------- sample_multinomial
def test_sample_multinomial_statistics():
    mx.random.seed(3)
    probs = nd.array(np.asarray([[0.1, 0.2, 0.7],
                                 [0.8, 0.1, 0.1]], np.float32))
    draws = nd.sample_multinomial(probs, shape=(4000,)).asnumpy()
    assert draws.shape == (2, 4000)
    assert draws.dtype == np.int32
    f0 = (draws[0] == 2).mean()
    f1 = (draws[1] == 0).mean()
    assert abs(f0 - 0.7) < 0.05, f0
    assert abs(f1 - 0.8) < 0.05, f1


def test_sample_multinomial_get_prob():
    mx.random.seed(4)
    probs = nd.array(np.asarray([[0.25, 0.75]], np.float32))
    out, logp = nd.sample_multinomial(probs, shape=(64,), get_prob=True)
    o, lp = out.asnumpy(), logp.asnumpy()
    want = np.where(o == 1, np.log(0.75), np.log(0.25))
    # rtol covers the chip's f32 log (measured 2e-4 rel off vs f64)
    np.testing.assert_allclose(lp, want, rtol=1e-3)


def test_sample_multinomial_scalar_shape():
    mx.random.seed(5)
    probs = nd.array(np.asarray([[0.0, 1.0, 0.0]], np.float32))
    out = nd.sample_multinomial(probs).asnumpy()
    assert out.shape == (1,)
    assert (out == 1).all()
