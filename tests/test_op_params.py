"""Typed op-param schemas (the dmlc::Parameter analogue, ops/params.py)."""

import json

import pytest

import mxnet_tpu as mx
from mxnet_tpu.ops import (P, describe_op, list_documented_ops, op_params,
                           register, schema_to_json, validate_params)


def test_builtin_ops_documented():
    docs = list_documented_ops()
    for name in ("Convolution", "Pooling", "BatchNorm", "Dropout",
                 "_contrib_box_nms", "_contrib_Proposal"):
        assert name in docs, name


def test_describe_and_json_roundtrip():
    d = describe_op("Convolution")
    names = [p["name"] for p in d["params"]]
    assert "kernel" in names and "num_filter" in names
    j = json.loads(schema_to_json("Pooling"))
    pool_type = next(p for p in j["params"] if p["name"] == "pool_type")
    assert pool_type["choices"] == ["max", "avg", "sum", "lp"]
    assert pool_type["default"] == "max"


def test_docstring_gained_parameter_section():
    from mxnet_tpu.ops.registry import get

    doc = get("Convolution").fn.__doc__
    assert "Op Parameters" in doc
    assert "num_filter : int, required" in doc


def test_validate_coerces_string_attrs():
    # symbol-JSON attrs arrive as strings; validation must type them
    out = validate_params("Convolution", {
        "kernel": [3, 3], "num_filter": "16", "no_bias": "True",
        "stride": 2,
    })
    assert out["num_filter"] == 16
    assert out["no_bias"] is True
    assert out["stride"] == (2,)


def test_validate_rejects_bad_values():
    with pytest.raises(ValueError, match="below minimum"):
        validate_params("Convolution", {"kernel": (1, 1), "num_filter": 0})
    with pytest.raises(ValueError, match="not in"):
        validate_params("Pooling", {"pool_type": "median"})
    with pytest.raises(ValueError, match="missing required"):
        validate_params("Convolution", {"stride": 1})
    with pytest.raises(ValueError, match="unknown param"):
        validate_params("Pooling", {"bogus": 1}, allow_unknown=False)


def test_custom_op_schema_via_decorator():
    @op_params(
        P("alpha", "float", default=1.0, low=0.0, doc="scale factor"),
    )
    @register("_test_scaled_copy", namespaces=())
    def _test_scaled_copy(data, alpha=1.0, **kw):
        """Test op."""
        return data * alpha

    d = describe_op("_test_scaled_copy")
    assert d["params"][0]["name"] == "alpha"
    assert validate_params("_test_scaled_copy", {"alpha": "2.5"}) == \
        {"alpha": 2.5}


def test_env_registry_lists_consulted_vars():
    from mxnet_tpu.base import env_str, list_env_registry

    env_str("MXNET_ENGINE_TYPE", "ThreadedEnginePerDevice")
    reg = list_env_registry()
    assert "MXNET_ENGINE_TYPE" in reg
