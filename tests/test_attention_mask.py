"""Padding-mask (valid_length) support through the attention stack.

Reference semantics: softmax ``use_length`` + the contrib transformer ops'
key-padding masks (``src/operator/nn/softmax.cc``,
``src/operator/contrib/transformer.cc`` [unverified]) — keys at positions
>= valid_length[b] must not contribute to attention for batch row b.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

import mxnet_tpu as mx
from mxnet_tpu import autograd, gluon, nd
from mxnet_tpu.ops.pallas import flash_attention


def _naive_attention(q, k, v, valid_length=None, causal=False, sm_scale=None):
    """Dense O(S^2) reference in f32."""
    if sm_scale is None:
        sm_scale = 1.0 / np.sqrt(q.shape[-1])
    s = jnp.einsum("bhqd,bhkd->bhqk", q.astype(jnp.float32),
                   k.astype(jnp.float32)) * sm_scale
    Sq, Sk = q.shape[2], k.shape[2]
    mask = jnp.ones((q.shape[0], 1, Sq, Sk), bool)
    if valid_length is not None:
        mask = mask & (jnp.arange(Sk)[None, None, None, :]
                       < valid_length[:, None, None, None])
    if causal:
        mask = mask & (jnp.arange(Sk)[None, None, None, :]
                       <= jnp.arange(Sq)[None, None, :, None])
    s = jnp.where(mask, s, -1e30)
    p = jax.nn.softmax(s, axis=-1)
    p = jnp.where(mask, p, 0.0)
    return jnp.einsum("bhqk,bhkd->bhqd", p, v.astype(jnp.float32))


def _rand_qkv(B=2, H=3, S=37, D=16, seed=0):
    rng = np.random.RandomState(seed)
    q = jnp.asarray(rng.randn(B, H, S, D).astype(np.float32))
    k = jnp.asarray(rng.randn(B, H, S, D).astype(np.float32))
    v = jnp.asarray(rng.randn(B, H, S, D).astype(np.float32))
    return q, k, v


def test_flash_valid_length_forward_parity():
    q, k, v = _rand_qkv()
    vl = jnp.asarray([17, 37], jnp.int32)
    out = flash_attention(q, k, v, vl)
    ref = _naive_attention(q, k, v, vl)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-4, atol=2e-4)


def test_flash_valid_length_causal_forward_parity():
    q, k, v = _rand_qkv(seed=1)
    vl = jnp.asarray([9, 30], jnp.int32)
    out = flash_attention(q, k, v, vl, True)
    ref = _naive_attention(q, k, v, vl, causal=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-4, atol=2e-4)


def test_flash_valid_length_matches_truncated_keys():
    q, k, v = _rand_qkv(B=1, seed=2)
    vl = jnp.asarray([21], jnp.int32)
    out_masked = flash_attention(q, k, v, vl)
    out_trunc = flash_attention(q, k[:, :, :21], v[:, :, :21])
    np.testing.assert_allclose(np.asarray(out_masked), np.asarray(out_trunc),
                               rtol=2e-4, atol=2e-4)


def test_flash_valid_length_grads_parity():
    q, k, v = _rand_qkv(B=2, H=2, S=29, D=8, seed=3)
    vl = jnp.asarray([13, 29], jnp.int32)

    def loss_flash(q, k, v):
        return jnp.sum(flash_attention(q, k, v, vl) ** 2)

    def loss_ref(q, k, v):
        return jnp.sum(_naive_attention(q, k, v, vl) ** 2)

    gf = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    gr = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(gf, gr):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=2e-3, atol=2e-3)


def test_flash_masked_key_grads_are_zero():
    q, k, v = _rand_qkv(B=1, H=1, S=16, D=4, seed=4)
    vl = jnp.asarray([10], jnp.int32)

    def loss(k, v):
        return jnp.sum(flash_attention(q, k, v, vl))

    dk, dv = jax.grad(loss, argnums=(0, 1))(k, v)
    np.testing.assert_allclose(np.asarray(dk)[0, 0, 10:], 0.0, atol=1e-7)
    np.testing.assert_allclose(np.asarray(dv)[0, 0, 10:], 0.0, atol=1e-7)
    assert np.abs(np.asarray(dv)[0, 0, :10]).max() > 0


def test_flash_valid_length_none_unchanged():
    q, k, v = _rand_qkv(seed=5)
    full = jnp.asarray([q.shape[2]] * q.shape[0], jnp.int32)
    np.testing.assert_allclose(
        np.asarray(flash_attention(q, k, v)),
        np.asarray(flash_attention(q, k, v, full)),
        rtol=1e-6,
    )


def test_mha_layer_valid_length():
    """Padded batch through the layer == truncated batch, on valid rows."""
    rng = np.random.RandomState(0)
    B, S, units, H = 2, 12, 16, 4
    vl_np = np.array([7, 12])
    mha = gluon.nn.MultiHeadAttention(units, H, self_attention=True)
    mha.initialize()
    x = rng.randn(B, S, units).astype(np.float32)
    x_pad = x.copy()
    x_pad[0, 7:] = 99.0  # garbage in the padding region
    out = mha(nd.array(x_pad), valid_length=nd.array(vl_np, dtype="int32"))
    # row 0: compare against running only its valid prefix
    out_ref = mha(nd.array(x[:1, :7]))
    np.testing.assert_allclose(
        out.asnumpy()[0, :7], out_ref.asnumpy()[0], rtol=2e-4, atol=2e-4
    )


def test_mha_valid_length_autograd():
    rng = np.random.RandomState(1)
    B, S, units, H = 2, 10, 8, 2
    mha = gluon.nn.MultiHeadAttention(units, H)
    mha.initialize()
    x = nd.array(rng.randn(B, S, units).astype(np.float32))
    vl = nd.array(np.array([5, 10]), dtype="int32")
    with autograd.record():
        out = mha(x, valid_length=vl)
        loss = (out ** 2).sum()
    loss.backward()
    w = mha.qkv_proj.weight
    assert w.grad() is not None
    assert np.isfinite(w.grad().asnumpy()).all()


def test_bert_padding_invariance():
    """Changing token content past valid_length must not change valid-row
    outputs (the property that makes ragged-batch pretraining correct)."""
    from mxnet_tpu.gluon.model_zoo.bert import BERTModel

    net = BERTModel(vocab_size=50, units=16, hidden_size=32, num_layers=2,
                    num_heads=2, max_length=32, dropout=0.0)
    net.initialize()
    rng = np.random.RandomState(0)
    ids = rng.randint(0, 50, (2, 12)).astype(np.int32)
    vl = nd.array(np.array([8, 12]), dtype="int32")
    seq1, _ = net(nd.array(ids, dtype="int32"), None, vl)
    ids2 = ids.copy()
    ids2[0, 8:] = (ids2[0, 8:] + 7) % 50  # scramble padding tokens
    seq2, _ = net(nd.array(ids2, dtype="int32"), None, vl)
    np.testing.assert_allclose(
        seq1.asnumpy()[0, :8], seq2.asnumpy()[0, :8], rtol=2e-4, atol=2e-4
    )
    np.testing.assert_allclose(
        seq1.asnumpy()[1], seq2.asnumpy()[1], rtol=2e-4, atol=2e-4
    )


def test_bert_ragged_pretrain_step():
    """One fused train step on a ragged batch: finite loss, params move."""
    from mxnet_tpu import optimizer as opt
    from mxnet_tpu.gluon.model_zoo.bert import BERTModel
    from mxnet_tpu.parallel import TrainStep

    net = BERTModel(vocab_size=50, units=16, hidden_size=32, num_layers=2,
                    num_heads=2, max_length=32, dropout=0.0)
    net.initialize()
    net._probe_shapes(nd.zeros((2, 8), dtype="int32"))
    ce = gluon.loss.SoftmaxCrossEntropyLoss()
    word_w = net.word_embed.weight

    class _MLMLoss:
        def __call__(self, seq_out, pooled, label):
            w = word_w.data()
            logits = seq_out.reshape(-1, seq_out.shape[-1]).dot(w.T)
            return ce(logits, label.reshape(-1))

    step = TrainStep(net, _MLMLoss(), opt.SGD(learning_rate=0.1))
    rng = np.random.RandomState(0)
    ids = nd.array(rng.randint(0, 50, (4, 16)), dtype="int32")
    types = nd.zeros((4, 16), dtype="int32")
    vl = nd.array(np.array([16, 9, 12, 5]), dtype="int32")
    labels = nd.array(rng.randint(0, 50, (4, 16)), dtype="int32")
    l1 = float(step(ids, types, vl, labels).asscalar())
    l2 = float(step(ids, types, vl, labels).asscalar())
    assert np.isfinite(l1) and np.isfinite(l2)
    assert l2 < l1  # same batch twice: loss must drop


def test_nd_flash_attention_keyword_valid_length():
    # keyword NDArray args must be unwrapped by the op itself
    rng = np.random.RandomState(7)
    q = nd.array(rng.randn(1, 2, 8, 4).astype(np.float32))
    k = nd.array(rng.randn(1, 2, 8, 4).astype(np.float32))
    v = nd.array(rng.randn(1, 2, 8, 4).astype(np.float32))
    vl = nd.array(np.array([5]), dtype="int32")
    out_kw = mx.nd.flash_attention(q, k, v, valid_length=vl)
    out_pos = mx.nd.flash_attention(q, k, v, vl)
    np.testing.assert_allclose(out_kw.asnumpy(), out_pos.asnumpy(), rtol=1e-6)


def test_keyword_length_accepts_numpy():
    # numpy arrays expose a .data memoryview — the kwarg unwrap must not
    # mistake them for NDArrays
    x = nd.array(np.random.RandomState(0).randn(2, 5).astype("float32"))
    out = mx.nd.softmax(x, length=np.array([2, 3]), use_length=True)
    assert out.shape == (2, 5)
    q = nd.array(np.random.RandomState(1).randn(1, 2, 8, 4).astype("float32"))
    out2 = mx.nd.flash_attention(q, q, q,
                                 valid_length=np.array([5], np.int32))
    assert out2.shape == (1, 2, 8, 4)


class TestPallasBackwardParity:
    """The Pallas backward kernel must match the XLA recompute scan
    bit-for-tolerance across mask modes."""

    @pytest.mark.parametrize("causal", [False, True])
    @pytest.mark.parametrize("use_vl", [False, True])
    def test_bwd_paths_agree(self, causal, use_vl):
        import importlib

        import jax.numpy as jnp

        fa = importlib.import_module("mxnet_tpu.ops.pallas.flash_attention")

        rng = np.random.RandomState(0)
        B, H, S, D = 2, 2, 64, 16
        q = jnp.asarray(rng.randn(B, H, S, D).astype(np.float32))
        k = jnp.asarray(rng.randn(B, H, S, D).astype(np.float32))
        v = jnp.asarray(rng.randn(B, H, S, D).astype(np.float32))
        do = jnp.asarray(rng.randn(B, H, S, D).astype(np.float32))
        vl = jnp.asarray([40, 64], jnp.int32) if use_vl \
            else jnp.full((B,), S, jnp.int32)
        out, lse = fa._flash_fwd(q, k, v, vl if use_vl else None, causal,
                                 0.25, 128, 128)
        a = fa._flash_bwd_pallas(q, k, v, vl, out, lse, do, causal, 0.25)
        b = fa._flash_bwd_xla(q, k, v, vl, out, lse, do, causal, 0.25, 128)
        for x, y, name in zip(a, b, ["dq", "dk", "dv"]):
            np.testing.assert_allclose(np.asarray(x), np.asarray(y),
                                       rtol=2e-4, atol=2e-5, err_msg=name)

    def test_bwd_multi_block(self):
        """Sq, Sk > block size exercises the q loop and k grid."""
        import importlib

        import jax.numpy as jnp

        fa = importlib.import_module("mxnet_tpu.ops.pallas.flash_attention")

        rng = np.random.RandomState(1)
        B, H, S, D = 1, 1, 256, 8
        q = jnp.asarray(rng.randn(B, H, S, D).astype(np.float32))
        k = jnp.asarray(rng.randn(B, H, S, D).astype(np.float32))
        v = jnp.asarray(rng.randn(B, H, S, D).astype(np.float32))
        do = jnp.asarray(rng.randn(B, H, S, D).astype(np.float32))
        vl = jnp.full((B,), S, jnp.int32)
        out, lse = fa._flash_fwd(q, k, v, None, True, 0.3, 128, 128)
        a = fa._flash_bwd_pallas(q, k, v, vl, out, lse, do, True, 0.3,
                                 block_q=128, block_k=128)
        b = fa._flash_bwd_xla(q, k, v, vl, out, lse, do, True, 0.3, 128)
        for x, y, name in zip(a, b, ["dq", "dk", "dv"]):
            np.testing.assert_allclose(np.asarray(x), np.asarray(y),
                                       rtol=2e-4, atol=2e-5, err_msg=name)

    def test_bwd_unaligned_seq(self):
        """Sq not a multiple of the block exercises the lse padding guard."""
        import importlib

        import jax.numpy as jnp

        fa = importlib.import_module("mxnet_tpu.ops.pallas.flash_attention")

        rng = np.random.RandomState(2)
        B, H, S, D = 1, 2, 100, 8
        q = jnp.asarray(rng.randn(B, H, S, D).astype(np.float32))
        k = jnp.asarray(rng.randn(B, H, S, D).astype(np.float32))
        v = jnp.asarray(rng.randn(B, H, S, D).astype(np.float32))
        do = jnp.asarray(rng.randn(B, H, S, D).astype(np.float32))
        vl = jnp.full((B,), S, jnp.int32)
        out, lse = fa._flash_fwd(q, k, v, None, False, 0.25, 128, 128)
        a = fa._flash_bwd_pallas(q, k, v, vl, out, lse, do, False, 0.25)
        b = fa._flash_bwd_xla(q, k, v, vl, out, lse, do, False, 0.25, 128)
        for x, y, name in zip(a, b, ["dq", "dk", "dv"]):
            np.testing.assert_allclose(np.asarray(x), np.asarray(y),
                                       rtol=2e-4, atol=2e-5, err_msg=name)
