"""Self-healing serving plane: hot weight swap, router failover, faults.

Contracts under test (ISSUE 7 tentpole + ISSUE 10 cross-process plane):

- a hot weight swap under sustained ``DynamicBatcher`` load loses ZERO
  requests, responses carry the ``weights_version`` their dispatch
  actually served, and post-swap greedy outputs are BIT-identical to a
  fresh engine built from the same checkpoint;
- killing one of two router replicas mid-load (fault injection, no real
  process death needed) completes every submitted future with
  ``serve/failovers >= 1`` and zero steady-state recompiles;
- the failure paths themselves are deterministic: ``serving.faults``
  drives dispatch raises, dispatcher-thread death, hangs, stale
  heartbeats, and torn checkpoints from env specs or test code;
- CROSS-PROCESS (ISSUE 10): real ``serving.worker`` processes behind
  the socket transport — SIGKILL mid-decode loses zero requests (one
  failover, a respawned REAL process rejoins at the current version),
  SIGTERM drains gracefully (exit 0, every in-flight request served),
  and a coordinated swap flips every process onto ONE version tag with
  post-swap greedy tokens bit-identical to a fresh engine;
- LOAD SHEDDING: with every replica degraded the router sheds at
  admission (``Backpressure`` + ``serve/shed_*``) and the backlog stays
  bounded by construction; any healthy replica keeps admission open.
"""

import json
import os
import threading
import time

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import nd
from mxnet_tpu import checkpoint_sharded as cs
from mxnet_tpu.base import MXNetError
from mxnet_tpu.gluon.model_zoo.transformer import TransformerModel
from mxnet_tpu.parallel import InferStep
from mxnet_tpu.serving import (Backpressure, CheckpointWatcher,
                               DeadlineExceeded, DynamicBatcher,
                               RemoteReplica, Replica, ReplicaUnavailable,
                               Router, RpcClient, RpcServer,
                               TransportError, faults)
from mxnet_tpu.serving.worker import make_transformer_net, spawn_worker
from mxnet_tpu.telemetry.watchdog import Watchdog, read_heartbeat

WORKER_ENV = {"JAX_PLATFORMS": os.environ.get("MXTPU_TEST_PLATFORM",
                                              "cpu")}


def _make_net(seed, prefix="serve_net_"):
    """Tiny decode-capable transformer. A FIXED prefix keeps param names
    identical across instances — the train->serve checkpoint contract
    (trainer and server build the net from the same code)."""
    np.random.seed(seed)
    mx.random.seed(seed)
    net = TransformerModel(src_vocab=61, tgt_vocab=61, units=16,
                           hidden_size=32, num_layers=1, num_heads=2,
                           max_length=64, dropout=0.0, prefix=prefix)
    net.initialize(mx.initializer.Xavier())
    net._probe_shapes(nd.zeros((2, 8), dtype="int32"),
                      nd.zeros((2, 8), dtype="int32"))
    return net


@pytest.fixture(scope="module")
def net_a():
    return _make_net(0)


@pytest.fixture(scope="module")
def net_b():
    return _make_net(1)


@pytest.fixture(scope="module")
def shared_engine(net_a):
    """One warmed engine reused by the batcher/router tests (router
    replicas may share an engine — two batchers, one param set)."""
    eng = InferStep(net_a, max_len=24)
    eng.warmup([(2, 8)], max_new_tokens=4)
    return eng


@pytest.fixture(autouse=True)
def _clean_faults():
    faults.clear()
    yield
    faults.clear()


def _batcher(engine, **kw):
    cfg = dict(bucket_keys=(8,), slots=2, timeout_ms=5.0,
               max_new_tokens=4)
    cfg.update(kw)
    return DynamicBatcher(engine, **cfg)


def _prompts(rng, n, lo=3, hi=61, lmin=3, lmax=8):
    return [rng.randint(lo, hi, (rng.randint(lmin, lmax + 1),))
            .astype(np.int32) for _ in range(n)]


def _save_params(directory, net):
    return cs.save_sharded(
        directory, {n: p._data.data
                    for n, p in net.collect_params().items()})


# ---------------------------------------------------------------- faults
class TestFaultHarness:
    def test_programmatic_inject_and_fire(self):
        faults.inject("x.p", times=2)
        with pytest.raises(faults.FaultInjected):
            faults.fire("x.p")
        with pytest.raises(faults.FaultInjected):
            faults.fire("x.p")
        faults.fire("x.p")  # exhausted -> no-op
        assert faults.specs()[0]["fired"] == 2

    def test_after_skips_hits(self):
        faults.inject("x.after", times=1, after=2)
        faults.fire("x.after")
        faults.fire("x.after")
        with pytest.raises(faults.FaultInjected):
            faults.fire("x.after")

    def test_match_restricts_tag(self):
        faults.inject("x.m", times=None, match="r1")
        faults.fire("x.m", tag="r2")  # no match -> no-op
        with pytest.raises(faults.FaultInjected):
            faults.fire("x.m", tag="r1-main")
        faults.fire("x.m", tag=None)  # no tag can never match

    def test_delay_mode_sleeps_not_raises(self):
        faults.inject("x.d", times=1, delay=0.05)
        t0 = time.perf_counter()
        faults.fire("x.d")
        assert time.perf_counter() - t0 >= 0.045

    def test_env_spec_parsed(self, monkeypatch):
        monkeypatch.setenv("MXTPU_FAULT_E_P", "times=1;match=zz")
        assert faults.check("e.p", tag="aa") is None
        assert faults.check("e.p", tag="a-zz-a") is not None
        assert faults.check("e.p", tag="a-zz-a") is None  # exhausted

    def test_env_spec_bad_key_raises(self, monkeypatch):
        monkeypatch.setenv("MXTPU_FAULT_E_BAD", "bogus=1")
        with pytest.raises(MXNetError):
            faults.check("e.bad")

    def test_fault_counter(self):
        before = mx.telemetry.registry().counter(
            "serve/faults_injected").value
        faults.inject("x.c", times=1)
        with pytest.raises(faults.FaultInjected):
            faults.fire("x.c")
        assert mx.telemetry.registry().counter(
            "serve/faults_injected").value == before + 1


# -------------------------------------------------------------- heartbeat
class TestAtomicHeartbeat:
    def test_never_observes_partial_json(self, tmp_path):
        """Hammer heartbeat writes from two watchdogs sharing a
        directory while reading concurrently: every read parses — the
        tmp+fsync+rename publish can never expose a partial file."""
        wds = [Watchdog(str(tmp_path), interval=9.0) for _ in range(2)]
        stop = threading.Event()
        bad = []

        def writer(wd):
            while not stop.is_set():
                wd._write_heartbeat()

        threads = [threading.Thread(target=writer, args=(wd,), daemon=True)
                   for wd in wds]
        for t in threads:
            t.start()
        path = os.path.join(str(tmp_path), "heartbeat.json")
        deadline = time.perf_counter() + 1.0
        reads = 0
        while time.perf_counter() < deadline:
            try:
                with open(path) as f:
                    json.load(f)
                reads += 1
            except FileNotFoundError:
                continue
            except ValueError as e:
                bad.append(e)
        stop.set()
        for t in threads:
            t.join(timeout=5)
        assert reads > 0 and not bad, \
            f"{len(bad)} torn heartbeat reads out of {reads}"

    def test_tmp_name_unique_per_writer(self, tmp_path):
        wd = Watchdog(str(tmp_path), interval=9.0)
        wd._write_heartbeat()
        # the shared fixed-name tmp file of the old scheme must be gone
        assert not os.path.exists(wd.heartbeat_path + ".tmp")
        assert read_heartbeat(wd.heartbeat_path)["status"] == "alive"

    def test_read_heartbeat_torn_is_none(self, tmp_path):
        p = tmp_path / "heartbeat.json"
        p.write_text('{"status": "al')  # torn mid-write
        assert read_heartbeat(str(p)) is None
        assert read_heartbeat(str(tmp_path / "missing.json")) is None

    def test_suppression_fault_freezes_heartbeat(self, tmp_path):
        wd = Watchdog(str(tmp_path), interval=9.0)
        wd._write_heartbeat()
        first = read_heartbeat(wd.heartbeat_path)
        faults.inject("watchdog.heartbeat", times=None,
                      match=str(tmp_path))
        time.sleep(0.01)
        wd._write_heartbeat()
        assert read_heartbeat(wd.heartbeat_path)["time"] == first["time"]


# ---------------------------------------------------------- batcher health
class TestBatcherHealth:
    def test_healthy_lifecycle(self, shared_engine):
        bat = _batcher(shared_engine)
        assert bat.healthy
        bat.stop()
        assert not bat.healthy

    def test_submit_after_stop_fails_future_immediately(
            self, shared_engine):
        bat = _batcher(shared_engine)
        bat.stop()
        fut = bat.submit([3, 4, 5])
        assert fut.done()
        with pytest.raises(RuntimeError, match="not accepting"):
            fut.result(timeout=0)

    def test_submit_after_thread_death_fails_future(self, shared_engine):
        faults.inject("batcher.thread", times=1, match="dead-replica")
        bat = _batcher(shared_engine, name="dead-replica")
        deadline = time.perf_counter() + 10
        while bat._thread.is_alive() and time.perf_counter() < deadline:
            time.sleep(0.005)
        assert not bat.healthy
        fut = bat.submit([3, 4])
        assert fut.done() and isinstance(fut.exception(), RuntimeError)

    def test_stop_fails_undrained_queue(self, shared_engine):
        """stop(drain=False) with work still queued (here: stuck behind
        a hung dispatch) fails those futures instead of leaking them."""
        faults.inject("batcher.hang", times=1, delay=0.3,
                      match="undrained")
        bat = _batcher(shared_engine, name="undrained")
        blocker = bat.submit([9, 10])  # dispatched, hangs 300 ms
        time.sleep(0.05)
        queued = bat.submit([3, 4, 5])
        assert not queued.done()
        bat.stop(drain=False)
        assert isinstance(blocker.result(timeout=60), list)
        assert queued.done()
        with pytest.raises(RuntimeError, match="queued"):
            queued.result(timeout=0)

    def test_thread_death_fails_queued_futures(self, shared_engine):
        """A crashing dispatcher fails what it held queued — no future
        is ever left unresolvable."""
        faults.inject("batcher.hang", times=1, delay=0.3,
                      match="dying-replica")
        faults.inject("batcher.thread", times=1, after=1,
                      match="dying-replica")
        bat = _batcher(shared_engine, name="dying-replica",
                       timeout_ms=1.0)
        fut = bat.submit([3, 4])  # dispatched, hangs 300 ms
        time.sleep(0.1)
        fut2 = bat.submit([5, 6])  # queued; the thread dies next pass
        assert isinstance(fut.result(timeout=60), list)
        with pytest.raises(RuntimeError):
            fut2.result(timeout=60)


# ------------------------------------------------------------- deadlines
class TestDeadlines:
    def test_expired_in_queue_fails_not_dispatches(self, shared_engine):
        """A request whose deadline passes while queued (here: behind a
        hung dispatch) is failed with DeadlineExceeded; the batch it
        would have ridden dispatches without it and occupancy telemetry
        reflects only the live rows."""
        mx.telemetry.reset()
        faults.inject("batcher.hang", times=1, delay=0.2,
                      match="dl-replica")
        bat = _batcher(shared_engine, slots=2, timeout_ms=5.0,
                       name="dl-replica")
        try:
            blocker = bat.submit([9, 10])  # dispatched, hangs 200 ms
            time.sleep(0.05)  # blocker is in its (hung) dispatch alone
            doomed = bat.submit([3, 4, 5], deadline_ms=20.0)
            live = bat.submit([6, 7, 8])  # same batch as doomed, no limit
            assert isinstance(blocker.result(timeout=60), list)
            assert isinstance(live.result(timeout=60), list)
            with pytest.raises(DeadlineExceeded):
                doomed.result(timeout=60)
            reg = mx.telemetry.registry()
            assert reg.counter("serve/deadline_exceeded").value == 1
            # the expired row never occupied a slot: the second dispatch
            # carried 1 live row of 2 slots, and only 2 requests total
            # were ever dispatched
            assert reg.gauge("infer/batch_occupancy").value == 0.5
            assert reg.counter("infer/requests").value == 2
        finally:
            bat.stop()
            mx.telemetry.reset()

    def test_unexpired_deadline_dispatches_normally(self, shared_engine):
        bat = _batcher(shared_engine)
        try:
            fut = bat.submit([3, 4, 5], deadline_ms=60_000.0)
            assert isinstance(fut.result(timeout=60), list)
        finally:
            bat.stop()

    def test_router_deadline_on_hung_replica(self, shared_engine):
        """A dispatched-but-hung request settles via its deadline
        instead of waiting on the wedged engine forever."""
        faults.inject("batcher.hang", times=1, delay=1.5,
                      match="hang-replica")
        bat = _batcher(shared_engine, name="hang-replica")
        router = Router([Replica("hang-replica", bat)],
                        health_interval_s=10.0, start=True)
        try:
            fut = router.submit([3, 4, 5], deadline_ms=150.0)
            with pytest.raises(DeadlineExceeded):
                fut.result(timeout=60)
        finally:
            router.stop()


# ------------------------------------------------------------ weight swap
class TestHotWeightSwap:
    def test_swap_params_flips_version_and_values(self, net_a, net_b):
        eng = InferStep(net_a, max_len=24)
        assert eng.weights_version == "v0"
        arrays = {n: p._data.data
                  for n, p in net_b.collect_params().items()}
        ver = eng.swap_params(arrays)
        assert ver == "v1" and eng.weights_version == "v1"
        name = next(iter(arrays))
        np.testing.assert_array_equal(
            np.asarray(eng._values[name]), np.asarray(arrays[name]))

    def test_swap_validates_names_and_shapes(self, net_a):
        eng = InferStep(net_a, max_len=24)
        with pytest.raises(MXNetError, match="missing parameter"):
            eng.swap_params({})
        arrays = {n: p._data.data
                  for n, p in net_a.collect_params().items()}
        k = next(iter(arrays))
        bad = dict(arrays)
        bad[k] = np.zeros((3, 3), np.float32)
        with pytest.raises(MXNetError, match="shape mismatch"):
            eng.swap_params(bad)

    def test_swap_accepts_trainstep_naming(self, net_a, net_b):
        eng = InferStep(net_a, max_len=24)
        arrays = {"values/" + n: p._data.data
                  for n, p in net_b.collect_params().items()}
        arrays["opt/m/whatever"] = np.zeros((1,), np.float32)  # ignored
        assert eng.swap_params(arrays) == "v1"

    def test_swapped_outputs_bit_identical_to_fresh_engine(
            self, net_a, net_b, tmp_path):
        """Acceptance: post-swap greedy outputs == a fresh engine loaded
        from the same checkpoint, bit-identically."""
        _save_params(str(tmp_path / "step_1"), net_b)
        eng = InferStep(net_a, max_len=24)
        rng = np.random.RandomState(3)
        src = rng.randint(3, 61, (2, 8)).astype(np.int32)
        vl = np.array([6, 8], np.int32)
        before = eng.decode_n(src, vl, max_new_tokens=4)
        before = (before[0].asnumpy(), before[1].asnumpy())
        w = CheckpointWatcher(eng, str(tmp_path), start=False)
        ver = w.poll_once()
        assert ver is not None and eng.weights_version == ver
        after = eng.decode_n(src, vl, max_new_tokens=4)
        after = (after[0].asnumpy(), after[1].asnumpy())
        fresh_eng = InferStep(net_b, max_len=24)
        fresh = fresh_eng.decode_n(src, vl, max_new_tokens=4)
        fresh = (fresh[0].asnumpy(), fresh[1].asnumpy())
        assert not np.array_equal(after[0], before[0])
        np.testing.assert_array_equal(after[0], fresh[0])
        np.testing.assert_array_equal(after[1], fresh[1])
        # a swap to IDENTICAL shapes/dtypes adds no program signatures
        assert eng.compile_guard.steady_state_recompiles == 0

    def test_swap_under_load_loses_nothing(self, net_b, tmp_path):
        """Acceptance: a swap mid-stream resolves every future, tags the
        responses with the version that served them, and never
        recompiles."""
        net = _make_net(7)
        eng = InferStep(net, max_len=24)
        eng.warmup([(2, 8)], max_new_tokens=4)
        _save_params(str(tmp_path / "step_9"), net_b)
        watcher = CheckpointWatcher(eng, str(tmp_path), start=False)
        bat = _batcher(eng, warmup=False)
        rng = np.random.RandomState(11)
        futs = []
        try:
            for i, p in enumerate(_prompts(rng, 30)):
                futs.append(bat.submit(p))
                if i == 12:
                    assert watcher.poll_once() is not None
                time.sleep(0.002)
            results = [f.result(timeout=120) for f in futs]
        finally:
            bat.stop()
        assert all(isinstance(r, list) for r in results)
        versions = {f.weights_version for f in futs}
        assert "v0" in versions and len(versions) == 2, versions
        # version tags are MONOTONIC: once the swap lands, no later
        # dispatch serves the old weights
        seen_new = False
        for f in futs:
            if f.weights_version != "v0":
                seen_new = True
            else:
                assert not seen_new, "old version served after the swap"
        assert eng.compile_guard.steady_state_recompiles == 0

    def test_torn_checkpoint_keeps_serving_old(self, net_a, net_b,
                                               tmp_path):
        mx.telemetry.reset()
        _save_params(str(tmp_path / "step_1"), net_b)
        eng = InferStep(net_a, max_len=24)
        w = CheckpointWatcher(eng, str(tmp_path), start=False)
        faults.inject("ckpt.load", times=1)
        assert w.poll_once() is None
        assert isinstance(w.last_error, faults.FaultInjected)
        assert eng.weights_version == "v0"
        assert mx.telemetry.registry().counter(
            "serve/swap_failures").value == 1
        # fault exhausted: the NEXT poll retries the same commit and wins
        assert w.poll_once() is not None
        assert mx.telemetry.registry().counter("serve/swaps").value == 1
        mx.telemetry.reset()

    def test_uncommitted_checkpoint_invisible(self, net_a, net_b,
                                              tmp_path):
        d = tmp_path / "step_1"
        _save_params(str(d), net_b)
        os.unlink(d / "DONE.p0")  # retract the commit
        assert cs.latest_committed(str(tmp_path)) is None
        w = CheckpointWatcher(InferStep(net_a, max_len=24), str(tmp_path),
                              start=False)
        assert w.poll_once() is None

    def test_latest_committed_prefers_newest(self, net_a, net_b,
                                             tmp_path):
        _save_params(str(tmp_path / "step_1"), net_a)
        time.sleep(0.01)
        _save_params(str(tmp_path / "step_2"), net_b)
        path, token = cs.latest_committed(str(tmp_path))
        assert path.endswith("step_2") and token is not None

    def test_commit_token_changes_on_resave(self, net_a, tmp_path):
        d = str(tmp_path / "ck")
        _save_params(d, net_a)
        t1 = cs.commit_token(d)
        time.sleep(0.01)
        _save_params(d, net_a)
        t2 = cs.commit_token(d)
        assert t1 is not None and t2 is not None and t1 != t2

    def test_background_thread_swaps(self, net_a, net_b, tmp_path):
        eng = InferStep(net_a, max_len=24)
        w = CheckpointWatcher(eng, str(tmp_path), poll_s=0.02)
        try:
            assert eng.weights_version == "v0"
            _save_params(str(tmp_path / "step_3"), net_b)
            deadline = time.perf_counter() + 30
            while eng.weights_version == "v0" and \
                    time.perf_counter() < deadline:
                time.sleep(0.01)
            assert eng.weights_version.startswith("step_3:")
        finally:
            w.stop()


# ----------------------------------------------------------------- router
class TestRouter:
    def _two_replicas(self, engine, **bkw):
        b1 = _batcher(engine, name="r1", **bkw)
        b2 = _batcher(engine, name="r2", **bkw)
        return [Replica("r1", b1), Replica("r2", b2)]

    def test_basic_routing_completes(self, shared_engine):
        router = Router(self._two_replicas(shared_engine),
                        health_interval_s=0.02)
        rng = np.random.RandomState(5)
        try:
            futs = [router.submit(p) for p in _prompts(rng, 8)]
            res = [f.result(timeout=120) for f in futs]
        finally:
            router.stop()
        assert all(isinstance(r, list) for r in res)
        assert all(f.replica in ("r1", "r2") for f in futs)

    def test_failover_on_replica_death(self, shared_engine):
        """Acceptance: killing one of two replicas mid-load completes
        every future, serve/failovers >= 1, zero steady recompiles."""
        mx.telemetry.reset()
        router = Router(self._two_replicas(shared_engine),
                        retry_backoff_s=0.01, health_interval_s=0.02)
        faults.inject("batcher.thread", times=1, after=1, match="r1")
        rng = np.random.RandomState(6)
        futs = []
        try:
            for p in _prompts(rng, 16):
                futs.append(router.submit(p))
                time.sleep(0.002)
            res = [f.result(timeout=120) for f in futs]
        finally:
            router.stop()
        assert all(isinstance(r, list) for r in res)
        reg = mx.telemetry.registry()
        assert reg.counter("serve/failovers").value >= 1
        assert reg.counter("serve/dropped").value == 0
        assert reg.counter("serve/completed").value == len(futs)
        assert [r for r in router.replicas if r.name == "r1"][0].evicted
        assert shared_engine.compile_guard.steady_state_recompiles == 0
        mx.telemetry.reset()

    def test_dispatch_error_retries_on_other_replica(self, shared_engine):
        """A transient dispatch failure is retried transparently — the
        caller sees tokens, the registry sees the retry."""
        mx.telemetry.reset()
        router = Router(self._two_replicas(shared_engine),
                        retry_backoff_s=0.01, health_interval_s=0.02)
        faults.inject("batcher.dispatch", times=1)
        rng = np.random.RandomState(7)
        try:
            fut = router.submit(rng.randint(3, 61, (5,)).astype(np.int32))
            assert isinstance(fut.result(timeout=120), list)
        finally:
            router.stop()
        assert mx.telemetry.registry().counter(
            "serve/retries").value >= 1
        mx.telemetry.reset()

    def test_retries_bounded_then_dropped(self, shared_engine):
        mx.telemetry.reset()
        router = Router(self._two_replicas(shared_engine),
                        max_retries=1, retry_backoff_s=0.01,
                        health_interval_s=0.02)
        faults.inject("batcher.dispatch", times=None)  # every dispatch
        rng = np.random.RandomState(8)
        try:
            fut = router.submit(rng.randint(3, 61, (5,)).astype(np.int32))
            with pytest.raises(faults.FaultInjected):
                fut.result(timeout=120)
        finally:
            router.stop()
        reg = mx.telemetry.registry()
        assert reg.counter("serve/dropped").value == 1
        assert reg.counter("serve/retries").value == 1  # bounded
        mx.telemetry.reset()

    def test_no_healthy_replica_fails_fast(self, shared_engine):
        rep = Replica("r1", _batcher(shared_engine))
        router = Router([rep], health_interval_s=0.02,
                        no_replica_timeout_s=0.2)
        try:
            rep.batcher.stop()
            deadline = time.perf_counter() + 10
            while not rep.evicted and time.perf_counter() < deadline:
                time.sleep(0.01)
            fut = router.submit([3, 4, 5])
            with pytest.raises(RuntimeError, match="no healthy"):
                fut.result(timeout=60)
        finally:
            router.stop()

    def test_queued_requests_resubmitted_on_eviction(self, shared_engine):
        """The eviction contract end-to-end: requests queued (and even
        in-flight) on a replica when it is evicted are transparently
        replayed on the healthy one — every future resolves, on r2."""
        faults.inject("batcher.hang", times=1, delay=0.5, match="r1")
        b1 = _batcher(shared_engine, name="r1")
        b2 = _batcher(shared_engine, name="r2")
        rep1, rep2 = Replica("r1", b1), Replica("r2", b2)
        router = Router([rep1, rep2], retry_backoff_s=0.01,
                        health_interval_s=0.02)
        rng = np.random.RandomState(9)
        try:
            # bias placement onto r1, whose first dispatch will hang
            rep2.inflight = 100
            futs = [router.submit(p) for p in _prompts(rng, 4)]
            time.sleep(0.05)  # first req dispatched+hung, rest queued
            rep2.inflight = 0
            router._evict(rep1, "test: operator eviction")
            res = [f.result(timeout=120) for f in futs]
        finally:
            router.stop()
        assert all(isinstance(r, list) for r in res)
        assert all(f.replica == "r2" for f in futs)
        assert rep1.evicted
        assert mx.telemetry.registry().counter(
            "serve/failovers").value >= 1

    def test_heartbeat_staleness_evicts(self, shared_engine, tmp_path):
        """Watchdog-driven failover: the replica's dispatcher is alive
        but its heartbeat is frozen (suppression fault) — the router
        evicts on staleness and the healthy replica serves."""
        mx.telemetry.reset()
        hb_dir = str(tmp_path / "wd1")
        wd = Watchdog(hb_dir, interval=0.02)
        b1 = _batcher(shared_engine, name="r1", watchdog=wd)
        b2 = _batcher(shared_engine, name="r2")
        wd.start()
        rep1 = Replica("r1", b1, heartbeat_path=wd.heartbeat_path,
                       heartbeat_stale_s=0.15)
        router = Router([rep1, Replica("r2", b2)],
                        retry_backoff_s=0.01, health_interval_s=0.02)
        try:
            rng = np.random.RandomState(10)
            # serves normally while the heartbeat is fresh
            fut = router.submit(rng.randint(3, 61, (5,)).astype(np.int32))
            fut.result(timeout=120)
            # wait until the FIRST heartbeat actually landed: freezing a
            # never-written heartbeat is indistinguishable from "no
            # watchdog wired", which health() treats as unknown
            deadline = time.perf_counter() + 30
            while read_heartbeat(wd.heartbeat_path) is None and \
                    time.perf_counter() < deadline:
                time.sleep(0.01)
            assert read_heartbeat(wd.heartbeat_path) is not None
            faults.inject("watchdog.heartbeat", times=None, match=hb_dir)
            deadline = time.perf_counter() + 30
            while not rep1.evicted and time.perf_counter() < deadline:
                time.sleep(0.01)
            assert rep1.evicted
            fut2 = router.submit(
                rng.randint(3, 61, (5,)).astype(np.int32))
            assert isinstance(fut2.result(timeout=120), list)
            assert fut2.replica == "r2"
            assert mx.telemetry.registry().counter(
                "serve/failovers").value >= 1
        finally:
            router.stop()
            wd.stop()
            mx.telemetry.reset()

    def test_respawn_via_factory(self, shared_engine):
        mx.telemetry.reset()
        made = []

        def factory():
            rep = Replica(f"r{2 + len(made)}", _batcher(shared_engine))
            made.append(rep)
            return rep

        rep1 = Replica("r1", _batcher(shared_engine))
        router = Router([rep1], replica_factory=factory,
                        respawn_backoff_s=0.01, retry_backoff_s=0.01,
                        health_interval_s=0.02)
        rng = np.random.RandomState(12)
        try:
            faults.inject("batcher.thread", times=1, match="r1")
            # poke r1 so its thread hits the fault point and dies
            deadline = time.perf_counter() + 30
            while not made and time.perf_counter() < deadline:
                time.sleep(0.01)
            assert made, "factory never invoked after eviction"
            fut = router.submit(rng.randint(3, 61, (5,)).astype(np.int32))
            assert isinstance(fut.result(timeout=120), list)
            assert fut.replica == made[0].name
            assert mx.telemetry.registry().counter(
                "serve/replica_restarts").value == 1
        finally:
            router.stop()
            mx.telemetry.reset()

    def test_backoff_delay_shape(self):
        from mxnet_tpu.serving.router import backoff_delay

        d0 = backoff_delay(1.0, 0, jitter=0.0)
        d3 = backoff_delay(1.0, 3, jitter=0.0)
        dcap = backoff_delay(1.0, 30, cap=30.0, jitter=0.0)
        assert d0 == 1.0 and d3 == 8.0 and dcap == 30.0
        j = backoff_delay(1.0, 0, jitter=0.25)
        assert 1.0 <= j <= 1.25


# -------------------------------------------------------- elastic restarts
class TestElasticBackoff:
    def test_restart_backoff_and_counter(self):
        import sys

        sys.path.insert(0, os.path.join(
            os.path.dirname(__file__), "..", "tools"))
        import launch

        mx.telemetry.reset()
        delays = []
        rc = launch.launch_elastic(
            1, [sys.executable, "-c", "import sys; sys.exit(3)"],
            max_restarts=2, backoff_s=0.2, _sleep=delays.append)
        assert rc == 3
        assert len(delays) == 2  # no sleep after the final attempt
        assert 0.2 <= delays[0] <= 0.25 * 1.01
        assert 0.4 <= delays[1] <= 0.5 * 1.01
        assert mx.telemetry.registry().counter(
            "launch/restarts").value == 2
        mx.telemetry.reset()

    def test_env_default_backoff(self, monkeypatch):
        import sys

        sys.path.insert(0, os.path.join(
            os.path.dirname(__file__), "..", "tools"))
        import launch

        monkeypatch.setenv("MXTPU_RESTART_BACKOFF_S", "0.125")
        assert launch.restart_backoff_s() == 0.125
        monkeypatch.setenv("MXTPU_RESTART_BACKOFF_S", "junk")
        assert launch.restart_backoff_s() == 1.0


# ------------------------------------------------------------- telemetry
class TestServeTelemetry:
    def test_report_serve_fields(self):
        mx.telemetry.reset()
        reg = mx.telemetry.registry()
        reg.counter("serve/swaps").inc(2)
        reg.counter("serve/failovers").inc()
        reg.gauge("serve/replicas_healthy").set(3)
        mx.telemetry.set_info(weights_version="step_5:abc")
        rep = mx.telemetry.report()
        assert rep["serve_swaps"] == 2
        assert rep["serve_failovers"] == 1
        assert rep["serve_replicas_healthy"] == 3
        assert rep["serve_dropped"] == 0
        assert rep["weights_version"] == "step_5:abc"
        mx.telemetry.reset()

    def test_telemetry_report_tool_prints_serve_family(self, tmp_path,
                                                       capsys):
        import sys

        sys.path.insert(0, os.path.join(
            os.path.dirname(__file__), "..", "tools"))
        import telemetry_report

        report = {
            "weights_version": "step_7:123",
            "counters": {"serve/swaps": 1, "serve/failovers": 2,
                         "serve/dropped": 1, "launch/restarts": 3},
            "gauges": {"serve/replicas_healthy": 1},
        }
        p = tmp_path / "report.json"
        p.write_text(json.dumps(report))
        telemetry_report._print_serve_family(str(p))
        out = capsys.readouterr().out
        assert "Self-healing serving" in out
        assert "serve/failovers" in out and "2" in out
        assert "launch/restarts" in out
        assert "WARNING" in out  # dropped > 0


# -------------------------------------------------------------- transport
class TestTransport:
    """In-process RPC protocol tests (no worker processes): schema,
    timeouts, streaming, and the transport fault points."""

    def _server(self, handlers, name="srv"):
        return RpcServer(handlers, name=name).start()

    def test_roundtrip_and_unknown_verb(self):
        srv = self._server({"ping": lambda m, r: r(pong=True, who="srv")})
        cli = RpcClient(("127.0.0.1", srv.port), name="cli").connect(
            budget_s=5.0)
        try:
            out = cli.call("ping", timeout_s=5.0)
            assert out["pong"] and out["who"] == "srv"
            with pytest.raises(MXNetError, match="unknown verb"):
                cli.call("bogus", timeout_s=5.0)
        finally:
            cli.close()
            srv.stop()

    def test_per_call_timeout(self):
        srv = self._server({"slow": lambda m, r: None})  # never replies
        cli = RpcClient(("127.0.0.1", srv.port), name="cli").connect(
            budget_s=5.0)
        try:
            t0 = time.perf_counter()
            with pytest.raises(TransportError, match="timed out"):
                cli.call("slow", timeout_s=0.2)
            assert time.perf_counter() - t0 < 5.0
            # the connection survives a timed-out call
            assert cli.dead is None
        finally:
            cli.close()
            srv.stop()

    def test_connect_refused_within_budget(self):
        cli = RpcClient(("127.0.0.1", 1), name="nobody")
        with pytest.raises(TransportError, match="could not connect"):
            cli.connect(budget_s=0.3)

    def test_submit_streams_then_resolves(self):
        def submit(msg, respond):
            respond(done=False, stream=[1, 2])
            respond(done=False, stream=[3])
            respond(tokens=[1, 2, 3], weights_version="v7",
                    queue_wait_ms=1.5, replica="srv")

        srv = self._server({"submit": submit})
        cli = RpcClient(("127.0.0.1", srv.port), name="cli").connect(
            budget_s=5.0)
        try:
            fut = cli.submit([9, 9], 3)
            chunks = list(fut.tokens_iter(timeout=10.0))
            assert [t for c in chunks for t in c] == [1, 2, 3]
            assert fut.result(timeout=10) == [1, 2, 3]
            assert fut.weights_version == "v7" and fut.replica == "srv"
        finally:
            cli.close()
            srv.stop()

    def test_remote_error_maps_to_local_class(self):
        def submit(msg, respond):
            respond(ok=False, error={"type": "Backpressure",
                                     "message": "pool full"})

        srv = self._server({"submit": submit})
        cli = RpcClient(("127.0.0.1", srv.port), name="cli").connect(
            budget_s=5.0)
        try:
            fut = cli.submit([1], 2)
            with pytest.raises(Backpressure, match="pool full"):
                fut.result(timeout=10)
        finally:
            cli.close()
            srv.stop()

    def test_recv_fault_kills_connection_and_fails_pending(self):
        """The `transport.recv` point in raise mode = a dropped link:
        every pending call fails with the client's dead_error and the
        client reports dead (the router's eviction signal)."""
        srv = self._server({"submit": lambda m, r: None})  # holds forever
        cli = RpcClient(("127.0.0.1", srv.port), name="cli-drop",
                        dead_error=ReplicaUnavailable).connect(budget_s=5.0)
        try:
            fut = cli.submit([1, 2], 2)
            assert not fut.done()
            faults.inject("transport.recv", times=1, match="cli-drop")
            # next inbound frame attempt trips the fault in the reader
            srv_conns = srv._conns
            deadline = time.perf_counter() + 10
            while not srv_conns and time.perf_counter() < deadline:
                time.sleep(0.01)
            for conn in list(srv_conns):
                conn.send({"id": 999, "ok": True, "done": True})
            deadline = time.perf_counter() + 10
            while cli.dead is None and time.perf_counter() < deadline:
                time.sleep(0.01)
            assert cli.dead is not None
            with pytest.raises(ReplicaUnavailable):
                fut.result(timeout=10)
        finally:
            cli.close()
            srv.stop()

    def test_send_fault_marks_dead(self):
        srv = self._server({"ping": lambda m, r: r(pong=True)})
        cli = RpcClient(("127.0.0.1", srv.port), name="cli-send",
                        dead_error=ReplicaUnavailable).connect(budget_s=5.0)
        try:
            faults.inject("transport.send", times=1, match="cli-send")
            with pytest.raises(TransportError):
                cli.call("ping", timeout_s=5.0)
            assert cli.dead is not None
        finally:
            cli.close()
            srv.stop()


# ----------------------------------------------------------- load shedding
class TestLoadShedding:
    def _hung_replicas(self, engine, names=("shed-r1", "shed-r2"),
                       delay=0.25):
        for n in names:
            faults.inject("batcher.hang", times=None, delay=delay,
                          match=n)
        return [Replica(n, _batcher(engine, name=n)) for n in names]

    def test_all_degraded_bounds_queue(self, shared_engine):
        """Acceptance: with every replica degraded (backlog past the
        threshold) the router backlog never exceeds shed_max_queue and
        every excess request is shed with Backpressure, counted in
        serve/shed_queue_full."""
        mx.telemetry.reset()
        router = Router(self._hung_replicas(shared_engine),
                        retry_backoff_s=0.01, health_interval_s=0.02,
                        shed_queue_depth=1, shed_max_queue=3)
        rng = np.random.RandomState(31)
        futs, max_backlog = [], 0
        try:
            for p in _prompts(rng, 12):
                futs.append(router.submit(p))
                max_backlog = max(max_backlog, len(router._inflight))
            shed = [f for f in futs
                    if isinstance(f.exception(), Backpressure)]
            assert shed, "no request was shed under a degraded fleet"
            assert max_backlog <= 3, max_backlog
            reg = mx.telemetry.registry()
            assert reg.counter("serve/shed_queue_full").value == len(shed)
            # the admitted ones still complete (bounded, not starved)
            for f in futs:
                if f not in shed:
                    assert isinstance(f.result(timeout=120), list)
        finally:
            router.stop()
            mx.telemetry.reset()

    def test_deadline_infeasible_shed_immediately(self, shared_engine):
        """A deadline the rolling wait p50 cannot meet is shed AT
        admission (serve/shed_deadline) instead of queueing until the
        deadline fails it."""
        mx.telemetry.reset()
        router = Router(self._hung_replicas(
            shared_engine, names=("shed-r3", "shed-r4")),
            retry_backoff_s=0.01, health_interval_s=0.02,
            shed_queue_depth=1, shed_max_queue=64)
        rng = np.random.RandomState(32)
        try:
            # occupy both replicas so the fleet counts as degraded
            pinned = [router.submit(p) for p in _prompts(rng, 2)]
            time.sleep(0.05)
            with router._lock:  # prime the rolling wait window
                router._recent_waits.extend([200.0] * 10)
            doomed = router.submit(rng.randint(3, 61, (5,))
                                   .astype(np.int32), deadline_ms=50.0)
            assert isinstance(doomed.exception(), Backpressure)
            assert mx.telemetry.registry().counter(
                "serve/shed_deadline").value == 1
            # a feasible deadline is still admitted
            ok = router.submit(rng.randint(3, 61, (5,)).astype(np.int32),
                               deadline_ms=60_000.0)
            assert isinstance(ok.result(timeout=120), list)
            for f in pinned:
                f.result(timeout=120)
        finally:
            router.stop()
            mx.telemetry.reset()

    def test_healthy_replica_keeps_admission_open(self, shared_engine):
        """Shedding must NOT engage while any replica is in good shape —
        placement, not admission control, handles partial degradation."""
        faults.inject("batcher.hang", times=None, delay=0.25,
                      match="shed-r5")
        reps = [Replica("shed-r5", _batcher(shared_engine, name="shed-r5")),
                Replica("shed-ok", _batcher(shared_engine, name="shed-ok"))]
        router = Router(reps, retry_backoff_s=0.01,
                        health_interval_s=0.02, shed_queue_depth=3,
                        shed_max_queue=2)
        rng = np.random.RandomState(33)
        try:
            futs = []
            for p in _prompts(rng, 6):
                futs.append(router.submit(p))
                time.sleep(0.05)  # the healthy replica keeps draining
            assert not any(isinstance(f.exception(), Backpressure)
                           for f in futs)
            for f in futs:
                assert isinstance(f.result(timeout=120), list)
        finally:
            router.stop()

    def test_report_shed_fields_and_transport_section(self, tmp_path,
                                                      capsys):
        import sys

        sys.path.insert(0, os.path.join(
            os.path.dirname(__file__), "..", "tools"))
        import telemetry_report

        report = {
            "counters": {"serve/shed_queue_full": 4,
                         "serve/shed_deadline": 2,
                         "transport/reconnects": 1,
                         "transport/errors": 1},
            "histograms": {"transport/rpc_ms":
                           {"p50": 1.0, "p95": 2.0, "count": 9}},
        }
        p = tmp_path / "report.json"
        p.write_text(json.dumps(report))
        telemetry_report._print_transport_family(str(p))
        out = capsys.readouterr().out
        assert "Cross-process transport" in out
        assert "transport/rpc_ms" in out
        assert "serve/shed_queue_full" in out
        assert "shed at router admission" in out  # shed warning
        assert "dead worker connection" in out    # error warning


# ------------------------------------------------------------ cross-process
def _spawn_pair(tmp_path, ckpt_dir, n=2, **kw):
    wkw = dict(model=dict(seed=0), max_len=24, bucket_keys=(8,), slots=2,
               max_new=4, ckpt_dir=ckpt_dir, extra_env=WORKER_ENV,
               heartbeat_s=0.1)
    wkw.update(kw)
    return [spawn_worker(str(tmp_path / f"w{i}"), name=f"w{i}", **wkw)
            for i in range(n)]


@pytest.mark.chaos
class TestCrossProcess:
    def test_sigkill_failover_respawn_and_coordinated_swap(self, tmp_path):
        """THE cross-process acceptance scenario: 2 real worker
        processes under load; a coordinated swap lands, then one worker
        is SIGKILL'd mid-decode. Zero lost requests, exactly one
        failover, the factory respawns a REAL process that rejoins at
        the swapped version, every live process reports ONE coherent
        version tag, and post-swap greedy tokens are bit-identical to a
        fresh in-process engine from the same checkpoint."""
        mx.telemetry.reset()
        ckpt = str(tmp_path / "ckpt")
        handles = _spawn_pair(tmp_path, ckpt)
        made = []

        def factory():
            h = spawn_worker(str(tmp_path / f"w{2 + len(made)}"),
                             name=f"w{2 + len(made)}", model=dict(seed=0),
                             max_len=24, bucket_keys=(8,), slots=2,
                             max_new=4, ckpt_dir=ckpt,
                             extra_env=WORKER_ENV, heartbeat_s=0.1)
            made.append(h)
            return RemoteReplica.spawning(h, heartbeat_stale_s=1.0)

        reps = [RemoteReplica(h.name, address=h.address,
                              heartbeat_path=h.heartbeat_path,
                              heartbeat_stale_s=1.0) for h in handles]
        router = Router(reps, retry_backoff_s=0.02,
                        health_interval_s=0.05, replica_factory=factory,
                        respawn_backoff_s=0.05, no_replica_timeout_s=60.0)
        net_b = make_transformer_net(seed=1)
        cs.save_sharded(os.path.join(ckpt, "step_1"),
                        {n: p._data.data
                         for n, p in net_b.collect_params().items()})
        watcher = CheckpointWatcher(router.engines, ckpt, start=False)
        rng = np.random.RandomState(17)
        futs, swap_ver = [], None
        try:
            for i, p in enumerate(_prompts(rng, 30)):
                futs.append(router.submit(p))
                if i == 8:
                    swap_ver = watcher.poll_once()
                    assert swap_ver is not None
                if i == 16:
                    handles[1].kill()  # SIGKILL mid-decode
                time.sleep(0.01)
            results = [f.result(timeout=240) for f in futs]
            assert all(isinstance(r, list) for r in results)
            reg = mx.telemetry.registry()
            assert reg.counter("serve/failovers").value == 1
            assert reg.counter("serve/dropped").value == 0
            versions = {f.weights_version for f in futs}
            assert versions == {"v0", swap_ver}, versions
            # respawned process rejoins, healthy, on the swapped version
            deadline = time.perf_counter() + 120
            live = []
            while time.perf_counter() < deadline:
                live = [r for r in router.replicas
                        if not r.evicted and r.healthy]
                if len(live) >= 2:
                    break
                time.sleep(0.1)
            assert len(live) >= 2, "respawned worker never became healthy"
            assert made, "factory never invoked"
            assert {r.weights_version for r in live} == {swap_ver}
            assert reg.counter("serve/replica_restarts").value == 1
            # post-swap greedy tokens bit-identical to a fresh engine
            fresh = InferStep(net_b, max_len=24)
            src = rng.randint(3, 61, (2, 8)).astype(np.int32)
            toks, lens = fresh.decode_n(src, np.array([8, 8], np.int32),
                                        max_new_tokens=4)
            toks, lens = toks.asnumpy(), lens.asnumpy()
            for r in live:
                for row in range(2):
                    got = r.batcher.submit(src[row], 4).result(timeout=120)
                    want = toks[row, :min(int(lens[row]), 4)].tolist()
                    assert got == want, (r.name, got, want)
        finally:
            router.stop()
            for h in handles + made:
                if h.alive():
                    h.terminate()
            for h in handles + made:
                try:
                    h.wait(timeout=60)
                except Exception:  # noqa: BLE001 - teardown best-effort
                    h.kill()
            mx.telemetry.reset()

    def test_sigterm_drains_gracefully(self, tmp_path):
        """SIGTERM mid-load: every already-accepted request is served
        (drained, not dropped), the worker exits 0, and post-drain
        submits are rejected as retriable ReplicaUnavailable."""
        h = _spawn_pair(tmp_path, None, n=1)[0]
        rep = RemoteReplica(h.name, address=h.address,
                            heartbeat_path=h.heartbeat_path)
        rng = np.random.RandomState(19)
        try:
            futs = [rep.batcher.submit(p, 4) for p in _prompts(rng, 6)]
            time.sleep(0.2)  # ensure the worker accepted them
            h.terminate()
            results = [f.result(timeout=240) for f in futs]
            assert all(isinstance(r, list) for r in results)
            assert h.wait(timeout=120) == 0
        finally:
            if h.alive():
                h.kill()
            rep.batcher.stop(drain=False)


# ----------------------------------------------- future-path regressions
class TestFuturePathRegressions:
    """ISSUE 15 host-level regressions for the mxlint
    ``resource-leak.future-path`` findings: every error path that can
    strand a ``GenerationResult`` nobody will ever resolve must fail it
    instead — a stranded future is a caller camped on its deadline."""

    def test_disagg_handoff_wire_failure_fails_the_future(self):
        """``RemoteReplica._disagg_handoff``: the tail ``submit`` (after
        the prefill fallback) dying on the wire must fail the future the
        router holds, not leave it unresolved forever."""
        import types

        from mxnet_tpu.serving.batcher import GenerationResult

        fut = GenerationResult()

        class _DeadClient:
            address = ("127.0.0.1", 9)

            def submit(self, *a, **k):
                raise TransportError("dead socket")

        prefill_rep = types.SimpleNamespace(client=types.SimpleNamespace(
            call=lambda *a, **k: (_ for _ in ()).throw(
                TransportError("prefill worker gone"))))
        me = types.SimpleNamespace(_client=_DeadClient(), name="r-dec")
        # thread body called directly: it must swallow-and-fail, the
        # real thread has nobody above it to catch
        RemoteReplica._disagg_handoff(me, prefill_rep, [3, 4, 5], 4,
                                      None, "interactive", fut)
        assert fut.done()
        with pytest.raises(TransportError, match="dead socket"):
            fut.result(timeout=0)

    def test_submit_disagg_thread_spawn_failure_fails_the_future(
            self, monkeypatch):
        """``RemoteReplica.submit_disagg``: if the handoff thread cannot
        even start, the returned future must carry the error."""
        import types

        from mxnet_tpu.serving import remote as remote_mod

        class _BoomThread:
            def __init__(self, *a, **k):
                pass

            def start(self):
                raise RuntimeError("can't fork")

        monkeypatch.setattr(
            remote_mod, "threading",
            types.SimpleNamespace(Thread=_BoomThread))
        created = []
        real_fut = remote_mod.GenerationResult

        def _capturing():
            f = real_fut()
            created.append(f)
            return f

        monkeypatch.setattr(remote_mod, "GenerationResult", _capturing)
        me = types.SimpleNamespace(
            name="r-dec",
            _disagg_handoff=lambda *a, **k: None)
        with pytest.raises(RuntimeError, match="can't fork"):
            RemoteReplica.submit_disagg(me, object(), [3, 4, 5], 4)
        assert created and created[0].done()
        with pytest.raises(RuntimeError, match="can't fork"):
            created[0].result(timeout=0)

    def test_worker_submit_thread_spawn_failure_fails_the_future(
            self, monkeypatch):
        """``ServingWorker._handle_submit``: a stream-thread spawn
        failure must fail the batcher future (and propagate so the
        dispatch wrapper answers ok=False), not strand the row."""
        import types

        from mxnet_tpu.serving import worker as worker_mod

        failed = []

        class _Fut:
            def done(self):
                return False

            def _fail(self, e):
                failed.append(e)

        fut = _Fut()

        class _BoomThread:
            def __init__(self, *a, **k):
                pass

            def start(self):
                raise RuntimeError("no threads left")

        monkeypatch.setattr(
            worker_mod, "threading",
            types.SimpleNamespace(Thread=_BoomThread))
        me = types.SimpleNamespace(
            _draining=False, role="both", name="w0",
            batcher=types.SimpleNamespace(
                healthy=True, submit=lambda *a, **k: fut),
            _lock=threading.Lock(), _streamers=[],
            _stream_result=lambda *a, **k: None)
        with pytest.raises(RuntimeError, match="no threads left"):
            worker_mod.ServingWorker._handle_submit(
                me, {"prompt": [3, 4, 5], "max_new_tokens": 4},
                lambda **k: True)
        assert len(failed) == 1
        assert "no threads left" in str(failed[0])

    def test_router_submit_placement_raise_fails_the_future(self):
        """``Router.submit``: ``_assign_locked`` raising AFTER the
        request was handed to a replica must fail the outer future every
        holder shares, not strand it."""
        class _StubReplica:
            name = "stub"

        router = Router([_StubReplica()], start=False)
        seen = []

        def _boom(r):
            seen.append(r)  # the replica now "holds" r (and r.outer)
            raise RuntimeError("placement exploded")

        router._shed_reason_locked = lambda r: None
        router._assign_locked = _boom
        with pytest.raises(RuntimeError, match="placement exploded"):
            router.submit(np.array([3, 4, 5], np.int32), 4)
        assert seen and seen[0].outer.done()
        with pytest.raises(RuntimeError, match="placement exploded"):
            seen[0].outer.result(timeout=0)


# ------------------------------------------------------------ chaos smoke
@pytest.mark.chaos
def test_chaos_smoke_swap_and_failover_end_to_end(tmp_path, monkeypatch,
                                                  net_b):
    """Tier-1 chaos scenario, env-spec driven end to end: 2 replicas
    behind a router + checkpoint watcher; MXTPU_FAULT_BATCHER_THREAD
    kills replica r1 mid-load while a hot swap lands. Every future
    resolves, both weight versions served, serve/failovers >= 1, zero
    steady recompiles."""
    mx.telemetry.reset()
    monkeypatch.setenv("MXTPU_FAULT_BATCHER_THREAD",
                       "times=1;after=1;match=r1")
    faults.clear()  # drop the cached (unset) env scan for this point

    net = _make_net(21)
    eng = InferStep(net, max_len=24)
    eng.warmup([(2, 8)], max_new_tokens=4)
    reps = [Replica("r1", _batcher(eng, name="r1")),
            Replica("r2", _batcher(eng, name="r2"))]
    router = Router(reps, retry_backoff_s=0.01, health_interval_s=0.02)
    _save_params(str(tmp_path / "step_1"), net_b)
    watcher = CheckpointWatcher(router.engines, str(tmp_path),
                                start=False)
    rng = np.random.RandomState(13)
    futs = []
    try:
        for i, p in enumerate(_prompts(rng, 24)):
            futs.append(router.submit(p))
            if i == 10:
                assert watcher.poll_once() is not None
            time.sleep(0.002)
        results = [f.result(timeout=120) for f in futs]
    finally:
        router.stop()
        mx.telemetry.disable()
    assert all(isinstance(r, list) for r in results)
    versions = {f.weights_version for f in futs}
    assert "v0" in versions and len(versions) == 2, versions
    reg = mx.telemetry.registry()
    assert reg.counter("serve/failovers").value >= 1
    assert reg.counter("serve/swaps").value == 1
    assert reg.counter("serve/dropped").value == 0
    assert eng.compile_guard.steady_state_recompiles == 0
    mx.telemetry.reset()
