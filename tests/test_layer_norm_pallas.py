"""Fused Pallas LayerNorm: numeric parity (fwd + grads) with the jnp
composition, across the shapes the BERT path uses."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from mxnet_tpu.ops.pallas import layer_norm as pln


def _ref_ln(x, g, b, eps=1e-5):
    mean = jnp.mean(x, axis=-1, keepdims=True)
    var = jnp.var(x, axis=-1, keepdims=True)
    return (x - mean) * jax.lax.rsqrt(var + eps) * g + b


@pytest.mark.parametrize("n,c", [(64, 128), (300, 768), (1, 256),
                                 (257, 512)])
def test_forward_parity(n, c):
    rng = np.random.RandomState(0)
    x = jnp.asarray(rng.randn(n, c).astype(np.float32)) * 3 + 1
    g = jnp.asarray(rng.randn(c).astype(np.float32))
    b = jnp.asarray(rng.randn(c).astype(np.float32))
    out = pln.layer_norm_fused(x, g, b, 1e-5)
    ref = _ref_ln(x, g, b)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=2e-5,
                               atol=2e-5)


def test_forward_parity_bf16():
    rng = np.random.RandomState(1)
    x = jnp.asarray(rng.randn(128, 768).astype(np.float32)).astype(
        jnp.bfloat16
    )
    g = jnp.ones((768,), jnp.float32)
    b = jnp.zeros((768,), jnp.float32)
    out = pln.layer_norm_fused(x, g, b, 1e-5)
    assert out.dtype == jnp.bfloat16
    ref = _ref_ln(x.astype(jnp.float32), g, b)
    np.testing.assert_allclose(
        np.asarray(out).astype(np.float32), np.asarray(ref), rtol=2e-2,
        atol=2e-2,
    )


def test_gradient_parity():
    rng = np.random.RandomState(2)
    x = jnp.asarray(rng.randn(96, 256).astype(np.float32))
    g = jnp.asarray(rng.rand(256).astype(np.float32) + 0.5)
    b = jnp.asarray(rng.randn(256).astype(np.float32))
    dy = jnp.asarray(rng.randn(96, 256).astype(np.float32))

    def loss_fused(x, g, b):
        return (pln.layer_norm_fused(x, g, b, 1e-5) * dy).sum()

    def loss_ref(x, g, b):
        return (_ref_ln(x, g, b) * dy).sum()

    gf = jax.grad(loss_fused, argnums=(0, 1, 2))(x, g, b)
    gr = jax.grad(loss_ref, argnums=(0, 1, 2))(x, g, b)
    for a, r, name in zip(gf, gr, "x g b".split()):
        np.testing.assert_allclose(np.asarray(a), np.asarray(r), rtol=2e-4,
                                   atol=2e-4, err_msg=name)


def test_op_dispatches_to_fused_and_matches():
    from mxnet_tpu import nd

    rng = np.random.RandomState(3)
    x = nd.array(rng.randn(4, 16, 256).astype(np.float32))
    g = nd.array(rng.rand(256).astype(np.float32) + 0.5)
    b = nd.array(rng.randn(256).astype(np.float32))
    out = nd.LayerNorm(x, g, b)
    ref = _ref_ln(x.data, g.data, b.data)
    np.testing.assert_allclose(out.asnumpy(), np.asarray(ref), rtol=2e-5,
                               atol=2e-5)
    # unaligned channel count falls back to the jnp path
    x2 = nd.array(rng.randn(4, 100).astype(np.float32))
    g2 = nd.array(np.ones(100, np.float32))
    b2 = nd.array(np.zeros(100, np.float32))
    out2 = nd.LayerNorm(x2, g2, b2)
    assert np.isfinite(out2.asnumpy()).all()


def test_gluon_layernorm_trains_through_fused():
    import mxnet_tpu as mx
    from mxnet_tpu import autograd, gluon, nd
    from mxnet_tpu.gluon import nn

    net = nn.HybridSequential()
    net.add(nn.Dense(128, flatten=False), nn.LayerNorm(in_channels=128),
            nn.Dense(1, flatten=False))
    net.initialize()
    tr = gluon.Trainer(net.collect_params(), "adam", {"learning_rate": 1e-2})
    x = nd.array(np.random.RandomState(4).rand(16, 8).astype(np.float32))
    y = nd.array(np.random.RandomState(5).rand(16, 1).astype(np.float32))
    losses = []
    for _ in range(40):
        with autograd.record():
            L = ((net(x) - y) ** 2).mean()
        L.backward()
        tr.step(16)
        losses.append(float(L.asscalar()))
    # wiring smoke test (gradient parity is asserted above): loss drops
    assert losses[-1] < losses[0] * 0.8
