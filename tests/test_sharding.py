"""SPMD sharding spine on a forced 4-device CPU mesh: process-global
Mesh, ShardingRules (replicated / FSDP / pattern rules), TrainStep and
InferStep placement, checkpoint round-trip, per-shard memory planning.

Numerics contract (measured, not hoped): batch sharding keeps every
PER-ROW value bitwise identical (all in-row reductions are over
unsharded axes), so sharded forward outputs and greedy decode are
bit-identical to single-device; FSDP parameter sharding is bitwise
transparent w.r.t. the data-parallel step on the same mesh. The
AGGREGATED loss/grads cross the shard boundary through one psum whose
association differs from the single-device reduce, so single-vs-mesh
scalars agree to 1-2 ulp (asserted at 1e-6 abs), not bitwise.
"""

import os

import numpy as np
import pytest

import jax
import jax.numpy as jnp

import mxnet_tpu as mx
from mxnet_tpu import gluon, optimizer as opt
from mxnet_tpu.gluon import nn
from mxnet_tpu import parallel
from mxnet_tpu.parallel import InferStep, TrainStep
from mxnet_tpu.parallel import PartitionSpec as P
from mxnet_tpu.parallel import sharding as shard


@pytest.fixture(autouse=True)
def _clean_sharding_state():
    yield
    shard.reset_global_mesh()
    shard.reset_default_rules()


def mesh4():
    return shard.make_global_mesh({"data": 4}, devices=jax.devices()[:4])


# -------------------------------------------------------------- mesh spec
def test_parse_mesh_spec():
    assert shard.parse_mesh_spec(None) is None
    assert shard.parse_mesh_spec("off") is None
    assert shard.parse_mesh_spec("0") is None
    assert shard.parse_mesh_spec("4") == {"data": 4}
    assert shard.parse_mesh_spec("2x2") == {"data": 2, "model": 2}
    assert shard.parse_mesh_spec("data=2,model=2") == {
        "data": 2, "model": 2}
    assert shard.parse_mesh_spec("auto") == {"data": -1}
    with pytest.raises(mx.MXNetError):
        shard.parse_mesh_spec("data=2,oops")


def test_make_global_mesh_subset_and_fill():
    m = shard.make_global_mesh({"data": 4})
    assert m.shape == {"data": 4}  # first 4 of the 8 visible devices
    m = shard.make_global_mesh({"data": -1})
    assert m.shape == {"data": 8}
    m = shard.make_global_mesh({"data": -1, "model": 2})
    assert m.shape == {"data": 4, "model": 2}
    with pytest.raises(mx.MXNetError):
        shard.make_global_mesh({"data": 16})


def test_global_mesh_env_and_pin(monkeypatch):
    monkeypatch.setenv("MXTPU_MESH", "data=4")
    shard.reset_global_mesh()
    m = shard.global_mesh()
    assert m is not None and m.shape == {"data": 4}
    # an explicit pin overrides the env — including pinning "no mesh"
    shard.set_global_mesh(None)
    assert shard.global_mesh() is None
    m2 = mesh4()
    shard.set_global_mesh(m2)
    assert shard.global_mesh() is m2


# ------------------------------------------------------------------ rules
def test_fsdp_partition_spec():
    assert shard.fsdp_partition_spec((64, 8), "data", 4) == P("data")
    assert shard.fsdp_partition_spec((6, 64), "data", 4) == P(None, "data")
    assert shard.fsdp_partition_spec((5, 7), "data", 4) == P()
    # largest divisible dim wins
    assert shard.fsdp_partition_spec((8, 128), "data", 4) == \
        P(None, "data")


def test_rules_resolution_and_env_default(monkeypatch):
    m = mesh4()
    r = shard.ShardingRules.resolve("fsdp")
    assert r.params == "fsdp" and r.fsdp_axis == "data"
    assert shard.ShardingRules.resolve("fsdp:model").fsdp_axis == "model"
    assert shard.ShardingRules.resolve("replicated").params == "replicate"
    with pytest.raises(mx.MXNetError):
        shard.ShardingRules.resolve("bogus")
    assert shard.ShardingRules.resolve(None) is None  # env unset
    monkeypatch.setenv("MXTPU_SHARDING", "fsdp")
    shard.reset_default_rules()
    r = shard.ShardingRules.resolve(None)
    assert r is not None and r.params == "fsdp"
    assert r.batch_partition_spec(m) == P("data")


def test_rules_param_explain():
    m = mesh4()
    r = shard.ShardingRules.fsdp(min_size=32, rules=[
        (r"special_weight$", P(None, "data"))])
    spec, why = r.param_explain("x_special_weight", (8, 8), m)
    assert spec == P(None, "data") and why.startswith("rule:")
    spec, why = r.param_explain("w", (64, 16), m)
    assert spec == P("data") and why == "fsdp"
    spec, why = r.param_explain("tiny", (4,), m)
    assert spec == P() and why == "replicated:small"
    spec, why = r.param_explain("odd", (7, 9), m)
    assert spec == P() and why == "replicated:indivisible"
    spec, why = shard.ShardingRules.replicated().param_explain(
        "w", (64, 16), m)
    assert spec == P() and why == "replicated:default"


# ------------------------------------------------- TrainStep DP/FSDP parity
def _mlp(x, seed=7):
    mx.random.seed(seed)
    net = nn.HybridSequential()
    with net.name_scope():
        net.add(nn.Dense(64, activation="relu"), nn.Dense(8))
    net.initialize()
    net(mx.nd.array(x))
    return net


def test_dp_and_fsdp_step_parity():
    """DP losses match single-device to 1-2 ulp; FSDP is bitwise
    identical to DP on the same mesh; FSDP params/moments are actually
    partitioned; final params match unsharded within fp32 tolerance."""
    np.random.seed(0)
    x = np.random.randn(16, 16).astype("float32")
    y = np.random.randint(0, 8, 16)
    loss_fn = gluon.loss.SoftmaxCrossEntropyLoss()
    m = mesh4()

    def run(mesh=None, sharding=None, steps=5):
        net = _mlp(x)
        step = TrainStep(net, loss_fn, opt.Adam(learning_rate=1e-3),
                         mesh=mesh, sharding=sharding)
        losses = [float(step(mx.nd.array(x), mx.nd.array(y)).asscalar())
                  for _ in range(steps)]
        step.sync_params()
        params = {k.split("dense")[-1]: v.data().asnumpy()
                  for k, v in net.collect_params().items()}
        return losses, params, step

    losses_1, params_1, _ = run()
    losses_dp, params_dp, _ = run(mesh=m, sharding="replicated")
    fsdp = shard.ShardingRules.fsdp(min_size=32)
    losses_fs, params_fs, step_fs = run(mesh=m, sharding=fsdp)

    # FSDP vs DP: parameter sharding is bitwise transparent
    assert losses_fs == losses_dp
    # mesh vs single device: one psum association apart (1-2 ulp)
    np.testing.assert_allclose(losses_dp, losses_1, rtol=0, atol=1e-6)
    for k in params_1:
        np.testing.assert_allclose(params_fs[k], params_1[k], rtol=1e-5,
                                   atol=1e-6)
    # the big weights and their Adam moments really are partitioned
    w = [n for n in step_fs._train_vals if n.endswith("dense0_weight")][0]
    v = step_fs._train_vals[w]
    assert v.sharding.shard_shape(v.shape) == (16, 16)  # (64,16)/4
    for s in step_fs._opt_state[w]:
        assert s.sharding.shard_shape(s.shape) == (16, 16)
    summary = shard.shard_summary(step_fs._values, m)
    assert summary["params_sharded"] >= 2
    assert summary["param_bytes_per_shard"] < summary["param_bytes_total"]


def test_trainstep_adopts_global_mesh_and_env_rules(monkeypatch):
    monkeypatch.setenv("MXTPU_SHARDING", "fsdp")
    monkeypatch.setenv("MXTPU_FSDP_MIN_SIZE", "32")
    shard.reset_default_rules()
    shard.set_global_mesh(mesh4())
    x = np.random.randn(8, 16).astype("float32")
    net = _mlp(x)
    step = TrainStep(net, gluon.loss.SoftmaxCrossEntropyLoss(),
                     opt.SGD(learning_rate=0.1))  # no mesh= anywhere
    assert step._mesh is shard.global_mesh()
    w = [n for n in step._train_vals if n.endswith("dense0_weight")][0]
    assert step._train_vals[w].sharding.shard_shape(
        step._train_vals[w].shape) == (16, 16)
    L = step(mx.nd.array(x), mx.nd.array(np.random.randint(0, 8, 8)))
    assert np.isfinite(float(L.asscalar()))


# ------------------------------------------- recompiles / prefetch contract
def test_sharded_donated_state_zero_steady_recompiles():
    np.random.seed(1)
    loss_fn = gluon.loss.SoftmaxCrossEntropyLoss()
    m = mesh4()
    x = np.random.randn(16, 16).astype("float32")
    net = _mlp(x)
    step = TrainStep(net, loss_fn, opt.Adam(learning_rate=1e-3), mesh=m,
                     sharding=shard.ShardingRules.fsdp(min_size=32))
    sigs = [(((bs, 16), "float32"), ((bs,), "int64")) for bs in (8, 16)]
    compiled = step.warmup(sigs)
    assert compiled == 2
    for bs in (8, 16, 8, 16, 16):
        xb = np.random.randn(bs, 16).astype("float32")
        yb = np.random.randint(0, 8, bs)
        step(mx.nd.array(xb), mx.nd.array(yb))
    assert step.compile_guard.steady_state_recompiles == 0


def test_feed_spec_and_device_put_batch_sharded():
    """The prefetch placement contract stages batches straight onto the
    mesh placements; the pre-placed fast path is bit-identical."""
    np.random.seed(2)
    x = np.random.randn(16, 16).astype("float32")
    y = np.random.randint(0, 8, 16)
    loss_fn = gluon.loss.SoftmaxCrossEntropyLoss()
    m = mesh4()

    def build():
        net = _mlp(x)
        return TrainStep(net, loss_fn, opt.Adam(learning_rate=1e-3),
                         mesh=m,
                         sharding=shard.ShardingRules.fsdp(min_size=32))

    step_a = build()
    fs = step_a.feed_spec()
    assert fs["mesh"] is m
    assert fs["sharding"]["params"] == "fsdp"
    db = step_a.device_put_batch((mx.nd.array(x), mx.nd.array(y)))
    assert db.batch[0].sharding.is_equivalent_to(
        fs["data_sharding"], db.batch[0].ndim)
    l_fast = float(step_a(db).asscalar())
    step_b = build()
    l_raw = float(step_b(mx.nd.array(x), mx.nd.array(y)).asscalar())
    assert l_fast == l_raw


# ------------------------------------------------------- InferStep sharded
def _tiny_transformer(vocab=128, units=32, max_len=64):
    from mxnet_tpu.gluon.model_zoo.transformer import TransformerModel
    from mxnet_tpu import nd

    mx.random.seed(11)
    net = TransformerModel(
        src_vocab=vocab, tgt_vocab=vocab, units=units,
        hidden_size=units * 2, num_layers=1, num_heads=2,
        max_length=max_len, dropout=0.0)
    net.initialize(mx.initializer.Xavier())
    net._probe_shapes(nd.zeros((2, 8), dtype="int32"),
                      nd.zeros((2, 8), dtype="int32"))
    return net


def test_infer_step_sharded_forward_and_greedy_decode_identical():
    """Batch sharding (replicated params) keeps every per-row value
    bitwise stable, so the data-parallel engine's forward outputs AND
    greedy decode trajectory are IDENTICAL to the unsharded engine.
    FSDP additionally shards contraction dims (partial-dot + psum), so
    its forward agrees at ulp level and the greedy trajectory still
    matches (logit gaps are orders of magnitude above the psum noise)."""
    net = _tiny_transformer()
    rng = np.random.RandomState(3)
    src = rng.randint(3, 128, (4, 12)).astype("int32")
    tgt = rng.randint(3, 128, (4, 12)).astype("int32")
    vl = np.full((4,), 12, "int32")

    eng_plain = InferStep(net, max_len=48)
    m = mesh4()
    eng_dp = InferStep(net, mesh=m, max_len=48, sharding="replicated")
    eng_fs = InferStep(net, mesh=m, max_len=48,
                       sharding=shard.ShardingRules.fsdp(min_size=64))
    # params really sharded in the FSDP serving engine
    summary = shard.shard_summary(eng_fs._values, m)
    assert summary["params_sharded"] >= 1
    assert summary["param_bytes_per_shard"] < summary["param_bytes_total"]

    out_a = eng_plain(src, tgt, vl)
    out_dp = eng_dp(src, tgt, vl)
    out_fs = eng_fs(src, tgt, vl)
    np.testing.assert_array_equal(out_a.asnumpy(), out_dp.asnumpy())
    np.testing.assert_allclose(out_a.asnumpy(), out_fs.asnumpy(),
                               rtol=1e-5, atol=1e-5)

    tok_a, len_a = eng_plain.decode_n(src, vl, max_new_tokens=8)
    tok_dp, len_dp = eng_dp.decode_n(src, vl, max_new_tokens=8)
    tok_fs, len_fs = eng_fs.decode_n(src, vl, max_new_tokens=8)
    np.testing.assert_array_equal(tok_a.asnumpy(), tok_dp.asnumpy())
    np.testing.assert_array_equal(len_a.asnumpy(), len_dp.asnumpy())
    np.testing.assert_array_equal(tok_a.asnumpy(), tok_fs.asnumpy())


def test_fsdp_model_exceeding_one_device_budget(monkeypatch):
    """The FSDP acceptance: a model whose FULL fp32 step does not fit one
    simulated device's budget (per memory_analysis) trains AND serves
    once sharded 4 ways."""
    np.random.seed(4)
    loss_fn = gluon.loss.SoftmaxCrossEntropyLoss()
    m = mesh4()
    x = np.random.randn(16, 64).astype("float32")
    mx.random.seed(5)
    net = nn.HybridSequential()
    with net.name_scope():
        net.add(nn.Dense(256, activation="relu"), nn.Dense(8))
    net.initialize()
    net(mx.nd.array(x))
    step = TrainStep(net, loss_fn, opt.Adam(learning_rate=1e-3), mesh=m,
                     sharding=shard.ShardingRules.fsdp(min_size=32))
    sig = (((16, 64), "float32"), ((16,), "int64"))
    ma = step.memory_analysis(sig)
    assert ma["mesh_devices"] == 4
    assert ma["peak_bytes_per_shard"] == ma["peak_bytes_estimate"] // 4
    # a budget one shard fits but the full program does not
    budget = (ma["peak_bytes_per_shard"] + ma["peak_bytes_estimate"]) // 2
    monkeypatch.setenv("MXTPU_HBM_BYTES", str(budget))
    monkeypatch.setenv("MXTPU_HBM_HEADROOM", "1.0")
    assert parallel.hbm_budget_bytes() == budget
    assert ma["peak_bytes_estimate"] > budget  # full model does NOT fit
    assert ma["peak_bytes_per_shard"] < budget  # one shard does
    for _ in range(2):
        L = step(mx.nd.array(x), mx.nd.array(np.random.randint(0, 8, 16)))
        assert np.isfinite(float(L.asscalar()))
    # and the same rules serve it (sharded jitted forward)
    eng = InferStep(net, mesh=m,
                    sharding=shard.ShardingRules.fsdp(min_size=32))
    out = eng(mx.nd.array(x))
    assert np.isfinite(out.asnumpy()).all()


def test_plan_batch_bisects_per_shard_budget():
    np.random.seed(6)
    loss_fn = gluon.loss.SoftmaxCrossEntropyLoss()
    m = mesh4()
    x = np.random.randn(16, 32).astype("float32")
    net = _mlp(x)
    step = TrainStep(net, loss_fn, opt.Adam(learning_rate=1e-3), mesh=m,
                     sharding=shard.ShardingRules.fsdp(min_size=32))

    def sig(bs):
        return (((bs, 32), "float32"), ((bs,), "int64"))

    ma = step.memory_analysis(sig(8))
    budget = (ma["peak_bytes_per_shard"] + ma["peak_bytes_estimate"]) // 2
    b_shard, _ = parallel.plan_batch(step, sig, budget, start=4,
                                     max_batch=64)
    b_global, _ = parallel.plan_batch(step, sig, budget, start=4,
                                      max_batch=64, per_shard=False)
    # one device's budget admits a ~4x larger batch once the mesh splits
    # the working set (per-shard bisection is the planning default)
    assert b_shard > b_global


# ----------------------------------------------------- checkpoint roundtrip
def test_checkpoint_sharded_roundtrip_fsdp(tmp_path):
    np.random.seed(7)
    x = np.random.randn(16, 16).astype("float32")
    y = np.random.randint(0, 8, 16)
    loss_fn = gluon.loss.SoftmaxCrossEntropyLoss()
    m = mesh4()
    rules = shard.ShardingRules.fsdp(min_size=32)

    def build():
        net = _mlp(x)
        return TrainStep(net, loss_fn, opt.Adam(learning_rate=1e-3),
                         mesh=m, sharding=rules)

    step_a = build()
    step_a(mx.nd.array(x), mx.nd.array(y))
    ckpt = str(tmp_path / "ck")
    step_a.save_checkpoint(ckpt)
    ref = step_a.state_dict()

    step_b = build()
    step_b.load_checkpoint(ckpt)
    got = step_b.state_dict()
    for name, v in ref["values"].items():
        np.testing.assert_array_equal(np.asarray(v),
                                      np.asarray(got["values"][name]))
    # restored arrays carry the declared FSDP placements
    w = [n for n in step_b._train_vals if n.endswith("dense0_weight")][0]
    v = step_b._train_vals[w]
    assert v.sharding.shard_shape(v.shape) == (16, 16)
    # the loaded step trains on (donated sharded state round-trips)
    L = step_b(mx.nd.array(x), mx.nd.array(y))
    assert np.isfinite(float(L.asscalar()))


def test_load_sharded_replaces_under_mesh(tmp_path):
    """Low-level NamedSharded round-trip: the saved PartitionSpec is
    recorded and restore re-places under the CURRENT mesh without the
    caller passing shardings (and without a full host gather — each
    shard reads only its overlapping pieces)."""
    from mxnet_tpu import checkpoint_sharded as cs
    from jax.sharding import NamedSharding

    m = mesh4()
    a = jax.device_put(jnp.arange(64, dtype=jnp.float32).reshape(8, 8),
                       NamedSharding(m, P("data")))
    b = jax.device_put(jnp.ones((4,), jnp.float32), NamedSharding(m, P()))
    d = str(tmp_path / "ck")
    cs.save_sharded(d, {"a": a, "b": b})
    out = cs.load_sharded(d, mesh=m)
    np.testing.assert_array_equal(np.asarray(out["a"]), np.asarray(a))
    assert out["a"].sharding.is_equivalent_to(a.sharding, a.ndim)
    assert out["b"].sharding.is_equivalent_to(b.sharding, b.ndim)
    # resharding onto no mesh still restores (single-device placement)
    out2 = cs.load_sharded(d)
    np.testing.assert_array_equal(np.asarray(out2["a"]), np.asarray(a))


# ----------------------------------------------------- telemetry / trainer
def test_shard_telemetry_family_and_report():
    np.random.seed(8)
    x = np.random.randn(16, 16).astype("float32")
    net = _mlp(x)
    TrainStep(net, gluon.loss.SoftmaxCrossEntropyLoss(),
              opt.Adam(learning_rate=1e-3), mesh=mesh4(),
              sharding=shard.ShardingRules.fsdp(min_size=32))
    rep = mx.telemetry.report()
    assert rep["mesh_shape"] == "data=4"
    assert rep["sharding"].startswith("fsdp")
    assert rep["shard_param_bytes_total"] > \
        rep["shard_param_bytes_per_shard"] > 0
    assert rep["shard_collective_bytes_per_step"] > 0
    g = mx.telemetry.registry().snapshot()["gauges"]
    assert g["shard/mesh_devices"] == 4
    assert g["shard/params_sharded"] >= 2


def test_mesh_spans_processes_and_trainer_skip(monkeypatch):
    # single-process: never claims to span
    assert not shard.mesh_spans_processes(mesh4())
    # a fake 2-process mesh covering both processes
    class _Dev:
        def __init__(self, p):
            self.process_index = p

    class _FakeMesh:
        devices = np.array([_Dev(0), _Dev(1)])

    monkeypatch.setattr(jax, "process_count", lambda: 2)
    assert shard.mesh_spans_processes(_FakeMesh())
    # and one that leaves process 1 out does NOT own cross-process sync
    class _LocalMesh:
        devices = np.array([_Dev(0), _Dev(0)])

    assert not shard.mesh_spans_processes(_LocalMesh())

    # Trainer: with a spanning mesh the host push/pull loop is skipped
    x = np.random.randn(8, 16).astype("float32")
    net = _mlp(x)
    trainer = gluon.Trainer(net.collect_params(), "sgd",
                            {"learning_rate": 0.1})

    class _BoomKV:
        num_workers = 2

        def push(self, *a, **k):
            raise AssertionError("host allreduce must be skipped")

        pull = push
        init = push

    trainer._kvstore = _BoomKV()
    trainer._kv_initialized = True
    trainer._update_on_kvstore = False
    monkeypatch.setattr(shard, "mesh_spans_processes", lambda mesh=None: True)
    trainer._allreduce_grads()  # must not touch the kvstore


# ------------------------------------------------------------ estimator
def test_estimator_fused_train_step_fit():
    from mxnet_tpu.gluon.contrib.estimator import Estimator

    np.random.seed(9)
    x = np.random.randn(16, 16).astype("float32")
    y = np.random.randint(0, 8, 16)
    net = _mlp(x)
    step = TrainStep(net, gluon.loss.SoftmaxCrossEntropyLoss(),
                     opt.Adam(learning_rate=1e-3), mesh=mesh4(),
                     sharding=shard.ShardingRules.fsdp(min_size=32))
    est = Estimator(net, gluon.loss.SoftmaxCrossEntropyLoss(),
                    train_step=step)
    assert est.trainer is None  # the step owns the optimizer
    data = [(mx.nd.array(x), mx.nd.array(y)) for _ in range(3)]
    est.fit(data, epochs=2,
            warmup=[(((16, 16), "float32"), ((16,), "int64"))])
    assert step.compile_guard.steady_state_recompiles == 0
    assert np.isfinite(est.train_loss_metric.get()[1])
    losses = [float(step(mx.nd.array(x), mx.nd.array(y)).asscalar())]
    assert np.isfinite(losses[0])
