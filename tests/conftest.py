"""Test config: run everything on an 8-device virtual CPU mesh.

Mirrors the reference's test leverage (SURVEY.md section 4): one suite,
re-runnable across contexts; distributed behavior tested in-process — here by
asking XLA for 8 virtual CPU devices so every sharding/collective path
compiles and executes without TPU hardware (the driver separately dry-runs
the multi-chip path).
"""

import os

# must be set before jax import; FORCE cpu — the session environment pins
# JAX_PLATFORMS to the tunneled TPU (axon), but the suite needs the 8-device
# virtual CPU mesh (set MXTPU_TEST_PLATFORM to override, e.g. for a TPU run)
os.environ["XLA_FLAGS"] = (
    os.environ.get("XLA_FLAGS", "") + " --xla_force_host_platform_device_count=8"
).strip()
_platform = os.environ.get("MXTPU_TEST_PLATFORM", "cpu")
if _platform != "cpu":
    # keep the host backend registered alongside the accelerator so
    # ctx=mx.cpu() placement (reference semantics) stays real on TPU runs
    _platform = f"{_platform},cpu"
os.environ["JAX_PLATFORMS"] = _platform

import jax

# a pytest plugin may import jax before this conftest runs, freezing the
# env-derived platform config — override through the config API as well
jax.config.update("jax_platforms", _platform)
import numpy as np
import pytest

# numeric parity checks assume true f32 matmuls (TPU perf path uses bf16 via
# AMP explicitly; the default low-precision dot would fail fp32 tolerance)
jax.config.update("jax_default_matmul_precision", "highest")


def pytest_configure(config):
    config.addinivalue_line(
        "markers", "slow: long-running; excluded from the tier-1 run "
        "(-m 'not slow')")
    config.addinivalue_line(
        "markers", "chaos: fault-injected failure-path scenario "
        "(serving resilience; runs in tier-1)")


@pytest.fixture(autouse=True)
def _seed_rng():
    import mxnet_tpu as mx

    mx.random.seed(42)
    np.random.seed(42)
    yield
