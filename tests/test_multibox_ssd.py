"""MultiBox ops + SSD model family (reference:
``src/operator/contrib/multibox_*.cc`` + GluonCV SSD [unverified])."""

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import autograd, gluon, nd
from mxnet_tpu.gluon.model_zoo.ssd import SSDTargetGenerator, ssd_tiny


class TestMultiBoxPrior:
    def test_anchor_count_and_values(self):
        x = nd.zeros((1, 3, 2, 2))
        anchors = nd.MultiBoxPrior(x, sizes=(0.5, 0.25), ratios=(1, 2))
        # A = len(sizes) + len(ratios) - 1 = 3 per pixel
        assert anchors.shape == (1, 2 * 2 * 3, 4)
        a = anchors.asnumpy().reshape(2, 2, 3, 4)
        # pixel (0,0): center (0.25, 0.25), first anchor size 0.5 ratio 1
        np.testing.assert_allclose(
            a[0, 0, 0], [0.25 - 0.25, 0.25 - 0.25, 0.25 + 0.25, 0.5],
            atol=1e-6,
        )
        # second anchor: size 0.25 ratio 1 -> half-width 0.125
        np.testing.assert_allclose(
            a[0, 0, 1], [0.125, 0.125, 0.375, 0.375], atol=1e-6
        )
        # third: size 0.5 ratio 2 -> w = 0.5*sqrt(2), h = 0.5/sqrt(2)
        w, h = 0.5 * np.sqrt(2), 0.5 / np.sqrt(2)
        np.testing.assert_allclose(
            a[0, 0, 2],
            [0.25 - w / 2, 0.25 - h / 2, 0.25 + w / 2, 0.25 + h / 2],
            atol=1e-6,
        )

    def test_clip(self):
        x = nd.zeros((1, 1, 1, 1))
        anchors = nd.MultiBoxPrior(x, sizes=(1.5,), ratios=(1,), clip=True)
        a = anchors.asnumpy()
        assert a.min() >= 0.0 and a.max() <= 1.0


class TestMultiBoxTarget:
    def test_assignment_and_encoding(self):
        # one anchor exactly on the gt, one far away
        anchors = nd.array(np.array(
            [[[0.1, 0.1, 0.3, 0.3], [0.7, 0.7, 0.9, 0.9]]], np.float32
        ))
        labels = nd.array(np.array(
            [[[1.0, 0.1, 0.1, 0.3, 0.3]]], np.float32
        ))  # class 1 at the first anchor
        cls_preds = nd.zeros((1, 3, 2))  # (B, num_cls+1, N)
        bt, bm, ct = nd.MultiBoxTarget(anchors, labels, cls_preds)
        ct = ct.asnumpy()
        assert ct.shape == (1, 2)
        assert ct[0, 0] == 2.0  # class 1 -> target 2 (bg=0)
        assert ct[0, 1] == 0.0  # background
        bm = bm.asnumpy().reshape(1, 2, 4)
        assert bm[0, 0].sum() == 4.0 and bm[0, 1].sum() == 0.0
        bt = bt.asnumpy().reshape(1, 2, 4)
        np.testing.assert_allclose(bt[0, 0], np.zeros(4), atol=1e-5)

    def test_forced_match_below_threshold(self):
        """Every valid gt claims its best anchor even under the IoU
        threshold (reference bipartite stage)."""
        anchors = nd.array(np.array(
            [[[0.0, 0.0, 0.2, 0.2], [0.5, 0.5, 1.0, 1.0]]], np.float32
        ))
        # gt overlaps anchor 1 only slightly, still must be assigned
        labels = nd.array(np.array(
            [[[0.0, 0.45, 0.45, 0.6, 0.6]]], np.float32
        ))
        cls_preds = nd.zeros((1, 2, 2))
        bt, bm, ct = nd.MultiBoxTarget(anchors, labels, cls_preds,
                                       overlap_threshold=0.9)
        assert ct.asnumpy()[0, 1] == 1.0  # class 0 -> 1

    def test_padded_labels_ignored(self):
        anchors = nd.array(np.array([[[0.1, 0.1, 0.3, 0.3]]], np.float32))
        labels = nd.array(np.array(
            [[[-1.0, 0, 0, 0, 0], [-1.0, 0, 0, 0, 0]]], np.float32
        ))
        cls_preds = nd.zeros((1, 2, 1))
        bt, bm, ct = nd.MultiBoxTarget(anchors, labels, cls_preds)
        assert ct.asnumpy()[0, 0] == 0.0
        assert bm.asnumpy().sum() == 0.0


class TestMultiBoxDetection:
    def test_decode_identity_and_nms(self):
        anchors = nd.array(np.array(
            [[[0.1, 0.1, 0.3, 0.3], [0.11, 0.11, 0.31, 0.31],
              [0.6, 0.6, 0.8, 0.8]]], np.float32
        ))
        # zero offsets -> boxes == anchors
        loc = nd.zeros((1, 12))
        probs = nd.array(np.array(  # (B, num_cls+1, N)
            [[[0.1, 0.2, 0.8], [0.9, 0.8, 0.2]]], np.float32
        ))
        out = nd.MultiBoxDetection(probs, loc, anchors, threshold=0.3,
                                   nms_threshold=0.5).asnumpy()[0]
        kept = out[out[:, 0] >= 0]
        # anchors 0 and 1 overlap heavily -> one suppressed; anchor 2's
        # foreground prob 0.2 falls under the 0.3 score threshold
        assert kept.shape[0] == 1
        np.testing.assert_allclose(kept[0, 2:], [0.1, 0.1, 0.3, 0.3],
                                   atol=1e-5)
        assert kept[0, 0] == 0.0 and abs(kept[0, 1] - 0.9) < 1e-5


class TestSSDModel:
    def test_shapes_consistent(self):
        net = ssd_tiny(num_classes=2)
        net.initialize()
        x = nd.zeros((2, 3, 32, 32))
        anchors, cls_preds, box_preds = net(x)
        N = anchors.shape[1]
        assert cls_preds.shape == (2, N, 3)
        assert box_preds.shape == (2, N * 4)
        # 32->16->8->4 fmaps, 4 anchors each per pixel
        assert N == (16 * 16 + 8 * 8 + 4 * 4) * 4
        # stages into one XLA program too
        net.hybridize()
        a2, c2, b2 = net(x)
        np.testing.assert_allclose(a2.asnumpy(), anchors.asnumpy(),
                                   rtol=1e-6)
        np.testing.assert_allclose(c2.asnumpy(), cls_preds.asnumpy(),
                                   rtol=2e-3, atol=2e-4)

    def test_train_step_decreases_loss(self):
        mx.random.seed(0)
        net = ssd_tiny(num_classes=1)
        net.initialize()
        tgen = SSDTargetGenerator()
        trainer = gluon.Trainer(net.collect_params(), "adam",
                                {"learning_rate": 5e-3})
        ce = gluon.loss.SoftmaxCrossEntropyLoss()
        l1 = gluon.loss.L1Loss()
        rng = np.random.RandomState(0)
        x = nd.array(rng.rand(2, 3, 32, 32).astype(np.float32))
        labels = nd.array(np.array(
            [[[0.0, 0.2, 0.2, 0.5, 0.5]], [[0.0, 0.4, 0.4, 0.8, 0.8]]],
            np.float32,
        ))
        losses = []
        for _ in range(12):
            with autograd.record():
                anchors, cls_preds, box_preds = net(x)
                bt, bm, ct = tgen(anchors, labels, cls_preds)
                L = ce(cls_preds.reshape(-1, 2), ct.reshape(-1)).mean() + \
                    l1(box_preds * bm, bt * bm).mean()
            L.backward()
            trainer.step(2)
            losses.append(float(L.asscalar()))
        assert losses[-1] < losses[0] * 0.8

    def test_detect_finds_planted_object(self):
        """After overfitting on one image, detect() returns a box near the
        planted ground truth."""
        mx.random.seed(1)
        net = ssd_tiny(num_classes=1)
        net.initialize()
        tgen = SSDTargetGenerator()
        trainer = gluon.Trainer(net.collect_params(), "adam",
                                {"learning_rate": 1e-2})
        ce = gluon.loss.SoftmaxCrossEntropyLoss()
        l1 = gluon.loss.L1Loss()
        rng = np.random.RandomState(2)
        x_np = rng.rand(1, 3, 32, 32).astype(np.float32) * 0.1
        x_np[:, :, 8:24, 8:24] = 1.0  # bright square = the object
        x = nd.array(x_np)
        gt = [0.25, 0.25, 0.75, 0.75]
        labels = nd.array(np.array([[[0.0] + gt]], np.float32))
        for _ in range(60):
            with autograd.record():
                anchors, cls_preds, box_preds = net(x)
                bt, bm, ct = tgen(anchors, labels, cls_preds)
                L = ce(cls_preds.reshape(-1, 2), ct.reshape(-1)).mean() + \
                    0.5 * l1(box_preds * bm, bt * bm).mean()
            L.backward()
            trainer.step(1)
        det = net.detect(x).asnumpy()[0]
        best = det[np.argmax(det[:, 1])]
        assert best[0] == 0.0  # class 0 found
        from mxnet_tpu.ops.contrib import box_iou
        import jax.numpy as jnp

        iou = float(np.asarray(box_iou(
            jnp.asarray(best[None, None, 2:]),
            jnp.asarray(np.array([[gt]], np.float32)),
        )).reshape(-1)[0])
        assert iou > 0.4, (best, iou)


class TestReviewRegressions:
    def test_steps_offsets_are_y_then_x(self):
        x = nd.zeros((1, 1, 2, 4))  # H=2, W=4
        a = nd.MultiBoxPrior(x, sizes=(0.1,), ratios=(1,),
                             steps=(0.5, 0.25), offsets=(0.5, 0.5))
        a = a.asnumpy().reshape(2, 4, 1, 4)
        # center of pixel (0,0): y = 0.5*0.5 = 0.25, x = 0.5*0.25 = 0.125
        cx = (a[0, 0, 0, 0] + a[0, 0, 0, 2]) / 2
        cy = (a[0, 0, 0, 1] + a[0, 0, 0, 3]) / 2
        np.testing.assert_allclose([cx, cy], [0.125, 0.25], atol=1e-6)

    def test_nonsquare_aspect_scaling(self):
        """size-s ratio-1 anchors are square in pixel space (reference
        in_height/in_width factor)."""
        x = nd.zeros((1, 1, 2, 4))  # H=2, W=4 -> aspect 0.5
        a = nd.MultiBoxPrior(x, sizes=(0.4,), ratios=(1,))
        a = a.asnumpy().reshape(-1, 4)[0]
        w, h = a[2] - a[0], a[3] - a[1]
        np.testing.assert_allclose(w, 0.4 * 2 / 4, atol=1e-6)
        np.testing.assert_allclose(h, 0.4, atol=1e-6)

    def test_padded_gt_cannot_steal_anchor_zero(self):
        """Padding rows must not clobber a valid gt's forced match at
        anchor 0 (duplicate-scatter race)."""
        anchors = nd.array(np.array(
            [[[0.4, 0.4, 0.6, 0.6], [0.0, 0.0, 0.1, 0.1]]], np.float32
        ))
        # valid gt matches anchor 0; THEN padding rows (argmax of all -1
        # IoU lands on anchor 0 too)
        labels = nd.array(np.array(
            [[[2.0, 0.4, 0.4, 0.6, 0.6],
              [-1.0, 0, 0, 0, 0], [-1.0, 0, 0, 0, 0]]], np.float32
        ))
        cls_preds = nd.zeros((1, 4, 2))
        bt, bm, ct = nd.MultiBoxTarget(anchors, labels, cls_preds)
        assert ct.asnumpy()[0, 0] == 3.0  # class 2 -> 3, not stolen

    def test_hard_negative_mining(self):
        rng = np.random.RandomState(0)
        lo = np.linspace(0, 0.85, 40).astype(np.float32)
        anchors = nd.array(
            np.stack([lo, lo, lo + 0.1, lo + 0.1], axis=-1)[None]
        )
        # one gt on anchor 0's box
        a0 = anchors.asnumpy()[0, 0]
        labels = nd.array(np.array([[[0.0, *a0]]], np.float32))
        cls_preds = nd.array(rng.rand(1, 2, 40).astype(np.float32))
        bt, bm, ct = nd.MultiBoxTarget(anchors, labels, cls_preds,
                                       negative_mining_ratio=3.0)
        ct = ct.asnumpy()[0]
        n_pos = (ct > 0).sum()
        n_bg = (ct == 0).sum()
        n_ignored = (ct == -1).sum()
        assert n_pos >= 1
        assert n_bg <= 3 * n_pos + 2  # ratio bound (+ threshold ties)
        assert n_ignored > 0

    def test_two_gts_sharing_best_anchor_both_match(self):
        """Greedy bipartite: the second gt claims its next-best anchor."""
        anchors = nd.array(np.array(
            [[[0.4, 0.4, 0.6, 0.6], [0.42, 0.42, 0.62, 0.62]]], np.float32
        ))
        # both gts' best anchor is 0 (first gt exactly, second closely)
        labels = nd.array(np.array(
            [[[0.0, 0.4, 0.4, 0.6, 0.6], [1.0, 0.41, 0.41, 0.61, 0.61]]],
            np.float32,
        ))
        cls_preds = nd.zeros((1, 3, 2))
        bt, bm, ct = nd.MultiBoxTarget(anchors, labels, cls_preds,
                                       overlap_threshold=0.95)
        ct = ct.asnumpy()[0]
        assert set(ct.tolist()) == {1.0, 2.0}  # both classes assigned
