"""Eager per-op jit cache + fused multi-tensor Trainer update (round 4).

The imperative hot loop (SURVEY §3.1): per-op dispatch must not change
numerics. These tests pin jit-on vs jit-off parity for forward, autograd
(cached recompute-backward), the fused SGD trainer apply, and the
blacklist fallback for trace-hostile functions.
"""

import os

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import autograd, gluon, nd
from mxnet_tpu import imperative


def _train_steps(flag, steps=3):
    os.environ["MXTPU_EAGER_JIT"] = flag
    mx.random.seed(11)
    rng = np.random.RandomState(0)
    net = gluon.nn.HybridSequential()
    with net.name_scope():
        net.add(gluon.nn.Conv2D(4, kernel_size=3, activation="relu"),
                gluon.nn.Flatten(),
                gluon.nn.Dense(10))
    net.initialize(mx.initializer.Xavier())
    loss_fn = gluon.loss.SoftmaxCrossEntropyLoss()
    trainer = gluon.Trainer(net.collect_params(), "sgd",
                            {"learning_rate": 0.05, "momentum": 0.9})
    x = nd.array(rng.rand(8, 1, 8, 8).astype(np.float32))
    y = nd.array(rng.randint(0, 10, 8).astype(np.float32))
    losses = []
    for _ in range(steps):
        with autograd.record():
            loss = loss_fn(net(x), y)
        loss.backward()
        trainer.step(8)
        losses.append(float(loss.mean().asscalar()))
    # name-counter suffixes differ between instantiations: compare by
    # declaration order
    return losses, [v.data().asnumpy()
                    for _, v in sorted(net.collect_params().items())]


def test_eager_jit_training_parity():
    l1, p1 = _train_steps("1")
    l0, p0 = _train_steps("0")
    os.environ.pop("MXTPU_EAGER_JIT", None)
    np.testing.assert_allclose(l1, l0, rtol=1e-5, atol=1e-6)
    for i, (a, b) in enumerate(zip(p1, p0)):
        np.testing.assert_allclose(a, b, rtol=1e-4, atol=1e-5,
                                   err_msg=f"param {i}")


def test_fused_sgd_trainer_engages():
    os.environ["MXTPU_EAGER_JIT"] = "1"
    try:
        net = gluon.nn.Dense(3)
        net.initialize()
        trainer = gluon.Trainer(net.collect_params(), "sgd",
                                {"learning_rate": 0.1, "momentum": 0.9})
        x = nd.array(np.random.rand(4, 5).astype(np.float32))
        with autograd.record():
            loss = net(x).sum()
        loss.backward()
        updater = trainer._updaters[0]
        # first step builds state through the fused path
        trainer.step(4)
        # the fused path must actually ENGAGE (returns True), not fall
        # back to per-param updates
        trainer._optimizer.rescale_grad = trainer._scale / 4
        assert trainer._fused_sgd_update(updater) is True
        assert all(isinstance(s, mx.nd.NDArray) or s is None
                   for s in updater.states.values())
        # momentum state must exist and be updated by the fused call
        assert any(s is not None and float(np.abs(s.asnumpy()).sum()) > 0
                   for s in updater.states.values())
    finally:
        os.environ.pop("MXTPU_EAGER_JIT", None)


def test_cache_blacklist_fallback():
    """A trace-hostile function must fall back to the plain path and
    still produce the right result (and not poison later calls)."""
    calls = []

    def hostile(a):
        import jax

        calls.append(1)
        if isinstance(a, jax.core.Tracer):
            raise RuntimeError("no tracers here")  # fails only under jit
        return a * 2

    x = nd.array(np.ones(3, np.float32))
    out = imperative.invoke_fn(hostile, x)
    np.testing.assert_allclose(out.asnumpy(), 2 * np.ones(3), rtol=0)


def test_rng_ops_not_frozen():
    """Dropout is deny-listed: two eager calls must draw different
    masks (a frozen jit constant would repeat them)."""
    os.environ["MXTPU_EAGER_JIT"] = "1"
    try:
        mx.random.seed(3)
        x = nd.ones((64, 64))
        a = mx.nd.Dropout(x, p=0.5, mode="always").asnumpy()
        b = mx.nd.Dropout(x, p=0.5, mode="always").asnumpy()
        assert not np.array_equal(a, b)
    finally:
        os.environ.pop("MXTPU_EAGER_JIT", None)


def test_lambda_key_distinguishes_closures():
    """NDArray method lambdas close over args (e.g. reshape target);
    different closure values must not collide in the cache."""
    x = nd.array(np.arange(12, dtype=np.float32).reshape(3, 4))
    a = x.reshape((4, 3))
    b = x.reshape((2, 6))
    assert a.shape == (4, 3) and b.shape == (2, 6)
    t1 = x.transpose()
    assert t1.shape == (4, 3)


def test_dataloader_nonpersistent_sees_mutation():
    """persistent_workers=False re-forks per epoch (reference
    semantics), so dataset mutations between epochs are visible."""
    class Ds:
        def __init__(self):
            self.scale = 1.0

        def __len__(self):
            return 4

        def __getitem__(self, i):
            return np.full((2,), i * self.scale, np.float32)

    ds = Ds()
    dl = gluon.data.DataLoader(ds, batch_size=2, num_workers=1,
                               persistent_workers=False)
    first = [b.asnumpy() for b in dl]
    ds.scale = 10.0
    second = [b.asnumpy() for b in dl]
    np.testing.assert_allclose(second[0], first[0] * 10.0)


def test_dist_async_warns_once():
    import warnings

    from mxnet_tpu.kvstore import kvstore as kvmod

    kvmod._ASYNC_WARNED[0] = False
    with warnings.catch_warnings(record=True) as w:
        warnings.simplefilter("always")
        mx.kv.create("dist_async")
        assert any("bounded-staleness" in str(x.message) for x in w)
    with warnings.catch_warnings(record=True) as w:
        warnings.simplefilter("always")
        mx.kv.create("dist_async")
        assert not any("bounded-staleness" in str(x.message) for x in w)


def test_proposal_flat_layout():
    rng = np.random.RandomState(0)
    B, A, H, W = 2, 3, 4, 4
    cls_prob = nd.array(rng.rand(B, 2 * A, H, W).astype(np.float32))
    bbox_pred = nd.array((rng.rand(B, 4 * A, H, W) * 0.1).astype(np.float32))
    im_info = nd.array(np.tile([64.0, 64.0, 1.0], (B, 1)).astype(np.float32))
    kw = dict(rpn_pre_nms_top_n=12, rpn_post_nms_top_n=5,
              scales=(8.,), ratios=(0.5, 1., 2.), feature_stride=16)
    batched = mx.nd.contrib.Proposal(cls_prob, bbox_pred, im_info,
                                     **kw).asnumpy()
    flat = mx.nd.contrib.Proposal(cls_prob, bbox_pred, im_info,
                                  layout="flat", **kw).asnumpy()
    assert batched.shape == (2, 5, 5)
    assert flat.shape == (10, 5)
    np.testing.assert_allclose(flat, batched.reshape(10, 5))


def test_env_keyed_ops_not_frozen():
    """Ops whose bodies read env vars must re-trace when the var flips:
    MXTPU_ATTN_DENSE_MAX=0 must genuinely select the flash kernel (found
    via a long-context example where flash == dense EXACTLY because both
    calls hit one cached executable)."""
    rng = np.random.RandomState(0)
    q = nd.array(rng.randn(1, 2, 64, 16).astype(np.float32) * 0.1)
    os.environ["MXTPU_EAGER_JIT"] = "1"
    try:
        # the op may execute through the per-op jit cache or (bulked)
        # through the segment cache; the env fingerprint is part of the
        # key either way — count both
        def entries():
            return len(imperative._EAGER_FWD_CACHE) + \
                len(imperative._SEG_CACHE)

        before = entries()
        os.environ["MXTPU_ATTN_DENSE_MAX"] = "1000000"
        dense = mx.nd.contrib.flash_attention(q, q, q).asnumpy()
        mid = entries()
        os.environ["MXTPU_ATTN_DENSE_MAX"] = "0"
        flash = mx.nd.contrib.flash_attention(q, q, q).asnumpy()
        after = entries()
        # distinct cache entries per env value: the second call re-traced
        assert mid > before and after > mid, (before, mid, after)
        np.testing.assert_allclose(flash, dense, rtol=2e-4, atol=2e-5)
    finally:
        os.environ.pop("MXTPU_ATTN_DENSE_MAX", None)
        os.environ.pop("MXTPU_EAGER_JIT", None)
