"""Regression tests for the ADVICE round-5 fixes.

1. ``_BulkQueue.flush`` cross-queue mutual dependencies must resolve
   entry-by-entry instead of recursing whole-queue flushes to
   ``RecursionError``.
2. The TPU staleness probe must probe EVERY input and disambiguate via a
   freshly allocated host buffer, so locally flat ops (or ops that
   legitimately ignore one input) are not falsely skipped.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

import mxnet_tpu as mx
from mxnet_tpu import imperative as imp
from mxnet_tpu.test_utils import _probe_rig_staleness, check_numeric_gradient


def _enqueue(q, key, fn, datas):
    struct = jax.ShapeDtypeStruct((4,), jnp.float32)
    (out,), _ = q.enqueue(key, fn, datas, [struct], False, None)
    return out, out._chunk.data  # (NDArray, _Pending)


class TestBulkQueueCrossFlush:
    def test_mutual_dependency_resolves_without_recursion(self):
        qA, qB = imp._BulkQueue(), imp._BulkQueue()
        a0 = jnp.ones(4)
        oA1, pA1 = _enqueue(qA, "r5A1", lambda x: x + 1, [a0])
        oB1, pB1 = _enqueue(qB, "r5B1", lambda x: x * 2, [pA1])
        oA2, _ = _enqueue(qA, "r5A2", lambda x: x - 3, [pB1])
        # pre-fix: qA.flush -> qB.flush -> qA.flush -> ... RecursionError
        qA.flush()
        assert np.allclose(np.asarray(oA1.data), 2.0)
        assert np.allclose(np.asarray(oB1.data), 4.0)
        assert np.allclose(np.asarray(oA2.data), 1.0)
        qB.flush()
        assert not qA.entries and not qB.entries

    def test_three_queue_cycle(self):
        qA, qB, qC = (imp._BulkQueue() for _ in range(3))
        a0 = jnp.full(4, 2.0)
        oA1, pA1 = _enqueue(qA, "r5cA1", lambda x: x + 1, [a0])
        oB1, pB1 = _enqueue(qB, "r5cB1", lambda x: x * 2, [pA1])
        oC1, pC1 = _enqueue(qC, "r5cC1", lambda x: x + 10, [pB1])
        oA2, _ = _enqueue(qA, "r5cA2", lambda x: x / 2, [pC1])
        qA.flush()
        assert np.allclose(np.asarray(oA2.data), 8.0)  # ((2+1)*2+10)/2
        qB.flush()
        qC.flush()

    def test_same_queue_chain_still_fuses(self):
        q = imp._BulkQueue()
        a0 = jnp.ones(4)
        o1, p1 = _enqueue(q, "r5s1", lambda x: x + 1, [a0])
        o2, _ = _enqueue(q, "r5s2", lambda x: x * 3, [p1])
        q.flush()
        assert np.allclose(np.asarray(o2.data), 6.0)

    def test_foreign_flush_from_consumer_thread(self):
        """A plain (acyclic) cross-queue dependency keeps working: the
        consumer queue's flush resolves the producer queue wholesale."""
        qA, qB = imp._BulkQueue(), imp._BulkQueue()
        oA1, pA1 = _enqueue(qA, "r5fA1", lambda x: x * 5, [jnp.ones(4)])
        oB1, _ = _enqueue(qB, "r5fB1", lambda x: x - 1, [pA1])
        qB.flush()
        assert np.allclose(np.asarray(oB1.data), 4.0)
        assert not qA.entries

    def test_error_in_producing_entry_surfaces(self):
        qA, qB = imp._BulkQueue(), imp._BulkQueue()

        def boom(x):
            raise ValueError("producer exploded")

        oA1, pA1 = _enqueue(qA, "r5eA1", boom, [jnp.ones(4)])
        oB1, pB1 = _enqueue(qB, "r5eB1", lambda x: x, [pA1])
        oA2, _ = _enqueue(qA, "r5eA2", lambda x: x, [pB1])
        with pytest.raises(ValueError, match="producer exploded"):
            qA.flush()
            qB.flush()
            np.asarray(oB1.data)


class TestStalenessProbe:
    def test_smooth_fn_not_stale(self):
        f = lambda *xs: float(sum((x ** 2).sum() for x in xs))
        assert not _probe_rig_staleness(f, [np.ones(4), np.ones(3)], 1e-3)

    def test_locally_flat_fn_not_flagged(self):
        # sign/round/STE-style flatness used to be misread as staleness
        g = lambda x: float(np.sign(x).sum())
        assert not _probe_rig_staleness(g, [np.ones(5)], 1e-3)

    def test_ignored_first_input_probes_the_rest(self):
        # an index/mask first arg the output ignores must not trigger a
        # skip while input 1 demonstrably reaches the output
        h = lambda idx, x: float((x ** 2).sum())
        assert not _probe_rig_staleness(
            h, [np.arange(3.0), np.ones(4)], 1e-3)

    def test_stale_rig_detected(self):
        # a rig that serves the FIRST transfer of each buffer forever
        # (in-place mutation invisible; fresh buffers honest) — the
        # tunneled-TPU failure signature
        class StaleRig:
            def __init__(self):
                self.cache = {}

            def __call__(self, x):
                k = id(x)
                if k not in self.cache:
                    self.cache[k] = float((x ** 3).sum())
                return self.cache[k]

        assert _probe_rig_staleness(StaleRig(), [np.ones(4)], 1e-3)

    def test_fn_ignoring_all_inputs_not_stale(self):
        # "op ignores its input" must FAIL the gradient comparison, not
        # skip: the probe may not flag it
        f = lambda x: 7.0
        assert not _probe_rig_staleness(f, [np.ones(4)], 1e-3)

    def test_check_numeric_gradient_cpu_path_unaffected(self):
        check_numeric_gradient(lambda x: (x * x).sum(),
                               [np.random.RandomState(0).rand(5)])
